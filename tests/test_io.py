"""Object-store round-trips (C3) and artifact persistence (C10): a trained
model saved, restored in a *fresh process*, and asserted bitwise-identical."""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pandas as pd
import pytest

from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, MLPArtifact, ObjectStore


@pytest.fixture()
def store(tmp_path):
    return ObjectStore(str(tmp_path / "lake"))


def test_bytes_json_roundtrip(store):
    store.put_bytes("a/b/blob.bin", b"\x00\x01tpu")
    assert store.get_bytes("a/b/blob.bin") == b"\x00\x01tpu"
    assert store.exists("a/b/blob.bin") and not store.exists("a/b/nope")
    store.put_json("meta.json", {"auc": 0.95, "params": {"depth": 3}})
    assert store.get_json("meta.json")["params"]["depth"] == 3
    store.delete("a/b/blob.bin")
    assert not store.exists("a/b/blob.bin")


def test_file_uri_and_listing(tmp_path):
    store = ObjectStore(f"file://{tmp_path}/lake2")
    store.put_bytes("x/1.bin", b"1")
    store.put_bytes("x/2.bin", b"2")
    store.put_bytes("y/3.bin", b"3")
    assert list(store.list("x")) == ["x/1.bin", "x/2.bin"]
    assert len(list(store.list())) == 3


def test_key_escape_rejected(store):
    with pytest.raises(ValueError):
        store.put_bytes("../../escape", b"nope")


def test_frame_roundtrip(store):
    df = pd.DataFrame({"a": [1.5, np.nan, 3.0], "s": ["x", "y", "z"]})
    store.save_frame("dataset/2-intermediate/cleaned_01.csv", df)
    back = store.load_frame("dataset/2-intermediate/cleaned_01.csv")
    pd.testing.assert_frame_equal(df, back)


def test_content_pointer(store):
    store.put_bytes("raw.csv", b"col\n1\n2\n")
    ptr = store.write_pointer("raw.csv")
    assert ptr["size"] == 8
    assert store.verify_pointer("raw.csv")
    store.put_bytes("raw.csv", b"col\n1\n3\n")  # content drifted
    assert not store.verify_pointer("raw.csv")


@pytest.fixture(scope="module")
def trained_gbdt(train_test):
    from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTClassifier

    X_train, X_test, y_train, _, names = train_test
    model = GBDTClassifier(n_estimators=20, max_depth=3, n_bins=64)
    model.fit(X_train[:2000], y_train[:2000])
    return model, X_test[:256], names


def test_gbdt_artifact_roundtrip_in_process(store, trained_gbdt):
    model, X_test, names = trained_gbdt
    art = GBDTArtifact(
        forest=model.forest,
        bin_spec=model.bin_spec,
        feature_names=tuple(names),
        config={"n_estimators": 20},
        metrics={"auc": 0.9},
    )
    art.save(store, "models/gbdt/model_tree")
    assert store.get_json("models/gbdt/model_tree.features.json") == list(names)
    back = GBDTArtifact.load(store, "models/gbdt/model_tree")
    assert back.feature_names == tuple(names)
    assert back.config == {"n_estimators": 20}
    m0 = np.asarray(model.predict_margin(X_test))
    from cobalt_smart_lender_ai_tpu.models.gbdt import predict_margin

    m1 = np.asarray(predict_margin(back.forest, X_test))
    np.testing.assert_array_equal(m0, m1)  # bitwise


def test_gbdt_artifact_fresh_process_bitwise(tmp_path, trained_gbdt):
    """train -> save -> load in a NEW python process -> identical predictions
    (the reference's S3-pickle restore contract, cobalt_fast_api.py:42-47)."""
    model, X_test, names = trained_gbdt
    store = ObjectStore(str(tmp_path / "lake"))
    GBDTArtifact(
        forest=model.forest, bin_spec=model.bin_spec, feature_names=tuple(names)
    ).save(store, "m")
    np.save(tmp_path / "X.npy", X_test)
    np.save(tmp_path / "margin.npy", np.asarray(model.predict_margin(X_test)))
    script = (
        "import numpy as np\n"
        "from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, ObjectStore\n"
        "from cobalt_smart_lender_ai_tpu.models.gbdt import predict_margin\n"
        f"store = ObjectStore({str(tmp_path / 'lake')!r})\n"
        "art = GBDTArtifact.load(store, 'm')\n"
        f"X = np.load({str(tmp_path / 'X.npy')!r})\n"
        f"want = np.load({str(tmp_path / 'margin.npy')!r})\n"
        "got = np.asarray(predict_margin(art.forest, X))\n"
        "np.testing.assert_array_equal(got, want)\n"
        "print('FRESH_PROCESS_OK')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # Hermetic fresh process: drop any tunneled-accelerator sitecustomize
    # from PYTHONPATH (it dials its backend at interpreter start; a wedged
    # tunnel then hangs this CPU-only restore check indefinitely).
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p
    )
    out = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True,
        text=True,
        env=env,
        timeout=240,
    )
    assert out.returncode == 0, out.stderr
    assert "FRESH_PROCESS_OK" in out.stdout


def test_mlp_artifact_roundtrip(store):
    from cobalt_smart_lender_ai_tpu.models.nn import MLP, MinMaxStats

    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 7)).astype(np.float32)
    module = MLP(hidden=(8, 4))
    params = module.init(jax.random.PRNGKey(0), X[:1])
    scaler = MinMaxStats.fit(X)
    art = MLPArtifact(
        params=params,
        scaler_low=np.asarray(scaler.low),
        scaler_range=np.asarray(scaler.range_),
        feature_names=tuple(f"f{i}" for i in range(7)),
        hidden_sizes=(8, 4),
    )
    art.save(store, "models/nn/challenger")
    back = MLPArtifact.load(store, "models/nn/challenger")
    logits0 = np.asarray(module.apply(params, X))
    logits1 = np.asarray(MLP(hidden=back.hidden_sizes).apply(back.params, X))
    np.testing.assert_array_equal(logits0, logits1)
    np.testing.assert_array_equal(np.asarray(scaler.low), back.scaler_low)


def test_artifact_kind_mismatch(store, trained_gbdt):
    model, _, names = trained_gbdt
    GBDTArtifact(
        forest=model.forest, bin_spec=model.bin_spec, feature_names=tuple(names)
    ).save(store, "m2")
    with pytest.raises(ValueError, match="kind"):
        MLPArtifact.from_bytes(store.get_bytes("m2.npz"))


def test_unsupported_future_format_rejected(store):
    from cobalt_smart_lender_ai_tpu.io.artifacts import _pack

    blob = _pack({}, {"kind": "gbdt", "format_version": 99, "feature_names": []})
    with pytest.raises(ValueError, match="newer"):
        GBDTArtifact.from_bytes(blob)


# --- s3 backend against a stubbed boto3 ---------------------------------------


class _FakeS3Client:
    """In-memory bucket honoring the exact boto3 surface _S3Store touches."""

    class _ClientError(Exception):
        pass

    def __init__(self):
        self.objects: dict[tuple[str, str], bytes] = {}
        self.exceptions = type("Exc", (), {"ClientError": self._ClientError})

    def put_object(self, Bucket, Key, Body):
        self.objects[(Bucket, Key)] = bytes(Body)

    def get_object(self, Bucket, Key):
        import io as _io

        if (Bucket, Key) not in self.objects:
            raise self._ClientError(f"NoSuchKey: {Key}")
        return {"Body": _io.BytesIO(self.objects[(Bucket, Key)])}

    def head_object(self, Bucket, Key):
        if (Bucket, Key) not in self.objects:
            raise self._ClientError(f"404: {Key}")
        return {"ContentLength": len(self.objects[(Bucket, Key)])}

    def delete_object(self, Bucket, Key):
        self.objects.pop((Bucket, Key), None)

    def get_paginator(self, op):
        assert op == "list_objects_v2"
        objects = self.objects

        class _Pager:
            def paginate(self, Bucket, Prefix=""):
                keys = sorted(
                    k for (b, k) in objects if b == Bucket and k.startswith(Prefix)
                )
                yield {"Contents": [{"Key": k} for k in keys]}

        return _Pager()


@pytest.fixture()
def s3_store(monkeypatch):
    """ObjectStore('s3://...') wired to the in-memory client: the real
    _S3Store code paths (prefix joining, pagination, error mapping) execute;
    only the AWS wire is faked."""
    import types as _types

    fake = _FakeS3Client()
    boto3 = _types.ModuleType("boto3")
    boto3.client = lambda name: fake if name == "s3" else None
    monkeypatch.setitem(sys.modules, "boto3", boto3)
    return ObjectStore("s3://bucket/pre/fix"), fake


def test_s3_bytes_json_roundtrip(s3_store):
    store, fake = s3_store
    store.put_bytes("a/b.bin", b"\x00tpu")
    assert ("bucket", "pre/fix/a/b.bin") in fake.objects  # prefix joined
    assert store.get_bytes("a/b.bin") == b"\x00tpu"
    assert store.exists("a/b.bin") and not store.exists("a/nope")
    store.put_json("meta.json", {"auc": 0.9})
    assert store.get_json("meta.json") == {"auc": 0.9}
    store.delete("a/b.bin")
    assert not store.exists("a/b.bin")


def test_s3_list_strips_prefix(s3_store):
    store, _ = s3_store
    for k in ("m/a.npz", "m/b.npz", "other/c.txt"):
        store.put_bytes(k, b"x")
    assert list(store.list("m/")) == ["m/a.npz", "m/b.npz"]
    assert list(store.list()) == ["m/a.npz", "m/b.npz", "other/c.txt"]


def test_s3_frame_and_artifact_roundtrip(s3_store, trained_gbdt):
    store, _ = s3_store
    df = pd.DataFrame({"a": [1.0, 2.0], "b": ["x", "y"]})
    store.save_frame("frames/f.csv", df)
    back = store.load_frame("frames/f.csv")
    assert back["a"].tolist() == [1.0, 2.0] and back["b"].tolist() == ["x", "y"]
    model, _, names = trained_gbdt
    GBDTArtifact(
        forest=model.forest, bin_spec=model.bin_spec, feature_names=tuple(names)
    ).save(store, "m/s3model")
    art = GBDTArtifact.load(store, "m/s3model")
    np.testing.assert_array_equal(
        np.asarray(art.forest.leaf_value), np.asarray(model.forest.leaf_value)
    )


def test_s3_without_boto3_raises(monkeypatch):
    monkeypatch.setitem(sys.modules, "boto3", None)
    with pytest.raises(ImportError, match="boto3"):
        ObjectStore("s3://bucket/x")
