"""Numeric parity of jitted metrics vs sklearn (SURVEY §4b)."""

import numpy as np
import pytest
from sklearn import metrics as skm

from cobalt_smart_lender_ai_tpu.ops.metrics import (
    binary_classification_report,
    confusion_matrix,
    roc_auc,
)


@pytest.fixture(scope="module")
def scored():
    rng = np.random.default_rng(0)
    n = 3000
    y = (rng.random(n) < 0.2).astype(np.float32)
    # correlated, with heavy ties to stress tie handling
    s = np.round(y * 0.8 + rng.normal(0, 0.6, n), 1).astype(np.float32)
    return y, s


def test_roc_auc_matches_sklearn(scored):
    y, s = scored
    ours = float(roc_auc(y, s))
    ref = skm.roc_auc_score(y, s)
    assert abs(ours - ref) < 1e-5


def test_roc_auc_weighted_matches_sklearn(scored):
    y, s = scored
    rng = np.random.default_rng(1)
    w = rng.random(len(y)).astype(np.float32)
    ours = float(roc_auc(y, s, w))
    ref = skm.roc_auc_score(y, s, sample_weight=w)
    assert abs(ours - ref) < 1e-5


def test_roc_auc_masked_equals_subset(scored):
    y, s = scored
    mask = (np.arange(len(y)) % 3 == 0).astype(np.float32)
    ours = float(roc_auc(y, s, mask))
    ref = skm.roc_auc_score(y[mask > 0], s[mask > 0])
    assert abs(ours - ref) < 1e-5


def test_confusion_matrix_matches_sklearn(scored):
    y, s = scored
    pred = (s > 0.4).astype(np.float32)
    ours = np.asarray(confusion_matrix(y, pred))
    ref = skm.confusion_matrix(y, pred)
    np.testing.assert_allclose(ours, ref)


def test_classification_report_schema_and_values(scored):
    y, s = scored
    pred = (s > 0.4).astype(np.float32)
    ours = binary_classification_report(y, pred)
    ref = skm.classification_report(y, pred, output_dict=True)
    for cls in ("0", "1"):
        for k in ("precision", "recall", "f1-score", "support"):
            assert abs(ours[cls][k] - ref[f"{cls}.0"][k]) < 1e-5, (cls, k)
    assert abs(ours["accuracy"] - ref["accuracy"]) < 1e-5
    assert abs(ours["weighted avg"]["f1-score"] - ref["weighted avg"]["f1-score"]) < 1e-5
