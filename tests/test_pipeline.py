"""End-to-end pipeline test (C6): one call from raw synthetic table to a
tuned, persisted model — asserting the headline-AUC regime (VERDICT r1 §3:
tuned test AUC >= 0.93 on the planted-signal table), the reference's
metrics.json schema, and artifact round-trip through the object store."""

import numpy as np
import pytest

from cobalt_smart_lender_ai_tpu.config import (
    GBDTConfig,
    MeshConfig,
    PipelineConfig,
    RFEConfig,
    TuneConfig,
)
from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, ObjectStore
from cobalt_smart_lender_ai_tpu.pipeline import run_pipeline


@pytest.fixture(scope="module")
def pipeline_run(tmp_path_factory):
    from cobalt_smart_lender_ai_tpu.data.synthetic import (
        synthetic_lendingclub_frame,
    )

    cfg = PipelineConfig(
        gbdt=GBDTConfig(n_bins=64),
        rfe=RFEConfig(n_select=12, step=30, n_estimators=20, max_depth=3),
        tune=TuneConfig(
            n_iter=2,
            cv_folds=2,
            param_space={
                "n_estimators": (100, 150),
                "max_depth": (3,),
                "learning_rate": (0.1,),
            },
        ),
        mesh=MeshConfig(hp=1),
    )
    store = ObjectStore(str(tmp_path_factory.mktemp("pipeline") / "lake"))
    raw = synthetic_lendingclub_frame(6000, seed=5)
    result = run_pipeline(cfg, raw=raw, store=store)
    return cfg, store, result


def test_headline_auc_regime(pipeline_run):
    """clean -> engineer -> RFE -> tuned search -> eval must reach the
    reference's post-leakage AUC regime even in the slimmed test config."""
    _, _, result = pipeline_run
    assert result.test_auc >= 0.93, result.test_auc
    assert result.cv_auc >= 0.90
    # CV estimate and test score should agree reasonably (no leakage)
    assert abs(result.cv_auc - result.test_auc) < 0.05


def test_rfe_selected_versioned(pipeline_run):
    cfg, store, result = pipeline_run
    assert len(result.selected_features) == cfg.rfe.n_select
    # the selected set is versioned with the artifact (SURVEY §2.1 known
    # inconsistency: the reference's feature set was implicit)
    assert store.get_json(cfg.serve.model_key + ".features.json") == list(
        result.selected_features
    )


def test_metrics_json_reference_schema(pipeline_run):
    cfg, store, result = pipeline_run
    metrics = store.get_json(cfg.serve.model_key + ".metrics.json")
    # exact top-level schema of model_tree_train_test.py:235-242
    assert set(metrics) == {"auc", "classification_report", "best_params"}
    assert metrics["auc"] == pytest.approx(result.test_auc)
    report = metrics["classification_report"]
    assert set(report) == {"0", "1", "accuracy", "macro avg", "weighted avg"}
    assert set(report["1"]) == {"precision", "recall", "f1-score", "support"}
    assert set(metrics["best_params"]) <= set(cfg.tune.param_space)


def test_intermediate_frames_round_trip(pipeline_run):
    cfg, store, _ = pipeline_run
    cleaned = store.load_frame(cfg.data.cleaned_key)
    tree = store.load_frame(cfg.data.tree_key)
    nn = store.load_frame(cfg.data.nn_key)
    assert len(cleaned) >= len(tree) > 0
    assert "loan_default" in tree.columns and "loan_default" in nn.columns
    # the class balance stays in the LendingClub regime (~20% defaults)
    assert 0.1 < tree["loan_default"].mean() < 0.35


def test_pipeline_on_sharded_mesh():
    """The whole composition must also run with jobs sharded over hp=2 and
    rows over dp=4 (the 8-virtual-device mesh) — RFE's dp-sharded refits,
    the fan-out search, and the final fit all together."""
    from cobalt_smart_lender_ai_tpu.data.synthetic import (
        synthetic_lendingclub_frame,
    )
    from cobalt_smart_lender_ai_tpu.parallel.mesh import make_mesh

    cfg = PipelineConfig(
        save_intermediate=False,
        gbdt=GBDTConfig(n_bins=32),
        rfe=RFEConfig(n_select=10, step=40, n_estimators=10, max_depth=3),
        tune=TuneConfig(
            n_iter=2,
            cv_folds=2,
            chunk_trees=30,  # exercise the chunked dispatch path too
            param_space={
                "n_estimators": (60,),
                "max_depth": (3,),
                "learning_rate": (0.1,),
            },
        ),
        mesh=MeshConfig(hp=2),
    )
    raw = synthetic_lendingclub_frame(3000, seed=9)
    result = run_pipeline(cfg, raw=raw, mesh=make_mesh(cfg.mesh))
    assert result.test_auc > 0.9
    assert len(result.selected_features) == 10


def test_plot_artifacts_emitted(pipeline_run):
    """The reference uploads confusion-matrix + feature-importance PNGs next
    to the model (model_tree_train_test.py:184-210); the pipeline must too."""
    cfg, store, _ = pipeline_run
    for suffix in (".confusion_matrix.png", ".feature_importance.png"):
        png = store.get_bytes(cfg.serve.model_key + suffix)
        assert png[:8] == b"\x89PNG\r\n\x1a\n", suffix
        assert len(png) > 1000


def test_artifact_restores_and_scores(pipeline_run):
    cfg, store, result = pipeline_run
    art = GBDTArtifact.load(store, cfg.serve.model_key)
    assert art.feature_names == result.selected_features
    assert art.metrics["auc"] == pytest.approx(result.test_auc)
    assert art.plan is not None
    # restored forest reproduces the in-memory estimator bitwise
    from cobalt_smart_lender_ai_tpu.models.gbdt import predict_margin

    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, len(art.feature_names))).astype(np.float32)
    m0 = np.asarray(predict_margin(result.artifact.forest, X))
    m1 = np.asarray(predict_margin(art.forest, X))
    np.testing.assert_array_equal(m0, m1)
