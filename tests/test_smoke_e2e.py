"""Automated smoke test — the reference's manual `automation_test.py:5-39`
flow made assertive: 10 labeled borrowers (5 defaulted, 5 paid) extracted
from the engineered tree frame, scored through the *served HTTP API*, and
checked against their true labels instead of eyeballed."""

import json
import urllib.request

import numpy as np
import pandas as pd
import pytest
from sklearn.metrics import roc_auc_score

from cobalt_smart_lender_ai_tpu.data import schema
from cobalt_smart_lender_ai_tpu.data.split import train_test_split_hashed
from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, ObjectStore
from cobalt_smart_lender_ai_tpu.serve import ScorerService


def _fast_cfg():
    """Default serving config minus the all-bucket prewarm — this module
    doesn't exercise cold-bucket tails, and the extra per-bucket compiles
    are pure tier-1 wall time."""
    from cobalt_smart_lender_ai_tpu.config import ServeConfig

    return ServeConfig(prewarm_all_buckets=False)

from cobalt_smart_lender_ai_tpu.serve.http_asyncio import make_async_server


@pytest.fixture(scope="module")
def smoke_env(tmp_path_factory, engineered):
    """Train on the 20-feature serving contract, persist, restore, serve."""
    from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTClassifier

    tree_ff, _, _ = engineered
    ff = tree_ff.select(schema.SERVING_FEATURES)
    X_train, X_test, y_train, y_test = train_test_split_hashed(
        ff.X, ff.y, test_fraction=0.2, seed=22
    )
    y_np = np.asarray(y_train)
    spw = (len(y_np) - y_np.sum()) / max(y_np.sum(), 1.0)
    model = GBDTClassifier(
        n_estimators=80, max_depth=3, n_bins=64, learning_rate=0.1,
        scale_pos_weight=float(spw),
    )
    model.fit(np.asarray(X_train), y_np)
    store = ObjectStore(str(tmp_path_factory.mktemp("smoke") / "lake"))
    GBDTArtifact(
        forest=model.forest,
        bin_spec=model.bin_spec,
        feature_names=tuple(schema.SERVING_FEATURES),
    ).save(store, "models/gbdt/model_tree")
    service = ScorerService.from_store(store, _fast_cfg())
    server = make_async_server(service, "127.0.0.1", 0)
    url = f"http://127.0.0.1:{server.port}"

    # 10-row labeled sample, balanced like a smoke operator would pick
    # (automation_test.py samples 10 rows and prints the labels).
    # Like the reference's operator, pick scoreable borrowers: rows with a
    # complete 20-field payload (the CSV wire format can carry NaN, but the
    # smoke flow mirrors automation_test.py's fully-populated records; the
    # full-schema synthetic frame block-masks some serving features).
    Xte, yte = np.asarray(X_test), np.asarray(y_test)
    full = ~np.isnan(Xte.astype(np.float64)).any(axis=1)
    pos = np.flatnonzero((yte == 1) & full)[:5]
    neg = np.flatnonzero((yte == 0) & full)[:5]
    idx = np.concatenate([pos, neg])
    sample = pd.DataFrame(Xte[idx], columns=list(schema.SERVING_FEATURES))
    labels = yte[idx]
    yield url, sample, labels
    server.close()


def _post(url, body, content_type):
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": content_type}, method="POST"
    )
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read().decode())


def test_bulk_smoke_beats_label_floor(smoke_env):
    url, sample, labels = smoke_env
    resp = _post(
        url + "/predict_bulk_csv",
        sample.to_csv(index=False).encode(),
        "text/csv",
    )
    probs = np.array([rec["prob_default"] for rec in resp["predictions"]])
    assert probs.shape == (10,)
    # the served model must separate the 5 defaulted from the 5 paid rows
    assert roc_auc_score(labels, probs) >= 0.75
    # thresholded accuracy floor (balanced sample -> 0.5 is chance)
    assert ((probs >= 0.5).astype(int) == labels).mean() >= 0.6


def test_single_and_bulk_paths_agree(smoke_env):
    url, sample, _ = smoke_env
    bulk = _post(
        url + "/predict_bulk_csv",
        sample.to_csv(index=False).encode(),
        "text/csv",
    )
    n_compared = 0
    for i in range(len(sample)):
        row = sample.iloc[i]
        payload = {
            c: float(row[c]) for c in sample.columns if not pd.isna(row[c])
        }
        if len(payload) < len(sample.columns):
            continue  # /predict requires all 20 fields; skip rows with NaN
        single = _post(
            url + "/predict", json.dumps(payload).encode(), "application/json"
        )
        assert single["prob_default"] == pytest.approx(
            bulk["predictions"][i]["prob_default"], abs=1e-6
        )
        n_compared += 1
        if n_compared == 3:
            break
    assert n_compared > 0, "no NaN-free row found; parity never checked"
