"""MLP + FT-Transformer tests: learning on the engineered feature frame,
early stopping on validation AUC, class weighting, dropout determinism."""

import jax
import numpy as np
import pytest
from sklearn.metrics import roc_auc_score

from cobalt_smart_lender_ai_tpu.config import FTTransformerConfig, MLPConfig
from cobalt_smart_lender_ai_tpu.models.ft_transformer import FTTransformerClassifier
from cobalt_smart_lender_ai_tpu.models.nn import MLPClassifier


def test_mlp_learns_engineered_frame(train_test):
    X_train, X_test, y_train, y_test, _ = train_test
    model = MLPClassifier(MLPConfig(epochs=10, batch_size=512, hidden_sizes=(64, 16)))
    model.fit(X_train, y_train)
    auc = roc_auc_score(y_test, np.asarray(model.predict_proba(X_test)[:, 1]))
    assert auc > 0.68
    assert len(model.history["loss"]) <= 10
    assert len(model.history["val_auc"]) == len(model.history["loss"])


def test_mlp_early_stopping_restores_best():
    # lr=1e-2: the 40-epoch x ~6-step budget undershoots at the 1e-3 default
    # (val AUC ~0.73); the identical loop reaches 0.95 here. The loop's epoch
    # accounting is pinned bit-exactly by
    # test_epochs_per_dispatch_is_bit_identical.
    rng = np.random.default_rng(0)
    X = rng.normal(size=(1500, 8)).astype(np.float32)
    y = (X[:, 0] + 0.5 * rng.normal(size=1500) > 0).astype(np.int64)
    model = MLPClassifier(
        MLPConfig(
            epochs=40,
            batch_size=256,
            early_stop_patience=3,
            hidden_sizes=(16,),
            learning_rate=1e-2,
        )
    )
    model.fit(X, y)
    # patience must be able to stop the run early
    assert len(model.history["loss"]) <= 40
    best = max(model.history["val_auc"])
    # restored params should score the best recorded validation AUC
    assert best > 0.8


def test_mlp_nan_inputs_handled():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(1200, 6)).astype(np.float32)
    y = (X[:, 1] > 0).astype(np.int64)
    X[rng.random(X.shape) < 0.1] = np.nan
    model = MLPClassifier(MLPConfig(epochs=25, batch_size=128, hidden_sizes=(16,)))
    model.fit(X, y)
    p = np.asarray(model.predict_proba(X)[:, 1])
    assert np.isfinite(p).all()
    assert roc_auc_score(y, p) > 0.8


@pytest.fixture(scope="module")
def ft_data():
    rng = np.random.default_rng(2)
    n = 2500
    Xn = rng.normal(size=(n, 6)).astype(np.float32)
    Xc = rng.integers(0, 5, size=(n, 2))
    logits = Xn[:, 0] - Xn[:, 1] + (Xc[:, 0] == 2) * 1.5
    y = (logits + rng.normal(size=n) * 0.5 > 0).astype(np.int64)
    return Xn, Xc, y


def test_ft_transformer_learns_mixed_columns(ft_data):
    Xn, Xc, y = ft_data
    tr = slice(0, 2000)
    te = slice(2000, None)
    ft = FTTransformerClassifier(
        (5, 5),
        FTTransformerConfig(epochs=5, batch_size=256, d_token=16, n_blocks=1, n_heads=2),
    )
    ft.fit(Xn[tr], Xc[tr], y[tr])
    p = np.asarray(ft.predict_proba(Xn[te], Xc[te])[:, 1])
    assert roc_auc_score(y[te], p) > 0.8


def test_ft_transformer_prediction_deterministic(ft_data):
    Xn, Xc, y = ft_data
    ft = FTTransformerClassifier(
        (5, 5),
        FTTransformerConfig(epochs=2, batch_size=256, d_token=16, n_blocks=1, n_heads=2),
    )
    ft.fit(Xn[:1000], Xc[:1000], y[:1000])
    p1 = np.asarray(ft.predict_proba(Xn[:100], Xc[:100]))
    p2 = np.asarray(ft.predict_proba(Xn[:100], Xc[:100]))
    np.testing.assert_array_equal(p1, p2)  # dropout off at inference


def test_ft_transformer_out_of_vocab_codes_clamp(ft_data):
    Xn, Xc, y = ft_data
    ft = FTTransformerClassifier(
        (5, 5),
        FTTransformerConfig(epochs=1, batch_size=256, d_token=16, n_blocks=1, n_heads=2),
    )
    ft.fit(Xn[:1000], Xc[:1000], y[:1000])
    bad = Xc[:50].copy()
    bad[:, 0] = 99  # unseen category
    p = np.asarray(ft.predict_proba(Xn[:50], bad)[:, 1])
    assert np.isfinite(p).all()


def test_ft_transformer_chunked_predict_matches_single_shot(ft_data):
    """predict_logits chunks rows through one compiled program (the
    full-batch attention transient OOMs real HBM at ~50k rows); the chunked
    path must score identically to the single-dispatch path."""
    Xn, Xc, y = ft_data
    ft = FTTransformerClassifier(
        (5, 5),
        FTTransformerConfig(epochs=1, batch_size=256, d_token=16, n_blocks=1, n_heads=2),
    )
    ft.fit(Xn[:1000], Xc[:1000], y[:1000])
    whole = np.asarray(ft.predict_logits(Xn[:300], Xc[:300]))
    chunked = np.asarray(ft.predict_logits(Xn[:300], Xc[:300], batch_rows=128))
    np.testing.assert_allclose(chunked, whole, rtol=1e-5, atol=1e-6)


def test_epochs_per_dispatch_is_bit_identical():
    """K-epoch super-steps keep the early-stop state machine on device; for
    ANY K the selected params, history, and early-stop epoch must equal the
    per-epoch (K=1) loop — same RNG split order, same update rule."""
    rng = np.random.default_rng(5)
    X = rng.normal(size=(600, 12)).astype(np.float32)
    y = (X[:, 0] - X[:, 1] + rng.logistic(size=600) * 0.4 > 0).astype(np.int32)

    def run(k):
        m = MLPClassifier(
            MLPConfig(
                hidden_sizes=(16, 8), epochs=12, batch_size=128,
                early_stop_patience=3, epochs_per_dispatch=k, seed=3,
            )
        )
        m.fit(X, y)
        return m

    a, b, c = run(1), run(5), run(12)
    assert a.history["loss"] == b.history["loss"] == c.history["loss"]
    assert a.history["val_auc"] == b.history["val_auc"] == c.history["val_auc"]
    pa = jax.tree.leaves(a.params)
    for other in (b, c):
        for la, lo in zip(pa, jax.tree.leaves(other.params)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lo))
