"""Tail-latency forensics: flight-recorder capture rules, SLO burn-rate
math under a fake clock, Chrome-trace export validity, exemplar round-trip,
trace ids on log lines — and the end-to-end acceptance drill: an injected
slow SHAP call must be nameable from the outside (README "Debugging tail
latency")."""

import json
import logging
import urllib.request

import pytest

from cobalt_smart_lender_ai_tpu.telemetry import (
    FlightRecorder,
    MetricsRegistry,
    Objective,
    SLOEngine,
    Tracer,
    add_phase,
    chrome_trace,
    collect_phases,
    get_logger,
    parse_exposition,
    render_chrome_trace,
)
from cobalt_smart_lender_ai_tpu.telemetry.flight import PhaseAccumulator


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# --- flight recorder: rings, capture rules, top-K board -----------------------


def _rec(fr, *, duration_s, status=200, rid="r", phases=None):
    return fr.record(
        request_id=rid,
        trace_id=7,
        route="/predict",
        method="POST",
        status=status,
        duration_s=duration_s,
        phases=phases,
    )


def test_recent_ring_bounded_newest_first():
    fr = FlightRecorder(capacity=4, slow_threshold_s=1.0, clock=FakeClock())
    for i in range(10):
        _rec(fr, duration_s=0.001, rid=f"r{i}")
    recs = fr.records()
    assert [r["request_id"] for r in recs] == ["r9", "r8", "r7", "r6"]
    assert fr.stats()["recorded"] == 10


def test_error_ring_survives_a_burst_of_healthy_traffic():
    """The one 500 an operator is hunting must not be evicted by fast 200s
    — the always-capture rule the recent ring alone can't give."""
    fr = FlightRecorder(capacity=8, slow_threshold_s=1.0, clock=FakeClock())
    _rec(fr, duration_s=0.002, status=500, rid="the-bad-one")
    for i in range(50):
        _rec(fr, duration_s=0.001, rid=f"ok{i}")
    assert all(r["request_id"] != "the-bad-one" for r in fr.records())
    errs = fr.errors()
    assert [r["request_id"] for r in errs] == ["the-bad-one"]
    assert errs[0]["error"] and errs[0]["status"] == 500
    assert fr.stats()["errors"] == 1


def test_slowest_board_keeps_topk_ever_seen_not_ring_window():
    fr = FlightRecorder(capacity=4, slow_threshold_s=0.1, top_k=3,
                        clock=FakeClock())
    _rec(fr, duration_s=9.0, rid="slowest-ever")
    for i in range(20):  # plenty to evict it from the recent ring
        _rec(fr, duration_s=0.001 + i * 1e-6, rid=f"fast{i}")
    _rec(fr, duration_s=3.0, rid="second")
    _rec(fr, duration_s=5.0, rid="third")
    board = fr.slowest()
    assert [r["request_id"] for r in board] == [
        "slowest-ever", "third", "second",
    ]
    assert [r["slow"] for r in board] == [True, True, True]
    assert fr.slowest(1)[0]["request_id"] == "slowest-ever"
    assert fr.stats()["slow"] == 3


def test_record_phases_rounding_and_unattributed_remainder():
    fr = FlightRecorder(capacity=4, slow_threshold_s=0.05, clock=FakeClock())
    rec = _rec(
        fr,
        duration_s=0.1,
        phases={"dispatch": 0.06, "shap": 0.0301, "validate": 0.0},
    )
    # zero-duration phases are dropped; the rest round to ms
    assert rec["phases_ms"] == {"dispatch": 60.0, "shap": 30.1}
    assert rec["other_ms"] == pytest.approx(9.9, abs=0.01)
    assert rec["slow"] and not rec["error"]
    over = _rec(fr, duration_s=0.01, phases={"dispatch": 0.02})
    assert over["other_ms"] == 0.0  # clamped: attribution can over-count


def test_phase_accumulator_contextvar_scoping():
    acc = PhaseAccumulator()
    acc.add("shap", 0.01)
    acc.add("shap", 0.02)
    acc.add("dispatch", -5.0)  # negative clamps to zero, never subtracts
    assert acc.phases == {"shap": pytest.approx(0.03), "dispatch": 0.0}

    add_phase("dispatch", 1.0)  # outside any block: silently dropped
    with collect_phases() as phases:
        add_phase("dispatch", 0.5)
    assert phases.phases == {"dispatch": 0.5}
    add_phase("dispatch", 1.0)  # after the block: dropped again
    assert phases.phases == {"dispatch": 0.5}


# --- SLO engine: burn-rate math under a fake clock ----------------------------

BUCKETS = (0.005, 0.01, 0.05, 0.1, 1.0)


def _latency_engine(clk, *, target=0.99, threshold_s=0.01,
                    windows=(60.0, 3600.0)):
    reg = MetricsRegistry()
    hist = reg.histogram(
        "cobalt_request_latency_seconds", "t", ("route", "status"),
        buckets=BUCKETS,
    )
    obj = Objective(
        name="latency", kind="latency", target=target,
        labels={"route": "/predict"}, threshold_s=threshold_s,
    )
    return reg, hist, SLOEngine(reg, [obj], clock=clk, windows_s=windows)


def test_burn_rate_is_bad_fraction_over_budget():
    clk = FakeClock()
    _, hist, eng = _latency_engine(clk)  # budget = 1 - 0.99 = 1%
    child = hist.labels(route="/predict", status="200")
    for _ in range(98):
        child.observe(0.004)  # good: under the 10ms effective threshold
    for _ in range(2):
        child.observe(0.5)  # bad
    clk.advance(30.0)
    report = eng.evaluate(force=True)
    (obj,) = report["objectives"]
    assert obj["total"] == 100 and obj["bad"] == 2
    for win in obj["windows"]:
        # 2% bad against a 1% budget: burning twice the allowed pace,
        # measured against the zero-counts snapshot seeded at engine birth
        assert win["total"] == 100 and win["bad"] == 2
        assert win["bad_ratio"] == pytest.approx(0.02)
        assert win["burn_rate"] == pytest.approx(2.0)
    assert not obj["fast_burn"] and not report["fast_burn"]
    assert obj["threshold_ms"] == 10.0
    assert obj["effective_threshold_ms"] == 10.0


def test_fast_burn_needs_every_window_over_threshold():
    """A 100%-bad burst after an hour of clean traffic floods the 1-minute
    window but not the 1-hour one — fast_burn stays down until the burn is
    sustained (the SRE-workbook multi-window AND)."""
    clk = FakeClock()
    _, hist, eng = _latency_engine(clk)
    good = hist.labels(route="/predict", status="200")
    for _ in range(1000):
        good.observe(0.004)
    clk.advance(3500.0)
    eng.evaluate(force=True)  # snapshot: (1000 good, 1000 total) @ t=3500
    clk.advance(60.0)
    for _ in range(20):
        good.observe(0.5)  # burst: every request bad
    report = eng.evaluate(force=True)
    (obj,) = report["objectives"]
    short, long_ = obj["windows"]
    assert short["window_s"] == 60.0
    assert short["total"] == 20 and short["bad"] == 20
    assert short["burn_rate"] == pytest.approx(100.0)
    assert long_["total"] == 1020 and long_["bad"] == 20
    assert long_["burn_rate"] < 14.4
    assert not obj["fast_burn"]


def test_fast_burn_when_all_windows_burn():
    clk = FakeClock()
    _, hist, eng = _latency_engine(clk)
    child = hist.labels(route="/predict", status="200")
    for _ in range(50):
        child.observe(0.5)  # nothing but bad requests since birth
    clk.advance(10.0)
    report = eng.evaluate(force=True)
    (obj,) = report["objectives"]
    assert all(w["burn_rate"] == pytest.approx(100.0) for w in obj["windows"])
    assert obj["fast_burn"] and report["fast_burn"]


def test_windowed_delta_not_cumulative():
    """Old badness must age out of the short window: burn is computed from
    snapshot deltas, not lifetime totals."""
    clk = FakeClock()
    _, hist, eng = _latency_engine(clk)
    child = hist.labels(route="/predict", status="200")
    for _ in range(10):
        child.observe(0.5)  # a bad start
    clk.advance(5.0)
    assert eng.evaluate(force=True)["objectives"][0]["fast_burn"]
    for t in range(12):  # 2 minutes of clean traffic, snapshotted along
        clk.advance(10.0)
        for _ in range(50):
            child.observe(0.004)
        eng.evaluate(force=True)
    report = eng.evaluate(force=True)
    (obj,) = report["objectives"]
    short = obj["windows"][0]
    assert short["bad"] == 0 and short["burn_rate"] == 0.0
    assert not obj["fast_burn"]
    assert obj["bad"] == 10  # lifetime counters still tell the whole story


def test_effective_threshold_snaps_to_bucket_resolution():
    clk = FakeClock()
    _, _, eng = _latency_engine(clk, threshold_s=0.03)
    (obj,) = eng.objectives
    # 30ms sits between the 10ms and 50ms buckets: the histogram can only
    # answer at 10ms, and the report must say so
    assert eng.effective_threshold_s(obj) == 0.01
    report = eng.evaluate(force=True)
    assert report["objectives"][0]["threshold_ms"] == 30.0
    assert report["objectives"][0]["effective_threshold_ms"] == 10.0


def test_availability_counts_5xx_bad_and_shed_429_good():
    clk = FakeClock()
    reg = MetricsRegistry()
    hist = reg.histogram(
        "cobalt_request_latency_seconds", "t", ("route", "status"),
        buckets=BUCKETS,
    )
    obj = Objective(
        name="availability", kind="availability", target=0.999,
        labels={"route": ("/predict", "/predict_bulk_csv")},
    )
    eng = SLOEngine(reg, [obj], clock=clk)
    for status, n in (("200", 90), ("429", 5), ("422", 3), ("500", 2)):
        child = hist.labels(route="/predict", status=status)
        for _ in range(n):
            child.observe(0.004)
    # a 500 on a non-scoring route must not count against the objective
    hist.labels(route="/metrics", status="500").observe(0.001)
    clk.advance(1.0)
    report = eng.evaluate(force=True)
    (out,) = report["objectives"]
    assert out["total"] == 100
    assert out["bad"] == 2  # the 5xx only; 429/422 are policy, not downtime
    assert out["windows"][0]["bad_ratio"] == pytest.approx(0.02)


def test_slo_gauges_mirror_the_report():
    clk = FakeClock()
    reg, hist, eng = _latency_engine(clk)
    eng.register_gauges()
    child = hist.labels(route="/predict", status="200")
    for _ in range(10):
        child.observe(0.5)
    clk.advance(10.0)
    eng.evaluate(force=True)
    families = parse_exposition(reg.render())
    samples = families["cobalt_slo_burn_rate"]["samples"]
    assert samples["cobalt_slo_burn_rate|objective=latency|window=60s"] \
        == pytest.approx(100.0)
    assert families["cobalt_slo_fast_burn"]["samples"][
        "cobalt_slo_fast_burn|objective=latency"
    ] == 1.0
    assert families["cobalt_slo_target"]["samples"][
        "cobalt_slo_target|objective=latency"
    ] == pytest.approx(0.99)


def test_objective_validation():
    with pytest.raises(ValueError, match="kind"):
        Objective(name="x", kind="speed", target=0.9)
    with pytest.raises(ValueError, match="target"):
        Objective(name="x", kind="availability", target=1.0)
    with pytest.raises(ValueError, match="threshold_s"):
        Objective(name="x", kind="latency", target=0.99)


# --- Chrome-trace export ------------------------------------------------------


def test_chrome_trace_events_nest_and_ids_match_the_ring():
    clk = FakeClock(100.0)
    tracer = Tracer(clock=clk, jax_annotations=False)
    with tracer.span("http.request", route="/predict") as root:
        clk.advance(0.001)
        with tracer.span("serve.dispatch") as child:
            clk.advance(0.005)
        clk.advance(0.001)

    doc = json.loads(render_chrome_trace(tracer))  # must be valid JSON
    assert doc["displayTimeUnit"] == "ms"
    complete = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
    assert set(complete) == {"http.request", "serve.dispatch"}
    parent, kid = complete["http.request"], complete["serve.dispatch"]
    # ids join back to the span ring / flight records
    assert parent["args"]["span_id"] == root.span_id
    assert parent["args"]["parent_id"] is None
    assert kid["args"]["parent_id"] == root.span_id
    assert kid["args"]["trace_id"] == root.trace_id == root.span_id
    assert parent["args"]["route"] == "/predict"
    # microsecond complete events, child strictly inside the parent
    assert kid["ts"] >= parent["ts"]
    assert kid["ts"] + kid["dur"] <= parent["ts"] + parent["dur"]
    assert parent["dur"] == pytest.approx(7000.0)  # 7ms in us
    # one thread_name metadata event names the track
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert len(meta) == 1 and meta[0]["name"] == "thread_name"
    assert meta[0]["tid"] == parent["tid"]


def test_chrome_trace_skips_unfinished_spans():
    clk = FakeClock()
    tracer = Tracer(clock=clk, jax_annotations=False)
    with tracer.span("done"):
        clk.advance(0.001)
    tracer.record_span("also_done", 5.0, 6.0)
    # only finished spans reach the ring, so every event has an extent
    doc = chrome_trace(tracer)
    assert {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"} == {
        "done", "also_done",
    }
    assert doc["otherData"]["span_count"] == 2


# --- exemplars: /metrics buckets link back to traces --------------------------


def test_latency_exemplar_roundtrip_openmetrics_only():
    reg = MetricsRegistry()
    hist = reg.histogram("h_seconds", "t", ("route",), buckets=(0.01, 1.0))
    hist.labels(route="/p").observe(0.004, exemplar="12345")

    classic = reg.render()
    assert "trace_id" not in classic and "# EOF" not in classic
    parse_exposition(classic)

    om = reg.render(openmetrics=True)
    assert om.rstrip().endswith("# EOF")
    fams = parse_exposition(om)
    exemplars = fams["h_seconds"]["exemplars"]
    assert exemplars["h_seconds_bucket|le=0.01|route=/p"]["trace_id"] == "12345"
    # exemplar rides the first bucket the observation lands in, only there
    assert all("le=+Inf" not in k for k in exemplars)


# --- log lines carry trace ids ------------------------------------------------


def test_log_lines_inside_a_span_carry_trace_and_span_ids(caplog):
    from cobalt_smart_lender_ai_tpu.telemetry import default_tracer

    log = get_logger("test.flight")
    with caplog.at_level(logging.INFO, logger="cobalt.test.flight"):
        with default_tracer().span("http.request") as root:
            with default_tracer().span("serve.shap") as child:
                log.info("explaining")
        log.info("after")
    inside = json.loads(caplog.records[0].getMessage())
    assert inside["trace_id"] == root.span_id == child.trace_id
    assert inside["span_id"] == child.span_id
    outside = json.loads(caplog.records[1].getMessage())
    assert "trace_id" not in outside and "span_id" not in outside


# --- prewarm: every coalescable bucket compiled at startup --------------------


def test_prewarm_compiles_every_power_of_two_bucket(serving_artifact):
    from cobalt_smart_lender_ai_tpu.config import ServeConfig
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    store, _ = serving_artifact
    svc = ScorerService.from_store(
        store,
        ServeConfig(precompile_batch_buckets=(), microbatch_max_rows=4),
    )
    try:
        ready, payload = svc.ready()
        assert ready
        assert payload["microbatch"]["prewarm_all_buckets"] is True
        # /readyz lists the warmed set: margin AND shap for 1, 2, 4
        assert set(payload["compiled_batch_buckets"]) >= {1, 2, 4}
        assert set(payload["compiled_shap_buckets"]) >= {1, 2, 4}
    finally:
        svc.close()

    svc = ScorerService.from_store(
        store,
        ServeConfig(
            precompile_batch_buckets=(),
            microbatch_max_rows=4,
            prewarm_all_buckets=False,
        ),
    )
    try:
        _, payload = svc.ready()
        assert payload["microbatch"]["prewarm_all_buckets"] is False
        assert 2 not in payload["compiled_batch_buckets"]  # only the cap
        assert 4 in payload["compiled_batch_buckets"]
    finally:
        svc.close()


# --- acceptance: the injected slow request is nameable from the outside -------


def _payload() -> dict:
    from cobalt_smart_lender_ai_tpu.data import schema
    from cobalt_smart_lender_ai_tpu.serve.service import SINGLE_INPUT_FIELDS

    return {
        canonical: 1 if canonical in schema.SERVING_INT_FEATURES else 1.5
        for canonical in SINGLE_INPUT_FIELDS.values()
    }


def test_slow_request_visible_end_to_end(serving_artifact):
    """The ISSUE acceptance drill over a real socket: inject one slow SHAP
    call, then (a) /debug/slowest names the request and blames the shap
    phase, (b) its trace id resolves in /debug/trace to a serve.shap span,
    (c) /slo shows the latency objectives burning while availability stays
    clean."""
    import time

    from cobalt_smart_lender_ai_tpu.config import ServeConfig
    from cobalt_smart_lender_ai_tpu.serve.http_asyncio import make_async_server
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    store, _ = serving_artifact
    svc = ScorerService.from_store(
        store,
        ServeConfig(
            precompile_batch_buckets=(),
            microbatch_enabled=False,  # direct path: no prewarm, no worker
            flight_slow_threshold_ms=50.0,
            slo_p99_ms=10.0,
        ),
    )
    orig_shap = svc._model.shap_fn

    def slow_shap(*args, **kwargs):
        time.sleep(0.12)
        return orig_shap(*args, **kwargs)

    svc._model.shap_fn = slow_shap
    server = make_async_server(svc, "127.0.0.1", 0)
    base = f"http://127.0.0.1:{server.port}"

    def get(path):
        with urllib.request.urlopen(base + path, timeout=30) as resp:
            return json.loads(resp.read())

    try:
        req = urllib.request.Request(
            base + "/predict",
            data=json.dumps(_payload()).encode(),
            headers={
                "Content-Type": "application/json",
                "X-Request-ID": "slow-one",
            },
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.status == 200

        # (a) the flight recorder names the request and the phase
        board = get("/debug/slowest?k=5")["slowest"]
        rec = board[0]
        assert rec["request_id"] == "slow-one" and rec["slow"]
        assert max(rec["phases_ms"], key=rec["phases_ms"].get) == "shap"
        assert rec["phases_ms"]["shap"] >= 100.0
        recent = get("/debug/requests?n=5")["recent"]
        assert recent[0]["request_id"] == "slow-one"

        # (b) its trace id resolves on the exported timeline
        events = get("/debug/trace")["traceEvents"]
        mine = [
            e for e in events
            if e["ph"] == "X" and e["args"].get("trace_id") == rec["trace_id"]
        ]
        names = {e["name"] for e in mine}
        assert {"http.request", "serve.shap"} <= names
        shap_ev = next(e for e in mine if e["name"] == "serve.shap")
        assert shap_ev["dur"] >= 100_000  # >=100ms, in microseconds

        # (c) the SLO engine sees the burn — latency only
        report = get("/slo")
        by_name = {o["name"]: o for o in report["objectives"]}
        p99 = by_name["predict_latency_p99"]
        assert p99["bad"] >= 1 and p99["fast_burn"]
        assert by_name["availability"]["bad"] == 0
        assert not by_name["availability"]["fast_burn"]
    finally:
        server.close()
        svc.close()
