"""UI data-path tests: form payload → served API → waterfall/bulk rendering
data, over real HTTP against the stdlib server (no Streamlit needed — the
render shell is `ui/app.py`; everything it computes lives in `ui/core`)."""

import math

import matplotlib

matplotlib.use("Agg")

import matplotlib.pyplot as plt
import numpy as np
import pandas as pd
import pytest

from cobalt_smart_lender_ai_tpu.data import schema
from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, ObjectStore
from cobalt_smart_lender_ai_tpu.serve import ScorerService
from cobalt_smart_lender_ai_tpu.serve.http_asyncio import make_async_server
from cobalt_smart_lender_ai_tpu.serve.service import validate_single_input


def _fast_cfg():
    """Default serving config minus the all-bucket prewarm — this module
    doesn't exercise cold-bucket tails, and the extra per-bucket compiles
    are pure tier-1 wall time."""
    from cobalt_smart_lender_ai_tpu.config import ServeConfig

    return ServeConfig(prewarm_all_buckets=False)

from cobalt_smart_lender_ai_tpu.ui import core


@pytest.fixture(scope="module")
def ui_env(tmp_path_factory, engineered):
    """Small model on the 20-feature serving contract behind a live server."""
    from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTClassifier

    tree_ff, _, _ = engineered
    ff = tree_ff.select(schema.SERVING_FEATURES)
    model = GBDTClassifier(n_estimators=20, max_depth=3, n_bins=32)
    model.fit(np.asarray(ff.X), np.asarray(ff.y))
    store = ObjectStore(str(tmp_path_factory.mktemp("ui") / "lake"))
    GBDTArtifact(
        forest=model.forest,
        bin_spec=model.bin_spec,
        feature_names=tuple(schema.SERVING_FEATURES),
    ).save(store, "models/gbdt/model_tree")
    server = make_async_server(
        ScorerService.from_store(store, _fast_cfg()), "127.0.0.1", 0
    )
    yield core.ApiClient(f"http://127.0.0.1:{server.port}")
    server.close()


def default_form_payload():
    numeric = {f: d for f, _, d in core.NUMERIC_INPUTS}
    checkboxes = {"grade_E": True, "home_ownership_MORTGAGE": True}
    return core.build_single_payload(numeric, checkboxes, "No_Hardship")


def test_payload_matches_serving_schema():
    payload = default_form_payload()
    # exactly the 20 canonical serving names, aliases already applied
    assert set(payload) == set(schema.SERVING_FEATURES)
    assert payload["hardship_status_No Hardship"] == 1
    assert payload["application_type_Joint App"] == 0
    assert payload["grade_E"] == 1
    # and it passes the server-side schema validation unchanged
    row = validate_single_input(payload)
    assert row["loan_amnt"] == 10000.0


def test_unknown_hardship_rejected():
    numeric = {f: d for f, _, d in core.NUMERIC_INPUTS}
    with pytest.raises(ValueError):
        core.build_single_payload(numeric, {}, "NOT_A_STATUS")


def test_single_prediction_waterfall_additivity(ui_env):
    resp = ui_env.predict(default_form_payload())
    assert 0.0 <= resp["prob_default"] <= 1.0
    wf = core.build_waterfall(resp, max_display=10)
    # f(x) = base + sum(phi) = logit(prob): the waterfall must land exactly
    # on the served margin (TreeSHAP additivity surfaced through the UI path)
    margin = math.log(resp["prob_default"] / (1 - resp["prob_default"]))
    assert wf.fx == pytest.approx(margin, abs=1e-4)
    assert wf.base_value == pytest.approx(resp["base_value"])
    # bars accumulate: each starts where the previous ended
    cum = wf.base_value
    for item in wf.items:
        assert item.start == pytest.approx(cum, abs=1e-9)
        cum += item.value
    assert cum == pytest.approx(wf.fx)
    # 20 features, max_display 10 -> 9 shown + 1 collapsed remainder
    assert len(wf.items) == 10
    assert wf.items[0].label == "11 other features"
    # shown bars ordered ascending |phi| bottom-to-top (largest next to f(x))
    mags = [abs(i.value) for i in wf.items[1:]]
    assert mags == sorted(mags)


def test_waterfall_render_draws_all_bars(ui_env):
    wf = core.build_waterfall(ui_env.predict(default_form_payload()))
    fig, ax = plt.subplots()
    core.render_waterfall(ax, wf)
    assert len(ax.patches) == len(wf.items)
    plt.close(fig)


def test_bulk_flow_results_and_importances(ui_env, engineered):
    tree_ff, _, _ = engineered
    ff = tree_ff.select(schema.SERVING_FEATURES)
    sample = pd.DataFrame(
        np.asarray(ff.X[:8]), columns=list(schema.SERVING_FEATURES)
    )
    records = ui_env.predict_bulk_csv(
        "sample.csv", sample.to_csv(index=False).encode()
    )
    df = core.coerce_results_frame(records)
    assert len(df) == 8 and "prob_default" in df.columns
    # "null" strings (server-side NaN encoding) coerced back to NaN floats
    assert df["prob_default"].between(0, 1).all()
    assert all(df.dtypes[c].kind in "fi" for c in df.columns)

    imp = core.importance_series(ui_env.feature_importance_bulk(records))
    assert 0 < len(imp) <= 10
    assert list(imp.values) == sorted(imp.values, reverse=True)
    assert all(name in schema.SERVING_FEATURES for name in imp.index)


def test_app_module_imports_without_streamlit():
    # the render shell must stay importable in environments without the
    # [ui] extra (streamlit is deferred into main())
    from cobalt_smart_lender_ai_tpu.ui import app

    assert callable(app.main)
