"""NaN-guard / finite-check / profiler hooks (SURVEY §5.1-5.2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cobalt_smart_lender_ai_tpu.debug import (
    assert_all_finite,
    nan_guard,
    profile_trace,
)


def test_assert_all_finite_passes_and_raises():
    assert_all_finite({"a": jnp.ones(3), "b": np.zeros(2)})
    with pytest.raises(FloatingPointError, match="loss"):
        assert_all_finite({"loss": jnp.array([1.0, jnp.nan])}, name="")


def test_nan_guard_toggles_config():
    assert not jax.config.jax_debug_nans
    with nan_guard():
        assert jax.config.jax_debug_nans
        with pytest.raises(FloatingPointError):
            jnp.log(jnp.zeros(2)) - jnp.log(jnp.zeros(2))  # inf - inf
    assert not jax.config.jax_debug_nans


def test_train_loop_raises_on_divergence():
    from cobalt_smart_lender_ai_tpu.models.nn import MLP
    from cobalt_smart_lender_ai_tpu.models.train_loop import (
        TrainSettings,
        fit_binary,
    )

    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.normal(size=(64, 4)).astype(np.float32))
    y = jnp.asarray((rng.random(64) > 0.5).astype(np.float32))
    module = MLP(hidden=(4,))
    params = module.init(jax.random.PRNGKey(0), X[:1])
    settings = TrainSettings(epochs=2, batch_size=32, l2=1e38)  # loss -> inf
    with pytest.raises(FloatingPointError, match="diverged"):
        fit_binary(
            lambda p, xb, rngs: module.apply(p, xb), params, X, y, settings
        )


def test_profile_trace_writes_events(tmp_path):
    with profile_trace(str(tmp_path / "trace")):
        jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))).block_until_ready()
    files = list((tmp_path / "trace").rglob("*"))
    assert any(f.is_file() for f in files)


def test_profile_trace_noop_when_disabled():
    with profile_trace(None):
        pass


def test_persistent_compile_cache_sets_config(tmp_path):
    from cobalt_smart_lender_ai_tpu.debug import enable_persistent_compile_cache

    prev = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        d = enable_persistent_compile_cache(str(tmp_path / "cache"))
        assert d == str(tmp_path / "cache")
        assert jax.config.jax_compilation_cache_dir == d
        assert (tmp_path / "cache").is_dir()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", prev_min)


def test_persistent_compile_cache_degrades_on_unwritable_dir(tmp_path):
    """Opportunistic for real: an unwritable cache path must disable caching
    (return None), never raise into the caller (the serve entrypoint calls
    this unconditionally)."""
    from cobalt_smart_lender_ai_tpu.debug import enable_persistent_compile_cache

    blocker = tmp_path / "file"
    blocker.write_text("not a dir")
    prev = jax.config.jax_compilation_cache_dir
    try:
        # makedirs under a regular file raises OSError -> swallowed.
        assert enable_persistent_compile_cache(str(blocker / "cache")) is None
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_retry_first_dispatch_policy():
    """Retries the transient remote-compile failure on the first dispatch
    only (rebuilding state), re-raises everything else."""
    from cobalt_smart_lender_ai_tpu.debug import retry_first_dispatch

    calls = {"n": 0, "rebuilt": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise jax.errors.JaxRuntimeError(
                "INTERNAL: http://x/remote_compile: response body closed "
                "before all bytes were read"
            )
        return "ok"

    out = retry_first_dispatch(
        flaky, lambda: calls.__setitem__("rebuilt", calls["rebuilt"] + 1),
        is_first=True,
    )
    assert out == "ok" and calls == {"n": 2, "rebuilt": 1}

    def always():
        raise jax.errors.JaxRuntimeError(
            "remote_compile: response body closed before all bytes were read"
        )

    with pytest.raises(jax.errors.JaxRuntimeError):  # not first -> no retry
        retry_first_dispatch(always, lambda: None, is_first=False)
    with pytest.raises(ValueError):  # non-transient -> no retry
        retry_first_dispatch(
            lambda: (_ for _ in ()).throw(ValueError("boom")),
            lambda: None,
            is_first=True,
        )


def test_transient_match_requires_rpc_symptom():
    """A deterministic compiler failure that merely MENTIONS remote_compile
    must fail fast (no 3x retry) — only the RPC channel-death symptoms are
    transient."""
    from cobalt_smart_lender_ai_tpu.debug import is_transient_compile_error

    rpc = jax.errors.JaxRuntimeError(
        "INTERNAL: http://x/remote_compile: response body closed before "
        "all bytes were read"
    )
    assert is_transient_compile_error(rpc)
    assert is_transient_compile_error(
        jax.errors.JaxRuntimeError("remote_compile: UNAVAILABLE: connection reset")
    )
    deterministic = jax.errors.JaxRuntimeError(
        "INVALID_ARGUMENT: remote_compile failed: HLO verification error"
    )
    assert not is_transient_compile_error(deterministic)
    assert not is_transient_compile_error(ValueError("response body closed"))


def test_force_virtual_cpu_devices_is_idempotent_on_cpu():
    """Under the test harness the backend is already the 8-device virtual
    CPU; re-forcing the same count must keep the flag singular and the
    platform cpu (the helper regex-replaces rather than appends)."""
    import os

    from cobalt_smart_lender_ai_tpu.debug import force_virtual_cpu_devices

    force_virtual_cpu_devices(8)
    flags = os.environ.get("XLA_FLAGS", "")
    assert flags.count("xla_force_host_platform_device_count") == 1
    assert len(jax.devices()) == 8
