"""Pallas histogram kernel (`ops/hist_pallas.py`): parity with the exact
segment-sum formulation across shapes, run in interpret mode on the CPU
backend (the kernel itself targets TPU; interpret mode executes the same
program). The g/h channels carry the same deliberate bf16-operand rounding
as the TPU matmul formulation (`ops/histogram.py:20-28`): ~0.4% relative,
rank-statistic-safe; the w (cover) channel is exact."""

import numpy as np
import pytest

import jax.numpy as jnp

from cobalt_smart_lender_ai_tpu.ops.histogram import gradient_histogram
from cobalt_smart_lender_ai_tpu.ops.hist_pallas import (
    hist_pallas,
    pallas_supported,
)


@pytest.mark.parametrize(
    "N,F,B,K",
    [
        (3000, 10, 16, 4),  # mid-level node fan
        (1000, 7, 16, 1),  # root level, ragged feature count
        (5000, 33, 64, 2),  # bench bin width
        (2048, 4, 256, 8),  # widest bins, deep level
    ],
)
def test_parity_with_segsum(N, F, B, K):
    rng = np.random.default_rng(N + F + B + K)
    bins = jnp.asarray(rng.integers(0, B, (N, F)).astype(np.uint8))
    node = jnp.asarray(rng.integers(0, K, N).astype(np.int32))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.abs(g) + 0.1
    w = jnp.asarray((rng.random(N) < 0.9).astype(np.float32))
    ref = np.asarray(
        gradient_histogram(bins, node, g, h, w, n_nodes=K, n_bins=B, impl="segsum")
    )
    got = np.asarray(
        hist_pallas(bins, node, g, h, w, n_nodes=K, n_bins=B, interpret=True)
    )
    assert got.shape == (K, F, B, 3)
    # cover channel is 0/1 sums — exact in bf16
    np.testing.assert_array_equal(got[..., 2], ref[..., 2])
    # g/h: bf16 operand rounding, scale-relative to the node totals
    scale = np.abs(ref[..., :2]).max()
    np.testing.assert_allclose(got[..., :2], ref[..., :2], atol=1e-2 * scale)


def test_zero_weight_rows_contribute_nothing():
    rng = np.random.default_rng(0)
    N, F, B, K = 515, 5, 16, 2  # deliberately not a multiple of the row block
    bins = jnp.asarray(rng.integers(0, B, (N, F)).astype(np.uint8))
    node = jnp.asarray(rng.integers(0, K, N).astype(np.int32))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.abs(g) + 0.1
    w = jnp.zeros(N)
    got = np.asarray(
        hist_pallas(bins, node, g * 0, h * 0, w, n_nodes=K, n_bins=B, interpret=True)
    )
    np.testing.assert_array_equal(got, np.zeros_like(got))


def test_supported_guard():
    assert pallas_supported(100, 64, 4)  # the bench shape
    assert pallas_supported(100, 255, 4)  # config-default bins
    assert not pallas_supported(100, 64, 64)  # C = 192 lanes: too wide


def test_matmul_impl_matches_segsum():
    """The TPU matmul formulation (per-block node-one-hot rhs built inside
    the scan) must agree with the segment-sum oracle on every channel."""
    rng = np.random.default_rng(3)
    N, F, K, B = 5000, 7, 8, 16
    bins = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.int32))
    node = jnp.asarray(rng.integers(0, K, size=(N,), dtype=np.int32))
    g = jnp.asarray(rng.normal(size=N).astype(np.float32))
    h = jnp.asarray(rng.random(N).astype(np.float32))
    w = jnp.asarray((rng.random(N) < 0.8).astype(np.float32))
    ref = gradient_histogram(bins, node, g, h, w, n_nodes=K, n_bins=B, impl="segsum")
    # row_block smaller than N exercises the block padding path too
    out = gradient_histogram(
        bins, node, g, h, w, n_nodes=K, n_bins=B, impl="matmul", row_block=1024
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=1e-3)
