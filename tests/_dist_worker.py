"""Worker for the real multi-process bootstrap test (test_distributed.py).

Launched twice by the parent test with COORDINATOR_ADDRESS / NUM_PROCESSES /
PROCESS_ID in the environment — the exact env contract `DistributedConfig.
from_env` reads on a TPU pod — on the CPU backend. Executes the real
`jax.distributed.initialize` path (parallel/distributed.py:80-84), builds the
global (hp, dp) mesh over both processes' devices, and psums a per-process
value across them; the parent asserts both ranks print the full-mesh sum.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # a sitecustomize may pre-import jax

import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from cobalt_smart_lender_ai_tpu.config import MeshConfig  # noqa: E402
from cobalt_smart_lender_ai_tpu.parallel.compat import shard_map  # noqa: E402
from cobalt_smart_lender_ai_tpu.parallel.distributed import (  # noqa: E402
    init_distributed,
    make_global_mesh,
)


def main() -> None:
    active = init_distributed()  # config comes from the env, as on a pod
    assert active, "expected a multi-process runtime"
    assert jax.process_count() == 2, jax.process_count()
    rank = jax.process_index()
    n = jax.device_count()
    assert n >= 2 and jax.local_device_count() < n

    mesh = make_global_mesh(MeshConfig(hp=1, dp=n))
    sharding = NamedSharding(mesh, P(None, "dp"))
    local = np.full(
        (1, jax.local_device_count()), float(rank + 1), dtype=np.float32
    )
    arr = jax.make_array_from_process_local_data(sharding, local, (1, n))

    from functools import partial

    @jax.jit
    @partial(
        shard_map, mesh=mesh, in_specs=P(None, "dp"), out_specs=P(None, "dp")
    )
    def total(x):
        return jax.numpy.broadcast_to(jax.lax.psum(x.sum(), "dp"), x.shape)

    out = total(arr)
    # Every shard must hold sum over ranks of (rank+1) * local_device_count.
    expect = sum(
        (r + 1) * (n // jax.process_count()) for r in range(jax.process_count())
    )
    got = float(np.asarray(out.addressable_shards[0].data)[0, 0])
    assert got == expect, (got, expect)
    print(f"RANK{rank}_PSUM_OK={got}", flush=True)


if __name__ == "__main__":
    main()
    sys.exit(0)
