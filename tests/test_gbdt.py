"""Histogram-GBDT tests: parity vs a CPU gradient-boosting oracle (sklearn
stands in for the reference's XGBoost, which isn't installed here), predict
path consistency, missing-value routing, and the vmapped HPO axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.ensemble import HistGradientBoostingClassifier
from sklearn.metrics import roc_auc_score

from cobalt_smart_lender_ai_tpu.models.gbdt import (
    GBDTClassifier,
    GBDTHyperparams,
    fit_binned,
    gain_importances,
    predict_margin,
)
from cobalt_smart_lender_ai_tpu.ops.binning import compute_bin_edges, transform


@pytest.fixture(scope="module")
def fitted(train_test):
    X_train, X_test, y_train, y_test, _ = train_test
    model = GBDTClassifier(
        n_estimators=60, max_depth=4, learning_rate=0.3, n_bins=64, seed=42
    )
    model.fit(X_train, y_train)
    return model


def test_auc_parity_with_sklearn(train_test, fitted):
    """Parity gate (SURVEY §7.3): within 2 AUC points of the CPU oracle on
    identical engineered LendingClub-style data."""
    X_train, X_test, y_train, y_test, _ = train_test
    ours = roc_auc_score(y_test, np.asarray(fitted.predict_proba(X_test)[:, 1]))
    oracle = HistGradientBoostingClassifier(
        max_iter=60, max_depth=4, learning_rate=0.3, max_bins=63, random_state=0
    ).fit(X_train, y_train)
    theirs = roc_auc_score(y_test, oracle.predict_proba(X_test)[:, 1])
    assert ours > 0.70
    assert ours >= theirs - 0.02, f"ours={ours:.4f} oracle={theirs:.4f}"


def test_binned_and_float_predict_agree(train_test, fitted):
    X_train, X_test, *_ = train_test
    bins = transform(fitted.bin_spec, jnp.asarray(X_test, jnp.float32))
    mb = predict_margin(fitted.forest, bins, use_binned=True)
    mf = fitted.predict_margin(X_test)
    np.testing.assert_allclose(np.asarray(mb), np.asarray(mf), rtol=1e-5, atol=1e-5)


def test_predict_proba_shape_and_range(train_test, fitted):
    _, X_test, *_ = train_test
    proba = np.asarray(fitted.predict_proba(X_test))
    assert proba.shape == (X_test.shape[0], 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)
    assert ((proba >= 0) & (proba <= 1)).all()


def test_missing_values_learned_direction():
    """A feature whose NaN-ness is itself the signal: the tree must route
    missing rows to the correct side (xgboost's learned default direction)."""
    rng = np.random.default_rng(3)
    n = 2000
    X = rng.normal(size=(n, 3)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.int32)
    X[y == 1, 0] = np.nan  # missingness encodes the label
    model = GBDTClassifier(n_estimators=5, max_depth=2, n_bins=16).fit(X, y)
    p = np.asarray(model.predict_proba(X)[:, 1])
    assert roc_auc_score(y, p) > 0.99


def test_scale_pos_weight_raises_positive_recall(train_test):
    X_train, X_test, y_train, y_test, _ = train_test
    spw = float((y_train == 0).sum() / max((y_train == 1).sum(), 1))
    base = GBDTClassifier(n_estimators=30, max_depth=3, n_bins=32).fit(X_train, y_train)
    weighted = GBDTClassifier(
        n_estimators=30, max_depth=3, n_bins=32, scale_pos_weight=spw
    ).fit(X_train, y_train)
    rec = lambda m: ((np.asarray(m.predict(X_test)) == 1) & (y_test == 1)).sum() / max(
        (y_test == 1).sum(), 1
    )
    assert rec(weighted) > rec(base)


def test_vmapped_hyperparameter_candidates(train_test):
    """The HPO design bet: all hyperparams (incl. n_estimators/max_depth) are
    traced, so a candidate grid is one vmap — no per-candidate recompiles."""
    X_train, _, y_train, _, _ = train_test
    X = jnp.asarray(X_train[:1500], jnp.float32)
    y = jnp.asarray(y_train[:1500])
    spec = compute_bin_edges(X, n_bins=32)
    bins = transform(spec, X)
    sw = jnp.ones(X.shape[0])
    fm = jnp.ones(X.shape[1], bool)

    f32, i32 = jnp.float32, jnp.int32
    ones = jnp.ones(2, f32)
    hps = GBDTHyperparams(
        learning_rate=jnp.array([0.3, 0.1], f32),
        gamma=ones * 0,
        reg_lambda=ones,
        min_child_weight=ones,
        scale_pos_weight=ones,
        subsample=ones,
        colsample_bytree=ones,
        n_estimators=jnp.array([20, 8], i32),
        max_depth=jnp.array([3, 2], i32),
    )
    keys = jax.random.split(jax.random.PRNGKey(0), 2)
    forests = jax.vmap(
        lambda hp, k: fit_binned(
            bins, y, sw, fm, hp, k, n_trees_cap=20, depth_cap=3, n_bins=32
        )
    )(hps, keys)
    # candidate 1: trees beyond its n_estimators=8 must be inert
    lv = np.asarray(forests.leaf_value)
    assert np.all(lv[1, 8:] == 0) and np.any(lv[1, :8] != 0)
    # candidate 1: max_depth=2 within depth_cap=3 → level-2 nodes are trivial
    assert not np.asarray(forests.gain)[1][:, 3:7].any()
    margins = jax.vmap(lambda fo: predict_margin(fo, bins, use_binned=True))(forests)
    for i in range(2):
        assert roc_auc_score(np.asarray(y), np.asarray(margins[i])) > 0.75


def test_feature_mask_excludes_features(train_test):
    """RFE support: masked features never appear in real splits."""
    X_train, _, y_train, _, _ = train_test
    F = X_train.shape[1]
    mask = np.ones(F, bool)
    mask[: F // 2] = False
    model = GBDTClassifier(n_estimators=10, max_depth=3, n_bins=32)
    model.fit(X_train, y_train, feature_mask=mask)
    real = np.asarray(model.forest.is_real_split())
    used = np.unique(np.asarray(model.forest.feature)[real])
    assert np.all(mask[used])


def test_gain_importances_rank_signal_over_noise():
    rng = np.random.default_rng(0)
    n = 3000
    signal = rng.normal(size=(n, 2)).astype(np.float32)
    noise = rng.normal(size=(n, 4)).astype(np.float32)
    y = (signal[:, 0] + 2 * signal[:, 1] > 0).astype(np.int32)
    X = np.concatenate([signal, noise], axis=1)
    model = GBDTClassifier(n_estimators=20, max_depth=3, n_bins=32).fit(X, y)
    imp = model.feature_importances_
    assert imp[:2].sum() > 0.8
    total_gain, n_splits = gain_importances(model.forest, 6)
    assert float(n_splits.sum()) > 0


def test_chunked_classifier_fit_is_identical():
    """GBDTConfig.chunk_trees splits the fit across dispatches without
    changing a single bit of the model (global tree offsets preserve RNG
    streams and the n_estimators mask)."""
    rng = np.random.default_rng(3)
    X = rng.normal(size=(1500, 10)).astype(np.float32)
    y = (X[:, 0] - X[:, 1] > 0).astype(np.int32)
    a = GBDTClassifier(n_estimators=30, max_depth=3, n_bins=32, subsample=0.8).fit(X, y)
    b = GBDTClassifier(
        n_estimators=30, max_depth=3, n_bins=32, subsample=0.8, chunk_trees=7
    ).fit(X, y)
    np.testing.assert_array_equal(
        np.asarray(a.predict_margin(X)), np.asarray(b.predict_margin(X))
    )
    np.testing.assert_array_equal(
        np.asarray(a.forest.feature), np.asarray(b.forest.feature)
    )


def test_wide_binning_routing_is_exact():
    """n_bins > 256 (binning.py emits int32 bins there) must route rows
    exactly: the fit's carried margin and a fresh `predict_margin` re-route
    of the same forest are bitwise equal — bf16 would round integer bin
    values above 256 and silently misroute (the routing dtype rule)."""
    from cobalt_smart_lender_ai_tpu.models.gbdt import fit_binned_resumable

    rng = np.random.default_rng(3)
    N, F, n_bins = 3000, 6, 300
    X = jnp.asarray(rng.normal(size=(N, F)).astype(np.float32))
    y = jnp.asarray((np.asarray(X[:, 0]) > 0.2).astype(np.int32))
    spec = compute_bin_edges(X, n_bins=n_bins)
    bins = transform(spec, X)
    assert int(jnp.max(bins)) > 256  # the regime under test
    hp = GBDTHyperparams.from_config(
        __import__(
            "cobalt_smart_lender_ai_tpu.config", fromlist=["GBDTConfig"]
        ).GBDTConfig(n_estimators=12, max_depth=5, n_bins=n_bins)
    )
    forest, margin_fit = fit_binned_resumable(
        bins, y, jnp.ones((N,)), jnp.ones((F,), bool), hp,
        jax.random.PRNGKey(0),
        n_trees_cap=12, depth_cap=5, n_bins=n_bins,
    )
    margin_pred = predict_margin(forest, bins, use_binned=True)
    np.testing.assert_array_equal(
        np.asarray(margin_fit), np.asarray(margin_pred)
    )
