"""Asyncio-native serving core: one event loop from socket to batcher future.

Pins the tentpole guarantees of `serve.http_asyncio` + the service's async
mode:

- `MicroBatcher.submit_async` coalesces awaiting coroutines into one device
  dispatch exactly like thread-blocked `submit` callers (deterministic via
  `pause`);
- a queued request whose deadline expires resolves its 504 on the event
  loop with NO batch slot consumed and NO thread parked — the batcher can be
  wedged solid and the client still gets its typed answer on time;
- a hot reload racing an in-flight awaited batch never mixes models inside
  one batch;
- the error taxonomy (422/400/404/429 shed/503 circuit_open/500
  reload_failed) holds exactly on the asyncio adapter, and scoring bodies
  are byte-stable across server instances (the contract the removed
  threaded adapter used to be pinned against);
- the /readyz, /slo, /debug/*, /metrics (classic + OpenMetrics) contracts
  hold unchanged on the asyncio adapter;
- request ids minted at ingress for id-less clients join across logs,
  flight records, batch spans and exemplars (the ``"request_ids": []``
  regression);
- chaos soak (marked ``slow`` + ``faults``, CI faults job): store faults +
  latency + concurrent hot swaps against the asyncio adapter produce zero
  untyped 500s.
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from cobalt_smart_lender_ai_tpu.config import ReliabilityConfig, ServeConfig
from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, ObjectStore
from cobalt_smart_lender_ai_tpu.reliability import (
    DeadlineExceeded,
    FaultInjectingStore,
    FaultSpec,
    start_deadline,
)
from cobalt_smart_lender_ai_tpu.serve.http_asyncio import make_async_server
from cobalt_smart_lender_ai_tpu.serve.service import ScorerService


def _cfg(**kw) -> ServeConfig:
    rel = {
        k: kw.pop(k)
        for k in list(kw)
        if k in ReliabilityConfig.__dataclass_fields__
    }
    base = dict(prewarm_all_buckets=False)
    base.update(kw)
    if rel:
        base["reliability"] = ReliabilityConfig(**rel)
    return ServeConfig(**base)


def _valid_payload() -> dict:
    from cobalt_smart_lender_ai_tpu.data import schema
    from cobalt_smart_lender_ai_tpu.serve.service import SINGLE_INPUT_FIELDS

    return {
        canonical: (1 if canonical in schema.SERVING_INT_FEATURES else 1.5)
        for canonical in SINGLE_INPUT_FIELDS.values()
    }


@contextlib.contextmanager
def _serving(service):
    """Run ``service`` behind the asyncio adapter; yields the base URL."""
    server = make_async_server(service)
    try:
        yield f"http://127.0.0.1:{server.port}"
    finally:
        server.close()


def _request(url, data=None, content_type="application/json", headers=None):
    req = urllib.request.Request(
        url, data=data, method="POST" if data is not None else "GET"
    )
    if data is not None:
        req.add_header("Content-Type", content_type)
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


# --- awaitable-future batcher mode --------------------------------------------


def test_submit_async_coalesces_under_paused_batcher(serving_artifact):
    """N coroutines awaiting `submit_async` under a paused batcher all land
    in ONE dispatched batch when the pause lifts — the awaitable mode feeds
    the same queue/worker as the thread-blocking mode."""
    store, _ = serving_artifact
    svc = ScorerService.from_store(
        store, _cfg(microbatch_enabled=True, microbatch_max_wait_ms=5.0)
    )
    try:
        payload = _valid_payload()
        before = svc.batcher.stats()

        async def drive():
            with svc.batcher.pause():
                tasks = [
                    asyncio.ensure_future(svc.predict_single_async(payload))
                    for _ in range(5)
                ]
                # let every coroutine run to its await (enqueue its row)
                for _ in range(20):
                    await asyncio.sleep(0.005)
                    if svc.batcher.queue_depth() == 5:
                        break
                assert svc.batcher.queue_depth() == 5
            return await asyncio.gather(*tasks)

        resps = asyncio.run(drive())
        assert len(resps) == 5
        assert len({r["prob_default"] for r in resps}) == 1
        after = svc.batcher.stats()
        assert after["batches"] == before["batches"] + 1
        assert after["coalesced_rows"] == before["coalesced_rows"] + 5
    finally:
        svc.close()


def test_queued_deadline_504_resolves_without_batch_slot(serving_artifact):
    """A deadline expiring while the request sits in the batcher queue must
    resolve the awaiting coroutine with a 504 ON TIME — while the batcher is
    still wedged (paused), so no dispatch and no worker involvement produced
    the answer, and no OS thread sat parked on `Future.result`. The worker
    later counts the expiry exactly once when it finally drains the queue."""
    store, _ = serving_artifact
    svc = ScorerService.from_store(store, _cfg(microbatch_enabled=True))
    try:
        payload = _valid_payload()
        threads_before = threading.active_count()

        async def drive():
            with svc.batcher.pause():
                dl = start_deadline(0.2)
                t0 = time.monotonic()
                with pytest.raises(DeadlineExceeded) as ei:
                    await svc.predict_single_async(payload, deadline=dl)
                elapsed = time.monotonic() - t0
                # resolved by the loop timer, not by a batch slot
                assert svc.batcher.stats()["batches"] == 0
                return ei.value, elapsed

        exc, elapsed = asyncio.run(drive())
        assert exc.status == 504
        assert "queued" in str(exc.detail)
        assert elapsed < 2.0  # loop timer, not a 30s default deadline
        assert threading.active_count() <= threads_before + 1
        # pause lifted: the worker drains the stale entry and accounts it
        deadline_drain = time.monotonic() + 5.0
        while (
            svc.batcher.stats()["expired_in_queue"] < 1
            and time.monotonic() < deadline_drain
        ):
            time.sleep(0.01)
        assert svc.batcher.stats()["expired_in_queue"] == 1
        # and the service still scores cleanly afterwards
        resp = svc.predict_single(payload)
        assert 0.0 <= resp["prob_default"] <= 1.0
    finally:
        svc.close()


def test_hot_reload_mid_await_never_mixes_models(tmp_path, serving_artifact):
    """Requests awaiting in the batcher queue when a hot reload lands are
    scored wholly by ONE model — the batch snapshots its model under the
    dispatch lock, so a swap mid-await can delay a batch but never split
    it across models."""
    shared, _ = serving_artifact
    art = GBDTArtifact.load(shared, "models/gbdt/model_tree")
    store = ObjectStore(str(tmp_path / "lake"))
    art.save(store, "models/gbdt/model_tree")
    # all-zero leaves: margin 0 -> P(default) exactly 0.5 for any input
    import jax.numpy as jnp

    zeroed = dataclasses.replace(
        art,
        forest=dataclasses.replace(
            art.forest, leaf_value=jnp.zeros_like(art.forest.leaf_value)
        ),
    )
    zeroed.save(store, "models/gbdt/v2")

    svc = ScorerService.from_store(
        store, _cfg(microbatch_enabled=True, score_cache_size=0)
    )
    try:
        payload = _valid_payload()
        old_prob = svc.predict_single(payload)["prob_default"]
        assert old_prob != 0.5  # otherwise the swap would be unobservable

        async def drive():
            from cobalt_smart_lender_ai_tpu.serve.service import _in_executor

            with svc.batcher.pause():
                tasks = [
                    asyncio.ensure_future(svc.predict_single_async(payload))
                    for _ in range(4)
                ]
                for _ in range(40):
                    await asyncio.sleep(0.005)
                    if svc.batcher.queue_depth() == 4:
                        break
                assert svc.batcher.queue_depth() == 4
                # the reload parks on the batcher's pause gate; its own
                # pause count keeps the worker held until publish completes
                reload_fut = _in_executor(
                    svc.reload_from_store, model_key="models/gbdt/v2"
                )
                await asyncio.sleep(0.05)
                assert not reload_fut.done()
            resps = await asyncio.gather(*tasks)
            assert (await reload_fut)["status"] == "ok"
            return resps

        resps = asyncio.run(drive())
        probs = {r["prob_default"] for r in resps}
        assert len(probs) == 1, f"one batch scored by two models: {probs}"
        assert probs == {0.5}  # publish happened before the batch dispatched
    finally:
        svc.close()


# --- taxonomy + byte-stability (the removed threaded adapter's coverage) ------


def _taxonomy_trace(tag: str, tmp_path, serving_artifact) -> list[tuple]:
    shared, _ = serving_artifact
    art = GBDTArtifact.load(shared, "models/gbdt/model_tree")
    store = ObjectStore(str(tmp_path / f"lake-{tag}"))
    art.save(store, "models/gbdt/model_tree")
    flaky = FaultInjectingStore(store, faults={})
    svc = ScorerService.from_store(
        flaky,
        _cfg(
            microbatch_enabled=True,
            max_in_flight=1,
            breaker_failure_threshold=3,
            breaker_reset_s=60.0,
        ),
    )
    ok = json.dumps(_valid_payload()).encode()
    trace: list[tuple] = []

    def probe(path, data=None, ct="application/json"):
        status, body, headers = _request(base + path, data, ct)
        parsed = json.loads(body.decode()) if body else {}
        trace.append(
            (path, status, parsed.get("error"), "Retry-After" in headers)
        )
        return status, parsed

    try:
        with _serving(svc) as base:
            probe("/predict", ok)  # 200
            probe("/predict", b"{}")  # 422 invalid_input
            probe("/feature_importance_bulk", b'{"data": []}')  # 400
            probe("/nope", b"{}")  # 404
            slot = svc.admission.admit()
            slot.__enter__()
            try:
                probe("/predict", ok)  # 429 shed + Retry-After
            finally:
                slot.__exit__(None, None, None)
            flaky.faults["get"] = FaultSpec(fail_after=0)
            for _ in range(3):
                probe("/admin/reload", b"{}")  # 500 reload_failed x3
            probe("/admin/reload", b"{}")  # 503 circuit_open + Retry-After
    finally:
        svc.close()
    return trace


def test_error_taxonomy_exact_sequence(tmp_path, serving_artifact):
    """Admission 429, breaker 503, and the 4xx taxonomy present the exact
    (status, error-code, Retry-After) sequence the removed threaded adapter
    was pinned to — the contract survives the adapter."""
    trace = _taxonomy_trace("asyncio", tmp_path, serving_artifact)
    statuses = [s for _, s, _, _ in trace]
    assert statuses == [200, 422, 400, 404, 429, 500, 500, 500, 503]
    codes = [c for _, _, c, _ in trace]
    assert codes[1] == "invalid_input"
    assert codes[4] == "shed"
    assert codes[5:8] == ["reload_failed"] * 3
    assert codes[8] == "circuit_open"
    retry_after = [ra for _, _, _, ra in trace]
    assert retry_after[4] and retry_after[8]  # shed + circuit_open carry it


def test_bodies_byte_stable_across_server_instances(serving_artifact):
    """Two independent server instances over one service return
    byte-for-byte identical bodies for every deterministic route — the
    serialization-stability half of the old adapter byte-parity pin."""
    from cobalt_smart_lender_ai_tpu.data import schema

    store, X = serving_artifact
    # cache off: every response goes through the batcher, so a hit-vs-miss
    # difference can never masquerade as serialization stability
    svc = ScorerService.from_store(
        store, _cfg(microbatch_enabled=True, score_cache_size=0)
    )
    import pandas as pd

    csv = (
        pd.DataFrame(X[:3], columns=list(schema.SERVING_FEATURES))
        .to_csv(index=False)
        .encode()
    )
    ok = json.dumps(_valid_payload()).encode()
    probes = [
        ("/predict", ok, "application/json"),
        ("/predict", b"{}", "application/json"),
        ("/predict", b"{not json", "application/json"),
        ("/predict_bulk_csv", csv, "text/csv"),
        ("/feature_importance_bulk", b'{"data": [{"a": 1.0}]}',
         "application/json"),
        ("/feature_importance_bulk", b'{"data": []}', "application/json"),
        ("/healthz", None, ""),
        ("/nope", None, ""),
        ("/nope", b"{}", "application/json"),
    ]
    try:
        observed: dict[str, list] = {}
        for run in ("first", "second"):
            with _serving(svc) as base:
                observed[run] = [
                    _request(base + path, data, ct)[:2]
                    for path, data, ct in probes
                ]
        for (path, _, _), a, b in zip(
            probes, observed["first"], observed["second"]
        ):
            assert a == b, f"{path}: first {a} != second {b}"
    finally:
        svc.close()


# --- observability contracts on the asyncio adapter ---------------------------


def test_asyncio_adapter_observability_contracts(serving_artifact):
    """/readyz, /slo, /debug/* and /metrics (classic + OpenMetrics) serve
    their exact threaded-era contracts from the event loop."""
    from cobalt_smart_lender_ai_tpu.telemetry import parse_exposition

    store, _ = serving_artifact
    svc = ScorerService.from_store(store, _cfg(microbatch_enabled=True))
    ok = json.dumps(_valid_payload()).encode()
    try:
        with _serving(svc) as base:
            for _ in range(3):
                status, _, _ = _request(base + "/predict", ok)
                assert status == 200

            status, body, _ = _request(base + "/readyz")
            ready = json.loads(body)
            assert status == 200 and ready["status"] == "ok"
            assert {"model_key", "admission", "breaker"} <= set(ready)

            status, body, _ = _request(base + "/slo")
            slo = json.loads(body)
            assert status == 200
            assert {"fast_burn", "windows_s", "objectives"} <= set(slo)

            status, body, _ = _request(base + "/debug/requests?limit=5")
            recent = json.loads(body)["recent"]
            assert recent and {"request_id", "trace_id", "phases_ms"} <= set(
                recent[0]
            )
            status, body, _ = _request(base + "/debug/requests?limit=0")
            assert status == 422
            assert json.loads(body)["error"] == "invalid_input"
            status, body, _ = _request(base + "/debug/requests?phase=nope")
            assert status == 422
            assert json.loads(body)["error"] == "invalid_input"

            status, body, _ = _request(base + "/debug/slowest?limit=3")
            assert status == 200 and "slowest" in json.loads(body)

            status, body, _ = _request(base + "/debug/programs")
            progs = json.loads(body)
            assert status == 200
            assert {"programs", "totals"} <= set(progs)

            status, body, _ = _request(base + "/debug/trace")
            assert status == 200 and "traceEvents" in json.loads(body)

            status, body, headers = _request(base + "/metrics")
            assert status == 200
            assert headers["Content-Type"].startswith("text/plain")
            families = parse_exposition(body.decode())
            assert "cobalt_request_latency_seconds" in families
            assert "cobalt_request_phase_seconds" in families

            status, body, headers = _request(
                base + "/metrics",
                headers={"Accept": "application/openmetrics-text"},
            )
            text = body.decode()
            assert status == 200
            assert headers["Content-Type"].startswith(
                "application/openmetrics-text"
            )
            assert text.rstrip().endswith("# EOF")
            assert "# {trace_id=" in text  # exemplars on latency buckets
    finally:
        svc.close()


def test_request_id_minted_at_ingress_joins_everything(serving_artifact):
    """An id-less client gets a minted X-Request-ID whose value joins the
    flight record, the batch span's ``request_ids`` (previously ``[]`` for
    id-less clients), and — via the flight record's trace id — the
    OpenMetrics exemplars. Error logs carry the same id."""
    import logging

    store, _ = serving_artifact
    svc = ScorerService.from_store(
        store, _cfg(microbatch_enabled=True, score_cache_size=0)
    )
    ok = json.dumps(_valid_payload()).encode()
    try:
        with _serving(svc) as base:
            status, _, headers = _request(base + "/predict", ok)
            assert status == 200
            rid = headers["X-Request-ID"]
            assert rid  # minted server-side, echoed back

            # flight record join
            _, body, _ = _request(base + "/debug/requests?limit=50")
            recs = [
                r
                for r in json.loads(body)["recent"]
                if r["request_id"] == rid
            ]
            assert recs, "minted id absent from the flight recorder"
            trace_id = recs[0]["trace_id"]

            # batch span join: the dispatch span names the minted id
            _, body, _ = _request(base + "/debug/trace")
            batch_spans = [
                ev
                for ev in json.loads(body)["traceEvents"]
                if ev.get("name") == "serve.microbatch_dispatch"
            ]
            assert batch_spans, "no dispatch spans in the ring"
            sped = [
                ev
                for ev in batch_spans
                if rid in (ev.get("args") or {}).get("request_ids", [])
            ]
            assert sped, "minted id absent from batch span request_ids"
            for ev in batch_spans:
                assert (ev.get("args") or {}).get("request_ids"), (
                    "empty request_ids on a dispatch span: ingress minting "
                    "regressed"
                )

            # exemplar join via the flight record's trace id
            _, body, _ = _request(
                base + "/metrics",
                headers={"Accept": "application/openmetrics-text"},
            )
            assert f'trace_id="{trace_id}"' in body.decode()

            # log join: an error log inside the same request context carries
            # the client-visible id
            logger = logging.getLogger("cobalt.serve.http_asyncio")
            seen: list[str] = []

            class _Tap(logging.Handler):
                def emit(self, record):
                    seen.append(record.getMessage())

            tap = _Tap()
            logger.addHandler(tap)
            try:
                status, _, headers = _request(base + "/predict", b"{}")
                assert status == 422
                err_rid = headers["X-Request-ID"]
                # the warning is emitted on the server's loop thread; give
                # it a beat to land before inspecting
                give_up = time.monotonic() + 5.0
                while (
                    not any(err_rid in line for line in seen)
                    and time.monotonic() < give_up
                ):
                    time.sleep(0.01)
            finally:
                logger.removeHandler(tap)
            assert any(err_rid in line for line in seen)
    finally:
        svc.close()


# --- chaos soak ---------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.faults
def test_async_chaos_soak_zero_untyped_500s(tmp_path, serving_artifact):
    """Store faults + injected latency + concurrent hot swaps against the
    ASYNCIO adapter: every observed failure is a typed policy decision
    (zero untyped 500s), scoring keeps working, and the loop serves
    /metrics throughout."""
    from cobalt_smart_lender_ai_tpu.data import schema

    shared, X = serving_artifact
    art = GBDTArtifact.load(shared, "models/gbdt/model_tree")
    store = ObjectStore(str(tmp_path / "lake"))
    art.save(store, "models/gbdt/model_tree")
    import jax.numpy as jnp

    zeroed = dataclasses.replace(
        art,
        forest=dataclasses.replace(
            art.forest, leaf_value=jnp.zeros_like(art.forest.leaf_value)
        ),
    )
    zeroed.save(store, "models/gbdt/v2")
    store.put_bytes("models/poison.npz", b"\x00poisoned artifact bytes")
    flaky = FaultInjectingStore(store, seed=7, faults={})
    svc = ScorerService.from_store(
        flaky,
        _cfg(
            microbatch_enabled=True,
            request_deadline_s=10.0,
            max_in_flight=4,
            breaker_failure_threshold=3,
            breaker_reset_s=0.2,
        ),
    )
    flaky.faults["get"] = FaultSpec(rate=0.25, delay_s=0.002, delay_jitter_s=0.004)

    import pandas as pd

    ok = json.dumps(_valid_payload()).encode()
    csv = (
        pd.DataFrame(X[:8], columns=list(schema.SERVING_FEATURES))
        .to_csv(index=False)
        .encode()
    )
    cycle = [
        ("/predict", ok, "application/json"),
        ("/predict", b"{}", "application/json"),
        ("/predict_bulk_csv", csv, "text/csv"),
        ("/feature_importance_bulk", b'{"data": []}', "application/json"),
        ("/metrics", None, ""),
        ("/readyz", None, ""),
    ]
    results: list[tuple[str, int, bytes]] = []
    lock = threading.Lock()
    stop = threading.Event()

    def hammer(offset: int) -> None:
        i = offset
        while not stop.is_set():
            path, data, ct = cycle[i % len(cycle)]
            i += 1
            try:
                status, body, _ = _request(base + path, data, ct)
            except urllib.error.URLError:
                continue
            with lock:
                results.append((path, status, body))

    try:
        with _serving(svc) as base:
            threads = [
                threading.Thread(target=hammer, args=(i,), daemon=True)
                for i in range(4)
            ]
            for t in threads:
                t.start()
            reload_ok = rolled_back = 0
            # Two good keys per poison attempt: with a strict poison/good
            # alternation, every half-open breaker probe lands back on the
            # always-failing poison key and the good key only ever sees
            # circuit_open 503s (lock-step starvation).
            keys = ["models/gbdt/v2", "models/poison", "models/gbdt/model_tree"]
            give_up = time.monotonic() + 120.0
            # Keep the chaos running a while even after both outcomes are
            # observed, so the hammer threads accumulate real traffic.
            min_soak = time.monotonic() + 8.0
            i = 0
            while (
                reload_ok < 1
                or rolled_back < 1
                or time.monotonic() < min_soak
            ) and time.monotonic() < give_up:
                status, body, _ = _request(
                    base + "/admin/reload",
                    json.dumps({"model_key": keys[i % len(keys)]}).encode(),
                )
                i += 1
                parsed = json.loads(body)
                if status == 200 and parsed.get("status") == "ok":
                    reload_ok += 1
                elif status == 500 and parsed.get("error") == "reload_failed":
                    rolled_back += 1
                elif status == 503:
                    time.sleep(0.25)
                time.sleep(0.01)
            stop.set()
            for t in threads:
                t.join(timeout=30)
            del flaky.faults["get"]
            final_status, final_body, _ = _request(base + "/predict", ok)
    finally:
        svc.close()

    assert reload_ok >= 1, "no hot swap succeeded during chaos"
    assert rolled_back >= 1, "no poisoned swap rolled back during chaos"
    assert final_status == 200
    assert 0.0 <= json.loads(final_body)["prob_default"] <= 1.0
    assert len(results) > 50, "soak produced too little traffic"
    allowed = {200, 400, 413, 422, 429, 500, 503, 504}
    for path, status, body in results:
        assert status in allowed, (path, status, body)
        if status == 500:
            assert "error" in json.loads(body), (path, body)
    statuses = {s for _, s, _ in results}
    assert 200 in statuses
