"""Deployment-manifest parity (C13): the compose file and Dockerfiles must
preserve the reference's deployed surface — two services named api/ui on
ports 8000/8001, a shared bridge network, the UI wired to the api service via
API_URL (docker-compose.yml:1-26) — and every path/command they reference
must exist in this repo."""

import pathlib
import re

import pytest
import yaml

ROOT = pathlib.Path(__file__).resolve().parent.parent


def compose():
    return yaml.safe_load((ROOT / "docker-compose.yml").read_text())


def test_compose_two_services_on_reference_ports():
    doc = compose()
    services = doc["services"]
    assert set(services) == {"api", "ui"}
    assert "8000:8000" in services["api"]["ports"]
    assert "8001:8001" in services["ui"]["ports"]
    # shared bridge network, like the reference's cobalt-network
    net = next(iter(doc["networks"].values()))
    assert net["driver"] == "bridge"
    for svc in services.values():
        assert list(doc["networks"]) == svc["networks"]


def test_compose_ui_reaches_api_by_service_name():
    services = compose()["services"]
    env = dict(e.split("=", 1) for e in services["ui"]["environment"])
    assert env["API_URL"].split("#")[0].strip() == "http://api:8000"


def test_compose_dockerfiles_exist_and_expose_declared_ports():
    services = compose()["services"]
    for name, port in [("api", 8000), ("ui", 8001)]:
        df_path = ROOT / services[name]["build"]["dockerfile"]
        assert df_path.exists(), df_path
        text = df_path.read_text()
        assert f"EXPOSE {port}" in text
        # every COPY source in the build context must exist
        for line in text.splitlines():
            if line.startswith("COPY"):
                for src in line.split()[1:-1]:
                    assert (ROOT / src).exists(), f"{df_path.name}: {src}"


def test_api_container_entrypoint_is_the_serve_cli():
    text = (ROOT / "deploy" / "api.Dockerfile").read_text()
    cmd = re.search(r'CMD \[(.+?)\]', text, re.S).group(1)
    assert "cobalt_smart_lender_ai_tpu.serve" in cmd
    # the module the CMD runs must be executable (python -m) in this repo
    assert (
        ROOT / "cobalt_smart_lender_ai_tpu" / "serve" / "__main__.py"
    ).exists()


def test_ui_container_runs_the_streamlit_shell():
    text = (ROOT / "deploy" / "ui.Dockerfile").read_text()
    m = re.search(r"CMD \[(.+?)\]", text, re.S).group(1)
    assert "streamlit" in m and "ui/app.py" in m
    assert (ROOT / "cobalt_smart_lender_ai_tpu" / "ui" / "app.py").exists()


def test_store_uri_env_reaches_the_serve_cli(monkeypatch, tmp_path):
    # compose sets COBALT_STORE_URI; the CLI must restore from that URI when
    # no --store flag is passed. Capture the ObjectStore the CLI builds by
    # stubbing the restore + server steps.
    import cobalt_smart_lender_ai_tpu.serve.__main__ as m

    monkeypatch.setenv("COBALT_STORE_URI", str(tmp_path / "lake"))
    monkeypatch.setattr("sys.argv", ["serve"])
    seen = {}

    class FakeService:
        feature_names = ["f0"]

    def fake_from_store(store, cfg, **_kw):  # clock= rides along since the
        seen["store"] = store  # ReplicaSet facade took over the CLI entry
        raise SystemExit  # stop before the HTTP server starts

    monkeypatch.setattr(m.ScorerService, "from_store", fake_from_store)
    with __import__("pytest").raises(SystemExit):
        m.main()
    assert str(tmp_path / "lake") in repr(vars(seen["store"]))


@pytest.mark.skipif(
    not (ROOT / "artifacts" / "models" / "gbdt").exists(),
    reason="committed artifact not yet trained (tools/train_artifact.py)",
)
def test_committed_artifact_serves_out_of_the_box():
    """The reference ships its trained model in-repo
    (src/api/models/xgb_model_tree.pkl) so the API container serves without
    a training run (cobalt_fast_api.py:36-54). Our counterpart: the
    committed GBDTArtifact at the default ServeConfig location must restore
    and score a full 20-feature payload in a fresh ScorerService."""
    import numpy as np

    from cobalt_smart_lender_ai_tpu.config import ServeConfig
    from cobalt_smart_lender_ai_tpu.data import schema
    from cobalt_smart_lender_ai_tpu.io import ObjectStore
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    cfg = ServeConfig()
    store = ObjectStore(str(ROOT / "artifacts"))
    service = ScorerService.from_store(store, cfg)
    assert tuple(service.feature_names) == schema.SERVING_FEATURES
    row = {name: 1.0 for name in schema.SERVING_FEATURES}
    row.update({
        "loan_amnt": 12000.0, "term": 36.0, "installment": 380.0,
        "fico_range_low": 690.0, "last_fico_range_high": 700.0,
        "earliest_cr_line_days": 5200.0, "emp_length_num": 6.0,
    })
    out = service.predict_single(row)
    p = out["prob_default"]
    assert 0.0 <= p <= 1.0 and np.isfinite(p)
    assert len(out["shap_values"]) == len(schema.SERVING_FEATURES)
    # provenance rides the artifact
    assert service.artifact.metrics.get("test_auc", 0) >= 0.9
