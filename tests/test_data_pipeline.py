"""Golden tests for the cleaning + feature-engineering rules (SURVEY §4a)."""

import numpy as np
import pandas as pd
import pytest

from cobalt_smart_lender_ai_tpu.data import schema
from cobalt_smart_lender_ai_tpu.data.clean import (
    clean_raw_frame,
    parse_percent,
    parse_term,
)
from cobalt_smart_lender_ai_tpu.data.features import (
    drop_training_leakage,
    engineer_features,
    prepare_cleaned_frame,
)
from cobalt_smart_lender_ai_tpu.data.split import (
    split_mask,
    stratified_fold_ids,
    train_test_split_hashed,
)


def test_parse_term_and_percent():
    assert parse_term(pd.Series([" 36 months", " 60 months"])).tolist() == [36, 60]
    out = parse_percent(pd.Series(["13.56%", "7.00%"]))
    np.testing.assert_allclose(out.to_numpy(), [0.1356, 0.07])


def test_clean_drops_unnamed_and_sparse_and_duplicates(raw_frame):
    cleaned, report = clean_raw_frame(raw_frame)
    assert "Unnamed: 0" not in cleaned.columns
    assert not any(c.startswith("junk_sparse") for c in cleaned.columns)
    for c in schema.CLEAN_UNNECESSARY_COLS:
        assert c not in cleaned.columns
    assert report.n_duplicates_removed >= 1
    assert cleaned.duplicated().sum() == 0
    # missing-means-zero columns are fully filled
    for c in schema.FILL_ZERO_COLS:
        assert cleaned[c].isnull().sum() == 0
    # term / int_rate parsed to numerics
    assert np.issubdtype(cleaned["term"].dtype, np.number)
    assert cleaned["int_rate"].between(0, 1).all()
    assert cleaned["hardship_status"].isnull().sum() == 0


def test_prepare_creates_label_and_numeric_conversions(raw_frame):
    cleaned, _ = clean_raw_frame(raw_frame)
    prepared = prepare_cleaned_frame(cleaned)
    # leakage + useless columns are gone (feature_engineering.py:56-63)
    for c in schema.FE_LEAKAGE_COLS + schema.FE_USELESS_COLS:
        assert c not in prepared.columns
    assert schema.LABEL_COL in prepared.columns
    assert set(np.unique(prepared[schema.LABEL_COL])) <= {0, 1}
    assert "emp_length_num" in prepared.columns
    assert prepared["emp_length_num"].max() <= 10
    assert "earliest_cr_line_days" in prepared.columns
    assert prepared["earliest_cr_line_days"].min() > 0
    assert prepared["revol_util"].dtype.kind == "f"


def test_label_map_matches_reference():
    statuses = list(schema.LOAN_STATUS_MAP)
    df = pd.DataFrame({"loan_status": statuses})
    out = prepare_cleaned_frame(df)
    expected = [schema.LOAN_STATUS_MAP[s] for s in statuses]
    assert out[schema.LABEL_COL].tolist() == expected


def test_engineer_tree_one_hot_and_log(raw_frame):
    cleaned, _ = clean_raw_frame(raw_frame)
    prepared = prepare_cleaned_frame(cleaned)
    tree_ff, nn_ff, plan = engineer_features(prepared)

    # one-hot columns exist with drop_first semantics: first sorted category absent
    assert "grade_B" in tree_ff.feature_names
    assert "grade_A" not in tree_ff.feature_names
    assert "hardship_status_No Hardship" in tree_ff.feature_names
    assert "application_type_Joint App" in tree_ff.feature_names

    # one-hot block values are 0/1 and rows sum to <= 1 per categorical
    gcols = [i for i, n in enumerate(tree_ff.feature_names) if n.startswith("grade_")]
    gblock = np.asarray(tree_ff.X[:, gcols])
    assert set(np.unique(gblock)) <= {0.0, 1.0}
    assert (gblock.sum(axis=1) <= 1).all()

    # log1p applied to a strictly-positive skewed column: values shrink
    li = tree_ff.feature_names.index("annual_inc")
    raw_inc = prepared["annual_inc"].to_numpy()
    np.testing.assert_allclose(
        np.asarray(tree_ff.X[:, li]), np.log1p(raw_inc), rtol=1e-4
    )

    # a non-log column is untouched
    ti = tree_ff.feature_names.index("term")
    np.testing.assert_allclose(
        np.asarray(tree_ff.X[:, ti]), prepared["term"].to_numpy(), rtol=1e-6
    )


def test_engineer_nn_impute_and_indicators(raw_frame):
    cleaned, _ = clean_raw_frame(raw_frame)
    prepared = prepare_cleaned_frame(cleaned)
    _, nn_ff, plan = engineer_features(prepared)
    Xnn = np.asarray(nn_ff.X)
    assert not np.isnan(Xnn).any()
    # indicator exists for a column with missingness
    assert "mths_since_last_delinq_NA" in nn_ff.feature_names
    assert "no_income" in nn_ff.feature_names
    assert "dti_NA" in nn_ff.feature_names
    # indicator agrees with raw missingness
    ind = Xnn[:, nn_ff.feature_names.index("mths_since_last_delinq_NA")]
    raw_nan = prepared["mths_since_last_delinq"].isnull().to_numpy()
    np.testing.assert_array_equal(ind.astype(bool), raw_nan)
    # imputed value equals the median of the log-transformed column
    col = np.log1p(prepared["mths_since_last_delinq"].to_numpy())
    med = np.nanmedian(col)
    filled = Xnn[:, nn_ff.feature_names.index("mths_since_last_delinq")]
    np.testing.assert_allclose(filled[raw_nan], med, rtol=1e-5)
    # categorical label codes are integral and in range
    gcol = Xnn[:, nn_ff.feature_names.index("grade")]
    assert gcol.min() >= 0 and gcol.max() < len(plan.categorical_vocab["grade"]) + 1


def test_drop_training_leakage(engineered):
    tree_ff, _, _ = engineered
    ff = drop_training_leakage(tree_ff)
    for c in schema.TRAIN_LEAKAGE_COLS:
        assert c not in ff.feature_names
    assert ff.X.shape[1] == len(ff.feature_names)


def test_split_deterministic_and_sized():
    m1 = np.asarray(split_mask(10_000, 0.2, 22))
    m2 = np.asarray(split_mask(10_000, 0.2, 22))
    np.testing.assert_array_equal(m1, m2)
    assert abs(m1.mean() - 0.2) < 0.02
    # stable under growth: first 10k assignments unchanged at 20k rows
    m3 = np.asarray(split_mask(20_000, 0.2, 22))
    np.testing.assert_array_equal(m1, m3[:10_000])
    # different seed → different split
    assert not np.array_equal(m1, np.asarray(split_mask(10_000, 0.2, 23)))


def test_split_arrays_shapes():
    X = np.arange(200, dtype=np.float32).reshape(100, 2)
    y = (np.arange(100) % 2).astype(np.float32)
    X_tr, X_te, y_tr, y_te = train_test_split_hashed(X, y, test_fraction=0.3, seed=1)
    assert X_tr.shape[0] + X_te.shape[0] == 100
    assert y_tr.shape[0] == X_tr.shape[0]


def test_stratified_folds_balance():
    y = np.array([0] * 90 + [1] * 9)
    folds = stratified_fold_ids(y, 3, seed=0)
    for k in range(3):
        sel = folds == k
        assert y[sel].sum() == 3  # positives evenly spread
        assert sel.sum() == 33
