"""Golden tests for the cleaning + feature-engineering rules (SURVEY §4a)."""

import numpy as np
import pandas as pd
import pytest

from cobalt_smart_lender_ai_tpu.data import schema
from cobalt_smart_lender_ai_tpu.data.clean import (
    clean_raw_frame,
    parse_percent,
    parse_term,
)
from cobalt_smart_lender_ai_tpu.data.features import (
    drop_training_leakage,
    engineer_features,
    prepare_cleaned_frame,
)
from cobalt_smart_lender_ai_tpu.data.split import (
    split_mask,
    stratified_fold_ids,
    train_test_split_hashed,
)


def test_parse_term_and_percent():
    assert parse_term(pd.Series([" 36 months", " 60 months"])).tolist() == [36, 60]
    out = parse_percent(pd.Series(["13.56%", "7.00%"]))
    np.testing.assert_allclose(out.to_numpy(), [0.1356, 0.07])


def test_parse_percent_degenerate_cells():
    # Whitespace-only / empty / NaN / garbage cells coerce to NaN instead of
    # raising; clean parses survive alongside them. Already-numeric input
    # still divides by 100 (clean rule 4 applies it unconditionally).
    out = parse_percent(pd.Series(["13.56%", "  ", "", None, np.nan, "bogus"]))
    np.testing.assert_allclose(out.iloc[0], 0.1356)
    assert out.iloc[1:].isnull().all()
    np.testing.assert_allclose(
        parse_percent(pd.Series([13.56, 7.0])).to_numpy(), [0.1356, 0.07]
    )


def test_parse_term_degenerate_cells():
    # Same tolerance for term: degenerate cells -> NaN, which degrades the
    # column to float (NaN has no int representation); an all-present
    # column keeps the reference's int dtype.
    out = parse_term(pd.Series([" 36 months", "   ", "", None, np.nan]))
    assert out.iloc[0] == 36.0
    assert out.dtype.kind == "f"
    assert out.iloc[1:].isnull().all()
    clean = parse_term(pd.Series([" 36 months", " 60 months"]))
    assert clean.dtype.kind == "i"
    # numeric passthrough keeps values as-is
    assert parse_term(pd.Series([36.0, 60.0])).tolist() == [36, 60]


def test_clean_drops_unnamed_and_sparse_and_duplicates(raw_frame):
    cleaned, report = clean_raw_frame(raw_frame)
    assert "Unnamed: 0" not in cleaned.columns
    assert not any(c.startswith("junk_sparse") for c in cleaned.columns)
    for c in schema.CLEAN_UNNECESSARY_COLS:
        assert c not in cleaned.columns
    assert report.n_duplicates_removed >= 1
    assert cleaned.duplicated().sum() == 0
    # missing-means-zero columns are fully filled
    for c in schema.FILL_ZERO_COLS:
        assert cleaned[c].isnull().sum() == 0
    # term / int_rate parsed to numerics
    assert np.issubdtype(cleaned["term"].dtype, np.number)
    assert cleaned["int_rate"].between(0, 1).all()
    assert cleaned["hardship_status"].isnull().sum() == 0


def test_prepare_creates_label_and_numeric_conversions(raw_frame):
    cleaned, _ = clean_raw_frame(raw_frame)
    prepared = prepare_cleaned_frame(cleaned)
    # leakage + useless columns are gone (feature_engineering.py:56-63)
    for c in schema.FE_LEAKAGE_COLS + schema.FE_USELESS_COLS:
        assert c not in prepared.columns
    assert schema.LABEL_COL in prepared.columns
    assert set(np.unique(prepared[schema.LABEL_COL])) <= {0, 1}
    assert "emp_length_num" in prepared.columns
    assert prepared["emp_length_num"].max() <= 10
    assert "earliest_cr_line_days" in prepared.columns
    assert prepared["earliest_cr_line_days"].min() > 0
    assert prepared["revol_util"].dtype.kind == "f"


def test_label_map_matches_reference():
    statuses = list(schema.LOAN_STATUS_MAP)
    df = pd.DataFrame({"loan_status": statuses})
    out = prepare_cleaned_frame(df)
    expected = [schema.LOAN_STATUS_MAP[s] for s in statuses]
    assert out[schema.LABEL_COL].tolist() == expected


def test_engineer_tree_one_hot_and_log(raw_frame):
    cleaned, _ = clean_raw_frame(raw_frame)
    prepared = prepare_cleaned_frame(cleaned)
    tree_ff, nn_ff, plan = engineer_features(prepared)

    # one-hot columns exist with drop_first semantics: first sorted category absent
    assert "grade_B" in tree_ff.feature_names
    assert "grade_A" not in tree_ff.feature_names
    assert "hardship_status_No Hardship" in tree_ff.feature_names
    assert "application_type_Joint App" in tree_ff.feature_names

    # one-hot block values are 0/1 and rows sum to <= 1 per categorical
    gcols = [i for i, n in enumerate(tree_ff.feature_names) if n.startswith("grade_")]
    gblock = np.asarray(tree_ff.X[:, gcols])
    assert set(np.unique(gblock)) <= {0.0, 1.0}
    assert (gblock.sum(axis=1) <= 1).all()

    # log1p applied to a strictly-positive skewed column: values shrink
    li = tree_ff.feature_names.index("annual_inc")
    raw_inc = prepared["annual_inc"].to_numpy()
    np.testing.assert_allclose(
        np.asarray(tree_ff.X[:, li]), np.log1p(raw_inc), rtol=1e-4
    )

    # a non-log column is untouched
    ti = tree_ff.feature_names.index("term")
    np.testing.assert_allclose(
        np.asarray(tree_ff.X[:, ti]), prepared["term"].to_numpy(), rtol=1e-6
    )


def test_engineer_nn_impute_and_indicators(raw_frame):
    cleaned, _ = clean_raw_frame(raw_frame)
    prepared = prepare_cleaned_frame(cleaned)
    _, nn_ff, plan = engineer_features(prepared)
    Xnn = np.asarray(nn_ff.X)
    assert not np.isnan(Xnn).any()
    # indicator exists for a column with missingness
    assert "mths_since_last_delinq_NA" in nn_ff.feature_names
    assert "no_income" in nn_ff.feature_names
    assert "dti_NA" in nn_ff.feature_names
    # indicator agrees with raw missingness
    ind = Xnn[:, nn_ff.feature_names.index("mths_since_last_delinq_NA")]
    raw_nan = prepared["mths_since_last_delinq"].isnull().to_numpy()
    np.testing.assert_array_equal(ind.astype(bool), raw_nan)
    # imputed value equals the median of the log-transformed column
    col = np.log1p(prepared["mths_since_last_delinq"].to_numpy())
    med = np.nanmedian(col)
    filled = Xnn[:, nn_ff.feature_names.index("mths_since_last_delinq")]
    np.testing.assert_allclose(filled[raw_nan], med, rtol=1e-5)
    # categorical label codes are integral and in range
    gcol = Xnn[:, nn_ff.feature_names.index("grade")]
    assert gcol.min() >= 0 and gcol.max() < len(plan.categorical_vocab["grade"]) + 1


def test_drop_training_leakage(engineered):
    tree_ff, _, _ = engineered
    ff = drop_training_leakage(tree_ff)
    for c in schema.TRAIN_LEAKAGE_COLS:
        assert c not in ff.feature_names
    assert ff.X.shape[1] == len(ff.feature_names)


# The reference's raw table after dropping the two index-artifact columns:
# 141 columns, transcribed from /root/reference/notebooks/01_data_cleaning.ipynb
# cell 26 (`df_dropped.isnull().sum()` lists every column).
REFERENCE_RAW_COLUMNS = (
    "id loan_amnt funded_amnt funded_amnt_inv term int_rate installment "
    "grade sub_grade emp_title emp_length home_ownership annual_inc "
    "verification_status issue_d loan_status pymnt_plan url purpose title "
    "zip_code addr_state dti delinq_2yrs earliest_cr_line fico_range_low "
    "fico_range_high inq_last_6mths mths_since_last_delinq "
    "mths_since_last_record open_acc pub_rec revol_bal revol_util total_acc "
    "initial_list_status out_prncp out_prncp_inv total_pymnt total_pymnt_inv "
    "total_rec_prncp total_rec_int total_rec_late_fee recoveries "
    "collection_recovery_fee last_pymnt_d last_pymnt_amnt next_pymnt_d "
    "last_credit_pull_d last_fico_range_high last_fico_range_low "
    "collections_12_mths_ex_med mths_since_last_major_derog policy_code "
    "application_type annual_inc_joint dti_joint verification_status_joint "
    "acc_now_delinq tot_coll_amt tot_cur_bal open_acc_6m open_act_il "
    "open_il_12m open_il_24m mths_since_rcnt_il total_bal_il il_util "
    "open_rv_12m open_rv_24m max_bal_bc all_util total_rev_hi_lim inq_fi "
    "total_cu_tl inq_last_12m acc_open_past_24mths avg_cur_bal "
    "bc_open_to_buy bc_util chargeoff_within_12_mths delinq_amnt "
    "mo_sin_old_il_acct mo_sin_old_rev_tl_op mo_sin_rcnt_rev_tl_op "
    "mo_sin_rcnt_tl mort_acc mths_since_recent_bc mths_since_recent_bc_dlq "
    "mths_since_recent_inq mths_since_recent_revol_delinq "
    "num_accts_ever_120_pd num_actv_bc_tl num_actv_rev_tl num_bc_sats "
    "num_bc_tl num_il_tl num_op_rev_tl num_rev_accts num_rev_tl_bal_gt_0 "
    "num_sats num_tl_120dpd_2m num_tl_30dpd num_tl_90g_dpd_24m "
    "num_tl_op_past_12m pct_tl_nvr_dlq percent_bc_gt_75 "
    "pub_rec_bankruptcies tax_liens tot_hi_cred_lim total_bal_ex_mort "
    "total_bc_limit total_il_high_credit_limit revol_bal_joint "
    "sec_app_fico_range_low sec_app_fico_range_high "
    "sec_app_earliest_cr_line sec_app_inq_last_6mths sec_app_mort_acc "
    "sec_app_open_acc sec_app_revol_util sec_app_open_act_il "
    "sec_app_num_rev_accts sec_app_chargeoff_within_12_mths "
    "sec_app_collections_12_mths_ex_med hardship_flag hardship_type "
    "hardship_reason hardship_status deferral_term hardship_amount "
    "hardship_start_date hardship_end_date payment_plan_start_date "
    "hardship_length hardship_dpd hardship_loan_status "
    "orig_projected_additional_accrued_interest "
    "hardship_payoff_balance_amount hardship_last_payment_amount "
    "debt_settlement_flag"
).split()


def test_reference_schema_census():
    """Pin the pipeline's observable column census to the reference's.

    Raw: the full-schema synthetic frame must cover the reference's 141 raw
    columns exactly (01_data_cleaning.ipynb cell 26). Downstream widths are
    pinned with an exact reconciliation to the reference notebook's counts
    (03_feature_engineering.ipynb cells 3/23): the notebook keeps
    `last_credit_pull_d` and `mths_since_recent_revol_delinq`, which
    src/clean_data.py:133 (our contract, schema.CLEAN_UNNECESSARY_COLS)
    drops — so cleaned = 106 - 2 = 104 and the NN frame = 116 - 3 = 113
    (those two columns plus mths_since_recent_revol_delinq_NA). A silent
    drift in data/schema.py now fails here instead of passing the suite.
    """
    import jax

    from cobalt_smart_lender_ai_tpu.data.synthetic import (
        synthetic_lendingclub_frame,
    )

    with jax.default_device(jax.devices("cpu")[0]):
        raw = synthetic_lendingclub_frame(n_rows=8000, seed=11)
        assert set(REFERENCE_RAW_COLUMNS) <= set(raw.columns), (
            sorted(set(REFERENCE_RAW_COLUMNS) - set(raw.columns))
        )
        # Only declared synthetics beyond the reference set: index artifacts
        # (dropped by UNNAMED_COLS) and the junk_sparse drop-rule probes.
        extras = set(raw.columns) - set(REFERENCE_RAW_COLUMNS)
        assert all(
            c.startswith(("Unnamed", "junk_sparse")) for c in extras
        ), sorted(extras)

        cleaned, _ = clean_raw_frame(raw)
        assert cleaned.shape[1] == 104  # reference notebook: 106 (see above)
        prepared = prepare_cleaned_frame(cleaned)
        # Row-null allowance drops the bureau-block rows, like the
        # reference's 99,995 -> 97,557 (~2.4%).
        frac_dropped = 1 - len(prepared) / len(raw)
        assert 0.01 < frac_dropped < 0.06, frac_dropped
        tree_ff, nn_ff, _ = engineer_features(prepared)
        assert len(tree_ff.feature_names) == 114
        assert len(nn_ff.feature_names) == 113  # reference: 116 (see above)
        ff = drop_training_leakage(tree_ff)
        assert len(ff.feature_names) == 104

        # Exact one-hot name set (get_dummies drop_first over the observed
        # vocabularies) and the 20-feature serving contract.
        onehots = {
            n for n in tree_ff.feature_names
            if any(n.startswith(p + "_") for p in schema.ONE_HOT_COLS)
        }
        assert len(onehots) == 31
        for want in (
            "grade_E", "home_ownership_MORTGAGE",
            "verification_status_Verified", "application_type_Joint App",
            "hardship_status_BROKEN", "hardship_status_COMPLETE",
            "hardship_status_COMPLETED", "hardship_status_No Hardship",
        ):
            assert want in onehots, want
        assert "grade_A" not in onehots  # drop_first
        for c in schema.SERVING_FEATURES:
            assert c in ff.feature_names, c

        # The imputation indicators the reference records in cell 18, minus
        # the notebook-only mths_since_recent_revol_delinq_NA.
        na_cols = {n for n in nn_ff.feature_names if n.endswith("_NA")}
        for want in (
            "emp_length_num_NA", "revol_util_NA", "open_act_il_NA",
            "open_il_12m_NA", "open_il_24m_NA", "mths_since_rcnt_il_NA",
            "total_bal_il_NA", "open_rv_12m_NA", "open_rv_24m_NA",
            "max_bal_bc_NA", "inq_fi_NA", "total_cu_tl_NA",
            "avg_cur_bal_NA", "bc_open_to_buy_NA", "bc_util_NA",
            "mo_sin_old_il_acct_NA", "mths_since_recent_bc_NA",
            "mths_since_recent_inq_NA", "num_tl_120dpd_2m_NA",
            "pct_tl_nvr_dlq_NA", "percent_bc_gt_75_NA", "dti_NA",
        ):
            assert want in na_cols, want
        assert "no_income" in nn_ff.feature_names


def test_split_deterministic_and_sized():
    m1 = np.asarray(split_mask(10_000, 0.2, 22))
    m2 = np.asarray(split_mask(10_000, 0.2, 22))
    np.testing.assert_array_equal(m1, m2)
    assert abs(m1.mean() - 0.2) < 0.02
    # stable under growth: first 10k assignments unchanged at 20k rows
    m3 = np.asarray(split_mask(20_000, 0.2, 22))
    np.testing.assert_array_equal(m1, m3[:10_000])
    # different seed → different split
    assert not np.array_equal(m1, np.asarray(split_mask(10_000, 0.2, 23)))


def test_split_arrays_shapes():
    X = np.arange(200, dtype=np.float32).reshape(100, 2)
    y = (np.arange(100) % 2).astype(np.float32)
    X_tr, X_te, y_tr, y_te = train_test_split_hashed(X, y, test_fraction=0.3, seed=1)
    assert X_tr.shape[0] + X_te.shape[0] == 100
    assert y_tr.shape[0] == X_tr.shape[0]


def test_stratified_folds_balance():
    y = np.array([0] * 90 + [1] * 9)
    folds = stratified_fold_ids(y, 3, seed=0)
    for k in range(3):
        sel = folds == k
        assert y[sel].sum() == 3  # positives evenly spread
        assert sel.sum() == 33
