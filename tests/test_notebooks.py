"""Exploration notebooks (C8): the committed notebooks must be valid
nbformat, fully executed, and error-free — the automated stand-in for the
reference's by-inspection notebook validation (SURVEY §4)."""

import pathlib

import pytest

nbformat = pytest.importorskip("nbformat")

NB_DIR = pathlib.Path(__file__).resolve().parent.parent / "notebooks"
EXPECTED = [
    "01_data_cleaning.ipynb",
    "02_eda.ipynb",
    "03_feature_engineering.ipynb",
    "04_model_training.ipynb",
]


@pytest.mark.parametrize("name", EXPECTED)
def test_notebook_executed_without_errors(name):
    nb = nbformat.read(NB_DIR / name, as_version=4)
    nbformat.validate(nb)
    code_cells = [c for c in nb.cells if c.cell_type == "code"]
    assert code_cells, "no code cells"
    for cell in code_cells:
        assert cell.execution_count is not None, "unexecuted cell committed"
        for out in cell.get("outputs", []):
            assert out.output_type != "error", out.get("evalue")


def test_training_notebook_demonstrates_the_leakage_lesson():
    nb = nbformat.read(NB_DIR / "04_model_training.ipynb", as_version=4)
    text = "".join(c.source for c in nb.cells)
    # the notebook must reproduce the reference's leakage discovery and the
    # honest retrain (its cells 11-16), plus the SHAP additivity check
    assert "drop_training_leakage" in text
    assert "shap_values" in text
    assert "randomized_search" in text
