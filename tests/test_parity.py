"""Protocol-scale parity gates (VERDICT r2 item 1).

Two layers of evidence:

1. ``test_parity_artifact_gate`` (fast, every CI run) — the committed
   ``PARITY.json`` head-to-head artifact must exist, be internally
   consistent, and pass the parity criterion
   ``ours.test_auc >= oracle.test_auc - 0.005``.

2. ``test_protocol_parity_head_to_head`` (slow-marked, ``-m slow``) — re-runs
   the live head-to-head through `tools/parity.py`: the FULL reference
   protocol (clean -> engineer -> RFE-20 step 1 -> 20x3 randomized search ->
   test eval, `model_tree_train_test.py:111-179`) on identical matrices and
   fold masks, our GBDT vs sklearn's HistGradientBoostingClassifier oracle.
   Rows default to the VERDICT's >=100k protocol scale; override with
   ``PARITY_ROWS`` for a faster local run.
"""

import json
import os
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

PARITY_MARGIN = 0.005


def _load_parity_module():
    sys.path.insert(0, str(REPO))
    try:
        from tools import parity
    finally:
        sys.path.remove(str(REPO))
    return parity


def test_parity_artifact_gate():
    """The committed artifact is the round's parity evidence; regressing it
    (or deleting it) must fail CI."""
    path = REPO / "PARITY.json"
    assert path.exists(), (
        "PARITY.json missing — run tools/parity.py (ours on the accelerator, "
        "oracle on CPU, then merge) and commit the artifact"
    )
    doc = json.loads(path.read_text())
    ours, oracle = doc["ours"], doc["oracle"]
    # Internal consistency: the recorded gap and gate must match the AUCs.
    gap = ours["test_auc"] - oracle["test_auc"]
    assert abs(doc["auc_gap_ours_minus_oracle"] - gap) < 1e-4
    assert doc["parity_margin"] == PARITY_MARGIN
    # Protocol scale: the VERDICT's >=100k-row requirement.
    assert doc["n_rows"] >= 100_000
    # Both sides ran the whole protocol: RFE chose exactly 20 of the shared
    # feature space, and the search picked a candidate from the space.
    assert len(ours["selected_features"]) == 20
    assert len(oracle["selected_features"]) == 20
    assert ours["best_params"] and oracle["best_params"]
    print(
        f"PARITY.json: ours={ours['test_auc']:.4f} "
        f"oracle={oracle['test_auc']:.4f} gap={gap:+.4f}"
    )
    assert doc["parity_ok"], (
        f"parity regressed: ours {ours['test_auc']:.4f} < "
        f"oracle {oracle['test_auc']:.4f} - {PARITY_MARGIN}"
    )
    assert gap >= -PARITY_MARGIN


@pytest.mark.slow
def test_protocol_parity_head_to_head():
    """Live full-protocol head-to-head on this backend (virtual CPU mesh in
    CI). Minutes-to-hours depending on PARITY_ROWS; deselected by default."""
    parity = _load_parity_module()
    rows = int(os.environ.get("PARITY_ROWS", "100000"))
    result = parity.run_head_to_head(rows)
    print(json.dumps(result, indent=2))
    ours, oracle = result["ours"], result["oracle"]
    print(
        f"ours={ours['test_auc']:.4f} oracle={oracle['test_auc']:.4f} "
        f"gap={result['auc_gap_ours_minus_oracle']:+.4f}"
    )
    assert result["parity_ok"], (
        f"ours {ours['test_auc']:.4f} < oracle {oracle['test_auc']:.4f} "
        f"- {PARITY_MARGIN} at {rows} rows"
    )
