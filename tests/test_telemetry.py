"""Telemetry layer: exposition format, spans, structured logs, propagation.

Pins the contracts the observability layer promises (README "Observability"):

- the Prometheus text `render()` escapes correctly, keeps labels in declared
  order, and emits cumulative histogram buckets — round-tripped through the
  strict `parse_exposition` CI uses against a live scrape;
- spans nest through the contextvar parent and time exactly under an
  injectable clock;
- structured log lines are one JSON object carrying the in-scope request id;
- a request id crosses the micro-batcher's thread boundary (captured at
  submit, visible in the dispatch span);
- ``GET /metrics`` on the stdlib adapter serves a parseable exposition with
  route/status-labeled request latencies, and the adapter echoes
  ``X-Request-ID``;
- `FaultInjectingStore` counters surface through a registry.
"""

from __future__ import annotations

import json
import logging
import math
import threading
import urllib.request

import pytest

from cobalt_smart_lender_ai_tpu.telemetry import (
    EXPOSITION_CONTENT_TYPE,
    MetricsRegistry,
    Tracer,
    get_logger,
    log_buckets,
    parse_exposition,
    request_context,
    snapshot,
)

# --- exposition format --------------------------------------------------------


def test_render_roundtrips_counter_gauge_histogram():
    reg = MetricsRegistry()
    c = reg.counter("t_requests_total", "requests", ("route", "status"))
    c.labels(route="/predict", status="200").inc()
    c.labels(route="/predict", status="200").inc(2)
    reg.gauge("t_depth", "queue depth").set(3)
    h = reg.histogram("t_latency_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)

    out = parse_exposition(reg.render())
    assert out["t_requests_total"]["type"] == "counter"
    assert out["t_depth"]["type"] == "gauge"
    assert out["t_latency_seconds"]["type"] == "histogram"
    samples = out["t_requests_total"]["samples"]
    assert samples == {"t_requests_total|route=/predict|status=200": 3.0}
    assert out["t_depth"]["samples"] == {"t_depth": 3.0}


def test_label_value_escaping_roundtrips():
    """Backslash, double-quote and newline in a label value must survive
    render -> parse unchanged — the three characters the format escapes."""
    nasty = 'a\\b"c\nd'
    reg = MetricsRegistry()
    reg.counter("t_esc_total", 'help with "quotes", \\ and\nnewline', ("k",)).labels(
        k=nasty
    ).inc()
    text = reg.render()
    assert '\\\\' in text and '\\"' in text and "\\n" in text
    out = parse_exposition(text)
    assert out["t_esc_total"]["samples"] == {f"t_esc_total|k={nasty}": 1.0}


def test_labels_render_in_declared_order_not_alphabetical():
    reg = MetricsRegistry()
    reg.counter("t_order_total", "order", ("zeta", "alpha")).labels(
        zeta="z", alpha="a"
    ).inc()
    line = [
        ln for ln in reg.render().splitlines() if ln.startswith("t_order_total{")
    ][0]
    assert line == 't_order_total{zeta="z",alpha="a"} 1'


def test_histogram_buckets_are_cumulative_with_inf_sum_count():
    reg = MetricsRegistry()
    h = reg.histogram("t_h_seconds", "h", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 3.0, 100.0):
        h.observe(v)

    cum = h._solo().cumulative()
    assert cum == [(1.0, 1), (2.0, 2), (4.0, 3), (math.inf, 4)]

    out = parse_exposition(reg.render())
    samples = out["t_h_seconds"]["samples"]

    def bucket(le: str) -> float:
        return samples[f"t_h_seconds_bucket|le={le}"]

    assert [bucket(le) for le in ("1", "2", "4", "+Inf")] == [1, 2, 3, 4]
    assert samples["t_h_seconds_count"] == 4
    assert samples["t_h_seconds_sum"] == pytest.approx(105.0)
    # +Inf bucket == _count: the invariant scrapers aggregate on
    assert bucket("+Inf") == samples["t_h_seconds_count"]


def test_registry_get_or_create_and_conflicts():
    reg = MetricsRegistry()
    a = reg.counter("t_same_total", "x", ("op",))
    assert reg.counter("t_same_total", "x", ("op",)) is a
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("t_same_total", "x", ("op",))  # kind conflict
    with pytest.raises(ValueError, match="already registered"):
        reg.counter("t_same_total", "x", ("other",))  # labelname conflict
    with pytest.raises(ValueError):
        a.labels(op="get").inc(-1)  # counters only go up
    with pytest.raises(ValueError):
        reg.counter("0bad", "x")  # invalid metric name


def test_collect_callback_failure_degrades_to_nan_not_crash():
    reg = MetricsRegistry()
    g = reg.gauge("t_live", "sampled at collect time")

    def dead():
        raise LookupError("source object is gone")

    g.set_function(dead)
    assert math.isnan(g.value)
    out = parse_exposition(reg.render())  # a dead callback must not kill scrape
    assert math.isnan(out["t_live"]["samples"]["t_live"])


def test_log_buckets_geometric_and_bounded():
    b = log_buckets(1e-3, 10.0, per_decade=2)
    assert b[0] == pytest.approx(1e-3)
    assert b[-1] >= 10.0
    assert list(b) == sorted(b)
    assert len(set(b)) == len(b)
    with pytest.raises(ValueError):
        log_buckets(1.0, 0.5)


# --- spans under an injectable clock ------------------------------------------


class FakeClock:
    def __init__(self):
        self.now = 100.0

    def __call__(self) -> float:
        return self.now


def test_span_nesting_and_exact_durations_under_fake_clock():
    clk = FakeClock()
    tr = Tracer(clock=clk, jax_annotations=False)
    with tr.span("outer", stage="fit") as outer:
        clk.now += 1.0
        with tr.span("inner") as inner:
            clk.now += 0.25
        clk.now += 0.5
    spans = tr.export()
    assert [s["name"] for s in spans] == ["inner", "outer"]  # finish order
    by_name = {s["name"]: s for s in spans}
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["outer"]["parent_id"] is None
    assert by_name["inner"]["duration_s"] == pytest.approx(0.25)
    assert by_name["outer"]["duration_s"] == pytest.approx(1.75)
    assert by_name["outer"]["attrs"] == {"stage": "fit"}
    assert outer.span_id != inner.span_id


def test_record_span_parents_under_open_span_and_ring_bounds():
    clk = FakeClock()
    tr = Tracer(clock=clk, capacity=4, jax_annotations=False)
    with tr.span("pipeline.run") as root:
        tr.record_span("pipeline.clean", 100.0, 101.5, rows=10)
    spans = {s["name"]: s for s in tr.export()}
    assert spans["pipeline.clean"]["parent_id"] == root.span_id
    assert spans["pipeline.clean"]["duration_s"] == pytest.approx(1.5)
    # ring keeps only the most recent `capacity` spans
    for i in range(10):
        tr.record_span(f"s{i}", 0.0, 1.0)
    assert len(tr.export()) == 4
    assert [s["name"] for s in tr.export()] == ["s6", "s7", "s8", "s9"]
    assert len(tr.export(limit=2)) == 2
    tr.clear()
    assert tr.export() == []
    # the whole export must be JSON-able (bench records embed it)
    json.dumps(snapshot(MetricsRegistry(), tr))


# --- structured logs ----------------------------------------------------------


def test_structured_log_is_json_and_carries_request_id(caplog):
    log = get_logger("test.telemetry")
    assert log.stdlib.name == "cobalt.test.telemetry"
    with caplog.at_level(logging.INFO, logger="cobalt.test.telemetry"):
        with request_context("req-abc-123") as rid:
            assert rid == "req-abc-123"
            log.info("scored", route="/predict", status=200)
        log.warning("drained")  # outside the context: no request_id key
    first = json.loads(caplog.records[0].getMessage())
    assert first["event"] == "scored"
    assert first["request_id"] == "req-abc-123"
    assert first["route"] == "/predict" and first["status"] == 200
    assert first["level"] == "INFO" and "ts" in first
    second = json.loads(caplog.records[1].getMessage())
    assert "request_id" not in second
    assert second["level"] == "WARNING"


def test_request_context_mints_id_when_client_sent_none():
    with request_context() as rid:
        assert isinstance(rid, str) and len(rid) == 16
        with request_context("outer-wins-not") as inner:
            assert inner == "outer-wins-not"
        from cobalt_smart_lender_ai_tpu.telemetry import current_request_id

        assert current_request_id() == rid


# --- request-id propagation through the micro-batcher -------------------------


def _payload(seed: float = 1.5) -> dict:
    from cobalt_smart_lender_ai_tpu.data import schema
    from cobalt_smart_lender_ai_tpu.serve.service import SINGLE_INPUT_FIELDS

    return {
        canonical: 1 if canonical in schema.SERVING_INT_FEATURES else seed
        for canonical in SINGLE_INPUT_FIELDS.values()
    }


def _cfg(**kw):
    from cobalt_smart_lender_ai_tpu.config import ServeConfig

    kw.setdefault("precompile_batch_buckets", ())
    kw.setdefault("prewarm_all_buckets", False)  # keep tier-1 compile count flat
    kw.setdefault("microbatch_max_wait_ms", 25.0)
    return ServeConfig(**kw)


def test_request_ids_cross_the_batcher_thread_boundary(serving_artifact):
    """Two requests submitted under distinct request contexts coalesce into
    one dispatch; the dispatch span (recorded on the worker thread, where
    neither context is live) carries BOTH ids — the submit-time capture."""
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService
    from cobalt_smart_lender_ai_tpu.telemetry import default_tracer

    store, _ = serving_artifact
    svc = ScorerService.from_store(store, _cfg(microbatch_max_rows=2))
    default_tracer().clear()
    rids = ("rid-aaaa", "rid-bbbb")

    def client(i: int) -> None:
        with request_context(rids[i]):
            svc.predict_single(_payload(seed=0.5 * (i + 1)))

    threads = [threading.Thread(target=client, args=(i,)) for i in range(2)]
    with svc.batcher.pause():
        for t in threads:
            t.start()
        deadline = threading.Event()
        for _ in range(5000):
            if svc.batcher.queue_depth() == 2:
                break
            deadline.wait(0.002)
        assert svc.batcher.queue_depth() == 2
    for t in threads:
        t.join(timeout=30.0)

    dispatches = [
        s
        for s in default_tracer().export()
        if s["name"] == "serve.microbatch_dispatch"
        and set(s.get("attrs", {}).get("request_ids", ())) == set(rids)
    ]
    assert dispatches, "no dispatch span carried both submitted request ids"
    assert dispatches[0]["attrs"]["rows"] == 2
    svc.close()


# --- asyncio adapter: /metrics + X-Request-ID ---------------------------------


@pytest.fixture()
def telemetry_http(serving_artifact):
    from cobalt_smart_lender_ai_tpu.serve.http_asyncio import make_async_server
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    store, _ = serving_artifact
    svc = ScorerService.from_store(store, _cfg())
    server = make_async_server(svc, "127.0.0.1", 0)
    yield f"http://127.0.0.1:{server.port}", svc
    server.close()
    svc.close()


def _request(url, body=None, headers=None):
    req = urllib.request.Request(
        url,
        data=body,
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST" if body is not None else "GET",
    )
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def test_metrics_endpoint_serves_labeled_latencies(telemetry_http):
    base, svc = telemetry_http
    status, headers, _ = _request(
        base + "/predict",
        json.dumps(_payload()).encode(),
        headers={"X-Request-ID": "client-chose-this"},
    )
    assert status == 200
    # the adapter honors and echoes the client's id (correlatable reports)
    assert headers["X-Request-ID"] == "client-chose-this"
    status, headers, _ = _request(base + "/predict", b"{}")
    assert status == 422
    assert len(headers["X-Request-ID"]) == 16  # minted when absent

    status, headers, body = _request(base + "/metrics")
    assert status == 200
    assert headers["Content-Type"] == EXPOSITION_CONTENT_TYPE
    out = parse_exposition(body.decode())  # must be valid text format

    lat = out["cobalt_request_latency_seconds"]["samples"]
    ok = lat["cobalt_request_latency_seconds_count|route=/predict|status=200"]
    bad = lat["cobalt_request_latency_seconds_count|route=/predict|status=422"]
    assert ok >= 1 and bad >= 1
    errs = out["cobalt_request_errors_total"]["samples"]
    assert (
        errs["cobalt_request_errors_total|code=invalid_input|route=/predict"]
        >= 1
    )
    # the microbatch instruments are registered on the same registry
    assert "cobalt_microbatch_batch_rows" in out
    assert "cobalt_admission_in_flight" in out
    assert "cobalt_breaker_state" in out
    # and the scrape itself was recorded by the middleware on the next scrape
    status, _, body = _request(base + "/metrics")
    out2 = parse_exposition(body.decode())
    assert (
        out2["cobalt_request_latency_seconds"]["samples"][
            "cobalt_request_latency_seconds_count|route=/metrics|status=200"
        ]
        >= 1
    )


def test_unknown_paths_fold_into_one_route_label(telemetry_http):
    base, svc = telemetry_http
    for probe in ("/nope", "/admin/../etc", "/predict2"):
        status, _, _ = _request(base + probe, b"{}")
        assert status == 404
    text = svc.registry.render()
    assert 'route="unmatched"' in text
    for probe in ("/nope", "/predict2"):
        assert f'route="{probe}"' not in text  # cardinality stays bounded


# --- fault-store counters through a registry ----------------------------------


def test_fault_store_counters_surface_in_registry(tmp_path):
    from cobalt_smart_lender_ai_tpu.io import ObjectStore
    from cobalt_smart_lender_ai_tpu.reliability import (
        FaultInjectingStore,
        FaultSpec,
    )

    reg = MetricsRegistry()
    store = FaultInjectingStore(
        ObjectStore(str(tmp_path / "lake")),
        seed=3,
        faults={"get": FaultSpec(fail_after=1, max_faults=2)},
        registry=reg,
    )
    store.put_bytes("k", b"v")
    assert store.get_bytes("k") == b"v"
    for _ in range(2):
        with pytest.raises(ConnectionError):
            store.get_bytes("k")
    assert store.get_bytes("k") == b"v"  # budget spent: calls run clean

    out = parse_exposition(reg.render())

    def sample(name: str, op: str) -> float:
        return out[name]["samples"][f"{name}|op={op}"]

    assert sample("cobalt_store_fault_calls_total", "get") == 4
    assert sample("cobalt_store_fault_calls_total", "put") == 1
    assert sample("cobalt_store_faults_injected_total", "get") == 2
    assert sample("cobalt_store_faults_injected_total", "put") == 0
    # the registry mirrors, it does not own: the store stays single writer
    assert store.calls["get"] == 4 and store.injected["get"] == 2
