"""TabNet challenger (BASELINE configs[3]): sparsemax correctness, learning
on planted signal, and mask-based feature importances."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cobalt_smart_lender_ai_tpu.models.tabnet import (
    TabNetClassifier,
    TabNetConfig,
    sparsemax,
)


def _simplex_project_ref(z):
    """O(F log F) reference implementation (Martins & Astudillo alg. 1)."""
    z = np.asarray(z, np.float64)
    u = np.sort(z)[::-1]
    css = np.cumsum(u)
    k = np.arange(1, len(z) + 1)
    cond = 1.0 + k * u > css
    k_star = k[cond][-1]
    tau = (css[cond][-1] - 1.0) / k_star
    return np.maximum(z - tau, 0.0)


def test_sparsemax_matches_reference_and_is_sparse():
    rng = np.random.default_rng(0)
    Z = rng.normal(scale=2.0, size=(64, 9)).astype(np.float32)
    out = np.asarray(sparsemax(jnp.asarray(Z)))
    np.testing.assert_allclose(out.sum(axis=-1), 1.0, atol=1e-5)
    assert (out >= 0).all()
    for i in range(8):
        np.testing.assert_allclose(
            out[i], _simplex_project_ref(Z[i]), atol=1e-5
        )
    # sharp scores must produce exact zeros (softmax never does)
    assert (out == 0.0).mean() > 0.2
    # argmax preserved
    assert (out.argmax(axis=-1) == Z.argmax(axis=-1)).all()


def test_sparsemax_uniform_and_onehot_limits():
    # equal scores -> uniform
    np.testing.assert_allclose(
        np.asarray(sparsemax(jnp.zeros((3, 5)))), np.full((3, 5), 0.2), atol=1e-6
    )
    # one dominant score -> one-hot
    z = jnp.asarray([[10.0, 0.0, 0.0]])
    np.testing.assert_allclose(
        np.asarray(sparsemax(z)), [[1.0, 0.0, 0.0]], atol=1e-6
    )


@pytest.fixture(scope="module")
def planted():
    rng = np.random.default_rng(3)
    n = 6000
    signal = rng.normal(size=(n, 3)).astype(np.float32)
    noise = rng.normal(size=(n, 9)).astype(np.float32)
    logit = 1.5 * signal[:, 0] - 1.2 * signal[:, 1] + 0.8 * signal[:, 2]
    y = (logit + rng.logistic(size=n) * 0.7 > 0).astype(np.int32)
    X = np.concatenate([signal, noise], axis=1)
    return X, y


def test_tabnet_learns_planted_signal(planted):
    X, y = planted
    clf = TabNetClassifier(
        TabNetConfig(n_steps=3, width=16, epochs=25, batch_size=1024)
    ).fit(X[:5000], y[:5000], X_val=X[5000:], y_val=y[5000:])
    auc = clf.score_auc(X[5000:], y[5000:])
    assert auc > 0.85, auc
    proba = np.asarray(clf.predict_proba(X[:8]))
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)
    assert clf.history is not None and len(clf.history["val_auc"]) > 0


def test_tabnet_masks_find_signal_features(planted):
    X, y = planted
    clf = TabNetClassifier(
        TabNetConfig(n_steps=3, width=16, epochs=25, batch_size=1024)
    ).fit(X, y)
    imp = clf.feature_importances_
    assert imp.shape == (12,)
    np.testing.assert_allclose(imp.sum(), 1.0, atol=1e-5)
    # the three planted-signal features should dominate the mask mass
    assert imp[:3].sum() > 0.5, imp
