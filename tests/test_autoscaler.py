"""Load-adaptive fleet: the autoscaler control loop, the brownout ladder,
runtime resize, admission rescale, and the synthetic traffic generator.

Pins the PR's guarantees:

- the control loop scales UP on a fast-burning SLO (build from artifact +
  smoke + admit through `add_replica`) and the new replica takes traffic;
- the scale-up cooldown prevents flapping: a burn inside the cooldown
  engages the brownout ladder instead of adding another replica;
- scale-down needs ``stable_ticks`` consecutive idle evaluations plus both
  cooldowns, retires only the tail, and NEVER goes below one routable
  replica — no signal combination can darken the fleet;
- brownout rungs engage strictly in declared order and release strictly in
  reverse, one rung per tick, before any capacity is retired; the serving
  hooks honor each rung (canary taps off, ``degraded: true`` without SHAP
  and without persisting `model.shap_error`, bulk shed, full shed);
- a resize mid-traffic loses zero in-flight requests (drain before pop;
  stragglers finish against the retired object);
- `AdmissionController.rescale` recomputes the fleet's in-flight cap and
  token bucket on every resize, and `ReplicaSet` calls it from both resize
  paths;
- `reliability.traffic` schedules are pure functions of the seed;
- the operator plane (``POST /admin/autoscaler``) and the ``/readyz``
  autoscaler/brownout blocks work over live HTTP.
"""

from __future__ import annotations

import contextlib
import json
import random
import threading
import urllib.error
import urllib.request

import pytest

from cobalt_smart_lender_ai_tpu.config import ServeConfig
from cobalt_smart_lender_ai_tpu.data import schema
from cobalt_smart_lender_ai_tpu.reliability.admission import (
    AdmissionController,
    TokenBucket,
)
from cobalt_smart_lender_ai_tpu.reliability.errors import (
    RequestShed,
    ValidationError,
)
from cobalt_smart_lender_ai_tpu.reliability.traffic import (
    KINDS,
    TenantPopulation,
    TrafficGenerator,
    bursty,
    shape_by_name,
    steady,
)
from cobalt_smart_lender_ai_tpu.serve.autoscaler import (
    BROWNOUT_RUNGS,
    LEVEL_NO_SHAP,
    LEVEL_SHED_ALL,
    BrownoutLadder,
    brownout_gate,
)
from cobalt_smart_lender_ai_tpu.serve.http_asyncio import make_async_server
from cobalt_smart_lender_ai_tpu.serve.replicas import ReplicaSet
from cobalt_smart_lender_ai_tpu.serve.service import SINGLE_INPUT_FIELDS


def _cfg(**kw) -> ServeConfig:
    """Autoscaled fleet config tuned for fast tests: no prewarm, no score
    cache, snappy supervisor, autoscaler enabled with small cooldowns the
    fake clock steps over explicitly."""
    base = dict(
        replicas=2,
        microbatch_enabled=False,
        precompile_batch_buckets=(),
        prewarm_all_buckets=False,
        score_cache_size=0,
        supervisor_probe_deadline_s=0.3,
        supervisor_probe_failures=1,
        supervisor_drain_timeout_s=1.0,
        replica_close_timeout_s=2.0,
        autoscaler_enabled=True,
        autoscaler_min_replicas=1,
        autoscaler_max_replicas=4,
        autoscaler_scale_up_cooldown_s=5.0,
        autoscaler_scale_down_cooldown_s=15.0,
        autoscaler_stable_ticks=3,
    )
    base.update(kw)
    return ServeConfig(**base)


def _payload() -> dict:
    return {
        canonical: 1 if canonical in schema.SERVING_INT_FEATURES else 1.5
        for canonical in SINGLE_INPUT_FIELDS.values()
    }


class _FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _drive(scaler, **kw) -> None:
    """Replace the signal read with a controlled classification; the
    replica count stays live so resize decisions see their own effects."""
    fleet = scaler.fleet

    def fake_signals():
        sig = {
            "fast_burn": False,
            "queue_wait_p95_ms": None,
            "util": 0.0,
            "queue_depth": 0,
            "in_flight": 0,
            "replicas": len(fleet.replicas),
        }
        sig.update(kw)
        return sig

    scaler._signals = fake_signals


@contextlib.contextmanager
def _serving(service):
    server = make_async_server(service)
    try:
        yield f"http://127.0.0.1:{server.port}"
    finally:
        server.close()


def _request(url, data=None):
    req = urllib.request.Request(
        url, data=data, method="POST" if data is not None else "GET"
    )
    if data is not None:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# --- the control loop (fake clock, controlled signals) ------------------------


def test_scale_up_on_fast_burn(serving_artifact):
    store, _ = serving_artifact
    clock = _FakeClock()
    fleet = ReplicaSet.from_store(store, _cfg(), clock=clock)
    try:
        scaler = fleet.autoscaler
        assert scaler is not None and not scaler.running
        _drive(scaler, fast_burn=True)
        summary = scaler.tick()
        assert "scale_up" in summary["actions"]
        assert len(fleet.replicas) == 3
        assert int(scaler._m_resizes.labels(direction="up").value) == 1
        # the admitted replica takes traffic through the fleet router
        for _ in range(12):
            resp = fleet.predict_single(_payload())
            assert 0.0 <= resp["prob_default"] <= 1.0
        # and its per-slot gauge family exists (stable labels)
        assert fleet._g_state.labels(replica="2").value is not None
    finally:
        fleet.close()


def test_cooldown_prevents_flapping_and_engages_brownout(serving_artifact):
    store, _ = serving_artifact
    clock = _FakeClock()
    fleet = ReplicaSet.from_store(store, _cfg(), clock=clock)
    try:
        scaler = fleet.autoscaler
        _drive(scaler, fast_burn=True)
        assert "scale_up" in scaler.tick()["actions"]
        # Inside the cooldown a burning SLO must not add another replica —
        # the ladder absorbs the overload instead.
        summary = scaler.tick()
        assert "scale_up" not in summary["actions"]
        assert f"brownout:{BROWNOUT_RUNGS[1]}" in summary["actions"]
        assert len(fleet.replicas) == 3
        # past the cooldown the next burn tick scales again
        clock.advance(5.1)
        assert "scale_up" in scaler.tick()["actions"]
        assert len(fleet.replicas) == 4
    finally:
        fleet.close()


def test_scale_down_needs_stable_idle_and_stops_at_floor(serving_artifact):
    store, _ = serving_artifact
    clock = _FakeClock()
    fleet = ReplicaSet.from_store(store, _cfg(replicas=3), clock=clock)
    try:
        scaler = fleet.autoscaler
        _drive(scaler)  # idle
        assert "scale_down" not in scaler.tick()["actions"]  # idle_ticks=1
        assert "scale_down" not in scaler.tick()["actions"]  # idle_ticks=2
        assert "scale_down" in scaler.tick()["actions"]  # stable_ticks met
        assert len(fleet.replicas) == 2
        # the scale-down cooldown holds the next retire
        for _ in range(5):
            assert "scale_down" not in scaler.tick()["actions"]
        clock.advance(15.1)
        # idle evidence kept accumulating through the cooldown: first cooled
        # tick retires the next tail replica
        assert "scale_down" in scaler.tick()["actions"]
        assert len(fleet.replicas) == 1
        # the floor: no amount of idle evidence retires the last replica
        clock.advance(15.1)
        for _ in range(6):
            assert "scale_down" not in scaler.tick()["actions"]
        assert len(fleet.replicas) == 1
    finally:
        fleet.close()


def test_remove_replica_never_darkens_the_fleet(serving_artifact):
    store, _ = serving_artifact
    fleet = ReplicaSet.from_store(store, _cfg())
    try:
        # tail quarantined -> being healed -> refuse to retire it
        fleet.quarantine_replica(1, reason="drill")
        with pytest.raises(ValidationError):
            fleet.remove_replica()
        fleet.readmit_replica(1)
        # head quarantined -> tail is the last routable replica -> refuse
        fleet.quarantine_replica(0, reason="drill")
        with pytest.raises(ValidationError):
            fleet.remove_replica()
    finally:
        fleet.close()


# --- the brownout ladder ------------------------------------------------------


def test_brownout_rungs_walk_in_declared_order():
    ladder = BrownoutLadder()
    seen = []
    while True:
        step = ladder.engage("test")
        if step is None:
            break
        seen.append(BROWNOUT_RUNGS[step[1]])
    assert seen == list(BROWNOUT_RUNGS[1:])  # healthy excluded, order exact
    assert ladder.level == LEVEL_SHED_ALL
    released = []
    while True:
        step = ladder.release("test")
        if step is None:
            break
        released.append(BROWNOUT_RUNGS[step[0]])
    assert released == list(reversed(BROWNOUT_RUNGS[1:]))  # strict reverse
    assert ladder.level == 0
    assert ladder.engaged_total == ladder.released_total == 5


def test_brownout_max_level_caps_the_ladder():
    ladder = BrownoutLadder(max_level=3)
    for _ in range(10):
        ladder.engage("test")
    assert ladder.level == 3  # never reaches the shed rungs


def test_brownout_gate_sheds_bulk_before_single():
    ladder = BrownoutLadder()
    ladder.level = 4  # shed_bulk
    with pytest.raises(RequestShed):
        brownout_gate(ladder, "bulk")
    brownout_gate(ladder, "single")  # still served
    ladder.level = 5  # shed_all
    with pytest.raises(RequestShed):
        brownout_gate(ladder, "single")
    brownout_gate(None, "bulk")  # bare service: no ladder, no gate


def test_ladder_releases_fully_before_any_retire(serving_artifact):
    store, _ = serving_artifact
    clock = _FakeClock()
    fleet = ReplicaSet.from_store(
        store, _cfg(autoscaler_max_replicas=2), clock=clock
    )
    try:
        scaler = fleet.autoscaler
        _drive(scaler, fast_burn=True)
        scaler.tick()  # at the ceiling: engage, not scale
        scaler.tick()
        assert fleet.brownout.level == 2
        # burn clears into full idle; recovery must come before savings
        _drive(scaler)
        clock.advance(20.0)  # every cooldown long since expired
        s1 = scaler.tick()
        assert f"brownout_release:{BROWNOUT_RUNGS[1]}" in s1["actions"]
        assert "scale_down" not in s1["actions"]
        s2 = scaler.tick()
        assert f"brownout_release:{BROWNOUT_RUNGS[0]}" in s2["actions"]
        assert "scale_down" not in s2["actions"]
        assert fleet.brownout.level == 0
        for _ in range(3):
            summary = scaler.tick()
        assert "scale_down" in summary["actions"]
    finally:
        fleet.close()


def test_brownout_shap_shed_degrades_without_persisting(serving_artifact):
    store, _ = serving_artifact
    fleet = ReplicaSet.from_store(store, _cfg(brownout_max_level=5))
    try:
        payload = _payload()
        healthy = fleet.predict_single(payload)
        assert healthy["shap_values"] is not None
        assert "degraded" not in healthy

        fleet.brownout.level = LEVEL_NO_SHAP
        resp = fleet.predict_single(payload)
        assert resp["degraded"] is True
        assert resp["shap_values"] is None and resp["base_value"] is None
        # transient shed, not a broken program: nothing persisted
        assert all(rep._model.shap_error is None for rep in fleet.replicas)

        fleet.brownout.level = 0
        recovered = fleet.predict_single(payload)
        assert recovered["shap_values"] is not None
        assert "degraded" not in recovered
        ok, _ = fleet.ready()
        assert ok
    finally:
        fleet.close()


def test_brownout_shed_rungs_429_the_scoring_plane(serving_artifact):
    store, _ = serving_artifact
    fleet = ReplicaSet.from_store(store, _cfg(brownout_max_level=5))
    try:
        csv_bytes = (
            ",".join(_payload()) + "\n"
            + ",".join(str(v) for v in _payload().values()) + "\n"
        ).encode()
        fleet.brownout.level = 4  # shed_bulk
        with pytest.raises(RequestShed):
            fleet.predict_bulk_csv(csv_bytes)
        with pytest.raises(RequestShed):
            fleet.feature_importance_bulk({"data": [_payload()]})
        fleet.predict_single(_payload())  # single-row still serves
        fleet.brownout.level = 5  # shed_all
        with pytest.raises(RequestShed):
            fleet.predict_single(_payload())
        fleet.brownout.level = 0
        assert fleet.predict_single(_payload())["prob_default"] >= 0.0
    finally:
        fleet.close()


# --- resize under live traffic ------------------------------------------------


def test_resize_mid_traffic_loses_zero_requests(serving_artifact):
    store, _ = serving_artifact
    fleet = ReplicaSet.from_store(store, _cfg())
    errors: list[BaseException] = []
    done = threading.Event()

    def hammer():
        payload = _payload()
        while not done.is_set():
            try:
                resp = fleet.predict_single(payload)
                assert 0.0 <= resp["prob_default"] <= 1.0
            except BaseException as exc:  # noqa: BLE001 - the assertion
                errors.append(exc)
                return

    threads = [threading.Thread(target=hammer, daemon=True) for _ in range(6)]
    try:
        for t in threads:
            t.start()
        scaler = fleet.autoscaler
        for _ in range(2):  # grow 2 -> 4 under load
            assert scaler._scale_up()
        for _ in range(3):  # shrink 4 -> 1 under load (drain before pop)
            result = fleet.remove_replica()
            assert result["status"] == "retired"
        assert len(fleet.replicas) == 1
    finally:
        done.set()
        for t in threads:
            t.join(timeout=10.0)
        fleet.close()
    assert errors == []


# --- admission rescale --------------------------------------------------------


def test_token_bucket_resize_refills_then_clamps():
    clock = _FakeClock()
    bucket = TokenBucket(rate_rps=10.0, burst=10, clock=clock)
    for _ in range(10):
        assert bucket.try_acquire()
    assert not bucket.try_acquire()  # drained
    clock.advance(0.5)  # 5 tokens accrue at the OLD rate
    bucket.resize(rate_rps=20.0, burst=4)  # refill first, then clamp to 4
    for _ in range(4):
        assert bucket.try_acquire()
    assert not bucket.try_acquire()
    clock.advance(0.2)  # the NEW rate: 20/s * 0.2s = 4 tokens
    for _ in range(4):
        assert bucket.try_acquire()
    with pytest.raises(ValueError):
        bucket.resize(rate_rps=0.0, burst=4)


def test_admission_rescale_multiplies_base_capacity():
    adm = AdmissionController(max_in_flight=4, rate_rps=10.0, burst=10)
    out = adm.rescale(3)
    assert out == {"units": 3, "max_in_flight": 12, "rate_rps": 30.0}
    assert adm.stats()["max_in_flight"] == 12
    assert adm.stats()["scale_units"] == 3
    # back down: capacity follows the fleet, floored at one unit
    adm.rescale(0)
    assert adm.max_in_flight == 4
    # unlimited knobs stay unlimited at any scale
    free = AdmissionController(max_in_flight=None, rate_rps=None)
    free.rescale(5)
    assert free.max_in_flight is None


def test_fleet_resize_recomputes_admission(serving_artifact):
    store, _ = serving_artifact
    fleet = ReplicaSet.from_store(store, _cfg())
    try:
        base = fleet.admission._base_max_in_flight
        assert fleet.admission.max_in_flight == base * 2
        assert fleet.autoscaler._scale_up()
        assert fleet.admission.max_in_flight == base * 3
        fleet.remove_replica()
        assert fleet.admission.max_in_flight == base * 2
    finally:
        fleet.close()


# --- the traffic generator ----------------------------------------------------


def _tenants() -> TenantPopulation:
    return TenantPopulation(["a", "b", "c"], ["b"], n_tenants=8, seed=3)


def test_schedule_is_a_pure_function_of_the_seed():
    def gen(seed):
        return TrafficGenerator(
            shape_by_name("flash_crowd"),
            base_rps=5.0,
            peak_rps=80.0,
            duration_s=10.0,
            tenants=_tenants(),
            seed=seed,
        )

    a, b = gen(7).schedule(), gen(7).schedule()
    assert a == b  # replayable: same seed, identical arrivals
    assert gen(8).schedule() != a
    assert all(x.t <= y.t for x, y in zip(a, a[1:]))  # sorted by fire time
    assert {x.kind for x in a} <= set(KINDS)
    assert all(0.0 <= x.t < 10.0 for x in a)


def test_flash_crowd_shape_spikes_and_decays():
    shape = shape_by_name("flash_crowd")
    assert shape.at(0.1) == pytest.approx(0.05)
    assert shape.at(0.4) == 1.0  # plateau
    assert shape.at(0.99) < 0.15  # decayed back toward baseline
    gen = TrafficGenerator(
        shape,
        base_rps=10.0,
        peak_rps=100.0,
        duration_s=100.0,
        tenants=_tenants(),
    )
    assert gen.target_rps(10.0) == pytest.approx(14.5)
    assert gen.target_rps(40.0) == pytest.approx(100.0)


def test_shapes_compose_and_unknown_names_fail_loudly():
    combo = (steady(1.0) + bursty(seed=1)).scaled(0.5)
    assert 0.0 <= combo.at(0.5) <= 1.0
    with pytest.raises(ValueError):
        shape_by_name("tsunami")
    with pytest.raises(ValueError):
        TrafficGenerator(
            steady(),
            base_rps=10.0,
            peak_rps=5.0,  # peak < base
            duration_s=1.0,
            tenants=_tenants(),
        )
    with pytest.raises(ValueError):
        TrafficGenerator(
            steady(),
            base_rps=1.0,
            peak_rps=2.0,
            duration_s=1.0,
            tenants=_tenants(),
            mix={"telepathy": 1.0},
        )


def test_tenant_population_zipf_weights_and_payload_jitter():
    pop = _tenants()
    rng = random.Random(0)
    picks = [pop.pick(rng) for _ in range(4000)]
    assert picks.count(0) > picks.count(7) * 2  # hot head, cold tail
    row = pop.payload(2, random.Random(1))
    assert set(row) == {"a", "b", "c"}
    assert row["b"] in (0, 1)  # int fields never jitter
    # caller-supplied base rows are used verbatim (cycled over tenants)
    real = TenantPopulation(
        ["a", "b"], base_rows=[{"a": 1.0, "b": 2.0}], jitter=0.0, n_tenants=3
    )
    assert real.payload(2, random.Random(2)) == {"a": 1.0, "b": 2.0}


# --- the operator plane over live HTTP ---------------------------------------


def test_admin_autoscaler_and_readyz_blocks_over_http(serving_artifact):
    store, _ = serving_artifact
    fleet = ReplicaSet.from_store(
        store, _cfg(autoscaler_interval_s=30.0)  # loop idles during the test
    )
    try:
        with _serving(fleet) as url:
            status, body = _request(f"{url}/readyz")
            assert status == 200
            ready = json.loads(body)
            assert ready["brownout"]["rung"] == "healthy"
            assert ready["autoscaler"]["enabled"] is True
            assert ready["autoscaler"]["running"] is True  # socket-open hook
            assert ready["autoscaler"]["replicas"] == 2

            status, body = _request(
                f"{url}/admin/autoscaler",
                json.dumps({"action": "pause"}).encode(),
            )
            assert status == 200 and json.loads(body)["status"] == "paused"
            assert fleet.autoscaler.tick() == {"status": "paused"}

            status, body = _request(
                f"{url}/admin/autoscaler",
                json.dumps({"action": "force", "replicas": 3}).encode(),
            )
            assert status == 200
            out = json.loads(body)
            assert out["replicas"] == 3 and out["steps"] == ["up"]
            assert len(fleet.replicas) == 3

            status, body = _request(
                f"{url}/admin/autoscaler",
                json.dumps({"action": "force", "replicas": 99}).encode(),
            )
            assert status == 422  # bounds still apply to operators

            status, body = _request(
                f"{url}/admin/autoscaler",
                json.dumps({"action": "resume"}).encode(),
            )
            assert status == 200 and json.loads(body)["status"] == "resumed"

            status, body = _request(
                f"{url}/admin/autoscaler",
                json.dumps({"action": "explode"}).encode(),
            )
            assert status == 422
    finally:
        fleet.close()


def test_admin_autoscaler_422_on_bare_service(serving_artifact):
    store, _ = serving_artifact
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    service = ScorerService.from_store(
        store, _cfg(replicas=1, autoscaler_enabled=False)
    )
    try:
        with _serving(service) as url:
            status, body = _request(
                f"{url}/admin/autoscaler",
                json.dumps({"action": "status"}).encode(),
            )
            assert status == 422
            assert json.loads(body)["error"] == "invalid_input"
    finally:
        service.close()
