"""Golden parity gates for the device-resident ingest flow (ISSUE 16).

The contract mirrors the mesh bit-parity gates in test_partitioner.py: the
jitted columnar path (`data/device_pipeline.py`) must reproduce the pandas
path (`clean.py` -> `features.py`) bit-identically for integer, categorical,
one-hot and indicator columns, and within float32 tolerance for derived
floats (log1p outputs and the medians imputed from them — XLA lowers
`log1p` with 1-ulp differences across fusion contexts, so cross-program
bit-equality of logged values is not achievable even on one device). The
mesh run must match the single-device run bit-identically everywhere: both
trace the same programs, so sharding may not change a single bit.
"""

from datetime import datetime

import numpy as np
import pandas as pd
import pytest

from cobalt_smart_lender_ai_tpu.data import schema
from cobalt_smart_lender_ai_tpu.data.clean import clean_raw_frame
from cobalt_smart_lender_ai_tpu.data.device_pipeline import (
    run_device_ingest,
    tokenize_raw_frame,
    transform_raw_rows,
)
from cobalt_smart_lender_ai_tpu.data.features import (
    engineer_features,
    prepare_cleaned_frame,
)
from cobalt_smart_lender_ai_tpu.ops import binning

#: Pinned so both paths derive identical `earliest_cr_line_days` ages.
TODAY = datetime(2026, 8, 1)

#: Relative tolerance for log1p-derived floats: a few ulps of float32.
LOG_RTOL = 3e-7


def _assert_cols(names, A, B, exact_pred, context):
    """Per-column comparison: exact (NaN==NaN) where `exact_pred`, float32
    tolerance elsewhere."""
    assert A.shape == B.shape
    for j, name in enumerate(names):
        a, b = A[:, j], B[:, j]
        nan_ok = np.isnan(a) & np.isnan(b)
        if exact_pred(name):
            ok = (a == b) | nan_ok
            assert ok.all(), (
                f"{context}: column {name!r} not bit-identical "
                f"({int((~ok).sum())} cells, first at row {int(np.argmax(~ok))})"
            )
        else:
            ok = np.isclose(a, b, rtol=LOG_RTOL, atol=0.0) | nan_ok
            assert ok.all(), (
                f"{context}: column {name!r} outside float32 tolerance"
            )


@pytest.fixture(scope="module")
def pandas_path(raw_frame):
    cleaned, report = clean_raw_frame(raw_frame.copy())
    prepared = prepare_cleaned_frame(cleaned, today=TODAY)
    tree, nn, plan = engineer_features(prepared)
    return report, tree, nn, plan


@pytest.fixture(scope="module")
def device_path(raw_frame):
    tok = tokenize_raw_frame(raw_frame.copy(), today=TODAY)
    return tok, run_device_ingest(tok)


def test_clean_report_parity(pandas_path, device_path):
    ref, _, _, _ = pandas_path
    got = device_path[1].report
    assert got.n_rows_in == ref.n_rows_in
    assert got.n_rows_out == ref.n_rows_out
    assert got.n_rows_dropped_near_complete == ref.n_rows_dropped_near_complete
    assert got.n_duplicates_removed == ref.n_duplicates_removed
    assert got.dropped_null_columns == ref.dropped_null_columns
    assert got.dropped_fixed_columns == ref.dropped_fixed_columns


def test_plan_parity(pandas_path, device_path):
    _, _, _, ref = pandas_path
    got = device_path[1].plan
    assert got.numeric_names == ref.numeric_names
    assert dict(got.categorical_vocab) == dict(ref.categorical_vocab)
    assert dict(got.label_vocab) == dict(ref.label_vocab)
    assert got.log_cols == ref.log_cols
    assert got.tree_feature_names == ref.tree_feature_names
    assert got.nn_feature_names == ref.nn_feature_names
    assert got.asof == TODAY.strftime("%Y-%m-%d")
    assert set(got.medians) == set(ref.medians)
    for k in ref.medians:
        assert np.isclose(got.medians[k], ref.medians[k], rtol=LOG_RTOL), k


def test_tree_matrix_golden_parity(pandas_path, device_path):
    _, ref, _, plan = pandas_path
    got = device_path[1].tree
    assert got.feature_names == ref.feature_names
    log_cols = set(plan.log_cols)
    # Everything past the numeric block is a one-hot indicator -> exact;
    # numeric columns are exact unless log1p touched them.
    _assert_cols(
        ref.feature_names,
        np.asarray(ref.X),
        np.asarray(got.X),
        lambda n: n not in log_cols,
        "tree",
    )
    ya, yb = np.asarray(ref.y), np.asarray(got.y)
    ok = (ya == yb) | (np.isnan(ya) & np.isnan(yb))
    assert ok.all(), "labels not bit-identical"


def test_nn_matrix_golden_parity(pandas_path, device_path):
    _, _, ref, plan = pandas_path
    got = device_path[1].nn
    assert got.feature_names == ref.feature_names
    # Imputed numeric columns inherit the log tolerance through their
    # medians; indicators, no_income/dti_NA flags and categorical codes
    # must be bit-identical.
    log_cols = set(plan.log_cols)
    _assert_cols(
        ref.feature_names,
        np.asarray(ref.X),
        np.asarray(got.X),
        lambda n: n not in log_cols,
        "nn",
    )


def test_binning_fused_parity(pandas_path, device_path):
    """The fused sketch must equal composing ops/binning.py's stages on the
    device path's own features (bit-identical bins), and stay within float
    tolerance of edges derived from the pandas matrix."""
    res = device_path[1]
    spec = binning.compute_bin_edges(res.tree.X, n_bins=255)
    bins = binning.transform(spec, res.tree.X)
    assert res.bin_spec.n_bins == 255
    assert (np.asarray(bins) == np.asarray(res.bins)).all()
    assert (np.asarray(spec.edges) == np.asarray(res.bin_spec.edges)).all()
    _, ref_tree, _, _ = pandas_path
    ref_edges = np.asarray(binning.compute_bin_edges(ref_tree.X, n_bins=255).edges)
    got_edges = np.asarray(res.bin_spec.edges)
    ok = (
        np.isclose(ref_edges, got_edges, rtol=LOG_RTOL, atol=0.0)
        | (np.isinf(ref_edges) & np.isinf(got_edges))
    )
    assert ok.all()


def test_mesh_matches_single_device(device_path):
    """Forced 4-device mesh ingest must match the single-device run
    bit-identically on every output — the ingest analog of the
    test_partitioner mesh bit-parity gates."""
    from cobalt_smart_lender_ai_tpu.parallel.partitioner import make_partitioner

    tok, single = device_path
    mesh = run_device_ingest(
        tok, partitioner=make_partitioner(4, kind_prefix="ingest")
    )
    for name, a, b in (
        ("tree", single.tree.X, mesh.tree.X),
        ("nn", single.nn.X, mesh.nn.X),
        ("y", single.tree.y, mesh.tree.y),
        ("bins", single.bins, mesh.bins),
        ("edges", single.bin_spec.edges, mesh.bin_spec.edges),
    ):
        A, B = np.asarray(a), np.asarray(b)
        ok = (A == B) | (
            np.isnan(A.astype(np.float64)) & np.isnan(B.astype(np.float64))
        )
        assert ok.all(), f"mesh {name} diverged from single-device run"


def test_ingest_programs_registered_and_timed(device_path):
    """RunLedger attribution coverage: every device-ingest stage shows up as
    a named ingest.* program with nonzero measured dispatch wall."""
    from cobalt_smart_lender_ai_tpu.telemetry.programs import program_table

    device_path[1].tree.X.block_until_ready()
    rows = program_table(kind="ingest")
    kinds = {r["name"].split(".", 1)[1].split("[", 1)[0] for r in rows}
    assert {
        "null_stats", "row_compact", "fill", "dedupe",
        "vocab_census", "stats", "assemble",
    } <= kinds
    assert "binning" in kinds or {"sketch", "bin_transform"} <= kinds
    assert sum(r.get("dispatch_seconds") or 0.0 for r in rows) > 0.0


def test_tokenize_degenerate_cells():
    """Whitespace-only / NaN string cells tokenize to NaN (missing) instead
    of raising, and the hardship vocabulary gains the clean-stage fill
    token exactly when the raw column has nulls."""
    df = pd.DataFrame(
        {
            "term": [" 36 months", "   ", None],
            "int_rate": ["10.0%", "", "5.5%"],
            "emp_length": ["< 1 year", "10+ years", None],
            "hardship_status": ["ACTIVE", None, None],
            "loan_amnt": [1000.0, 2000.0, 3000.0],
        }
    )
    tok = tokenize_raw_frame(df, today=TODAY)
    X = np.asarray(tok.X)
    term = X[:, tok.columns.index("term")]
    assert term[0] == 36.0 and np.isnan(term[1]) and np.isnan(term[2])
    rate = X[:, tok.columns.index("int_rate")]
    assert np.isclose(rate[0], 0.10) and np.isnan(rate[1])
    emp = X[:, tok.columns.index("emp_length")]
    assert emp[0] == 0.0 and emp[1] == 10.0 and np.isnan(emp[2])
    hpos = tok.columns.index("hardship_status")
    assert tok.vocab[hpos] == ("ACTIVE", schema.HARDSHIP_FILL)


def test_raw_row_serve_path_no_skew(raw_frame, device_path, tmp_path):
    """Kills train/serve skew by construction: a raw row scored through
    `ScorerService.predict_raw` must produce the same engineered features
    and the same probability as the batch pipeline produced for that row."""
    import jax

    from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, ObjectStore
    from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTClassifier
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    res = device_path[1]
    plan = res.plan
    missing = [
        n for n in schema.SERVING_FEATURES
        if n not in plan.tree_feature_names
    ]
    assert not missing, f"device plan lacks serving features: {missing}"
    ff = res.tree.select(schema.SERVING_FEATURES)
    model = GBDTClassifier(n_estimators=25, max_depth=3, n_bins=64)
    model.fit(np.asarray(ff.X), np.asarray(ff.y))
    store = ObjectStore(str(tmp_path / "lake"))
    GBDTArtifact(
        forest=model.forest,
        bin_spec=model.bin_spec,
        feature_names=tuple(schema.SERVING_FEATURES),
        plan=plan,
    ).save(store, "models/gbdt/model_tree")
    svc = ScorerService.from_store(store)

    tree_np = np.asarray(res.tree.X)
    sel = [plan.tree_feature_names.index(n) for n in schema.SERVING_FEATURES]
    checked = 0
    for i in (0, 1, 2):
        payload = raw_frame.iloc[i].to_dict()
        feats = transform_raw_rows(plan, [payload], today=TODAY)
        # The raw row must reproduce its batch-pipeline feature vector
        # exactly (the row survived cleaning iff it appears in the matrix).
        eq = (tree_np == feats[0][None, :]) | (
            np.isnan(tree_np) & np.isnan(feats[0][None, :])
        )
        match = np.flatnonzero(eq.all(axis=1))
        if match.size == 0:
            continue  # row was dropped by cleaning; nothing to compare
        resp = svc.predict_raw(payload)
        assert 0.0 <= resp["prob_default"] <= 1.0
        assert resp["features"] == list(schema.SERVING_FEATURES)
        batch_x = np.ascontiguousarray(
            tree_np[match[0]][sel][None, :], dtype=np.float32
        )
        batch_prob = float(
            jax.nn.sigmoid(svc._model.margin_fn(batch_x))[0]
        )
        assert resp["prob_default"] == batch_prob
        checked += 1
    assert checked, "no raw row survived into the feature matrix"


def test_raw_row_missing_and_unknown_values(device_path):
    """Missing numerics -> NaN (GBDT missing direction), unknown categories
    -> all-zero one-hot block, missing hardship -> the clean-stage fill —
    the training-time semantics, not serving-time improvisation."""
    plan = device_path[1].plan
    payload = {
        "loan_amnt": 10000.0,
        "term": " 36 months",
        "int_rate": "11.5%",
        "grade": "ZZZ-not-a-grade",
    }
    out = transform_raw_rows(plan, [payload], today=TODAY)
    names = list(plan.tree_feature_names)
    assert out[0][names.index("loan_amnt")] == np.float32(np.log1p(10000.0))
    assert out[0][names.index("term")] == 36.0
    grade_cols = [j for j, n in enumerate(names) if n.startswith("grade_")]
    assert grade_cols and (out[0][grade_cols] == 0.0).all()
    hs_cols = [
        j for j, n in enumerate(names) if n.startswith("hardship_status_")
    ]
    fill_col = names.index(f"hardship_status_{schema.HARDSHIP_FILL}")
    expected = {
        j: (1.0 if j == fill_col else 0.0) for j in hs_cols
    }
    for j, want in expected.items():
        assert out[0][j] == want, names[j]
    # absent numeric -> NaN
    assert np.isnan(out[0][names.index("annual_inc")])
