"""Resilience layer (reliability/) under test.

Every claim is exercised, not asserted: the backoff schedule is checked
against a fake clock (tier-1 never sleeps for real), fault injection is
replayed under a fixed seed, a pipeline run against a store that drops calls
must complete *via retries* (observable counter), a run killed after RFE
must resume without re-running clean/engineer/RFE (stage-execution
counters), and a service whose SHAP program is broken must still serve
probabilities over both HTTP adapters with ``"degraded": true`` instead of
HTTP 500.
"""

import json
import random
import threading

import numpy as np
import pytest

from cobalt_smart_lender_ai_tpu.config import (
    GBDTConfig,
    MeshConfig,
    PipelineConfig,
    ReliabilityConfig,
    RFEConfig,
    TuneConfig,
)
from cobalt_smart_lender_ai_tpu.io import ObjectStore, StoreKeyError
from cobalt_smart_lender_ai_tpu.reliability import (
    CorruptObjectError,
    FaultInjectingStore,
    FaultSpec,
    InjectedFault,
    PipelineCheckpoint,
    ResilientStore,
    RetryPolicy,
    call_with_retry,
    config_fingerprint,
)


def _fast_cfg():
    """Default serving config minus the all-bucket prewarm — this module
    doesn't exercise cold-bucket tails, and the extra per-bucket compiles
    are pure tier-1 wall time."""
    from cobalt_smart_lender_ai_tpu.config import ServeConfig

    return ServeConfig(prewarm_all_buckets=False)


class FakeClock:
    """Deterministic sleep/monotonic pair: sleeping advances the clock."""

    def __init__(self):
        self.now = 0.0
        self.sleeps: list[float] = []

    def sleep(self, s: float) -> None:
        self.sleeps.append(s)
        self.now += s

    def monotonic(self) -> float:
        return self.now


# --- retry policy -------------------------------------------------------------


def test_backoff_schedule_exponential_capped():
    """base * mult^i capped at max_delay, asserted against the fake clock."""
    clock = FakeClock()
    policy = RetryPolicy(
        max_attempts=5, base_delay_s=1.0, max_delay_s=5.0, multiplier=2.0, jitter=0.0
    )
    calls = []

    def flaky():
        calls.append(1)
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        call_with_retry(
            flaky, policy, sleep=clock.sleep, monotonic=clock.monotonic
        )
    assert len(calls) == 5
    assert clock.sleeps == [1.0, 2.0, 4.0, 5.0]  # 8.0 capped to max_delay


def test_jitter_deterministic_under_seed():
    policy = RetryPolicy(base_delay_s=1.0, jitter=0.5)
    a = [policy.delay(i, random.Random(7)) for i in range(4)]
    b = [policy.delay(i, random.Random(7)) for i in range(4)]
    c = [policy.delay(i, random.Random(8)) for i in range(4)]
    assert a == b != c
    for i, d in enumerate(a):  # within the documented [1-j, 1+j] band
        raw = min(1.0 * 2.0**i, policy.max_delay_s)
        assert raw * 0.5 <= d <= raw * 1.5


def test_succeeds_midway_returns_value():
    clock = FakeClock()
    state = {"n": 0}

    def eventually():
        state["n"] += 1
        if state["n"] < 3:
            raise TimeoutError("later")
        return "ok"

    assert (
        call_with_retry(
            eventually,
            RetryPolicy(max_attempts=4, jitter=0.0),
            sleep=clock.sleep,
            monotonic=clock.monotonic,
        )
        == "ok"
    )
    assert state["n"] == 3 and len(clock.sleeps) == 2


def test_non_retryable_raises_immediately():
    clock = FakeClock()
    calls = []

    def missing():
        calls.append(1)
        raise FileNotFoundError("no such object")

    with pytest.raises(FileNotFoundError):
        call_with_retry(
            missing, RetryPolicy(max_attempts=5), sleep=clock.sleep,
            monotonic=clock.monotonic,
        )
    assert len(calls) == 1 and clock.sleeps == []


def test_deadline_caps_wall_time():
    clock = FakeClock()
    policy = RetryPolicy(
        max_attempts=10, base_delay_s=1.0, max_delay_s=10.0, multiplier=2.0,
        jitter=0.0, deadline_s=4.0,
    )
    calls = []

    def flaky():
        calls.append(1)
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        call_with_retry(flaky, policy, sleep=clock.sleep, monotonic=clock.monotonic)
    # sleeps 1 + 2 taken; the next delay (4) would cross the 4s deadline
    assert clock.sleeps == [1.0, 2.0]
    assert len(calls) == 3


def test_store_key_error_not_retryable():
    from cobalt_smart_lender_ai_tpu.reliability.retry import is_transient_store_error

    assert not is_transient_store_error(StoreKeyError("escape"))
    assert not is_transient_store_error(ValueError("bad"))
    assert is_transient_store_error(InjectedFault("drop"))
    assert is_transient_store_error(CorruptObjectError("mismatch"))


# --- fault injection ----------------------------------------------------------


@pytest.mark.faults
def test_fault_injection_deterministic_under_seed(tmp_path):
    def run(seed: int) -> tuple:
        inner = ObjectStore(str(tmp_path / f"lake{seed}"))
        store = FaultInjectingStore(
            inner, seed=seed, faults={"put": FaultSpec(rate=0.5)}
        )
        outcomes = []
        for i in range(30):
            try:
                store.put_bytes(f"k{i}", b"v")
                outcomes.append("ok")
            except InjectedFault:
                outcomes.append("fault")
        return tuple(outcomes)

    # replaying the same seed reproduces the exact fault sequence; a
    # different seed draws a different one
    assert run(3) == run(3)
    assert run(3) != run(4)


@pytest.mark.faults
def test_fail_after_and_budget(tmp_path):
    inner = ObjectStore(str(tmp_path / "lake"))
    store = FaultInjectingStore(
        inner, faults={"exists": FaultSpec(fail_after=2, max_faults=3)}
    )
    assert store.exists("a") is False  # calls 1-2 clean
    assert store.exists("a") is False
    for _ in range(3):  # calls 3-5 fault (budget of 3)
        with pytest.raises(InjectedFault):
            store.exists("a")
    assert store.exists("a") is False  # budget spent: clean again
    assert store.injected["exists"] == 3


@pytest.mark.faults
def test_corruption_detected_by_pointer_verification(tmp_path):
    inner = ObjectStore(str(tmp_path / "lake"))
    inner.put_bytes("data.bin", b"payload")
    inner.write_pointer("data.bin")
    faulty = FaultInjectingStore(
        inner, seed=1, faults={"get": FaultSpec(corrupt_rate=1.0, max_faults=2)}
    )
    resilient = ResilientStore(
        faulty, RetryPolicy(max_attempts=6, base_delay_s=0.0, jitter=0.0)
    )
    # first two reads of the data return flipped bytes -> CorruptObjectError
    # -> retried until the budget is spent and a clean read verifies
    assert resilient.get_bytes("data.bin") == b"payload"
    assert resilient.retries > 0
    assert faulty.injected["get"] == 2


# --- resilient store ----------------------------------------------------------


@pytest.mark.faults
def test_resilient_store_retries_transient_faults(tmp_path):
    inner = ObjectStore(str(tmp_path / "lake"))
    faulty = FaultInjectingStore(
        inner,
        seed=5,
        faults={"put": FaultSpec(rate=0.3), "get": FaultSpec(rate=0.3)},
    )
    store = ResilientStore(
        faulty, RetryPolicy(max_attempts=8, base_delay_s=0.0, jitter=0.0)
    )
    for i in range(40):
        store.put_bytes(f"obj/{i}", f"value-{i}".encode())
    for i in range(40):
        assert store.get_bytes(f"obj/{i}") == f"value-{i}".encode()
    assert store.retries > 0, "fault rate 0.3 over 80 calls must trigger retries"
    assert faulty.injected["put"] > 0 and faulty.injected["get"] > 0


def test_resilient_store_does_not_retry_missing_objects(tmp_path):
    inner = ObjectStore(str(tmp_path / "lake"))
    counting = FaultInjectingStore(inner)  # no faults, just call counters
    store = ResilientStore(counting, RetryPolicy(base_delay_s=0.0))
    with pytest.raises(FileNotFoundError):
        store.get_bytes("never/written")
    assert counting.calls["get"] == 1  # deterministic failure: one attempt
    assert store.retries == 0


def test_resilient_store_detects_persistent_corruption(tmp_path):
    inner = ObjectStore(str(tmp_path / "lake"))
    inner.put_bytes("k", b"original")
    inner.write_pointer("k")
    inner.put_bytes("k", b"tampered!")  # rewrite WITHOUT re-pinning
    store = ResilientStore(
        inner, RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0)
    )
    with pytest.raises(CorruptObjectError):
        store.get_bytes("k")
    assert store.get_bytes("k" + ".ptr.json")  # pointer itself still readable


def test_resilient_store_inherits_conveniences(tmp_path):
    """put_json/save_frame etc. compose over the retried primitives."""
    import pandas as pd

    store = ResilientStore(
        ObjectStore(str(tmp_path / "lake")), RetryPolicy(base_delay_s=0.0)
    )
    store.put_json("m.json", {"a": 1})
    assert store.get_json("m.json") == {"a": 1}
    store.save_frame("f.csv", pd.DataFrame({"x": [1, 2]}))
    assert list(store.load_frame("f.csv")["x"]) == [1, 2]
    assert "m.json" in list(store.list(""))


# --- store satellites ---------------------------------------------------------


def test_store_key_escape_rejected(tmp_path):
    store = ObjectStore(str(tmp_path / "lake"))
    for bad in ("/etc/passwd", "a/../../b", "..", "../x", "\\\\evil"):
        with pytest.raises(StoreKeyError):
            store.put_bytes(bad, b"x")
    # StoreKeyError stays a ValueError for existing callers
    with pytest.raises(ValueError):
        store.get_bytes("../y")
    # dots WITHIN a segment are legal keys
    store.put_bytes("a..b/c.txt", b"ok")
    assert store.get_bytes("a..b/c.txt") == b"ok"


def test_verify_pointer_never_raises(tmp_path):
    store = ObjectStore(str(tmp_path / "lake"))
    assert store.verify_pointer("absent") is False  # no pointer, no object
    store.put_bytes("k", b"v")
    assert store.verify_pointer("k") is False  # object but no pointer
    store.write_pointer("k")
    assert store.verify_pointer("k") is True
    store.put_bytes("k" + ".ptr.json", b"{not json")
    assert store.verify_pointer("k") is False  # malformed pointer
    store.put_bytes("k2", b"v")
    store.write_pointer("k2")
    store.delete("k2")  # key gone, pointer dangling
    assert store.verify_pointer("k2") is False


def test_concurrent_put_bytes_no_temp_collision(tmp_path):
    """Concurrent writers of the SAME key must not truncate each other via a
    shared temp name; the survivor is one complete payload, no .tmp left."""
    store = ObjectStore(str(tmp_path / "lake"))
    payloads = [bytes([i]) * 4096 for i in range(16)]
    errors = []

    def write(data: bytes):
        try:
            for _ in range(20):
                store.put_bytes("contended/key.bin", data)
        except Exception as e:  # pragma: no cover - the regression we guard
            errors.append(e)

    threads = [threading.Thread(target=write, args=(p,)) for p in payloads]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors
    assert store.get_bytes("contended/key.bin") in payloads
    leftovers = [k for k in store.list("") if k.endswith(".tmp")]
    assert leftovers == []


# --- checkpoint manifests -----------------------------------------------------


def test_config_fingerprint_sensitivity():
    a = config_fingerprint("rfe", RFEConfig())
    assert a == config_fingerprint("rfe", RFEConfig())
    assert a != config_fingerprint("rfe", RFEConfig(n_select=10))
    assert a != config_fingerprint("search", RFEConfig())


def test_manifest_validates_and_invalidates(tmp_path):
    store = ObjectStore(str(tmp_path / "lake"))
    ckpt = PipelineCheckpoint(store, prefix="ck/")
    store.put_bytes("out.csv", b"rows")
    fp = config_fingerprint("stage", {"k": 1})
    ckpt.write("stage", fingerprint=fp, outputs=["out.csv"], extra={"n": 3})
    assert ckpt.valid("stage", fp)
    assert ckpt.load("stage")["extra"] == {"n": 3}
    # changed config slice -> invalid
    assert not ckpt.valid("stage", config_fingerprint("stage", {"k": 2}))
    # drifted output bytes -> invalid even though fingerprint matches
    store.put_bytes("out.csv", b"drifted")
    assert not ckpt.valid("stage", fp)
    # missing manifest -> load None, valid False
    ckpt.invalidate("stage")
    assert ckpt.load("stage") is None and not ckpt.valid("stage", fp)


def test_manifest_foreign_format_ignored(tmp_path):
    store = ObjectStore(str(tmp_path / "lake"))
    ckpt = PipelineCheckpoint(store)
    store.put_json(ckpt.manifest_key("clean"), {"format": 999})
    assert ckpt.load("clean") is None
    store.put_bytes(ckpt.manifest_key("clean"), b"not json")
    assert ckpt.load("clean") is None


# --- pipeline checkpoint/resume ----------------------------------------------


def _tiny_pipeline_config(**rel_kw) -> PipelineConfig:
    """Smallest config that still walks every stage."""
    return PipelineConfig(
        gbdt=GBDTConfig(n_bins=32),
        rfe=RFEConfig(n_select=10, step=40, n_estimators=8, max_depth=3),
        tune=TuneConfig(
            n_iter=2,
            cv_folds=2,
            param_space={
                "n_estimators": (40,),
                "max_depth": (3,),
                "learning_rate": (0.1,),
            },
        ),
        mesh=MeshConfig(hp=1),
        reliability=ReliabilityConfig(
            base_delay_s=0.0, max_delay_s=0.0, jitter=0.0, **rel_kw
        ),
    )


@pytest.fixture(scope="module")
def small_raw():
    from cobalt_smart_lender_ai_tpu.data.synthetic import (
        synthetic_lendingclub_frame,
    )

    return synthetic_lendingclub_frame(2500, seed=11)


def test_resume_after_crash_skips_completed_stages(tmp_path, small_raw, monkeypatch):
    """ISSUE acceptance: a run killed after the RFE stage resumes with
    --resume without re-running clean/engineer/RFE."""
    import cobalt_smart_lender_ai_tpu.pipeline as pl

    cfg = _tiny_pipeline_config()
    store = ObjectStore(str(tmp_path / "lake"))

    def boom(*a, **k):
        raise RuntimeError("killed mid-search")

    monkeypatch.setattr(pl, "randomized_search", boom)
    with pytest.raises(RuntimeError, match="killed mid-search"):
        pl.run_pipeline(cfg, raw=small_raw, store=store)
    monkeypatch.undo()

    # crash left manifests for every completed stage
    ckpt = PipelineCheckpoint(store, cfg.reliability.checkpoint_prefix)
    for stage in ("clean", "engineer", "rfe"):
        assert ckpt.load(stage) is not None, stage

    result = pl.run_pipeline(cfg, store=store, resume=True)  # no raw needed
    assert set(result.stages_skipped) >= {"clean", "engineer", "rfe"}
    assert "rfe" not in result.stages_run
    assert set(result.stages_run) >= {"search", "eval"}
    assert len(result.selected_features) == cfg.rfe.n_select
    assert result.test_auc > 0.85


def test_resume_full_run_then_config_change(tmp_path, small_raw):
    """A fully-successful run resumes clean through search; changing only the
    RFE config re-runs RFE + search while clean/engineer stay skipped."""
    import dataclasses

    from cobalt_smart_lender_ai_tpu.pipeline import run_pipeline

    cfg = _tiny_pipeline_config()
    store = ObjectStore(str(tmp_path / "lake"))
    first = run_pipeline(cfg, raw=small_raw, store=store)
    assert first.stages_skipped == ()
    assert set(first.stages_run) == {"clean", "engineer", "rfe", "search", "eval"}

    second = run_pipeline(cfg, store=store, resume=True)
    assert set(second.stages_skipped) == {"clean", "engineer", "rfe", "search"}
    assert second.stages_run == ("eval",)
    assert second.selected_features == first.selected_features
    assert second.best_params == first.best_params
    assert second.cv_auc == first.cv_auc

    changed = dataclasses.replace(
        cfg, rfe=dataclasses.replace(cfg.rfe, n_select=8)
    )
    third = run_pipeline(changed, store=store, resume=True)
    assert set(third.stages_skipped) == {"clean", "engineer"}
    assert set(third.stages_run) == {"rfe", "search", "eval"}
    assert len(third.selected_features) == 8


def test_resume_off_recomputes(tmp_path, small_raw):
    from cobalt_smart_lender_ai_tpu.pipeline import run_pipeline

    cfg = _tiny_pipeline_config()
    store = ObjectStore(str(tmp_path / "lake"))
    run_pipeline(cfg, raw=small_raw, store=store)
    again = run_pipeline(cfg, raw=small_raw, store=store)  # resume not requested
    assert again.stages_skipped == ()


@pytest.mark.faults
def test_pipeline_completes_under_injected_faults(tmp_path, small_raw):
    """ISSUE acceptance: the pipeline against a FaultInjectingStore with
    transient faults completes via retries (observable retry counter)."""
    from cobalt_smart_lender_ai_tpu.pipeline import run_pipeline

    cfg = _tiny_pipeline_config(max_attempts=8)
    inner = ObjectStore(str(tmp_path / "lake"))
    faulty = FaultInjectingStore(
        inner,
        seed=13,
        faults={
            "put": FaultSpec(rate=0.15),
            "get": FaultSpec(rate=0.15),
            "exists": FaultSpec(rate=0.15),
        },
    )
    result = run_pipeline(cfg, raw=small_raw, store=faulty)
    assert result.test_auc > 0.85
    assert sum(faulty.injected.values()) > 0, "faults must actually fire"
    # artifact round-trips through the still-faulty store via retries
    resilient = ResilientStore(
        faulty, RetryPolicy(max_attempts=8, base_delay_s=0.0, jitter=0.0)
    )
    assert json.loads(
        resilient.get_bytes(cfg.serve.model_key + ".metrics.json")
    )["auc"] == pytest.approx(result.test_auc)


# --- serving: degraded SHAP + health over both adapters ----------------------


@pytest.fixture()
def degraded_service(serving_artifact, monkeypatch):
    """ScorerService whose SHAP program fails to build (forced), configured
    to degrade rather than die."""
    import cobalt_smart_lender_ai_tpu.parallel.partitioner as partitioner_mod
    import cobalt_smart_lender_ai_tpu.serve.service as service_mod

    def broken_shap(*a, **k):
        raise RuntimeError("SHAP compile forced to fail")

    class _BrokenFused:
        def lower(self, *a, **k):
            raise RuntimeError("fused lowering forced to fail")

    # The SHAP program is compiled by the partitioner (not the service), and
    # structure-identical forests share cached executables — swap in an empty
    # cache so the forced compile failure actually fires. The fused kernel
    # computes SHAP itself (it never calls shap_values), so break its
    # lowering too: this fixture now exercises the full
    # fused -> reference -> degrade fallback chain.
    monkeypatch.setattr(partitioner_mod, "shap_values", broken_shap)
    monkeypatch.setattr(partitioner_mod, "fused_score", _BrokenFused())
    monkeypatch.setattr(partitioner_mod, "_EXEC_CACHE", {})
    store, _ = serving_artifact
    return service_mod.ScorerService.from_store(store, _fast_cfg())


def _contract_payload() -> dict:
    from cobalt_smart_lender_ai_tpu.data import schema
    from cobalt_smart_lender_ai_tpu.serve.service import SINGLE_INPUT_FIELDS

    return {
        field: 1 if canonical in schema.SERVING_INT_FEATURES else 1.5
        for field, canonical in SINGLE_INPUT_FIELDS.items()
    }


def test_degraded_shap_serves_probability(degraded_service):
    svc = degraded_service
    assert svc._shap_fn is None and svc._shap_error
    resp = svc.predict_single(_contract_payload())
    assert 0.0 <= resp["prob_default"] <= 1.0
    assert resp["shap_values"] is None
    assert resp["base_value"] is None
    assert resp["degraded"] is True
    ready, payload = svc.ready()
    assert ready  # still scorable: degraded SHAP does not fail readiness
    assert payload["shap"] == "degraded" and payload["degraded"] is True
    assert "shap_error" in payload


def test_degraded_flag_absent_when_healthy(serving_artifact):
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    store, _ = serving_artifact
    svc = ScorerService.from_store(store, _fast_cfg())
    resp = svc.predict_single(_contract_payload())
    # the reference's exact response keys — no degraded flag on healthy paths
    assert set(resp) == {
        "prob_default", "shap_values", "base_value", "features", "input_row",
    }
    assert len(resp["shap_values"]) == len(svc.feature_names)


def test_runtime_shap_failure_degrades(serving_artifact):
    """Failure at execution time (not compile time) also degrades."""
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    store, _ = serving_artifact
    svc = ScorerService.from_store(store, _fast_cfg())

    def exec_boom(x):
        raise RuntimeError("device OOM mid-shap")

    svc._shap_fn = exec_boom
    resp = svc.predict_single(_contract_payload())
    assert resp["degraded"] is True and resp["shap_values"] is None
    assert 0.0 <= resp["prob_default"] <= 1.0


def test_degrade_disabled_raises(serving_artifact, monkeypatch):
    """degrade_shap=False keeps the old fail-fast behavior."""
    import cobalt_smart_lender_ai_tpu.parallel.partitioner as partitioner_mod
    import cobalt_smart_lender_ai_tpu.serve.service as service_mod
    from cobalt_smart_lender_ai_tpu.config import ServeConfig

    def broken_shap(*a, **k):
        raise RuntimeError("SHAP compile forced to fail")

    class _BrokenFused:
        def lower(self, *a, **k):
            raise RuntimeError("fused lowering forced to fail")

    monkeypatch.setattr(partitioner_mod, "shap_values", broken_shap)
    monkeypatch.setattr(partitioner_mod, "fused_score", _BrokenFused())
    monkeypatch.setattr(partitioner_mod, "_EXEC_CACHE", {})
    store, _ = serving_artifact
    cfg = ServeConfig(
        reliability=ReliabilityConfig(degrade_shap=False)
    )
    with pytest.raises(RuntimeError, match="forced to fail"):
        service_mod.ScorerService.from_store(store, cfg)


def test_asyncio_adapter_degraded_and_health(degraded_service):
    """ISSUE acceptance: POST /predict over real HTTP returns 200 with
    degraded=true and a valid prob_default; /healthz + /readyz respond."""
    import http.client

    from cobalt_smart_lender_ai_tpu.serve.http_asyncio import make_async_server

    server = make_async_server(degraded_service)
    try:
        host, port = "127.0.0.1", server.port

        def request(method: str, path: str, body: bytes | None = None):
            conn = http.client.HTTPConnection(host, port, timeout=30)
            headers = {"Content-Type": "application/json"} if body else {}
            conn.request(method, path, body=body, headers=headers)
            r = conn.getresponse()
            data = json.loads(r.read().decode())
            conn.close()
            return r.status, data

        status, resp = request(
            "POST", "/predict", json.dumps(_contract_payload()).encode()
        )
        assert status == 200, resp
        assert resp["degraded"] is True and resp["shap_values"] is None
        assert 0.0 <= resp["prob_default"] <= 1.0

        status, health = request("GET", "/healthz")
        assert (status, health) == (200, {"status": "ok"})
        status, ready = request("GET", "/readyz")
        assert status == 200  # degraded-but-scorable is still ready
        assert ready["shap"] == "degraded"
        assert ready["compiled_batch_buckets"]
    finally:
        server.close()


def test_fastapi_adapter_degraded_and_health(degraded_service, monkeypatch):
    """The same degraded contract through the FastAPI adapter (stubbed:
    fastapi is not installed in this image — see test_serve_fastapi_stub)."""
    import sys
    import types

    class _HTTPException(Exception):
        def __init__(self, status_code, detail=""):
            self.status_code = status_code
            self.detail = detail

    class _App:
        def __init__(self, title="", lifespan=None):
            self.lifespan = lifespan
            self.posts, self.gets = {}, {}

        def post(self, path):
            return lambda fn: self.posts.setdefault(path, fn)

        def get(self, path):
            return lambda fn: self.gets.setdefault(path, fn)

    class _Model:
        def __init__(self, **kw):
            self._data = kw

        def __init_subclass__(cls):
            pass

        def model_dump(self, by_alias=False):
            return dict(self._data)

    fastapi_mod = types.ModuleType("fastapi")
    fastapi_mod.FastAPI = _App
    fastapi_mod.HTTPException = _HTTPException
    fastapi_mod.UploadFile = object
    fastapi_mod.File = lambda *a, **k: None
    pydantic_mod = types.ModuleType("pydantic")
    pydantic_mod.BaseModel = _Model
    pydantic_mod.ConfigDict = dict
    pydantic_mod.Field = lambda alias=None: None
    monkeypatch.setitem(sys.modules, "fastapi", fastapi_mod)
    monkeypatch.setitem(sys.modules, "pydantic", pydantic_mod)

    from cobalt_smart_lender_ai_tpu.serve.http_fastapi import create_app

    app = create_app(service=degraded_service)
    # payload keyed by field names: _Model.model_dump has no aliasing, and
    # validate_single_input accepts field names directly; scoring handlers
    # are native coroutines since the asyncio serving core
    import asyncio

    resp = asyncio.run(app.posts["/predict"](_Model(**_contract_payload())))
    assert resp["degraded"] is True and resp["shap_values"] is None
    assert 0.0 <= resp["prob_default"] <= 1.0
    assert app.gets["/healthz"]() == {"status": "ok"}
    ready = app.gets["/readyz"]()
    assert ready["shap"] == "degraded" and ready["degraded"] is True


# --- UI client retry ----------------------------------------------------------


def test_api_client_retries_connection_errors(monkeypatch):
    import requests

    from cobalt_smart_lender_ai_tpu.ui.core import ApiClient

    sleeps: list[float] = []
    attempts = {"n": 0}

    class _Resp:
        def raise_for_status(self):
            pass

        def json(self):
            return {"prob_default": 0.5}

    def flaky_post(url, **kw):
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise requests.exceptions.ConnectionError("refused")
        return _Resp()

    monkeypatch.setattr(requests, "post", flaky_post)
    client = ApiClient(
        "http://127.0.0.1:9", retries=3, backoff_s=0.2, sleep=sleeps.append
    )
    assert client.predict({"loan_amnt": 1.0}) == {"prob_default": 0.5}
    assert attempts["n"] == 3
    assert sleeps == [0.2, 0.4]  # exponential backoff between attempts


def test_api_client_exhausts_and_raises(monkeypatch):
    import requests

    from cobalt_smart_lender_ai_tpu.ui.core import ApiClient

    attempts = {"n": 0}

    def always_down(url, **kw):
        attempts["n"] += 1
        raise requests.exceptions.ConnectionError("refused")

    monkeypatch.setattr(requests, "post", always_down)
    client = ApiClient("http://127.0.0.1:9", retries=3, sleep=lambda s: None)
    with pytest.raises(requests.exceptions.ConnectionError):
        client.predict({})
    assert attempts["n"] == 3


def test_api_client_does_not_retry_http_errors(monkeypatch):
    import requests

    from cobalt_smart_lender_ai_tpu.ui.core import ApiClient

    attempts = {"n": 0}

    class _Resp422:
        def raise_for_status(self):
            raise requests.exceptions.HTTPError("422 Unprocessable")

        def json(self):  # pragma: no cover
            return {}

    def post(url, **kw):
        attempts["n"] += 1
        return _Resp422()

    monkeypatch.setattr(requests, "post", post)
    client = ApiClient("http://127.0.0.1:9", retries=3, sleep=lambda s: None)
    with pytest.raises(requests.exceptions.HTTPError):
        client.predict({})
    assert attempts["n"] == 1  # an HTTP answer is an answer, not a flake
