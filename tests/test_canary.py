"""The continuous-training loop end to end: drift sketches/PSI units, the
canary shadow tap, the promotion gate (rejecting a label-shuffled degraded
candidate with a structured reason), atomic fleet promotion with score-cache
invalidation, SLO-burn automatic rollback inside the guard window, the /drift
+ /readyz + /metrics observability surface, and the chaos drill (typed errors
only, pointers never torn, canary scores never in a caller's response)."""

import json
import shutil
import urllib.error
import urllib.request

import numpy as np
import pytest

from cobalt_smart_lender_ai_tpu.config import ServeConfig
from cobalt_smart_lender_ai_tpu.data import schema
from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, ObjectStore
from cobalt_smart_lender_ai_tpu.io.model_registry import ModelRegistry
from cobalt_smart_lender_ai_tpu.reliability.errors import (
    PromotionRejected,
    RollbackFailed,
)
from cobalt_smart_lender_ai_tpu.serve.canary import rank_correlation
from cobalt_smart_lender_ai_tpu.serve.service import ScorerService
from cobalt_smart_lender_ai_tpu.telemetry.drift import FeatureSketch, psi
from tools.retrain import retrain_candidate


class ManualClock:
    def __init__(self, start: float = 100.0):
        self.now = start

    def __call__(self) -> float:
        return self.now

    def advance(self, s: float) -> None:
        self.now += s


_MINI = dict(rows=1200, n_estimators=8, max_depth=3, train_mlp=False)


@pytest.fixture(scope="module")
def seeded_lake(tmp_path_factory):
    """One miniature retrain, bootstrapped to `latest` (with the MLP
    challenger) — copied per test so registry mutations stay isolated."""
    root = tmp_path_factory.mktemp("canary") / "lake"
    store = ObjectStore(str(root))
    report = retrain_candidate(
        store, rows=1200, seed=5, n_estimators=8, max_depth=3,
        train_mlp=True, mlp_epochs=2, bootstrap=True,
    )
    return str(root), report


@pytest.fixture
def lake(seeded_lake, tmp_path):
    src, _ = seeded_lake
    dst = tmp_path / "lake"
    shutil.copytree(src, dst)
    return ObjectStore(str(dst))


def _cfg(**kw) -> ServeConfig:
    base = dict(
        canary_enabled=True,
        microbatch_enabled=False,
        prewarm_all_buckets=False,
        canary_sample_rate=1.0,
        canary_min_samples=6,
        # shadow vs request-path timings are both sub-ms here; a real ratio
        # bound would flake, and the check itself is still exercised
        canary_max_latency_ratio=1000.0,
        drift_min_samples=8,
    )
    base.update(kw)
    return ServeConfig(**base)


def _rows_from(X: np.ndarray, n: int, start: int = 0) -> list[dict]:
    out = []
    for i in range(start, start + n):
        row = {}
        for j, f in enumerate(schema.SERVING_FEATURES):
            v = float(X[i % len(X), j])
            if not np.isfinite(v):
                v = 0.0  # request validation requires finite numbers
            row[f] = int(v) if f in schema.SERVING_INT_FEATURES else v
        out.append(row)
    return out


# --- units: PSI / sketches / rank correlation ---------------------------------


def test_feature_sketch_psi():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(2000, 3))
    names = ["a", "b", "c"]
    base = FeatureSketch.from_data(X, names, bins=10)
    assert base.n == 2000

    # same distribution -> tiny PSI; shifted distribution -> large PSI on
    # exactly the shifted feature; NaNs land in the missing bin and count
    live = base.empty_like()
    live.observe(rng.normal(size=(1000, 3)))
    same = base.psi_vs(live)
    assert all(v < 0.1 for v in same.values())

    shifted = base.empty_like()
    Y = rng.normal(size=(1000, 3))
    Y[:, 1] += 5.0
    shifted.observe(Y)
    drifted = base.psi_vs(shifted)
    assert drifted["b"] > 0.25
    assert drifted["a"] < 0.1 and drifted["c"] < 0.1

    nan_live = base.empty_like()
    Z = rng.normal(size=(500, 3))
    Z[:, 2] = np.nan
    nan_live.observe(Z)
    assert base.psi_vs(nan_live)["c"] > 0.25  # missing-rate drift scores too

    # JSON round-trip (what rides in the registry provenance record)
    back = FeatureSketch.from_json(base.to_json())
    assert back.feature_names == names and back.n == 2000
    np.testing.assert_array_equal(back.counts, base.counts)
    assert psi(base.counts[0], base.counts[0]) == pytest.approx(0.0)


def test_feature_sketch_observe_row_by_name():
    base = FeatureSketch.from_data(
        np.random.default_rng(1).normal(size=(200, 2)), ["x", "y"]
    )
    live = base.empty_like()
    live.observe_row({"x": 0.1, "y": -0.2})
    live.observe_row({"x": 0.3})  # missing feature -> NaN bin, not a crash
    assert live.n == 2
    assert live.counts[1, -1] == 1


def test_rank_correlation_is_nan_safe():
    a = np.linspace(0.0, 1.0, 50)
    assert rank_correlation(a, a) == pytest.approx(1.0)
    assert rank_correlation(a, 1.0 - a) == pytest.approx(-1.0)
    # constant vector — the label-shuffled-candidate signature — reads as
    # zero agreement, never NaN
    assert rank_correlation(a, np.full(50, 0.3)) == 0.0
    assert rank_correlation(np.asarray([1.0]), np.asarray([1.0])) == 0.0


# --- retrain driver -----------------------------------------------------------


def test_retrain_publishes_canary_with_provenance(seeded_lake):
    src, report = seeded_lake
    reg = ModelRegistry(ObjectStore(src))
    # bootstrap promoted the first champion; nothing left in canary
    assert report["bootstrapped"] and report["channel"] == "latest"
    assert reg.channel("gbdt", "latest")["version"] == 1
    assert reg.channel("gbdt", "canary") is None
    record = reg.record("gbdt", 1)
    prov = record.provenance
    assert prov["dataset_md5"] and prov["config_hash"]
    sketch = FeatureSketch.from_json(prov["feature_sketch"])
    assert sketch.feature_names == list(schema.SERVING_FEATURES)
    assert sketch.n > 0
    assert record.metrics["test_auc"] > 0.5
    # the MLP challenger trained and published under its own name, to canary
    assert report["challenger"]["model"] == "gbdt_mlp"
    assert reg.channel("gbdt_mlp", "canary")["version"] == 1
    assert reg.record("gbdt_mlp", 1).kind == "MLPArtifact"


# --- the loop end to end (the ISSUE acceptance drill) -------------------------


def test_canary_loop_end_to_end_across_replicas(lake, serving_artifact):
    """Degraded candidate rejected with a structured reason; good candidate
    promoted atomically across both replicas (score caches invalidated);
    post-promotion SLO fast burn auto-rolls back to `previous` inside the
    guard window — all observable via model_info / metrics / readyz."""
    from cobalt_smart_lender_ai_tpu.serve.replicas import ReplicaSet

    _, X = serving_artifact
    clock = ManualClock()
    cfg = _cfg(replicas=2, replica_devices=False, score_cache_size=64)
    fleet = ReplicaSet.from_store(lake, cfg, clock=clock)
    try:
        assert isinstance(fleet, ReplicaSet)
        assert fleet.model_info == {
            "version": "v1", "channel": "latest",
            "provenance_md5": ModelRegistry(lake).channel("gbdt", "latest")["md5"],
        }
        v1_key = "models/gbdt/v1"
        assert all(r._model_key == v1_key for r in fleet.replicas)

        # -- a label-shuffled candidate lands in canary and is REJECTED ----
        retrain_candidate(lake, seed=6, degrade=True, **_MINI)
        fleet.canary.refresh()
        assert fleet.canary.status()["loaded"]
        rows = _rows_from(X, 16)
        for row in rows:
            resp = fleet.predict_single(row)
            assert resp["model_version"] == "v1"
            assert "canary" not in resp  # shadow result never leaks out
        assert fleet.canary.flush()
        with pytest.raises(PromotionRejected) as exc:
            fleet.promote_canary()
        report = exc.value.report
        assert not report["eligible"] and report["reasons"]
        assert any(
            r.startswith(("score_correlation", "score_delta"))
            for r in report["reasons"]
        ), report["reasons"]
        # nothing moved: the fleet and the registry still serve v1
        assert ModelRegistry(lake).channel("gbdt", "latest")["version"] == 1
        assert all(r._model_key == v1_key for r in fleet.replicas)

        # -- a good candidate passes the gate and lands fleet-wide ---------
        retrain_candidate(lake, seed=5, **_MINI)  # same regime as champion
        fleet.canary.refresh()
        for row in rows:
            fleet.predict_single(row)
        # warm both replicas' score caches, then promotion must clear them
        for _ in range(4):
            fleet.predict_single(rows[0])
        assert sum(len(r._score_cache) for r in fleet.replicas) > 0
        assert fleet.canary.flush()
        result = fleet.promote_canary()
        assert result["status"] == "promoted"
        assert result["promoted_version"] == 3 and result["previous_version"] == 1
        assert result["gate"]["checks"]["score_rank_correlation"] > 0.9
        v3_key = "models/gbdt/v3"
        assert all(r._model_key == v3_key for r in fleet.replicas)
        assert all(len(r._score_cache) == 0 for r in fleet.replicas)
        assert fleet.model_info["version"] == "v3"
        assert fleet.predict_single(rows[0])["model_version"] == "v3"
        reg = ModelRegistry(lake)
        assert reg.channel("gbdt", "latest")["version"] == 3
        assert reg.channel("gbdt", "previous")["version"] == 1
        assert reg.channel("gbdt", "canary") is None
        ok, payload = fleet.ready()
        assert ok and payload["model"]["version"] == "v3"
        assert payload["canary"]["guard"]["promoted_version"] == 3

        # -- SLO fast burn inside the guard window: automatic rollback -----
        clock.advance(1.0)
        for _ in range(5):
            fleet.observe_request("/predict", 500, 0.001)
        assert fleet.model_info["version"] == "v1"
        assert all(r._model_key == v1_key for r in fleet.replicas)
        latest = reg.channel("gbdt", "latest")
        assert latest["version"] == 1 and latest["rolled_back_from"] == 3
        assert reg.channel("gbdt", "previous")["version"] == 3  # forensics
        _, payload = fleet.ready()
        assert payload["canary"]["guard"] is None
        assert payload["canary"]["last_promotion"]["action"] == "rolled_back"
        assert payload["canary"]["last_promotion"]["trigger"] == "slo_fast_burn"

        # the whole story is on /metrics
        text = fleet.registry.render()
        assert 'cobalt_model_info{version="v1",channel="latest"' in text
        assert (
            'cobalt_canary_promotions_total{outcome="rejected"} 1' in text
        )
        assert (
            'cobalt_canary_promotions_total{outcome="promoted"} 1' in text
        )
        assert (
            'cobalt_canary_rollbacks_total{trigger="slo_fast_burn"} 1' in text
        )
        assert "cobalt_canary_shadow_total" in text
        assert "cobalt_drift_max_psi" in text
    finally:
        fleet.close()


# --- drift detection ----------------------------------------------------------


def test_drift_alarm_fires_once_and_can_trigger_retrain(lake, serving_artifact):
    _, X = serving_artifact
    alarms = []
    cfg = _cfg(canary_enabled=False, model_key="models/gbdt/v1")
    svc = ScorerService.from_store(lake, cfg)
    try:
        svc.enable_canary(on_drift=alarms.append)  # the retrain hook
        assert svc.model_info["version"] == "v1"
        report = svc.drift_report()
        assert report["status"] == "ok" and report["n_live"] == 0
        assert report["max_psi"] is None  # below min samples: no alarm

        # live traffic from far outside the training distribution
        for row in _rows_from(X * 1000.0, 12):
            svc.canary.tap(row, 0.5, None)
        assert svc.canary.flush()
        report = svc.drift_report()
        assert report["alarm"] and report["max_psi"] > 0.25
        assert report["n_live"] == 12
        assert set(report["features"]) == set(schema.SERVING_FEATURES)
        assert len(alarms) == 1 and alarms[0]["status"] == "ok"

        # edge-triggered: staying in alarm does not re-fire the hook
        for row in _rows_from(X * 1000.0, 4, start=12):
            svc.canary.tap(row, 0.5, None)
        assert svc.canary.flush()
        assert len(alarms) == 1

        text = svc.registry.render()
        assert "cobalt_drift_alarm 1" in text
        assert 'cobalt_drift_psi{feature="loan_amnt"}' in text
    finally:
        svc.close()


# --- HTTP surface -------------------------------------------------------------


def _http(base, path, payload=None, method=None):
    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(
        base + path, data=data, method=method,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=60) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        body = err.read()
        return err.code, json.loads(body) if body else {}


@pytest.fixture
def live_service(lake):
    from cobalt_smart_lender_ai_tpu.serve.http_asyncio import make_async_server

    svc = ScorerService.from_store(lake, _cfg())
    server = make_async_server(svc, "127.0.0.1", 0)
    try:
        yield svc, f"http://127.0.0.1:{server.port}"
    finally:
        server.close()
        svc.close()


def test_http_canary_surface(live_service):
    svc, base = live_service

    status, ready = _http(base, "/readyz")
    assert status == 200
    assert ready["model"]["version"] == "v1"
    assert ready["model"]["channel"] == "latest"
    assert ready["canary"]["enabled"] and not ready["canary"]["loaded"]

    status, drift = _http(base, "/drift")
    assert status == 200 and drift["status"] == "ok"

    # no canary published: promote is a typed 409 with the structured report
    status, body = _http(base, "/admin/promote", payload={})
    assert status == 409
    assert body["error"] == "promotion_rejected"
    assert body["report"]["reasons"] == ["no_canary"]

    # nothing to restore either: typed 409, champion untouched
    status, body = _http(base, "/admin/rollback", payload={"reason": "x"})
    assert status == 409 and body["error"] == "rollback_failed"
    assert svc.model_info["version"] == "v1"

    from cobalt_smart_lender_ai_tpu.telemetry import parse_exposition

    with urllib.request.urlopen(base + "/metrics", timeout=30) as resp:
        text = resp.read().decode()
    parse_exposition(text)
    assert 'cobalt_model_info{version="v1",channel="latest"' in text
    assert "cobalt_canary_loaded 0" in text


# --- chaos: the loop under injected faults ------------------------------------


@pytest.mark.faults
def test_canary_cycle_under_faults_yields_typed_errors_only(lake, serving_artifact):
    """Publish/shadow/promote/rollback over live HTTP against a store
    dropping calls and injecting latency: every response is 2xx or a TYPED
    error (zero untyped 500s), channel pointers are never torn, and no
    response ever carries a canary score."""
    from cobalt_smart_lender_ai_tpu.reliability import ResilientStore, RetryPolicy
    from cobalt_smart_lender_ai_tpu.reliability.faults import (
        FaultInjectingStore,
        FaultSpec,
    )
    from cobalt_smart_lender_ai_tpu.serve.http_asyncio import make_async_server
    from cobalt_smart_lender_ai_tpu.telemetry import MetricsRegistry

    _, X = serving_artifact
    flaky = FaultInjectingStore(
        lake,
        seed=29,
        faults={
            "put": FaultSpec(rate=0.2, max_faults=25, delay_s=0.001),
            "get": FaultSpec(rate=0.15, max_faults=25, delay_s=0.001),
            "exists": FaultSpec(rate=0.1, max_faults=15),
        },
        sleep=lambda s: None,
        registry=MetricsRegistry(),
    )
    store = ResilientStore(
        flaky,
        RetryPolicy(max_attempts=6, base_delay_s=0.0, jitter=0.0),
        verify_reads=True,
    )
    svc = ScorerService.from_store(store, _cfg(canary_min_samples=4))
    server = make_async_server(svc, "127.0.0.1", 0)
    base = f"http://127.0.0.1:{server.port}"

    allowed_codes = {
        "promotion_rejected", "rollback_failed", "reload_failed",
        "circuit_open", "shed",
    }
    resp_keys = {
        "prob_default", "shap_values", "base_value", "features",
        "input_row", "model_version", "degraded",
    }
    observed = []

    def check(status, body):
        observed.append((status, body))
        if status >= 400:
            assert body.get("error") in allowed_codes, (status, body)
        return status, body

    def pointers_whole():
        reg = ModelRegistry(lake)  # the clean inner view
        for ch in ("latest", "canary", "previous"):
            ptr = reg.channel("gbdt", ch)
            if ptr is not None:
                assert reg.record("gbdt", int(ptr["version"])).key == ptr["key"]
                GBDTArtifact.load(lake, ptr["key"])

    try:
        # an identical-regime candidate: publishes retry through the faults
        art = GBDTArtifact.load(lake, "models/gbdt/v1")
        ModelRegistry(store).publish("gbdt", art)
        pointers_whole()

        # premature promote: empty window -> typed 409, never untyped
        check(*_http(base, "/admin/promote", payload={}))

        rows = _rows_from(X, 10)
        for row in rows:
            status, body = check(*_http(base, "/predict", payload=row))
            if status == 200:
                assert set(body) <= resp_keys, set(body)
        svc.canary.refresh()
        for row in rows:
            check(*_http(base, "/predict", payload=row))
        assert svc.canary.flush()

        promoted = False
        for _ in range(5):
            status, body = check(*_http(base, "/admin/promote", payload={}))
            pointers_whole()
            if status == 200:
                promoted = body["status"] == "promoted"
                break
        assert promoted, observed[-1]
        assert svc.model_info["version"] == "v2"

        for _ in range(5):
            status, body = check(
                *_http(base, "/admin/rollback", payload={"reason": "chaos"})
            )
            pointers_whole()
            if status == 200:
                break
        assert status == 200 and body["status"] == "rolled_back"
        assert svc.model_info["version"] == "v1"

        assert flaky.injected.total() > 0  # the drill actually injected
        assert all(
            s < 500 or b.get("error") in allowed_codes for s, b in observed
        )
    finally:
        server.close()
        svc.close()
