"""Micro-batching scheduler: coalescing, per-request correctness, deadline
composition, and hot-swap atomicity.

The batcher's coalescing tick runs on the real clock (it is a throughput
knob, not request policy), so determinism comes from `MicroBatcher.pause`:
tests quiesce the worker, stack the queue to a known depth, release, and
assert on the exact batch that forms. Request *deadlines* stay on the
service's injectable clock, so the queued-expiry 504 is pinned with
`ManualClock.advance` — no test sleeps to make a deadline pass.
"""

from __future__ import annotations

import dataclasses
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from cobalt_smart_lender_ai_tpu.config import ReliabilityConfig, ServeConfig
from cobalt_smart_lender_ai_tpu.data import schema
from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, ObjectStore
from cobalt_smart_lender_ai_tpu.reliability import DeadlineExceeded
from cobalt_smart_lender_ai_tpu.serve.service import (
    SINGLE_INPUT_FIELDS,
    ScorerService,
)


class ManualClock:
    def __init__(self, start: float = 0.0):
        self.now = start

    def __call__(self) -> float:
        return self.now

    def advance(self, s: float) -> None:
        self.now += s


def _payload(seed: float = 1.5) -> dict:
    """Schema-complete /predict body; ``seed`` varies the continuous fields
    so concurrent requests carry distinct rows."""
    return {
        canonical: 1 if canonical in schema.SERVING_INT_FEATURES else seed
        for canonical in SINGLE_INPUT_FIELDS.values()
    }


def _cfg(max_wait_ms: float = 25.0, max_rows: int = 16, **rel) -> ServeConfig:
    return ServeConfig(
        precompile_batch_buckets=(),
        prewarm_all_buckets=False,  # compile only the cap: keeps tier-1 fast
        microbatch_max_wait_ms=max_wait_ms,
        microbatch_max_rows=max_rows,
        reliability=ReliabilityConfig(**rel),
    )


def _wait_for(predicate, timeout_s: float = 10.0) -> None:
    import time

    end = time.monotonic() + timeout_s
    while not predicate():
        assert time.monotonic() < end, "condition not reached in time"
        time.sleep(0.002)


# --- coalescing + per-request correctness -------------------------------------


def test_concurrent_requests_coalesce_into_one_dispatch(serving_artifact):
    """N threads scoring distinct rows form exactly ONE batch under a paused
    scheduler, and every caller gets its own row's probability and SHAP —
    bit-comparable to the direct (unbatched) path on the same model."""
    store, _ = serving_artifact
    n = 16
    svc = ScorerService.from_store(store, _cfg(max_rows=n))
    direct = ScorerService.from_store(
        store, dataclasses.replace(_cfg(), microbatch_enabled=False)
    )
    payloads = [_payload(seed=0.25 * i) for i in range(n)]
    results: list[dict | None] = [None] * n

    def client(i: int) -> None:
        results[i] = svc.predict_single(payloads[i])

    threads = [threading.Thread(target=client, args=(i,)) for i in range(n)]
    with svc.batcher.pause():
        for t in threads:
            t.start()
        # all n requests are queued behind the paused worker
        _wait_for(lambda: svc.batcher.queue_depth() == n)
        assert svc.batcher.batches == 0
    for t in threads:
        t.join(timeout=30.0)
    assert svc.batcher.batches == 1  # ONE device dispatch for all n callers
    assert svc.batcher.max_batch_rows == n
    assert svc.batcher.stats()["coalesced_rows"] == n

    for i, resp in enumerate(results):
        want = direct.predict_single(payloads[i])
        np.testing.assert_allclose(
            resp["prob_default"], want["prob_default"], atol=1e-6
        )
        np.testing.assert_allclose(
            resp["shap_values"], want["shap_values"], atol=1e-4
        )
        assert resp["input_row"] == want["input_row"]
        assert set(resp) == set(want)  # exact response-shape parity
    # distinct rows produced distinct scores (the batch wasn't transposed)
    probs = {round(r["prob_default"], 9) for r in results}
    assert len(probs) > 1
    svc.close()
    direct.close()


def test_queued_deadline_expiry_resolves_504_without_batch_slot(
    serving_artifact,
):
    """A request whose deadline expires while queued gets DeadlineExceeded
    (HTTP 504) at dispatch time and does NOT occupy a batch slot — the
    batch that would have carried it never forms when it was the only row."""
    store, _ = serving_artifact
    clk = ManualClock()
    svc = ScorerService.from_store(
        store, _cfg(request_deadline_s=1.0), clock=clk
    )
    caught: list[BaseException] = []

    def client() -> None:
        try:
            svc.predict_single(_payload())
        except BaseException as exc:
            caught.append(exc)

    t = threading.Thread(target=client)
    with svc.batcher.pause():
        t.start()
        _wait_for(lambda: svc.batcher.queue_depth() == 1)
        clk.advance(2.0)  # the deadline passes while the request is queued
    t.join(timeout=30.0)
    assert len(caught) == 1
    assert isinstance(caught[0], DeadlineExceeded)
    assert caught[0].status == 504
    assert "queued for micro-batch" in str(caught[0])
    assert svc.batcher.expired_in_queue == 1
    assert svc.batcher.batches == 0  # expired rows never reach the device
    svc.close()


# --- hot swap atomicity -------------------------------------------------------


def _zeroed(art: GBDTArtifact) -> GBDTArtifact:
    """Every leaf 0 — margin 0, P(default) exactly 0.5 for any input, so a
    swap to it is observable from any single prediction."""
    return dataclasses.replace(
        art,
        forest=dataclasses.replace(
            art.forest, leaf_value=jnp.zeros_like(art.forest.leaf_value)
        ),
    )


def test_mid_batch_hot_swap_never_mixes_models(serving_artifact, tmp_path):
    """Clients hammering predict_single while the model is hot-swapped see
    either the old model's score or the new one's — never a mixture, and no
    request errors. After the swap every new request scores on the new
    model."""
    shared, _ = serving_artifact
    art = GBDTArtifact.load(shared, "models/gbdt/model_tree")
    store = ObjectStore(str(tmp_path / "lake"))
    art.save(store, "models/gbdt/model_tree")
    svc = ScorerService.from_store(store, _cfg(max_wait_ms=1.0))
    payload = _payload()
    old_prob = svc.predict_single(payload)["prob_default"]
    assert abs(old_prob - 0.5) > 1e-6, "seed model must not score exactly 0.5"
    _zeroed(art).save(store, "models/gbdt/model_tree")

    stop = threading.Event()
    probs: list[float] = []
    errors: list[BaseException] = []
    lock = threading.Lock()

    def client() -> None:
        while not stop.is_set():
            try:
                p = svc.predict_single(payload)["prob_default"]
            except BaseException as exc:
                with lock:
                    errors.append(exc)
                return
            with lock:
                probs.append(p)

    threads = [threading.Thread(target=client) for _ in range(8)]
    for t in threads:
        t.start()
    _wait_for(lambda: len(probs) >= 16)  # traffic flowing pre-swap
    result = svc.reload_from_store()
    _wait_for(lambda: len(probs) >= 64)  # and post-swap
    stop.set()
    for t in threads:
        t.join(timeout=30.0)

    assert result["status"] == "ok"
    assert not errors, f"swap under load errored: {errors[:3]}"
    for p in probs:
        assert abs(p - old_prob) < 1e-6 or abs(p - 0.5) < 1e-9, (
            f"score {p} belongs to neither the old nor the new model"
        )
    assert svc.predict_single(payload)["prob_default"] == pytest.approx(0.5)
    svc.close()


# --- warming, readiness, degrade, shutdown ------------------------------------


def test_warming_precompiles_coalescing_cap_bucket(serving_artifact):
    """Construction warms margin AND SHAP programs at the batcher's cap
    bucket, and /readyz reports both warmed sets plus live batcher stats."""
    store, _ = serving_artifact
    svc = ScorerService.from_store(store, _cfg(max_rows=8))
    assert 8 in svc.compiled_batch_buckets
    assert svc.compiled_shap_buckets == (1, 8)
    ready, payload = svc.ready()
    assert ready
    assert payload["compiled_shap_buckets"] == [1, 8]
    mb = payload["microbatch"]
    assert mb["enabled"] is True
    assert mb["max_rows"] == 8
    assert {"batches", "coalesced_rows", "queued", "expired_in_queue"} <= set(mb)
    svc.close()

    off = ScorerService.from_store(
        store, dataclasses.replace(_cfg(), microbatch_enabled=False)
    )
    assert off.ready()[1]["microbatch"] == {"enabled": False}
    off.close()


def test_batched_shap_degrade_keeps_probability_contract(serving_artifact):
    """SHAP unavailable (degraded model) with the batcher on: probabilities
    still resolve through the coalesced dispatch, responses carry
    shap_values null + degraded flag — same contract as the direct path."""
    store, _ = serving_artifact
    svc = ScorerService.from_store(store, _cfg())
    svc._shap_fn = None  # the established degraded-model injection point
    svc._shap_error = "injected: SHAP compile failed"
    resp = svc.predict_single(_payload())
    assert 0.0 <= resp["prob_default"] <= 1.0
    assert resp["shap_values"] is None and resp["base_value"] is None
    assert resp["degraded"] is True
    assert svc.batcher.batches >= 1  # it went through the batched path
    svc.close()


def test_close_drains_and_falls_back_to_direct_path(serving_artifact):
    """After close() the service keeps scoring on the per-request path —
    the adapters call close() at shutdown and stragglers must not 500."""
    store, _ = serving_artifact
    svc = ScorerService.from_store(store, _cfg())
    svc.close()
    svc.close()  # idempotent
    before = svc.batcher.batches
    resp = svc.predict_single(_payload())
    assert 0.0 <= resp["prob_default"] <= 1.0
    assert len(resp["shap_values"]) == len(schema.SERVING_FEATURES)
    assert svc.batcher.batches == before  # scored without the batcher
