"""Self-healing fleet: replica supervision, quarantine/restart, hedged
failover, and the chaos harness (`reliability.chaos`).

Pins the PR's guarantees:

- `ReplicaHealth` is a pure fake-clock state machine: EWMA thresholds drive
  healthy -> degraded -> quarantined, recovery drops back to healthy, and
  without a supervisor to heal (``allow_quarantine=False``) the machine tops
  out at degraded;
- the dead-replica black hole is fixed: an error-storming replica is
  penalized, then quarantined, and does NOT capture the fleet's traffic —
  every request still succeeds (hedged failover rescues the ones that
  landed on it first);
- hedged failover retries exactly once, on a different replica, only for
  replica-*internal* failures, and never with an exhausted deadline;
- the micro-batch worker watchdog turns a killed worker thread into typed
  500 ``worker_dead`` futures (zero lost requests), restarts the worker,
  and surfaces ``worker_alive`` in `stats()` / ``/readyz``;
- the supervisor quarantines on failed deadline-bounded probes (a
  chaos-hung worker) and heals: drain -> rebuild -> smoke-check -> swap ->
  readmit, all observable via ``tick()`` summaries and metrics;
- `ReplicaSet.close()` stays bounded with a chaos-wedged replica;
- the manual admin plane (``POST /admin/quarantine`` / ``/admin/readmit``)
  works over live HTTP, shows up in ``/readyz`` drill-down, and diverts
  traffic;
- live heal under concurrent HTTP load: chaos kills + error-storms one
  replica mid-run, clients see zero untyped 500s, and the fleet returns to
  all-healthy without operator action — while the same scenario with
  supervision and hedging OFF demonstrably degrades.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from cobalt_smart_lender_ai_tpu.config import ServeConfig
from cobalt_smart_lender_ai_tpu.data import schema
from cobalt_smart_lender_ai_tpu.reliability import (
    ChaosError,
    ChaosPlan,
    WorkerDead,
)
from cobalt_smart_lender_ai_tpu.reliability.deadline import Deadline
from cobalt_smart_lender_ai_tpu.reliability.errors import (
    DeadlineExceeded,
    RequestShed,
    ValidationError,
)
from cobalt_smart_lender_ai_tpu.serve.http_asyncio import make_async_server
from cobalt_smart_lender_ai_tpu.serve.replicas import ReplicaSet
from cobalt_smart_lender_ai_tpu.serve.service import (
    SINGLE_INPUT_FIELDS,
    ScorerService,
)
from cobalt_smart_lender_ai_tpu.serve.supervisor import (
    DEGRADED,
    HEALTHY,
    QUARANTINED,
    ReplicaHealth,
    replica_internal,
)


def _cfg(**kw) -> ServeConfig:
    """Fleet config tuned for fast tests: no prewarm, no score cache (chaos
    tests count real dispatches), snappy supervisor knobs."""
    base = dict(
        replicas=3,
        microbatch_enabled=False,
        precompile_batch_buckets=(),
        prewarm_all_buckets=False,
        score_cache_size=0,
        supervisor_probe_deadline_s=0.3,
        supervisor_probe_failures=1,
        supervisor_drain_timeout_s=1.0,
        replica_close_timeout_s=2.0,
    )
    base.update(kw)
    return ServeConfig(**base)


def _payload() -> dict:
    return {
        canonical: 1 if canonical in schema.SERVING_INT_FEATURES else 1.5
        for canonical in SINGLE_INPUT_FIELDS.values()
    }


def _routed_counts(fleet: ReplicaSet) -> list[int]:
    return [
        int(fleet._m_routed.labels(replica=str(i)).value)
        for i in range(len(fleet.replicas))
    ]


def _hedge_counts(fleet: ReplicaSet) -> dict:
    return {
        o: int(fleet._m_hedges.labels(outcome=o).value)
        for o in ("rescued", "failed")
    }


class _FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@contextlib.contextmanager
def _serving(service):
    server = make_async_server(service)
    try:
        yield f"http://127.0.0.1:{server.port}"
    finally:
        server.close()


def _request(url, data=None, headers=None):
    req = urllib.request.Request(
        url, data=data, method="POST" if data is not None else "GET"
    )
    if data is not None:
        req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


# --- ReplicaHealth: the pure state machine (fake clock, no fleet) -------------


def test_replica_internal_classification():
    """Only failures that indict the replica feed the EWMA / hedging: typed
    client-policy errors and caller-side BaseExceptions never do."""
    assert replica_internal(WorkerDead("worker died"))
    assert replica_internal(RuntimeError("boom"))
    assert replica_internal(ChaosError("injected"))
    assert not replica_internal(ValidationError("bad field"))
    assert not replica_internal(DeadlineExceeded("too slow"))
    assert not replica_internal(RequestShed("shed"))
    assert not replica_internal(KeyboardInterrupt())


def test_ewma_walk_healthy_degraded_quarantined():
    """Defaults (alpha=.2): failure EWMA is 1-0.8^n, so degraded lands on
    the 2nd consecutive failure (.36 >= .3) and quarantine on the 5th
    (.67 >= .6)."""
    clock = _FakeClock()
    h = ReplicaHealth(0, clock=clock)
    assert h.state == HEALTHY and h.routable

    assert h.record_outcome(False, allow_quarantine=True) is None  # .2
    t = h.record_outcome(False, allow_quarantine=True)  # .36
    assert t == (HEALTHY, DEGRADED)
    assert h.routable  # degraded stays in rotation, penalized
    for _ in range(2):  # .488, .59 — still degraded
        assert h.record_outcome(False, allow_quarantine=True) is None
    t = h.record_outcome(False, allow_quarantine=True)  # .67
    assert t == (DEGRADED, QUARANTINED)
    assert not h.routable
    assert h.quarantines == 1
    assert h.quarantined_at == clock.t


def test_ewma_recovery_resets_to_healthy():
    clock = _FakeClock()
    h = ReplicaHealth(1, clock=clock)
    for _ in range(2):
        h.record_outcome(False, allow_quarantine=True)
    assert h.state == DEGRADED
    transitions = [
        h.record_outcome(True, allow_quarantine=True) for _ in range(8)
    ]
    assert (DEGRADED, HEALTHY) in [t for t in transitions if t]
    assert h.state == HEALTHY
    assert h.error_ewma == 0.0  # readmission wipes the slate


def test_without_supervisor_tops_out_at_degraded():
    """No supervisor -> nobody to heal a quarantined replica -> the machine
    must never evict; the router penalty does the shielding instead."""
    h = ReplicaHealth(0, clock=_FakeClock())
    for _ in range(50):
        h.record_outcome(False, allow_quarantine=False)
    assert h.state == DEGRADED
    assert h.routable


def test_snapshot_uses_injected_clock():
    clock = _FakeClock()
    h = ReplicaHealth(2, clock=clock)
    h.to(QUARANTINED, "operator says so", manual=True)
    clock.advance(3.5)
    snap = h.snapshot()
    assert snap["state"] == QUARANTINED
    assert snap["manual"] is True
    assert snap["reason"] == "operator says so"
    assert snap["since_transition_s"] == 3.5


# --- router: the dead-replica black hole fix ----------------------------------


def test_error_storming_replica_does_not_capture_fleet(serving_artifact):
    """THE regression this PR exists for: a replica failing instantly used
    to report zero load and win every least-loaded pick. Now its EWMA
    penalty sheds traffic, auto-quarantine evicts it, and hedged failover
    rescues the requests that hit it first — the client sees zero errors."""
    store, _ = serving_artifact
    fleet = ReplicaSet.from_store(store, _cfg())
    try:

        def _boom(payload, deadline=None):
            raise RuntimeError("injected storm")

        fleet.replicas[0].predict_single = _boom
        payload = _payload()
        for _ in range(30):  # no exception may escape
            resp = fleet.predict_single(payload)
            assert 0.0 <= resp["prob_default"] <= 1.0
        # the one storm that landed was hedged elsewhere...
        assert _hedge_counts(fleet)["rescued"] >= 1
        # ...and the EWMA penalty shed the rest of the traffic: on the old
        # least-loaded router the instantly-failing replica reported ZERO
        # load and won every pick (0 routed to the healthy pair)
        counts = _routed_counts(fleet)
        assert counts[0] <= 3
        assert counts[1] + counts[2] >= 30
        assert fleet.replica_health[0].error_ewma > 0.0
    finally:
        fleet.close()


def test_manual_quarantine_diverts_traffic_and_readmit_restores(
    serving_artifact,
):
    store, _ = serving_artifact
    fleet = ReplicaSet.from_store(store, _cfg())
    try:
        result = fleet.quarantine_replica(1, reason="operator drill")
        assert result["status"] == "quarantined"
        assert fleet.replica_health[1].manual is True
        # the supervisor must leave manual quarantines to the operator
        summary = fleet.supervisor.tick()
        assert summary["healed"] == 0
        assert fleet.replica_health[1].state == QUARANTINED

        before = _routed_counts(fleet)
        for _ in range(10):
            fleet.predict_single(_payload())
        after = _routed_counts(fleet)
        assert after[1] == before[1]

        ok, payload = fleet.ready()
        assert ok  # a healing fleet still serves
        assert payload["router"]["routable"] == [True, False, True]
        assert payload["per_replica"][1]["supervisor"]["state"] == QUARANTINED

        assert fleet.readmit_replica(1)["status"] == "readmitted"
        before = _routed_counts(fleet)
        for _ in range(9):
            fleet.predict_single(_payload())
        assert _routed_counts(fleet)[1] > before[1]
    finally:
        fleet.close()


def test_quarantine_refuses_to_darken_the_fleet(serving_artifact):
    store, _ = serving_artifact
    fleet = ReplicaSet.from_store(store, _cfg(replicas=2))
    try:
        fleet.quarantine_replica(0)
        with pytest.raises(ValidationError):
            fleet.quarantine_replica(1)  # last routable replica
        with pytest.raises(ValidationError):
            fleet.quarantine_replica(99)  # out of range
        with pytest.raises(ValidationError):
            fleet.readmit_replica(1)  # healthy, nothing to readmit
    finally:
        fleet.close()


# --- hedged failover ----------------------------------------------------------


def test_hedge_target_decision_table(serving_artifact):
    store, _ = serving_artifact
    fleet = ReplicaSet.from_store(store, _cfg(replicas=2))
    try:
        assert fleet._hedge_target(RuntimeError("x"), None, 0) == (0,)
        assert fleet._hedge_target(RuntimeError("x"), Deadline(5.0), 0) == (0,)
        # typed policy errors fail identically anywhere: never hedge
        assert fleet._hedge_target(ValidationError("x"), None, 0) is None
        assert fleet._hedge_target(DeadlineExceeded("x"), None, 0) is None
        assert fleet._hedge_target(RequestShed("x"), None, 0) is None
        # an exhausted deadline must never be violated by a hedge
        assert fleet._hedge_target(RuntimeError("x"), Deadline(0.0), 0) is None
        # unknown failed index (the failure predates a pick)
        assert fleet._hedge_target(RuntimeError("x"), None, None) is None
    finally:
        fleet.close()


def test_hedged_failover_rescues_on_internal_error(serving_artifact):
    store, _ = serving_artifact
    fleet = ReplicaSet.from_store(store, _cfg(replicas=2))
    try:

        def _boom(payload, deadline=None):
            raise RuntimeError("replica-internal fault")

        fleet.replicas[0].predict_single = _boom
        fleet._rr = 0  # force the next pick onto the poisoned replica
        resp = fleet.predict_single(_payload())
        assert 0.0 <= resp["prob_default"] <= 1.0
        counts = _hedge_counts(fleet)
        assert counts["rescued"] == 1 and counts["failed"] == 0
        assert _routed_counts(fleet) == [1, 1]  # one failed try, one rescue
    finally:
        fleet.close()


def test_no_hedge_on_typed_client_errors(serving_artifact):
    store, _ = serving_artifact
    fleet = ReplicaSet.from_store(store, _cfg(replicas=2))
    try:
        before = _hedge_counts(fleet)
        with pytest.raises(ValidationError):
            fleet.predict_single({"loan_amnt": "not-a-number"})
        with pytest.raises(DeadlineExceeded):
            fleet.predict_single(_payload(), deadline=Deadline(0.0))
        assert _hedge_counts(fleet) == before
        # typed errors never feed the health EWMA either
        assert all(h.error_ewma == 0.0 for h in fleet.replica_health)
    finally:
        fleet.close()


# --- micro-batch worker watchdog ----------------------------------------------


def test_worker_death_resolves_futures_typed_and_restarts(serving_artifact):
    """A chaos-killed worker must (a) fail every queued future with the
    typed 500 ``worker_dead`` — zero lost requests — and (b) restart itself
    so the next request scores normally."""
    store, _ = serving_artifact
    svc = ScorerService.from_store(
        store,
        _cfg(replicas=1, microbatch_enabled=True, microbatch_max_wait_ms=1.0),
    )
    plan = ChaosPlan(seed=1).kill_worker(replica=0)
    try:
        plan.inject(svc)
        row = {name: 0.0 for name in svc.feature_names}
        with svc.batcher.pause():  # coalesce three rows into the doomed batch
            futs = [svc.batcher.submit(row, None) for _ in range(3)]
        for fut in futs:
            with pytest.raises(WorkerDead) as ei:
                fut.result(timeout=10.0)
            assert ei.value.status == 500
            assert ei.value.body()["error"] == "worker_dead"

        deadline = time.monotonic() + 10.0
        while not svc.batcher.worker_alive() and time.monotonic() < deadline:
            time.sleep(0.01)
        stats = svc.batcher.stats()
        assert stats["worker_alive"] is True
        assert stats["worker_restarts"] >= 1
        resp = svc.predict_single(_payload())  # the revived worker serves
        assert 0.0 <= resp["prob_default"] <= 1.0
        ok, ready = svc.ready()
        assert ok and ready["microbatch"]["worker_alive"] is True
    finally:
        plan.release()
        svc.close()


def test_ensure_worker_revives_a_dead_thread(serving_artifact):
    store, _ = serving_artifact
    svc = ScorerService.from_store(
        store,
        _cfg(replicas=1, microbatch_enabled=True, microbatch_max_wait_ms=1.0),
    )
    try:
        assert svc.batcher.ensure_worker() is False  # alive -> no-op
        dead = threading.Thread(target=lambda: None)
        dead.start()
        dead.join()
        svc.batcher._thread = dead
        assert svc.batcher.worker_alive() is False
        assert svc.batcher.ensure_worker() is True
        assert svc.batcher.worker_alive() is True
        resp = svc.predict_single(_payload())
        assert 0.0 <= resp["prob_default"] <= 1.0
    finally:
        svc.close()


# --- the supervisor: probe -> quarantine -> heal ------------------------------


def test_probe_quarantines_hung_replica_and_heals(serving_artifact):
    """A chaos-hung worker wedges dispatch: the deadline-bounded probe times
    out, the supervisor quarantines, and the next tick heals — fresh
    replica compiled from the published artifact, swapped into the routing
    slot, readmitted. All driven via tick(), no background thread."""
    store, _ = serving_artifact
    fleet = ReplicaSet.from_store(
        store, _cfg(replicas=2, microbatch_enabled=True, microbatch_max_wait_ms=1.0)
    )
    plan = ChaosPlan(seed=2).hang_dispatch(replica=1, hang_s=60.0, max_events=1)
    try:
        plan.inject(fleet)
        old = fleet.replicas[1]
        summary = fleet.supervisor.tick()
        assert summary["quarantined"] == 1
        assert fleet.replica_health[1].state == QUARANTINED
        assert fleet.replica_health[1].manual is False

        summary = fleet.supervisor.tick()
        assert summary["healed"] == 1
        assert fleet.replica_health[1].state == HEALTHY
        assert fleet.replicas[1] is not old  # genuinely rebuilt, not readmitted
        heal_s = fleet.supervisor._m_heal_s.labels(replica="1").value
        assert heal_s >= 0.0
        rebuilt = fleet.supervisor._m_rebuilds.labels(
            replica="1", outcome="ok"
        ).value
        assert rebuilt == 1

        summary = fleet.supervisor.tick()  # the rebuilt replica passes probes
        assert summary["probed"] == 2 and summary["quarantined"] == 0
        for _ in range(6):  # and serves traffic
            resp = fleet.predict_single(_payload())
            assert 0.0 <= resp["prob_default"] <= 1.0
    finally:
        plan.release()  # un-wedge the reaped worker so close stays quick
        fleet.close()


def test_fleet_close_bounded_with_wedged_replica(serving_artifact):
    """One chaos-hung worker must not stall fleet shutdown: replicas close
    concurrently and stragglers are abandoned at the bound."""
    store, _ = serving_artifact
    fleet = ReplicaSet.from_store(
        store,
        _cfg(
            replicas=2,
            microbatch_enabled=True,
            microbatch_max_wait_ms=1.0,
            replica_close_timeout_s=1.0,
        ),
    )
    plan = ChaosPlan(seed=3).hang_dispatch(replica=1, hang_s=60.0, max_events=1)
    plan.inject(fleet)
    try:
        row = {name: 0.0 for name in fleet.feature_names}
        fleet.replicas[1].batcher.submit(row, None)  # wedge the worker
        give_up = time.monotonic() + 5.0
        while plan.events.get("hang", 0) == 0 and time.monotonic() < give_up:
            time.sleep(0.01)
        assert plan.events.get("hang", 0) == 1

        t0 = time.monotonic()
        fleet.close()
        assert time.monotonic() - t0 < 8.0  # bounded, not the 60s hang
    finally:
        plan.release()


# --- manual admin plane over live HTTP ----------------------------------------


def test_admin_quarantine_readmit_http(serving_artifact):
    store, _ = serving_artifact
    fleet = ReplicaSet.from_store(store, _cfg())
    with _serving(fleet) as url:
        status, body, _ = _request(
            f"{url}/admin/quarantine",
            json.dumps({"replica": 1, "reason": "drill"}).encode(),
        )
        assert status == 200
        out = json.loads(body)
        assert out["status"] == "quarantined" and out["replica"] == 1

        status, body, _ = _request(f"{url}/readyz")
        assert status == 200
        ready = json.loads(body)
        assert ready["router"]["routable"] == [True, False, True]
        assert ready["per_replica"][1]["supervisor"]["state"] == QUARANTINED
        assert ready["per_replica"][1]["supervisor"]["manual"] is True
        assert ready["supervisor"]["states"][1] == QUARANTINED

        before = _routed_counts(fleet)
        payload = json.dumps(_payload()).encode()
        for _ in range(8):
            status, _, _ = _request(f"{url}/predict", payload)
            assert status == 200
        assert _routed_counts(fleet)[1] == before[1]

        # idempotent repeat, then readmit, then readmit again -> typed 422
        status, body, _ = _request(
            f"{url}/admin/quarantine", json.dumps({"replica": 1}).encode()
        )
        assert status == 200 and json.loads(body)["status"] == "quarantined"
        status, body, _ = _request(
            f"{url}/admin/readmit", json.dumps({"replica": 1}).encode()
        )
        assert status == 200 and json.loads(body)["status"] == "readmitted"
        status, body, _ = _request(
            f"{url}/admin/readmit", json.dumps({"replica": 1}).encode()
        )
        assert status == 422 and json.loads(body)["error"] == "invalid_input"
        status, body, _ = _request(
            f"{url}/admin/quarantine", json.dumps({"replica": 99}).encode()
        )
        assert status == 422 and json.loads(body)["error"] == "invalid_input"
    fleet.close()


def test_admin_quarantine_on_single_replica_service_is_typed(serving_artifact):
    store, _ = serving_artifact
    svc = ScorerService.from_store(store, _cfg(replicas=1))
    with _serving(svc) as url:
        status, body, _ = _request(
            f"{url}/admin/quarantine", json.dumps({"replica": 0}).encode()
        )
        assert status == 422
        assert json.loads(body)["error"] == "invalid_input"
    svc.close()


# --- live heal under load (and the supervision-off contrast) ------------------


def _hammer(url: str, n_threads: int, duration_s: float):
    """Concurrent clients against POST /predict; returns (statuses, bodies)
    of every response observed."""
    payload = json.dumps(_payload()).encode()
    results: list[tuple[int, bytes]] = []
    lock = threading.Lock()
    stop_at = time.monotonic() + duration_s

    def client():
        while time.monotonic() < stop_at:
            status, body, _ = _request(f"{url}/predict", payload)
            with lock:
                results.append((status, body))

    threads = [threading.Thread(target=client) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def test_live_heal_under_load_zero_untyped_500s(serving_artifact):
    """The chaos heal demo: kill + error-storm one replica of three while
    concurrent HTTP clients hammer the fleet. Supervision + hedging must
    keep every response typed (zero untyped 500s) and return the fleet to
    all-healthy without operator action."""
    store, _ = serving_artifact
    fleet = ReplicaSet.from_store(
        store,
        _cfg(
            microbatch_enabled=True,
            microbatch_max_wait_ms=1.0,
            supervisor_probe_interval_s=0.15,
        ),
    )
    plan = ChaosPlan(seed=4)
    with _serving(fleet) as url:  # start_async starts the supervisor thread
        assert fleet.supervisor.running
        plan.inject(fleet)
        plan.kill_worker(replica=1, max_events=1)
        plan.error_storm(replica=1, rate=1.0, max_events=12)

        results = _hammer(url, n_threads=6, duration_s=3.0)
        assert len(results) > 50

        for status, body in results:
            if status != 200:
                out = json.loads(body)
                assert "error" in out, f"untyped {status}: {body!r}"
                assert status != 500 or out["error"] == "worker_dead"

        # the fleet self-heals: every replica back to healthy, no operator
        give_up = time.monotonic() + 25.0
        while time.monotonic() < give_up:
            if all(h.state == HEALTHY for h in fleet.replica_health):
                break
            time.sleep(0.2)
        assert all(h.state == HEALTHY for h in fleet.replica_health)
    plan.release()
    fleet.close()


def test_supervision_off_same_scenario_degrades(serving_artifact):
    """The control arm: supervision and hedging disabled, same storm. The
    client-visible failures that the self-healing fleet absorbed now leak —
    proof the new layer is doing the work, not the scenario being easy."""
    store, _ = serving_artifact
    fleet = ReplicaSet.from_store(
        store, _cfg(replicas=2, supervisor_enabled=False, hedge_enabled=False)
    )
    try:
        assert fleet.supervisor is None

        def _boom(payload, deadline=None):
            raise RuntimeError("injected storm")

        fleet.replicas[0].predict_single = _boom
        fleet._rr = 0
        failures = 0
        for _ in range(20):
            try:
                fleet.predict_single(_payload())
            except RuntimeError:
                failures += 1
        assert failures >= 1  # errors reach the client unhedged
        # and nothing heals or evicts: the machine tops out at degraded
        assert fleet.replica_health[0].state in (HEALTHY, DEGRADED)
        assert _hedge_counts(fleet) == {"rescued": 0, "failed": 0}
    finally:
        fleet.close()
