"""Headless render smoke for the Streamlit shell (`ui/app.py`).

streamlit cannot be installed in this offline image (so neither can its
`streamlit.testing.v1.AppTest`); instead a minimal scriptable stand-in is
injected as `sys.modules['streamlit']` and `ui.app.main()` runs for real —
every widget call, both sidebar modes, the live HTTP round-trip to a real
`ScorerService` behind the stdlib server, matplotlib figure rendering, and
the per-row SHAP explorer. What is NOT covered here is streamlit's own
rerun/session-state machinery; `ui/core.py` keeps all data logic out of it
by design (and `test_ui.py` unit-tests that layer directly).
"""

import sys
import types

import numpy as np
import pandas as pd
import pytest

from cobalt_smart_lender_ai_tpu.data import schema


def _fast_cfg():
    """Default serving config minus the all-bucket prewarm — this module
    doesn't exercise cold-bucket tails, and the extra per-bucket compiles
    are pure tier-1 wall time."""
    from cobalt_smart_lender_ai_tpu.config import ServeConfig

    return ServeConfig(prewarm_all_buckets=False)



class _Sidebar:
    def __init__(self, app):
        self.app = app

    def radio(self, label, options):
        self.app.calls.append(("sidebar.radio", label))
        return self.app.script["mode"]


class _Column:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class _FakeStreamlit(types.ModuleType):
    """Records every widget call; returns scripted values for inputs."""

    def __init__(self, script):
        super().__init__("streamlit")
        self.script = script
        self.calls: list[tuple] = []
        self.errors: list[str] = []
        self.figures: list = []
        self.sidebar = _Sidebar(self)
        self.session_state: dict = {}

    # layout / chrome
    def set_page_config(self, **kw):
        self.calls.append(("set_page_config",))

    def title(self, text):
        self.calls.append(("title", text))

    def subheader(self, text):
        self.calls.append(("subheader", text))

    def caption(self, text):
        self.calls.append(("caption", text))

    def columns(self, n):
        return [_Column() for _ in range(n)]

    # inputs (scripted)
    def number_input(self, label, value=0.0, min_value=None, max_value=None,
                     step=None):
        self.calls.append(("number_input", label))
        return self.script.get("numbers", {}).get(label, value)

    def selectbox(self, label, options, index=0):
        self.calls.append(("selectbox", label))
        return self.script.get("selects", {}).get(label, options[index])

    def checkbox(self, label):
        self.calls.append(("checkbox", label))
        return label in self.script.get("checked", ())

    def button(self, label):
        self.calls.append(("button", label))
        return self.script.get("press_buttons", True)

    def file_uploader(self, label, type=None):
        self.calls.append(("file_uploader", label))
        return self.script.get("upload")

    # outputs
    def success(self, text):
        self.calls.append(("success", text))

    def error(self, text):
        self.errors.append(str(text))

    def info(self, text):
        self.errors.append(str(text))  # explorer fallback counts as failure

    def pyplot(self, fig):
        self.figures.append(fig)

    def dataframe(self, df):
        self.calls.append(("dataframe", len(df)))

    def download_button(self, label, data, filename):
        self.calls.append(("download_button", filename))


class _Upload:
    def __init__(self, name, data):
        self.name = name
        self._data = data

    def getvalue(self):
        return self._data


@pytest.fixture(scope="module")
def live_server(serving_artifact):
    from cobalt_smart_lender_ai_tpu.serve.http_asyncio import make_async_server
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    store, X = serving_artifact
    server = make_async_server(
        ScorerService.from_store(store, _fast_cfg()), "127.0.0.1", 0
    )
    yield f"http://127.0.0.1:{server.port}", X
    server.close()


def _run_app(monkeypatch, url, script):
    st = _FakeStreamlit(script)
    monkeypatch.setitem(sys.modules, "streamlit", st)
    monkeypatch.setenv("API_URL", url)
    import matplotlib

    matplotlib.use("Agg")
    from cobalt_smart_lender_ai_tpu.ui import app

    app.main()
    return st


def test_single_prediction_mode_renders(monkeypatch, live_server):
    url, _ = live_server
    st = _run_app(monkeypatch, url, {"mode": "Single Prediction"})
    assert st.errors == []
    # prediction succeeded and a waterfall figure was rendered
    assert any(c[0] == "success" for c in st.calls)
    assert len(st.figures) == 1
    labels = [c[1] for c in st.calls if c[0] == "number_input"]
    assert len(labels) == 11  # 12 numeric inputs minus the term selectbox


def _complete_rows(X, k: int) -> np.ndarray:
    """First ``k`` NaN-free rows: the explorer rebuilds a /predict JSON body,
    whose contract (all 20 fields required and typed, like the reference's
    pydantic schema) cannot express a missing value — the full-schema
    synthetic frame now carries block-missing serving features by design."""
    Xn = np.asarray(X, dtype=np.float64)
    full = ~np.isnan(Xn).any(axis=1)
    return Xn[np.flatnonzero(full)[:k]]


def test_bulk_mode_renders_table_importance_and_row_explorer(
    monkeypatch, live_server
):
    url, X = live_server
    df = pd.DataFrame(
        _complete_rows(X, 6),
        columns=list(schema.SERVING_FEATURES),
    )
    script = {
        "mode": "Bulk Prediction + SHAP",
        "upload": _Upload("batch.csv", df.to_csv(index=False).encode()),
        "numbers": {"Row to explain": 3},
    }
    st = _run_app(monkeypatch, url, script)
    assert st.errors == []
    assert ("dataframe", 6) in st.calls
    assert any(c[0] == "download_button" for c in st.calls)
    # importance barh + row-3 waterfall
    assert len(st.figures) == 2
    assert any(
        c[0] == "caption" and "Row 3" in c[1] for c in st.calls
    ), st.calls

    # Streamlit rerun-on-interaction: the button reads False on the next run,
    # but results persist in session_state so changing the explorer row still
    # renders — the regression the session_state refactor exists to prevent.
    from cobalt_smart_lender_ai_tpu.ui import app

    st.script["press_buttons"] = False
    st.script["numbers"] = {"Row to explain": 5}
    app.main()
    assert st.errors == []
    assert any(
        c[0] == "caption" and "Row 5" in c[1] for c in st.calls
    ), "explorer did not survive the rerun"


def test_bulk_results_invalidate_on_new_upload_and_importance_is_cached(
    monkeypatch, live_server
):
    """A replaced upload must drop the previous file's cached results, and
    explorer reruns must reuse the cached importance response instead of
    re-posting every record to /feature_importance_bulk per interaction."""
    from cobalt_smart_lender_ai_tpu.ui import app, core

    url, X = live_server
    cols = list(schema.SERVING_FEATURES)
    rows = _complete_rows(X, 10)
    df_a = pd.DataFrame(rows[:4], columns=cols)
    df_b = pd.DataFrame(rows[4:10], columns=cols)

    counts = {"importance": 0}
    orig = core.ApiClient.feature_importance_bulk

    def counting(self, records):
        counts["importance"] += 1
        return orig(self, records)

    monkeypatch.setattr(core.ApiClient, "feature_importance_bulk", counting)

    script = {
        "mode": "Bulk Prediction + SHAP",
        "upload": _Upload("a.csv", df_a.to_csv(index=False).encode()),
    }
    st = _run_app(monkeypatch, url, script)
    assert st.errors == []
    assert ("dataframe", 4) in st.calls
    assert counts["importance"] == 1

    # Explorer interaction rerun: cached results render, importance NOT refetched.
    st.script["press_buttons"] = False
    st.script["numbers"] = {"Row to explain": 2}
    app.main()
    assert st.errors == []
    assert counts["importance"] == 1, "importance re-posted on a rerun"

    # New upload without pressing Run: the old file's results must vanish.
    st.script["upload"] = _Upload("b.csv", df_b.to_csv(index=False).encode())
    n_tables = sum(1 for c in st.calls if c[0] == "dataframe")
    app.main()
    assert st.errors == []
    assert sum(1 for c in st.calls if c[0] == "dataframe") == n_tables, (
        "stale results rendered for a new upload"
    )

    # Running on the new upload scores it fresh.
    st.script["press_buttons"] = True
    app.main()
    assert st.errors == []
    assert ("dataframe", 6) in st.calls
    assert counts["importance"] == 2
