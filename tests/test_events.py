"""Fleet event journal + causal incident forensics (`telemetry/events.py`).

Pins the PR's guarantees:

- `EventJournal` is a bounded fake-clock ring: wraps evict oldest, drops
  are counted only when the victim never shipped, unknown component/kind
  pairs raise, and filters/`chain()` behave;
- causal links survive a real supervisor heal: the rebuild/swap/readmit
  events chain back to the quarantine that triggered them, so the
  kill -> heal story is walkable from the journal alone;
- ``GET /events`` works on the asyncio adapter (filters, typed 422s from
  the shared validators) and on the stubbed FastAPI adapter;
- durable shipping round-trips md5-pinned segments through
  `FaultInjectingStore`: a failed put re-ships the same events, a torn
  segment is skipped by `load_events`, never a crash;
- journal events export as valid Perfetto instant events through
  `chrome_trace`;
- `tools/incident_report.py` renders the postmortem and its
  ``--require-cause`` gate exits 0 / 4 / 2 correctly.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

# fixture re-export: the stubbed-fastapi harness (in-memory FastAPI/pydantic
# doubles) lives with the adapter contract tests; /events only needs the
# fixture itself
from test_serve_fastapi_stub import fastapi_stubbed  # noqa: F401

from cobalt_smart_lender_ai_tpu.config import ServeConfig
from cobalt_smart_lender_ai_tpu.io import ObjectStore
from cobalt_smart_lender_ai_tpu.reliability.faults import (
    FaultInjectingStore,
    FaultSpec,
    InjectedFault,
)
from cobalt_smart_lender_ai_tpu.telemetry.events import (
    EventJournal,
    current_event_id,
    event_context,
    load_events,
    merge_events,
)


class _Clock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _journal(capacity: int = 8, **kw) -> tuple[EventJournal, _Clock]:
    clock = _Clock()
    j = EventJournal(capacity=capacity, clock=clock, mono=clock, **kw)
    return j, clock


# --- ring discipline ----------------------------------------------------------


def test_ring_bounds_and_drop_accounting():
    j, clock = _journal(capacity=3)
    ids = []
    for n in range(5):
        ids.append(j.emit("chaos", "inject", payload={"n": n}))
        clock.advance(1.0)
    stats = j.stats()
    assert stats["depth"] == 3 and stats["capacity"] == 3
    assert stats["emitted"] == 5
    # two wraps, nothing ever shipped -> two dropped events
    assert stats["dropped"] == 2
    assert [e["payload"]["n"] for e in j.events()] == [2, 3, 4]
    # ids are strictly increasing (process-wide mint)
    assert ids == sorted(ids) and len(set(ids)) == 5


def test_emit_rejects_unknown_taxonomy():
    j, _ = _journal()
    with pytest.raises(ValueError):
        j.emit("supervisor", "no_such_kind")
    with pytest.raises(ValueError):
        j.emit("no_such_component", "transition")


def test_filters_chain_and_context():
    j, clock = _journal(capacity=16)
    root = j.emit("supervisor", "probe_failure", replica=1)
    clock.advance(5.0)
    with event_context(root):
        assert current_event_id() == root
        mid = j.emit(
            "supervisor",
            "transition",
            replica=1,
            payload={"to": "quarantined"},
        )
    assert current_event_id() is None
    leaf = j.emit("supervisor", "rebuild", replica=1, cause_id=mid)
    j.emit("autoscaler", "resize", payload={"to": 2})

    # ambient event_context stamped the cause_id
    assert j.events(kind="transition")[0]["cause_id"] == root
    # component/kind/since/limit filters
    assert {e["component"] for e in j.events(component="autoscaler")} == {
        "autoscaler"
    }
    assert [e["event_id"] for e in j.events(since=clock.t)] == [
        mid,
        leaf,
        leaf + 1,
    ]
    assert len(j.events(limit=2)) == 2
    # chain walks leaf -> root, returned root-first
    assert [e["event_id"] for e in j.chain(leaf)] == [root, mid, leaf]


def test_merge_events_totals_order():
    a, _ = _journal()
    b, _ = _journal()
    ids = [
        a.emit("chaos", "inject"),
        b.emit("autoscaler", "resize", payload={"to": 2}),
        a.emit("chaos", "inject"),
    ]
    merged = merge_events([a, b])
    assert [e["event_id"] for e in merged] == sorted(ids)
    assert [e["event_id"] for e in merge_events([a, b], limit=1)] == [ids[-1]]


def test_metrics_family_and_readyz_block():
    from cobalt_smart_lender_ai_tpu.telemetry import (
        MetricsRegistry,
        parse_exposition,
    )

    reg = MetricsRegistry()
    j, _ = _journal(registry=reg)
    j.emit("chaos", "inject")
    j.emit("chaos", "inject")
    j.emit("autoscaler", "retune")
    text = reg.render()
    parse_exposition(text)
    assert 'cobalt_events_total{component="chaos",kind="inject"} 2' in text
    assert "cobalt_events_ring_depth 3" in text


# --- causal integrity under a real heal ---------------------------------------


def _fleet_cfg(**kw) -> ServeConfig:
    base = dict(
        replicas=2,
        microbatch_enabled=True,
        precompile_batch_buckets=(),
        prewarm_all_buckets=False,
        score_cache_size=0,
        supervisor_probe_deadline_s=0.3,
        supervisor_probe_failures=1,
        supervisor_drain_timeout_s=1.0,
        replica_close_timeout_s=2.0,
    )
    base.update(kw)
    return ServeConfig(**base)


@pytest.mark.slow
def test_heal_chain_links_rebuild_to_quarantine(serving_artifact):
    """After a chaos kill + supervisor heal, the journal alone tells the
    story: quarantine -> restarting -> rebuild -> swap -> healthy, every
    link via cause_id."""
    from cobalt_smart_lender_ai_tpu.reliability import ChaosPlan
    from cobalt_smart_lender_ai_tpu.serve.replicas import ReplicaSet
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService
    from cobalt_smart_lender_ai_tpu.serve.supervisor import HEALTHY

    store, _ = serving_artifact
    cfg = _fleet_cfg()
    fleet = ReplicaSet(
        [ScorerService.from_store(store, cfg) for _ in range(2)], cfg
    )
    try:
        plan = ChaosPlan(seed=3, registry=fleet.registry)
        plan.add_latency(replica=1, delay_s=0.001, max_events=1)
        plan.inject(fleet)
        plan._on_dispatch(1)  # one fault through the chaos checkpoint
        fleet.supervisor.quarantine(1, "test chaos", manual=False)
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            fleet.supervisor.tick()
            if fleet.replica_health[1].state == HEALTHY:
                break
            time.sleep(0.05)
        assert fleet.replica_health[1].state == HEALTHY
        plan.release()

        events = fleet.events(component="supervisor")
        by_kind = {}
        for e in events:
            if e["replica"] == 1:
                by_kind.setdefault(
                    (e["kind"], (e["payload"] or {}).get("to")), e
                )
        quarantine = by_kind[("transition", "quarantined")]
        rebuild = by_kind[("rebuild", None)]
        swap = by_kind[("swap", None)]
        healthy = by_kind[("transition", "healthy")]
        # the acceptance chain: rebuild links to its quarantine, swap to
        # the rebuild, readmission to the swap
        assert rebuild["cause_id"] == quarantine["event_id"]
        assert swap["cause_id"] == rebuild["event_id"]
        assert healthy["cause_id"] == swap["event_id"]
        # chaos hang landed in the merged journal too
        assert any(
            e["component"] == "chaos" for e in fleet.events()
        )
        # the gated kind always carries a cause snapshot
        assert quarantine["cause"]
    finally:
        fleet.close()


# --- /events over HTTP --------------------------------------------------------


def _get(url: str):
    try:
        with urllib.request.urlopen(url) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


@pytest.mark.slow
def test_events_route_asyncio_filters_and_422(serving_artifact):
    from cobalt_smart_lender_ai_tpu.serve.http_asyncio import (
        make_async_server,
    )
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    store, _ = serving_artifact
    svc = ScorerService.from_store(
        store, ServeConfig(prewarm_all_buckets=False)
    )
    server = make_async_server(svc)
    url = f"http://127.0.0.1:{server.port}"
    try:
        svc.journal.emit("reload", "publish", model="m1")
        svc.journal.emit("breaker", "open")
        status, doc = _get(url + "/events")
        assert status == 200
        assert doc["count"] == len(doc["events"]) >= 2
        assert doc["stats"]["depth"] >= 2
        status, doc = _get(url + "/events?component=breaker")
        assert status == 200
        assert {e["component"] for e in doc["events"]} == {"breaker"}
        status, doc = _get(url + "/events?component=reload&kind=publish")
        assert doc["events"][0]["model"] == "m1"
        status, doc = _get(url + "/events?limit=1")
        assert doc["count"] == 1

        # typed 422s from the shared validators
        for bad in (
            "/events?component=nope",
            "/events?kind=nope",
            "/events?component=breaker&kind=publish",
            "/events?since=abc",
            "/events?limit=-2",
        ):
            status, doc = _get(url + bad)
            assert status == 422, bad
            assert doc["error"] == "invalid_input", bad
    finally:
        server.close()
        svc.close()


def test_events_route_fastapi_stub(fastapi_stubbed, serving_artifact):
    from cobalt_smart_lender_ai_tpu.serve.http_fastapi import create_app
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    store, _ = serving_artifact
    svc = ScorerService.from_store(
        store, ServeConfig(prewarm_all_buckets=False)
    )
    try:
        app = create_app(service=svc)
        svc.journal.emit("canary", "promote", model="v2")
        doc = app.get_routes["/events"]()
        assert doc["count"] >= 1
        assert any(e["component"] == "canary" for e in doc["events"])
        doc = app.get_routes["/events"](component="canary", kind="promote")
        assert doc["events"][-1]["model"] == "v2"
        with pytest.raises(fastapi_stubbed.HTTPException) as ei:
            app.get_routes["/events"](component="nope")
        assert ei.value.status_code == 422
    finally:
        svc.close()


def test_readyz_carries_events_block(fastapi_stubbed, serving_artifact):
    from cobalt_smart_lender_ai_tpu.serve.http_fastapi import create_app
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    store, _ = serving_artifact
    svc = ScorerService.from_store(
        store, ServeConfig(prewarm_all_buckets=False)
    )
    try:
        app = create_app(service=svc)
        svc.journal.emit("reload", "publish")
        ready = app.get_routes["/readyz"]()
        assert ready["events"]["depth"] >= 1
        assert ready["events"]["shipping"]["enabled"] is False
    finally:
        svc.close()


# --- durable segments ---------------------------------------------------------


def test_durable_ship_and_load_round_trip(tmp_path):
    store = ObjectStore(str(tmp_path / "lake"))
    j, clock = _journal(capacity=4, store=store, ship_interval_s=0)
    ids = [j.emit("chaos", "inject", payload={"n": n}) for n in range(3)]
    key = j.ship()
    assert key and store.verify_pointer(key)
    assert j.ship() is None  # nothing new
    # wrap past capacity: shipped events evict without counting as drops
    ids += [j.emit("chaos", "inject", payload={"n": n}) for n in range(3, 8)]
    assert j.stats()["dropped"] == 1  # only the one unshipped victim
    j.ship()
    loaded = load_events(store)
    assert [e["event_id"] for e in loaded] == sorted(
        set(e for e in ids) - {ids[3]}
    )
    assert j.stats()["shipping"]["segments"] == 2


def test_ship_failure_reships_same_events(tmp_path):
    inner = ObjectStore(str(tmp_path / "lake"))
    flaky = FaultInjectingStore(
        inner, seed=0, faults={"put": FaultSpec(fail_after=0, max_faults=1)}
    )
    j, _ = _journal(capacity=8, store=flaky, ship_interval_s=0)
    ids = [j.emit("autoscaler", "retune", payload={"n": n}) for n in range(2)]
    with pytest.raises(InjectedFault):
        j.ship()
    # high-water mark did not advance past the failed write
    assert j.stats()["shipping"]["shipped_until_id"] == 0
    key = j.ship()  # budget spent: this one lands
    assert key is not None
    assert [e["event_id"] for e in load_events(flaky)] == ids


def test_torn_segment_skipped_by_loader(tmp_path):
    store = ObjectStore(str(tmp_path / "lake"))
    j, _ = _journal(capacity=8, store=store, ship_interval_s=0)
    j.emit("breaker", "open")
    torn = j.ship()
    j.emit("breaker", "close")
    good = j.ship()
    # tear the first segment after its pointer was pinned
    store.put_bytes(torn, b'{"schema": 1, "seq": 1, "events": [')
    loaded = load_events(store)
    assert [e["kind"] for e in loaded] == ["close"]
    assert good != torn


def test_stop_does_final_ship(tmp_path):
    store = ObjectStore(str(tmp_path / "lake"))
    j, _ = _journal(capacity=8, store=store, ship_interval_s=3600.0)
    j.start()
    j.emit("reload", "rollback", cause={"error": "boom"})
    j.stop()
    assert [e["kind"] for e in load_events(store)] == ["rollback"]


# --- Perfetto export ----------------------------------------------------------


def test_chrome_trace_journal_instant_events():
    from cobalt_smart_lender_ai_tpu.telemetry.traceexport import chrome_trace

    j, clock = _journal(capacity=8)
    eid = j.emit("autoscaler", "brownout", payload={"level": 2})
    j.emit("supervisor", "swap", replica=1, cause_id=eid)
    doc = chrome_trace(journal=j)
    instants = [e for e in doc["traceEvents"] if e.get("cat") == "event"]
    assert len(instants) == 2
    for ev in instants:
        assert ev["ph"] == "i" and ev["s"] == "p"
        assert isinstance(ev["ts"], float) and ev["ts"] >= 0
        assert "event_id" in ev["args"]
    names = {e["name"] for e in instants}
    assert names == {"autoscaler.brownout", "supervisor.swap"}
    assert instants[1]["args"]["cause_id"] == eid
    assert doc["otherData"]["journal_event_count"] == 2
    json.dumps(doc)  # must remain JSON-serializable


# --- incident_report tool -----------------------------------------------------

_TOOL = str(
    Path(__file__).resolve().parent.parent / "tools" / "incident_report.py"
)


def _run_tool(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, _TOOL, *args], capture_output=True, text=True
    )


def _bench_doc(journal: EventJournal) -> dict:
    return {
        "bench": "serve_chaos",
        "load": {"requests": 10, "errors": 0, "untyped_errors": 0,
                 "p99_ms": 4.2},
        "events": {"journal": journal.events(), "stats": journal.stats()},
    }


def test_incident_report_renders_chain_and_passes_gate(tmp_path):
    j, clock = _journal(capacity=32)
    kill = j.emit("chaos", "inject", replica=1, payload={"fault": "kill"},
                  cause={"plan": "chaos"})
    clock.advance(0.5)
    pf = j.emit("supervisor", "probe_failure", replica=1,
                payload={"consecutive": 1})
    q = j.emit("supervisor", "transition", replica=1,
               payload={"from": "healthy", "to": "quarantined"},
               cause={"reason": "probe"}, cause_id=pf)
    clock.advance(1.0)
    rb = j.emit("supervisor", "rebuild", replica=1,
                payload={"outcome": "ok"}, cause_id=q)
    sw = j.emit("supervisor", "swap", replica=1, cause_id=rb)
    j.emit("supervisor", "transition", replica=1,
           payload={"from": "restarting", "to": "healthy"}, cause_id=sw)
    bench = tmp_path / "bench.json"
    bench.write_text(json.dumps(_bench_doc(j)))
    out = tmp_path / "incident.md"
    proc = _run_tool("--bench", str(bench), "--require-cause",
                     "--out", str(out))
    assert proc.returncode == 0, proc.stderr
    report = out.read_text()
    assert "time to healthy: **1.000s**" in report
    assert "suspected trigger: `chaos.inject`" in report
    assert "orphans (no cause, no cause_id): 0" in report
    # --window keeps only the heal tail
    proc = _run_tool("--bench", str(bench), "--window", "0.6:")
    assert proc.returncode == 0
    assert "chaos.inject" not in proc.stdout.split("## Incidents")[1]


def test_incident_report_require_cause_orphan_exits_4(tmp_path):
    j, _ = _journal(capacity=8)
    j.emit("autoscaler", "resize", payload={"direction": "up", "to": 2})
    bench = tmp_path / "bench.json"
    bench.write_text(json.dumps(_bench_doc(j)))
    proc = _run_tool("--bench", str(bench), "--require-cause")
    assert proc.returncode == 4
    assert "orphan" in proc.stderr
    # without the gate the same input renders fine
    assert _run_tool("--bench", str(bench)).returncode == 0


def test_incident_report_unreadable_input_exits_2(tmp_path):
    assert _run_tool("--bench", str(tmp_path / "nope.json")).returncode == 2
    empty = tmp_path / "empty.json"
    empty.write_text("{}")
    assert _run_tool("--bench", str(empty)).returncode == 2


# --- log lines carry event_id -------------------------------------------------


def test_structured_logs_stamp_event_id(caplog):
    import logging

    from cobalt_smart_lender_ai_tpu.telemetry.logging import get_logger

    log = get_logger("test.events")
    with caplog.at_level(logging.INFO, logger="cobalt.test.events"):
        with event_context(77):
            log.info("inside_context")
        log.info("outside_context")
    inside = json.loads(caplog.records[0].getMessage())
    outside = json.loads(caplog.records[1].getMessage())
    assert inside["event"] == "inside_context"
    assert inside["event_id"] == 77
    assert "event_id" not in outside
