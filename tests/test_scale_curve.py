"""Gate the committed oracle scale-curve artifact (PARITY_SCALE.json).

The artifact's claim discipline: measured points must be real oracle-side
parity runs committed next to it, the per-leg power-law fits must reproduce
their own measured points, and the target-scale numbers must be labelled as
extrapolations and arithmetically consistent with the fit.
"""

import json
import math
import pathlib

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
SCALE = ROOT / "PARITY_SCALE.json"


@pytest.fixture(scope="module")
def doc():
    if not SCALE.exists():
        pytest.skip("PARITY_SCALE.json not committed")
    return json.loads(SCALE.read_text())


def test_measured_points_come_from_committed_oracle_runs(doc):
    committed = {}
    for p in ROOT.glob("PARITY_oracle_*.json"):
        run = json.loads(p.read_text())
        assert run["side"] == "oracle"
        committed[run["n_rows"]] = run
    assert len(committed) >= 2
    for leg, curve in doc["curves"].items():
        for rows, wall in curve["measured_points"].items():
            run = committed[int(rows)]
            assert run["seconds"][leg] == wall, (leg, rows)


def test_fit_is_consistent_and_extrapolation_labelled(doc):
    assert "EXTRAPOLATED" in doc["note"].upper() or "extrapolat" in doc["note"]
    for leg, curve in doc["curves"].items():
        walls = list(curve["measured_points"].values())
        if "band_wall_s" in curve:
            # flat-band mode: the target wall is the measured maximum — the
            # conservative-against-us choice — and the rejected power fit
            # is recorded with its reason.
            assert curve["band_wall_s"] == [min(walls), max(walls)]
            assert curve["extrapolated_wall_s_at_target"] == max(walls)
            rej = curve["power_fit_rejected"]
            assert rej["p"] < 0.05 or rej["max_relative_residual"] > 0.25
            continue
        c, p = curve["c"], curve["p"]
        # the fit reproduces its own measured points
        assert curve["max_relative_residual"] < 0.25, leg
        for rows, wall in curve["measured_points"].items():
            fitted = c * int(rows) ** p
            assert abs(fitted - wall) / wall <= curve["max_relative_residual"] + 1e-6
        # the target number is the fit evaluated at target_rows
        want = c * doc["target_rows"] ** p
        assert math.isclose(
            curve["extrapolated_wall_s_at_target"], want, rel_tol=0.01
        ), leg


def test_speedups_match_ours_measured(doc):
    ours = doc.get("ours_measured_at_target")
    if not ours:
        pytest.skip("no ours-side comparison embedded")
    for leg, ratio in doc["speedup_at_target"].items():
        oracle = doc["curves"][leg]["extrapolated_wall_s_at_target"]
        assert math.isclose(ratio, oracle / ours["seconds"][leg], rel_tol=0.02)
