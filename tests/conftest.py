"""Test harness: force an 8-device virtual CPU backend before JAX is imported.

This is the standard JAX fake-backend trick (SURVEY §4c): all sharding /
collective / fan-out code paths run in CI on a single CPU host exactly as they
would over 8 TPU chips, so the mesh-parallel code is exercised on every test
run without pod hardware.
"""

import os

# Force, don't setdefault: the environment may pre-set JAX_PLATFORMS to a real
# accelerator; tests must run on the 8-device virtual CPU backend regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# A sitecustomize may have imported jax at interpreter startup (before this
# file), freezing jax_platforms from the outer env; override via config. The
# XLA flag above is still read lazily at first backend init, so the CPU
# backend comes up with 8 virtual devices.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def raw_frame():
    from cobalt_smart_lender_ai_tpu.data.synthetic import synthetic_lendingclub_frame

    return synthetic_lendingclub_frame(n_rows=4000, seed=7)


@pytest.fixture(scope="session")
def engineered(raw_frame):
    """(tree_ff, nn_ff, plan) built once per session from the synthetic raw frame."""
    from cobalt_smart_lender_ai_tpu.data.clean import clean_raw_frame
    from cobalt_smart_lender_ai_tpu.data.features import (
        engineer_features,
        prepare_cleaned_frame,
    )

    cleaned, _ = clean_raw_frame(raw_frame)
    prepared = prepare_cleaned_frame(cleaned)
    return engineer_features(prepared)


@pytest.fixture(scope="session")
def serving_artifact(tmp_path_factory, engineered):
    """Train a model on exactly the 20-feature serving contract and persist
    it, as `model_tree_train_test.py:215-230` does. Session-scoped: shared by
    the serving, smoke, and fastapi-stub test modules."""
    from cobalt_smart_lender_ai_tpu.data import schema
    from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, ObjectStore
    from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTClassifier

    tree_ff, _, _ = engineered
    missing = [n for n in schema.SERVING_FEATURES if n not in tree_ff.feature_names]
    assert not missing, f"synthetic frame lacks serving features: {missing}"
    ff = tree_ff.select(schema.SERVING_FEATURES)
    model = GBDTClassifier(n_estimators=25, max_depth=3, n_bins=64)
    model.fit(np.asarray(ff.X), np.asarray(ff.y))
    store = ObjectStore(str(tmp_path_factory.mktemp("serve") / "lake"))
    art = GBDTArtifact(
        forest=model.forest,
        bin_spec=model.bin_spec,
        feature_names=tuple(schema.SERVING_FEATURES),
    )
    art.save(store, "models/gbdt/model_tree")
    # np.array, not np.asarray: asarray zero-copies the device buffer and the
    # result is read-only — consumers (bulk-CSV test) mutate their frames.
    return store, np.array(ff.X)


@pytest.fixture(scope="session")
def train_test(engineered):
    """Leakage-dropped tree matrix split into train/test numpy arrays."""
    from cobalt_smart_lender_ai_tpu.data.features import drop_training_leakage
    from cobalt_smart_lender_ai_tpu.data.split import train_test_split_hashed

    tree_ff, _, _ = engineered
    ff = drop_training_leakage(tree_ff)
    X_train, X_test, y_train, y_test = train_test_split_hashed(
        ff.X, ff.y, test_fraction=0.2, seed=22
    )
    return (
        np.asarray(X_train), np.asarray(X_test),
        np.asarray(y_train), np.asarray(y_test),
        ff.feature_names,
    )
