"""Mesh-sharded bulk scoring: the `parallel.partitioner` abstraction and its
service integration. The load-bearing assert is bit-exact parity — margins and
SHAP from a forced multi-device ``dp`` mesh must equal the single-device
program's output *bitwise* (`np.array_equal`, no tolerance), because the
scoring contractions are per-row and both paths funnel through the one numpy
sigmoid. Alongside parity: the padding protocol (N not divisible by the shard
count, N smaller than the mesh), shard-count resolution, partition-rule
matching, and the between-dispatch deadline checkpoint.

conftest.py forces 8 virtual host devices (``xla_force_host_platform_device_
count``), so the 4-way mesh here exists on any CI box."""

import dataclasses

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from cobalt_smart_lender_ai_tpu.config import ServeConfig
from cobalt_smart_lender_ai_tpu.io import GBDTArtifact
from cobalt_smart_lender_ai_tpu.parallel.partitioner import (
    DEFAULT_RULES,
    MeshPartitioner,
    SingleDevicePartitioner,
    make_partitioner,
    match_partition_rule,
)
from cobalt_smart_lender_ai_tpu.reliability import Deadline, DeadlineExceeded
from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

SHARDS = 4


def _cfg(**kw) -> ServeConfig:
    """Bulk-only service: no micro-batcher, no bucket prewarm — this module
    exercises the bulk partitioner path, not the single-row hot path."""
    kw.setdefault("max_batch_rows", 64)  # small chunks: multi-chunk at N=1000
    return ServeConfig(
        microbatch_enabled=False,
        precompile_batch_buckets=(),
        prewarm_all_buckets=False,
        score_cache_size=0,
        **kw,
    )


@pytest.fixture(scope="module")
def single_svc(serving_artifact):
    store, _ = serving_artifact
    return ScorerService.from_store(store, _cfg(bulk_shards=1))


@pytest.fixture(scope="module")
def mesh_svc(serving_artifact):
    store, _ = serving_artifact
    return ScorerService.from_store(store, _cfg(bulk_shards=SHARDS))


# --- bit-exact parity ---------------------------------------------------------


@pytest.mark.parametrize("n", [1, 3, 4, 37, 256, 1000])
def test_mesh_margins_bit_identical_to_single(single_svc, mesh_svc, serving_artifact, n):
    """The headline guarantee: sharding the row axis over a 4-way dp mesh
    changes WHERE rows score, never WHAT they score — probabilities are
    bitwise equal for row counts below, at, and far above the mesh size,
    divisible and not."""
    _, X = serving_artifact
    assert mesh_svc._model.bulk_part.n_shards == SHARDS
    p1 = single_svc.predict_proba(X[:n])
    p4 = mesh_svc.predict_proba(X[:n])
    assert p1.shape == (n,)
    assert np.array_equal(p1, p4), (
        f"mesh/single divergence at n={n}: "
        f"max |diff| {np.max(np.abs(p1 - p4))}"
    )


@pytest.mark.parametrize("n", [1, 5, 64])
def test_mesh_shap_bit_identical_to_single(single_svc, mesh_svc, serving_artifact, n):
    _, X = serving_artifact
    phis1, base1 = single_svc.shap_bulk(X[:n])
    phis4, base4 = mesh_svc.shap_bulk(X[:n])
    assert phis1.shape == (n, single_svc._model.n_features)
    assert np.array_equal(phis1, phis4)
    assert base1 == base4


def test_partitioner_level_parity(serving_artifact):
    """Same assert one layer down, against the raw compiled programs — no
    service chunking in the way. 8 rows over a 4-way mesh is exactly 2 rows
    per shard."""
    store, X = serving_artifact
    art = GBDTArtifact.load(store, "models/gbdt/model_tree")
    nf = len(art.feature_names)
    X8 = np.ascontiguousarray(X[:8, :nf], dtype=np.float32)
    single = SingleDevicePartitioner()
    mesh = MeshPartitioner(jax.devices()[:SHARDS])
    m1 = np.asarray(single.compile_margin(art.forest, nf, 8)(X8))
    m4 = np.asarray(mesh.compile_margin(art.forest, nf, 8)(X8))
    assert np.array_equal(m1, m4)
    phis1, base1 = single.compile_shap(art.forest, nf, 8)(X8)
    phis4, base4 = mesh.compile_shap(art.forest, nf, 8)(X8)
    assert np.array_equal(np.asarray(phis1), np.asarray(phis4))
    assert float(base1) == float(base4)


# --- padding protocol ---------------------------------------------------------


def test_chunker_pads_to_shard_multiple(mesh_svc, serving_artifact):
    """N=37 does not divide 4: the chunker must hand the compiled program
    ceil(37/4)=10 -> bucket 16 rows per shard = 64 padded rows, and report
    n=37 so the caller slices the padding back off."""
    _, X = serving_artifact
    model = mesh_svc._model
    chunks = list(model._bulk_chunks(np.asarray(X[:37], np.float32), None))
    assert len(chunks) == 1
    start, n, bucket, padded = chunks[0]
    assert (start, n) == (0, 37)
    assert bucket == 16  # power-of-two cover of the PER-SHARD row count
    assert padded.shape[0] == bucket * SHARDS
    assert np.all(padded[37:] == 0.0)  # tail is inert padding


@pytest.mark.parametrize("n", [1, 2, 3])
def test_fewer_rows_than_devices(mesh_svc, serving_artifact, n):
    """N < mesh size still works: one row per shard (bucket 1), real rows in
    the leading shards, padding in the rest."""
    _, X = serving_artifact
    model = mesh_svc._model
    [(start, got_n, bucket, padded)] = model._bulk_chunks(
        np.asarray(X[:n], np.float32), None
    )
    assert (start, got_n, bucket) == (0, n, 1)
    assert padded.shape[0] == SHARDS
    # and the scores for those rows are real, not padding artifacts
    assert np.array_equal(
        mesh_svc.predict_proba(X[:n]),
        mesh_svc.predict_proba(X[:8])[:n],
    )


def test_mesh_rejects_undivisible_rows():
    """The compile-time guard behind the padding contract: handing a mesh
    program a row count that does not divide the shard count is a caller bug,
    not something to mask."""
    mesh = MeshPartitioner(jax.devices()[:SHARDS])
    with pytest.raises(ValueError, match="pad to shard_multiple"):
        mesh.compile_margin(None, 20, 10)
    assert mesh.shard_multiple == SHARDS


# --- deadline checkpoints between dispatches ----------------------------------


class _ManualClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self) -> float:
        return self.now


def test_deadline_checked_between_sharded_dispatches(mesh_svc, serving_artifact):
    """The deadline is the cooperative cancellation point between mesh
    dispatches: burn the budget during dispatch 2 of 3 (via the on_dispatch
    hook) and the third chunk must 504 before launching, naming the row it
    stopped at."""
    _, X = serving_artifact
    clk = _ManualClock()
    dl = Deadline(1.0, clock=clk)
    step = mesh_svc.config.max_batch_rows * SHARDS  # 256 rows per dispatch

    def burn(rows, seconds):
        clk.now += 0.6  # two dispatches overrun the 1.0s budget

    with pytest.raises(DeadlineExceeded) as ei:
        mesh_svc._model.predict_margin_bulk(
            np.asarray(X[: step * 2 + 100], np.float32), dl, burn
        )
    assert f"bulk scoring, row {step * 2}/" in str(ei.value)


# --- shard-count resolution and rules -----------------------------------------


def test_make_partitioner_resolution():
    n_dev = len(jax.devices())
    assert isinstance(make_partitioner(0), SingleDevicePartitioner)
    assert isinstance(make_partitioner(1), SingleDevicePartitioner)
    every = make_partitioner(-1)
    assert isinstance(every, MeshPartitioner)
    assert every.n_shards == n_dev
    assert make_partitioner(3).n_shards == 3
    # over-asking clamps to the host, never crashes
    assert make_partitioner(10 * n_dev).n_shards == n_dev


def test_match_partition_rule():
    assert match_partition_rule(DEFAULT_RULES, "rows", "dp") == P("dp", None)
    assert match_partition_rule(DEFAULT_RULES, "X", "dp") == P("dp", None)
    assert match_partition_rule(DEFAULT_RULES, "forest", "dp") == P()
    with pytest.raises(ValueError, match="no partition rule"):
        match_partition_rule((), "rows", "dp")


def test_describe_shapes(mesh_svc, single_svc):
    d4 = mesh_svc._model.bulk_part.describe()
    assert d4["shards"] == SHARDS
    assert d4["mesh"] == {"dp": SHARDS}
    assert len(d4["devices"]) == SHARDS
    d1 = single_svc._model.bulk_part.describe()
    assert d1 == {"shards": 1, "mesh": None, "devices": None}


def test_readyz_reports_mesh_shape(mesh_svc, serving_artifact):
    """/readyz carries the bulk block the CI bulk-smoke job asserts on:
    mesh shape plus the sharded buckets compiled so far."""
    _, X = serving_artifact
    mesh_svc.predict_proba(X[:8])  # ensure at least one compiled bucket
    ok, payload = mesh_svc.ready()
    assert ok
    bulk = payload["bulk"]
    assert bulk["shards"] == SHARDS
    assert bulk["mesh"] == {"dp": SHARDS}
    assert bulk["compiled_buckets"], "no sharded bucket recorded after a dispatch"
