"""TreeSHAP tests: additivity, brute-force Shapley exactness, NaN routing,
and the TreeExplainer facade."""

import itertools
import math

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.datasets import make_classification

from cobalt_smart_lender_ai_tpu.explain import TreeExplainer
from cobalt_smart_lender_ai_tpu.explain.treeshap import shap_values
from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTClassifier


@pytest.fixture(scope="module")
def small_model():
    X, y = make_classification(
        n_samples=800, n_features=6, n_informative=4, random_state=0
    )
    X = X.astype(np.float32)
    X[np.random.default_rng(0).random(X.shape) < 0.03] = np.nan
    model = GBDTClassifier(n_estimators=10, max_depth=3, n_bins=16).fit(X, y)
    return model, X


def _brute_force_phi(forest, x, n_features, n_trees):
    """Path-dependent Shapley by explicit subset enumeration."""
    d = forest.depth
    n_internal = 2**d - 1

    def tree_expect(t, S):
        feat = np.asarray(forest.feature[t])
        thr = np.asarray(forest.thr_float[t])
        ml = np.asarray(forest.missing_left[t])
        cov = np.asarray(forest.cover[t])
        lv = np.asarray(forest.leaf_value[t])

        def rec(node, level, w):
            if level == d:
                return w * lv[node - n_internal]
            j = feat[node]
            l, r = 2 * node + 1, 2 * node + 2
            if j in S:
                go_left = ml[node] if np.isnan(x[j]) else x[j] <= thr[node]
                return rec(l if go_left else r, level + 1, w)
            pc = cov[node]
            if pc <= 0:
                return 0.0
            return rec(l, level + 1, w * cov[l] / pc) + rec(
                r, level + 1, w * cov[r] / pc
            )

        return rec(0, 0, 1.0)

    phi = np.zeros(n_features)
    for i in range(n_features):
        others = [j for j in range(n_features) if j != i]
        for k in range(n_features):
            for S in itertools.combinations(others, k):
                w = (
                    math.factorial(len(S))
                    * math.factorial(n_features - len(S) - 1)
                    / math.factorial(n_features)
                )
                v1 = sum(tree_expect(t, set(S) | {i}) for t in range(n_trees))
                v0 = sum(tree_expect(t, set(S)) for t in range(n_trees))
                phi[i] += w * (v1 - v0)
    return phi


def test_additivity(small_model):
    """The TreeExplainer contract: base + sum(shap) == margin, per row."""
    model, X = small_model
    Xq = jnp.asarray(X[:50])
    phis, base = shap_values(model.forest, Xq, n_features=6)
    margins = np.asarray(model.predict_margin(X[:50]))
    np.testing.assert_allclose(
        float(base) + np.asarray(phis).sum(axis=1), margins, atol=1e-4
    )


def test_matches_brute_force_shapley(small_model):
    model, X = small_model
    for row in (0, 7):
        phis, _ = shap_values(model.forest, jnp.asarray(X[row : row + 1]), n_features=6)
        bf = _brute_force_phi(model.forest, X[row], 6, 10)
        np.testing.assert_allclose(np.asarray(phis)[0], bf, atol=1e-4)


def test_nan_rows_explained(small_model):
    model, X = small_model
    x = X[0].copy()
    x[:] = np.nan
    phis, base = shap_values(model.forest, jnp.asarray(x[None]), n_features=6)
    assert np.isfinite(np.asarray(phis)).all()
    margin = float(model.predict_margin(x[None])[0])
    assert abs(float(base) + float(np.asarray(phis).sum()) - margin) < 1e-4


def test_depth9_exact_and_bounded():
    """The shipped search space's corner (config.py max_depth up to 9): the
    polynomial algorithm must stay exact AND bounded there — the old subset
    enumeration needed 512 * 512 * 9 intermediates per row per tree and could
    not serve a tuned depth-9 artifact."""
    X, y = make_classification(
        n_samples=600, n_features=6, n_informative=4, random_state=1
    )
    X = X.astype(np.float32)
    model = GBDTClassifier(n_estimators=8, max_depth=9, n_bins=16).fit(X, y)
    assert model.forest.depth == 9
    phis, base = shap_values(model.forest, jnp.asarray(X[:20]), n_features=6)
    margins = np.asarray(model.predict_margin(X[:20]))
    np.testing.assert_allclose(
        float(base) + np.asarray(phis).sum(axis=1), margins, atol=1e-3
    )
    bf = _brute_force_phi(model.forest, X[3], 6, 8)
    np.testing.assert_allclose(np.asarray(phis)[3], bf, atol=1e-3)


def test_explainer_facade(small_model):
    model, X = small_model
    ex = TreeExplainer(model)
    sv = ex.shap_values(X[:10], chunk_size=4)
    assert sv.shape == (10, 6)
    assert np.isfinite(ex.expected_value)
    margins = np.asarray(model.predict_margin(X[:10]))
    np.testing.assert_allclose(ex.expected_value + sv.sum(axis=1), margins, atol=1e-4)
