"""TreeSHAP tests: additivity, brute-force Shapley exactness, NaN routing,
and the TreeExplainer facade."""

import itertools
import math

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.datasets import make_classification

from cobalt_smart_lender_ai_tpu.explain import TreeExplainer
from cobalt_smart_lender_ai_tpu.explain.treeshap import shap_values
from cobalt_smart_lender_ai_tpu.models.gbdt import (
    Forest,
    GBDTClassifier,
    predict_margin,
)


@pytest.fixture(scope="module")
def small_model():
    X, y = make_classification(
        n_samples=800, n_features=6, n_informative=4, random_state=0
    )
    X = X.astype(np.float32)
    X[np.random.default_rng(0).random(X.shape) < 0.03] = np.nan
    model = GBDTClassifier(n_estimators=10, max_depth=3, n_bins=16).fit(X, y)
    return model, X


def _brute_force_phi(forest, x, n_features, n_trees):
    """Path-dependent Shapley by explicit subset enumeration."""
    d = forest.depth
    n_internal = 2**d - 1

    def tree_expect(t, S):
        feat = np.asarray(forest.feature[t])
        thr = np.asarray(forest.thr_float[t])
        ml = np.asarray(forest.missing_left[t])
        cov = np.asarray(forest.cover[t])
        lv = np.asarray(forest.leaf_value[t])

        def rec(node, level, w):
            if level == d:
                return w * lv[node - n_internal]
            j = feat[node]
            l, r = 2 * node + 1, 2 * node + 2
            if j in S:
                go_left = ml[node] if np.isnan(x[j]) else x[j] <= thr[node]
                return rec(l if go_left else r, level + 1, w)
            pc = cov[node]
            if pc <= 0:
                return 0.0
            return rec(l, level + 1, w * cov[l] / pc) + rec(
                r, level + 1, w * cov[r] / pc
            )

        return rec(0, 0, 1.0)

    phi = np.zeros(n_features)
    for i in range(n_features):
        others = [j for j in range(n_features) if j != i]
        for k in range(n_features):
            for S in itertools.combinations(others, k):
                w = (
                    math.factorial(len(S))
                    * math.factorial(n_features - len(S) - 1)
                    / math.factorial(n_features)
                )
                v1 = sum(tree_expect(t, set(S) | {i}) for t in range(n_trees))
                v0 = sum(tree_expect(t, set(S)) for t in range(n_trees))
                phi[i] += w * (v1 - v0)
    return phi


def test_additivity(small_model):
    """The TreeExplainer contract: base + sum(shap) == margin, per row."""
    model, X = small_model
    Xq = jnp.asarray(X[:50])
    phis, base = shap_values(model.forest, Xq, n_features=6)
    margins = np.asarray(model.predict_margin(X[:50]))
    np.testing.assert_allclose(
        float(base) + np.asarray(phis).sum(axis=1), margins, atol=1e-4
    )


def test_matches_brute_force_shapley(small_model):
    model, X = small_model
    for row in (0, 7):
        phis, _ = shap_values(model.forest, jnp.asarray(X[row : row + 1]), n_features=6)
        bf = _brute_force_phi(model.forest, X[row], 6, 10)
        np.testing.assert_allclose(np.asarray(phis)[0], bf, atol=1e-4)


def test_nan_rows_explained(small_model):
    model, X = small_model
    x = X[0].copy()
    x[:] = np.nan
    phis, base = shap_values(model.forest, jnp.asarray(x[None]), n_features=6)
    assert np.isfinite(np.asarray(phis)).all()
    margin = float(model.predict_margin(x[None])[0])
    assert abs(float(base) + float(np.asarray(phis).sum()) - margin) < 1e-4


def test_depth9_exact_and_bounded():
    """The shipped search space's corner (config.py max_depth up to 9): the
    polynomial algorithm must stay exact AND bounded there — the old subset
    enumeration needed 512 * 512 * 9 intermediates per row per tree and could
    not serve a tuned depth-9 artifact."""
    X, y = make_classification(
        n_samples=600, n_features=6, n_informative=4, random_state=1
    )
    X = X.astype(np.float32)
    model = GBDTClassifier(n_estimators=8, max_depth=9, n_bins=16).fit(X, y)
    assert model.forest.depth == 9
    phis, base = shap_values(model.forest, jnp.asarray(X[:20]), n_features=6)
    margins = np.asarray(model.predict_margin(X[:20]))
    np.testing.assert_allclose(
        float(base) + np.asarray(phis).sum(axis=1), margins, atol=1e-3
    )
    bf = _brute_force_phi(model.forest, X[3], 6, 8)
    np.testing.assert_allclose(np.asarray(phis)[3], bf, atol=1e-3)


def test_serving_shape_bounded_and_additive():
    """The shape a tuned depth-9 artifact would actually ship — 300 trees x
    depth 9 x the 20-feature serving contract — run through the bulk
    explainer at its serving chunk size: additivity must hold and a chunk
    must clear in interactive time (the O(L*d^3) math says ~tens of ms/row;
    the bound is generous for the 1-core CI box). The forest is synthesized
    structurally (consistent parent/child covers) rather than trained: the
    algorithm's exactness is pinned by the brute-force tests above; this
    test pins time/memory at the artifact shape `cobalt_fast_api.py:100`
    serves per request."""
    import time

    T, depth, F = 300, 9, 20
    n_internal = 2**depth - 1
    n_leaves = 2**depth
    rng = np.random.default_rng(0)
    cover = np.zeros((T, n_internal + n_leaves), np.float32)
    cover[:, 0] = 100_000.0
    ratios = rng.uniform(0.2, 0.8, size=(T, n_internal)).astype(np.float32)
    for i in range(n_internal):
        cover[:, 2 * i + 1] = cover[:, i] * ratios[:, i]
        cover[:, 2 * i + 2] = cover[:, i] * (1.0 - ratios[:, i])
    forest = Forest(
        feature=jnp.asarray(rng.integers(0, F, size=(T, n_internal)), jnp.int32),
        thr_bin=jnp.zeros((T, n_internal), jnp.int32),
        thr_float=jnp.asarray(
            rng.normal(size=(T, n_internal)), jnp.float32
        ),
        missing_left=jnp.asarray(rng.random((T, n_internal)) < 0.5),
        gain=jnp.ones((T, n_internal), jnp.float32),
        cover=jnp.asarray(cover),
        leaf_value=jnp.asarray(
            rng.normal(scale=0.01, size=(T, n_leaves)), jnp.float32
        ),
        depth=depth,
    )
    X = rng.normal(size=(64, F)).astype(np.float32)
    X[rng.random(X.shape) < 0.02] = np.nan

    phis, base = shap_values(forest, jnp.asarray(X), n_features=F)  # warmup
    t0 = time.time()
    phis, base = shap_values(forest, jnp.asarray(X), n_features=F)
    phis = np.asarray(phis)
    elapsed = time.time() - t0
    margins = np.asarray(predict_margin(forest, jnp.asarray(X)))
    np.testing.assert_allclose(
        float(base) + phis.sum(axis=1), margins, atol=1e-3
    )
    assert phis.shape == (64, F) and np.isfinite(phis).all()
    # Interactive bound: a 64-row serving chunk at the full artifact shape.
    assert elapsed < 60.0, f"serving-shape SHAP chunk took {elapsed:.1f}s"


def test_explainer_facade(small_model):
    model, X = small_model
    ex = TreeExplainer(model)
    sv = ex.shap_values(X[:10], chunk_size=4)
    assert sv.shape == (10, 6)
    assert np.isfinite(ex.expected_value)
    margins = np.asarray(model.predict_margin(X[:10]))
    np.testing.assert_allclose(ex.expected_value + sv.sum(axis=1), margins, atol=1e-4)
