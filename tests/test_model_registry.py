"""Model registry: versioned publishes, channel-pointer semantics
(latest/canary/previous), promote/rollback flips, keep-last-K GC — and the
chaos drill: pointer writes under injected store faults are atomic (stale is
allowed, torn is not)."""

import json

import numpy as np
import pytest

from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, ObjectStore
from cobalt_smart_lender_ai_tpu.io.model_registry import (
    CHANNELS,
    ModelRegistry,
    ModelVersion,
)


@pytest.fixture
def lake(tmp_path, serving_artifact):
    """Private store + the session artifact to publish from."""
    shared, _ = serving_artifact
    art = GBDTArtifact.load(shared, "models/gbdt/model_tree")
    return ObjectStore(str(tmp_path / "lake")), art


def test_publish_mints_versions_with_provenance(lake):
    store, art = lake
    reg = ModelRegistry(store)
    mv = reg.publish(
        "gbdt", art, provenance={"dataset_md5": "abc", "config_hash": "ff"}
    )
    assert (mv.name, mv.version, mv.kind) == ("gbdt", 1, "GBDTArtifact")
    assert mv.key == "models/gbdt/v1"
    assert mv.parent_version is None
    assert mv.provenance["dataset_md5"] == "abc"
    # record round-trips, artifact restores from the versioned key, and the
    # stored npz hashes back to the recorded md5
    back = reg.record("gbdt", 1)
    assert back == mv
    assert isinstance(back, ModelVersion)
    restored = GBDTArtifact.load(store, mv.key)
    assert restored.feature_names == art.feature_names
    assert reg.verify("gbdt", 1)
    # content pin written: ResilientStore verified reads cover the model blob
    assert store.exists(mv.key + ".npz.ptr.json")
    # default channel is canary, never latest
    assert reg.resolve("gbdt", "canary") == mv.key
    assert reg.resolve("gbdt", "latest") is None
    assert reg.names() == ["gbdt"]
    assert reg.versions("gbdt") == [1]


def test_publish_is_write_once(lake):
    store, art = lake
    reg = ModelRegistry(store)
    reg.publish("gbdt", art)
    # registry invariant: a version record is immutable once minted
    reg._next_version = lambda name: 1
    with pytest.raises(FileExistsError):
        reg.publish("gbdt", art)


def test_promote_and_rollback_flips(lake):
    store, art = lake
    reg = ModelRegistry(store)
    reg.publish("gbdt", art)
    flip = reg.promote("gbdt")
    assert flip["promoted_version"] == 1 and flip["previous_version"] is None
    assert reg.resolve("gbdt", "latest") == "models/gbdt/v1"
    assert reg.channel("gbdt", "canary") is None  # pointer cleared

    mv2 = reg.publish("gbdt", art)
    assert mv2.version == 2 and mv2.parent_version == 1
    flip = reg.promote("gbdt")
    assert flip["promoted_version"] == 2 and flip["previous_version"] == 1
    assert reg.channel("gbdt", "latest")["version"] == 2
    assert reg.channel("gbdt", "previous")["version"] == 1

    back = reg.rollback("gbdt", reason="slo burn")
    assert back["restored_version"] == 1 and back["demoted_version"] == 2
    latest = reg.channel("gbdt", "latest")
    assert latest["version"] == 1
    assert latest["rolled_back_from"] == 2 and latest["reason"] == "slo burn"
    # the demoted champion stays reachable for forensics
    assert reg.channel("gbdt", "previous")["version"] == 2


def test_promote_and_rollback_require_their_channels(lake):
    store, art = lake
    reg = ModelRegistry(store)
    with pytest.raises(LookupError):
        reg.promote("gbdt")  # nothing published
    reg.publish("gbdt", art)
    reg.promote("gbdt")
    with pytest.raises(LookupError):
        reg.rollback("gbdt")  # no previous yet


def test_channel_pointer_guards(lake):
    store, art = lake
    reg = ModelRegistry(store)
    reg.publish("gbdt", art)
    with pytest.raises(ValueError, match="unknown channel"):
        reg.set_channel("gbdt", "prod", 1)
    with pytest.raises(FileNotFoundError):
        reg.set_channel("gbdt", "latest", 99)  # pointers never dangle


def test_gc_keeps_channel_pinned_and_last_k(lake):
    store, art = lake
    reg = ModelRegistry(store)
    for _ in range(4):
        reg.publish("gbdt", art, channel=None)
    reg.set_channel("gbdt", "latest", 1)  # pin an old version

    dry = reg.gc(keep_last=1, dry_run=True)
    assert dry["dry_run"] and dry["models"]["gbdt"]["deleted"] == [2, 3]
    assert store.exists("models/gbdt/v2.npz")  # dry-run touched nothing

    applied = reg.gc(keep_last=1, dry_run=False)
    assert applied["models"]["gbdt"] == {"kept": [1, 4], "deleted": [2, 3]}
    assert not store.exists("models/gbdt/v2.npz")
    assert not store.exists("registry/models/gbdt/v3.json")
    assert store.exists("models/gbdt/v1.npz")  # channel-pinned survives
    assert store.exists("models/gbdt/v4.npz")  # newest survives
    assert reg.versions("gbdt") == [1, 4]
    # the pinned pointer still resolves to a loadable artifact
    GBDTArtifact.load(store, reg.resolve("gbdt", "latest"))


def test_registry_gc_cli_dry_run(lake, capsys):
    store, art = lake
    reg = ModelRegistry(store)
    for _ in range(3):
        reg.publish("gbdt", art, channel=None)
    from tools.registry_gc import main as gc_main

    gc_main(["--store", store.uri, "--keep-last", "1"])
    report = json.loads(capsys.readouterr().out)
    assert report["dry_run"] is True
    assert report["models"]["gbdt"]["deleted"] == [1, 2]
    assert store.exists("models/gbdt/v1.npz")  # nothing deleted


# --- chaos: pointers under injected faults ------------------------------------


def _assert_no_torn_pointers(store: ObjectStore, reg: ModelRegistry) -> None:
    """The continuous-training invariant: every channel pointer that exists
    parses as JSON, names a version whose record exists, and its artifact
    restores. Stale is acceptable after a fault; torn or dangling is not."""
    for name in reg.names():
        for ch in CHANNELS:
            key = reg._channel_key(name, ch)
            if not store.exists(key):
                continue
            ptr = json.loads(store.get_bytes(key).decode())
            assert {"name", "channel", "version", "key"} <= set(ptr)
            record = reg.record(name, int(ptr["version"]))
            assert record.key == ptr["key"]
            GBDTArtifact.load(store, ptr["key"])


@pytest.mark.faults
def test_publish_promote_rollback_cycle_under_faults(tmp_path, serving_artifact):
    """Drive full canary lifecycles against a store dropping ~1 in 5 calls
    (plus injected latency): with `ResilientStore` retries every cycle
    completes, and after EVERY step the channel pointers are whole."""
    from cobalt_smart_lender_ai_tpu.reliability import ResilientStore, RetryPolicy
    from cobalt_smart_lender_ai_tpu.reliability.faults import (
        FaultInjectingStore,
        FaultSpec,
    )
    from cobalt_smart_lender_ai_tpu.telemetry import MetricsRegistry

    shared, _ = serving_artifact
    art = GBDTArtifact.load(shared, "models/gbdt/model_tree")
    inner = ObjectStore(str(tmp_path / "lake"))
    flaky = FaultInjectingStore(
        inner,
        seed=13,
        faults={
            "put": FaultSpec(rate=0.2, max_faults=40),
            "get": FaultSpec(rate=0.15, max_faults=40),
            "exists": FaultSpec(rate=0.1, max_faults=20),
            "delete": FaultSpec(rate=0.2, max_faults=10),
        },
        sleep=lambda s: None,
        registry=MetricsRegistry(),
    )
    store = ResilientStore(
        flaky,
        RetryPolicy(max_attempts=6, base_delay_s=0.0, jitter=0.0),
        verify_reads=True,
    )
    reg = ModelRegistry(store)

    reg.publish("gbdt", art)
    _assert_no_torn_pointers(store, reg)
    reg.promote("gbdt")
    _assert_no_torn_pointers(store, reg)
    for cycle in range(2):
        reg.publish("gbdt", art)
        _assert_no_torn_pointers(store, reg)
        reg.promote("gbdt")
        _assert_no_torn_pointers(store, reg)
        reg.rollback("gbdt", reason=f"cycle {cycle}")
        _assert_no_torn_pointers(store, reg)
    assert flaky.injected.total() > 0  # the drill actually injected faults
