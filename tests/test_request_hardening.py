"""Request-path hardening: deadlines, admission control, circuit breaker,
and hot model swap with rollback.

Every behavior is first pinned deterministically against fake clocks (no test
below sleeps to make time pass — `ManualClock.advance` *is* the passage of
time), then the HTTP surface is exercised through the stdlib adapter so the
status codes, bodies and ``Retry-After`` headers of the taxonomy
(`reliability.errors`) are asserted on the wire. The chaos soak at the bottom
(marked ``slow`` + ``faults``; run by the CI ``faults`` job and excluded from
tier-1) drives the real asyncio server under injected store faults and
latency while hot-swapping models concurrently, and asserts the ISSUE's
headline: zero untyped 500s — every failure a client sees is a policy
decision with a machine-readable code, not a bug escape.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import json
import threading
import time
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from cobalt_smart_lender_ai_tpu.config import ReliabilityConfig, ServeConfig
from cobalt_smart_lender_ai_tpu.data import schema
from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, ObjectStore
from cobalt_smart_lender_ai_tpu.reliability import (
    AdmissionController,
    CircuitBreaker,
    CircuitOpenError,
    Deadline,
    DeadlineExceeded,
    FaultInjectingStore,
    FaultSpec,
    InjectedFault,
    PayloadTooLarge,
    RequestShed,
    TokenBucket,
    start_deadline,
)
from cobalt_smart_lender_ai_tpu.serve.http_asyncio import make_async_server
from cobalt_smart_lender_ai_tpu.serve.service import (
    SINGLE_INPUT_FIELDS,
    ScorerService,
)

# --- clocks -------------------------------------------------------------------


class ManualClock:
    """Time passes only when the test says so."""

    def __init__(self, start: float = 0.0):
        self.now = start

    def __call__(self) -> float:
        return self.now

    def advance(self, s: float) -> None:
        self.now += s


class TickingClock:
    """Every read advances a fixed tick — simulates wall time elapsing while
    the service works, without any real sleeping."""

    def __init__(self, tick: float):
        self.now = 0.0
        self.tick = tick

    def __call__(self) -> float:
        t = self.now
        self.now += self.tick
        return t


# --- helpers ------------------------------------------------------------------


def _valid_payload() -> dict:
    """One schema-complete /predict body, keyed by canonical feature names."""
    return {
        canonical: 1 if canonical in schema.SERVING_INT_FEATURES else 1.5
        for canonical in SINGLE_INPUT_FIELDS.values()
    }


def _request(url: str, data: bytes | None = None, content_type: str = "application/json"):
    """(status, json body, headers) for GET (data=None) or POST."""
    req = urllib.request.Request(url, data=data)
    if data is not None:
        req.add_header("Content-Type", content_type)
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read().decode()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode()), dict(e.headers)


@contextlib.contextmanager
def _running(service: ScorerService):
    server = make_async_server(service)
    try:
        yield f"http://127.0.0.1:{server.port}"
    finally:
        server.close()


def _csv_bytes(X: np.ndarray, n: int) -> bytes:
    df = pd.DataFrame(X[:n], columns=list(schema.SERVING_FEATURES))
    return df.to_csv(index=False).encode()


def _cfg(**rel) -> ServeConfig:
    return ServeConfig(
        precompile_batch_buckets=(),
        prewarm_all_buckets=False,  # compile only the cap: keeps tier-1 fast
        reliability=ReliabilityConfig(**rel),
    )


@pytest.fixture()
def fresh_store(tmp_path, serving_artifact):
    """Private copy of the trained serving artifact — swap/soak tests write
    new model versions and poison blobs, which must not leak into the
    session-scoped store other modules share."""
    shared, X = serving_artifact
    art = GBDTArtifact.load(shared, "models/gbdt/model_tree")
    store = ObjectStore(str(tmp_path / "lake"))
    art.save(store, "models/gbdt/model_tree")
    return store, art, X


def _zeroed(art: GBDTArtifact) -> GBDTArtifact:
    """A valid model whose every leaf is 0 — margin 0, P(default) exactly 0.5
    for any input: a hot swap to it is observable from a single prediction."""
    return dataclasses.replace(
        art,
        forest=dataclasses.replace(
            art.forest, leaf_value=jnp.zeros_like(art.forest.leaf_value)
        ),
    )


# --- deadlines ----------------------------------------------------------------


def test_deadline_expires_on_fake_clock():
    clk = ManualClock()
    dl = Deadline(1.0, clock=clk)
    dl.check("start")
    assert not dl.expired()
    clk.advance(0.5)
    assert dl.remaining() == pytest.approx(0.5)
    clk.advance(0.6)
    assert dl.expired()
    with pytest.raises(DeadlineExceeded) as ei:
        dl.check("bulk scoring, row 6/8")
    assert "bulk scoring, row 6/8" in str(ei.value)
    assert ei.value.status == 504 and ei.value.code == "deadline_exceeded"


def test_start_deadline_none_disables():
    assert start_deadline(None) is None
    assert isinstance(start_deadline(1.0, ManualClock()), Deadline)


def test_predict_single_deadline_504_shape(serving_artifact):
    """With a ticking clock, the budget expires between the validation and
    SHAP checkpoints — and must surface as DeadlineExceeded, NOT be swallowed
    into a degraded-SHAP 200. Pinned to the direct (unbatched) path: the
    micro-batcher's own deadline checkpoints are covered in
    test_microbatch.py, and a ticking clock shared with the batcher thread
    would advance nondeterministically."""
    store, _ = serving_artifact
    clk = TickingClock(tick=0.03)
    svc = ScorerService.from_store(
        store,
        dataclasses.replace(
            _cfg(request_deadline_s=0.05), microbatch_enabled=False
        ),
        clock=clk,
    )
    with pytest.raises(DeadlineExceeded) as ei:
        svc.predict_single(_valid_payload())
    assert "probability scored" in str(ei.value)


def test_bulk_deadline_trips_between_chunks(serving_artifact):
    store, X = serving_artifact
    clk = TickingClock(tick=0.01)
    cfg = dataclasses.replace(
        _cfg(request_deadline_s=0.05), max_batch_rows=2
    )
    svc = ScorerService.from_store(store, cfg, clock=clk)
    with pytest.raises(DeadlineExceeded) as ei:
        svc.predict_bulk_csv(_csv_bytes(X, 8))
    assert "bulk scoring, row" in str(ei.value)


def test_deadline_maps_to_http_504(serving_artifact):
    store, _ = serving_artifact
    clk = TickingClock(tick=0.03)
    svc = ScorerService.from_store(
        store, _cfg(request_deadline_s=0.05), clock=clk
    )
    with _running(svc) as base:
        status, body, _ = _request(
            base + "/predict", json.dumps(_valid_payload()).encode()
        )
    assert status == 504
    assert body["error"] == "deadline_exceeded"
    assert "deadline" in body["detail"]


# --- admission control --------------------------------------------------------


def test_token_bucket_fake_clock():
    clk = ManualClock()
    tb = TokenBucket(rate_rps=2.0, burst=2, clock=clk)
    assert tb.try_acquire() and tb.try_acquire()
    assert not tb.try_acquire()
    assert tb.retry_after_s() == pytest.approx(0.5)
    clk.advance(0.5)  # exactly one token refilled
    assert tb.try_acquire()
    clk.advance(100.0)  # refill is capped at burst
    assert tb.try_acquire() and tb.try_acquire()
    assert not tb.try_acquire()


def test_admission_rate_shed_carries_retry_after():
    clk = ManualClock()
    adm = AdmissionController(rate_rps=1.0, burst=1, clock=clk)
    with adm.admit():
        pass
    with pytest.raises(RequestShed) as ei:
        with adm.admit():
            pass
    assert ei.value.status == 429 and ei.value.code == "shed"
    assert ei.value.retry_after_s == pytest.approx(1.0)
    assert ei.value.headers() == {"Retry-After": "1"}
    assert adm.stats()["shed_rate"] == 1
    clk.advance(1.0)
    with adm.admit():  # token refilled: admitted again
        pass
    assert adm.stats()["admitted"] == 2


def test_admission_capacity_shed_and_release():
    adm = AdmissionController(max_in_flight=2, shed_retry_after_s=3.0)
    slots = [adm.admit() for _ in range(2)]
    for cm in slots:
        cm.__enter__()
    assert adm.stats()["in_flight"] == 2
    with pytest.raises(RequestShed) as ei:
        with adm.admit():
            pass
    assert ei.value.headers() == {"Retry-After": "3"}
    for cm in slots:
        cm.__exit__(None, None, None)
    with adm.admit():  # slots released: admitted again
        pass
    assert adm.stats() == {
        "in_flight": 0,
        "admitted": 3,
        "shed_rate": 0,
        "shed_capacity": 1,
        "max_in_flight": 2,
        "scale_units": 1,
    }


def test_shed_maps_to_http_429(serving_artifact):
    store, _ = serving_artifact
    svc = ScorerService.from_store(store, _cfg(max_in_flight=1))
    body = json.dumps(_valid_payload()).encode()
    with _running(svc) as base:
        slot = svc.admission.admit()  # occupy the only slot
        slot.__enter__()
        try:
            status, resp, headers = _request(base + "/predict", body)
        finally:
            slot.__exit__(None, None, None)
        assert status == 429
        assert resp["error"] == "shed"
        assert int(headers["Retry-After"]) >= 1
        # slot released: the same request is admitted and scored
        status, resp, _ = _request(base + "/predict", body)
        assert status == 200 and 0.0 <= resp["prob_default"] <= 1.0
        # shed requests are visible in /readyz admission stats
        _, ready, _ = _request(base + "/readyz")
        assert ready["admission"]["shed_capacity"] == 1


# --- circuit breaker ----------------------------------------------------------


def _boom():
    raise InjectedFault("store down")


def test_breaker_trips_after_consecutive_failures():
    clk = ManualClock()
    brk = CircuitBreaker(failure_threshold=3, reset_timeout_s=10.0, clock=clk)
    for _ in range(2):
        with pytest.raises(InjectedFault):
            brk.call(_boom)
    assert brk.state == "closed"  # streak below threshold
    with pytest.raises(InjectedFault):
        brk.call(_boom)
    assert brk.state == "open"
    # open: calls fail fast with the time until half-open, store untouched
    with pytest.raises(CircuitOpenError) as ei:
        brk.call(lambda: pytest.fail("must not reach the store"))
    assert ei.value.status == 503 and ei.value.code == "circuit_open"
    assert 0.0 < ei.value.retry_after_s <= 10.0
    assert brk.fast_failures == 1
    clk.advance(10.0)
    assert brk.state == "half_open"
    assert brk.call(lambda: "probe") == "probe"
    assert brk.state == "closed"
    assert brk.transitions == ["open", "half_open", "closed"]


def test_breaker_success_resets_failure_streak():
    brk = CircuitBreaker(failure_threshold=3, clock=ManualClock())
    for _ in range(2):
        with pytest.raises(InjectedFault):
            brk.call(_boom)
    assert brk.call(lambda: "ok") == "ok"  # resets the streak
    for _ in range(2):
        with pytest.raises(InjectedFault):
            brk.call(_boom)
    assert brk.state == "closed"
    with pytest.raises(InjectedFault):
        brk.call(_boom)
    assert brk.state == "open"


def test_breaker_failed_probe_reopens_and_restarts_timer():
    clk = ManualClock()
    brk = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0, clock=clk)
    with pytest.raises(InjectedFault):
        brk.call(_boom)
    clk.advance(5.0)
    with pytest.raises(InjectedFault):
        brk.call(_boom)  # the half-open probe itself fails
    assert brk.state == "open"
    clk.advance(4.9)
    assert brk.state == "open"  # timer restarted by the failed probe
    clk.advance(0.1)
    assert brk.call(lambda: "up") == "up"
    assert brk.transitions == ["open", "half_open", "open", "half_open", "closed"]
    assert brk.opened_count == 2


def test_breaker_half_open_limits_probes():
    clk = ManualClock()
    brk = CircuitBreaker(failure_threshold=1, reset_timeout_s=1.0, clock=clk)
    with pytest.raises(InjectedFault):
        brk.call(_boom)
    clk.advance(1.0)

    def probe():
        # While this probe is in flight, a second caller must be rejected —
        # half-open admits exactly half_open_max_calls concurrent probes.
        with pytest.raises(CircuitOpenError):
            brk.call(lambda: "second")
        return "first"

    assert brk.call(probe) == "first"
    assert brk.state == "closed"


# --- bounded bulk requests (413) ----------------------------------------------


def test_bulk_rows_bound(serving_artifact):
    store, X = serving_artifact
    cfg = dataclasses.replace(_cfg(), max_bulk_rows=4)
    svc = ScorerService.from_store(store, cfg)
    assert len(svc.predict_bulk_csv(_csv_bytes(X, 4))["predictions"]) == 4
    with pytest.raises(PayloadTooLarge) as ei:
        svc.predict_bulk_csv(_csv_bytes(X, 5))
    assert ei.value.status == 413 and "max_bulk_rows" in str(ei.value)


def test_bulk_bytes_bound_rejects_before_parse(serving_artifact):
    store, _ = serving_artifact
    cfg = dataclasses.replace(_cfg(), max_bulk_bytes=64)
    svc = ScorerService.from_store(store, cfg)
    with pytest.raises(PayloadTooLarge) as ei:
        svc.predict_bulk_csv(b"x" * 65)  # not even valid CSV: bytes gate first
    assert "max_bulk_bytes" in str(ei.value)


def test_payload_too_large_maps_to_http_413(serving_artifact):
    store, X = serving_artifact
    cfg = dataclasses.replace(_cfg(), max_bulk_rows=4)
    svc = ScorerService.from_store(store, cfg)
    with _running(svc) as base:
        status, body, _ = _request(
            base + "/predict_bulk_csv", _csv_bytes(X, 8), "text/csv"
        )
    assert status == 413
    assert body["error"] == "payload_too_large"


# --- hot model swap -----------------------------------------------------------


def test_hot_swap_changes_served_model(fresh_store):
    store, art, _ = fresh_store
    svc = ScorerService.from_store(store, _cfg())
    payload = _valid_payload()
    assert svc.predict_single(payload)["prob_default"] != pytest.approx(0.5)
    _zeroed(art).save(store, "models/gbdt/v2")

    result = svc.reload_from_store(model_key="models/gbdt/v2")
    assert result == {
        "status": "ok",
        "model_key": "models/gbdt/v2",
        "n_features": 20,
    }
    # the zeroed forest serves margin 0 -> probability exactly 0.5
    assert svc.predict_single(payload)["prob_default"] == pytest.approx(0.5)
    ready, payload_r = svc.ready()
    assert ready
    assert payload_r["model_key"] == "models/gbdt/v2"
    assert payload_r["last_reload"]["status"] == "ok"


def test_hot_swap_over_http_admin_endpoint(fresh_store):
    store, art, _ = fresh_store
    svc = ScorerService.from_store(store, _cfg())
    _zeroed(art).save(store, "models/gbdt/v2")
    body = json.dumps(_valid_payload()).encode()
    with _running(svc) as base:
        status, resp, _ = _request(
            base + "/admin/reload",
            json.dumps({"model_key": "models/gbdt/v2"}).encode(),
        )
        assert status == 200 and resp["status"] == "ok"
        status, pred, _ = _request(base + "/predict", body)
        assert status == 200
        assert pred["prob_default"] == pytest.approx(0.5)
        _, ready, _ = _request(base + "/readyz")
        assert ready["model_key"] == "models/gbdt/v2"


def test_poisoned_artifact_swap_rolls_back(fresh_store):
    store, _, _ = fresh_store
    svc = ScorerService.from_store(store, _cfg())
    payload = _valid_payload()
    before = svc.predict_single(payload)["prob_default"]
    store.put_bytes("models/poison.npz", b"\x00this is not an npz archive")

    result = svc.reload_from_store(model_key="models/poison")
    assert result["status"] == "rolled_back"
    assert result["model_key"] == "models/poison"
    assert result["error"]
    # the previous model is still serving, untouched
    assert svc.predict_single(payload)["prob_default"] == before
    _, ready_payload = svc.ready()
    assert ready_payload["model_key"] == "models/gbdt/model_tree"
    assert ready_payload["last_reload"]["status"] == "rolled_back"


def test_smoke_check_rejects_nonfinite_model(fresh_store):
    """A loadable artifact whose leaves are NaN scores the pinned smoke row
    to NaN — validation must reject it before it is published."""
    store, art, _ = fresh_store
    svc = ScorerService.from_store(store, _cfg())
    nan_art = dataclasses.replace(
        art,
        forest=dataclasses.replace(
            art.forest,
            leaf_value=jnp.full_like(art.forest.leaf_value, jnp.nan),
        ),
    )
    nan_art.save(store, "models/gbdt/nan")
    result = svc.reload_from_store(model_key="models/gbdt/nan")
    assert result["status"] == "rolled_back"
    assert "expected [0, 1]" in result["error"]
    assert svc._model_key == "models/gbdt/model_tree"


def test_smoke_check_rejects_feature_contract_change(fresh_store):
    store, art, _ = fresh_store
    svc = ScorerService.from_store(store, _cfg())
    renamed = dataclasses.replace(
        art,
        feature_names=("zzz_not_a_feature",) + tuple(art.feature_names[1:]),
    )
    renamed.save(store, "models/gbdt/renamed")
    result = svc.reload_from_store(model_key="models/gbdt/renamed")
    assert result["status"] == "rolled_back"
    assert "feature contract changed" in result["error"]


def test_reload_without_store_is_an_error(serving_artifact):
    store, _ = serving_artifact
    art = GBDTArtifact.load(store, "models/gbdt/model_tree")
    svc = ScorerService(art, _cfg())  # constructed without a store handle
    with pytest.raises(RuntimeError, match="no store bound"):
        svc.reload_from_store()


def test_http_reload_failure_is_typed_500(fresh_store):
    store, _, _ = fresh_store
    svc = ScorerService.from_store(store, _cfg())
    store.put_bytes("models/poison.npz", b"garbage")
    with _running(svc) as base:
        status, body, _ = _request(
            base + "/admin/reload",
            json.dumps({"model_key": "models/poison"}).encode(),
        )
    assert status == 500
    assert body["error"] == "reload_failed"
    assert body["status"] == "rolled_back"


# --- breaker x reload integration ---------------------------------------------


def test_breaker_opens_on_flaky_store_and_recovers(fresh_store):
    store, _, _ = fresh_store
    clk = ManualClock()
    flaky = FaultInjectingStore(store, faults={}, sleep=clk.advance)
    cfg = _cfg(breaker_failure_threshold=2, breaker_reset_s=5.0)
    svc = ScorerService.from_store(flaky, cfg, clock=clk)

    flaky.faults["get"] = FaultSpec(fail_after=0)  # store goes hard down
    assert svc.reload_from_store()["status"] == "rolled_back"
    assert svc.reload_from_store()["status"] == "rolled_back"
    assert svc.store_breaker.state == "open"
    # open circuit: reload fails fast as 503 material, not another rollback
    with pytest.raises(CircuitOpenError):
        svc.reload_from_store()
    _, ready_payload = svc.ready()
    assert ready_payload["breaker"] == "open"
    # requests keep serving the in-memory model throughout the outage
    assert 0.0 <= svc.predict_single(_valid_payload())["prob_default"] <= 1.0

    clk.advance(5.0)  # reset timeout elapses; store comes back
    del flaky.faults["get"]
    assert svc.reload_from_store()["status"] == "ok"
    assert svc.store_breaker.state == "closed"
    assert svc.store_breaker.transitions == ["open", "half_open", "closed"]


# --- latency injection (FaultInjectingStore) ----------------------------------


def test_latency_injection_fixed_delay(tmp_path):
    inner = ObjectStore(str(tmp_path / "lake"))
    inner.put_bytes("k", b"v")
    slept: list[float] = []
    flaky = FaultInjectingStore(
        inner, faults={"get": FaultSpec(delay_s=0.01)}, sleep=slept.append
    )
    assert flaky.get_bytes("k") == b"v"
    assert flaky.get_bytes("k") == b"v"
    assert slept == [0.01, 0.01]
    assert flaky.delays["get"] == 2
    assert flaky.delayed_s["get"] == pytest.approx(0.02)
    assert flaky.injected["get"] == 0  # delays are not faults


def test_latency_jitter_is_seeded_and_applies_to_faulting_calls(tmp_path):
    inner = ObjectStore(str(tmp_path / "lake"))
    inner.put_bytes("k", b"v")

    def build():
        slept: list[float] = []
        store = FaultInjectingStore(
            inner,
            seed=5,
            faults={
                "get": FaultSpec(
                    fail_after=0, delay_s=0.005, delay_jitter_s=0.01
                )
            },
            sleep=slept.append,
        )
        return store, slept

    flaky, slept = build()
    for _ in range(3):
        with pytest.raises(InjectedFault):
            flaky.get_bytes("k")  # the slow store is slow even when it fails
    assert len(slept) == 3
    assert all(0.005 <= s < 0.015 for s in slept)
    assert len(set(slept)) > 1  # jitter actually varies
    # determinism: same seed, same call sequence -> identical delays
    flaky2, slept2 = build()
    for _ in range(3):
        with pytest.raises(InjectedFault):
            flaky2.get_bytes("k")
    assert slept2 == slept


def test_ops_without_delay_spec_run_clean(tmp_path):
    inner = ObjectStore(str(tmp_path / "lake"))
    slept: list[float] = []
    flaky = FaultInjectingStore(
        inner, faults={"get": FaultSpec(delay_s=0.5)}, sleep=slept.append
    )
    flaky.put_bytes("k", b"v")  # put has no spec: no delay, no fault
    assert slept == []
    assert flaky.get_bytes("k") == b"v"
    assert slept == [0.5]


# --- UI client: Retry-After + degraded states ---------------------------------


class _Resp:
    def __init__(self, status_code, body=None, headers=None):
        self.status_code = status_code
        self._body = body or {}
        self.headers = headers or {}

    def json(self):
        return self._body

    def raise_for_status(self):
        if self.status_code >= 400:
            raise AssertionError(
                f"{self.status_code} should have been mapped before "
                "raise_for_status"
            )


def test_api_client_honors_retry_after_on_429(monkeypatch):
    import requests

    from cobalt_smart_lender_ai_tpu.ui.core import ApiClient

    sleeps: list[float] = []
    responses = [
        _Resp(429, {"error": "shed"}, {"Retry-After": "2"}),
        _Resp(429, {"error": "shed"}, {"Retry-After": "2"}),
        _Resp(200, {"prob_default": 0.25}),
    ]
    monkeypatch.setattr(requests, "post", lambda url, **kw: responses.pop(0))
    client = ApiClient("http://x", retries=3, backoff_s=0.2, sleep=sleeps.append)
    assert client.predict({})["prob_default"] == 0.25
    assert sleeps == [2.0, 2.0]  # the server's pacing, not the client's guess


def test_api_client_caps_pessimistic_retry_after(monkeypatch):
    import requests

    from cobalt_smart_lender_ai_tpu.ui.core import ApiClient

    sleeps: list[float] = []
    responses = [
        _Resp(429, {"error": "shed"}, {"Retry-After": "600"}),
        _Resp(200, {"prob_default": 0.5}),
    ]
    monkeypatch.setattr(requests, "post", lambda url, **kw: responses.pop(0))
    client = ApiClient(
        "http://x", retries=2, sleep=sleeps.append, max_retry_after_s=5.0
    )
    assert client.predict({})["prob_default"] == 0.5
    assert sleeps == [5.0]


@pytest.mark.parametrize(
    "resp, reason",
    [
        (_Resp(429, {"error": "shed"}, {"Retry-After": "1"}), "shed"),
        (_Resp(503, {"error": "circuit_open", "detail": "x"}), "circuit_open"),
        (_Resp(504, {"error": "deadline_exceeded", "detail": "x"}), "deadline"),
    ],
)
def test_api_client_surfaces_degraded_states(monkeypatch, resp, reason):
    import requests

    from cobalt_smart_lender_ai_tpu.ui.core import ApiClient, ServiceDegraded

    attempts = {"n": 0}

    def post(url, **kw):
        attempts["n"] += 1
        return resp

    monkeypatch.setattr(requests, "post", post)
    client = ApiClient("http://x", retries=2, sleep=lambda s: None)
    with pytest.raises(ServiceDegraded) as ei:
        client.predict({})
    assert ei.value.reason == reason
    # 429 burns the retry budget; breaker-open and deadline answer immediately
    assert attempts["n"] == (2 if reason == "shed" else 1)


def test_api_client_other_503s_stay_http_errors(monkeypatch):
    """A 503 without the circuit_open code (e.g. /readyz unavailable) is not
    a degraded state the client should soften — it stays an HTTPError."""
    import requests

    from cobalt_smart_lender_ai_tpu.ui.core import ApiClient

    class _R503:
        status_code = 503
        headers: dict = {}

        def json(self):
            return {"detail": "not ready"}

        def raise_for_status(self):
            raise requests.exceptions.HTTPError("503 Service Unavailable")

    monkeypatch.setattr(requests, "post", lambda url, **kw: _R503())
    client = ApiClient("http://x", retries=2, sleep=lambda s: None)
    with pytest.raises(requests.exceptions.HTTPError):
        client.predict({})


# --- chaos soak ---------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.faults
def test_chaos_soak_zero_untyped_500s(fresh_store):
    """Threaded clients hammer every route through the real stdlib server
    while the store injects faults + latency and an operator hot-swaps
    between a good model and a poisoned artifact. The soak asserts the
    hardening contract end to end:

    - every response status is in the taxonomy (no surprise codes),
    - zero untyped 500s (every 500 body carries a machine-readable code),
    - every 429 carries Retry-After,
    - at least one hot swap succeeds and one poisoned swap rolls back
      *during* the chaos,
    - the breaker walks open -> half_open -> closed under a forced outage,
    - and the service still scores cleanly afterwards.
    """
    store, art, X = fresh_store
    _zeroed(art).save(store, "models/gbdt/v2")
    store.put_bytes("models/poison.npz", b"\x00poisoned artifact bytes")

    flaky = FaultInjectingStore(store, seed=11, faults={})
    cfg = dataclasses.replace(
        _cfg(
            request_deadline_s=10.0,
            max_in_flight=4,
            breaker_failure_threshold=3,
            breaker_reset_s=0.2,
        ),
        max_bulk_rows=64,
    )
    svc = ScorerService.from_store(flaky, cfg)  # restore before faults start
    flaky.faults["get"] = FaultSpec(rate=0.4, delay_s=0.002, delay_jitter_s=0.004)

    ok_payload = json.dumps(_valid_payload()).encode()
    requests_cycle = [
        ("/predict", ok_payload, "application/json"),
        ("/predict", b"{}", "application/json"),  # -> 422
        ("/predict_bulk_csv", _csv_bytes(X, 8), "text/csv"),
        ("/predict_bulk_csv", _csv_bytes(X, 100), "text/csv"),  # -> 413
        (
            "/feature_importance_bulk",
            json.dumps({"data": [{"a": 1}]}).encode(),
            "application/json",
        ),
        ("/feature_importance_bulk", b'{"data": []}', "application/json"),  # 400
        ("/readyz", None, ""),
    ]
    results: list[tuple[str, int, dict, dict]] = []
    results_lock = threading.Lock()
    stop = threading.Event()

    def hammer(offset: int) -> None:
        i = offset
        while not stop.is_set():
            path, data, ct = requests_cycle[i % len(requests_cycle)]
            i += 1
            try:
                status, body, headers = _request(base + path, data, ct)
            except urllib.error.URLError:
                continue  # socket-level teardown noise is not what we measure
            with results_lock:
                results.append((path, status, body, headers))

    with _running(svc) as base:
        threads = [
            threading.Thread(target=hammer, args=(i,), daemon=True)
            for i in range(6)
        ]
        for t in threads:
            t.start()

        # Operator thread (this one): hot-swap between good and poisoned
        # artifacts through the flaky store until both outcomes are observed.
        reload_ok = rolled_back = 0
        keys = itertools.cycle(
            ["models/gbdt/v2", "models/poison", "models/gbdt/model_tree"]
        )
        give_up = time.monotonic() + 60.0
        while (reload_ok < 1 or rolled_back < 1) and time.monotonic() < give_up:
            status, body, _ = _request(
                base + "/admin/reload",
                json.dumps({"model_key": next(keys)}).encode(),
            )
            if status == 200 and body.get("status") == "ok":
                reload_ok += 1
            elif status == 500 and body.get("error") == "reload_failed":
                rolled_back += 1
            elif status == 503:  # breaker open: wait out the reset timeout
                time.sleep(0.25)
            time.sleep(0.01)

        stop.set()
        for t in threads:
            t.join(timeout=30)

        # Deterministic shed probe: wait for in-flight stragglers to drain,
        # fill every admission slot, and the next request must be 429.
        drain_by = time.monotonic() + 10.0
        while (
            svc.admission.stats()["in_flight"] > 0
            and time.monotonic() < drain_by
        ):
            time.sleep(0.02)
        slots = []
        for _ in range(4):
            cm = svc.admission.admit()
            try:
                cm.__enter__()
            except RequestShed:
                break  # a straggler still holds a slot: cap already reached
            slots.append(cm)
        shed_status, shed_body, shed_headers = _request(
            base + "/predict", ok_payload
        )
        for cm in slots:
            cm.__exit__(None, None, None)

        # Stabilize: faults off, drive reloads until the breaker has closed
        # and a reload succeeds (the mixed phase may have left it open).
        del flaky.faults["get"]
        recover_by = time.monotonic() + 30.0
        while True:
            assert time.monotonic() < recover_by, "breaker never re-closed"
            try:
                if (
                    svc.reload_from_store()["status"] == "ok"
                    and svc.store_breaker.state == "closed"
                ):
                    break
            except CircuitOpenError:
                pass  # still open: wait out the reset timeout
            time.sleep(0.05)

        # Forced outage: breaker must walk open -> half_open -> closed.
        flaky.faults["get"] = FaultSpec(fail_after=0)
        mark = len(svc.store_breaker.transitions)
        for _ in range(3):
            status, body, _ = _request(base + "/admin/reload", b"{}")
            assert status == 500 and body["error"] == "reload_failed"
        assert svc.store_breaker.state == "open"
        status, body, headers = _request(base + "/admin/reload", b"{}")
        assert status == 503 and body["error"] == "circuit_open"
        assert "Retry-After" in headers
        time.sleep(0.25)  # reset timeout (real clock: the server owns it)
        del flaky.faults["get"]
        status, body, _ = _request(base + "/admin/reload", b"{}")
        assert status == 200 and body["status"] == "ok"
        assert svc.store_breaker.transitions[mark:] == [
            "open",
            "half_open",
            "closed",
        ]

        # Recovery: chaos over, the service serves cleanly.
        final_status, final_body, _ = _request(base + "/predict", ok_payload)

    # -- the hardening contract over everything observed -----------------------
    assert shed_status == 429 and shed_body["error"] == "shed"
    assert int(shed_headers["Retry-After"]) >= 1
    assert reload_ok >= 1, "no hot swap succeeded during chaos"
    assert rolled_back >= 1, "no poisoned swap rolled back during chaos"
    assert final_status == 200
    assert 0.0 <= final_body["prob_default"] <= 1.0

    assert len(results) > 50, "soak produced too little traffic to mean much"
    allowed = {200, 400, 413, 422, 429, 500, 503, 504}
    for path, status, body, headers in results:
        assert status in allowed, (path, status, body)
        if status == 500:
            # THE headline assertion: a 500 without a typed code is a bug
            # escape, not a policy decision.
            assert "error" in body, (path, body)
        if status == 429:
            assert "Retry-After" in headers, (path, headers)
    statuses = {s for _, s, _, _ in results}
    assert 200 in statuses  # scoring kept working under chaos
    assert 413 in statuses and 422 in statuses  # typed rejections observed
