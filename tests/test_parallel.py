"""Mesh-parallel tests on the 8-device virtual CPU backend: dp-sharded GBDT
training parity, the CV x HPO fan-out, and RFE feature selection."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.datasets import make_classification
from sklearn.metrics import roc_auc_score

from cobalt_smart_lender_ai_tpu.config import (
    GBDTConfig,
    MeshConfig,
    RFEConfig,
    TuneConfig,
)
from cobalt_smart_lender_ai_tpu.models.gbdt import (
    GBDTHyperparams,
    fit_binned,
    predict_margin,
)
from cobalt_smart_lender_ai_tpu.ops.binning import compute_bin_edges, transform
from cobalt_smart_lender_ai_tpu.parallel import (
    cross_validate_gbdt,
    fit_binned_dp,
    make_mesh,
    predict_margin_dp,
    randomized_search,
    rfe_select,
    stratified_kfold_masks,
)


@pytest.fixture(scope="module")
def small_binned():
    X, y = make_classification(
        n_samples=2003, n_features=12, n_informative=5, random_state=0
    )  # odd N exercises dp padding
    X = jnp.asarray(X, jnp.float32)
    spec = compute_bin_edges(X, n_bins=32)
    return transform(spec, X), jnp.asarray(y), np.asarray(y)


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_dp_sharded_fit_matches_single_device(small_binned):
    bins, y, _ = small_binned
    hp = GBDTHyperparams.from_config(GBDTConfig(n_estimators=20, max_depth=3))
    rng = jax.random.PRNGKey(0)
    mesh = make_mesh(MeshConfig(hp=1))
    kw = dict(n_trees_cap=20, depth_cap=3, n_bins=32)
    f_dp = fit_binned_dp(mesh, bins, y, None, None, hp, rng, **kw)
    # Same algorithm on one device: dp (>1 devices) builds direct histograms
    # so psum-reduced split decisions stay bit-identical; sibling subtraction
    # is a single-device-axis fast path (models/gbdt.py hist_subtract).
    f_1 = fit_binned(
        bins, y, jnp.ones(bins.shape[0]), jnp.ones(bins.shape[1], bool), hp, rng,
        hist_subtract=False, **kw
    )
    # psum-reduced histograms must reproduce single-device split decisions
    np.testing.assert_array_equal(np.asarray(f_dp.feature), np.asarray(f_1.feature))
    np.testing.assert_array_equal(np.asarray(f_dp.thr_bin), np.asarray(f_1.thr_bin))
    m_dp = predict_margin_dp(mesh, f_dp, bins, use_binned=True)
    m_1 = predict_margin(f_1, bins, use_binned=True)
    np.testing.assert_allclose(np.asarray(m_dp), np.asarray(m_1), atol=1e-4)


def test_stratified_kfold_masks():
    y = np.array([0] * 70 + [1] * 30)
    masks = stratified_kfold_masks(y, 3, seed=0)
    assert masks.shape == (3, 100)
    assert masks.sum(axis=0).tolist() == [1] * 100  # exact partition
    for m in masks:
        pos_rate = y[m].mean()
        assert 0.2 < pos_rate < 0.4  # stratification preserved


def test_cross_validate_fanout(small_binned):
    bins, y, y_np = small_binned
    mesh = make_mesh(MeshConfig(hp=4))
    cands = [
        GBDTHyperparams.from_config(GBDTConfig(n_estimators=15, max_depth=3)),
        GBDTHyperparams.from_config(GBDTConfig(n_estimators=15, max_depth=3, learning_rate=0.01)),
    ]
    hps = jax.tree.map(lambda *xs: jnp.stack(xs), *cands)
    val_masks = jnp.asarray(stratified_kfold_masks(y_np, 3, seed=1))
    aucs = cross_validate_gbdt(
        mesh,
        bins,
        y,
        hps,
        val_masks,
        jax.random.PRNGKey(0),
        n_trees_cap=15,
        depth_cap=3,
        n_bins=32,
    )
    assert aucs.shape == (2, 3)
    assert float(aucs.min()) > 0.5  # all folds learn something
    # the lr=0.3 candidate should beat lr=0.01 at 15 trees
    assert float(aucs[0].mean()) > float(aucs[1].mean())


def test_cross_validate_padding_parity(small_binned):
    """dp-padded rows (N % dp != 0) must carry zero training weight (ADVICE
    round-1 medium finding). Exact invariance check: the internal padding of
    an N=2003 run must be bitwise-equivalent to explicitly passing the padded
    rows with sample_weight 0 — same mesh, same RNG streams, so any leak of
    padding into training/validation breaks exact equality."""
    bins, y, y_np = small_binned
    N = bins.shape[0]
    dp = 8
    assert N % dp != 0  # the scenario under test
    mesh = make_mesh(MeshConfig(hp=1))
    hp = GBDTHyperparams.from_config(GBDTConfig(n_estimators=10, max_depth=3))
    hps = jax.tree.map(lambda a: a[None], hp)
    val_masks = jnp.asarray(stratified_kfold_masks(y_np, 2, seed=3))
    rng = jax.random.PRNGKey(5)
    kw = dict(n_trees_cap=10, depth_cap=3, n_bins=32)
    aucs_internal = cross_validate_gbdt(
        mesh, bins, y, hps, val_masks, rng, **kw
    )
    pad = (-N) % dp
    bins_x = jnp.concatenate([bins, jnp.zeros((pad, bins.shape[1]), bins.dtype)])
    y_x = jnp.concatenate([y, jnp.zeros((pad,), y.dtype)])
    val_x = jnp.concatenate([val_masks, jnp.zeros((2, pad), val_masks.dtype)], axis=1)
    sw_x = jnp.concatenate([jnp.ones((N,)), jnp.zeros((pad,))])
    aucs_explicit = cross_validate_gbdt(
        mesh, bins_x, y_x, hps, val_x, rng, sample_weight=sw_x, **kw
    )
    np.testing.assert_array_equal(np.asarray(aucs_internal), np.asarray(aucs_explicit))


def test_bucketed_dispatch_matches_joint_dispatch(small_binned):
    """Depth-bucketed cross_validate dispatches with global cand_ids must
    reproduce the joint dispatch's scores exactly — including the
    subsample/colsample RNG streams (subsample < 1 exercises them)."""
    from cobalt_smart_lender_ai_tpu.parallel.tune import stack_candidates

    bins, y, y_np = small_binned
    mesh = make_mesh(MeshConfig(hp=1))
    cands = [
        {"n_estimators": 10, "max_depth": 2, "subsample": 0.7},
        {"n_estimators": 10, "max_depth": 4, "subsample": 0.7},
        {"n_estimators": 15, "max_depth": 2, "subsample": 0.9},
    ]
    base = GBDTConfig(n_bins=32)
    masks = jnp.asarray(stratified_kfold_masks(y_np, 2, seed=0))
    rng = jax.random.PRNGKey(3)

    hps, tc, dc = stack_candidates(cands, base)
    joint = np.asarray(
        cross_validate_gbdt(
            mesh, bins, y, hps, masks, rng, n_trees_cap=tc, depth_cap=dc, n_bins=32
        )
    )
    bucketed = np.zeros_like(joint)
    for idxs in ([0, 2], [1]):  # the depth buckets
        hps_b, tc_b, dc_b = stack_candidates([cands[i] for i in idxs], base)
        aucs = cross_validate_gbdt(
            mesh, bins, y, hps_b, masks, rng,
            n_trees_cap=tc_b, depth_cap=dc_b, n_bins=32,
            cand_ids=jnp.asarray(idxs, jnp.int32),
        )
        bucketed[idxs] = np.asarray(aucs)
    np.testing.assert_allclose(bucketed, joint, atol=1e-6)


def test_chunked_cv_matches_single_dispatch(small_binned):
    """Tree-chunked fan-out dispatches (margins carried between them) must be
    numerically identical to the single joint dispatch — same RNG streams
    via global tree offsets, same traced n_estimators mask."""
    from cobalt_smart_lender_ai_tpu.parallel.tune import stack_candidates

    bins, y, y_np = small_binned
    mesh = make_mesh(MeshConfig(hp=2))
    cands = [
        {"n_estimators": 9, "max_depth": 3, "subsample": 0.8},
        {"n_estimators": 12, "max_depth": 3, "subsample": 0.7},
        {"n_estimators": 5, "max_depth": 2},
    ]
    hps, tc, dc = stack_candidates(cands, GBDTConfig(n_bins=32))
    masks = jnp.asarray(stratified_kfold_masks(y_np, 2, seed=0))
    kw = dict(n_trees_cap=tc, depth_cap=dc, n_bins=32)
    one = cross_validate_gbdt(
        mesh, bins, y, hps, masks, jax.random.PRNGKey(7), **kw
    )
    chunked = cross_validate_gbdt(
        mesh, bins, y, hps, masks, jax.random.PRNGKey(7), chunk_trees=5, **kw
    )
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(one), atol=1e-6)


def test_cv_auc_invariant_to_depth_cap(small_binned):
    """A candidate's CV AUC must not depend on the structural depth_cap it
    is batched under (levels beyond its traced max_depth are forced
    trivial) — the invariant that makes the depth-bucketed search dispatch
    score-preserving."""
    bins, y, y_np = small_binned
    mesh = make_mesh(MeshConfig(hp=1))
    hp = GBDTHyperparams.from_config(
        GBDTConfig(n_estimators=10, max_depth=2, n_bins=32)
    )
    hps = jax.tree.map(lambda x: jnp.stack([x]), hp)
    masks = jnp.asarray(stratified_kfold_masks(y_np, 2, seed=0))
    kw = dict(n_trees_cap=10, n_bins=32)
    a2 = cross_validate_gbdt(
        mesh, bins, y, hps, masks, jax.random.PRNGKey(0), depth_cap=2, **kw
    )
    a4 = cross_validate_gbdt(
        mesh, bins, y, hps, masks, jax.random.PRNGKey(0), depth_cap=4, **kw
    )
    np.testing.assert_allclose(np.asarray(a2), np.asarray(a4), atol=1e-6)


def test_randomized_search_end_to_end(small_binned):
    _, _, y_np = small_binned
    X, y = make_classification(
        n_samples=2003, n_features=12, n_informative=5, random_state=0
    )
    X = X.astype(np.float32)
    res = randomized_search(
        X,
        y,
        GBDTConfig(n_bins=32),
        TuneConfig(
            n_iter=4,
            cv_folds=2,
            param_space={"n_estimators": (10, 20), "max_depth": (2, 3)},
        ),
        make_mesh(MeshConfig(hp=2)),
    )
    assert res.best_score_ == max(res.cv_results_["mean_test_score"])
    assert set(res.best_params_) == {"n_estimators", "max_depth"}
    # depth-bucketed dispatch must fill every candidate's split scores
    split = res.cv_results_["split_test_scores"]
    assert split.shape == (4, 2) and (split > 0.5).all()
    p = np.asarray(res.best_estimator_.predict_proba(X)[:, 1])
    assert roc_auc_score(y, p) > 0.9


def test_rfe_keeps_signal_features():
    rng = np.random.default_rng(1)
    n = 2000
    signal = rng.normal(size=(n, 3)).astype(np.float32)
    noise = rng.normal(size=(n, 9)).astype(np.float32)
    y = ((signal[:, 0] + signal[:, 1] - signal[:, 2]) > 0).astype(np.int64)
    X = np.concatenate([signal, noise], axis=1)
    res = rfe_select(X, y, RFEConfig(n_select=3, step=2, n_estimators=15, max_depth=3))
    assert res.n_features_ == 3
    assert set(np.flatnonzero(res.support_)) == {0, 1, 2}
    assert (res.ranking_[res.support_] == 1).all()
    assert res.ranking_.max() > 1
    assert res.cv_scores_ is None  # plain RFE carries no CV results


def test_rfecv_scores_and_held_out_auc():
    """CV-scored elimination (the reference's RFECV exploration path,
    notebook cell 13): every surviving count gets a mean fold AUC, the chosen
    support maximizes it, and the selection is at least as good as plain
    RFE's on held-out data."""
    from sklearn.metrics import roc_auc_score

    from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTClassifier

    rng = np.random.default_rng(5)
    n = 3000
    signal = rng.normal(size=(n, 4)).astype(np.float32)
    noise = rng.normal(size=(n, 12)).astype(np.float32)
    y = ((signal[:, 0] + signal[:, 1] - 0.5 * signal[:, 2] + 0.3 * signal[:, 3]
          + rng.normal(scale=0.4, size=n)) > 0).astype(np.int64)
    X = np.concatenate([signal, noise], axis=1)
    Xtr, Xte, ytr, yte = X[:2400], X[2400:], y[:2400], y[2400:]

    cfg = RFEConfig(n_select=2, step=5, n_estimators=20, max_depth=3)
    plain = rfe_select(Xtr, ytr, cfg)
    cv = rfe_select(Xtr, ytr, cfg, cv_folds=3)

    # RFECV semantics: scores recorded at the full set, every step-5
    # survivor count, and the floor; winner maximizes mean fold AUC.
    assert cv.cv_scores_ is not None and 16 in cv.cv_scores_ and 2 in cv.cv_scores_
    assert cv.n_features_ == max(
        (n_feat for n_feat in cv.cv_scores_), key=lambda n_feat: (cv.cv_scores_[n_feat], -n_feat)
    )
    assert cv.n_features_ >= 2
    # The CV-chosen support must not lose to plain RFE's floor count on
    # held-out AUC (it may tie when both recover the planted signal).
    def fit_auc(support):
        model = GBDTClassifier(n_estimators=40, max_depth=3, n_bins=32).fit(
            Xtr[:, support], ytr
        )
        return roc_auc_score(yte, np.asarray(model.predict_proba(Xte[:, support])[:, 1]))

    assert fit_auc(cv.support_) >= fit_auc(plain.support_) - 0.01


@pytest.mark.parametrize("steps", [None, 0])  # device-stepped / host-stepped
def test_hist_subtract_false_gives_cross_mesh_identical_rfe(steps):
    """The GBDTConfig/RFEConfig ``hist_subtract=False`` escape hatch must make
    a single-device run bit-identical to a dp>1 run of the same config+seed —
    the advertised cross-mesh reproducibility contract (the knob has to reach
    the fan-out loops — including the host-stepped fit_binned_dp branch —
    not just GBDTClassifier.fit)."""
    rng = np.random.default_rng(7)
    X = rng.normal(size=(600, 10)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 3] + rng.normal(0, 1, 600) > 0).astype(np.int32)
    cfg = RFEConfig(
        n_select=4, step=2, n_estimators=10, max_depth=3, hist_subtract=False,
        steps_per_dispatch=steps,
    )
    res_1 = rfe_select(
        X, y, cfg,
        mesh=make_mesh(MeshConfig(dp=1, hp=1), devices=jax.devices()[:1]),
    )
    res_dp = rfe_select(
        X, y, cfg,
        mesh=make_mesh(MeshConfig(dp=4, hp=1), devices=jax.devices()[:4]),
    )
    np.testing.assert_array_equal(res_1.support_, res_dp.support_)
    np.testing.assert_array_equal(res_1.ranking_, res_dp.ranking_)


def test_hist_subtraction_quality_matches_direct(small_binned):
    """Sibling subtraction (the single-device fast path) may flip near-tie
    splits vs direct histograms, but the fitted model's quality must be
    equivalent: same-regime train AUC and near-identical margins."""
    from cobalt_smart_lender_ai_tpu.ops.metrics import roc_auc

    bins, y, y_np = small_binned
    hp = GBDTHyperparams.from_config(GBDTConfig(n_estimators=25, max_depth=5))
    kw = dict(n_trees_cap=25, depth_cap=5, n_bins=32)
    sw = jnp.ones(bins.shape[0])
    fm = jnp.ones(bins.shape[1], bool)
    rng = jax.random.PRNGKey(3)
    f_sub = fit_binned(bins, y, sw, fm, hp, rng, hist_subtract=True, **kw)
    f_dir = fit_binned(bins, y, sw, fm, hp, rng, hist_subtract=False, **kw)
    yf = jnp.asarray(y_np, jnp.float32)
    auc_sub = float(roc_auc(yf, predict_margin(f_sub, bins, use_binned=True)))
    auc_dir = float(roc_auc(yf, predict_margin(f_dir, bins, use_binned=True)))
    assert abs(auc_sub - auc_dir) < 0.005
    assert auc_sub > 0.9


def test_budget_auto_chunk_derivation(tmp_path, monkeypatch):
    """The dispatch-budget model must reproduce the calibration points' safe
    chunk sizes: whole fits for tiny work, the measured-safe 1-2 rounds at
    the full-table depth-9 bucket, and — under the deliberately conservative
    A_LEVEL — a 130k-row depth-9 chunk safely below the crashed 50 while
    keeping the estimated dispatch inside the budget. (Empty calibration
    store pinned: the assertions are about the MODEL, and this machine's
    real store may hold measured ratios for these exact shape buckets.)"""
    from cobalt_smart_lender_ai_tpu.parallel import budget
    from cobalt_smart_lender_ai_tpu.parallel.budget import (
        DISPATCH_BUDGET_S,
        auto_chunk_trees,
        est_tree_seconds,
        resolve_chunk_trees,
    )

    monkeypatch.setattr(
        budget, "_CALIBRATION_PATH", str(tmp_path / "empty.json")
    )

    assert (
        auto_chunk_trees(300, n_rows=2000, n_feats=12, n_bins=64, depth=3)
        is None
    )
    big = auto_chunk_trees(
        300, n_rows=2_300_000, n_feats=20, n_bins=255, depth=9, n_jobs=33
    )
    assert 1 <= big <= 3
    mid = auto_chunk_trees(
        300, n_rows=130_000, n_feats=20, n_bins=255, depth=9, n_jobs=33
    )
    assert 5 <= mid <= 45
    # Estimated dispatch wall respects the budget (and so the ~60s kill).
    assert (
        est_tree_seconds(130_000, 20, 255, 9, 33) * mid
        <= DISPATCH_BUDGET_S + 1.0
    )
    shape = dict(n_trees=300, n_rows=10, n_feats=2, n_bins=4, depth=2)
    assert resolve_chunk_trees(7, **shape) == 7
    assert resolve_chunk_trees(None, **shape) is None
    assert resolve_chunk_trees("auto", **shape) is None  # tiny => one dispatch


def test_dispatch_wall_calibration_store(tmp_path, monkeypatch):
    """Measured walls feed back into chunk derivation: a shape bucket whose
    dispatches measured ~half the model's estimate doubles the auto chunk
    (clamped to CALIBRATION_CLAMP so one sample can never push a dispatch
    past the kill threshold), and an unwritable store degrades silently."""
    from cobalt_smart_lender_ai_tpu.parallel import budget

    monkeypatch.setattr(
        budget, "_CALIBRATION_PATH", str(tmp_path / "walls.json")
    )
    shape = dict(n_rows=130_000, n_feats=20, n_bins=255, depth=9, n_jobs=33)
    base = budget.auto_chunk_trees(300, **shape)
    assert budget.calibration_factor(**shape) == 1.0  # no samples yet

    t_model = budget.est_tree_seconds(**shape)
    # Three runs measured at half the model's s/tree.
    for _ in range(3):
        budget.record_dispatch_walls(
            **shape, n_trees=10, wall_s=10 * t_model * 0.5
        )
    assert abs(budget.calibration_factor(**shape) - 0.5) < 0.05
    assert budget.auto_chunk_trees(300, **shape) >= int(1.9 * base)

    # Clamp: an absurdly fast measurement cannot push beyond the band.
    for _ in range(8):
        budget.record_dispatch_walls(
            **shape, n_trees=10, wall_s=10 * t_model * 0.01
        )
    assert budget.calibration_factor(**shape) == budget.CALIBRATION_CLAMP[0]

    # A different shape bucket is untouched.
    other = dict(shape, depth=5)
    assert budget.calibration_factor(**other) == 1.0

    # Unwritable store: best-effort no-op, never raises.
    monkeypatch.setattr(
        budget, "_CALIBRATION_PATH", "/proc/definitely/not/writable.json"
    )
    budget.record_dispatch_walls(**shape, n_trees=10, wall_s=1.0)
    assert budget.calibration_factor(**shape) == 1.0


def test_rfe_device_steps_match_host_loop():
    """The on-device K-step elimination (round-4 default) must reproduce the
    host-stepped loop exactly — same support, same ranking — for K covering
    the whole schedule, for K=2 (multi-dispatch, inert tail steps), and on a
    multi-device mesh."""
    rng = np.random.default_rng(9)
    n = 1800
    signal = rng.normal(size=(n, 3)).astype(np.float32)
    noise = rng.normal(size=(n, 8)).astype(np.float32)
    y = ((signal[:, 0] + signal[:, 1] - signal[:, 2]) > 0).astype(np.int64)
    X = np.concatenate([signal, noise], axis=1)
    base = RFEConfig(n_select=3, step=2, n_estimators=12, max_depth=3)

    host = rfe_select(X, y, dataclasses.replace(base, steps_per_dispatch=0))
    dev = rfe_select(X, y, base)  # auto K: whole schedule, one dispatch
    np.testing.assert_array_equal(host.support_, dev.support_)
    np.testing.assert_array_equal(host.ranking_, dev.ranking_)

    dev2 = rfe_select(X, y, dataclasses.replace(base, steps_per_dispatch=2))
    np.testing.assert_array_equal(host.support_, dev2.support_)
    np.testing.assert_array_equal(host.ranking_, dev2.ranking_)

    mesh = make_mesh(MeshConfig())
    host_m = rfe_select(
        X, y, dataclasses.replace(base, steps_per_dispatch=0), mesh=mesh
    )
    dev_m = rfe_select(X, y, base, mesh=mesh)
    np.testing.assert_array_equal(host_m.support_, dev_m.support_)
    np.testing.assert_array_equal(host_m.ranking_, dev_m.ranking_)


def test_rfecv_device_steps_match_host_loop():
    """CV-scored elimination through the device-stepped loop: the per-count
    scores and the winning support must match the host-stepped run (scoring
    never influences which feature drops, only which count wins)."""
    rng = np.random.default_rng(3)
    n = 1500
    signal = rng.normal(size=(n, 3)).astype(np.float32)
    noise = rng.normal(size=(n, 6)).astype(np.float32)
    y = ((signal[:, 0] - signal[:, 1] + 0.5 * signal[:, 2]) > 0).astype(
        np.int64
    )
    X = np.concatenate([signal, noise], axis=1)
    base = RFEConfig(n_select=2, step=3, n_estimators=10, max_depth=3)
    host = rfe_select(
        X, y, dataclasses.replace(base, steps_per_dispatch=0), cv_folds=2
    )
    dev = rfe_select(X, y, base, cv_folds=2)
    assert host.cv_scores_ is not None and dev.cv_scores_ is not None
    assert set(host.cv_scores_) == set(dev.cv_scores_)
    for k in host.cv_scores_:
        assert host.cv_scores_[k] == pytest.approx(dev.cv_scores_[k], abs=1e-6)
    np.testing.assert_array_equal(host.support_, dev.support_)


def test_rfe_chunked_refits_match_single_dispatch():
    """RFEConfig.chunk_trees routes single-device refits through
    fit_binned_chunked (margin-carried); the selected features and rankings
    must be identical to the one-dispatch fit."""
    rng = np.random.default_rng(4)
    n = 1500
    signal = rng.normal(size=(n, 3)).astype(np.float32)
    noise = rng.normal(size=(n, 7)).astype(np.float32)
    y = ((signal[:, 0] - signal[:, 1] + signal[:, 2]) > 0).astype(np.int64)
    X = np.concatenate([signal, noise], axis=1)
    base = RFEConfig(n_select=3, step=2, n_estimators=12, max_depth=3)
    plain = rfe_select(X, y, base)
    chunked = rfe_select(
        X, y, dataclasses.replace(base, chunk_trees=5)
    )
    np.testing.assert_array_equal(plain.support_, chunked.support_)
    np.testing.assert_array_equal(plain.ranking_, chunked.ranking_)


def test_fit_binned_dp_chunked_matches_unchunked(small_binned):
    """Chunked dp fit (margin carried, row-sharded) must be bit-identical to
    the one-dispatch dp fit — same global tree indices drive the RNG streams
    and the n_estimators mask."""
    from cobalt_smart_lender_ai_tpu.parallel.sharded import (
        fit_binned_dp,
        fit_binned_dp_chunked,
    )

    from cobalt_smart_lender_ai_tpu.parallel.mesh import make_mesh

    bins, y, _ = small_binned
    mesh = make_mesh(MeshConfig())
    hp = GBDTHyperparams.from_config(
        GBDTConfig(n_estimators=8, max_depth=3, n_bins=32)
    )
    kw = dict(n_trees_cap=8, depth_cap=3, n_bins=32)
    rng = jax.random.PRNGKey(9)
    whole = fit_binned_dp(mesh, bins, y, None, None, hp, rng, **kw)
    chunked = fit_binned_dp_chunked(
        mesh, bins, y, None, None, hp, rng, chunk_trees=3, **kw
    )
    for a, b in zip(jax.tree.leaves(whole), jax.tree.leaves(chunked)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
