"""Multi-host distributed layer (`parallel/distributed.py`, SURVEY §5.8):
process bootstrap is a single-host no-op, the real 2-process bootstrap wires
two CPU processes into one runtime, and the topology-aware global mesh drives
the same psum-reduced training paths as the plain mesh."""

import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cobalt_smart_lender_ai_tpu.config import GBDTConfig, MeshConfig
from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTHyperparams, fit_binned
from cobalt_smart_lender_ai_tpu.ops.binning import compute_bin_edges, transform
from cobalt_smart_lender_ai_tpu.parallel.distributed import (
    DistributedConfig,
    init_distributed,
    make_global_mesh,
)
from cobalt_smart_lender_ai_tpu.parallel.sharded import (
    fit_binned_dp,
    predict_margin_dp,
)


def test_init_distributed_single_host_noop():
    """With no coordinator configured this must be a no-op returning False —
    every local entry point (tests, bench, serving) relies on that."""
    assert init_distributed(DistributedConfig()) is False
    assert jax.process_count() == 1  # runtime untouched


def test_distributed_config_from_env(monkeypatch):
    monkeypatch.setenv("COORDINATOR_ADDRESS", "10.0.0.1:8476")
    monkeypatch.setenv("NUM_PROCESSES", "4")
    monkeypatch.setenv("PROCESS_ID", "2")
    cfg = DistributedConfig.from_env()
    assert cfg.coordinator_address == "10.0.0.1:8476"
    assert cfg.num_processes == 4 and cfg.process_id == 2
    monkeypatch.delenv("COORDINATOR_ADDRESS")
    monkeypatch.delenv("NUM_PROCESSES")
    monkeypatch.delenv("PROCESS_ID")
    empty = DistributedConfig.from_env()
    assert empty.coordinator_address is None and empty.num_processes is None


def test_two_process_bootstrap_and_psum():
    """The real multi-process path: two spawned CPU processes call
    `init_distributed` through the pod env contract (COORDINATOR_ADDRESS /
    NUM_PROCESSES / PROCESS_ID), form one 2-device runtime, build the global
    mesh, and psum across process boundaries — `jax.distributed.initialize`
    (parallel/distributed.py:80-84) actually executes, not the no-op."""
    with socket.socket() as s:  # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    worker = Path(__file__).with_name("_dist_worker.py")
    procs = []
    try:
        for rank in range(2):
            env = dict(
                os.environ,
                JAX_PLATFORMS="cpu",
                COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                NUM_PROCESSES="2",
                PROCESS_ID=str(rank),
                # The workers import the package by path, not install — their
                # sys.path[0] is tests/, so the repo root must be explicit.
                PYTHONPATH=str(Path(__file__).resolve().parent.parent)
                + (os.pathsep + os.environ["PYTHONPATH"] if os.environ.get("PYTHONPATH") else ""),
            )
            # The workers must each see ONE local CPU device so the global
            # mesh truly spans processes; drop the 8-device virtualization.
            env.pop("XLA_FLAGS", None)
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(worker)],
                    env=env,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )
        outs = [p.communicate(timeout=300)[0] for p in procs]
    finally:
        # One rank dying leaves the other blocked in distributed init
        # forever; never leak it past the test.
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(
        "Multiprocess computations aren't implemented on the CPU backend" in out
        for out in outs
    ):
        # The bootstrap itself succeeded (two processes formed one runtime and
        # reached the collective); this jaxlib's CPU backend simply cannot
        # EXECUTE cross-process computations. Newer jaxlibs can — skip, don't
        # fail, on the capability gap.
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"RANK{rank}_PSUM_OK=3.0" in out, out


def test_global_mesh_shape_and_axes():
    mesh = make_global_mesh(MeshConfig(hp=2))
    assert mesh.axis_names == ("hp", "dp")
    assert mesh.devices.shape == (2, 4)
    # every device appears exactly once
    assert len({d.id for d in mesh.devices.flat}) == 8
    with pytest.raises(ValueError):
        make_global_mesh(MeshConfig(hp=3))


def test_global_mesh_trains_identically_to_single_device():
    """dp-sharded fit over the topology-ordered mesh must be bit-identical
    to the unsharded fit — the device reordering must not change semantics."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(512, 12)).astype(np.float32)
    y = (X[:, 0] - X[:, 1] + rng.logistic(size=512) * 0.5 > 0).astype(np.int32)
    spec = compute_bin_edges(jnp.asarray(X), n_bins=16)
    bins = transform(spec, jnp.asarray(X))
    hp = GBDTHyperparams.from_config(
        GBDTConfig(n_estimators=8, max_depth=3, n_bins=16, subsample=1.0)
    )
    kw = dict(n_trees_cap=8, depth_cap=3, n_bins=16)
    # Same algorithm on both sides: dp (>1 devices) builds direct histograms
    # (models/gbdt.py hist_subtract), so the single-device reference must too.
    ref = fit_binned(
        bins, jnp.asarray(y), jnp.ones(512), jnp.ones(12, bool), hp,
        jax.random.PRNGKey(0), hist_subtract=False, **kw,
    )
    mesh = make_global_mesh(MeshConfig(hp=1))
    got = fit_binned_dp(
        mesh, bins, jnp.asarray(y), None, None, hp, jax.random.PRNGKey(0), **kw
    )
    np.testing.assert_array_equal(np.asarray(ref.feature), np.asarray(got.feature))
    np.testing.assert_array_equal(np.asarray(ref.thr_bin), np.asarray(got.thr_bin))
    m_ref = np.asarray(predict_margin_dp(mesh, got, bins, use_binned=True))
    assert np.isfinite(m_ref).all()
