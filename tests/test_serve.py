"""Serving-layer contract tests: response-shape parity with the reference's
three endpoints (`cobalt_fast_api.py:96-143`), the 20-field schema with its
two aliased names, and the stdlib HTTP adapter end-to-end over a socket."""

import io
import json
import urllib.request

import numpy as np
import pytest

from cobalt_smart_lender_ai_tpu.data import schema
from cobalt_smart_lender_ai_tpu.serve import (
    ScorerService,
    ValidationError,
    validate_single_input,
)


# serving_artifact lives in conftest.py (shared with the fastapi stub tests)


def _fast_cfg():
    """Default serving config minus the all-bucket prewarm — this module
    doesn't exercise cold-bucket tails, and the extra per-bucket compiles
    are pure tier-1 wall time."""
    from cobalt_smart_lender_ai_tpu.config import ServeConfig

    return ServeConfig(prewarm_all_buckets=False)



@pytest.fixture(scope="module")
def service(serving_artifact):
    store, _ = serving_artifact
    return ScorerService.from_store(store, _fast_cfg())


def _example_payload(aliased: bool = True) -> dict:
    vals = {
        "loan_amnt": 9.2, "term": 36.0, "installment": 5.7,
        "fico_range_low": 6.55, "last_fico_range_high": 690.0,
        "open_il_12m": 1.0, "open_il_24m": 2.0, "max_bal_bc": 5000.0,
        "num_rev_accts": 2.3, "pub_rec_bankruptcies": 0.0,
        "emp_length_num": 5.0, "earliest_cr_line_days": 8.6,
        "grade_E": 0, "home_ownership_MORTGAGE": 1,
        "verification_status_Verified": 0,
        "application_type_Joint App": 0,
        "hardship_status_BROKEN": 0, "hardship_status_COMPLETE": 0,
        "hardship_status_COMPLETED": 0, "hardship_status_No Hardship": 1,
    }
    if not aliased:
        vals["application_type_Joint_App"] = vals.pop("application_type_Joint App")
        vals["hardship_status_No_Hardship"] = vals.pop("hardship_status_No Hardship")
    return vals


# --- schema validation --------------------------------------------------------


def test_validate_accepts_aliases_and_field_names():
    row_a = validate_single_input(_example_payload(aliased=True))
    row_f = validate_single_input(_example_payload(aliased=False))
    assert row_a == row_f
    assert set(row_a) == set(schema.SERVING_FEATURES)


def test_validate_missing_field():
    bad = _example_payload()
    bad.pop("loan_amnt")
    with pytest.raises(ValidationError, match="loan_amnt"):
        validate_single_input(bad)


def test_validate_rejects_non_numeric_and_non_integer():
    bad = _example_payload()
    bad["term"] = "36 months"
    with pytest.raises(ValidationError, match="term"):
        validate_single_input(bad)
    bad2 = _example_payload()
    bad2["grade_E"] = 0.5  # int-typed field in the reference schema
    with pytest.raises(ValidationError, match="grade_E"):
        validate_single_input(bad2)


def test_validate_ignores_unknown_keys():
    extra = {**_example_payload(), "unknown_column": 1.0}
    assert set(validate_single_input(extra)) == set(schema.SERVING_FEATURES)


# --- endpoint handlers --------------------------------------------------------


def test_predict_single_response_shape(service):
    resp = service.predict_single(_example_payload())
    # exact key set of cobalt_fast_api.py:102-108
    assert set(resp) == {
        "prob_default", "shap_values", "base_value", "features", "input_row",
    }
    assert 0.0 <= resp["prob_default"] <= 1.0
    assert resp["features"] == list(schema.SERVING_FEATURES)
    assert len(resp["shap_values"]) == 20
    assert set(resp["input_row"]) == set(schema.SERVING_FEATURES)
    # SHAP additivity: sigmoid(base + sum(phis)) == prob_default
    margin = resp["base_value"] + sum(resp["shap_values"])
    prob = 1.0 / (1.0 + np.exp(-margin))
    np.testing.assert_allclose(prob, resp["prob_default"], atol=1e-4)


def test_predict_bulk_csv(service, serving_artifact):
    _, X = serving_artifact
    import pandas as pd

    df = pd.DataFrame(X[:10], columns=list(schema.SERVING_FEATURES))
    df.loc[0, "emp_length_num"] = np.nan  # must serialize as "null"
    csv_bytes = df.to_csv(index=False).encode()
    resp = service.predict_bulk_csv(csv_bytes)
    assert set(resp) == {"predictions"}
    assert len(resp["predictions"]) == 10
    for rec in resp["predictions"]:
        assert 0.0 <= rec["prob_default"] <= 1.0
    assert resp["predictions"][0]["emp_length_num"] == "null"


def test_predict_bulk_csv_missing_column(service):
    with pytest.raises(ValidationError, match="term"):
        service.predict_bulk_csv(b"loan_amnt\n1.0\n")


def test_feature_importance_bulk(service):
    resp = service.feature_importance_bulk({"data": [{"loan_amnt": 1.0}]})
    top = resp["top_features"]
    assert 0 < len(top) <= 10
    assert all(set(t) == {"feature", "importance"} for t in top)
    imps = [t["importance"] for t in top]
    assert imps == sorted(imps, reverse=True)
    assert all(t["feature"] in schema.SERVING_FEATURES for t in top)


def test_feature_importance_bulk_empty_rejected(service):
    with pytest.raises(ValidationError):
        service.feature_importance_bulk({"data": []})


def test_bulk_scoring_shape_buckets(serving_artifact):
    """Bulk scoring must pad to power-of-two row buckets: a second,
    differently-sized batch that lands in an already-compiled bucket must NOT
    compile a new program (each compile is tens of seconds on a cold
    backend), oversize requests chunk at max_batch_rows, and padding/chunking
    must not change any row's probability."""
    from cobalt_smart_lender_ai_tpu.config import ServeConfig

    store, X = serving_artifact
    # microbatch off: its warming would pre-compile the coalescing cap bucket
    # (covered in test_microbatch.py) and blur the cache-growth assertions.
    svc = ScorerService.from_store(
        store,
        ServeConfig(
            max_batch_rows=64,
            precompile_batch_buckets=(8,),
            microbatch_enabled=False,
        ),
    )
    assert svc.compiled_batch_buckets == (1, 8)  # (1,F) reuse + warmed
    p5 = svc.predict_proba(X[:5])
    assert svc.compiled_batch_buckets == (1, 8)  # 5 -> bucket 8: cache hit
    p7 = svc.predict_proba(X[:7])
    assert svc.compiled_batch_buckets == (1, 8)  # second size, same bucket
    p9 = svc.predict_proba(X[:9])  # -> bucket 16: exactly one new program
    assert svc.compiled_batch_buckets == (1, 8, 16)
    p150 = svc.predict_proba(X[:150])  # 64 + 64 + 22 -> buckets 64 and 32
    assert svc.compiled_batch_buckets == (1, 8, 16, 32, 64)
    svc.predict_proba(X[:150])
    svc.predict_proba(X[:40])
    assert svc.compiled_batch_buckets == (1, 8, 16, 32, 64)  # lifetime-bounded
    # Padding rows and chunking must be invisible in the outputs.
    np.testing.assert_allclose(p7[:5], p5, atol=1e-6)
    np.testing.assert_allclose(p150[:9], p9, atol=1e-6)
    np.testing.assert_allclose(p150[:5], p5, atol=1e-6)
    assert p150.shape == (150,)


# --- asyncio HTTP adapter end-to-end -----------------------------------------


@pytest.fixture(scope="module")
def http_server(service):
    from cobalt_smart_lender_ai_tpu.serve.http_asyncio import make_async_server

    server = make_async_server(service, "127.0.0.1", 0)
    yield f"http://127.0.0.1:{server.port}"
    server.close()


def _post(url, body: bytes, content_type: str):
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": content_type}, method="POST"
    )
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_http_predict(http_server):
    status, resp = _post(
        http_server + "/predict",
        json.dumps(_example_payload()).encode(),
        "application/json",
    )
    assert status == 200
    assert set(resp) == {
        "prob_default", "shap_values", "base_value", "features", "input_row",
    }


def test_http_predict_422(http_server):
    status, resp = _post(http_server + "/predict", b"{}", "application/json")
    assert status == 422
    assert "missing fields" in resp["detail"]


def test_http_bulk_csv_multipart(http_server, serving_artifact):
    _, X = serving_artifact
    import pandas as pd

    csv = pd.DataFrame(X[:3], columns=list(schema.SERVING_FEATURES)).to_csv(
        index=False
    )
    boundary = "testboundary123"
    body = (
        f"--{boundary}\r\n"
        'Content-Disposition: form-data; name="file"; filename="rows.csv"\r\n'
        "Content-Type: text/csv\r\n\r\n"
        f"{csv}\r\n"
        f"--{boundary}--\r\n"
    ).encode()
    status, resp = _post(
        http_server + "/predict_bulk_csv",
        body,
        f"multipart/form-data; boundary={boundary}",
    )
    assert status == 200
    assert len(resp["predictions"]) == 3


def test_http_importance_400_on_empty(http_server):
    status, resp = _post(
        http_server + "/feature_importance_bulk",
        json.dumps({"data": []}).encode(),
        "application/json",
    )
    assert status == 400
    assert resp["detail"] == "No data provided."


def test_http_healthz_and_404(http_server):
    with urllib.request.urlopen(http_server + "/healthz") as r:
        assert r.status == 200
    status, _ = _post(http_server + "/nope", b"{}", "application/json")
    assert status == 404


# --- fastapi adapter (runs only where fastapi is installed) -------------------


def test_fastapi_adapter_if_available(service):
    pytest.importorskip("fastapi")
    from fastapi.testclient import TestClient

    from cobalt_smart_lender_ai_tpu.serve.http_fastapi import create_app

    client = TestClient(create_app(service=service))
    r = client.post("/predict", json=_example_payload())
    assert r.status_code == 200
    assert set(r.json()) == {
        "prob_default", "shap_values", "base_value", "features", "input_row",
    }
