"""Coverage for the FastAPI adapter without fastapi in the image.

fastapi cannot be installed offline, so the Dockerfile's serving path
(`serve/http_fastapi.py`) is exercised two ways:

- an AST contract test pins the pydantic `SingleInput` schema (field names,
  int/float types, the two space-containing aliases) to the canonical
  contract in `data/schema.py` — the drift the reference's pydantic model
  guards against;
- a stub-execution test installs minimal `fastapi`/`pydantic` stand-ins and
  runs `create_app` plus every route handler and the lifespan restore, so
  all adapter logic (dump-by-alias, error->status mapping, upload reading)
  executes in CI. Pydantic's own validation engine is NOT re-tested here;
  `test_serve.py::test_fastapi_adapter_if_available` covers it wherever the
  real fastapi exists.
"""

import ast
import asyncio
import sys
import types
from pathlib import Path

import numpy as np
import pytest

from cobalt_smart_lender_ai_tpu.data import schema
from cobalt_smart_lender_ai_tpu.serve.service import SINGLE_INPUT_FIELDS


def _fast_cfg():
    """Default serving config minus the all-bucket prewarm — this module
    doesn't exercise cold-bucket tails, and the extra per-bucket compiles
    are pure tier-1 wall time."""
    from cobalt_smart_lender_ai_tpu.config import ServeConfig

    return ServeConfig(prewarm_all_buckets=False)


ADAPTER = (
    Path(__file__).resolve().parent.parent
    / "cobalt_smart_lender_ai_tpu"
    / "serve"
    / "http_fastapi.py"
)


def _single_input_classdef() -> ast.ClassDef:
    tree = ast.parse(ADAPTER.read_text())
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "SingleInput":
            return node
    raise AssertionError("SingleInput class not found in http_fastapi.py")


def test_fastapi_schema_matches_serving_contract():
    """The pydantic model must carry exactly the 20 contract fields with the
    reference's int/float typing and the two aliased names."""
    cls = _single_input_classdef()
    fields: dict[str, str] = {}
    aliases: dict[str, str] = {}
    for stmt in cls.body:
        if not isinstance(stmt, ast.AnnAssign) or not isinstance(
            stmt.target, ast.Name
        ):
            continue
        name = stmt.target.id
        if name == "model_config":
            continue
        fields[name] = ast.unparse(stmt.annotation)
        if (
            isinstance(stmt.value, ast.Call)
            and getattr(stmt.value.func, "id", "") == "Field"
        ):
            for kw in stmt.value.keywords:
                if kw.arg == "alias":
                    # alias=schema.SERVING_FIELD_ALIASES[...] — resolve it
                    aliases[name] = eval(  # noqa: S307 - our own source
                        compile(ast.Expression(kw.value), "<alias>", "eval"),
                        {"schema": schema},
                    )
    assert set(fields) == set(SINGLE_INPUT_FIELDS), (
        set(fields) ^ set(SINGLE_INPUT_FIELDS)
    )
    for name, ann in fields.items():
        want = "int" if SINGLE_INPUT_FIELDS[name] in schema.SERVING_INT_FEATURES else "float"
        assert ann == want or ann.startswith(want), (name, ann)
    assert aliases == schema.SERVING_FIELD_ALIASES


# --- minimal fastapi/pydantic stand-ins ---------------------------------------


class _HTTPException(Exception):
    def __init__(self, status_code, detail="", headers=None):
        self.status_code = status_code
        self.detail = detail
        self.headers = headers


class _FieldInfo:
    def __init__(self, alias=None):
        self.alias = alias


def _Field(alias=None):
    return _FieldInfo(alias=alias)


class _BaseModel:
    """Stores constructor kwargs keyed by field name; model_dump(by_alias)
    re-keys through the class's _FieldInfo aliases, like pydantic."""

    def __init__(self, **kw):
        self._data = kw

    def __init_subclass__(cls):
        cls._aliases = {
            k: v.alias
            for k, v in vars(cls).items()
            if isinstance(v, _FieldInfo) and v.alias
        }

    def model_dump(self, by_alias=False):
        if not by_alias:
            return dict(self._data)
        al = getattr(type(self), "_aliases", {})
        return {al.get(k, k): v for k, v in self._data.items()}


class _FastAPI:
    def __init__(self, title="", lifespan=None):
        self.title = title
        self.lifespan = lifespan
        self.routes: dict[str, object] = {}  # POST routes (historical name)
        self.get_routes: dict[str, object] = {}

    def post(self, path):
        def deco(fn):
            self.routes[path] = fn
            return fn

        return deco

    def get(self, path):
        def deco(fn):
            self.get_routes[path] = fn
            return fn

        return deco


class _UploadFile:
    def __init__(self, data: bytes):
        self._data = data

    async def read(self) -> bytes:
        return self._data


class _Request:
    def __init__(self, headers=None):
        self.headers = dict(headers or {})


class _Response:
    def __init__(self, content=None, media_type=None):
        self.content = content
        self.media_type = media_type
        self.headers: dict[str, str] = {}


@pytest.fixture
def fastapi_stubbed(monkeypatch):
    fastapi_mod = types.ModuleType("fastapi")
    fastapi_mod.FastAPI = _FastAPI
    fastapi_mod.HTTPException = _HTTPException
    fastapi_mod.UploadFile = _UploadFile
    fastapi_mod.File = lambda *a, **k: None
    fastapi_mod.Request = _Request
    fastapi_mod.Response = _Response
    pydantic_mod = types.ModuleType("pydantic")
    pydantic_mod.BaseModel = _BaseModel
    pydantic_mod.ConfigDict = dict
    pydantic_mod.Field = _Field
    monkeypatch.setitem(sys.modules, "fastapi", fastapi_mod)
    monkeypatch.setitem(sys.modules, "pydantic", pydantic_mod)
    return fastapi_mod


def _payload_by_field_name() -> dict:
    vals = {}
    for field, canonical in SINGLE_INPUT_FIELDS.items():
        vals[field] = 1 if canonical in schema.SERVING_INT_FEATURES else 1.5
    return vals


def test_fastapi_adapter_routes_execute(fastapi_stubbed, serving_artifact):
    """Every route handler and the error mapping run against a real service."""
    from cobalt_smart_lender_ai_tpu.serve.http_fastapi import create_app
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    store, X = serving_artifact
    svc = ScorerService.from_store(store, _fast_cfg())
    app = create_app(service=svc)
    assert set(app.routes) == {
        "/predict",
        "/predict_bulk_csv",
        "/feature_importance_bulk",
        "/admin/reload",
        "/admin/promote",
        "/admin/rollback",
        "/admin/quarantine",
        "/admin/readmit",
        "/admin/autoscaler",
    }
    assert set(app.get_routes) == {
        "/healthz",
        "/readyz",
        "/metrics",
        "/slo",
        "/drift",
        "/debug/requests",
        "/debug/slowest",
        "/debug/trace",
        "/debug/programs",
        "/history",
        "/events",
        "/dashboard",
    }

    # health/readiness GET routes: healthy service -> ok, shap ok, 200 path
    assert app.get_routes["/healthz"]() == {"status": "ok"}
    ready_payload = app.get_routes["/readyz"]()
    assert ready_payload["shap"] == "ok" and not ready_payload["degraded"]

    # /metrics GET: valid Prometheus text over the service's registry
    from cobalt_smart_lender_ai_tpu.telemetry import parse_exposition

    scrape = app.get_routes["/metrics"]()
    assert scrape.media_type.startswith("text/plain")
    parse_exposition(scrape.content)

    # /debug/programs GET: the live program cost table payload
    progs = app.get_routes["/debug/programs"]()
    assert "programs" in progs and "totals" in progs

    # /predict happy path: the handler only needs model_dump(by_alias=True),
    # so a stand-in with the contract's two aliases drives it; the REAL
    # SingleInput's field/alias fidelity is pinned by the AST contract test
    # above (the class itself is local to create_app and, with PEP 563
    # annotations, never escapes into the handler closure).
    predict = app.routes["/predict"]

    class SingleStub(_BaseModel):
        application_type_Joint_App = _FieldInfo(
            alias=schema.SERVING_FIELD_ALIASES["application_type_Joint_App"]
        )
        hardship_status_No_Hardship = _FieldInfo(
            alias=schema.SERVING_FIELD_ALIASES["hardship_status_No_Hardship"]
        )

    # handlers are natively async (the event-loop request path); the stub
    # harness drives each coroutine on its own loop
    resp = asyncio.run(predict(SingleStub(**_payload_by_field_name())))
    assert 0.0 <= resp["prob_default"] <= 1.0
    assert len(resp["shap_values"]) == 20

    # /predict_bulk_csv: async upload read + CSV scoring.
    import pandas as pd

    df = pd.DataFrame(X[:4], columns=list(schema.SERVING_FEATURES))
    up = _UploadFile(df.to_csv(index=False).encode())
    bulk = asyncio.run(app.routes["/predict_bulk_csv"](file=up))
    assert len(bulk["predictions"]) == 4

    # /predict_bulk_csv error path -> 422, not a crash.
    with pytest.raises(_HTTPException) as ei:
        asyncio.run(app.routes["/predict_bulk_csv"](file=_UploadFile(b"loan_amnt\n1\n")))
    assert ei.value.status_code == 422

    # /feature_importance_bulk happy + empty-data 400.
    class BulkStub(_BaseModel):
        pass

    top = asyncio.run(
        app.routes["/feature_importance_bulk"](BulkStub(data=[{"a": 1.0}]))
    )
    assert top["top_features"]
    with pytest.raises(_HTTPException) as ei:
        asyncio.run(app.routes["/feature_importance_bulk"](BulkStub(data=[])))
    assert ei.value.status_code == 400

    # /admin/reload: hot swap of the currently-served key succeeds (the
    # rollback and breaker paths are covered service-level in
    # test_request_hardening.py; here the route wiring executes).
    class ReloadStub(_BaseModel):
        def __getattr__(self, name):
            try:
                return self.__dict__["_data"][name]
            except KeyError:
                raise AttributeError(name)

    result = asyncio.run(app.routes["/admin/reload"](ReloadStub(model_key=None)))
    assert result["status"] == "ok"


def test_fastapi_lifespan_restores_from_store(fastapi_stubbed, serving_artifact):
    """create_app(store_uri=...) must restore the model inside the lifespan
    hook exactly like the reference's startup S3 download."""
    from cobalt_smart_lender_ai_tpu.serve.http_fastapi import create_app

    store, X = serving_artifact
    app = create_app(store_uri=store.uri)

    async def drive():
        async with app.lifespan(app):
            row = np.asarray(X[:1], dtype=np.float32)
            # the service exists only after lifespan ran
            return app  # closure state is internal; routes prove it below

    asyncio.run(drive())
    # after lifespan, the /feature_importance_bulk route must serve
    class BulkStub(_BaseModel):
        pass

    resp = asyncio.run(
        app.routes["/feature_importance_bulk"](BulkStub(data=[{"x": 1}]))
    )
    assert resp["top_features"]
