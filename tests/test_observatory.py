"""Performance-observatory tests: program cost attribution on the forced
8-device mesh, graceful cost_analysis degradation, run-ledger round-trips
plus obs_report rendering/diffing, Perfetto counter tracks, and the debug
routes' limit/phase query validation."""

import json
import math
import urllib.error
import urllib.request

import numpy as np
import pytest

from cobalt_smart_lender_ai_tpu.telemetry.programs import (
    ProgramRegistry,
    cost_analysis_estimates,
    default_program_registry,
    peak_flops_estimate,
    set_default_program_registry,
)


@pytest.fixture()
def fresh_programs():
    """Swap in an empty process program registry; restore the old one."""
    reg = ProgramRegistry()
    prev = set_default_program_registry(reg)
    yield reg
    set_default_program_registry(prev)


# --- cost_analysis guarding ---------------------------------------------------


class _RaisingCompiled:
    def cost_analysis(self):
        raise RuntimeError("backend does not implement cost analysis")


class _NoneCompiled:
    def cost_analysis(self):
        return None


class _ListCompiled:
    def cost_analysis(self):
        return [{"flops": 12.5, "bytes accessed": 300.0}]


class _DictCompiled:
    def cost_analysis(self):
        return {"flops": 7.0, "bytes accessed": float("nan"), "other": 1}


def test_cost_analysis_estimates_guards_every_backend_shape():
    assert cost_analysis_estimates(_RaisingCompiled()) == {}
    assert cost_analysis_estimates(_NoneCompiled()) == {}
    assert cost_analysis_estimates(object()) == {}  # no method at all
    est = cost_analysis_estimates(_ListCompiled())
    assert est == {"flops": 12.5, "bytes_accessed": 300.0}
    # NaN / non-positive values are dropped, valid keys kept
    assert cost_analysis_estimates(_DictCompiled()) == {"flops": 7.0}


def test_program_handle_degrades_without_cost(fresh_programs):
    prog = fresh_programs.register("x", kind="test")
    prog.record_compile(0.5, _RaisingCompiled())
    prog.record_dispatch(0.25, count=2)
    row = prog.snapshot()
    assert row["flops"] is None
    assert row["achieved_flops_per_second"] is None
    assert row["roofline_utilization"] is None
    assert row["dispatches"] == 2 and row["dispatch_seconds"] == 0.25


def test_roofline_only_for_known_kinds(fresh_programs):
    assert peak_flops_estimate("TPU v4") == 275e12
    assert peak_flops_estimate("cpu") is None
    assert peak_flops_estimate(None) is None
    prog = fresh_programs.register(
        "y", kind="test", meta={"device_kind": "TPU v4"}
    )
    prog.record_compile(0.0, _ListCompiled())
    prog.record_dispatch(0.5)
    row = prog.snapshot()
    assert row["achieved_flops_per_second"] == pytest.approx(12.5 / 0.5)
    assert row["roofline_utilization"] == pytest.approx(25.0 / 275e12)


# --- capture on the forced multi-device mesh ---------------------------------


def test_mesh_partitioner_programs_captured(fresh_programs):
    import jax
    import jax.numpy as jnp

    from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTClassifier
    from cobalt_smart_lender_ai_tpu.parallel.partitioner import (
        make_partitioner,
    )

    assert jax.device_count() == 8  # conftest forces the virtual mesh

    rng = np.random.default_rng(3)
    X = rng.normal(size=(128, 6)).astype(np.float32)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float32)
    model = GBDTClassifier(n_estimators=4, max_depth=2, n_bins=16)
    model.fit(X, y)

    part = make_partitioner(-1)
    assert part.n_shards == 8
    fn = part.compile_margin(model.forest, X.shape[1], 128)
    out = fn(jnp.asarray(X))
    assert np.asarray(out).shape == (128,)

    table = fresh_programs.table()
    # The default kernel mode routes mesh margins through the fused
    # one-pass program (margin view); the registry row carries the same
    # shard/compile/dispatch accounting either way.
    row = next(r for r in table if r["name"].startswith("serve.mesh_fused"))
    assert row["shards"] == 8
    assert row["compiles"] == 1 and row["compile_seconds"] > 0
    assert row["dispatches"] == 1 and row["dispatch_seconds"] > 0

    # Cache hit: no second compile, but dispatches keep accumulating.
    fn2 = part.compile_margin(model.forest, X.shape[1], 128)
    fn2(jnp.asarray(X))
    row = fresh_programs.get(row["name"]).snapshot()
    assert row["compiles"] == 1 and row["dispatches"] == 2

    totals = fresh_programs.totals()
    assert totals["dispatch_seconds"] >= row["dispatch_seconds"]


def test_program_metrics_families_publish(fresh_programs):
    from cobalt_smart_lender_ai_tpu.telemetry.metrics import MetricsRegistry

    prog = fresh_programs.register("serve.fake[rows=1]", kind="serve")
    reg = MetricsRegistry()
    fresh_programs.publish(reg)
    prog.record_dispatch(0.75, count=3)
    # A program registered AFTER publish is wired into the existing sink.
    late = fresh_programs.register("serve.late[rows=2]", kind="serve")
    late.record_dispatch(0.25)
    snap = reg.snapshot()
    fam = snap["cobalt_program_dispatch_seconds_total"]
    by_label = {
        s["labels"]["program"]: s["value"] for s in fam["samples"]
    }
    assert by_label["serve.fake[rows=1]"] == pytest.approx(0.75)
    assert by_label["serve.late[rows=2]"] == pytest.approx(0.25)
    # Unknown cost estimates render as NaN, not a missing family.
    flops = snap["cobalt_program_flops"]["samples"]
    assert all(math.isnan(s["value"]) for s in flops)


# --- debug routes: /debug/programs + limit/phase validation ------------------


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=30) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


@pytest.fixture()
def observatory_server(serving_artifact, fresh_programs):
    from cobalt_smart_lender_ai_tpu.config import ServeConfig
    from cobalt_smart_lender_ai_tpu.serve.http_asyncio import make_async_server
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    store, X = serving_artifact
    svc = ScorerService.from_store(
        store,
        ServeConfig(precompile_batch_buckets=(), microbatch_enabled=False),
    )
    server = make_async_server(svc, "127.0.0.1", 0)
    yield f"http://127.0.0.1:{server.port}", svc, X
    server.close()
    svc.close()


def test_debug_programs_and_metrics_live_on_serving(observatory_server):
    base, svc, X = observatory_server
    from cobalt_smart_lender_ai_tpu.data import schema

    payload = {
        name: float(v)
        for name, v in zip(schema.SERVING_FEATURES, np.asarray(X[0]))
    }
    req = urllib.request.Request(
        base + "/predict",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        assert resp.status == 200

    status, body = _get(base + "/debug/programs")
    assert status == 200
    rows = {r["name"]: r for r in body["programs"]}
    dispatched = [r for r in rows.values() if r["dispatches"] > 0]
    assert dispatched and all(
        r["dispatch_seconds"] > 0 for r in dispatched
    )
    assert any(name.startswith("serve.fused") for name in rows)
    assert body["totals"]["dispatch_seconds"] > 0

    # The SAME table rides the service's Prometheus scrape.
    with urllib.request.urlopen(base + "/metrics", timeout=30) as resp:
        text = resp.read().decode()
    assert "cobalt_program_dispatch_seconds_total" in text
    assert "cobalt_device_mem_bytes" in text
    assert "cobalt_host_rss_bytes" in text


def test_debug_limit_and_phase_validation(observatory_server):
    base, _, _ = observatory_server
    status, body = _get(base + "/debug/requests?limit=5")
    assert status == 200
    status, body = _get(base + "/debug/requests?limit=0")
    assert status == 422
    assert "limit" in body["detail"]
    status, body = _get(base + "/debug/slowest?limit=2000")
    assert status == 422
    status, body = _get(base + "/debug/requests?phase=bogus")
    assert status == 422
    assert "phase" in body["detail"]
    status, body = _get(base + "/debug/slowest?k=3&phase=dispatch")
    assert status == 200
    assert all(
        "dispatch" in r["phases_ms"] for r in body["slowest"]
    )
    # Legacy n= alias still works alongside limit=.
    status, body = _get(base + "/debug/requests?n=2")
    assert status == 200
    assert len(body["recent"]) <= 2


# --- run ledger + obs_report -------------------------------------------------


def _fake_ledger(tmp_path, name, *, search_secs, auc, fresh_reg):
    from cobalt_smart_lender_ai_tpu.telemetry.metrics import MetricsRegistry
    from cobalt_smart_lender_ai_tpu.telemetry.runledger import RunLedger

    mreg = MetricsRegistry()
    mreg.counter(
        "cobalt_search_dispatch_seconds",
        "measured search dispatch wall",
        ("mode",),
    ).labels(mode="halving").inc(search_secs)
    prog = fresh_reg.register(
        "search.cv_runner[mode=halving,depth=5,chunk=10,bins=64]",
        kind="search",
    )
    prog.record_dispatch(search_secs * 0.95, count=4)

    ledger = RunLedger("pipeline", fingerprint="fp-abc", meta={"quick": True})
    ledger.add_stage("search", search_secs)
    ledger.add_stage("eval", 0.5)
    ledger.set("final_metrics", {"test_auc": auc, "cv_auc": auc - 0.01})
    path = str(tmp_path / name)
    doc = ledger.write(path, registry=mreg)
    return path, doc


def test_ledger_roundtrip_and_attribution(tmp_path, fresh_programs):
    from cobalt_smart_lender_ai_tpu.telemetry.runledger import load_ledger

    path, doc = _fake_ledger(
        tmp_path, "a.json", search_secs=2.0, auc=0.79,
        fresh_reg=fresh_programs,
    )
    loaded = load_ledger(path)
    assert loaded["schema"] == doc["schema"] == 1
    assert loaded["fingerprint"] == "fp-abc"
    assert loaded["stages"]["search"] == pytest.approx(2.0)
    attr = loaded["dispatch_attribution"]
    assert attr["measured_seconds"] == pytest.approx(2.0)
    assert attr["ratio"] == pytest.approx(0.95)
    assert loaded["env"]["device_count"] == 8
    names = [p["name"] for p in loaded["programs"]]
    assert "search.cv_runner[mode=halving,depth=5,chunk=10,bins=64]" in names

    bad = tmp_path / "not_a_ledger.json"
    bad.write_text("{}")
    with pytest.raises(ValueError):
        load_ledger(str(bad))


def test_obs_report_render_and_diff(tmp_path, fresh_programs, capsys):
    from tools.obs_report import main as report_main
    from tools.obs_report import render_diff, render_report

    path_a, doc_a = _fake_ledger(
        tmp_path, "a.json", search_secs=2.0, auc=0.79,
        fresh_reg=fresh_programs,
    )
    path_b, doc_b = _fake_ledger(
        tmp_path, "b.json", search_secs=1.0, auc=0.80,
        fresh_reg=fresh_programs,
    )

    report = render_report(doc_a)
    assert "# Run report: pipeline" in report
    assert "search.cv_runner[mode=halving,depth=5,chunk=10,bins=64]" in report
    assert "ratio: 0.95" in report
    assert "test_auc: 0.79" in report

    diff = render_diff(doc_a, doc_b)
    assert "Stage deltas" in diff
    assert "search" in diff and "test_auc" in diff

    # CLI: render passes the 0.8 attribution gate, writes --out.
    out = tmp_path / "REPORT.md"
    rc = report_main([path_a, "--out", str(out), "--min-attribution", "0.8"])
    assert rc == 0
    assert "# Run report" in out.read_text()
    # Diff mode via positional second ledger.
    rc = report_main([path_a, path_b])
    assert rc == 0
    assert "Run diff" in capsys.readouterr().out

    # Gate failure: attribute far less than measured.
    from cobalt_smart_lender_ai_tpu.telemetry.metrics import MetricsRegistry
    from cobalt_smart_lender_ai_tpu.telemetry.runledger import RunLedger

    fresh_programs.reset()
    fresh_programs.register("search.tiny", kind="search").record_dispatch(0.1)
    mreg = MetricsRegistry()
    mreg.counter(
        "cobalt_search_dispatch_seconds", "measured wall", ("mode",)
    ).labels(mode="halving").inc(2.0)
    path_c = str(tmp_path / "c.json")
    RunLedger("pipeline").write(path_c, registry=mreg)
    rc = report_main([path_c, "--min-attribution", "0.8"])
    assert rc == 1


# --- device sampler + Perfetto counter tracks --------------------------------


def test_device_sampler_series_and_extra_callbacks():
    from cobalt_smart_lender_ai_tpu.telemetry.devices import DeviceSampler

    t = [100.0]
    sampler = DeviceSampler(clock=lambda: t[0])
    depth = [3.0]
    sampler.add_series("queue_depth", lambda: depth[0])
    sampler.add_series("broken", lambda: 1 / 0)  # raises: skipped, not fatal
    sampler.sample_once()
    t[0] = 101.0
    depth[0] = 5.0
    sampler.sample_once()
    series = sampler.series()
    assert series["queue_depth"] == [(100.0, 3.0), (101.0, 5.0)]
    assert "broken" not in series
    assert "host_rss_bytes" in series  # built-in, Linux-readable in CI
    # Removing a series stops sampling but keeps already-sampled points.
    sampler.remove_series("queue_depth")
    t[0] = 102.0
    sampler.sample_once()
    assert sampler.series()["queue_depth"][-1] == (101.0, 5.0)


def test_chrome_trace_counter_tracks_valid():
    from cobalt_smart_lender_ai_tpu.telemetry.traceexport import chrome_trace

    counters = {
        "queue_depth": [(1.0, 2.0), (1.5, 4.0)],
        "device_mem_bytes:cpu:0": [(1.25, 1024.0)],
    }
    doc = chrome_trace(counters=counters)
    events = [e for e in doc["traceEvents"] if e["ph"] == "C"]
    assert len(events) == 3
    for e in events:
        assert e["cat"] == "counter"
        assert isinstance(e["ts"], float) and e["ts"] > 0
        assert isinstance(e["args"]["value"], float)
    qd = [e for e in events if e["name"] == "queue_depth"]
    assert [e["args"]["value"] for e in qd] == [2.0, 4.0]
    assert qd[0]["ts"] == pytest.approx(1.0e6)
    assert doc["otherData"]["counter_event_count"] == 3
    # The whole document must stay JSON-serializable (the export contract).
    json.dumps(doc)


def test_host_rss_and_device_info_shapes():
    from cobalt_smart_lender_ai_tpu.telemetry.devices import (
        device_info,
        host_rss_bytes,
    )

    rss = host_rss_bytes()
    assert rss is None or rss > 0
    rows = device_info()
    assert len(rows) == 8
    assert {r["platform"] for r in rows} == {"cpu"}
    assert all(isinstance(r["id"], int) for r in rows)
