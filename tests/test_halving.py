"""Successive-halving search scheduler + persistent compile cache bootstrap.

Covers PR-10's tentpole invariants: halving prunes but picks the same winner
as the exhaustive fan-out (survivor scores bit-identical), exhaustive mode
stays the default fallback whenever the schedule doesn't chunk, the halving
knobs invalidate the search-stage checkpoint fingerprint both ways, and the
shared `bootstrap_compile_cache` helper honours its config/env policy.
"""

import dataclasses

import numpy as np
import pytest
from sklearn.datasets import make_classification

import cobalt_smart_lender_ai_tpu.compilecache as compilecache
from cobalt_smart_lender_ai_tpu.compilecache import (
    bootstrap_compile_cache,
    compile_stats,
    install_compile_telemetry,
)
from cobalt_smart_lender_ai_tpu.config import (
    CompileCacheConfig,
    GBDTConfig,
    MeshConfig,
    TuneConfig,
)
from cobalt_smart_lender_ai_tpu.parallel import make_mesh, randomized_search
from cobalt_smart_lender_ai_tpu.parallel.tune import (
    halving_ladder,
    sample_candidates,
)
from cobalt_smart_lender_ai_tpu.reliability import config_fingerprint

# --- sample_candidates: the sampling model feeding both schedulers ----------

GRID_SMALL = {"a": (1, 2, 3), "b": (10, 20)}  # 6 combos -> dense branch
GRID_BIG = {
    "a": tuple(range(8)),
    "b": tuple(range(8)),
    "c": (0.1, 0.2, 0.3, 0.4),
}  # 256 combos -> rejection branch


def _assert_in_grid(cands, space):
    for c in cands:
        assert set(c) == set(space)
        for k, v in c.items():
            assert v in space[k], (k, v)


@pytest.mark.parametrize(
    "space,n_iter",
    [(GRID_SMALL, 5), (GRID_BIG, 16)],
    ids=["dense-permutation", "rejection-sample"],
)
def test_sample_candidates_distinct_in_grid_seed_stable(space, n_iter):
    cands = sample_candidates(space, n_iter, seed=7)
    assert len(cands) == n_iter
    _assert_in_grid(cands, space)
    # without replacement while the grid can supply distinct combos
    keys = sorted(space)
    assert len({tuple(c[k] for k in keys) for c in cands}) == n_iter
    # seed-stable draw; a different seed moves it
    assert cands == sample_candidates(space, n_iter, seed=7)
    assert cands != sample_candidates(space, n_iter, seed=8)


def test_sample_candidates_full_grid_is_exact_enumeration():
    cands = sample_candidates(GRID_SMALL, 6, seed=0)
    combos = {(c["a"], c["b"]) for c in cands}
    assert combos == {(a, b) for a in (1, 2, 3) for b in (10, 20)}


def test_sample_candidates_overdraw_falls_back_with_replacement():
    cands = sample_candidates(GRID_SMALL, 10, seed=3)
    assert len(cands) == 10  # n_iter > total: duplicates, not truncation
    _assert_in_grid(cands, GRID_SMALL)
    assert len({(c["a"], c["b"]) for c in cands}) < 10  # pigeonhole
    assert cands == sample_candidates(GRID_SMALL, 10, seed=3)


# --- halving_ladder ----------------------------------------------------------


def test_halving_ladder_reference_grid_shape():
    # 20 candidates x 300-tree cap, eta 2: the PR-10 reference schedule.
    assert halving_ladder(300, 20, eta=2, min_rungs=2) == [19, 38, 75, 150, 300]


def test_halving_ladder_eta3():
    assert halving_ladder(27, 9, eta=3, min_rungs=2) == [3, 9, 27]


@pytest.mark.parametrize("cap,cands", [(40, 1), (1, 8), (300, 0)])
def test_halving_ladder_degenerate_returns_none(cap, cands):
    assert halving_ladder(cap, cands, eta=2, min_rungs=2) is None


def test_halving_ladder_min_rungs_gate():
    # 2 candidates support exactly 2 rungs; demanding 3 falls back.
    assert halving_ladder(100, 2, eta=2, min_rungs=2) == [50, 100]
    assert halving_ladder(100, 2, eta=2, min_rungs=3) is None


@pytest.mark.parametrize("cap", [7, 48, 300])
@pytest.mark.parametrize("cands", [2, 6, 20])
def test_halving_ladder_ascending_and_capped(cap, cands):
    budgets = halving_ladder(cap, cands, eta=2, min_rungs=2)
    assert budgets is not None
    assert budgets[-1] == cap
    assert all(b2 > b1 for b1, b2 in zip(budgets, budgets[1:]))


# --- halving search vs exhaustive -------------------------------------------


@pytest.fixture(scope="module")
def search_xy():
    X, y = make_classification(
        n_samples=1201, n_features=10, n_informative=5, random_state=1
    )
    return X.astype(np.float32), y


def _run_search(search_xy, *, halving, chunk_trees=12):
    X, y = search_xy
    tune = TuneConfig(
        n_iter=6,
        cv_folds=2,
        seed=3,
        chunk_trees=chunk_trees,
        halving_enabled=halving,
        param_space={
            "n_estimators": (24, 48),
            "max_depth": (2, 3),
            "learning_rate": (0.1, 0.3),
        },
    )
    return randomized_search(
        X, y, GBDTConfig(n_bins=32), tune, make_mesh(MeshConfig(hp=2))
    )


def test_halving_prunes_and_matches_exhaustive_winner(search_xy):
    ex = _run_search(search_xy, halving=False)
    hv = _run_search(search_xy, halving=True)
    assert "halving" not in ex.cv_results_
    report = hv.cv_results_["halving"]
    assert report["pruned_candidates"] > 0
    assert report["budgets"][-1] == 48
    assert len(report["budgets"]) >= 2
    # winner comes from the final-rung survivor set, and agrees with the
    # exhaustive fan-out on the same candidates/folds/seed
    assert hv.best_params_ == ex.best_params_
    assert hv.best_score_ == ex.best_score_
    # survivors boosted to the full budget carry margins bit-identical to a
    # full run, so their per-split scores match the exhaustive run exactly
    surv = report["survivors"]
    np.testing.assert_array_equal(
        hv.cv_results_["split_test_scores"][surv],
        ex.cv_results_["split_test_scores"][surv],
    )
    # pruned candidates keep partial-fidelity scores; they must never outrank
    # the winner
    assert hv.best_score_ == max(hv.cv_results_["mean_test_score"][surv])


def test_halving_unchunked_schedule_falls_back_exhaustive(search_xy):
    # chunk_trees=None -> a single dispatch per bucket: nothing to halve, so
    # enabling halving must leave the run bit-identical to exhaustive.
    ex = _run_search(search_xy, halving=False, chunk_trees=None)
    hv = _run_search(search_xy, halving=True, chunk_trees=None)
    assert "halving" not in hv.cv_results_
    assert hv.best_params_ == ex.best_params_
    np.testing.assert_array_equal(
        hv.cv_results_["split_test_scores"],
        ex.cv_results_["split_test_scores"],
    )


# --- checkpoint fingerprint invalidation (satellite 3) -----------------------


def test_search_fingerprint_tracks_halving_knobs():
    base = TuneConfig()
    fps = {
        config_fingerprint("search", cfg)
        for cfg in (
            base,
            dataclasses.replace(base, halving_enabled=False),
            dataclasses.replace(base, halving_eta=3),
            dataclasses.replace(base, halving_min_rungs=3),
        )
    }
    assert len(fps) == 4  # each knob flips the search-stage fingerprint


def test_resume_reruns_search_when_halving_flipped(tmp_path):
    """An exhaustive search checkpoint must not satisfy a halving-enabled
    resume, and vice versa — partial-fidelity cv scores are not
    interchangeable with exhaustive ones."""
    from cobalt_smart_lender_ai_tpu.config import (
        PipelineConfig,
        RFEConfig,
        ReliabilityConfig,
    )
    from cobalt_smart_lender_ai_tpu.data.synthetic import (
        synthetic_lendingclub_frame,
    )
    from cobalt_smart_lender_ai_tpu.io import ObjectStore
    from cobalt_smart_lender_ai_tpu.pipeline import run_pipeline

    cfg = PipelineConfig(
        gbdt=GBDTConfig(n_bins=32),
        rfe=RFEConfig(n_select=10, step=40, n_estimators=8, max_depth=3),
        tune=TuneConfig(
            n_iter=2,
            cv_folds=2,
            halving_enabled=True,
            param_space={
                "n_estimators": (40,),
                "max_depth": (3,),
                "learning_rate": (0.1,),
            },
        ),
        mesh=MeshConfig(hp=1),
        reliability=ReliabilityConfig(
            base_delay_s=0.0, max_delay_s=0.0, jitter=0.0
        ),
    )
    raw = synthetic_lendingclub_frame(2000, seed=11)
    store = ObjectStore(str(tmp_path / "lake"))
    run_pipeline(cfg, raw=raw, store=store)

    def flip(c, enabled):
        return dataclasses.replace(
            c, tune=dataclasses.replace(c.tune, halving_enabled=enabled)
        )

    # halving -> exhaustive: search re-runs, earlier stages stay skipped
    second = run_pipeline(flip(cfg, False), store=store, resume=True)
    assert "search" in second.stages_run
    assert {"clean", "engineer", "rfe"} <= set(second.stages_skipped)
    # exhaustive -> halving: the exhaustive checkpoint doesn't satisfy either
    third = run_pipeline(flip(cfg, True), store=store, resume=True)
    assert "search" in third.stages_run
    assert {"clean", "engineer", "rfe"} <= set(third.stages_skipped)
    # same flag again: now the checkpoint is valid and search is skipped
    fourth = run_pipeline(flip(cfg, True), store=store, resume=True)
    assert "search" in fourth.stages_skipped


# --- bootstrap_compile_cache policy (satellite 1) ----------------------------


@pytest.fixture()
def fresh_bootstrap(monkeypatch, tmp_path):
    """Reset the module's first-call-wins state and spy on the underlying
    debug helper so tests never mutate live jax.config cache settings."""
    calls = []

    def spy(cache_dir=None, *, min_compile_time_secs=5.0):
        calls.append(
            {"cache_dir": cache_dir, "min_secs": min_compile_time_secs}
        )
        return str(tmp_path / "cc")

    monkeypatch.setattr(compilecache, "_bootstrap_done", False)
    monkeypatch.setattr(compilecache, "_bootstrapped", None)
    monkeypatch.setattr(
        "cobalt_smart_lender_ai_tpu.debug.enable_persistent_compile_cache",
        spy,
    )
    monkeypatch.delenv("COBALT_COMPILE_CACHE", raising=False)
    monkeypatch.delenv("COBALT_COMPILE_CACHE_MIN_SECS", raising=False)
    return calls


def test_bootstrap_first_call_wins(fresh_bootstrap, tmp_path):
    calls = fresh_bootstrap
    first = bootstrap_compile_cache(
        CompileCacheConfig(cache_dir=str(tmp_path / "a"))
    )
    assert first == str(tmp_path / "cc")
    assert len(calls) == 1 and calls[0]["cache_dir"] == str(tmp_path / "a")
    # later calls (library code, different config) return the first result
    again = bootstrap_compile_cache(
        CompileCacheConfig(cache_dir=str(tmp_path / "b"))
    )
    assert again == first
    assert len(calls) == 1


def test_bootstrap_env_opt_out(fresh_bootstrap, monkeypatch):
    monkeypatch.setenv("COBALT_COMPILE_CACHE", "0")
    assert bootstrap_compile_cache() is None
    assert fresh_bootstrap == []  # cache never enabled


def test_bootstrap_config_disabled(fresh_bootstrap):
    assert bootstrap_compile_cache(CompileCacheConfig(enabled=False)) is None
    assert fresh_bootstrap == []


def test_bootstrap_env_min_secs_override(fresh_bootstrap, monkeypatch):
    monkeypatch.setenv("COBALT_COMPILE_CACHE_MIN_SECS", "0")
    bootstrap_compile_cache(CompileCacheConfig(min_compile_time_secs=5.0))
    assert fresh_bootstrap[0]["min_secs"] == 0.0


def test_compile_telemetry_counts_backend_compiles():
    import jax
    import jax.numpy as jnp

    assert install_compile_telemetry()
    before = compile_stats()
    assert set(before) == {
        "backend_compiles",
        "backend_compile_seconds",
        "cache_hits",
        "cache_misses",
        "cache_saved_seconds",
    }

    # a shape/closure combination no other test compiles
    @jax.jit
    def probe(x):
        return jnp.cumsum(x * 1.2345) - 0.5

    probe(jnp.arange(173.0)).block_until_ready()
    after = compile_stats()
    assert after["backend_compiles"] >= before["backend_compiles"] + 1
    assert (
        after["backend_compile_seconds"] > before["backend_compile_seconds"]
    )
