"""Content-hash score cache for repeated single-row payloads: the cache key
is the canonicalized (1, F) float32 vector's raw bytes, so two payloads that
validate to the same features hit the same entry whatever their key order or
alias spelling. Covered here: hit/miss counters (surfaced in ``/readyz`` from
the same ``cobalt_score_cache_*`` cells ``/metrics`` serves), LRU eviction at
the size bound, invalidation on hot reload (entries fingerprint the model
that is leaving), and the size-0 kill switch."""

import pytest

from cobalt_smart_lender_ai_tpu.config import ServeConfig
from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, ObjectStore
from cobalt_smart_lender_ai_tpu.serve.service import ScorerService


def _cfg(**kw) -> ServeConfig:
    return ServeConfig(
        microbatch_enabled=False,  # direct path: each miss is one dispatch
        precompile_batch_buckets=(),
        prewarm_all_buckets=False,
        **kw,
    )


def _payload(loan_amnt: float = 9.2, aliased: bool = True) -> dict:
    vals = {
        "loan_amnt": loan_amnt, "term": 36.0, "installment": 5.7,
        "fico_range_low": 6.55, "last_fico_range_high": 690.0,
        "open_il_12m": 1.0, "open_il_24m": 2.0, "max_bal_bc": 5000.0,
        "num_rev_accts": 2.3, "pub_rec_bankruptcies": 0.0,
        "emp_length_num": 5.0, "earliest_cr_line_days": 8.6,
        "grade_E": 0, "home_ownership_MORTGAGE": 1,
        "verification_status_Verified": 0,
        "application_type_Joint App": 0,
        "hardship_status_BROKEN": 0, "hardship_status_COMPLETE": 0,
        "hardship_status_COMPLETED": 0, "hardship_status_No Hardship": 1,
    }
    if not aliased:
        vals["application_type_Joint_App"] = vals.pop("application_type_Joint App")
        vals["hardship_status_No_Hardship"] = vals.pop("hardship_status_No Hardship")
    return vals


def _cache_stats(svc: ScorerService) -> dict:
    return svc.ready()[1]["score_cache"]


def test_repeat_payload_hits_and_matches(serving_artifact):
    store, _ = serving_artifact
    svc = ScorerService.from_store(store, _cfg())
    first = svc.predict_single(_payload())
    second = svc.predict_single(_payload())
    assert second == first  # a hit returns the full response, bit for bit
    stats = _cache_stats(svc)
    assert (stats["hits"], stats["misses"], stats["entries"]) == (1, 1, 1)
    svc.close()


def test_alias_spellings_share_one_entry(serving_artifact):
    """The two aliased field names canonicalize before hashing: the aliased
    and underscored spellings of the same application are ONE cached score."""
    store, _ = serving_artifact
    svc = ScorerService.from_store(store, _cfg())
    svc.predict_single(_payload(aliased=True))
    resp = svc.predict_single(_payload(aliased=False))
    stats = _cache_stats(svc)
    assert (stats["hits"], stats["misses"], stats["entries"]) == (1, 1, 1)
    assert resp["shap_values"] is not None
    svc.close()


def test_lru_eviction_at_size_bound(serving_artifact):
    store, _ = serving_artifact
    svc = ScorerService.from_store(store, _cfg(score_cache_size=2))
    for amt in (1.0, 2.0, 3.0):  # third insert evicts the LRU entry (1.0)
        svc.predict_single(_payload(loan_amnt=amt))
    assert _cache_stats(svc)["entries"] == 2
    svc.predict_single(_payload(loan_amnt=1.0))
    stats = _cache_stats(svc)
    assert stats["misses"] == 4 and stats["hits"] == 0  # 1.0 was evicted
    svc.predict_single(_payload(loan_amnt=3.0))
    assert _cache_stats(svc)["hits"] == 1  # 3.0 survived both evictions
    svc.close()


def test_reload_invalidates_cache(tmp_path, serving_artifact):
    """Cached scores fingerprint the model that produced them: a hot swap —
    even to a model scoring identically — must empty the cache, or stale
    probabilities would outlive the artifact they came from."""
    shared, _ = serving_artifact
    art = GBDTArtifact.load(shared, "models/gbdt/model_tree")
    store = ObjectStore(str(tmp_path / "lake"))
    art.save(store, "models/gbdt/model_tree")
    svc = ScorerService.from_store(store, _cfg())
    svc.predict_single(_payload())
    svc.predict_single(_payload())
    assert _cache_stats(svc)["entries"] == 1
    assert svc.reload_from_store()["status"] == "ok"
    assert _cache_stats(svc)["entries"] == 0
    svc.predict_single(_payload())
    stats = _cache_stats(svc)
    assert stats["misses"] == 2 and stats["entries"] == 1
    svc.close()


def test_size_zero_disables(serving_artifact):
    store, _ = serving_artifact
    svc = ScorerService.from_store(store, _cfg(score_cache_size=0))
    svc.predict_single(_payload())
    svc.predict_single(_payload())
    stats = _cache_stats(svc)
    assert stats == {"size": 0, "entries": 0, "hits": 0, "misses": 0}
    svc.close()
