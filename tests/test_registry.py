"""Dataset registry (C2/DVC-equivalent) + raw bootstrap (C1) tests."""

import hashlib
import json

import pandas as pd
import pytest

from cobalt_smart_lender_ai_tpu.data.bootstrap import (
    bootstrap_synthetic,
    download_raw_archive,
)
from cobalt_smart_lender_ai_tpu.io import ObjectStore
from cobalt_smart_lender_ai_tpu.io.registry import (
    REFERENCE_RAW_PINS,
    DatasetRegistry,
)


@pytest.fixture()
def registry(tmp_path):
    return DatasetRegistry(ObjectStore(str(tmp_path / "lake")))


def test_add_pull_roundtrip_and_layout(registry):
    data = b"row_id,loan_amnt\n1,1000\n"
    pin = registry.add("raw/sample.csv", data)
    assert pin.md5 == hashlib.md5(data).hexdigest()
    assert pin.size == len(data) and pin.hash == "md5"
    assert registry.pull("raw/sample.csv") == data
    # content-addressed DVC cache layout: cache/md5[:2]/md5[2:]
    assert registry.store.exists(f"dataset/cache/{pin.md5[:2]}/{pin.md5[2:]}")
    assert list(registry.names()) == ["raw/sample.csv"]


def test_identical_content_stored_once(registry):
    data = b"same bytes"
    p1 = registry.add("a.csv", data)
    p2 = registry.add("b.csv", data)
    assert p1.md5 == p2.md5
    cache_keys = [k for k in registry.store.list("dataset/cache/")]
    assert len(cache_keys) == 1  # dedup: one blob, two pins


def test_corruption_detected_on_pull(registry):
    pin = registry.add("x.bin", b"original")
    registry.store.put_bytes(f"dataset/cache/{pin.md5[:2]}/{pin.md5[2:]}", b"tampered")
    with pytest.raises(ValueError, match="failed verification"):
        registry.pull("x.bin")
    assert not registry.verify("x.bin")


def test_pin_survives_new_version(registry):
    registry.add("d.csv", b"v1")
    pin2 = registry.add("d.csv", b"v2-longer")
    assert registry.pull("d.csv") == b"v2-longer"
    assert registry.pin("d.csv") == pin2


def test_reference_pins_importable_and_verify_local(registry, tmp_path):
    registry.import_reference_pins()
    names = set(registry.names())
    assert {p.path for p in REFERENCE_RAW_PINS} <= names
    # pin fields are exactly the reference's .dvc outs schema
    raw = json.loads(
        registry.store.get_bytes(
            "dataset/pins/Loan_status_2007-2020Q3-100ksample.csv.json"
        )
    )
    assert raw == {
        "path": "Loan_status_2007-2020Q3-100ksample.csv",
        "md5": "4e01f7e3ef869a35b65c400d3edda715",
        "size": 73991891,
        "hash": "md5",
    }
    # a local file that doesn't match the pinned digest is rejected
    fake = tmp_path / "fake.csv"
    fake.write_bytes(b"not the real table")
    assert not registry.verify_local(
        "Loan_status_2007-2020Q3-100ksample.csv", fake
    )


def test_bootstrap_synthetic_writes_and_pins(registry, tmp_path):
    path = bootstrap_synthetic(
        tmp_path / "raw", registry=registry, n_rows=200, seed=3
    )
    assert path.exists()
    assert registry.verify("Loan_status_synthetic.csv")
    # pinned bytes are exactly the file on disk, and it parses as the raw schema
    assert registry.pull("Loan_status_synthetic.csv") == path.read_bytes()
    df = pd.read_csv(path, low_memory=False)
    # the generator plants duplicate rows for the cleaning stage to drop,
    # so the raw table is >= the requested row count
    assert len(df) >= 200 and "loan_status" in df.columns


def test_download_unreachable_raises_actionable_error(registry, tmp_path):
    with pytest.raises(ConnectionError, match="DatasetRegistry.add"):
        download_raw_archive(
            "http://127.0.0.1:1/never", tmp_path / "x.zip",
            registry=registry, timeout=0.5,
        )
    assert not (tmp_path / "x.zip").exists()


def test_download_pins_on_success(registry, tmp_path, monkeypatch):
    import io
    import urllib.request

    payload = b"archive-bytes"
    monkeypatch.setattr(
        urllib.request, "urlopen",
        lambda url, timeout=None: io.BytesIO(payload),
    )
    dest = download_raw_archive(
        "http://example.test/data.zip", tmp_path / "data.zip",
        registry=registry,
    )
    assert dest.read_bytes() == payload
    assert registry.pull("data.zip") == payload
