"""Fused scoring kernel (ops/score_pallas.py): parity, quantization, mesh.

The contract under test, layer by layer:

- **f32 bit-parity** (interpret mode, the CPU-CI lowering): the fused
  one-dispatch program's margins are bit-identical to `predict_margin`,
  its probabilities exactly `sigmoid(margin)`, and its SHAP phis match
  `shap_values` to float tolerance with additivity intact. The kernel
  accumulates leaf values in the same per-tree scan order as the
  reference, and the one-hot leaf mask adds exact zeros elsewhere — so
  equality is exact, not approximate.
- **Quantized packs** (bf16 / int8 thresholds + leaf values with affine
  scale/zero-point tables built at pack time): margins drift within the
  committed `PRECISION_TOLERANCES` contract and ranking survives — AUC on
  a trained mini forest stays within a hair of f32.
- **Mesh == single**: the shard_map'd fused program on a forced 4-device
  mesh returns bit-identical margins to the single-device program
  (tests/test_partitioner.py's anchor, now for the fused path).
- **Serving integration**: `serve.fused[...]` programs appear in
  ``GET /debug/programs``, /readyz reports the active kernel + precision
  per bucket, and the score cache never aliases across precisions.
"""

from __future__ import annotations

import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cobalt_smart_lender_ai_tpu.explain.treeshap import shap_values
from cobalt_smart_lender_ai_tpu.models.gbdt import (
    GBDTClassifier,
    predict_margin,
)
from cobalt_smart_lender_ai_tpu.ops.metrics import roc_auc
from cobalt_smart_lender_ai_tpu.ops.score_pallas import (
    PRECISION_TOLERANCES,
    fused_score,
    kernel_mode,
    pack_forest,
    probe_rows,
    quantization_report,
    set_kernel_mode,
)
from cobalt_smart_lender_ai_tpu.parallel.partitioner import (
    SingleDevicePartitioner,
    make_partitioner,
)

# --- fixtures -----------------------------------------------------------------


@pytest.fixture(scope="module")
def mini_forest():
    """Trained mini forest + the data that trained it (margins are real
    learned values, not synthetic tensors — threshold quantization error
    depends on learned split geometry)."""
    rng = np.random.default_rng(5)
    F = 12
    X = rng.normal(size=(1024, F)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] - 0.25 * X[:, 2] > 0).astype(np.int32)
    model = GBDTClassifier(n_estimators=20, max_depth=3, n_bins=64)
    model.fit(X, y)
    return model.forest, X, y, F


# --- f32 bit-parity (interpret mode) ------------------------------------------


@pytest.mark.parametrize("rows", [1, 7, 64])
def test_fused_f32_margins_bit_identical(mini_forest, rows):
    forest, X, _, F = mini_forest
    xb = X[:rows]
    # NaNs must follow the learned missing direction, same as the reference.
    xb = np.array(xb)
    xb[0, 3] = np.nan
    pack = pack_forest(forest, F, "f32")
    margin, prob, phis, base = fused_score(pack, jnp.asarray(xb), n_features=F)
    ref = predict_margin(forest, jnp.asarray(xb))
    assert bool(jnp.all(margin == ref))  # bit-identical, not approx
    assert bool(jnp.all(prob == jax.nn.sigmoid(ref)))  # sigmoid-matched
    ref_phis, ref_base = shap_values(forest, jnp.asarray(xb), n_features=F)
    np.testing.assert_allclose(phis, ref_phis, atol=1e-5)
    assert float(abs(base - ref_base)) < 1e-5
    # Additivity: base + sum(phis) == margin.
    np.testing.assert_allclose(
        base + np.asarray(phis).sum(axis=1), np.asarray(margin), atol=1e-4
    )


def test_fused_margin_only_view(mini_forest):
    forest, X, _, F = mini_forest
    pack = pack_forest(forest, F, "f32")
    margin, prob = fused_score(
        pack, jnp.asarray(X[:16]), n_features=F, with_shap=False
    )
    ref = predict_margin(forest, jnp.asarray(X[:16]))
    assert bool(jnp.all(margin == ref))
    assert bool(jnp.all(prob == jax.nn.sigmoid(ref)))


def test_kernel_mode_default_and_env(monkeypatch):
    assert kernel_mode() == "fused"  # default-on
    monkeypatch.setenv("COBALT_REFERENCE_KERNELS", "1")
    assert kernel_mode() == "reference"
    monkeypatch.delenv("COBALT_REFERENCE_KERNELS")
    set_kernel_mode("reference")
    try:
        assert kernel_mode() == "reference"
    finally:
        set_kernel_mode(None)
    assert kernel_mode() == "fused"


# --- quantized packs ----------------------------------------------------------


@pytest.mark.parametrize("precision", ["bf16", "int8"])
def test_quantized_roundtrip_within_committed_tolerance(mini_forest, precision):
    forest, X, y, F = mini_forest
    # pack_forest(check=True) already gates on the committed contract over
    # the deterministic probe rows; assert the report the gate consumed.
    pack = pack_forest(forest, F, precision)
    report = quantization_report(forest, pack, F)
    assert report["within_tolerance"], report
    tol = PRECISION_TOLERANCES[precision]
    assert report["mean_abs_margin_delta"] <= tol["mean_abs_margin_delta"]
    assert report["max_abs_margin_delta"] <= tol["max_abs_margin_delta"]
    assert report["mean_abs_prob_delta"] <= tol["mean_abs_prob_delta"]

    # Max-abs-delta on real (trained-distribution) rows, not just probes.
    xb = jnp.asarray(X[:256])
    q_margin = fused_score(pack, xb, n_features=F, with_shap=False)[0]
    ref = predict_margin(forest, xb)
    assert float(jnp.max(jnp.abs(q_margin - ref))) <= tol["max_abs_margin_delta"]

    # AUC preservation: quantization may nudge individual margins but must
    # not degrade ranking on the training distribution.
    auc_ref = float(roc_auc(jnp.asarray(y[:256]), ref))
    auc_q = float(roc_auc(jnp.asarray(y[:256]), q_margin))
    assert auc_q >= auc_ref - 0.01, (auc_ref, auc_q)


def test_quantized_packs_have_distinct_table_hashes(mini_forest):
    forest, _, _, F = mini_forest
    hashes = {
        p: pack_forest(forest, F, p).table_hash for p in ("f32", "bf16", "int8")
    }
    assert len(set(hashes.values())) == 3, hashes


def test_probe_rows_are_deterministic(mini_forest):
    forest, _, _, F = mini_forest
    a = probe_rows(forest, F)
    b = probe_rows(forest, F)
    np.testing.assert_array_equal(a, b)


# --- mesh == single -----------------------------------------------------------


def test_forced_mesh_fused_equals_single(mini_forest):
    forest, X, _, F = mini_forest
    # conftest forces 8 virtual devices; the CI kernel-smoke job forces 4.
    assert jax.device_count() >= 4
    single = SingleDevicePartitioner()
    mesh = make_partitioner(4)
    assert mesh.n_shards == 4
    rows = 128
    xb = X[:rows]
    ref = single.compile_margin(forest, F, rows, kernel="reference")(xb)
    mesh_margin = mesh.compile_margin(forest, F, rows)(xb)  # default = fused
    assert bool(jnp.all(mesh_margin == ref))
    mesh_phis, mesh_base = mesh.compile_shap(forest, F, rows)(xb)
    ref_phis, ref_base = single.compile_shap(forest, F, rows, kernel="reference")(xb)
    np.testing.assert_allclose(mesh_phis, ref_phis, atol=1e-5)
    assert float(abs(mesh_base - ref_base)) < 1e-5


def test_fused_programs_share_executable_cache(mini_forest):
    forest, _, _, F = mini_forest
    part = SingleDevicePartitioner()
    from cobalt_smart_lender_ai_tpu.parallel import partitioner as pmod

    pack = pack_forest(forest, F, "f32")
    part.compile_fused(pack, F, 32)
    before = len(pmod._EXEC_CACHE)
    # The SHAP view rides the same with_shap=True executable; the int8 pack
    # must get its OWN entry (precision + table hash key the cache).
    part.compile_shap(pack, F, 32, kernel="fused")
    assert len(pmod._EXEC_CACHE) == before
    part.compile_fused(pack_forest(forest, F, "int8"), F, 32)
    assert len(pmod._EXEC_CACHE) == before + 1


# --- serving integration ------------------------------------------------------


def _cfg(**kw):
    from cobalt_smart_lender_ai_tpu.config import ServeConfig

    kw.setdefault("precompile_batch_buckets", ())
    kw.setdefault("prewarm_all_buckets", False)
    return ServeConfig(**kw)


def test_score_cache_never_aliases_across_precisions(serving_artifact):
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    store, X = serving_artifact
    f32 = ScorerService.from_store(store, _cfg(microbatch_enabled=False))
    int8 = ScorerService.from_store(
        store, _cfg(microbatch_enabled=False, forest_precision="int8")
    )
    try:
        m32, m8 = f32._model, int8._model
        row = {"amount": 1.0}
        assert m32.cache_salt != m8.cache_salt
        # Identical feature bytes produce different cache keys.
        key32 = m32.cache_salt + m32.rows_array([row]).tobytes()
        key8 = m8.cache_salt + m8.rows_array([row]).tobytes()
        assert key32 != key8
        assert m8.quant_table_hash not in ("", "f32")
    finally:
        f32.close()
        int8.close()


def test_reference_kernels_opt_out(serving_artifact):
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    store, _ = serving_artifact
    svc = ScorerService.from_store(
        store, _cfg(microbatch_enabled=False, fused_kernels=False)
    )
    try:
        _, payload = svc.ready()
        assert payload["kernels"]["active"] == "reference"
        assert payload["kernels"]["fused_dispatch"] is False
        assert set(payload["kernels"]["buckets"].values()) == {"reference"}
    finally:
        svc.close()


def test_quantized_requires_fused(serving_artifact):
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    store, _ = serving_artifact
    with pytest.raises(ValueError, match="requires the fused kernel"):
        ScorerService.from_store(
            store,
            _cfg(
                microbatch_enabled=False,
                fused_kernels=False,
                forest_precision="int8",
            ),
        )


def test_live_http_smoke_fused_programs(serving_artifact):
    """End-to-end over the wire: score once through the micro-batcher, then
    assert the observatory saw fused programs and /readyz reports the
    kernel block — the ISSUE's serving acceptance in one smoke."""
    from cobalt_smart_lender_ai_tpu.data import schema
    from cobalt_smart_lender_ai_tpu.serve.http_asyncio import make_async_server
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    store, X = serving_artifact
    svc = ScorerService.from_store(store, _cfg())
    server = make_async_server(svc, "127.0.0.1", 0)
    base = f"http://127.0.0.1:{server.port}"
    try:
        payload = {
            name: float(v)
            for name, v in zip(schema.SERVING_FEATURES, np.asarray(X[0]))
        }
        req = urllib.request.Request(
            base + "/predict",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.status == 200
            body = json.loads(resp.read().decode())
        assert 0.0 <= body["prob_default"] <= 1.0
        assert body.get("shap_values") is not None  # fused dispatch carried phis

        with urllib.request.urlopen(base + "/debug/programs", timeout=30) as r:
            progs = json.loads(r.read().decode())
        names = [p["name"] for p in progs["programs"]]
        assert any(n.startswith("serve.fused[") for n in names), names

        with urllib.request.urlopen(base + "/readyz", timeout=30) as r:
            ready = json.loads(r.read().decode())
        kernels = ready["kernels"]
        assert kernels["active"] == "fused"
        assert kernels["precision"] == "f32"
        assert kernels["fused_dispatch"] is True
        assert "fused" in set(kernels["buckets"].values())
    finally:
        server.close()
        svc.close()
