"""Multi-replica serving engine: least-loaded routing (including drain-around
of a stalled replica), fleet shape in ``/readyz``, per-replica metric
families, and the atomic all-replica hot reload — one replica's candidate
failing must roll the WHOLE fleet back, even the replicas whose candidates
built fine."""

import dataclasses
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cobalt_smart_lender_ai_tpu.config import ServeConfig
from cobalt_smart_lender_ai_tpu.data import schema
from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, ObjectStore
from cobalt_smart_lender_ai_tpu.serve.replicas import (
    ReplicaSet,
    resolve_replica_devices,
)
from cobalt_smart_lender_ai_tpu.serve.service import (
    SINGLE_INPUT_FIELDS,
    ScorerService,
)

N_REPLICAS = 3


def _cfg(**kw) -> ServeConfig:
    kw.setdefault("replicas", N_REPLICAS)
    return ServeConfig(
        microbatch_enabled=False,
        precompile_batch_buckets=(),
        prewarm_all_buckets=False,
        score_cache_size=0,  # routing tests count real dispatches
        **kw,
    )


def _payload() -> dict:
    return {
        canonical: 1 if canonical in schema.SERVING_INT_FEATURES else 1.5
        for canonical in SINGLE_INPUT_FIELDS.values()
    }


def _routed_counts(fleet: ReplicaSet) -> list[int]:
    return [
        int(fleet._m_routed.labels(replica=str(i)).value)
        for i in range(len(fleet.replicas))
    ]


@pytest.fixture(scope="module")
def fleet(serving_artifact):
    store, _ = serving_artifact
    f = ReplicaSet.from_store(store, _cfg())
    yield f
    f.close()


# --- construction -------------------------------------------------------------


def test_from_store_single_replica_is_plain_service(serving_artifact):
    """replicas<=1 must NOT wrap: the facade adds nothing when there is
    nothing to route between, and the adapters get the exact object the
    pre-replica deployments ran."""
    store, _ = serving_artifact
    svc = ReplicaSet.from_store(store, _cfg(replicas=1))
    assert isinstance(svc, ScorerService)
    svc.close()


def test_resolve_replica_devices():
    n_dev = len(jax.devices())  # conftest forces 8
    assert resolve_replica_devices(4, False) == [None] * 4
    pinned = resolve_replica_devices(n_dev + 2, True)
    assert len(pinned) == n_dev + 2
    assert len({str(d) for d in pinned[:n_dev]}) == n_dev  # distinct first lap
    assert str(pinned[n_dev]) == str(pinned[0])  # then round-robin wraps


def test_fleet_shape_in_readyz(fleet):
    ok, payload = fleet.ready()
    assert ok and payload["status"] == "ok"
    assert payload["replicas"] == N_REPLICAS
    assert len(payload["replica_devices"]) == N_REPLICAS
    # 8 forced devices > 3 replicas: every replica pinned to its own device
    assert len(set(payload["replica_devices"])) == N_REPLICAS
    assert payload["router"]["policy"] == "least_loaded"
    assert payload["router"]["in_flight"] == [0] * N_REPLICAS
    assert len(payload["per_replica"]) == N_REPLICAS
    assert payload["bulk"]["shards"] == 1  # replicas scale out, not the mesh


# --- routing ------------------------------------------------------------------


def test_idle_fleet_round_robins(fleet):
    """Tie-breaking: an idle fleet (all loads 0) must rotate, not hotspot
    replica 0 — warm caches everywhere."""
    before = _routed_counts(fleet)
    for _ in range(2 * N_REPLICAS):
        resp = fleet.predict_single(_payload())
        assert 0.0 <= resp["prob_default"] <= 1.0
    after = _routed_counts(fleet)
    assert [a - b for a, b in zip(after, before)] == [2] * N_REPLICAS


def test_router_avoids_loaded_replica(fleet):
    """The load signal steers: with replica 1 carrying synthetic in-flight
    load, no pick lands on it until the load drains."""
    picks: list[int] = []
    with fleet._route_lock:
        fleet._inflight[1] += 5
    try:
        picks = [fleet._pick() for _ in range(2 * N_REPLICAS)]
        assert 1 not in picks
    finally:
        with fleet._route_lock:
            fleet._inflight[1] -= 5
            for i in picks:
                fleet._inflight[i] -= 1  # release the synthetic picks


def test_stalled_replica_drained_around(fleet):
    """The ISSUE's router scenario end-to-end: one replica wedges mid-request
    (its in-flight count stays up), and every subsequent request completes on
    the healthy replicas without queueing behind the stall."""
    release = threading.Event()
    stalled = threading.Event()
    claim_lock = threading.Lock()
    claimed: list[int] = []
    originals = [rep.predict_single for rep in fleet.replicas]

    def _wrap(i, orig):
        def wrapped(payload, *, deadline=None):
            with claim_lock:
                first = not claimed
                if first:
                    claimed.append(i)
            if first:  # only the first-routed request wedges
                stalled.set()
                release.wait(timeout=10)
            return orig(payload, deadline=deadline)

        return wrapped

    for i, rep in enumerate(fleet.replicas):
        rep.predict_single = _wrap(i, originals[i])
    try:
        t = threading.Thread(
            target=fleet.predict_single, args=(_payload(),), daemon=True
        )
        t.start()
        assert stalled.wait(timeout=10), "no request reached a replica"
        victim = claimed[0]
        before = _routed_counts(fleet)
        for _ in range(2 * N_REPLICAS):
            resp = fleet.predict_single(_payload())  # returns promptly
            assert "prob_default" in resp
        after = _routed_counts(fleet)
        assert after[victim] == before[victim], (
            "router sent traffic to the stalled replica"
        )
        assert sum(after) - sum(before) == 2 * N_REPLICAS
    finally:
        release.set()
        t.join(timeout=10)
        for rep, orig in zip(fleet.replicas, originals):
            rep.predict_single = orig
    assert not t.is_alive()


# --- per-replica metrics ------------------------------------------------------


def test_replica_metric_families_in_exposition(fleet):
    fleet.predict_single(_payload())
    text = fleet.registry.render()
    for family in (
        "cobalt_replica_count",
        "cobalt_replica_in_flight",
        "cobalt_replica_routed_total",
        "cobalt_replica_queue_depth",
        "cobalt_request_latency_seconds",
    ):
        assert family in text, f"{family} missing from fleet /metrics"
    assert 'replica="2"' in text  # labeled per replica, not aggregated


def test_observe_request_feeds_fleet_registry(fleet):
    fleet.observe_request("predict", 504, 0.25, code="deadline_exceeded")
    text = fleet.registry.render()
    assert "cobalt_request_errors_total" in text
    assert 'code="deadline_exceeded"' in text


# --- atomic fleet reload ------------------------------------------------------


def _zeroed(art: GBDTArtifact) -> GBDTArtifact:
    """Every leaf 0 -> margin 0 -> P(default) exactly 0.5: a fleet-wide swap
    to it is observable from one prediction per replica."""
    return dataclasses.replace(
        art,
        forest=dataclasses.replace(
            art.forest, leaf_value=jnp.zeros_like(art.forest.leaf_value)
        ),
    )


@pytest.fixture()
def private_fleet(tmp_path, serving_artifact):
    """2-replica fleet on a private store copy — reload tests write new model
    versions, which must not leak into the shared session store."""
    shared, X = serving_artifact
    art = GBDTArtifact.load(shared, "models/gbdt/model_tree")
    store = ObjectStore(str(tmp_path / "lake"))
    art.save(store, "models/gbdt/model_tree")
    f = ReplicaSet.from_store(store, _cfg(replicas=2))
    yield f, store, art
    f.close()


def test_fleet_reload_publishes_everywhere(private_fleet):
    fleet, store, art = private_fleet
    _zeroed(art).save(store, "models/gbdt/model_tree")
    result = fleet.reload_from_store()
    assert result["status"] == "ok"
    assert result["replicas"] == 2
    # EVERY replica serves the new model — probe each directly, not routed
    for rep in fleet.replicas:
        assert rep.predict_single(_payload())["prob_default"] == 0.5
    ok, payload = fleet.ready()
    assert ok and payload["last_reload"]["status"] == "ok"


def test_fleet_reload_is_all_or_nothing(private_fleet):
    """Atomicity, the hard half: replica 0's candidate builds FINE, replica
    1's fails — and replica 0 must still be serving the OLD model afterwards
    (its good candidate was never published)."""
    fleet, store, art = private_fleet
    baseline = [
        rep.predict_single(_payload())["prob_default"] for rep in fleet.replicas
    ]
    _zeroed(art).save(store, "models/gbdt/model_tree")

    def _boom(store, key):
        raise RuntimeError("injected candidate failure")

    fleet.replicas[1]._build_candidate = _boom
    result = fleet.reload_from_store()
    assert result["status"] == "rolled_back"
    assert "injected candidate failure" in result["error"]
    for rep, prob in zip(fleet.replicas, baseline):
        assert rep.predict_single(_payload())["prob_default"] == prob, (
            "a replica published a candidate despite the fleet rollback"
        )
    _, payload = fleet.ready()
    assert payload["last_reload"]["status"] == "rolled_back"


def test_fleet_reload_bad_artifact_rolls_back(private_fleet):
    """A genuinely bad artifact (feature names the schema can't serve) fails
    every candidate's smoke check and the fleet keeps serving."""
    fleet, store, art = private_fleet
    renamed = dataclasses.replace(
        art, feature_names=tuple(f"x_{i}" for i in range(len(art.feature_names)))
    )
    renamed.save(store, "models/gbdt/renamed")
    result = fleet.reload_from_store(model_key="models/gbdt/renamed")
    assert result["status"] == "rolled_back"
    assert fleet.predict_single(_payload())["prob_default"] == pytest.approx(
        fleet.replicas[0].predict_single(_payload())["prob_default"]
    )
