"""Telemetry history + trend gate tests: fake-clock downsampling math for
`telemetry.timeseries`, fleet-merge properties for `telemetry.aggregate`,
the `/history` + `/dashboard` HTTP contract on both adapters (with the
typed 422 taxonomy), durable segment round-trips under injected store
faults, and the `tools/perf_sentinel.py` exit-code matrix."""

import json
import math
import subprocess
import sys
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from cobalt_smart_lender_ai_tpu.io import ObjectStore
from cobalt_smart_lender_ai_tpu.reliability import (
    FaultInjectingStore,
    FaultSpec,
)
from cobalt_smart_lender_ai_tpu.telemetry.aggregate import (
    join_sample_key,
    merge_expositions,
    merge_registries,
    split_sample_key,
)
from cobalt_smart_lender_ai_tpu.telemetry.metrics import MetricsRegistry
from cobalt_smart_lender_ai_tpu.telemetry.timeseries import (
    TimeSeriesStore,
    load_segments,
    render_dashboard,
)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def _expo(counters=None, gauges=None, hist=None):
    """Build a parse_exposition-shaped snapshot from plain dicts.
    ``hist`` maps family -> ({le: cumulative}, count)."""
    out = {}
    for name, v in (counters or {}).items():
        out[name] = {"type": "counter", "samples": {name: float(v)}}
    for name, v in (gauges or {}).items():
        out[name] = {"type": "gauge", "samples": {name: float(v)}}
    for fam, (buckets, count) in (hist or {}).items():
        samples = {}
        for le, c in buckets.items():
            tag = "+Inf" if math.isinf(le) else f"{le:g}"
            samples[f"{fam}_bucket|le={tag}"] = float(c)
        samples[f"{fam}_count"] = float(count)
        samples[f"{fam}_sum"] = 0.0
        out[fam] = {"type": "histogram", "samples": samples}
    return out


# --- fake-clock sampling math -------------------------------------------------


def test_counter_becomes_windowed_rate():
    clock = FakeClock()
    snap = {"cum": 0.0}
    ts = TimeSeriesStore(
        scrape=lambda: _expo(counters={"reqs_total": snap["cum"]}),
        clock=clock,
        tiers=((1.0, 16), (10.0, 16)),
    )
    ts.sample_once()  # first tick: establishes the baseline, no point
    clock.t, snap["cum"] = 1.0, 5.0
    ts.sample_once()
    clock.t, snap["cum"] = 2.0, 15.0
    ts.sample_once()
    fine = ts.query("reqs_total:rate", step_s=1.0)
    assert fine["tier_s"] == 1.0
    assert fine["points"] == [[1.0, 5.0], [2.0, 10.0]]
    # the 10s tier accumulates both deltas into one bucket: 15 obs / 2 s
    coarse = ts.query("reqs_total:rate", step_s=10.0)
    assert coarse["points"] == [[0.0, 7.5]]


def test_counter_reset_treated_as_fresh_delta():
    clock = FakeClock()
    snap = {"cum": 100.0}
    ts = TimeSeriesStore(
        scrape=lambda: _expo(counters={"reqs_total": snap["cum"]}),
        clock=clock,
        tiers=((1.0, 16),),
    )
    ts.sample_once()
    clock.t, snap["cum"] = 1.0, 3.0  # process restarted behind the scrape
    ts.sample_once()
    assert ts.query("reqs_total:rate")["points"] == [[1.0, 3.0]]


def test_gauge_last_value_wins_within_bucket():
    clock = FakeClock()
    snap = {"v": 1.0}
    ts = TimeSeriesStore(
        scrape=lambda: _expo(gauges={"depth": snap["v"]}),
        clock=clock,
        tiers=((10.0, 8),),
    )
    for t, v in ((0.0, 1.0), (4.0, 9.0), (8.0, 2.0), (12.0, 7.0)):
        clock.t, snap["v"] = t, v
        ts.sample_once()
    assert ts.query("depth")["points"] == [[0.0, 2.0], [10.0, 7.0]]


def test_histogram_quantiles_interpolate_within_window():
    clock = FakeClock()
    state = {"buckets": {0.1: 0.0, 1.0: 0.0, math.inf: 0.0}, "count": 0.0}
    ts = TimeSeriesStore(
        scrape=lambda: _expo(hist={"lat": (state["buckets"], state["count"])}),
        clock=clock,
        tiers=((1.0, 16),),
    )
    ts.sample_once()
    # window 1: all 10 observations land below 0.1s
    clock.t = 1.0
    state["buckets"] = {0.1: 10.0, 1.0: 10.0, math.inf: 10.0}
    state["count"] = 10.0
    ts.sample_once()
    p50 = ts.query("lat:p50")["points"]
    p99 = ts.query("lat:p99")["points"]
    assert p50[-1] == [1.0, pytest.approx(0.05)]  # rank 5 of 10 in [0, 0.1]
    assert p99[-1] == [1.0, pytest.approx(0.099)]
    # window 2: 8 obs in (0.1, 1], 2 in (1, +Inf) -> p50 interpolates the
    # middle bucket, p999 clamps to the +Inf bucket's lower edge
    clock.t = 2.0
    state["buckets"] = {0.1: 10.0, 1.0: 18.0, math.inf: 20.0}
    state["count"] = 20.0
    ts.sample_once()
    assert ts.query("lat:p50")["points"][-1] == [
        2.0,
        pytest.approx(0.1 + 0.9 * 5 / 8),
    ]
    assert ts.query("lat:p999")["points"][-1] == [2.0, pytest.approx(1.0)]
    # the histogram count doubles as the QPS series
    assert ts.query("lat:rate")["points"] == [[1.0, 10.0], [2.0, 10.0]]


def test_empty_window_emits_no_quantile_point():
    clock = FakeClock()
    state = {"buckets": {1.0: 5.0, math.inf: 5.0}, "count": 5.0}
    ts = TimeSeriesStore(
        scrape=lambda: _expo(hist={"lat": (state["buckets"], state["count"])}),
        clock=clock,
        tiers=((1.0, 16),),
    )
    ts.sample_once()
    clock.t = 1.0  # no new observations
    ts.sample_once()
    with pytest.raises(KeyError):
        ts.query("lat:p50")


def test_query_tier_selection_and_unknown_series():
    clock = FakeClock()
    ts = TimeSeriesStore(
        scrape=lambda: _expo(gauges={"g": 1.0}),
        clock=clock,
        tiers=((10.0, 360), (60.0, 720)),
    )
    ts.sample_once()
    assert ts.query("g")["tier_s"] == 10.0  # default: finest
    # a window wider than the finest ring's span escalates tiers
    assert ts.query("g", window_s=5000.0)["tier_s"] == 60.0
    assert ts.query("g", step_s=60.0)["tier_s"] == 60.0
    with pytest.raises(KeyError):
        ts.query("nope")
    assert ts.series_names() == ["g"]
    assert ts.tiers() == [
        {"width_s": 10.0, "capacity": 360},
        {"width_s": 60.0, "capacity": 720},
    ]


def test_scrape_fault_never_kills_the_sampler():
    clock = FakeClock()
    calls = {"n": 0}

    def scrape():
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("transient scrape fault")
        return _expo(gauges={"g": float(calls["n"])})

    ts = TimeSeriesStore(scrape=scrape, clock=clock, tiers=((1.0, 8),))
    for t in (0.0, 1.0, 2.0):
        clock.t = t
        ts.sample_once()
    assert ts.sample_errors == 1
    assert ts.query("g")["points"] == [[0.0, 1.0], [2.0, 3.0]]


def test_exactly_one_of_registry_or_scrape():
    with pytest.raises(ValueError):
        TimeSeriesStore()
    with pytest.raises(ValueError):
        TimeSeriesStore(registry=MetricsRegistry(), scrape=lambda: {})


# --- fleet aggregation --------------------------------------------------------


def _snap_a():
    return _expo(counters={"reqs_total": 10.0}, gauges={"depth": 2.0})


def _snap_b():
    return _expo(counters={"reqs_total": 32.0}, gauges={"depth": 5.0})


def test_merge_is_commutative_and_sums_counters():
    ab = merge_expositions([_snap_a(), _snap_b()])
    ba = merge_expositions([_snap_b(), _snap_a()])
    assert ab == ba
    assert ab["reqs_total"]["samples"]["reqs_total"] == 42.0
    assert ab["depth"]["samples"]["depth"] == 7.0


def test_merge_is_associative():
    snaps = [_snap_a(), _snap_b(), _expo(counters={"reqs_total": 0.5})]
    once = merge_expositions(snaps)
    paired = merge_expositions(
        [merge_expositions(snaps[:2]), snaps[2]]
    )
    assert once == paired


def test_merge_keeps_per_source_series_under_joined_labels():
    merged = merge_expositions(
        [_snap_a(), _snap_b()],
        extra_labels=[{"replica": "0"}, {"replica": "1"}],
        keep_sources=True,
    )
    samples = merged["reqs_total"]["samples"]
    assert samples["reqs_total"] == 42.0
    assert samples["reqs_total|replica=0"] == 10.0
    assert samples["reqs_total|replica=1"] == 32.0


def test_merge_skips_nan_and_rejects_type_conflicts():
    healthy = _expo(gauges={"depth": 3.0})
    dead = _expo(gauges={"depth": math.nan})
    merged = merge_expositions([healthy, dead])
    assert merged["depth"]["samples"]["depth"] == 3.0
    with pytest.raises(ValueError, match="conflicts"):
        merge_expositions(
            [
                {"x": {"type": "counter", "samples": {"x": 1.0}}},
                {"x": {"type": "histogram", "samples": {}}},
            ]
        )


def test_sample_key_round_trip():
    name, labels = split_sample_key("lat_bucket|le=0.5|route=/predict")
    assert name == "lat_bucket"
    assert labels == {"le": "0.5", "route": "/predict"}
    assert join_sample_key(name, labels) == "lat_bucket|le=0.5|route=/predict"


def test_two_replica_fleet_counter_equals_sum_of_members():
    """The acceptance invariant: the fleet-level counter series is
    exactly the sum of the per-replica series, and both are scrapeable
    into one history store."""
    regs = [MetricsRegistry(), MetricsRegistry()]
    for i, reg in enumerate(regs):
        reg.counter("cobalt_requests_total", "requests").inc(10.0 * (i + 1))
    merged = merge_registries(regs)
    samples = merged["cobalt_requests_total"]["samples"]
    assert samples["cobalt_requests_total"] == pytest.approx(
        samples["cobalt_requests_total|replica=0"]
        + samples["cobalt_requests_total|replica=1"]
    )
    # and through a history store: fleet rate == sum of per-replica rates
    from cobalt_smart_lender_ai_tpu.telemetry.aggregate import fleet_scraper

    clock = FakeClock()
    ts = TimeSeriesStore(
        scrape=fleet_scraper(regs), clock=clock, tiers=((1.0, 8),)
    )
    ts.sample_once()
    clock.t = 1.0
    regs[0].counter("cobalt_requests_total", "requests").inc(4.0)
    regs[1].counter("cobalt_requests_total", "requests").inc(6.0)
    ts.sample_once()
    rate = lambda s: ts.query(s)["points"][-1][1]  # noqa: E731
    assert rate("cobalt_requests_total:rate") == pytest.approx(
        rate("cobalt_requests_total:rate|replica=0")
        + rate("cobalt_requests_total:rate|replica=1")
    )


# --- durable segments ---------------------------------------------------------


def _gauge_store(tmp_path, clock, store, **kw):
    snap = {"v": 0.0}
    ts = TimeSeriesStore(
        scrape=lambda: _expo(gauges={"g": snap["v"]}),
        clock=clock,
        tiers=((1.0, 64),),
        store=store,
        ship_interval_s=0.0,  # ship only when the test says so
        **kw,
    )
    return ts, snap


def test_segment_ship_and_load_round_trip(tmp_path):
    store = ObjectStore(str(tmp_path / "lake"))
    clock = FakeClock()
    ts, snap = _gauge_store(tmp_path, clock, store)
    for t in (0.0, 1.0, 2.0):
        clock.t, snap["v"] = t, t * 10
        ts.sample_once()
    key = ts.ship()
    assert key is not None and store.verify_pointer(key)
    assert ts.ship() is None  # nothing new since
    clock.t, snap["v"] = 3.0, 30.0
    ts.sample_once()
    assert ts.ship() is not None  # append-only second segment
    assert load_segments(store)["g"] == [
        [0.0, 0.0],
        [1.0, 10.0],
        [2.0, 20.0],
        [3.0, 30.0],
    ]


def test_failed_ship_reships_same_points(tmp_path):
    inner = ObjectStore(str(tmp_path / "lake"))
    faulty = FaultInjectingStore(
        inner, faults={"put": FaultSpec(fail_after=0, max_faults=2)}
    )
    clock = FakeClock()
    ts, snap = _gauge_store(tmp_path, clock, faulty)
    ts.ship_interval_s = 0.5  # every tick is ship-due
    for t in (0.0, 1.0, 2.0):
        clock.t, snap["v"] = t, t
        ts.sample_once()  # shipping faults are swallowed and counted
    assert ts.ship_failures >= 1
    clock.t, snap["v"] = 3.0, 3.0
    ts.sample_once()  # fault budget spent: this ship lands
    assert load_segments(inner)["g"] == [
        [0.0, 0.0],
        [1.0, 1.0],
        [2.0, 2.0],
        [3.0, 3.0],
    ]


def test_torn_segment_is_a_gap_not_a_crash(tmp_path):
    store = ObjectStore(str(tmp_path / "lake"))
    clock = FakeClock()
    ts, snap = _gauge_store(tmp_path, clock, store)
    clock.t = 0.0
    ts.sample_once()
    first = ts.ship()
    clock.t, snap["v"] = 1.0, 5.0
    ts.sample_once()
    second = ts.ship()
    store.put_bytes(first, b'{"torn')  # md5 pointer no longer verifies
    loaded = load_segments(store)
    assert loaded["g"] == [[1.0, 5.0]]  # torn segment skipped, rest intact
    assert store.verify_pointer(second)


def test_segment_gc_retains_newest(tmp_path):
    store = ObjectStore(str(tmp_path / "lake"))
    clock = FakeClock()
    ts, snap = _gauge_store(tmp_path, clock, store, retain_segments=2)
    for t in range(5):
        clock.t, snap["v"] = float(t), float(t)
        ts.sample_once()
        ts.ship()
    from cobalt_smart_lender_ai_tpu.io.store import PTR_SUFFIX

    segs = [
        k
        for k in store.list("telemetry/history/")
        if not k.endswith(PTR_SUFFIX)
    ]
    assert len(segs) == 2
    # newest points survived GC
    assert load_segments(store)["g"] == [[3.0, 3.0], [4.0, 4.0]]


# --- HTTP contract: /history + /dashboard on both adapters --------------------


def _history_cfg():
    from cobalt_smart_lender_ai_tpu.config import ServeConfig

    return ServeConfig(
        prewarm_all_buckets=False,
        microbatch_enabled=False,
        history_interval_s=0.03,
        history_tiers=((0.05, 400), (1.0, 120), (60.0, 60)),
    )


@pytest.fixture(scope="module")
def history_server(serving_artifact):
    from cobalt_smart_lender_ai_tpu.serve import ScorerService
    from cobalt_smart_lender_ai_tpu.serve.http_asyncio import (
        make_async_server,
    )

    store, _ = serving_artifact
    service = ScorerService.from_store(store, _history_cfg())
    server = make_async_server(service, "127.0.0.1", 0)
    yield f"http://127.0.0.1:{server.port}", service
    server.close()
    service.close()


def _get(url: str):
    try:
        with urllib.request.urlopen(url) as r:
            ctype = r.headers.get("Content-Type", "")
            return r.status, ctype, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get("Content-Type", ""), e.read()


def _get_json(url: str):
    status, _, body = _get(url)
    return status, json.loads(body.decode())


def _predict_payload():
    from cobalt_smart_lender_ai_tpu.data import schema
    from cobalt_smart_lender_ai_tpu.serve.service import SINGLE_INPUT_FIELDS

    return {
        canonical: 1 if canonical in schema.SERVING_INT_FEATURES else 1.5
        for canonical in SINGLE_INPUT_FIELDS.values()
    }


def test_live_history_latency_quantiles_span_windows(history_server):
    """Acceptance: under sustained load, /history on the asyncio adapter
    returns a latency-quantile series spanning >= 3 sample windows."""
    url, _ = history_server
    body = json.dumps(_predict_payload()).encode()
    series = "cobalt_request_latency_seconds:p99|route=/predict|status=200"
    deadline = time.monotonic() + 30.0
    points = []
    while time.monotonic() < deadline:
        for _ in range(8):
            req = urllib.request.Request(
                url + "/predict",
                data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req) as r:
                assert r.status == 200
        status, doc = _get_json(
            url + "/history?series=" + urllib.parse.quote(series)
        )
        if status == 200:
            points = doc["points"]
            if len(points) >= 3:
                break
    assert len(points) >= 3, f"only {len(points)} windows sampled"
    assert len({t for t, _ in points}) == len(points)  # distinct windows
    assert all(v >= 0 for _, v in points)
    assert doc["tier_s"] == 0.05
    # the same traffic also produced a QPS series (histogram _count rate)
    status, doc = _get_json(
        url
        + "/history?series="
        + urllib.parse.quote(
            "cobalt_request_latency_seconds:rate|route=/predict|status=200"
        )
    )
    assert status == 200 and len(doc["points"]) >= 1


def test_history_catalog_and_window_param(history_server):
    url, _ = history_server
    status, doc = _get_json(url + "/history")
    assert status == 200
    assert set(doc) == {"series", "tiers"}
    assert doc["tiers"][0] == {"width_s": 0.05, "capacity": 400}
    status, doc = _get_json(url + "/history?series=" + urllib.parse.quote(
        doc["series"][0]) + "&window=10")
    assert status == 200 and doc["tier_s"] == 0.05
    # a window wider than the finest ring escalates to a coarser tier
    status, doc = _get_json(url + "/history?series=" + urllib.parse.quote(
        doc["series"]) + "&window=3000")
    assert status == 200 and doc["tier_s"] == 60.0


def test_history_422_taxonomy_asyncio(history_server):
    url, _ = history_server
    status, doc = _get_json(url + "/history?series=no_such_series")
    assert status == 422
    assert doc["error"] == "invalid_input"
    assert "unknown series" in doc["detail"]
    for bad in ("window=abc", "window=-5", "step=0", "window=inf"):
        status, doc = _get_json(url + "/history?series=x&" + bad)
        assert status == 422, bad
        assert doc["error"] == "invalid_input"


def test_dashboard_html_asyncio(history_server):
    url, _ = history_server
    status, ctype, body = _get(url + "/dashboard")
    assert status == 200
    assert ctype.startswith("text/html")
    text = body.decode()
    assert "<svg" in text or "no samples yet" in text
    assert "Latency quantiles" in text
    status, doc = _get_json(url + "/dashboard?window=nope")
    assert status == 422 and doc["error"] == "invalid_input"


def test_history_disabled_404(serving_artifact):
    from cobalt_smart_lender_ai_tpu.config import ServeConfig
    from cobalt_smart_lender_ai_tpu.serve import ScorerService
    from cobalt_smart_lender_ai_tpu.serve.http_asyncio import (
        make_async_server,
    )

    store, _ = serving_artifact
    service = ScorerService.from_store(
        store,
        ServeConfig(
            prewarm_all_buckets=False,
            microbatch_enabled=False,
            history_enabled=False,
        ),
    )
    server = make_async_server(service, "127.0.0.1", 0)
    try:
        url = f"http://127.0.0.1:{server.port}"
        for route in ("/history", "/dashboard"):
            status, doc = _get_json(url + route)
            assert status == 404
            assert doc["error"] == "history_disabled"
    finally:
        server.close()
        service.close()


def test_history_contract_fastapi(serving_artifact):
    """Same surface on the FastAPI adapter: catalog, unknown-series 422,
    HTML dashboard (parity with the asyncio adapter)."""
    pytest.importorskip("fastapi")
    from fastapi.testclient import TestClient

    from cobalt_smart_lender_ai_tpu.serve import ScorerService
    from cobalt_smart_lender_ai_tpu.serve.http_fastapi import create_app

    store, _ = serving_artifact
    service = ScorerService.from_store(store, _history_cfg())
    try:
        service.history.sample_once()  # no lifespan: sample by hand
        client = TestClient(create_app(service=service))
        r = client.get("/history")
        assert r.status_code == 200
        assert set(r.json()) == {"series", "tiers"}
        r = client.get("/history", params={"series": "no_such_series"})
        assert r.status_code == 422
        assert "unknown series" in r.json()["detail"]
        r = client.get("/history", params={"series": "x", "window": "abc"})
        assert r.status_code == 422
        r = client.get("/dashboard")
        assert r.status_code == 200
        assert r.headers["content-type"].startswith("text/html")
        assert "Latency quantiles" in r.text
    finally:
        service.close()


def test_render_dashboard_with_samples():
    clock = FakeClock()
    state = {"buckets": {0.1: 0.0, math.inf: 0.0}, "count": 0.0}

    def scrape():
        return _expo(
            hist={
                "cobalt_request_latency_seconds": (
                    state["buckets"],
                    state["count"],
                )
            },
            gauges={"cobalt_microbatch_queue_depth": 3.0},
        )

    ts = TimeSeriesStore(scrape=scrape, clock=clock, tiers=((1.0, 32),))
    for t in (0.0, 1.0, 2.0):
        clock.t = t
        state["buckets"] = {0.1: 5.0 * t, math.inf: 5.0 * t}
        state["count"] = 5.0 * t
        ts.sample_once()
    html = render_dashboard(ts)
    assert "cobalt_request_latency_seconds:p99" in html
    assert "<svg" in html
    assert "cobalt_microbatch_queue_depth" in html


# --- perf sentinel ------------------------------------------------------------


from cobalt_smart_lender_ai_tpu.telemetry import trend as trendlib  # noqa: E402


def test_extract_metrics_known_shapes():
    assert trendlib.extract_metrics(
        {"metric": "rows_per_sec_per_chip", "value": 123.0}
    ) == {"rows_per_sec_per_chip": 123.0}
    # driver wrapper: failed run (rc!=0, parsed null) yields no metrics
    assert (
        trendlib.extract_metrics({"cmd": "x", "rc": 1, "parsed": None}) == {}
    )
    m = trendlib.extract_metrics(
        {
            "bench": "serve_throughput",
            "results": {"batcher_on": {"qps": 100.0, "p99.9_ms": 9.0}},
        }
    )
    assert m == {"serve.batcher_on.qps": 100.0, "serve.batcher_on.p999_ms": 9.0}
    m = trendlib.extract_metrics(
        {
            "bench": "search_halving_vs_exhaustive",
            "compile": {"cache_misses": 4},
            "runs": {"halving": {"dispatch_seconds": 2.5}},
        }
    )
    assert m == {
        "search.compile.cache_misses": 4.0,
        "search.halving.warm_dispatch_seconds": 2.5,
    }
    assert trendlib.extract_metrics({"totally": "unknown"}) == {}


def test_gate_policies():
    assert trendlib.policy_for("serve.batcher_on.qps")["kind"] == "ratio_min"
    assert (
        trendlib.policy_for("serve_async.asyncio.clients_128.p999_ms")["limit"]
        == 1.5
    )
    assert (
        trendlib.policy_for("search.halving.warm_dispatch_seconds")["limit"]
        == 1.25
    )
    assert trendlib.policy_for("search.compile.cache_misses")["kind"] == (
        "slack_max"
    )
    assert trendlib.policy_for("search.halving.cv_auc") is None


def _trend_with(rows):
    doc = trendlib.new_trend()
    for metrics in rows:
        trendlib.append_row(doc, source="test", metrics=metrics)
    return doc


def test_check_rolling_median_baseline():
    rows = [{"serve.batcher_on.qps": v} for v in (100, 90, 110, 95, 105)]
    # median of the 5 priors is 100 -> floor is 70
    ok = trendlib.check(_trend_with(rows + [{"serve.batcher_on.qps": 71.0}]))
    assert ok["status"] == "pass" and not ok["regressions"]
    bad = trendlib.check(_trend_with(rows + [{"serve.batcher_on.qps": 69.0}]))
    assert bad["status"] == "regression"
    assert bad["regressions"][0]["metric"] == "serve.batcher_on.qps"
    assert bad["regressions"][0]["baseline"] == 100.0


def test_check_missing_baseline_and_empty():
    assert trendlib.check(trendlib.new_trend())["status"] == "empty"
    first = trendlib.check(_trend_with([{"serve.batcher_on.qps": 10.0}]))
    assert first["status"] == "missing_baseline"
    # tracked-only metrics never gate
    tracked = trendlib.check(
        _trend_with([{"cv_auc": 0.9}, {"cv_auc": 0.1}])
    )
    assert tracked["status"] == "pass" and not tracked["checked"]


def _sentinel(tmp_path, *argv):
    import os

    return subprocess.run(
        [sys.executable, "tools/perf_sentinel.py", *argv],
        capture_output=True,
        text=True,
        cwd="/root/repo",
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


@pytest.mark.slow
def test_perf_sentinel_exit_code_matrix(tmp_path):
    trend_path = str(tmp_path / "TREND.json")
    record = {
        "bench": "serve_throughput",
        "results": {"batcher_on": {"qps": 100.0, "p99.9_ms": 10.0}},
    }
    src = tmp_path / "bench.json"
    src.write_text(json.dumps(record))
    # first row: gated metrics but nothing to compare against -> 3
    r = _sentinel(tmp_path, "--trend", trend_path, "ingest", str(src))
    assert r.returncode == 0, r.stderr
    assert (
        _sentinel(tmp_path, "--trend", trend_path, "check").returncode == 3
    )
    # steady state -> 0
    _sentinel(tmp_path, "--trend", trend_path, "ingest", str(src))
    assert (
        _sentinel(tmp_path, "--trend", trend_path, "check").returncode == 0
    )
    # synthetic regression -> 1
    record["results"]["batcher_on"] = {"qps": 10.0, "p99.9_ms": 200.0}
    src.write_text(json.dumps(record))
    _sentinel(tmp_path, "--trend", trend_path, "ingest", str(src))
    r = _sentinel(tmp_path, "--trend", trend_path, "check")
    assert r.returncode == 1
    report = json.loads(r.stdout)
    assert {e["metric"] for e in report["regressions"]} == {
        "serve.batcher_on.qps",
        "serve.batcher_on.p999_ms",
    }
    # render writes an HTML artifact with sparklines
    out = tmp_path / "trend.html"
    r = _sentinel(
        tmp_path, "--trend", trend_path, "render", "--out", str(out)
    )
    assert r.returncode == 0
    assert "<svg" in out.read_text()


def test_committed_trend_baseline_passes():
    """The committed TREND.json must gate clean — perf_sentinel --check
    exits zero on the repo's own baseline (the CI trend-gate contract)."""
    doc = trendlib.load_trend("/root/repo/TREND.json")
    assert len(doc["rows"]) >= 9
    report = trendlib.check(doc)
    assert report["status"] in ("pass", "missing_baseline"), report
    assert not report["regressions"]
