"""Portfolio scenario engine: grid determinism, hand-computed delta math,
kill/resume bit-parity on the forced mesh, checkpoint progress back-compat,
batch deadline semantics, PSI OOD flagging, and report/ledger round-trip.

The parity tests extend `tests/test_partitioner.py`'s contract one layer
up: not only is a mesh dispatch bit-identical to a single-device one, but a
chunked, checkpointed, killed-and-resumed *sweep* concatenates to the same
bits as an uninterrupted run — `np.array_equal`, no tolerances.
"""

import json

import numpy as np
import pytest

from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, ObjectStore
from cobalt_smart_lender_ai_tpu.reliability.checkpoint import (
    PipelineCheckpoint,
    config_fingerprint,
)
from cobalt_smart_lender_ai_tpu.reliability.deadline import Deadline
from cobalt_smart_lender_ai_tpu.reliability.errors import DeadlineExceeded
from cobalt_smart_lender_ai_tpu.scenario import (
    BASELINE,
    PortfolioInterrupted,
    PortfolioScorer,
    Scenario,
    ScenarioGrid,
    band_migration,
    delta_stats,
    feature_delta,
    feature_multiplier,
    pd_band_index,
    scenario_drift,
)
from cobalt_smart_lender_ai_tpu.telemetry.drift import FeatureSketch

SHARDS = 4
CHUNK = 64


@pytest.fixture(scope="module")
def portfolio_setup(serving_artifact):
    """(store, artifact, 256-row float32 portfolio matrix)."""
    store, X = serving_artifact
    art = GBDTArtifact.load(store, "models/gbdt/model_tree")
    return store, art, np.ascontiguousarray(X[:256], dtype=np.float32)


def _grid():
    return ScenarioGrid(
        [
            feature_delta("installment", [25.0, 50.0]),
            feature_multiplier("loan_amnt", [0.9]),
        ]
    )


# --- grid DSL ----------------------------------------------------------------


def test_grid_expansion_deterministic_order():
    grid = ScenarioGrid(
        [
            feature_delta("installment", [10.0, 20.0]),
            feature_multiplier("loan_amnt", [0.8, 1.2]),
        ]
    )
    ids = [s.scenario_id for s in grid.expand()]
    # Axes in declaration order, rightmost axis fastest (itertools.product).
    assert ids == [
        "installment+10,loan_amntx0.8",
        "installment+10,loan_amntx1.2",
        "installment+20,loan_amntx0.8",
        "installment+20,loan_amntx1.2",
    ]
    assert len(grid) == 4
    # Expansion is a pure function of the grid: repeat calls agree exactly.
    assert [s.scenario_id for s in grid.expand()] == ids


def test_grid_json_roundtrip_preserves_order():
    grid = _grid()
    clone = ScenarioGrid.from_json(json.loads(json.dumps(grid.to_json())))
    assert [s.scenario_id for s in clone.expand()] == [
        s.scenario_id for s in grid.expand()
    ]
    assert clone.to_json() == grid.to_json()


def test_scenario_apply_ops_and_unknown_feature():
    names = ["a", "b"]
    X = np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32)
    s = ScenarioGrid(
        [feature_delta("a", [10.0]), feature_multiplier("b", [0.5])]
    ).expand()[0]
    out = s.apply(X, names)
    np.testing.assert_array_equal(
        out, np.asarray([[11.0, 1.0], [13.0, 2.0]], np.float32)
    )
    np.testing.assert_array_equal(  # input untouched
        X, np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32)
    )
    assert BASELINE.apply(X, names).tolist() == X.tolist()
    with pytest.raises(KeyError, match="unknown"):
        Scenario("bad", s.perturbations).apply(X, ["a", "c"])


# --- delta math on a hand-computed grid --------------------------------------


def test_band_migration_and_delta_stats_hand_computed():
    bands = (0.02, 0.08, 0.20, 0.50)
    baseline = np.asarray([0.01, 0.04, 0.10, 0.30])
    scenario = np.asarray([0.03, 0.09, 0.12, 0.60])
    assert pd_band_index(baseline, bands).tolist() == [0, 1, 2, 3]
    assert pd_band_index(scenario, bands).tolist() == [1, 2, 2, 4]
    mig = band_migration(baseline, scenario, bands)
    assert mig["downgraded"] == 3
    assert mig["upgraded"] == 0
    assert mig["unchanged"] == 1
    matrix = np.asarray(mig["matrix"])
    assert matrix.sum() == 4
    assert matrix[0][1] == matrix[1][2] == matrix[2][2] == matrix[3][4] == 1
    stats = delta_stats(baseline, scenario)
    assert stats["mean"] == pytest.approx((0.02 + 0.05 + 0.02 + 0.30) / 4)
    assert stats["max"] == pytest.approx(0.30)
    assert stats["min"] == pytest.approx(0.02)


def test_engine_delta_math_consistent_on_2x2_grid(portfolio_setup):
    """Every report delta must re-derive exactly from the landed score
    arrays — the reducers and the artifacts cannot disagree."""
    store, art, X = portfolio_setup
    grid = ScenarioGrid(
        [
            feature_delta("installment", [10.0, 20.0]),
            feature_multiplier("loan_amnt", [0.8, 1.2]),
        ]
    )
    scorer = PortfolioScorer(
        art, store, shards=1, chunk_rows=CHUNK, compute_shap=False
    )
    report = scorer.run(X[:128], grid, run_id="t-2x2")
    assert len(report["scenarios"]) == 4
    base = store.load_array(report["keys"]["scores"]["baseline"])
    for block in report["scenarios"]:
        scores = store.load_array(block["scores_key"])
        deltas = store.load_array(block["deltas_key"])
        np.testing.assert_array_equal(
            deltas, np.asarray(scores, np.float64) - np.asarray(base, np.float64)
        )
        assert block["delta"]["mean"] == pytest.approx(float(deltas.mean()))
        assert block["mean_pd"] == pytest.approx(float(scores.mean()))
        mig = block["migration"]
        assert mig["downgraded"] + mig["upgraded"] + mig["unchanged"] == 128
        assert int(np.asarray(mig["matrix"]).sum()) == 128


# --- kill / resume bit-parity on the forced mesh -----------------------------


def test_resume_mid_sweep_bit_parity(portfolio_setup):
    store, art, X = portfolio_setup
    grid = _grid()

    ref = PortfolioScorer(art, store, shards=SHARDS, chunk_rows=CHUNK).run(
        X, grid, run_id="t-ref"
    )
    assert ref["partitioner"]["shards"] == SHARDS
    assert ref["resume"]["chunks_resumed"] == 0

    killed = PortfolioScorer(art, store, shards=SHARDS, chunk_rows=CHUNK)
    with pytest.raises(PortfolioInterrupted):
        killed.run(X, grid, run_id="t-kill", fail_after_chunks=5)
    resumed = killed.run(X, grid, run_id="t-kill", resume=True)
    assert resumed["resume"]["chunks_resumed"] == 5
    assert (
        resumed["resume"]["chunks_scored"]
        == resumed["resume"]["chunks_total"] - 5
    )

    for sid, key in ref["keys"]["scores"].items():
        a = store.load_array(key)
        b = store.load_array(resumed["keys"]["scores"][sid])
        assert np.array_equal(a, b), f"scenario {sid} drifted across resume"

    # Mesh-vs-single through the whole engine: same contract one layer up
    # from tests/test_partitioner.py.
    single = PortfolioScorer(art, store, shards=1, chunk_rows=CHUNK).run(
        X, grid, run_id="t-single"
    )
    for sid, key in ref["keys"]["scores"].items():
        assert np.array_equal(
            store.load_array(key),
            store.load_array(single["keys"]["scores"][sid]),
        ), f"scenario {sid} differs mesh vs single"

    # Resume without a matching checkpoint (fresh run-id) scores everything.
    fresh = PortfolioScorer(art, store, shards=SHARDS, chunk_rows=CHUNK).run(
        X, grid, run_id="t-fresh", resume=True
    )
    assert fresh["resume"]["chunks_resumed"] == 0


# --- checkpoint progress payload + back-compat -------------------------------


def test_checkpoint_progress_backcompat(tmp_path):
    store = ObjectStore(str(tmp_path / "lake"))
    store.put_bytes("out/a.bin", b"alpha")
    ckpt = PipelineCheckpoint(store)
    fp = config_fingerprint({"v": 1})

    # Old-style whole-stage write: no progress key in the JSON at all.
    ckpt.write("legacy", fingerprint=fp, outputs=["out/a.bin"])
    raw = store.get_json(ckpt.manifest_key("legacy"))
    assert "progress" not in raw
    assert ckpt.valid("legacy", fp)
    assert ckpt.progress("legacy") is None

    # A pre-progress manifest written by an older build loads unchanged.
    import hashlib

    old = {
        "format": 1,
        "stage": "ancient",
        "fingerprint": fp,
        "outputs": ["out/a.bin"],
        "pointers": {
            "out/a.bin": {
                "key": "out/a.bin",
                "md5": hashlib.md5(b"alpha").hexdigest(),
                "size": 5,
            }
        },
        "extra": {},
    }
    store.put_json(ckpt.manifest_key("ancient"), old)
    assert ckpt.load("ancient") == old
    assert ckpt.valid("ancient", fp)
    assert ckpt.progress("ancient") is None

    # Progress payloads round-trip and advance() accumulates outputs
    # without dropping history.
    store.put_bytes("out/b.bin", b"beta")
    ckpt.advance(
        "stream",
        fingerprint=fp,
        new_outputs=["out/a.bin"],
        progress={"items_done": 1, "items_total": 2},
    )
    ckpt.advance(
        "stream",
        fingerprint=fp,
        new_outputs=["out/b.bin"],
        progress={"items_done": 2, "items_total": 2},
    )
    manifest = ckpt.load("stream")
    assert manifest["outputs"] == ["out/a.bin", "out/b.bin"]
    assert ckpt.progress("stream") == {"items_done": 2, "items_total": 2}
    assert ckpt.valid("stream", fp)

    # A fingerprint change discards stale progress (fresh start semantics).
    fp2 = config_fingerprint({"v": 2})
    assert ckpt.progress("stream", fp2) is None
    ckpt.advance("stream", fingerprint=fp2, progress={"items_done": 0})
    assert ckpt.load("stream")["outputs"] == []


# --- batch deadline semantics ------------------------------------------------


class _TickClock:
    """Each read advances 30 fake seconds — a multi-minute-shaped run."""

    def __init__(self, step: float = 30.0):
        self.now = 0.0
        self.step = step

    def __call__(self) -> float:
        self.now += self.step
        return self.now


def test_deadline_none_never_aborts_long_runs(portfolio_setup):
    store, art, X = portfolio_setup
    clock = _TickClock(30.0)
    scorer = PortfolioScorer(
        art, store, shards=1, chunk_rows=CHUNK, compute_shap=False,
        clock=clock,
    )
    # 4 chunks x 30s+ of fake clock per chunk: far beyond any serving
    # deadline. deadline=None (the default) must never 504 the sweep.
    report = scorer.run(X, None, run_id="t-slow")
    assert clock.now > 120.0, "fake clock should have spanned minutes"
    assert report["resume"]["chunks_scored"] == 4
    assert report["baseline"]["mean_pd"] > 0.0


def test_explicit_deadline_still_honored_between_chunks(portfolio_setup):
    store, art, X = portfolio_setup
    clock = _TickClock(30.0)
    scorer = PortfolioScorer(
        art, store, shards=1, chunk_rows=CHUNK, compute_shap=False,
        clock=clock,
    )
    with pytest.raises(DeadlineExceeded):
        scorer.run(
            X, None, run_id="t-budget",
            deadline=Deadline(45.0, clock=clock),
        )
    # The tripped budget left a resumable checkpoint, not a corrupt run.
    resumed = scorer.run(X, None, run_id="t-budget", resume=True)
    ref = store.load_array(
        PortfolioScorer(
            art, store, shards=1, chunk_rows=CHUNK, compute_shap=False
        ).run(X, None, run_id="t-budget-ref")["keys"]["scores"]["baseline"]
    )
    assert np.array_equal(
        store.load_array(resumed["keys"]["scores"]["baseline"]), ref
    )


# --- PSI OOD flagging --------------------------------------------------------


def test_scenario_drift_flags_ood_stress_points():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(800, 2)).astype(np.float32)
    names = ["installment", "loan_amnt"]
    sketch = FeatureSketch.from_data(X, names, bins=10)

    benign = scenario_drift(sketch, X.copy(), names, ["installment"])
    assert benign["ood_features"] == []
    assert not benign["ood"]
    assert benign["psi"]["installment"] < 0.05

    shifted = X.copy()
    shifted[:, 0] += 50.0
    ood = scenario_drift(sketch, shifted, names, ["installment"])
    assert ood["ood_features"] == ["installment"]
    assert ood["ood"]
    assert ood["psi"]["installment"] > 0.25
    # Only perturbed features are scored — the warning targets the grid.
    assert "loan_amnt" not in ood["psi"]


def test_engine_reports_ood_warning_not_failure(portfolio_setup):
    store, art, X = portfolio_setup
    sketch = FeatureSketch.from_data(
        X, list(art.feature_names), bins=10
    )
    grid = ScenarioGrid([feature_delta("installment", [0.0, 1e6])])
    report = PortfolioScorer(
        art, store, shards=1, chunk_rows=CHUNK, compute_shap=False,
        training_sketch=sketch,
    ).run(X[:128], grid, run_id="t-ood")
    benign, extreme = report["scenarios"]
    assert not benign["drift"]["ood"]
    assert extreme["drift"]["ood_features"] == ["installment"]
    assert extreme["drift"]["psi"]["installment"] > 0.25

    # Without a sketch the report says why PSI was skipped.
    no_sketch = PortfolioScorer(
        art, store, shards=1, chunk_rows=CHUNK, compute_shap=False
    ).run(X[:128], None, run_id="t-nosketch")
    assert "drift_note" in no_sketch


# --- report / ledger round-trip ----------------------------------------------


def test_report_and_ledger_roundtrip(portfolio_setup, tmp_path):
    from cobalt_smart_lender_ai_tpu.telemetry import RunLedger, load_ledger
    from tools.obs_report import render_report

    store, art, X = portfolio_setup
    grid = ScenarioGrid([feature_delta("installment", [25.0])])
    ledger = RunLedger("portfolio", meta={"run_id": "t-ledger"})
    scorer = PortfolioScorer(art, store, shards=SHARDS, chunk_rows=CHUNK)
    report = scorer.run(X[:128], grid, run_id="t-ledger", ledger=ledger)

    # The report in the store is the report the engine returned (minus the
    # in-memory-only stage timings appended after the write).
    stored = store.get_json(report["keys"]["report"])
    assert stored["run_id"] == "t-ledger"
    assert stored["fingerprint"] == report["fingerprint"]
    assert stored["resume"] == report["resume"]
    assert [b["id"] for b in stored["scenarios"]] == ["installment+25"]
    assert stored["partitioner"]["shards"] == SHARDS
    assert store.exists(stored["keys"]["scores"]["baseline"])

    doc = ledger.write(str(tmp_path / "ledger.json"))
    loaded = load_ledger(str(tmp_path / "ledger.json"))
    assert loaded["kind"] == "portfolio"
    assert set(doc["stages"]) >= {"compile", "score", "reduce", "write"}
    assert loaded["scenario_report"]["run_id"] == "t-ledger"
    # The portfolio dispatch family is a measured family: attribution has
    # a denominator and the portfolio.* programs cover it.
    assert "cobalt_portfolio_dispatch_seconds" in loaded["metrics"]
    attr = loaded["dispatch_attribution"]
    assert attr["ratio"] is not None
    assert attr["ratio"] >= 0.8
    assert any(
        p["name"].startswith("portfolio.") for p in loaded["programs"]
    )
    rendered = render_report(loaded)
    assert "portfolio." in rendered
    assert "Dispatch attribution" in rendered
