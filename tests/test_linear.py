"""Minimum end-to-end slice (SURVEY §7.2): raw CSV-schema frame → cleaned →
engineered device matrix → jitted logistic fit → AUC."""

import numpy as np
from sklearn.linear_model import LogisticRegression as SkLogReg
from sklearn.metrics import roc_auc_score

from cobalt_smart_lender_ai_tpu.models.linear import LogisticRegression


def test_logreg_separable():
    rng = np.random.default_rng(0)
    X = rng.normal(0, 1, (500, 4)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    model = LogisticRegression(l2=1e-3).fit(X, y)
    auc = roc_auc_score(y, np.asarray(model.predict_proba(X)[:, 1]))
    assert auc > 0.99


def test_logreg_close_to_sklearn():
    rng = np.random.default_rng(1)
    n, f = 2000, 8
    X = rng.normal(0, 1, (n, f)).astype(np.float32)
    beta = rng.normal(0, 1, f)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ beta)))).astype(np.float32)
    ours = LogisticRegression(l2=1.0).fit(X, y)
    sk = SkLogReg(C=1.0, max_iter=500).fit((X - X.mean(0)) / X.std(0), y)
    auc_ours = roc_auc_score(y, np.asarray(ours.predict_proba(X)[:, 1]))
    auc_sk = roc_auc_score(y, sk.predict_proba((X - X.mean(0)) / X.std(0))[:, 1])
    assert abs(auc_ours - auc_sk) < 0.005


def test_logreg_handles_nan_and_pos_weight():
    rng = np.random.default_rng(2)
    X = rng.normal(0, 1, (1000, 5)).astype(np.float32)
    X[rng.random(X.shape) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0]) > 0.8).astype(np.float32)  # ~20% positive
    model = LogisticRegression(l2=0.1, pos_weight=4.0).fit(X, y)
    proba = np.asarray(model.predict_proba(X))
    assert proba.shape == (len(y), 2)
    assert np.isfinite(proba).all()
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)
    auc = roc_auc_score(y, proba[:, 1])
    assert auc > 0.85


def test_end_to_end_slice_on_pipeline(train_test):
    X_tr, X_te, y_tr, y_te, names = train_test
    pos = y_tr.mean()
    model = LogisticRegression(l2=1.0, pos_weight=float((1 - pos) / pos)).fit(X_tr, y_tr)
    auc = roc_auc_score(y_te, np.asarray(model.predict_proba(X_te)[:, 1]))
    # linear model on engineered features: decent but below tree-model regime
    assert auc > 0.75
