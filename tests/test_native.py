"""First-party native data-loader (C++ CSV reader, `native/csv_reader.cc`):
parity with pandas' C engine on the reference's data shapes — the capability
SURVEY §2.2 lists as "DataFrame ops: CSV parse ... pandas/numpy C internals".

Skips wholesale if no C++ toolchain is available (the reader then falls back
to pandas at runtime; `test_fallback_when_disabled` still covers that path).
"""

import io

import numpy as np
import pandas as pd
import pytest

from cobalt_smart_lender_ai_tpu import native


def _native_or_skip():
    if not native.native_available():
        pytest.skip("no C++ toolchain; native reader unavailable")


def _assert_frames_match(ours: pd.DataFrame, ref: pd.DataFrame):
    assert list(ours.columns) == list(ref.columns)
    assert len(ours) == len(ref)
    for col in ref.columns:
        if pd.api.types.is_numeric_dtype(ref[col]):
            # strtod and pandas' float parser may disagree in the last ulp
            np.testing.assert_allclose(
                ours[col].to_numpy(dtype=np.float64),
                ref[col].to_numpy(dtype=np.float64),
                rtol=1e-12,
                atol=0,
                equal_nan=True,
                err_msg=col,
            )
        else:
            a = ours[col].fillna("").astype(str).tolist()
            b = ref[col].fillna("").astype(str).tolist()
            assert a == b, col


def test_rfc4180_edge_cases_match_pandas():
    _native_or_skip()
    csv = (
        b"a,b c,d\n"  # header with a space
        b'1,"hello, world",x\n'
        b'2,"quote "" inside",\n'
        b'3,"multi\nline cell",y\r\n'  # embedded newline + CRLF terminator
        b",plain,z\n"
        b"\n"  # blank line mid-file is skipped
        b"4e-2,  ,w"  # trailing row without newline; whitespace-only cell
    )
    ours = native.read_csv(csv, engine="native")
    ref = pd.read_csv(io.BytesIO(csv))
    _assert_frames_match(ours, ref)


def test_synthetic_lendingclub_round_trip():
    """The real workload: the full-schema synthetic frame (mixed numeric /
    string / empty cells) written by `save_frame`, parsed back natively."""
    _native_or_skip()
    from cobalt_smart_lender_ai_tpu.data.synthetic import (
        synthetic_lendingclub_frame,
    )

    raw = synthetic_lendingclub_frame(2000, seed=3)
    buf = io.BytesIO()
    raw.to_csv(buf, index=False)
    data = buf.getvalue()
    ours = native.read_csv(data, engine="native")
    ref = pd.read_csv(io.BytesIO(data), low_memory=False)
    _assert_frames_match(ours, ref)


def test_numeric_inference_rules():
    _native_or_skip()
    csv = b"i,f,mixed,empty,nan_token\n1,1.5,1,,nan\n2,-2e3,x,,3\n"
    cols = native.parse_csv_columns(csv)
    assert isinstance(cols["i"], np.ndarray) and cols["i"].dtype == np.float64
    assert isinstance(cols["f"], np.ndarray)
    assert isinstance(cols["mixed"], list)  # "x" poisons numeric inference
    assert isinstance(cols["empty"], np.ndarray)  # all-empty stays numeric
    assert np.isnan(cols["empty"]).all()
    assert np.isnan(cols["nan_token"][0]) and cols["nan_token"][1] == 3.0


def test_whitespace_only_cell_is_not_zero():
    """A whitespace-only cell must not parse as 0.0 (strtod's no-conversion
    case) — it makes the column string-typed, as pandas does."""
    _native_or_skip()
    csv = b"a,b\n1,x\n  ,y\n2,z\n"
    ours = native.read_csv(csv, engine="native")
    ref = pd.read_csv(io.BytesIO(csv))
    _assert_frames_match(ours, ref)
    assert not pd.api.types.is_numeric_dtype(ours["a"])


def test_pandas_na_tokens_recognized():
    """pandas' default NA tokens (NA, N/A, NULL, None, <NA>, ...) must be
    missing values under the native engine too — same float64 dtype, same
    NaNs — or the pipeline would behave differently with/without g++."""
    _native_or_skip()
    csv = b"a,s\n1,x\nNA,NULL\n2,None\nN/A,<NA>\n"
    ours = native.read_csv(csv, engine="native")
    ref = pd.read_csv(io.BytesIO(csv))
    _assert_frames_match(ours, ref)
    assert pd.api.types.is_numeric_dtype(ours["a"])
    np.testing.assert_allclose(
        ours["a"].to_numpy(np.float64), [1.0, np.nan, 2.0, np.nan], equal_nan=True
    )
    assert ours["s"].isna().tolist() == [False, True, True, True]


def test_hex_and_locale_free_parsing():
    """strtod pitfalls the reader must not have: C99 hex floats must stay
    strings (pandas parity), while inf/nan tokens and padded/'+'-signed
    numbers parse as floats."""
    _native_or_skip()
    csv = b"hex,num\n0x1A,+1\n0x2B, 2.5 \nabc,inf\n"
    ours = native.read_csv(csv, engine="native")
    ref = pd.read_csv(io.BytesIO(csv))
    _assert_frames_match(ours, ref)
    assert not pd.api.types.is_numeric_dtype(ours["hex"])
    assert pd.api.types.is_numeric_dtype(ours["num"])
    assert np.isinf(ours["num"].to_numpy(np.float64)[2])


def test_quoted_empty_row_is_kept():
    """A single-column row containing '""' is a real (missing) row, not a
    blank line — row counts must match pandas."""
    _native_or_skip()
    csv = b'a\n""\n1\n'
    ours = native.read_csv(csv, engine="native")
    ref = pd.read_csv(io.BytesIO(csv))
    assert len(ours) == len(ref) == 2
    np.testing.assert_allclose(
        ours["a"].to_numpy(np.float64), [np.nan, 1.0], equal_nan=True
    )


def test_short_and_long_rows_tolerated():
    _native_or_skip()
    csv = b"a,b,c\n1,x\n2,y,3,EXTRA\n"
    ours = native.read_csv(csv, engine="native")
    assert len(ours) == 2
    assert np.isnan(ours["c"].to_numpy(np.float64)[0])  # short row padded
    assert ours["c"].to_numpy(np.float64)[1] == 3.0  # overflow cell dropped


def test_store_load_frame_uses_reader(tmp_path):
    """ObjectStore.load_frame round-trips a frame through whichever engine
    is active (native where built, pandas otherwise)."""
    from cobalt_smart_lender_ai_tpu.io import ObjectStore

    store = ObjectStore(str(tmp_path / "lake"))
    df = pd.DataFrame({"x": [1.0, np.nan, 3.0], "s": ["a", None, "c,d"]})
    store.save_frame("t.csv", df)
    out = store.load_frame("t.csv")
    np.testing.assert_allclose(
        out["x"].to_numpy(np.float64), [1.0, np.nan, 3.0], equal_nan=True
    )
    assert out["s"].fillna("").tolist() == ["a", "", "c,d"]


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_random_frames_match_pandas(seed):
    """Property test: random frames with adversarial cell content must parse
    identically (values + dtypes + row count) through both engines."""
    _native_or_skip()
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 200))
    pieces = {}
    nasty = [
        "", "a,b", 'say "hi"', "line\nbreak", "NA", "null", "None", "nan",
        "0x1F", " padded ", "+5", "-", ".", "1e", "e5", "inf", "-inf",
        "'quote", "trail,", "日本語", "a" * 200,
    ]
    for j in range(int(rng.integers(1, 8))):
        kind = rng.integers(0, 3)
        if kind == 0:  # numeric with missing
            col = rng.normal(size=n)
            col[rng.random(n) < 0.3] = np.nan
            pieces[f"num{j}"] = col
        elif kind == 1:  # ints
            pieces[f"int{j}"] = rng.integers(-1000, 1000, n)
        else:  # nasty strings
            pieces[f"str{j}"] = [
                nasty[int(rng.integers(len(nasty)))] for _ in range(n)
            ]
    df = pd.DataFrame(pieces)
    buf = io.BytesIO()
    df.to_csv(buf, index=False)
    data = buf.getvalue()
    ours = native.read_csv(data, engine="native")
    ref = pd.read_csv(io.BytesIO(data))
    _assert_frames_match(ours, ref)


def test_no_pyarrow_fallback_matches_pandas(monkeypatch):
    """The no-pyarrow branch of `_read_native` (str-list Series) must produce
    the same frame as the Arrow zero-copy path — pyarrow is installed in CI,
    so without this monkeypatch that branch never runs."""
    _native_or_skip()
    import sys

    csv = (
        b"a,b c,d\n"
        b'1,"hello, world",x\n'
        b'2,"quote "" inside",\n'  # empty string cell -> missing
        b"3,plain,y\n"
    )
    # None in sys.modules makes `import pyarrow` raise ImportError. Scope the
    # patch to the parse only: pandas itself lazily imports pyarrow when the
    # assertions below touch arrow-backed str columns.
    with monkeypatch.context() as m:
        m.setitem(sys.modules, "pyarrow", None)
        ours = native.read_csv(csv, engine="native")
    ref = pd.read_csv(io.BytesIO(csv))
    _assert_frames_match(ours, ref)
    # Empty cells mean missing in BOTH branches (the divergence the Arrow
    # path encodes with if_else(equal(arr, ""), None, arr)).
    assert ours["d"].isna().tolist() == [False, True, False]


def test_fallback_when_disabled(monkeypatch):
    """engine='auto' must work with the native reader force-disabled."""
    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setattr(native, "_LIB_ERR", "disabled for test")
    csv = b"a,b\n1,x\n"
    df = native.read_csv(csv, engine="auto")
    assert df["a"].tolist() == [1] and df["b"].tolist() == ["x"]
    with pytest.raises(RuntimeError):
        native.parse_csv_columns(csv)
