"""Closed-loop serving-throughput benchmark for the micro-batching scheduler.

Trains a small GBDT on the synthetic LendingClub frame in-process (no store,
no network), then hammers `ScorerService.predict_single` from N closed-loop
client threads — each client issues its next request the moment the previous
one resolves, exactly the concurrency shape the micro-batcher coalesces.
Run with ``--mode both`` to measure batcher-on vs batcher-off on the same
trained model and emit one JSON line suitable for committing as a
``BENCH_SERVE_*.json`` record:

    JAX_PLATFORMS=cpu python bench_serve.py --clients 32 --duration-s 5

``--mix mixed`` interleaves bulk-CSV calls (1 in 8) with single-row scoring
to show the batcher coexisting with large explicit batches; ``--smoke`` is
the CI profile (4 clients, ~1s) asserting the harness end-to-end without
burning minutes.

Latency percentiles are computed over single-row requests only (bulk calls
are reported separately) and the warmup window — which absorbs lazy bucket
compiles — is excluded from every metric.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import threading
import time


def _percentile(samples: list[float], q: float) -> float:
    """Nearest-rank percentile; ``samples`` must be sorted ascending."""
    if not samples:
        return float("nan")
    idx = min(len(samples) - 1, max(0, int(round(q * (len(samples) - 1)))))
    return samples[idx]


def build_service(config, n_rows: int, seed: int = 7):
    """Train a small serving-contract model and wrap it in a `ScorerService`
    (the conftest `serving_artifact` recipe, minus the object store)."""
    import numpy as np

    from cobalt_smart_lender_ai_tpu.data import schema
    from cobalt_smart_lender_ai_tpu.data.clean import clean_raw_frame
    from cobalt_smart_lender_ai_tpu.data.features import (
        engineer_features,
        prepare_cleaned_frame,
    )
    from cobalt_smart_lender_ai_tpu.data.synthetic import (
        synthetic_lendingclub_frame,
    )
    from cobalt_smart_lender_ai_tpu.io import GBDTArtifact
    from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTClassifier
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    raw = synthetic_lendingclub_frame(n_rows=n_rows, seed=seed)
    cleaned, _ = clean_raw_frame(raw)
    tree_ff, _, _ = engineer_features(prepare_cleaned_frame(cleaned))
    ff = tree_ff.select(schema.SERVING_FEATURES)
    model = GBDTClassifier(n_estimators=25, max_depth=3, n_bins=64)
    model.fit(np.asarray(ff.X), np.asarray(ff.y))
    artifact = GBDTArtifact(
        forest=model.forest,
        bin_spec=model.bin_spec,
        feature_names=tuple(schema.SERVING_FEATURES),
    )
    return ScorerService(artifact, config), np.array(ff.X)


def build_payloads(X, n_payloads: int = 256) -> list[dict]:
    """Distinct request bodies cycled by the clients, keyed by the aliased
    wire-format field names the validation schema expects. The tree matrix
    carries NaN (trees route missing natively) but the single-input schema
    requires finite values, so NaN becomes 0.0 on the wire."""
    import math

    from cobalt_smart_lender_ai_tpu.data import schema

    keys = [
        schema.SERVING_FIELD_ALIASES.get(name, name)
        for name in schema.SERVING_FEATURES
    ]
    payloads = []
    for i in range(min(n_payloads, X.shape[0])):
        payloads.append(
            {
                k: float(v) if math.isfinite(v) else 0.0
                for k, v in zip(keys, X[i])
            }
        )
    return payloads


def run_load(
    service,
    payloads: list[dict],
    csv_bytes: bytes | None,
    *,
    clients: int,
    duration_s: float,
    warmup_s: float,
    mix: str,
) -> dict:
    """Drive `clients` closed-loop threads against `service` and return the
    steady-state (post-warmup) throughput/latency summary."""
    start_barrier = threading.Barrier(clients + 1)
    stop_at = [0.0]  # filled in after the barrier releases
    record_from = [0.0]
    single_lat: list[list[float]] = [[] for _ in range(clients)]
    bulk_lat: list[list[float]] = [[] for _ in range(clients)]
    bulk_rows: list[int] = [0] * clients
    errors: list[int] = [0] * clients

    def client(idx: int) -> None:
        start_barrier.wait()
        i = idx  # offset so clients don't all score the same row
        while True:
            now = time.monotonic()
            if now >= stop_at[0]:
                return
            is_bulk = csv_bytes is not None and mix == "mixed" and i % 8 == 7
            t0 = time.perf_counter()
            try:
                if is_bulk:
                    resp = service.predict_bulk_csv(csv_bytes)
                    n = len(resp["predictions"])
                else:
                    service.predict_single(payloads[i % len(payloads)])
                    n = 0
            except Exception:
                errors[idx] += 1
                i += 1
                continue
            elapsed_ms = (time.perf_counter() - t0) * 1e3
            if now >= record_from[0]:
                if is_bulk:
                    bulk_lat[idx].append(elapsed_ms)
                    bulk_rows[idx] += n
                else:
                    single_lat[idx].append(elapsed_ms)
            i += 1

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(clients)
    ]
    for t in threads:
        t.start()
    t_start = time.monotonic()
    record_from[0] = t_start + warmup_s
    stop_at[0] = record_from[0] + duration_s
    start_barrier.wait()
    for t in threads:
        t.join()

    singles = sorted(lat for per in single_lat for lat in per)
    bulks = sorted(lat for per in bulk_lat for lat in per)
    n_singles, n_bulks = len(singles), len(bulks)
    result = {
        "requests": n_singles + n_bulks,
        "qps": round((n_singles + n_bulks) / duration_s, 1),
        "single_qps": round(n_singles / duration_s, 1),
        "errors": sum(errors),
        "p50_ms": round(_percentile(singles, 0.50), 3),
        "p95_ms": round(_percentile(singles, 0.95), 3),
        "p99_ms": round(_percentile(singles, 0.99), 3),
        "p99.9_ms": round(_percentile(singles, 0.999), 3),
        "max_ms": round(singles[-1], 3) if singles else float("nan"),
        "mean_ms": round(statistics.fmean(singles), 3) if singles else float("nan"),
    }
    if n_bulks:
        result["bulk_calls"] = n_bulks
        result["bulk_rows_per_s"] = round(sum(bulk_rows) / duration_s, 1)
        result["bulk_p95_ms"] = round(_percentile(bulks, 0.95), 3)
    if service.batcher is not None:
        result["microbatch"] = service.batcher.stats()
    phases = _phase_breakdown(service.registry)
    if phases:
        result["phases"] = phases
    return result


def _phase_breakdown(registry) -> dict[str, dict]:
    """Where the time went, per request phase, from the
    ``cobalt_request_phase_seconds`` histogram the service populates on
    every `predict_single` — the bench-record answer to "queue-wait or
    dispatch or SHAP?". Includes warmup traffic (cumulative counters), so
    cold compiles show up in the phase that paid them."""
    fam = registry.snapshot().get("cobalt_request_phase_seconds")
    if not fam:
        return {}
    out: dict[str, dict] = {}
    total_s = sum(s["sum"] for s in fam["samples"]) or 1.0
    for sample in fam["samples"]:
        phase = sample["labels"].get("phase", "?")
        count = sample["count"]
        if not count:
            continue
        out[phase] = {
            "count": count,
            "mean_ms": round(sample["sum"] / count * 1e3, 3),
            "total_ms": round(sample["sum"] * 1e3, 1),
            "share": round(sample["sum"] / total_s, 3),
        }
    return out


def run_http_smoke(
    config,
    artifact,
    payloads: list[dict],
    *,
    clients: int,
    duration_s: float,
) -> dict:
    """Stand up the stdlib HTTP server on a loopback port, drive concurrent
    POST /predict load over real sockets, and scrape ``GET /metrics`` both
    mid-load and after — validating the exposition parses and the
    request-latency histogram actually counted the traffic. This is the CI
    gate for the telemetry wiring (tier1.yml bench-smoke job)."""
    import http.client

    from cobalt_smart_lender_ai_tpu.serve.http_stdlib import make_server
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService
    from cobalt_smart_lender_ai_tpu.telemetry import parse_exposition

    service = ScorerService(artifact, config)
    httpd = make_server(service)
    port = httpd.server_address[1]
    server_thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    server_thread.start()

    errors = [0] * clients
    requests = [0] * clients
    stop_at = time.monotonic() + duration_s

    def client(idx: int) -> None:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        i = idx
        while time.monotonic() < stop_at:
            body = json.dumps(payloads[i % len(payloads)])
            try:
                conn.request(
                    "POST",
                    "/predict",
                    body,
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                resp.read()
                requests[idx] += 1
                if resp.status != 200:
                    errors[idx] += 1
            except Exception:
                errors[idx] += 1
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            i += 1
        conn.close()

    def scrape(path: str = "/metrics", accept: str | None = None) -> tuple[str, str]:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            conn.request("GET", path, headers={"Accept": accept} if accept else {})
            resp = conn.getresponse()
            text = resp.read().decode()
            return text, resp.getheader("Content-Type") or ""
        finally:
            conn.close()

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(clients)
    ]
    try:
        for t in threads:
            t.start()
        # scrape while the load is live: the endpoint must serve cleanly
        # under concurrent traffic, not just at rest
        time.sleep(duration_s / 2)
        during_text, during_ctype = scrape()
        parse_exposition(during_text)
        for t in threads:
            t.join()
        final_text, _ = scrape()
        families = parse_exposition(final_text)
        # the OpenMetrics variant (exemplar trace ids on latency buckets)
        # must parse through the same strict parser
        om_text, om_ctype = scrape(accept="application/openmetrics-text")
        parse_exposition(om_text)
        slo_report = json.loads(scrape("/slo")[0])
        slowest = json.loads(scrape("/debug/slowest?k=3")[0])
    finally:
        httpd.shutdown()
        httpd.server_close()
        service.close()

    latency = families.get("cobalt_request_latency_seconds", {"samples": {}})
    latency_count = sum(
        v
        for k, v in latency["samples"].items()
        if k.startswith("cobalt_request_latency_seconds_count")
    )
    batch_rows = families.get("cobalt_microbatch_batch_rows", {"samples": {}})
    batch_count = sum(
        v
        for k, v in batch_rows["samples"].items()
        if k.startswith("cobalt_microbatch_batch_rows_count")
    )
    top = (slowest.get("slowest") or [{}])[0]
    top_phases = top.get("phases_ms") or {}
    return {
        "requests": sum(requests),
        "errors": sum(errors),
        "families": len(families),
        "scrape_during_load_ok": bool(during_ctype.startswith("text/plain")),
        "openmetrics_ok": bool(
            om_ctype.startswith("application/openmetrics-text")
            and om_text.rstrip().endswith("# EOF")
        ),
        "request_latency_count": int(latency_count),
        "microbatch_batch_count": int(batch_count),
        # SLO + flight-recorder forensics over real sockets — CI fails the
        # build on fast_burn and keeps the slowest request's phase verdict
        # in the committed record
        "slo_fast_burn": bool(slo_report.get("fast_burn")),
        "slo_burn_rates": {
            o["name"]: o["windows"][0]["burn_rate"]
            for o in slo_report.get("objectives", [])
        },
        "slowest_ms": top.get("duration_ms"),
        "slowest_phase": (
            max(top_phases, key=top_phases.get) if top_phases else None
        ),
    }


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--clients", type=int, default=32)
    parser.add_argument("--duration-s", type=float, default=5.0)
    parser.add_argument("--warmup-s", type=float, default=1.5)
    parser.add_argument("--rows", type=int, default=2000,
                        help="synthetic training rows")
    parser.add_argument("--mix", choices=("single", "mixed"), default="single")
    parser.add_argument("--mode", choices=("both", "on", "off"), default="both")
    parser.add_argument("--microbatch-wait-ms", type=float, default=None)
    parser.add_argument("--microbatch-max-rows", type=int, default=None)
    parser.add_argument("--smoke", action="store_true",
                        help="CI profile: 4 clients, ~1s per mode")
    parser.add_argument("--http-smoke", action="store_true",
                        help="also drive load over real HTTP and scrape "
                        "/metrics during it (validates the telemetry wiring; "
                        "result lands under record['metrics_scrape'])")
    parser.add_argument("--out", default=None,
                        help="also write the JSON line to this path")
    parser.add_argument("--trace-out", default=None,
                        help="write the run's span ring as Chrome Trace "
                        "Event / Perfetto JSON to this path (open in "
                        "ui.perfetto.dev; CI uploads it as an artifact)")
    args = parser.parse_args(argv)
    if args.smoke:
        args.clients = min(args.clients, 4)
        args.duration_s = min(args.duration_s, 1.0)
        args.warmup_s = min(args.warmup_s, 0.5)
        args.rows = min(args.rows, 800)

    from cobalt_smart_lender_ai_tpu.config import ServeConfig

    mb_kwargs = {}
    if args.microbatch_wait_ms is not None:
        mb_kwargs["microbatch_max_wait_ms"] = args.microbatch_wait_ms
    if args.microbatch_max_rows is not None:
        mb_kwargs["microbatch_max_rows"] = args.microbatch_max_rows

    modes = {"both": ("off", "on"), "on": ("on",), "off": ("off",)}[args.mode]
    results: dict[str, dict] = {}
    service = None
    X = None
    for mode in modes:
        config = ServeConfig(microbatch_enabled=(mode == "on"), **mb_kwargs)
        if service is None:
            print(f"[bench] training model ({args.rows} synthetic rows)...",
                  file=sys.stderr)
            service, X = build_service(config, n_rows=args.rows)
        else:
            # same trained artifact, fresh compile cache per mode
            from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

            service = ScorerService(service.artifact, config)
        payloads = build_payloads(X)
        csv_bytes = None
        if args.mix == "mixed":
            import pandas as pd

            from cobalt_smart_lender_ai_tpu.data import schema

            csv_bytes = (
                pd.DataFrame(X[:64], columns=list(schema.SERVING_FEATURES))
                .to_csv(index=False)
                .encode()
            )
        print(
            f"[bench] batcher_{mode}: {args.clients} clients, "
            f"{args.duration_s:g}s measured (+{args.warmup_s:g}s warmup)...",
            file=sys.stderr,
        )
        results[f"batcher_{mode}"] = run_load(
            service,
            payloads,
            csv_bytes,
            clients=args.clients,
            duration_s=args.duration_s,
            warmup_s=args.warmup_s,
            mix=args.mix,
        )
        # attach this mode's metric values + recent spans so the committed
        # bench record carries the run's internals, not just the headline
        from cobalt_smart_lender_ai_tpu.telemetry import snapshot

        results[f"batcher_{mode}"]["telemetry"] = snapshot(
            service.registry, span_limit=32
        )
        artifact = service.artifact
        service.close()

    if args.http_smoke:
        print(
            f"[bench] http smoke: {min(args.clients, 4)} clients over real "
            "sockets, scraping /metrics...",
            file=sys.stderr,
        )
        # SLO thresholds are CI-noise-proof here: shared runners hiccup, and
        # the gate below is "no fast burn", not the production 10ms target
        record_scrape = run_http_smoke(
            ServeConfig(
                microbatch_enabled=True,
                slo_p99_ms=250.0,
                slo_p999_ms=2000.0,
                **mb_kwargs,
            ),
            artifact,
            payloads,
            clients=min(args.clients, 4),
            duration_s=min(args.duration_s, 2.0),
        )
    else:
        record_scrape = None

    record = {
        "bench": "serve_throughput",
        "clients": args.clients,
        "duration_s": args.duration_s,
        "mix": args.mix,
        "platform": _platform_tag(),
        "results": results,
    }
    if record_scrape is not None:
        record["metrics_scrape"] = record_scrape
    if "batcher_on" in results and "batcher_off" in results:
        off, on = results["batcher_off"], results["batcher_on"]
        if off["qps"] > 0:
            record["qps_speedup"] = round(on["qps"] / off["qps"], 2)
    line = json.dumps(record)
    print(line)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(line + "\n")
    if args.trace_out:
        from cobalt_smart_lender_ai_tpu.telemetry import (
            default_tracer,
            render_chrome_trace,
        )

        with open(args.trace_out, "w") as fh:
            fh.write(render_chrome_trace(default_tracer()))
        print(f"[bench] perfetto trace written to {args.trace_out}",
              file=sys.stderr)
    return 0


def _platform_tag() -> str:
    import jax

    return jax.devices()[0].platform


if __name__ == "__main__":
    raise SystemExit(main())
