"""Closed-loop serving-throughput benchmark for the micro-batching scheduler.

Trains a small GBDT on the synthetic LendingClub frame in-process (no store,
no network), then hammers `ScorerService.predict_single` from N closed-loop
client threads — each client issues its next request the moment the previous
one resolves, exactly the concurrency shape the micro-batcher coalesces.
Run with ``--mode both`` to measure batcher-on vs batcher-off on the same
trained model and emit one JSON line suitable for committing as a
``BENCH_SERVE_*.json`` record:

    JAX_PLATFORMS=cpu python bench_serve.py --clients 32 --duration-s 5

``--mix mixed`` interleaves bulk-CSV calls (1 in 8) with single-row scoring
to show the batcher coexisting with large explicit batches; ``--smoke`` is
the CI profile (4 clients, ~1s) asserting the harness end-to-end without
burning minutes.

Latency percentiles are computed over single-row requests only (bulk calls
are reported separately) and the warmup window — which absorbs lazy bucket
compiles — is excluded from every metric.

``--bulk`` switches to the mesh-sharded bulk-scoring bench (README "Scaling
out"): score one large (N, F) matrix through `ScorerService.predict_proba`
at each requested ``bulk_shards`` setting and record rows/s per shard count
plus the sharded-vs-single speedup and a bit-identity check, suitable for
committing as a ``BENCH_BULK_*.json`` record:

    XLA_FLAGS=--xla_force_host_platform_device_count=4 JAX_PLATFORMS=cpu \\
        python bench_serve.py --bulk --out BENCH_BULK_r01.json

(or pass ``--force-devices 4``, which sets the flag before JAX loads).
The record carries ``host_cpu_cores``: on a single-core host the forced
devices share one core, so the curve flattens — the scaling headroom shows
on hosts with >= one core per forced device, which is what the CI
bulk-smoke job runs.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import threading
import time


def _percentile(samples: list[float], q: float) -> float:
    """Nearest-rank percentile; ``samples`` must be sorted ascending."""
    if not samples:
        return float("nan")
    idx = min(len(samples) - 1, max(0, int(round(q * (len(samples) - 1)))))
    return samples[idx]


def _fetch_events(port: int, **params) -> dict:
    """``GET /events`` against the bench server — the fleet's event
    journal is the bench's source of truth for control-plane state
    (heals, resizes, brownout rungs), read over the same HTTP surface an
    operator would use instead of reaching into fleet internals."""
    import urllib.request
    from urllib.parse import urlencode

    qs = urlencode({k: v for k, v in params.items() if v is not None})
    url = f"http://127.0.0.1:{port}/events" + (f"?{qs}" if qs else "")
    with urllib.request.urlopen(url, timeout=5.0) as resp:
        return json.loads(resp.read().decode())


def _events_block(port: int) -> dict | None:
    """The committed record's journal snapshot (``events.journal`` +
    ``events.stats``) — the input `tools/incident_report.py` stitches
    into a postmortem. ``None`` if the server is already gone."""
    try:
        doc = _fetch_events(port)
    except Exception:
        return None
    return {"journal": doc.get("events", []), "stats": doc.get("stats", {})}


def build_service(config, n_rows: int, seed: int = 7):
    """Train a small serving-contract model and wrap it in a `ScorerService`
    (the conftest `serving_artifact` recipe, minus the object store)."""
    import numpy as np

    from cobalt_smart_lender_ai_tpu.data import schema
    from cobalt_smart_lender_ai_tpu.data.clean import clean_raw_frame
    from cobalt_smart_lender_ai_tpu.data.features import (
        engineer_features,
        prepare_cleaned_frame,
    )
    from cobalt_smart_lender_ai_tpu.data.synthetic import (
        synthetic_lendingclub_frame,
    )
    from cobalt_smart_lender_ai_tpu.io import GBDTArtifact
    from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTClassifier
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    raw = synthetic_lendingclub_frame(n_rows=n_rows, seed=seed)
    cleaned, _ = clean_raw_frame(raw)
    tree_ff, _, _ = engineer_features(prepare_cleaned_frame(cleaned))
    ff = tree_ff.select(schema.SERVING_FEATURES)
    model = GBDTClassifier(n_estimators=25, max_depth=3, n_bins=64)
    model.fit(np.asarray(ff.X), np.asarray(ff.y))
    artifact = GBDTArtifact(
        forest=model.forest,
        bin_spec=model.bin_spec,
        feature_names=tuple(schema.SERVING_FEATURES),
    )
    return ScorerService(artifact, config), np.array(ff.X)


def build_payloads(X, n_payloads: int = 256) -> list[dict]:
    """Distinct request bodies cycled by the clients, keyed by the aliased
    wire-format field names the validation schema expects. The tree matrix
    carries NaN (trees route missing natively) but the single-input schema
    requires finite values, so NaN becomes 0.0 on the wire."""
    import math

    from cobalt_smart_lender_ai_tpu.data import schema

    keys = [
        schema.SERVING_FIELD_ALIASES.get(name, name)
        for name in schema.SERVING_FEATURES
    ]
    payloads = []
    for i in range(min(n_payloads, X.shape[0])):
        payloads.append(
            {
                k: float(v) if math.isfinite(v) else 0.0
                for k, v in zip(keys, X[i])
            }
        )
    return payloads


def run_load(
    service,
    payloads: list[dict],
    csv_bytes: bytes | None,
    *,
    clients: int,
    duration_s: float,
    warmup_s: float,
    mix: str,
) -> dict:
    """Drive `clients` closed-loop threads against `service` and return the
    steady-state (post-warmup) throughput/latency summary."""
    start_barrier = threading.Barrier(clients + 1)
    stop_at = [0.0]  # filled in after the barrier releases
    record_from = [0.0]
    single_lat: list[list[float]] = [[] for _ in range(clients)]
    bulk_lat: list[list[float]] = [[] for _ in range(clients)]
    bulk_rows: list[int] = [0] * clients
    errors: list[int] = [0] * clients

    def client(idx: int) -> None:
        start_barrier.wait()
        i = idx  # offset so clients don't all score the same row
        while True:
            now = time.monotonic()
            if now >= stop_at[0]:
                return
            is_bulk = csv_bytes is not None and mix == "mixed" and i % 8 == 7
            t0 = time.perf_counter()
            try:
                if is_bulk:
                    resp = service.predict_bulk_csv(csv_bytes)
                    n = len(resp["predictions"])
                else:
                    service.predict_single(payloads[i % len(payloads)])
                    n = 0
            except Exception:
                errors[idx] += 1
                i += 1
                continue
            elapsed_ms = (time.perf_counter() - t0) * 1e3
            if now >= record_from[0]:
                if is_bulk:
                    bulk_lat[idx].append(elapsed_ms)
                    bulk_rows[idx] += n
                else:
                    single_lat[idx].append(elapsed_ms)
            i += 1

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(clients)
    ]
    for t in threads:
        t.start()
    t_start = time.monotonic()
    record_from[0] = t_start + warmup_s
    stop_at[0] = record_from[0] + duration_s
    start_barrier.wait()
    for t in threads:
        t.join()

    singles = sorted(lat for per in single_lat for lat in per)
    bulks = sorted(lat for per in bulk_lat for lat in per)
    n_singles, n_bulks = len(singles), len(bulks)
    result = {
        "requests": n_singles + n_bulks,
        "qps": round((n_singles + n_bulks) / duration_s, 1),
        "single_qps": round(n_singles / duration_s, 1),
        "errors": sum(errors),
        "p50_ms": round(_percentile(singles, 0.50), 3),
        "p95_ms": round(_percentile(singles, 0.95), 3),
        "p99_ms": round(_percentile(singles, 0.99), 3),
        "p99.9_ms": round(_percentile(singles, 0.999), 3),
        "max_ms": round(singles[-1], 3) if singles else float("nan"),
        "mean_ms": round(statistics.fmean(singles), 3) if singles else float("nan"),
    }
    if n_bulks:
        result["bulk_calls"] = n_bulks
        result["bulk_rows_per_s"] = round(sum(bulk_rows) / duration_s, 1)
        result["bulk_p95_ms"] = round(_percentile(bulks, 0.95), 3)
    if service.batcher is not None:
        result["microbatch"] = service.batcher.stats()
    phases = _phase_breakdown(service.registry)
    if phases:
        result["phases"] = phases
    return result


def _phase_breakdown(registry) -> dict[str, dict]:
    """Where the time went, per request phase, from the
    ``cobalt_request_phase_seconds`` histogram the service populates on
    every `predict_single` — the bench-record answer to "queue-wait or
    dispatch or SHAP?". Includes warmup traffic (cumulative counters), so
    cold compiles show up in the phase that paid them."""
    fam = registry.snapshot().get("cobalt_request_phase_seconds")
    if not fam:
        return {}
    out: dict[str, dict] = {}
    total_s = sum(s["sum"] for s in fam["samples"]) or 1.0
    for sample in fam["samples"]:
        phase = sample["labels"].get("phase", "?")
        count = sample["count"]
        if not count:
            continue
        out[phase] = {
            "count": count,
            "mean_ms": round(sample["sum"] / count * 1e3, 3),
            "total_ms": round(sample["sum"] * 1e3, 1),
            "share": round(sample["sum"] / total_s, 3),
        }
    return out


def run_http_smoke(
    config,
    artifact,
    payloads: list[dict],
    *,
    clients: int,
    duration_s: float,
) -> dict:
    """Stand up the asyncio HTTP server on a loopback port, drive concurrent
    POST /predict load over real sockets, and scrape ``GET /metrics`` both
    mid-load and after — validating the exposition parses and the
    request-latency histogram actually counted the traffic. This is the CI
    gate for the telemetry wiring (tier1.yml bench-smoke job)."""
    import http.client

    from cobalt_smart_lender_ai_tpu.serve.http_asyncio import make_async_server
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService
    from cobalt_smart_lender_ai_tpu.telemetry import parse_exposition

    service = ScorerService(artifact, config)
    server = make_async_server(service)
    port = server.port

    errors = [0] * clients
    requests = [0] * clients
    stop_at = time.monotonic() + duration_s

    def client(idx: int) -> None:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        i = idx
        while time.monotonic() < stop_at:
            body = json.dumps(payloads[i % len(payloads)])
            try:
                conn.request(
                    "POST",
                    "/predict",
                    body,
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                resp.read()
                requests[idx] += 1
                if resp.status != 200:
                    errors[idx] += 1
            except Exception:
                errors[idx] += 1
                conn.close()
                conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            i += 1
        conn.close()

    def scrape(path: str = "/metrics", accept: str | None = None) -> tuple[str, str]:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            conn.request("GET", path, headers={"Accept": accept} if accept else {})
            resp = conn.getresponse()
            text = resp.read().decode()
            return text, resp.getheader("Content-Type") or ""
        finally:
            conn.close()

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(clients)
    ]
    try:
        for t in threads:
            t.start()
        # scrape while the load is live: the endpoint must serve cleanly
        # under concurrent traffic, not just at rest
        time.sleep(duration_s / 2)
        during_text, during_ctype = scrape()
        parse_exposition(during_text)
        for t in threads:
            t.join()
        final_text, _ = scrape()
        families = parse_exposition(final_text)
        # the OpenMetrics variant (exemplar trace ids on latency buckets)
        # must parse through the same strict parser
        om_text, om_ctype = scrape(accept="application/openmetrics-text")
        parse_exposition(om_text)
        slo_report = json.loads(scrape("/slo")[0])
        slowest = json.loads(scrape("/debug/slowest?k=3")[0])
    finally:
        server.close()
        service.close()

    latency = families.get("cobalt_request_latency_seconds", {"samples": {}})
    latency_count = sum(
        v
        for k, v in latency["samples"].items()
        if k.startswith("cobalt_request_latency_seconds_count")
    )
    batch_rows = families.get("cobalt_microbatch_batch_rows", {"samples": {}})
    batch_count = sum(
        v
        for k, v in batch_rows["samples"].items()
        if k.startswith("cobalt_microbatch_batch_rows_count")
    )
    top = (slowest.get("slowest") or [{}])[0]
    top_phases = top.get("phases_ms") or {}
    return {
        "requests": sum(requests),
        "errors": sum(errors),
        "families": len(families),
        "scrape_during_load_ok": bool(during_ctype.startswith("text/plain")),
        "openmetrics_ok": bool(
            om_ctype.startswith("application/openmetrics-text")
            and om_text.rstrip().endswith("# EOF")
        ),
        "request_latency_count": int(latency_count),
        "microbatch_batch_count": int(batch_count),
        # SLO + flight-recorder forensics over real sockets — CI fails the
        # build on fast_burn and keeps the slowest request's phase verdict
        # in the committed record
        "slo_fast_burn": bool(slo_report.get("fast_burn")),
        "slo_burn_rates": {
            o["name"]: o["windows"][0]["burn_rate"]
            for o in slo_report.get("objectives", [])
        },
        "slowest_ms": top.get("duration_ms"),
        "slowest_phase": (
            max(top_phases, key=top_phases.get) if top_phases else None
        ),
    }


async def _read_http_response(reader) -> tuple[int, bytes]:
    """Minimal HTTP/1.1 response parse (status + Content-Length body) for
    the async closed-loop clients — keep-alive, no chunked encoding."""
    line = await reader.readline()
    if not line:
        raise ConnectionError("server closed connection")
    status = int(line.split(None, 2)[1])
    length = 0
    while True:
        h = await reader.readline()
        if h in (b"\r\n", b"\n"):
            break
        if not h:
            raise ConnectionError("connection closed inside headers")
        if h.lower().startswith(b"content-length:"):
            length = int(h.split(b":", 1)[1])
    body = await reader.readexactly(length) if length else b""
    return status, body


def _start_bench_server(impl: str, service) -> tuple[int, "object"]:
    """Stand up one adapter over ``service`` on a loopback port. Returns
    ``(port, shutdown_callable)``."""
    if impl != "asyncio":
        raise SystemExit(
            f"unknown serving impl {impl!r} (the threaded adapter was "
            "removed; only 'asyncio' remains)"
        )
    from cobalt_smart_lender_ai_tpu.serve.http_asyncio import (
        make_async_server,
    )

    server = make_async_server(service)
    return server.port, server.close


def run_async_load(
    port: int,
    payloads: list[dict],
    *,
    clients: int,
    duration_s: float,
    warmup_s: float,
) -> dict:
    """Drive ``clients`` concurrent closed-loop HTTP clients from ONE event
    loop (one harness thread total, vs `run_http_smoke`'s thread per client)
    — so a 512-client run measures the server, not the harness's ability to
    schedule 512 OS threads. Each client holds a keep-alive connection and
    issues its next request the moment the previous response lands.

    Every non-200 counts as an error; an error body that fails to carry the
    typed ``"error"`` code from `reliability.errors` counts as *untyped* —
    the CI gate for the taxonomy surviving the async rewrite."""
    import asyncio

    from cobalt_smart_lender_ai_tpu.telemetry import parse_exposition

    bodies = [json.dumps(p).encode() for p in payloads]
    requests_bytes = [
        (
            f"POST /predict HTTP/1.1\r\nHost: bench\r\n"
            f"Content-Type: application/json\r\n"
            f"Content-Length: {len(b)}\r\n\r\n"
        ).encode() + b
        for b in bodies
    ]

    lat: list[list[float]] = [[] for _ in range(clients)]
    counts = [0] * clients
    errors = [0] * clients
    untyped = [0] * clients
    scrape_ok = [False]

    async def client(idx: int, record_from: float, stop_at: float) -> None:
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        i = idx  # offset so clients don't all score the same row
        try:
            while time.monotonic() < stop_at:
                req = requests_bytes[i % len(requests_bytes)]
                t0 = time.perf_counter()
                try:
                    writer.write(req)
                    await writer.drain()
                    status, body = await _read_http_response(reader)
                except (ConnectionError, asyncio.IncompleteReadError):
                    # A clean close between requests is normal HTTP/1.1,
                    # not a scoring error — reconnect and retry.
                    writer.close()
                    reader, writer = await asyncio.open_connection(
                        "127.0.0.1", port
                    )
                    continue
                elapsed_ms = (time.perf_counter() - t0) * 1e3
                recording = time.monotonic() >= record_from
                if recording:
                    counts[idx] += 1
                    lat[idx].append(elapsed_ms)
                if status != 200:
                    if recording:
                        errors[idx] += 1
                    try:
                        typed = "error" in json.loads(body.decode())
                    except Exception:
                        typed = False
                    if not typed:
                        untyped[idx] += 1
                i += 1
        finally:
            writer.close()

    async def scraper(stop_at: float) -> None:
        # scrape /metrics while the load is live — the observability plane
        # must serve cleanly from the same loop that serves the data plane
        await asyncio.sleep(max(0.05, (stop_at - time.monotonic()) / 2))
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        try:
            writer.write(b"GET /metrics HTTP/1.1\r\nHost: bench\r\n\r\n")
            await writer.drain()
            status, body = await _read_http_response(reader)
            parse_exposition(body.decode())
            scrape_ok[0] = status == 200
        finally:
            writer.close()

    async def drive() -> None:
        t_start = time.monotonic()
        record_from = t_start + warmup_s
        stop_at = record_from + duration_s
        await asyncio.gather(
            scraper(stop_at),
            *(client(i, record_from, stop_at) for i in range(clients)),
        )

    asyncio.run(drive())
    singles = sorted(x for per in lat for x in per)
    n = len(singles)
    return {
        "clients": clients,
        "requests": n,
        "qps": round(n / duration_s, 1),
        "errors": sum(errors),
        "untyped_errors": sum(untyped),
        "scrape_during_load_ok": scrape_ok[0],
        "p50_ms": round(_percentile(singles, 0.50), 3),
        "p95_ms": round(_percentile(singles, 0.95), 3),
        "p99_ms": round(_percentile(singles, 0.99), 3),
        "p99.9_ms": round(_percentile(singles, 0.999), 3),
        "max_ms": round(singles[-1], 3) if singles else float("nan"),
        "mean_ms": round(statistics.fmean(singles), 3) if singles else float("nan"),
    }


def run_inproc_comparison(
    artifact,
    payloads: list[dict],
    *,
    clients: int,
    duration_s: float,
    warmup_s: float,
    mb_kwargs: dict,
) -> dict:
    """The BENCH_SERVE_r02 protocol (in-process clients, no sockets) at the
    r03 client count, once per request model: ``clients`` coroutines
    suspended on `predict_single_async` awaitable futures vs ``clients`` OS
    threads blocked in `predict_single`. This is the apples-to-apples
    successor to r02's 32-thread `queue_wait` number — the HTTP sections
    above it add socket/parse cost that r02 never paid."""
    import asyncio

    from cobalt_smart_lender_ai_tpu.config import ReliabilityConfig, ServeConfig
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    def _mk_service():
        return ScorerService(
            artifact,
            ServeConfig(
                microbatch_enabled=True,
                score_cache_size=0,
                slo_p99_ms=250.0,
                slo_p999_ms=2000.0,
                reliability=ReliabilityConfig(
                    max_in_flight=max(256, clients * 2)
                ),
                **mb_kwargs,
            ),
        )

    out: dict[str, dict] = {}

    service = _mk_service()
    print(
        f"[bench] in-process async @ {clients} clients (r02 protocol)...",
        file=sys.stderr,
    )
    lat: list[list[float]] = [[] for _ in range(clients)]
    errors = [0] * clients

    async def aclient(idx: int, record_from: float, stop_at: float) -> None:
        i = idx
        while time.monotonic() < stop_at:
            t0 = time.perf_counter()
            try:
                await service.predict_single_async(payloads[i % len(payloads)])
            except Exception:
                errors[idx] += 1
                i += 1
                continue
            elapsed_ms = (time.perf_counter() - t0) * 1e3
            if time.monotonic() >= record_from:
                lat[idx].append(elapsed_ms)
            i += 1

    async def adrive() -> None:
        t_start = time.monotonic()
        record_from = t_start + warmup_s
        stop_at = record_from + duration_s
        await asyncio.gather(
            *(aclient(i, record_from, stop_at) for i in range(clients))
        )

    asyncio.run(adrive())
    singles = sorted(x for per in lat for x in per)
    row = {
        "clients": clients,
        "requests": len(singles),
        "qps": round(len(singles) / duration_s, 1),
        "errors": sum(errors),
        "p50_ms": round(_percentile(singles, 0.50), 3),
        "p99_ms": round(_percentile(singles, 0.99), 3),
        "p99.9_ms": round(_percentile(singles, 0.999), 3),
        "phases": _phase_breakdown(service.registry),
        "microbatch": service.batcher.stats(),
    }
    service.close()
    out["async_futures"] = row

    service = _mk_service()
    print(
        f"[bench] in-process threaded @ {clients} clients (r02 protocol)...",
        file=sys.stderr,
    )
    row = run_load(
        service,
        payloads,
        None,
        clients=clients,
        duration_s=duration_s,
        warmup_s=warmup_s,
        mix="single",
    )
    row["phases"] = _phase_breakdown(service.registry)
    service.close()
    out["blocking_threads"] = row
    return out


def run_async_http_bench(
    artifact,
    payloads: list[dict],
    *,
    impls: list[str],
    client_counts: list[int],
    duration_s: float,
    warmup_s: float,
    mb_kwargs: dict,
) -> dict:
    """The BENCH_SERVE_r03 protocol: the same trained artifact served by
    each adapter in ``impls`` (asyncio, since the threaded rollback adapter
    was removed), driven at every requested client count over real sockets
    by `run_async_load`. The score cache is OFF so every request exercises
    the full request path (the r02 in-process baseline predates the cache);
    the batcher is ON — the protocol isolates the frontend."""
    import os

    from cobalt_smart_lender_ai_tpu.config import ReliabilityConfig, ServeConfig
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    results: dict[str, dict] = {}
    for impl in impls:
        per_impl: dict[str, dict] = {}
        for clients in client_counts:
            # Admission must clear the closed-loop concurrency or the bench
            # measures the shedder instead of the request path.
            max_in_flight = max(256, clients * 2)
            config = ServeConfig(
                microbatch_enabled=True,
                score_cache_size=0,
                slo_p99_ms=250.0,
                slo_p999_ms=2000.0,
                reliability=ReliabilityConfig(max_in_flight=max_in_flight),
                **mb_kwargs,
            )
            service = ScorerService(artifact, config)
            port, shutdown = _start_bench_server(impl, service)
            print(
                f"[bench] {impl} @ {clients} async clients, "
                f"{duration_s:g}s measured (+{warmup_s:g}s warmup)...",
                file=sys.stderr,
            )
            try:
                row = run_async_load(
                    port,
                    payloads,
                    clients=clients,
                    duration_s=duration_s,
                    warmup_s=warmup_s,
                )
            finally:
                shutdown()
            row["phases"] = _phase_breakdown(service.registry)
            if service.batcher is not None:
                row["microbatch"] = service.batcher.stats()
            service.close()
            per_impl[f"clients_{clients}"] = row
        results[impl] = per_impl
    inproc = run_inproc_comparison(
        artifact,
        payloads,
        clients=client_counts[0],
        duration_s=duration_s,
        warmup_s=warmup_s,
        mb_kwargs=mb_kwargs,
    )
    record = {
        "bench": "serve_async_http",
        "baseline": "BENCH_SERVE_r02.json (32 in-process threaded clients)",
        "duration_s": duration_s,
        "warmup_s": warmup_s,
        "client_counts": client_counts,
        "impls": impls,
        "score_cache": "off (every request exercises the full path)",
        "admission": "max_in_flight raised to max(256, 2x clients) per cell "
        "so the bench measures the request path, not the shedder",
        "notes": [
            "r02's 1.44ms queue_wait at 32 clients was window-limited: the "
            "worker idled inside the 2ms coalescing window, so a row's wait "
            "was window minus arrival stagger.",
            "At 128+ closed-loop clients on this host the batcher is "
            "congestion-limited: arrivals are continuous and a row's wait is "
            "bounded below by the batch work itself (dispatch + shap, "
            "~2.6ms/cycle on 1 CPU core), so the 1.44ms window-limited value "
            "is not reachable at this client count on this hardware.",
            "The r02-protocol in-process section isolates the request model: "
            "at the same 128 clients, coroutines suspended on awaitable "
            "futures wait ~3x less in queue than blocking threads.",
        ],
        "microbatch_knobs": {
            "max_wait_ms": mb_kwargs.get("microbatch_max_wait_ms", 2.0),
            "max_rows": mb_kwargs.get("microbatch_max_rows", 64),
        },
        "r02_protocol_inproc": inproc,
        "platform": _platform_tag(),
        "host_cpu_cores": len(os.sched_getaffinity(0))
        if hasattr(os, "sched_getaffinity")
        else (os.cpu_count() or 1),
        "results": results,
    }
    return record


def run_chaos_bench(
    artifact,
    payloads: list[dict],
    *,
    clients: int,
    duration_s: float,
    warmup_s: float,
    replicas: int = 3,
    mb_kwargs: dict,
    heal_timeout_s: float = 30.0,
) -> dict:
    """The BENCH_CHAOS protocol (chaos-fleet CI job): a supervised
    N-replica fleet behind the asyncio adapter under closed-loop async
    clients, with a `ChaosPlan` killing and then hanging one replica's
    micro-batch worker mid-run. The record is the self-healing headline:
    ``load.errors`` and ``load.untyped_errors`` must stay 0 (worker
    watchdog turns the kill into typed ``worker_dead`` futures, hedged
    failover rescues them on a healthy replica) and the supervisor must
    quarantine, rebuild and readmit the hurt replica within the heal
    budget — all without an operator."""
    import os
    import threading

    from cobalt_smart_lender_ai_tpu.config import ReliabilityConfig, ServeConfig
    from cobalt_smart_lender_ai_tpu.reliability import ChaosPlan
    from cobalt_smart_lender_ai_tpu.serve.replicas import ReplicaSet
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService
    from cobalt_smart_lender_ai_tpu.serve.supervisor import HEALTHY

    replicas = max(2, replicas)
    target = 1 % replicas
    hang_s = 1.5
    config = ServeConfig(
        replicas=replicas,
        microbatch_enabled=True,
        score_cache_size=0,
        prewarm_all_buckets=False,
        slo_p99_ms=250.0,
        slo_p999_ms=2000.0,
        # snappy supervision so time-to-heal measures the rebuild, not the
        # probe cadence
        supervisor_probe_interval_s=0.25,
        supervisor_probe_deadline_s=0.5,
        supervisor_probe_failures=1,
        supervisor_drain_timeout_s=2.0,
        reliability=ReliabilityConfig(max_in_flight=max(256, clients * 2)),
        **mb_kwargs,
    )
    fleet = ReplicaSet(
        [ScorerService(artifact, config) for _ in range(replicas)], config
    )
    port, shutdown = _start_bench_server("asyncio", fleet)  # starts supervisor
    plan = ChaosPlan(seed=11, registry=fleet.registry)
    plan.inject(fleet)

    chaos_at: list = [None]
    healed_in: list = [None]

    def saboteur() -> None:
        # Mid-run: murder the target's worker (queued futures -> typed
        # worker_dead, watchdog restarts it), then wedge the restarted
        # worker so probes time out and the supervisor quarantines + heals.
        time.sleep(warmup_s + duration_s / 3.0)
        chaos_at[0] = time.monotonic()
        plan.kill_worker(replica=target, max_events=1)
        plan.hang_dispatch(replica=target, hang_s=hang_s, max_events=1)
        print(
            f"[bench] chaos: kill + {hang_s:g}s hang on replica {target}",
            file=sys.stderr,
        )
        # Heal detection over GET /events: the target replica is healed
        # when the journal shows a transition back to "healthy" after its
        # quarantine — the same causal record `tools/incident_report.py`
        # reads, observed through the operator's HTTP surface rather than
        # by reaching into fleet internals.
        give_up = chaos_at[0] + heal_timeout_s
        while time.monotonic() < give_up:
            try:
                doc = _fetch_events(
                    port, component="supervisor", kind="transition"
                )
            except Exception:
                time.sleep(0.1)
                continue
            quarantined = False
            for event in doc.get("events", []):  # oldest-first
                if event.get("replica") != target:
                    continue
                to = (event.get("payload") or {}).get("to")
                if to == "quarantined":
                    quarantined = True
                elif to == "healthy" and quarantined:
                    healed_in[0] = round(
                        time.monotonic() - chaos_at[0], 3
                    )
                    return
            time.sleep(0.05)

    sab = threading.Thread(target=saboteur, daemon=True)
    sab.start()
    print(
        f"[bench] chaos fleet: {replicas} replicas @ {clients} async "
        f"clients, {duration_s:g}s measured (+{warmup_s:g}s warmup)...",
        file=sys.stderr,
    )
    try:
        row = run_async_load(
            port,
            payloads,
            clients=clients,
            duration_s=duration_s,
            warmup_s=warmup_s,
        )
        sab.join(timeout=heal_timeout_s + 5.0)
        events_block = _events_block(port)
    finally:
        shutdown()
    h = fleet.replica_health[target]
    supervisor_block = {
        "target_replica": target,
        "quarantines": h.quarantines,
        "rebuilds_ok": int(
            fleet.supervisor._m_rebuilds.labels(
                replica=str(target), outcome="ok"
            ).value
        ),
        "heal_s": healed_in[0],
        "states_at_end": [x.state for x in fleet.replica_health],
        "all_healthy": all(
            x.state == HEALTHY for x in fleet.replica_health
        ),
        "hedges_rescued": int(
            fleet._m_hedges.labels(outcome="rescued").value
        ),
        "worker_restarts": sum(
            int(rep.batcher.stats().get("worker_restarts", 0))
            for rep in fleet.replicas
            if rep.batcher is not None
        ),
    }
    chaos_block = {
        "seed": 11,
        "kill_worker_events": int(plan.events.get("kill", 0)),
        "hang_events": int(plan.events.get("hang", 0)),
        "hang_s": hang_s,
        "injected_mid_run": True,
    }
    plan.release()
    fleet.close()
    record = {
        "bench": "serve_chaos",
        "protocol": "kill + hang one replica's micro-batch worker mid-run; "
        "gate errors==0, untyped==0, heal within budget",
        "replicas": replicas,
        "clients": clients,
        "duration_s": duration_s,
        "warmup_s": warmup_s,
        "heal_timeout_s": heal_timeout_s,
        "load": row,
        "chaos": chaos_block,
        "supervisor": supervisor_block,
        "events": events_block,
        "platform": _platform_tag(),
        "host_cpu_cores": len(os.sched_getaffinity(0))
        if hasattr(os, "sched_getaffinity")
        else (os.cpu_count() or 1),
    }
    return record


def run_traffic_bench(
    artifact,
    payloads: list[dict],
    *,
    shape_name: str,
    base_rps: float,
    peak_rps: float,
    duration_s: float,
    seed: int = 0,
    start_replicas: int = 1,
    max_replicas: int = 3,
) -> dict:
    """The BENCH_TRAFFIC protocol (autoscale-smoke CI job): ONE replica
    behind the asyncio adapter with the load-adaptive control loop enabled
    (`serve.autoscaler`), driven by an **open-loop** seeded arrival schedule
    from `reliability.traffic` — arrivals fire at their scheduled time no
    matter how slow the server is, so overload is measured, not hidden by
    client self-throttling. A ``flash_crowd`` run is the headline: the spike
    must force scale-ups, a sustained fast-burn at the replica ceiling must
    walk the brownout ladder (``degraded: true`` responses without SHAP),
    and the decay must release every rung and retire the extra capacity —
    with zero errors and zero untyped error bodies end to end."""
    import asyncio
    import os

    from cobalt_smart_lender_ai_tpu.config import ReliabilityConfig, ServeConfig
    from cobalt_smart_lender_ai_tpu.data import schema
    from cobalt_smart_lender_ai_tpu.reliability.traffic import (
        KIND_BULK,
        KIND_SHAP,
        TenantPopulation,
        TrafficGenerator,
        shape_by_name,
    )
    from cobalt_smart_lender_ai_tpu.serve.replicas import ReplicaSet
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    config = ServeConfig(
        replicas=start_replicas,
        microbatch_enabled=True,
        score_cache_size=0,
        prewarm_all_buckets=False,
        # Tight latency objectives + short burn windows: the flash-crowd
        # plateau must register as a fast burn within the run, and the decay
        # must clear it before the run ends.
        slo_p99_ms=25.0,
        slo_p999_ms=120.0,
        slo_windows_s=(3.0, 12.0),
        history_enabled=True,
        history_interval_s=0.5,
        history_tiers=((0.5, 720),),
        supervisor_probe_interval_s=0.5,
        supervisor_probe_deadline_s=1.0,
        supervisor_drain_timeout_s=2.0,
        autoscaler_enabled=True,
        autoscaler_interval_s=0.25,
        autoscaler_min_replicas=1,
        autoscaler_max_replicas=max_replicas,
        autoscaler_scale_up_cooldown_s=1.0,
        autoscaler_scale_down_cooldown_s=2.0,
        autoscaler_stable_ticks=4,
        autoscaler_queue_wait_high_ms=15.0,
        autoscaler_queue_wait_low_ms=4.0,
        # brownout_max_level=3 (the default): the ladder degrades SHAP and
        # widens coalescing but never sheds, so "errors == 0" stays a hard
        # gate even at the spike's peak.
        reliability=ReliabilityConfig(max_in_flight=1024),
    )
    fleet = ReplicaSet(
        [ScorerService(artifact, config) for _ in range(start_replicas)],
        config,
    )
    port, shutdown = _start_bench_server("asyncio", fleet)

    gen = TrafficGenerator(
        shape_by_name(shape_name, seed),
        base_rps=base_rps,
        peak_rps=peak_rps,
        duration_s=duration_s,
        tenants=TenantPopulation(
            list(payloads[0]),
            # Int-typed wire fields must survive jitter integral or the
            # validation schema 422s every single-row request.
            [
                schema.SERVING_FIELD_ALIASES.get(n, n)
                for n in schema.SERVING_INT_FEATURES
            ],
            base_rows=payloads,
            jitter=0.03,
            seed=seed,
        ),
        seed=seed,
    )
    schedule = gen.schedule()
    csv_header = ",".join(payloads[0]) + "\n"

    def _body(arrival) -> tuple[str, bytes, str]:
        if arrival.kind == KIND_BULK:
            rows = "".join(
                ",".join(f"{v:g}" for v in arrival.payload.values()) + "\n"
                for _ in range(gen.bulk_rows)
            )
            return (
                "/predict_bulk_csv",
                (csv_header + rows).encode(),
                "text/csv",
            )
        if arrival.kind == KIND_SHAP:
            return (
                "/feature_importance_bulk",
                json.dumps({"data": [arrival.payload]}).encode(),
                "application/json",
            )
        return "/predict", json.dumps(arrival.payload).encode(), "application/json"

    n_conns = 64
    lat: list[float] = []
    counts = {"requests": 0, "errors": 0, "untyped": 0, "shed": 0,
              "degraded": 0}
    by_kind: dict[str, int] = {}
    timeline: list[dict] = []

    async def sampler(stop_at: float) -> None:
        # replica-count / brownout-level timeline alongside the load — the
        # committed record shows the control loop acting, not just its
        # end-state counters. Both series are *derived from the event
        # journal* over GET /events (resize payload "to", brownout payload
        # "level"): if an actuation ever failed to journal, this timeline
        # would go flat and the record would show it.
        loop = asyncio.get_running_loop()
        replicas_now, level_now = start_replicas, 0
        while loop.time() < stop_at:
            try:
                doc = await loop.run_in_executor(
                    None,
                    lambda: _fetch_events(port, component="autoscaler"),
                )
                replicas_now, level_now = start_replicas, 0
                for event in doc.get("events", []):  # oldest-first
                    payload = event.get("payload") or {}
                    if event.get("kind") == "resize":
                        replicas_now = int(
                            payload.get("to", replicas_now)
                        )
                    elif event.get("kind") == "brownout":
                        level_now = int(payload.get("level", level_now))
            except Exception:
                pass  # server mid-bind or draining: keep last-known state
            timeline.append(
                {
                    "t": round(time.monotonic() - t0[0], 2),
                    "replicas": replicas_now,
                    "brownout_level": level_now,
                }
            )
            await asyncio.sleep(0.5)

    t0 = [0.0]

    async def fire(arrival, conns: "asyncio.Queue") -> None:
        await asyncio.sleep(max(0.0, t0[0] + arrival.t - time.monotonic()))
        t_sched = t0[0] + arrival.t  # latency from *scheduled* fire time:
        # a backed-up harness queue counts against the server (open loop)
        path, body, ctype = _body(arrival)
        req = (
            f"POST {path} HTTP/1.1\r\nHost: bench\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n\r\n"
        ).encode() + body
        reader, writer = await conns.get()
        try:
            try:
                writer.write(req)
                await writer.drain()
                status, resp_body = await _read_http_response(reader)
            except (ConnectionError, asyncio.IncompleteReadError):
                writer.close()
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port
                )
                writer.write(req)
                await writer.drain()
                status, resp_body = await _read_http_response(reader)
            lat.append((time.monotonic() - t_sched) * 1e3)
            counts["requests"] += 1
            by_kind[arrival.kind] = by_kind.get(arrival.kind, 0) + 1
            if status == 200:
                if arrival.kind == "single" and b'"degraded"' in resp_body:
                    counts["degraded"] += 1
            else:
                try:
                    typed = "error" in json.loads(resp_body.decode())
                except Exception:
                    typed = False
                if status == 429 and typed:
                    counts["shed"] += 1  # designed backpressure, not failure
                else:
                    counts["errors"] += 1
                    if not typed:
                        counts["untyped"] += 1
        finally:
            conns.put_nowait((reader, writer))

    async def drive() -> None:
        conns: asyncio.Queue = asyncio.Queue()
        for _ in range(n_conns):
            conns.put_nowait(await asyncio.open_connection("127.0.0.1", port))
        t0[0] = time.monotonic()
        # The sampler outlives the arrivals by a quiet settle window — the
        # idle evidence the control loop needs to release remaining rungs
        # and retire the surge capacity before the record is cut.
        settle = max(8.0, duration_s / 3.0)
        stop_at = asyncio.get_running_loop().time() + duration_s + settle
        await asyncio.gather(
            sampler(stop_at), *(fire(a, conns) for a in schedule)
        )
        while not conns.empty():
            _, writer = conns.get_nowait()
            writer.close()

    print(
        f"[bench] traffic {shape_name}: {len(schedule)} open-loop arrivals, "
        f"{base_rps:g}->{peak_rps:g} rps over {duration_s:g}s, "
        f"{start_replicas}->{max_replicas} replicas available...",
        file=sys.stderr,
    )
    try:
        asyncio.run(drive())
        events_block = _events_block(port)
    finally:
        shutdown()
    scaler = fleet.autoscaler
    autoscaler_block = {
        "resizes_up": int(scaler._m_resizes.labels(direction="up").value),
        "resizes_down": int(scaler._m_resizes.labels(direction="down").value),
        "retunes_busy": int(scaler._m_retunes.labels(profile="busy").value),
        "retunes_idle": int(scaler._m_retunes.labels(profile="idle").value),
        "brownout_engaged": int(
            scaler._m_brownouts.labels(direction="engage").value
        ),
        "brownout_released": int(
            scaler._m_brownouts.labels(direction="release").value
        ),
        "final_level": fleet.brownout.level,
        "max_level_seen": max(
            (p["brownout_level"] for p in timeline), default=0
        ),
        "final_replicas": len(fleet.replicas),
        "max_replicas_seen": max(
            (p["replicas"] for p in timeline), default=start_replicas
        ),
        "ticks": int(scaler._m_ticks.value),
        "timeline": timeline,
    }
    fleet.close()
    singles = sorted(lat)
    record = {
        "bench": "serve_traffic",
        "protocol": "open-loop seeded arrivals against an autoscaled fleet; "
        "gate errors==0, untyped==0, >=1 scale-up and scale-down, brownout "
        "engaged and fully released",
        "traffic": gen.summary(),
        "start_replicas": start_replicas,
        "load": {
            "requests": counts["requests"],
            "qps": round(counts["requests"] / duration_s, 1),
            "errors": counts["errors"],
            "untyped_errors": counts["untyped"],
            "shed": counts["shed"],
            "degraded": counts["degraded"],
            "by_kind": by_kind,
            "p50_ms": round(_percentile(singles, 0.50), 3),
            "p95_ms": round(_percentile(singles, 0.95), 3),
            "p99_ms": round(_percentile(singles, 0.99), 3),
            "p99.9_ms": round(_percentile(singles, 0.999), 3),
            "max_ms": round(singles[-1], 3) if singles else float("nan"),
        },
        "autoscaler": autoscaler_block,
        "events": events_block,
        "platform": _platform_tag(),
        "host_cpu_cores": len(os.sched_getaffinity(0))
        if hasattr(os, "sched_getaffinity")
        else (os.cpu_count() or 1),
    }
    return record


def run_bulk_bench(
    artifact,
    X,
    *,
    shard_counts: list[int],
    query_rows: int,
    repeats: int,
    max_batch_rows: int,
) -> dict:
    """Score one (query_rows, F) matrix through the bulk path at each shard
    count and report rows/s (best of ``repeats``, after a full warmup pass
    that absorbs the compiles). Every shard count must produce bit-identical
    probabilities to the single-device path — the partitioner's contract —
    and the record says so explicitly."""
    import os

    import numpy as np

    from cobalt_smart_lender_ai_tpu.config import ServeConfig
    from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

    reps = max(1, int(np.ceil(query_rows / X.shape[0])))
    Xq = np.tile(np.nan_to_num(X, nan=0.0), (reps, 1))[:query_rows]
    results: dict[str, dict] = {}
    reference = None
    for shards in shard_counts:
        config = ServeConfig(
            microbatch_enabled=False,
            precompile_batch_buckets=(),
            max_batch_rows=max_batch_rows,
            bulk_shards=shards,
            score_cache_size=0,
        )
        service = ScorerService(artifact, config)
        actual = service._model.bulk_part.n_shards
        print(
            f"[bench] bulk shards={shards} (resolved {actual}): warmup + "
            f"{repeats} timed passes over {query_rows} rows...",
            file=sys.stderr,
        )
        probs = service.predict_proba(Xq)  # warmup pass pays the compiles
        best_s = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            probs = service.predict_proba(Xq)
            best_s = min(best_s, time.perf_counter() - t0)
        if reference is None:
            reference = probs
        entry = {
            "requested_shards": shards,
            "shards": actual,
            "rows_per_s": round(query_rows / best_s, 1),
            "best_pass_ms": round(best_s * 1e3, 3),
            "dispatches": int(
                service.registry.snapshot()["cobalt_bulk_dispatches_total"][
                    "samples"
                ][0]["value"]
            ),
            "bit_identical_to_single": bool(
                np.array_equal(reference, probs)
            ),
            "mesh": service._model.bulk_part.describe()["mesh"],
        }
        results[f"shards_{actual}"] = entry
        service.close()
    record = {
        "bench": "bulk_scoring",
        "query_rows": query_rows,
        "max_batch_rows": max_batch_rows,
        "platform": _platform_tag(),
        "devices": _device_count(),
        "host_cpu_cores": len(os.sched_getaffinity(0))
        if hasattr(os, "sched_getaffinity")
        else (os.cpu_count() or 1),
        "results": results,
    }
    keys = sorted(results, key=lambda k: results[k]["shards"])
    if len(keys) >= 2:
        base = results[keys[0]]["rows_per_s"]
        top = results[keys[-1]]["rows_per_s"]
        if base > 0:
            record["speedup"] = round(top / base, 2)
    record["bit_identical"] = all(
        r["bit_identical_to_single"] for r in results.values()
    )
    return record


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--clients", type=int, default=32)
    parser.add_argument("--duration-s", type=float, default=5.0)
    parser.add_argument("--warmup-s", type=float, default=1.5)
    parser.add_argument("--rows", type=int, default=2000,
                        help="synthetic training rows")
    parser.add_argument("--mix", choices=("single", "mixed"), default="single")
    parser.add_argument("--mode", choices=("both", "on", "off"), default="both")
    parser.add_argument("--microbatch-wait-ms", type=float, default=None)
    parser.add_argument("--microbatch-max-rows", type=int, default=None)
    parser.add_argument("--smoke", action="store_true",
                        help="CI profile: 4 clients, ~1s per mode")
    parser.add_argument("--bulk", action="store_true",
                        help="run the mesh-sharded bulk-scoring bench "
                        "instead of the closed-loop single-row bench")
    parser.add_argument("--bulk-rows", type=int, default=65536,
                        help="rows in the bulk query matrix")
    parser.add_argument("--bulk-shards", default="1,-1",
                        help="comma-separated bulk_shards settings to "
                        "compare (-1 = every visible device)")
    parser.add_argument("--bulk-repeats", type=int, default=3,
                        help="timed passes per shard count (best is kept)")
    parser.add_argument("--max-batch-rows", type=int, default=4096,
                        help="per-shard row cap of one compiled program")
    parser.add_argument("--force-devices", type=int, default=None,
                        help="set --xla_force_host_platform_device_count "
                        "before JAX loads (no-op if JAX is already up)")
    parser.add_argument("--async-clients", action="store_true",
                        help="run the async serving bench instead: drive "
                        "--client-counts concurrent closed-loop HTTP clients "
                        "from ONE event loop against each adapter in --impls "
                        "(the BENCH_SERVE_r03 protocol)")
    parser.add_argument("--client-counts", default="128,256,512",
                        help="comma-separated client counts for "
                        "--async-clients")
    parser.add_argument("--impls", default="asyncio",
                        help="comma-separated adapters for --async-clients "
                        "(only 'asyncio' remains)")
    parser.add_argument("--chaos", action="store_true",
                        help="run the self-healing fleet chaos bench: a "
                        "supervised replica fleet behind the asyncio "
                        "adapter with one replica's worker killed + hung "
                        "mid-run (the chaos-fleet CI job protocol)")
    parser.add_argument("--chaos-replicas", type=int, default=3,
                        help="fleet size for --chaos")
    parser.add_argument("--traffic", default=None,
                        metavar="SHAPE",
                        help="run the load-adaptive fleet bench: an open-"
                        "loop seeded arrival schedule of this shape "
                        "(flash_crowd, diurnal, bursty, ramp, steady) "
                        "against an autoscaler-enabled fleet (the "
                        "autoscale-smoke CI job protocol)")
    parser.add_argument("--traffic-base-rps", type=float, default=8.0,
                        help="trough arrival rate for --traffic")
    parser.add_argument("--traffic-peak-rps", type=float, default=600.0,
                        help="peak arrival rate for --traffic")
    parser.add_argument("--traffic-duration-s", type=float, default=24.0,
                        help="arrival-schedule length for --traffic "
                        "(a settle window is appended on top)")
    parser.add_argument("--traffic-seed", type=int, default=0,
                        help="arrival-schedule seed for --traffic")
    parser.add_argument("--traffic-max-replicas", type=int, default=3,
                        help="autoscaler replica ceiling for --traffic")
    parser.add_argument("--http-smoke", action="store_true",
                        help="also drive load over real HTTP and scrape "
                        "/metrics during it (validates the telemetry wiring; "
                        "result lands under record['metrics_scrape'])")
    parser.add_argument("--out", default=None,
                        help="also write the JSON line to this path")
    parser.add_argument("--trace-out", default=None,
                        help="write the run's span ring as Chrome Trace "
                        "Event / Perfetto JSON to this path (open in "
                        "ui.perfetto.dev; CI uploads it as an artifact)")
    parser.add_argument("--ledger-out", default=None,
                        help="write a run ledger (env, headline, program "
                        "cost table) to this path; render with "
                        "tools/obs_report.py")
    parser.add_argument("--trend-out", default=None,
                        help="append this run's headline metrics to the "
                        "given TREND.json (gate with tools/perf_sentinel.py "
                        "check)")
    args = parser.parse_args(argv)
    if args.force_devices:
        import os

        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={args.force_devices}"
            ).strip()
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.smoke:
        args.clients = min(args.clients, 4)
        args.duration_s = min(args.duration_s, 1.0)
        args.warmup_s = min(args.warmup_s, 0.5)
        args.rows = min(args.rows, 800)
        args.bulk_rows = min(args.bulk_rows, 16384)
        args.bulk_repeats = min(args.bulk_repeats, 2)

    def _write_trend(record: dict) -> None:
        if not args.trend_out:
            return
        from cobalt_smart_lender_ai_tpu.telemetry.trend import append_record

        append_record(
            args.trend_out, record, source="bench_serve.py", stamp=time.time()
        )

    def _write_ledger(record: dict) -> None:
        if not args.ledger_out:
            return
        from cobalt_smart_lender_ai_tpu.telemetry import (
            RunLedger,
            install_device_metrics,
            install_program_metrics,
        )

        install_program_metrics()
        install_device_metrics()
        ledger = RunLedger(
            "bench_serve",
            meta={
                "bulk": bool(args.bulk),
                "clients": args.clients,
                "duration_s": args.duration_s,
                "rows": args.rows,
                "mix": args.mix,
            },
        )
        ledger.set(
            "headline",
            {k: v for k, v in record.items() if k != "results"}
            | {
                name: {k: v for k, v in r.items() if k != "telemetry"}
                for name, r in (record.get("results") or {}).items()
            },
        )
        ledger.write(args.ledger_out)
        print(f"[bench] run ledger written to {args.ledger_out}",
              file=sys.stderr)

    if args.bulk:
        print(f"[bench] training model ({args.rows} synthetic rows)...",
              file=sys.stderr)
        from cobalt_smart_lender_ai_tpu.config import ServeConfig

        service, X = build_service(
            ServeConfig(microbatch_enabled=False, precompile_batch_buckets=()),
            n_rows=args.rows,
        )
        artifact = service.artifact
        service.close()
        record = run_bulk_bench(
            artifact,
            X,
            shard_counts=[int(s) for s in args.bulk_shards.split(",")],
            query_rows=args.bulk_rows,
            repeats=args.bulk_repeats,
            max_batch_rows=args.max_batch_rows,
        )
        line = json.dumps(record)
        print(line)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(line + "\n")
        _write_ledger(record)
        _write_trend(record)
        return 0

    from cobalt_smart_lender_ai_tpu.config import ServeConfig

    mb_kwargs = {}
    if args.microbatch_wait_ms is not None:
        mb_kwargs["microbatch_max_wait_ms"] = args.microbatch_wait_ms
    if args.microbatch_max_rows is not None:
        mb_kwargs["microbatch_max_rows"] = args.microbatch_max_rows

    if args.async_clients:
        client_counts = [int(c) for c in args.client_counts.split(",")]
        impls = [s.strip() for s in args.impls.split(",") if s.strip()]
        if args.smoke:
            client_counts = [min(c, 16) for c in client_counts][:1]
        print(f"[bench] training model ({args.rows} synthetic rows)...",
              file=sys.stderr)
        service, X = build_service(
            ServeConfig(microbatch_enabled=False, prewarm_all_buckets=False),
            n_rows=args.rows,
        )
        artifact = service.artifact
        service.close()
        payloads = build_payloads(X)
        record = run_async_http_bench(
            artifact,
            payloads,
            impls=impls,
            client_counts=client_counts,
            duration_s=args.duration_s,
            warmup_s=args.warmup_s,
            mb_kwargs=mb_kwargs,
        )
        line = json.dumps(record)
        print(line)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(line + "\n")
        _write_ledger(record)
        _write_trend(record)
        return 0

    if args.chaos:
        print(f"[bench] training model ({args.rows} synthetic rows)...",
              file=sys.stderr)
        service, X = build_service(
            ServeConfig(microbatch_enabled=False, prewarm_all_buckets=False),
            n_rows=args.rows,
        )
        artifact = service.artifact
        service.close()
        payloads = build_payloads(X)
        record = run_chaos_bench(
            artifact,
            payloads,
            clients=args.clients,
            duration_s=args.duration_s,
            warmup_s=args.warmup_s,
            replicas=args.chaos_replicas,
            mb_kwargs=mb_kwargs,
        )
        line = json.dumps(record)
        print(line)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(line + "\n")
        _write_ledger(record)
        _write_trend(record)
        return 0

    if args.traffic:
        print(f"[bench] training model ({args.rows} synthetic rows)...",
              file=sys.stderr)
        service, X = build_service(
            ServeConfig(microbatch_enabled=False, prewarm_all_buckets=False),
            n_rows=args.rows,
        )
        artifact = service.artifact
        service.close()
        payloads = build_payloads(X)
        if args.smoke:
            args.traffic_duration_s = min(args.traffic_duration_s, 18.0)
            args.traffic_peak_rps = min(args.traffic_peak_rps, 400.0)
        record = run_traffic_bench(
            artifact,
            payloads,
            shape_name=args.traffic,
            base_rps=args.traffic_base_rps,
            peak_rps=args.traffic_peak_rps,
            duration_s=args.traffic_duration_s,
            seed=args.traffic_seed,
            max_replicas=args.traffic_max_replicas,
        )
        line = json.dumps(record)
        print(line)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(line + "\n")
        _write_ledger(record)
        _write_trend(record)
        return 0

    modes = {"both": ("off", "on"), "on": ("on",), "off": ("off",)}[args.mode]
    results: dict[str, dict] = {}
    service = None
    X = None
    for mode in modes:
        config = ServeConfig(microbatch_enabled=(mode == "on"), **mb_kwargs)
        if service is None:
            print(f"[bench] training model ({args.rows} synthetic rows)...",
                  file=sys.stderr)
            service, X = build_service(config, n_rows=args.rows)
        else:
            # same trained artifact, fresh compile cache per mode
            from cobalt_smart_lender_ai_tpu.serve.service import ScorerService

            service = ScorerService(service.artifact, config)
        payloads = build_payloads(X)
        csv_bytes = None
        if args.mix == "mixed":
            import pandas as pd

            from cobalt_smart_lender_ai_tpu.data import schema

            csv_bytes = (
                pd.DataFrame(X[:64], columns=list(schema.SERVING_FEATURES))
                .to_csv(index=False)
                .encode()
            )
        print(
            f"[bench] batcher_{mode}: {args.clients} clients, "
            f"{args.duration_s:g}s measured (+{args.warmup_s:g}s warmup)...",
            file=sys.stderr,
        )
        results[f"batcher_{mode}"] = run_load(
            service,
            payloads,
            csv_bytes,
            clients=args.clients,
            duration_s=args.duration_s,
            warmup_s=args.warmup_s,
            mix=args.mix,
        )
        # attach this mode's metric values + recent spans so the committed
        # bench record carries the run's internals, not just the headline
        from cobalt_smart_lender_ai_tpu.telemetry import snapshot

        results[f"batcher_{mode}"]["telemetry"] = snapshot(
            service.registry, span_limit=32
        )
        artifact = service.artifact
        service.close()

    if args.http_smoke:
        print(
            f"[bench] http smoke: {min(args.clients, 4)} clients over real "
            "sockets, scraping /metrics...",
            file=sys.stderr,
        )
        # SLO thresholds are CI-noise-proof here: shared runners hiccup, and
        # the gate below is "no fast burn", not the production 10ms target
        record_scrape = run_http_smoke(
            ServeConfig(
                microbatch_enabled=True,
                slo_p99_ms=250.0,
                slo_p999_ms=2000.0,
                **mb_kwargs,
            ),
            artifact,
            payloads,
            clients=min(args.clients, 4),
            duration_s=min(args.duration_s, 2.0),
        )
    else:
        record_scrape = None

    record = {
        "bench": "serve_throughput",
        "clients": args.clients,
        "duration_s": args.duration_s,
        "mix": args.mix,
        "platform": _platform_tag(),
        "results": results,
    }
    if record_scrape is not None:
        record["metrics_scrape"] = record_scrape
    if "batcher_on" in results and "batcher_off" in results:
        off, on = results["batcher_off"], results["batcher_on"]
        if off["qps"] > 0:
            record["qps_speedup"] = round(on["qps"] / off["qps"], 2)
    line = json.dumps(record)
    print(line)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(line + "\n")
    _write_ledger(record)
    _write_trend(record)
    if args.trace_out:
        from cobalt_smart_lender_ai_tpu.telemetry import (
            default_tracer,
            render_chrome_trace,
        )

        with open(args.trace_out, "w") as fh:
            fh.write(render_chrome_trace(default_tracer()))
        print(f"[bench] perfetto trace written to {args.trace_out}",
              file=sys.stderr)
    return 0


def _platform_tag() -> str:
    import jax

    return jax.devices()[0].platform


def _device_count() -> int:
    import jax

    return len(jax.devices())


if __name__ == "__main__":
    raise SystemExit(main())
