"""Benchmark: full GBDT training throughput on one TPU chip.

Trains the reference's tuned production configuration (300 trees, depth 3,
lr 0.05 — BASELINE.md best hyperparams) on a 500k-row x 100-feature synthetic
credit table, end-to-end on device (quantile binning + all boosting rounds),
and reports rows/sec/chip.

``vs_baseline`` compares against the only training throughput the reference
ever recorded: the Keras MLP's ~26k rows/s on CPU (BASELINE.md, `04` cell 40)
— the reference never timed its XGBoost path.

Prints exactly one JSON line.
"""

import json
import time

import numpy as np

BASELINE_ROWS_PER_SEC = 26_000.0  # reference CPU training throughput
N_ROWS, N_FEATURES = 500_000, 100
N_TREES, MAX_DEPTH, N_BINS = 300, 3, 64
CHUNK_TREES = 100  # keep each dispatch well under the ~60s environment limit


def main() -> None:
    import jax
    import jax.numpy as jnp

    from cobalt_smart_lender_ai_tpu.config import GBDTConfig
    from cobalt_smart_lender_ai_tpu.models.gbdt import (
        GBDTHyperparams,
        fit_binned_chunked,
        predict_margin,
    )
    from cobalt_smart_lender_ai_tpu.ops.binning import compute_bin_edges, transform
    from cobalt_smart_lender_ai_tpu.ops.metrics import roc_auc

    rng = np.random.default_rng(0)
    X = rng.normal(size=(N_ROWS, N_FEATURES)).astype(np.float32)
    logits = X[:, :10] @ rng.normal(size=10) * 0.7
    y = (logits + rng.logistic(size=N_ROWS) > 0).astype(np.int32)
    X[rng.random(X.shape) < 0.02] = np.nan  # exercise missing-value routing

    hp = GBDTHyperparams.from_config(
        GBDTConfig(
            n_estimators=N_TREES, max_depth=MAX_DEPTH, learning_rate=0.05, n_bins=N_BINS
        )
    )
    Xd = jnp.asarray(X)
    yd = jnp.asarray(y)
    sw = jnp.ones((N_ROWS,), jnp.float32)
    fm = jnp.ones((N_FEATURES,), bool)

    def run(key):
        spec = compute_bin_edges(Xd, n_bins=N_BINS)
        bins = transform(spec, Xd)
        forest = fit_binned_chunked(
            bins,
            yd,
            sw,
            fm,
            hp,
            key,
            n_trees_cap=N_TREES,
            depth_cap=MAX_DEPTH,
            n_bins=N_BINS,
            chunk_trees=CHUNK_TREES,
        )
        # Fetch to force full execution (async dispatch otherwise lies).
        np.asarray(forest.leaf_value)
        return forest, bins

    run(jax.random.PRNGKey(0))  # compile warmup
    t0 = time.time()
    forest, bins = run(jax.random.PRNGKey(1))
    elapsed = time.time() - t0
    auc = float(roc_auc(yd.astype(jnp.float32), predict_margin(forest, bins, use_binned=True)))

    rows_per_sec = N_ROWS / elapsed
    print(
        json.dumps(
            {
                "metric": "gbdt_full_train_rows_per_sec_per_chip",
                "value": round(rows_per_sec, 1),
                "unit": f"rows/s (300 trees d3 {N_FEATURES}f, bin+fit, train AUC {auc:.3f})",
                "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
