"""Benchmark: the full-table north-star workload, end-to-end on one chip.

Runs the BASELINE.json north-star row count — 2.3M rows x 100 features, the
size of the reference's full LendingClub table it never managed to train on —
entirely on device: quantile binning, all 300 boosting rounds of the
reference's tuned production configuration (depth 3, lr 0.05, BASELINE.md
best hyperparams) on an 80% train split, then predict + held-out ROC-AUC.

``vs_baseline`` is the honest north-star framing (the reference records no
XGBoost wall-clock; its only training throughput is a Keras MLP at ~26k
rows/s on CPU): the target "2.3M rows end-to-end < 60 s on a v4-8" demands
>= 2.3M/60/8 ~ 4,791 rows/s/chip, so ``vs_baseline = rows_per_sec /
4791``. Values > 1 mean a single chip already beats the 8-chip budget
pro-rata; r2 measures ~140k rows/s/chip (after the histogram row-block
sweep, models/gbdt.py hist_row_block), i.e. the whole 8-chip-minute
workload fits on ONE chip in ~16 s.

The fit is dispatched in 100-tree chunks (each ~7 s) to respect this
environment's dispatch-duration limit; the timed quantity fetches the final
AUC, forcing the full pipeline to execute.

Label signal here is a quick planted logit over 10 features (test AUC ~0.91
at this noise level) — the framework's headline-AUC parity (>= 0.95 tuned on
the LendingClub-schema generator) is demonstrated in tests/test_pipeline.py
and BENCH notes, not here.

Prints exactly one JSON line. ``--profile DIR`` wraps the timed run in a
`jax.profiler` trace (SURVEY §5.1).

``--protocol`` instead times the FULL training protocol — the whole
`run_pipeline` composition (clean -> engineer -> RFE-20 step 1 -> 20x3
randomized search over the reference's space -> final fit + eval,
`model_tree_train_test.py:73-242`) on a synthetic raw frame of ``--rows``
rows — and prints that as the one JSON line. This is the north-star sentence
measured literally, every sequential refit and CV fit included; expect
hours, not seconds, at 2.3M rows on one chip. Its committed output lives in
`BENCH_PROTOCOL.json`; the default (single-fit) line embeds that artifact's
summary under ``protocol`` with its provenance so both metrics ride every
`BENCH_r*.json`.
"""

import argparse
import json
import os
import time

import numpy as np

NORTH_STAR_ROWS_PER_SEC_PER_CHIP = 2_300_000 / 60.0 / 8  # ~4,791 (v4-8 < 60s)
N_ROWS, N_FEATURES = 2_300_000, 100
N_TREES, MAX_DEPTH, N_BINS = 300, 3, 64
CHUNK_TREES = 100  # keep each dispatch well under the ~60s environment limit


def run_protocol(n_rows: int, seed: int = 5) -> dict:
    """Time the whole `run_pipeline` protocol on a synthetic raw frame.

    Dispatch budgets are derived per workload from the cost model in
    `parallel/budget.py` ("auto"): the search chunks each depth bucket's
    boosting rounds to ~24s dispatches (at full-table scale the depth-9
    33-job bucket lands at 1-2 rounds per dispatch, matching the
    measured-safe round-3 shape; at 130k rows it runs near-whole fits). The
    RFE elimination loop advances K whole steps per dispatch with the mask
    carried on device at sub-compile-risk scales; above
    budget.COMPILE_RISK_CELLS (the full-table case) it stays on the proven
    chunked host-stepped loop.
    """
    import dataclasses
    import logging

    import jax

    from cobalt_smart_lender_ai_tpu.config import PipelineConfig
    from cobalt_smart_lender_ai_tpu.data.synthetic import (
        synthetic_lendingclub_frame,
    )
    from cobalt_smart_lender_ai_tpu.pipeline import run_pipeline

    # Stage-progress visibility on stderr: a multi-hour run with a silent
    # stdout is undebuggable when the tunnel wedges mid-stage.
    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s [%(levelname)s] %(message)s"
    )
    cfg = PipelineConfig(save_intermediate=False)
    cfg = dataclasses.replace(
        cfg,
        gbdt=cfg.gbdt.replace(chunk_trees="auto"),
        tune=dataclasses.replace(cfg.tune, chunk_trees="auto"),
    )
    t0 = time.time()
    raw = synthetic_lendingclub_frame(n_rows=n_rows, seed=seed)
    t_gen = time.time() - t0

    t1 = time.time()
    result = run_pipeline(cfg, raw=raw)
    total = time.time() - t1

    from cobalt_smart_lender_ai_tpu.telemetry import snapshot

    return {
        # per-stage histogram observations + pipeline.run/stage spans, so the
        # committed record carries the run's internal timings (README
        # "Observability")
        "telemetry": snapshot(span_limit=32),
        "metric": "full_protocol_rows_per_sec_per_chip",
        "produced_by": "bench.py --protocol (single process)",
        "value": round(n_rows / total, 1),
        "unit": (
            f"rows/s ({n_rows/1e6:.1f}M-row raw frame through the whole "
            f"protocol — clean+engineer+RFE-20(step1)+search(20x3, full "
            f"reference space)+final fit+eval — in {total:.0f}s on one chip; "
            f"test AUC {result.test_auc:.4f}, cv AUC {result.cv_auc:.4f}; "
            "vs_baseline = x over the 4,791 rows/s/chip v4-8 <60s budget)"
        ),
        "vs_baseline": round(
            n_rows / total / NORTH_STAR_ROWS_PER_SEC_PER_CHIP, 3
        ),
        "seconds_total": round(total, 1),
        "seconds_stage": result.timings,
        "seconds_synthetic_datagen_excluded": round(t_gen, 1),
        "test_auc": round(result.test_auc, 4),
        "cv_auc": round(result.cv_auc, 4),
        "best_params": result.best_params,
        "n_rows": n_rows,
        "device": str(jax.devices()[0]),
    }


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--profile", default=None, help="jax.profiler trace dir")
    parser.add_argument("--rows", type=int, default=N_ROWS)
    parser.add_argument(
        "--protocol",
        action="store_true",
        help="time the full run_pipeline protocol instead of the single fit",
    )
    parser.add_argument(
        "--ledger-out",
        default=None,
        help="write a run ledger (env, stage durations, program cost table) "
        "to this path; render with tools/obs_report.py",
    )
    parser.add_argument(
        "--trend-out",
        default=None,
        help="append this run's headline metrics to the given TREND.json "
        "(gate with tools/perf_sentinel.py check)",
    )
    args = parser.parse_args()

    from cobalt_smart_lender_ai_tpu.compilecache import bootstrap_compile_cache

    bootstrap_compile_cache()
    ledger = None
    if args.ledger_out:
        from cobalt_smart_lender_ai_tpu.telemetry import (
            RunLedger,
            install_device_metrics,
            install_program_metrics,
        )

        install_program_metrics()
        install_device_metrics()
        ledger = RunLedger(
            "bench",
            meta={"rows": args.rows, "protocol": bool(args.protocol)},
        )
    if args.protocol:
        from cobalt_smart_lender_ai_tpu.debug import profile_trace as _trace

        with _trace(args.profile):
            out = run_protocol(args.rows)
        if ledger is not None:
            ledger.add_stages(out.get("seconds_stage") or {})
            ledger.set(
                "headline",
                {k: out[k] for k in out if k != "telemetry"},
            )
            ledger.write(args.ledger_out)
        if args.trend_out:
            from cobalt_smart_lender_ai_tpu.telemetry.trend import append_record

            append_record(
                args.trend_out, out, source="bench.py --protocol",
                stamp=time.time(),
            )
        print(json.dumps(out))
        return

    import jax
    import jax.numpy as jnp

    from cobalt_smart_lender_ai_tpu.config import GBDTConfig
    from cobalt_smart_lender_ai_tpu.debug import profile_trace
    from cobalt_smart_lender_ai_tpu.models.gbdt import (
        GBDTHyperparams,
        fit_binned_chunked,
        predict_margin,
    )
    from cobalt_smart_lender_ai_tpu.ops.binning import compute_bin_edges, transform
    from cobalt_smart_lender_ai_tpu.ops.metrics import roc_auc

    n = args.rows
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, N_FEATURES)).astype(np.float32)
    logits = X[:, :10] @ rng.normal(size=10) * 0.7
    y = (logits + rng.logistic(size=n) > 0).astype(np.int32)
    X[rng.random(X.shape) < 0.02] = np.nan  # exercise missing-value routing

    hp = GBDTHyperparams.from_config(
        GBDTConfig(
            n_estimators=N_TREES, max_depth=MAX_DEPTH, learning_rate=0.05, n_bins=N_BINS
        )
    )
    Xd = jnp.asarray(X)
    yd = jnp.asarray(y)
    test = np.zeros(n, bool)
    test[rng.choice(n, n // 5, replace=False)] = True
    train_w = jnp.asarray((~test).astype(np.float32))  # 80/20 split via weights
    test_w = jnp.asarray(test.astype(np.float32))
    fm = jnp.ones((N_FEATURES,), bool)

    def run(key) -> float:
        spec = compute_bin_edges(Xd, n_bins=N_BINS)
        bins = transform(spec, Xd)
        forest = fit_binned_chunked(
            bins,
            yd,
            train_w,
            fm,
            hp,
            key,
            n_trees_cap=N_TREES,
            depth_cap=MAX_DEPTH,
            n_bins=N_BINS,
            chunk_trees=CHUNK_TREES,
        )
        margin = predict_margin(forest, bins, use_binned=True)
        # Fetching the scalar forces the whole chain to execute (async
        # dispatch otherwise lies about wall-clock).
        return float(roc_auc(yd.astype(jnp.float32), margin, weight=test_w))

    from cobalt_smart_lender_ai_tpu.telemetry import snapshot, span

    run(jax.random.PRNGKey(0))  # compile warmup
    with profile_trace(args.profile):
        t0 = time.time()
        with span("bench.full_table_fit", rows=n, trees=N_TREES):
            auc = run(jax.random.PRNGKey(1))
        elapsed = time.time() - t0

    rows_per_sec = n / elapsed
    line = {
        "metric": "full_table_e2e_rows_per_sec_per_chip",
        "value": round(rows_per_sec, 1),
        "unit": (
            f"rows/s ({n/1e6:.1f}M rows, bin+300-tree-fit+predict+AUC "
            f"in {elapsed:.1f}s, held-out AUC {auc:.3f}; "
            "vs_baseline = x over the 4,791 rows/s/chip the v4-8 "
            "<60s north star requires)"
        ),
        "vs_baseline": round(rows_per_sec / NORTH_STAR_ROWS_PER_SEC_PER_CHIP, 2),
        "telemetry": snapshot(span_limit=16),
    }
    # Ride the committed full-protocol measurement (bench.py --protocol, a
    # multi-hour run not repeated per invocation) along the single line, with
    # provenance, so BENCH_r*.json carries both metrics.
    proto_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "BENCH_PROTOCOL.json")
    if os.path.exists(proto_path):
        with open(proto_path) as f:
            proto = json.load(f)
        # Quality and throughput must travel together on the HEADLINE line:
        # the 0.9x AUC above is a quick planted-logit signal, while the tuned
        # full-protocol AUC (>= the reference's 0.9530) is the parity claim.
        line["tuned_test_auc"] = proto.get("test_auc")
        line["unit"] += (
            f"; tuned full-protocol test AUC {proto.get('test_auc')} "
            "(see protocol)"
        )
        line["protocol"] = {
            "source": "BENCH_PROTOCOL.json ("
            + proto.get("produced_by", "full-protocol measurement")
            + "; measured on " + proto.get("device", "?") + ")",
            "rows_per_sec_per_chip": proto.get("value"),
            "seconds_total": proto.get("seconds_total"),
            "seconds_stage": proto.get("seconds_stage"),
            "test_auc": proto.get("test_auc"),
            "n_rows": proto.get("n_rows"),
            "vs_baseline": proto.get("vs_baseline"),
        }
    if ledger is not None:
        ledger.add_stage("full_table_fit", elapsed)
        ledger.set(
            "headline", {k: line[k] for k in line if k != "telemetry"}
        )
        ledger.write(args.ledger_out)
    if args.trend_out:
        from cobalt_smart_lender_ai_tpu.telemetry.trend import append_record

        append_record(args.trend_out, line, source="bench.py", stamp=time.time())
    print(json.dumps(line))


if __name__ == "__main__":
    main()
