"""Render a run ledger as a markdown cost-attribution report, or diff two.

The ledger (`telemetry/runledger.py`, written by `pipeline.py --ledger-out`,
`tools/retrain.py`, `tools/parity.py`, and the bench harnesses) carries a
run's config fingerprint, environment, stage durations, search rung history,
and the program cost table from `telemetry.programs`. This tool turns one
ledger into the report PERF_ATTRIBUTION.md was written by hand to be —
"which compiled program did the seconds go to" — and turns two ledgers into
the A/B comparison the real-TPU parity re-measure needs.

Usage:
    python tools/obs_report.py run.json                      # render one
    python tools/obs_report.py a.json b.json                 # diff two
    python tools/obs_report.py run.json --out REPORT.md
    python tools/obs_report.py run.json --min-attribution 0.8   # CI gate

``--min-attribution R`` exits nonzero when the ledger's measured dispatch
seconds exist but less than fraction R of them is attributed to named
programs — the observatory's coverage gate.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import Any

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _fmt_s(v: Any) -> str:
    try:
        return f"{float(v):.3f}"
    except (TypeError, ValueError):
        return "-"


def _fmt_rate(v: Any) -> str:
    """Human FLOP/s: 650 -> '650', 2.1e9 -> '2.10 G'."""
    try:
        v = float(v)
    except (TypeError, ValueError):
        return "-"
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(v) >= scale:
            return f"{v / scale:.2f} {suffix}"
    return f"{v:.0f}"


def _table(headers: list[str], rows: list[list[str]]) -> list[str]:
    out = ["| " + " | ".join(headers) + " |",
           "|" + "|".join("---" for _ in headers) + "|"]
    out += ["| " + " | ".join(r) + " |" for r in rows]
    return out


def render_report(doc: dict) -> str:
    """One ledger -> markdown cost-attribution report."""
    lines: list[str] = []
    lines.append(f"# Run report: {doc.get('kind', '?')}")
    lines.append("")
    fp = doc.get("fingerprint")
    if fp:
        lines.append(f"- config fingerprint: `{fp}`")
    meta = doc.get("meta") or {}
    if meta:
        lines.append(
            "- meta: "
            + ", ".join(f"{k}={v}" for k, v in sorted(meta.items()))
        )
    lines.append(f"- wall: {_fmt_s(doc.get('wall_seconds'))} s")
    env = doc.get("env") or {}
    lines.append(
        "- env: python {py}, jax {jx}, backend {be} x{n}".format(
            py=env.get("python", "?"),
            jx=env.get("jax", "?"),
            be=env.get("backend", "?"),
            n=env.get("device_count", "?"),
        )
    )
    devices = env.get("devices") or []
    if devices:
        kinds: dict[str, int] = {}
        for d in devices:
            kinds[d.get("kind", "?")] = kinds.get(d.get("kind", "?"), 0) + 1
        lines.append(
            "- devices: "
            + ", ".join(f"{n}x {k}" for k, n in sorted(kinds.items()))
        )
    lines.append("")

    stages = doc.get("stages") or {}
    if stages:
        lines.append("## Stages")
        lines.append("")
        total = sum(stages.values())
        lines += _table(
            ["stage", "seconds", "% of stages"],
            [
                [name, _fmt_s(sec),
                 f"{100.0 * sec / total:.1f}%" if total > 0 else "-"]
                for name, sec in sorted(
                    stages.items(), key=lambda kv: -kv[1]
                )
            ],
        )
        # Device-resident ingest splits L1/L2 wall into a host tokenize pass
        # and the jitted ingest.* programs; quote the host share directly so
        # a trend re-anchor can cite it without re-deriving from the table.
        host_s = stages.get("host_frontier")
        dev_s = stages.get("device_ingest")
        if host_s is not None and dev_s is not None and (host_s + dev_s) > 0:
            lines.append("")
            lines.append(
                f"Ingest host residual: {100.0 * host_s / (host_s + dev_s):.1f}% "
                f"of ingest wall ({_fmt_s(host_s)} stringy-frontier tokenize "
                f"vs {_fmt_s(dev_s)} device programs)."
            )
        lines.append("")

    programs = doc.get("programs") or []
    totals = doc.get("program_totals") or {}
    lines.append("## Program cost table")
    lines.append("")
    if programs:
        attr_total = float(totals.get("dispatch_seconds") or 0.0)
        rows = []
        for p in programs:
            disp_s = float(p.get("dispatch_seconds") or 0.0)
            rows.append([
                f"`{p.get('name', '?')}`",
                str(p.get("dispatches", 0)),
                _fmt_s(disp_s),
                f"{100.0 * disp_s / attr_total:.1f}%"
                if attr_total > 0 else "-",
                str(p.get("compiles", 0)),
                _fmt_s(p.get("compile_seconds")),
                _fmt_rate(p.get("flops")),
                _fmt_rate(p.get("achieved_flops_per_second")),
                "-" if p.get("roofline_utilization") is None
                else f"{100.0 * p['roofline_utilization']:.1f}%",
            ])
        lines += _table(
            ["program", "disp", "disp s", "% attr", "compiles",
             "compile s", "flops/disp", "achieved FLOP/s", "roofline"],
            rows,
        )
    else:
        lines.append("(no programs recorded)")
    lines.append("")

    attr = doc.get("dispatch_attribution") or {}
    measured = attr.get("measured_seconds")
    ratio = attr.get("ratio")
    lines.append("## Dispatch attribution")
    lines.append("")
    lines.append(f"- measured dispatch seconds: {_fmt_s(measured)}")
    lines.append(
        f"- attributed to named programs: {_fmt_s(attr.get('attributed_seconds'))}"
    )
    if ratio is None:
        lines.append("- ratio: n/a (no measured dispatch families this run)")
    else:
        lines.append(f"- ratio: {float(ratio):.3f}")
    lines.append("")

    comp = doc.get("compile") or {}
    if comp:
        lines.append("## Compile cache")
        lines.append("")
        for k in sorted(comp):
            lines.append(f"- {k}: {comp[k]}")
        lines.append("")

    halving = doc.get("search_halving")
    if isinstance(halving, dict) and halving.get("rungs"):
        lines.append("## Search rungs (successive halving)")
        lines.append("")
        lines += _table(
            ["rung", "budget trees", "live", "pruned"],
            [
                [str(i), str(r.get("budget", r.get("budget_trees", "?"))),
                 str(r.get("live", "?")), str(r.get("pruned", "?"))]
                for i, r in enumerate(halving["rungs"])
            ],
        )
        lines.append(
            f"\n- pruned candidates total: "
            f"{halving.get('pruned_candidates', '?')}"
        )
        lines.append("")

    final = doc.get("final_metrics")
    if isinstance(final, dict):
        lines.append("## Final metrics")
        lines.append("")
        for k, v in sorted(final.items()):
            lines.append(f"- {k}: {v}")
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"


def _program_seconds(doc: dict) -> dict[str, float]:
    return {
        p.get("name", "?"): float(p.get("dispatch_seconds") or 0.0)
        for p in (doc.get("programs") or [])
    }


def render_diff(a: dict, b: dict) -> str:
    """Two ledgers -> markdown A/B comparison (B relative to A)."""
    lines: list[str] = []
    lines.append(
        f"# Run diff: {a.get('kind', '?')} (A) vs {b.get('kind', '?')} (B)"
    )
    lines.append("")
    for label, doc in (("A", a), ("B", b)):
        env = doc.get("env") or {}
        lines.append(
            f"- {label}: backend {env.get('backend', '?')} "
            f"x{env.get('device_count', '?')}, "
            f"wall {_fmt_s(doc.get('wall_seconds'))} s, "
            f"fingerprint `{doc.get('fingerprint') or '-'}`"
        )
    if a.get("fingerprint") != b.get("fingerprint"):
        lines.append(
            "- **fingerprints differ** — the sides ran different configs"
        )
    lines.append("")

    sa, sb = a.get("stages") or {}, b.get("stages") or {}
    names = sorted(set(sa) | set(sb), key=lambda n: -(sa.get(n, 0.0)))
    if names:
        lines.append("## Stage deltas (B - A)")
        lines.append("")
        rows = []
        for n in names:
            va, vb = sa.get(n), sb.get(n)
            delta = None if va is None or vb is None else vb - va
            speed = (
                f"{va / vb:.2f}x"
                if va and vb and vb > 0 else "-"
            )
            rows.append([
                n, _fmt_s(va), _fmt_s(vb),
                "-" if delta is None else f"{delta:+.3f}", speed,
            ])
        lines += _table(["stage", "A s", "B s", "delta s", "A/B"], rows)
        lines.append("")

    pa, pb = _program_seconds(a), _program_seconds(b)
    names = sorted(
        set(pa) | set(pb),
        key=lambda n: -max(pa.get(n, 0.0), pb.get(n, 0.0)),
    )
    if names:
        lines.append("## Program dispatch-seconds deltas (B - A)")
        lines.append("")
        rows = []
        for n in names:
            va, vb = pa.get(n), pb.get(n)
            delta = None if va is None or vb is None else vb - va
            rows.append([
                f"`{n}`",
                "-" if va is None else _fmt_s(va),
                "-" if vb is None else _fmt_s(vb),
                "-" if delta is None else f"{delta:+.3f}",
            ])
        lines += _table(["program", "A s", "B s", "delta s"], rows)
        lines.append("")

    fa, fb = a.get("final_metrics") or {}, b.get("final_metrics") or {}
    keys = sorted(
        k for k in set(fa) | set(fb)
        if isinstance(fa.get(k, fb.get(k)), (int, float))
    )
    if keys:
        lines.append("## Final metric deltas (B - A)")
        lines.append("")
        rows = []
        for k in keys:
            va, vb = fa.get(k), fb.get(k)
            delta = (
                None
                if not isinstance(va, (int, float))
                or not isinstance(vb, (int, float))
                else vb - va
            )
            rows.append([
                k, str(va if va is not None else "-"),
                str(vb if vb is not None else "-"),
                "-" if delta is None else f"{delta:+.5f}",
            ])
        lines += _table(["metric", "A", "B", "delta"], rows)
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("ledger", help="run-ledger JSON path")
    ap.add_argument("ledger_b", nargs="?", default=None,
                    help="second ledger: render an A/B diff instead")
    ap.add_argument("--out", default=None,
                    help="write the markdown here (default: stdout)")
    ap.add_argument("--min-attribution", type=float, default=None,
                    help="exit 1 unless attributed/measured dispatch "
                    "seconds >= this fraction (skipped when the run "
                    "measured no dispatch seconds)")
    args = ap.parse_args(argv)

    from cobalt_smart_lender_ai_tpu.telemetry.runledger import load_ledger

    doc = load_ledger(args.ledger)
    if args.ledger_b:
        text = render_diff(doc, load_ledger(args.ledger_b))
    else:
        text = render_report(doc)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text)
    else:
        print(text)

    if args.min_attribution is not None:
        attr = doc.get("dispatch_attribution") or {}
        ratio = attr.get("ratio")
        if ratio is not None and float(ratio) < args.min_attribution:
            print(
                f"attribution ratio {float(ratio):.3f} below the "
                f"--min-attribution {args.min_attribution} gate",
                file=sys.stderr,
            )
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
