"""Causal incident forensics over the fleet event journal.

The control plane journals every decision it makes — supervisor
transitions, autoscaler resizes, brownout rungs, canary verdicts, reload
publishes, breaker flips, chaos injections — as typed events whose
``cause_id`` links chain each consequence back to its trigger
(`telemetry/events.py`). This tool turns that journal into the markdown
postmortem an operator would otherwise reconstruct by hand from four
dashboards: what fired, what caused it, what the data plane saw while it
happened, and how long until the fleet was healthy again.

Sources (either or both):
    --bench RECORD.json     a bench_serve.py record with the embedded
                            ``events.journal`` snapshot (chaos-fleet and
                            autoscale-smoke CI commit these)
    --store PATH [--prefix] durable md5-pinned segments shipped by the
                            journal (telemetry.events.load_events)

Usage:
    python tools/incident_report.py --bench BENCH_CHAOS_r02.json
    python tools/incident_report.py --store artifacts --out incident.md
    python tools/incident_report.py --bench b.json --window 10:40
    python tools/incident_report.py --bench b.json --require-cause

``--window A:B`` keeps events whose timestamp falls in [A, B]; values
under 1e6 are offsets in seconds from the first event, larger values are
absolute wall timestamps. Either side may be empty (``:30``, ``10:``).

``--require-cause`` is the CI gate: every quarantine transition, resize
and brownout step must carry a cause (trigger snapshot) or a ``cause_id``
link — an orphan means an emit site lost its causal thread. Exit 4 lists
the orphans; exit 2 means the input could not be read.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

#: (component, kind) pairs that make a causal tree worth a postmortem
#: section. Routine control-plane churn (retunes, publishes, breaker
#: close) still shows in the totals and event log, just not as an
#: incident of its own.
_INCIDENT_SEVERITY: dict[tuple[str, str], int] = {
    ("supervisor", "probe_failure"): 1,
    ("supervisor", "rebuild"): 2,
    ("supervisor", "swap"): 2,
    ("supervisor", "transition"): 2,
    ("autoscaler", "resize"): 1,
    ("autoscaler", "brownout"): 1,
    ("canary", "reject"): 2,
    ("canary", "rollback"): 2,
    ("reload", "rollback"): 2,
    ("breaker", "open"): 2,
    ("chaos", "inject"): 1,
}

#: Kinds the --require-cause gate audits: the three decisions an operator
#: always asks "why" about. Each must carry a cause snapshot or chain to
#: the event that triggered it.
_GATED = ("supervisor.transition:quarantined", "autoscaler.resize",
          "autoscaler.brownout")


def _gated(event: dict) -> str | None:
    """The gate label this event falls under, or None if ungated."""
    component, kind = event.get("component"), event.get("kind")
    if component == "supervisor" and kind == "transition":
        payload = event.get("payload") or {}
        if payload.get("to") == "quarantined":
            return _GATED[0]
        return None
    if component == "autoscaler" and kind in ("resize", "brownout"):
        return f"{component}.{kind}"
    return None


# -- loading -------------------------------------------------------------------

def load_bench(path: str) -> tuple[list[dict], dict]:
    """Events embedded in a bench record, plus the record itself (its
    load/supervisor/autoscaler blocks become the report's data-plane
    context)."""
    with open(path) as fh:
        doc = json.load(fh)
    block = doc.get("events") or {}
    journal = block.get("journal")
    if not isinstance(journal, list):
        raise ValueError(
            f"{path} has no events.journal block — re-run the bench with "
            "a journal-aware harness"
        )
    return [e for e in journal if isinstance(e, dict)], doc


def load_store(path: str, prefix: str) -> list[dict]:
    from cobalt_smart_lender_ai_tpu.io.store import ObjectStore
    from cobalt_smart_lender_ai_tpu.telemetry.events import load_events

    return load_events(ObjectStore(path), prefix)


def apply_window(events: list[dict], window: str | None) -> list[dict]:
    if not window:
        return events
    lo_s, _, hi_s = window.partition(":")
    t0 = min((float(e.get("t", 0.0)) for e in events), default=0.0)

    def _bound(raw: str) -> float | None:
        if not raw:
            return None
        v = float(raw)
        return t0 + v if abs(v) < 1e6 else v

    lo, hi = _bound(lo_s), _bound(hi_s)
    return [
        e
        for e in events
        if (lo is None or float(e.get("t", 0.0)) >= lo)
        and (hi is None or float(e.get("t", 0.0)) <= hi)
    ]


# -- causal reconstruction -----------------------------------------------------

def build_chains(events: list[dict]) -> list[list[dict]]:
    """Group events into causal trees by walking ``cause_id`` links.

    A root is an event whose cause_id is absent *or* points outside the
    window (its trigger was evicted or filtered — the chain is still
    worth reading from where it starts). Each tree is flattened
    depth-first in event-id order, so a chain reads top-to-bottom as
    trigger -> consequence."""
    by_id = {int(e["event_id"]): e for e in events if "event_id" in e}
    children: dict[int, list[int]] = {}
    roots: list[int] = []
    for eid in sorted(by_id):
        cause = by_id[eid].get("cause_id")
        if cause is not None and int(cause) in by_id:
            children.setdefault(int(cause), []).append(eid)
        else:
            roots.append(eid)

    def _flatten(eid: int, out: list[dict]) -> None:
        out.append(by_id[eid])
        for child in children.get(eid, ()):
            _flatten(child, out)

    trees: list[list[dict]] = []
    for root in roots:
        tree: list[dict] = []
        _flatten(root, tree)
        trees.append(tree)
    return trees


def _severity(tree: list[dict]) -> int:
    return max(
        (
            _INCIDENT_SEVERITY.get((e.get("component"), e.get("kind")), 0)
            for e in tree
        ),
        default=0,
    )


def suspected_trigger(
    tree: list[dict], events: list[dict]
) -> dict | None:
    """The most recent same-replica ``chaos.inject`` preceding the chain's
    root. Chaos faults surface to the supervisor only as probe failures,
    so the causal link is circumstantial by design — the report names the
    suspect rather than silently claiming certainty."""
    root = tree[0]
    if (root.get("component"), root.get("kind")) == ("chaos", "inject"):
        return None
    replicas = {e.get("replica") for e in tree if e.get("replica") is not None}
    if not replicas:
        return None
    best = None
    for e in events:
        if (e.get("component"), e.get("kind")) != ("chaos", "inject"):
            continue
        if e.get("replica") not in replicas:
            continue
        if float(e.get("t", 0.0)) > float(root.get("t", 0.0)):
            continue
        if best is None or float(e["t"]) > float(best["t"]):
            best = e
    return best


def heal_seconds(tree: list[dict]) -> float | None:
    """Quarantine -> healthy wall time within one chain, if both ends are
    present."""
    t_q = t_h = None
    for e in tree:
        if (e.get("component"), e.get("kind")) != ("supervisor", "transition"):
            continue
        to = (e.get("payload") or {}).get("to")
        if to == "quarantined" and t_q is None:
            t_q = float(e.get("t", 0.0))
        if to == "healthy" and t_q is not None:
            t_h = float(e.get("t", 0.0))
    if t_q is None or t_h is None:
        return None
    return round(t_h - t_q, 3)


def find_orphans(events: list[dict]) -> list[dict]:
    """Gated events carrying neither a cause snapshot nor a cause link."""
    return [
        e
        for e in events
        if _gated(e) is not None
        and not e.get("cause")
        and e.get("cause_id") is None
    ]


# -- rendering -----------------------------------------------------------------

def _payload_brief(event: dict, limit: int = 4) -> str:
    payload = event.get("payload") or {}
    parts = [
        f"{k}={payload[k]}"
        for k in list(payload)[:limit]
        if not isinstance(payload[k], (dict, list))
    ]
    return ", ".join(parts) if parts else "-"


def _chain_table(tree: list[dict], t0: float) -> list[str]:
    rows = []
    for e in tree:
        rows.append(
            "| {eid} | +{dt:.2f}s | {ck} | {rep} | {cause} | {detail} |".format(
                eid=e.get("event_id", "?"),
                dt=float(e.get("t", t0)) - t0,
                ck=f"{e.get('component')}.{e.get('kind')}",
                rep="-" if e.get("replica") is None else e["replica"],
                cause="-" if e.get("cause_id") is None else e["cause_id"],
                detail=_payload_brief(e),
            )
        )
    return [
        "| event | t | what | replica | cause | detail |",
        "|---|---|---|---|---|---|",
        *rows,
    ]


def render_report(
    events: list[dict],
    *,
    source: str,
    bench: dict | None = None,
    window: str | None = None,
) -> str:
    lines: list[str] = ["# Fleet incident report", ""]
    lines.append(f"- source: {source}")
    if window:
        lines.append(f"- window: `{window}`")
    lines.append(f"- events: {len(events)}")
    if not events:
        lines.append("")
        lines.append("No control-plane events in the window — nothing fired.")
        return "\n".join(lines) + "\n"
    t0 = min(float(e.get("t", 0.0)) for e in events)
    span = max(float(e.get("t", 0.0)) for e in events) - t0
    lines.append(f"- span: {span:.2f}s")
    lines.append("")

    counts: dict[str, int] = {}
    for e in events:
        key = f"{e.get('component')}.{e.get('kind')}"
        counts[key] = counts.get(key, 0) + 1
    lines.append("## What fired")
    lines.append("")
    lines.append("| event kind | count |")
    lines.append("|---|---|")
    for key in sorted(counts):
        lines.append(f"| {key} | {counts[key]} |")
    lines.append("")

    if bench is not None:
        lines += _bench_context(bench)

    trees = build_chains(events)
    incidents = [t for t in trees if _severity(t) >= 2]
    minor = [t for t in trees if _severity(t) == 1 and len(t) > 1]
    lines.append("## Incidents")
    lines.append("")
    if not incidents and not minor:
        lines.append("No incident-grade causal chains — routine churn only.")
        lines.append("")
    for n, tree in enumerate(incidents + minor, start=1):
        root = tree[0]
        title = f"{root.get('component')}.{root.get('kind')}"
        if root.get("replica") is not None:
            title += f" (replica {root['replica']})"
        lines.append(f"### Incident {n}: {title}")
        lines.append("")
        trigger = suspected_trigger(tree, events)
        if trigger is not None:
            lines.append(
                "- suspected trigger: `chaos.inject` "
                f"fault={((trigger.get('payload') or {}).get('fault'))!r} on "
                f"replica {trigger.get('replica')} at "
                f"+{float(trigger.get('t', t0)) - t0:.2f}s "
                f"(event {trigger.get('event_id')})"
            )
        heal = heal_seconds(tree)
        if heal is not None:
            lines.append(f"- time to healthy: **{heal:.3f}s**")
        cause = root.get("cause")
        if cause:
            brief = ", ".join(
                f"{k}={v}"
                for k, v in list(cause.items())[:4]
                if not isinstance(v, (dict, list))
            )
            if brief:
                lines.append(f"- root cause snapshot: {brief}")
        lines.append("")
        lines += _chain_table(tree, t0)
        lines.append("")

    orphans = find_orphans(events)
    lines.append("## Causal coverage")
    lines.append("")
    gated = [e for e in events if _gated(e) is not None]
    lines.append(
        f"- gated events (quarantine/resize/brownout): {len(gated)}, "
        f"orphans (no cause, no cause_id): {len(orphans)}"
    )
    for e in orphans:
        lines.append(
            f"  - ORPHAN event {e.get('event_id')}: "
            f"{e.get('component')}.{e.get('kind')} at "
            f"+{float(e.get('t', t0)) - t0:.2f}s"
        )
    lines.append("")
    return "\n".join(lines) + "\n"


def _bench_context(bench: dict) -> list[str]:
    """What the data plane saw while the control plane acted."""
    lines = ["## Data plane during the run", ""]
    load = bench.get("load") or {}
    if load:
        lines.append(
            "- load: {req} requests, {err} errors ({unt} untyped), "
            "p99 {p99} ms".format(
                req=load.get("requests", "?"),
                err=load.get("errors", "?"),
                unt=load.get("untyped_errors", "?"),
                p99=load.get("p99_ms", "?"),
            )
        )
    sup = bench.get("supervisor") or {}
    if sup:
        lines.append(
            "- supervisor: {q} quarantines, {r} rebuilds ok, heal "
            "{h}s, all healthy at end: {a}".format(
                q=sup.get("quarantines", "?"),
                r=sup.get("rebuilds_ok", "?"),
                h=sup.get("heal_s", "?"),
                a=sup.get("all_healthy", "?"),
            )
        )
    scaler = bench.get("autoscaler") or {}
    if scaler:
        lines.append(
            "- autoscaler: {u} up / {d} down, brownout engaged {e} / "
            "released {rel}, max level {m}".format(
                u=scaler.get("resizes_up", "?"),
                d=scaler.get("resizes_down", "?"),
                e=scaler.get("brownout_engaged", "?"),
                rel=scaler.get("brownout_released", "?"),
                m=scaler.get("max_level_seen", "?"),
            )
        )
    stats = (bench.get("events") or {}).get("stats") or {}
    if stats:
        lines.append(
            "- journal: {n} emitted, {drop} dropped, ring depth "
            "{depth}/{cap}".format(
                n=stats.get("emitted", "?"),
                drop=stats.get("dropped", "?"),
                depth=stats.get("depth", "?"),
                cap=stats.get("capacity", "?"),
            )
        )
    lines.append("")
    return lines


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--bench", default=None,
                    help="bench record JSON with an events.journal block")
    ap.add_argument("--store", default=None,
                    help="object store path holding shipped journal segments")
    ap.add_argument("--prefix", default="telemetry/events",
                    help="segment key prefix under --store")
    ap.add_argument("--window", default=None, metavar="A:B",
                    help="keep events in [A, B] (relative seconds when "
                         "< 1e6, else absolute wall timestamps)")
    ap.add_argument("--require-cause", action="store_true",
                    help="exit 4 if any quarantine/resize/brownout event "
                         "carries neither a cause nor a cause_id link")
    ap.add_argument("--out", default=None,
                    help="write the markdown report here (default stdout)")
    args = ap.parse_args(argv)

    if args.bench is None and args.store is None:
        ap.error("need --bench and/or --store")

    events: list[dict] = []
    bench_doc: dict | None = None
    sources: list[str] = []
    try:
        if args.bench is not None:
            bench_events, bench_doc = load_bench(args.bench)
            events += bench_events
            sources.append(f"bench `{args.bench}`")
        if args.store is not None:
            events += load_store(args.store, args.prefix)
            sources.append(f"store `{args.store}` prefix `{args.prefix}`")
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    # merge + dedup by event_id (bench snapshot and shipped segments overlap)
    merged = {int(e["event_id"]): e for e in events if "event_id" in e}
    events = [merged[eid] for eid in sorted(merged)]
    events = apply_window(events, args.window)

    report = render_report(
        events,
        source=" + ".join(sources),
        bench=bench_doc,
        window=args.window,
    )
    if args.out:
        Path(args.out).write_text(report)
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        print(report)

    if args.require_cause:
        orphans = find_orphans(events)
        if orphans:
            print(
                f"require-cause: {len(orphans)} orphan event(s) — a "
                "quarantine/resize/brownout lost its causal link:",
                file=sys.stderr,
            )
            for e in orphans:
                print(
                    f"  event {e.get('event_id')} "
                    f"{e.get('component')}.{e.get('kind')} "
                    f"payload={e.get('payload')}",
                    file=sys.stderr,
                )
            return 4
        gated = [e for e in events if _gated(e) is not None]
        print(
            f"require-cause: OK ({len(gated)} gated events, 0 orphans)",
            file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
