"""Train and commit the servable model artifact — the reference ships its
trained model in-repo (`/root/reference/src/api/models/xgb_model_tree.pkl`,
2.2MB) so `docker-compose up` serves out of the box (cobalt_fast_api.py:36-54);
this produces our counterpart: a GBDTArtifact npz + `.features.json` sidecar
at the default ServeConfig store location (`artifacts/models/gbdt/model_tree`),
trained on the 20 serving-contract features with the protocol's tuned
hyperparameters.

Usage:
    python tools/train_artifact.py [--rows 130000] [--out artifacts]

The training frame is the full-schema synthetic generator (the real table is
behind a private bucket — data/bootstrap.py); the artifact records provenance
(rows, seed, params, test AUC) in its metrics blob.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=130_000)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--out", default="artifacts")
    ap.add_argument("--key", default="models/gbdt/model_tree")
    args = ap.parse_args(argv)

    import jax.numpy as jnp

    from cobalt_smart_lender_ai_tpu.config import GBDTConfig
    from cobalt_smart_lender_ai_tpu.data import (
        clean_raw_frame,
        engineer_features,
        prepare_cleaned_frame,
        synthetic_lendingclub_frame,
        train_test_split_hashed,
    )
    from cobalt_smart_lender_ai_tpu.data import schema
    from cobalt_smart_lender_ai_tpu.data.features import drop_training_leakage
    from cobalt_smart_lender_ai_tpu.compilecache import bootstrap_compile_cache
    from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, ObjectStore
    from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTClassifier
    from cobalt_smart_lender_ai_tpu.ops.metrics import roc_auc

    bootstrap_compile_cache()
    t0 = time.time()
    raw = synthetic_lendingclub_frame(n_rows=args.rows, seed=args.seed)
    cleaned, _ = clean_raw_frame(raw)
    tree_ff, _, _ = engineer_features(prepare_cleaned_frame(cleaned))
    ff = drop_training_leakage(tree_ff).select(schema.SERVING_FEATURES)
    X_train, X_test, y_train, y_test = train_test_split_hashed(ff.X, ff.y)
    y_np = np.asarray(y_train)
    spw = (len(y_np) - y_np.sum()) / max(y_np.sum(), 1.0)

    # The protocol's tuned regime (BENCH_PROTOCOL.json best_params family):
    # deep-ish trees, low LR, full reference bin budget, class-weighted.
    cfg = GBDTConfig(
        n_estimators=300,
        max_depth=7,
        learning_rate=0.05,
        subsample=0.8,
        colsample_bytree=0.8,
        n_bins=255,
        scale_pos_weight=float(spw),
        chunk_trees="auto",
    )
    model = GBDTClassifier(cfg)
    model.fit(np.asarray(X_train), y_np)
    margin = model.predict_margin(jnp.asarray(X_test))
    test_auc = float(roc_auc(jnp.asarray(y_test, jnp.float32), margin))
    wall = time.time() - t0

    store = ObjectStore(args.out)
    GBDTArtifact(
        forest=model.forest,
        bin_spec=model.bin_spec,
        feature_names=tuple(schema.SERVING_FEATURES),
        config={
            k: getattr(cfg, k)
            for k in (
                "n_estimators", "max_depth", "learning_rate", "subsample",
                "colsample_bytree", "n_bins", "scale_pos_weight", "seed",
            )
        },
        metrics={
            "test_auc": round(test_auc, 4),
            "train_rows": int(np.asarray(X_train).shape[0]),
            "data": f"synthetic_lendingclub_frame(rows={args.rows}, seed={args.seed})",
            "trained_wall_s": round(wall, 1),
        },
    ).save(store, args.key)
    print(json.dumps({
        "artifact": f"{args.out}/{args.key}",
        "test_auc": round(test_auc, 4),
        "wall_s": round(wall, 1),
    }))


if __name__ == "__main__":
    main()
