"""Oracle wall-vs-rows scale curve -> PARITY_SCALE.json.

VERDICT r4 missing-item #2: "beats the oracle" was proven at 130k rows only,
while the full-scale claim rested on our 2.3M number alone. A 2.3M CPU-oracle
run would take ~10h on this 1-core host, so instead the oracle protocol legs
(tools/parity.py oracle — the sklearn HistGradientBoostingClassifier through
the reference's RFE + search protocol, model_tree_train_test.py:111-159) are
measured at several row counts and each leg's wall is fitted with a power law

    wall(N) = c * N^p        (least squares on log-log)

whose extrapolation to the 2.3M protocol scale is committed NEXT TO our
measured 2.3M wall (BENCH_PROTOCOL.json). The artifact labels the oracle
number as an extrapolation — the claim it supports is the *scaling shape*
("the gap grows with N"), anchored by the measured points it interpolates.

Usage:
    python tools/scale_curve.py PARITY_oracle.json /tmp/PARITY_oracle_260k.json \
        /tmp/PARITY_oracle_520k.json --target-rows 2300000 \
        --ours BENCH_PROTOCOL.json --out PARITY_SCALE.json
"""

from __future__ import annotations

import argparse
import json
import math
from pathlib import Path

LEGS = ("rfe", "search", "total")


def fit_power_law(points: list[tuple[int, float]]) -> tuple[float, float]:
    """Least-squares fit of log(wall) = log(c) + p*log(N); returns (c, p)."""
    xs = [math.log(n) for n, _ in points]
    ys = [math.log(w) for _, w in points]
    n = len(xs)
    mx = sum(xs) / n
    my = sum(ys) / n
    sxx = sum((x - mx) ** 2 for x in xs)
    sxy = sum((x - mx) * (y - my) for x, y in zip(xs, ys))
    p = sxy / sxx
    c = math.exp(my - p * mx)
    return c, p


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("oracle_jsons", nargs="+")
    ap.add_argument("--target-rows", type=int, default=2_300_000)
    ap.add_argument("--ours", default=None,
                    help="BENCH_PROTOCOL.json with our measured target-scale legs")
    ap.add_argument("--out", default="PARITY_SCALE.json")
    args = ap.parse_args(argv)

    runs = []
    for path in args.oracle_jsons:
        doc = json.loads(Path(path).read_text())
        if doc.get("side") != "oracle":
            raise SystemExit(f"{path} is not an oracle-side parity artifact")
        runs.append(doc)
    runs.sort(key=lambda d: d["n_rows"])
    if len({d["n_rows"] for d in runs}) < 2:
        raise SystemExit("need oracle runs at >= 2 distinct row counts")

    curves = {}
    for leg in LEGS:
        points = [(d["n_rows"], d["seconds"][leg]) for d in runs]
        c, p = fit_power_law(points)
        p = round(p, 4)  # committed precision; residuals use the SAME values
        fitted = {
            str(n): round(c * n**p, 1) for n, _ in points
        }
        max_resid = max(
            abs(c * n**p - w) / w for n, w in points
        )
        walls = [w for _, w in points]
        if max_resid > 0.25 or p < 0.05:
            # The oracle's wall is NOT meaningfully growing with N (sklearn
            # HGB early-stops once n_samples > 10k, so bigger inputs can
            # converge in FEWER boosting iterations) or the power law does
            # not hold across the measured points. Extrapolating a broken
            # fit would be fiction; commit the measured BAND instead and
            # take its maximum as the (conservative-against-us) target wall.
            curves[leg] = {
                "model": "flat band over measured points (no growth trend)",
                "measured_points": {str(n): w for n, w in points},
                "band_wall_s": [min(walls), max(walls)],
                "power_fit_rejected": {
                    "p": p, "max_relative_residual": round(max_resid, 4)
                },
                "extrapolated_wall_s_at_target": max(walls),
            }
            continue
        curves[leg] = {
            "model": "wall_s = c * rows^p",
            "c": c,
            "p": p,
            "measured_points": {str(n): w for n, w in points},
            "fitted_at_points": fitted,
            "max_relative_residual": round(max_resid + 5e-5, 4),
            "extrapolated_wall_s_at_target": round(
                c * args.target_rows**p, 1
            ),
        }

    doc = {
        "artifact": "oracle wall-vs-rows scale curve (extrapolated target)",
        "oracle_backend": runs[0]["backend"],
        "target_rows": args.target_rows,
        "n_measured_points": len(runs),
        "note": (
            "target-row oracle walls are EXTRAPOLATED from the measured "
            "points — per-leg power-law fits where a growth trend holds, "
            "otherwise the measured band's maximum (the sklearn oracle "
            "early-stops, so its wall is not monotone in rows); the "
            "measured points themselves are real solo runs of "
            "tools/parity.py oracle"
        ),
        "curves": curves,
    }
    if args.ours:
        ours = json.loads(Path(args.ours).read_text())
        stages = ours.get("seconds_stage", {})
        ours_legs = {
            "rfe": stages.get("rfe"),
            "search": stages.get("search"),
            "total": ours.get("seconds_total"),
        }
        doc["ours_measured_at_target"] = {
            "source": "BENCH_PROTOCOL.json (measured, one chip)",
            "n_rows": ours.get("n_rows"),
            "seconds": ours_legs,
        }
        doc["speedup_at_target"] = {
            leg: round(
                curves[leg]["extrapolated_wall_s_at_target"] / ours_legs[leg], 3
            )
            for leg in LEGS
            if ours_legs.get(leg)
        }
    Path(args.out).write_text(json.dumps(doc, indent=2))
    print(json.dumps({
        "out": args.out,
        "models": {leg: curves[leg]["model"] for leg in LEGS},
        "oracle_extrapolated_total_at_target":
            curves["total"]["extrapolated_wall_s_at_target"],
        "speedup_at_target": doc.get("speedup_at_target"),
    }))


if __name__ == "__main__":
    main()
