#!/usr/bin/env python
"""Scoring-kernel bench: the fused one-dispatch Pallas program against the
classic margin + SHAP program pair, at serving micro-batch bucket sizes,
per forest precision.

``reference`` is what the micro-batcher dispatched per coalesced batch
before the fused kernel: one `predict_margin` program THEN one
`shap_values` program (two device round-trips). ``fused`` is the one-pass
`ops/score_pallas.py` program — traversal + margin + sigmoid + SHAP
phi-accumulation in a single dispatch. Both sides are AOT-compiled through
the partitioner (one untimed warmup pays compiles), then the best of
``--repeats`` timed dispatches is kept (BENCH_BULK precedent).

The reference contraction only runs the exact f32 forest, so the record
carries one reference column (under ``f32``) and a fused column per
precision; bf16/int8 cells also report their margin deltas vs f32 so the
speed number never hides an accuracy regression.

Honest caveat, as prior BENCH files note: this container is a ~1-core CPU
host running the Pallas kernel in *interpret mode* — absolute numbers say
nothing about TPU wall time, and interpret-mode overhead flatters neither
side equally. The relative fused-vs-reference ratio is still the metric
the ``--check`` gate (CI kernel-smoke job) holds: the fused dispatch must
not be slower than the program pair it replaces.

    python tools/bench_kernels.py --out BENCH_KERNEL_r01.json
    python tools/perf_sentinel.py ingest BENCH_KERNEL_r01.json --no-stamp
    python tools/bench_kernels.py --check BENCH_KERNEL_r01.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: Serving-shaped workload: the conftest serving model's scale (25 trees,
#: depth 3, 20 features) so the bench measures the bucket sizes the
#: micro-batcher actually dispatches.
N_TREES = 25
DEPTH = 3
N_FEATURES = 20


def _platform_tag() -> str:
    import jax

    return jax.devices()[0].platform


def _host_cpu_cores() -> int:
    if hasattr(os, "sched_getaffinity"):
        return len(os.sched_getaffinity(0))
    return os.cpu_count() or 1


def _time_best(fn, repeats: int) -> float:
    fn()  # warmup: compiles, caches, page-in
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def run_kernel_bench(
    buckets: list[int], *, repeats: int, precisions: list[str]
) -> dict:
    import jax
    import numpy as np

    from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTClassifier
    from cobalt_smart_lender_ai_tpu.ops.score_pallas import (
        pack_forest,
        quantization_report,
    )
    from cobalt_smart_lender_ai_tpu.parallel.partitioner import (
        SingleDevicePartitioner,
    )

    rng = np.random.default_rng(19)
    X = rng.normal(size=(4096, N_FEATURES)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] - 0.3 * X[:, 2] > 0).astype(np.int32)
    model = GBDTClassifier(
        n_estimators=N_TREES, max_depth=DEPTH, n_bins=64
    ).fit(X, y)
    forest = model.forest

    part = SingleDevicePartitioner(kind_prefix="bench")
    results: dict[str, dict] = {}
    for precision in precisions:
        pack = pack_forest(forest, N_FEATURES, precision)
        quant = (
            None
            if precision == "f32"
            else quantization_report(forest, pack, N_FEATURES)
        )
        per_bucket: dict[str, dict] = {}
        for bucket in buckets:
            xb = X[:bucket]
            fused_fn = part.compile_fused(
                pack, N_FEATURES, bucket, with_shap=True
            )

            def fused_pass():
                jax.block_until_ready(fused_fn(xb))

            cell: dict[str, dict] = {}
            fused_s = _time_best(fused_pass, repeats)
            cell["fused"] = {
                "dispatch_seconds": round(fused_s, 6),
                "rows_per_s": round(bucket / fused_s, 1),
            }
            if precision == "f32":
                margin_fn = part.compile_margin(
                    forest, N_FEATURES, bucket, kernel="reference"
                )
                shap_fn = part.compile_shap(
                    forest, N_FEATURES, bucket, kernel="reference"
                )

                def reference_pass():
                    # The pre-fused serving hot path: margin dispatch,
                    # sigmoid on host, then the SHAP dispatch.
                    m = margin_fn(xb)
                    np.asarray(jax.nn.sigmoid(m))
                    jax.block_until_ready(shap_fn(xb))

                ref_s = _time_best(reference_pass, repeats)
                cell["reference"] = {
                    "dispatch_seconds": round(ref_s, 6),
                    "rows_per_s": round(bucket / ref_s, 1),
                }
                cell["speedup"] = round(ref_s / fused_s, 2)
            per_bucket[str(bucket)] = cell
            line = (
                f"[bench] {precision} bucket={bucket}: "
                f"fused {fused_s * 1e3:.2f}ms"
            )
            if "reference" in cell:
                line += (
                    f", reference {cell['reference']['dispatch_seconds'] * 1e3:.2f}ms"
                    f" ({cell['speedup']}x)"
                )
            print(line, file=sys.stderr)
        results[precision] = per_bucket
        if quant is not None:
            results[precision]["quantization"] = {
                k: v for k, v in quant.items() if k != "tolerance"
            }

    return {
        "bench": "score_kernels",
        "forest": {
            "n_trees": N_TREES,
            "depth": DEPTH,
            "n_features": N_FEATURES,
        },
        "repeats": repeats,
        "platform": _platform_tag(),
        "interpret_mode": _platform_tag() != "tpu",
        "devices": len(jax.devices()),
        "host_cpu_cores": _host_cpu_cores(),
        "results": results,
    }


def check_record(record: dict, *, slack: float) -> int:
    """The CI gate: at every bucket, the fused f32 dispatch must be no
    slower than ``slack`` x the reference program pair it replaces."""
    failures = []
    f32 = (record.get("results") or {}).get("f32") or {}
    for bucket, cell in f32.items():
        if not isinstance(cell, dict) or "reference" not in cell:
            continue
        fused_s = cell["fused"]["dispatch_seconds"]
        ref_s = cell["reference"]["dispatch_seconds"]
        if fused_s > ref_s * slack:
            failures.append(
                f"bucket {bucket}: fused {fused_s:.6f}s > "
                f"{slack:g}x reference {ref_s:.6f}s"
            )
    if failures:
        print("KERNEL GATE FAIL", file=sys.stderr)
        for f in failures:
            print(f"  {f}", file=sys.stderr)
        return 1
    print("kernel gate ok: fused <= reference at every bucket",
          file=sys.stderr)
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--buckets", default="16,64,256",
                        help="comma-separated serving bucket sizes")
    parser.add_argument("--repeats", type=int, default=5,
                        help="timed dispatches per cell (best is kept)")
    parser.add_argument("--precisions", default="f32,bf16,int8",
                        help="comma-separated forest precisions")
    parser.add_argument("--out", default=None,
                        help="write the record here (default: stdout)")
    parser.add_argument("--check", default=None, metavar="RECORD",
                        help="gate an existing record instead of running: "
                        "fused f32 dispatch <= --slack x reference")
    parser.add_argument("--slack", type=float, default=1.0,
                        help="multiplier the fused dispatch may not exceed "
                        "over the reference pair in --check")
    parser.add_argument("--force-devices", type=int, default=None,
                        help="set --xla_force_host_platform_device_count "
                        "before JAX loads (no-op if JAX is already up)")
    args = parser.parse_args(argv)

    if args.check:
        with open(args.check) as fh:
            return check_record(json.load(fh), slack=args.slack)

    if args.force_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.force_devices}"
        ).strip()

    buckets = sorted(int(b) for b in args.buckets.split(",") if b.strip())
    precisions = [p.strip() for p in args.precisions.split(",") if p.strip()]
    record = run_kernel_bench(
        buckets, repeats=args.repeats, precisions=precisions
    )
    text = json.dumps(record)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        print(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
