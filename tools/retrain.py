"""Retrain driver — the producing half of the continuous-training loop.

Trains a fresh GBDT champion candidate (and, by default, the Flax MLP
challenger from `models/nn.py`) on a new pull of the training frame and
publishes BOTH through the model registry's ``canary`` channel — never
directly to ``latest``. Promotion into ``latest`` only ever happens through
the serving side's gate (``POST /admin/promote``, `serve/canary.py`), after
the candidate has shadow-scored real traffic.

Every published version carries the provenance an incident review needs:
the dataset fingerprint (md5 of the exact training matrix), the pipeline
config hash (`reliability.checkpoint.config_fingerprint`), train/test
metrics, and the per-feature training-distribution sketch
(`telemetry.drift.FeatureSketch`) the serve side scores live traffic
against at ``GET /drift``.

Usage:
    python tools/retrain.py [--store artifacts] [--rows 20000] [--seed 17]
        [--model-name gbdt] [--no-mlp] [--bootstrap] [--degrade]

``--bootstrap`` additionally promotes the candidate when the registry has no
champion yet (first deployment); ``--degrade`` label-shuffles the training
set — a deliberately broken candidate for exercising the promotion gate's
rejection path end to end (used by the canary-smoke CI job and the chaos
tests, never in production).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def retrain_candidate(
    store,
    *,
    rows: int = 20_000,
    seed: int = 17,
    model_name: str = "gbdt",
    registry_prefix: str = "registry",
    degrade: bool = False,
    bootstrap: bool = False,
    train_mlp: bool = True,
    n_estimators: int = 60,
    max_depth: int = 5,
    mlp_epochs: int = 12,
    drift_bins: int = 10,
) -> dict:
    """Train + publish one candidate generation; returns the publish report.

    Importable so tests and the CI canary-smoke job can run a miniature
    retrain (small ``rows``/``n_estimators``) against an in-memory store.
    """
    import jax.numpy as jnp

    from cobalt_smart_lender_ai_tpu.config import GBDTConfig, MLPConfig
    from cobalt_smart_lender_ai_tpu.data import (
        clean_raw_frame,
        engineer_features,
        prepare_cleaned_frame,
        synthetic_lendingclub_frame,
        train_test_split_hashed,
    )
    from cobalt_smart_lender_ai_tpu.data import schema
    from cobalt_smart_lender_ai_tpu.data.features import drop_training_leakage
    from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, MLPArtifact
    from cobalt_smart_lender_ai_tpu.io.model_registry import ModelRegistry
    from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTClassifier
    from cobalt_smart_lender_ai_tpu.ops.metrics import roc_auc
    from cobalt_smart_lender_ai_tpu.reliability.checkpoint import (
        config_fingerprint,
    )
    from cobalt_smart_lender_ai_tpu.telemetry.drift import FeatureSketch

    t0 = time.time()
    raw = synthetic_lendingclub_frame(n_rows=rows, seed=seed)
    cleaned, _ = clean_raw_frame(raw)
    tree_ff, _, _ = engineer_features(prepare_cleaned_frame(cleaned))
    ff = drop_training_leakage(tree_ff).select(schema.SERVING_FEATURES)
    X_train, X_test, y_train, y_test = train_test_split_hashed(ff.X, ff.y)
    X_train = np.asarray(X_train)
    y_np = np.asarray(y_train)
    if degrade:
        # Sever the feature/label relationship: the candidate trains on
        # shuffled labels, scores near-noise, and MUST be rejected by the
        # serve-side promotion gate. Test/CI hook only.
        y_np = np.random.default_rng(seed).permutation(y_np)
    spw = (len(y_np) - y_np.sum()) / max(y_np.sum(), 1.0)

    cfg = GBDTConfig(
        n_estimators=n_estimators,
        max_depth=max_depth,
        learning_rate=0.1,
        n_bins=64,
        scale_pos_weight=float(spw),
        seed=seed,
    )
    model = GBDTClassifier(cfg)
    model.fit(X_train, y_np)
    margin = model.predict_margin(jnp.asarray(X_test))
    test_auc = float(roc_auc(jnp.asarray(y_test, jnp.float32), margin))

    # Provenance: the dataset fingerprint is the md5 of the EXACT float32
    # training matrix + labels (what `DatasetPin` records for dataset blobs),
    # the config hash covers the training regime, and the feature sketch is
    # the drift baseline `GET /drift` compares live traffic against.
    data_md5 = hashlib.md5(
        np.ascontiguousarray(X_train, dtype=np.float32).tobytes()
        + np.ascontiguousarray(y_np, dtype=np.float32).tobytes()
    ).hexdigest()
    sketch = FeatureSketch.from_data(
        X_train, schema.SERVING_FEATURES, bins=drift_bins
    )
    provenance = {
        "dataset": f"synthetic_lendingclub_frame(rows={rows}, seed={seed})",
        "dataset_md5": data_md5,
        "config_hash": config_fingerprint(cfg, {"rows": rows, "seed": seed}),
        "degraded": bool(degrade),
        "feature_sketch": sketch.to_json(),
    }

    registry = ModelRegistry(store, prefix=registry_prefix)
    champion = GBDTArtifact(
        forest=model.forest,
        bin_spec=model.bin_spec,
        feature_names=tuple(schema.SERVING_FEATURES),
        config={
            k: getattr(cfg, k)
            for k in ("n_estimators", "max_depth", "learning_rate",
                      "n_bins", "scale_pos_weight", "seed")
        },
        metrics={
            "test_auc": round(test_auc, 4),
            "train_rows": int(X_train.shape[0]),
        },
    )
    mv = registry.publish(
        model_name, champion, provenance=provenance, channel="canary"
    )
    report = {
        "model": model_name,
        "version": mv.version,
        "key": mv.key,
        "channel": "canary",
        "test_auc": round(test_auc, 4),
        "parent_version": mv.parent_version,
        "dataset_md5": data_md5,
    }

    if bootstrap and registry.channel(model_name, "latest") is None:
        # First deployment: there is no champion to shadow against, so the
        # registry-level promote seeds `latest` directly. Every later
        # generation goes through the serve-side gate.
        registry.promote(model_name)
        report["channel"] = "latest"
        report["bootstrapped"] = True

    if train_mlp:
        from cobalt_smart_lender_ai_tpu.models.nn import MLPClassifier

        # PR 7's early-stopping budget finding: at the default 1e-3 the
        # small-epoch regime undershoots; 1e-2 converges in this budget.
        mlp_cfg = MLPConfig(
            hidden_sizes=(32, 16),
            learning_rate=1e-2,
            epochs=mlp_epochs,
            seed=seed,
        )
        mlp = MLPClassifier(mlp_cfg)
        mlp.fit(X_train, y_np)
        mlp_auc = float(
            roc_auc(
                jnp.asarray(y_test, jnp.float32),
                mlp.predict_logits(jnp.asarray(X_test, jnp.float32)),
            )
        )
        challenger = MLPArtifact(
            params=mlp.params,
            scaler_low=np.asarray(mlp.scaler.low),
            scaler_range=np.asarray(mlp.scaler.range_),
            feature_names=tuple(schema.SERVING_FEATURES),
            hidden_sizes=tuple(mlp_cfg.hidden_sizes),
            config={"learning_rate": mlp_cfg.learning_rate,
                    "epochs": mlp_cfg.epochs, "seed": seed},
            metrics={"test_auc": round(mlp_auc, 4)},
        )
        mlp_mv = registry.publish(
            f"{model_name}_mlp",
            challenger,
            provenance=provenance,
            channel="canary",
        )
        report["challenger"] = {
            "model": f"{model_name}_mlp",
            "version": mlp_mv.version,
            "key": mlp_mv.key,
            "test_auc": round(mlp_auc, 4),
        }

    report["wall_s"] = round(time.time() - t0, 1)
    return report


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--store", default="artifacts")
    ap.add_argument("--rows", type=int, default=20_000)
    ap.add_argument("--seed", type=int, default=17)
    ap.add_argument("--model-name", default="gbdt")
    ap.add_argument("--registry-prefix", default="registry")
    ap.add_argument("--n-estimators", type=int, default=60)
    ap.add_argument("--max-depth", type=int, default=5)
    ap.add_argument("--no-mlp", action="store_true",
                    help="skip the MLP challenger")
    ap.add_argument("--bootstrap", action="store_true",
                    help="promote to 'latest' when no champion exists yet")
    ap.add_argument("--degrade", action="store_true",
                    help="label-shuffle the training set (gate-rejection "
                    "fixture for tests/CI; never use in production)")
    ap.add_argument("--trace-out", default=None,
                    help="write the run's spans (+ device counter tracks) "
                    "as Perfetto JSON to this path")
    ap.add_argument("--ledger-out", default=None,
                    help="write a run ledger (env, durations, program cost "
                    "table) to this path; render with tools/obs_report.py")
    args = ap.parse_args(argv)

    from cobalt_smart_lender_ai_tpu.compilecache import bootstrap_compile_cache
    from cobalt_smart_lender_ai_tpu.io import ObjectStore

    bootstrap_compile_cache()
    ledger = None
    if args.ledger_out:
        from cobalt_smart_lender_ai_tpu.telemetry import (
            RunLedger,
            install_device_metrics,
            install_program_metrics,
        )

        install_program_metrics()
        install_device_metrics()
        ledger = RunLedger(
            "retrain",
            meta={
                "rows": args.rows,
                "seed": args.seed,
                "model_name": args.model_name,
                "degrade": bool(args.degrade),
            },
        )
    report = retrain_candidate(
        ObjectStore(args.store),
        rows=args.rows,
        seed=args.seed,
        model_name=args.model_name,
        registry_prefix=args.registry_prefix,
        degrade=args.degrade,
        bootstrap=args.bootstrap,
        train_mlp=not args.no_mlp,
        n_estimators=args.n_estimators,
        max_depth=args.max_depth,
    )
    if ledger is not None:
        ledger.add_stage("retrain", float(report.get("wall_s", 0.0)))
        ledger.set("retrain_report", report)
        ledger.write(args.ledger_out)
    if args.trace_out:
        from cobalt_smart_lender_ai_tpu.telemetry import (
            default_tracer,
            render_chrome_trace,
        )

        with open(args.trace_out, "w") as f:
            f.write(render_chrome_trace(default_tracer()))
    print(json.dumps(report))


if __name__ == "__main__":
    main()
