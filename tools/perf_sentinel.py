#!/usr/bin/env python
"""Perf sentinel: ingest benchmark artifacts into TREND.json and gate
regressions against the rolling baseline.

    # append artifacts (BENCH_*.json, run ledgers, bench harness records)
    python tools/perf_sentinel.py ingest BENCH_SERVE_r03.json runs/*.json

    # gate the newest row (CI): exit 0 pass, 1 regression, 3 no baseline
    python tools/perf_sentinel.py check

    # render the sparkline trend page (CI artifact)
    python tools/perf_sentinel.py render --out trend.html

All math lives in `cobalt_smart_lender_ai_tpu.telemetry.trend`; this is
argv plumbing plus exit codes. `check` prints its report as JSON so CI
logs carry the numbers, not just the verdict. Note argparse itself exits
2 on bad usage, which stays distinct from the gate codes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cobalt_smart_lender_ai_tpu.telemetry import trend as trendlib

EXIT_PASS = 0
EXIT_REGRESSION = 1
EXIT_MISSING_BASELINE = 3


def _cmd_ingest(args: argparse.Namespace) -> int:
    doc = trendlib.load_trend(args.trend)
    for path in args.files:
        with open(path) as fh:
            text = fh.read()
        # bench.py emits one record per line; tolerate multi-line files too.
        records = []
        try:
            records.append(json.loads(text))
        except json.JSONDecodeError:
            for line in text.splitlines():
                line = line.strip()
                if line.startswith("{"):
                    records.append(json.loads(line))
        for record in records:
            row = trendlib.append_row(
                doc,
                source=os.path.basename(path),
                metrics=trendlib.extract_metrics(record),
                stamp=None if args.no_stamp else time.time(),
            )
            print(
                f"ingested {path}: {len(row['metrics'])} metrics",
                file=sys.stderr,
            )
    trendlib.save_trend(doc, args.trend)
    return EXIT_PASS


def _cmd_check(args: argparse.Namespace) -> int:
    doc = trendlib.load_trend(args.trend)
    report = trendlib.check(doc)
    print(json.dumps(report, indent=1, sort_keys=True))
    if report["status"] == "regression":
        return EXIT_REGRESSION
    if report["status"] in ("missing_baseline", "empty"):
        return EXIT_MISSING_BASELINE
    if report["missing"] and args.strict_missing:
        return EXIT_MISSING_BASELINE
    return EXIT_PASS


def _cmd_render(args: argparse.Namespace) -> int:
    doc = trendlib.load_trend(args.trend)
    html = trendlib.render_trend_html(doc)
    with open(args.out, "w") as fh:
        fh.write(html)
    print(f"wrote {args.out}", file=sys.stderr)
    return EXIT_PASS


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="perf_sentinel", description=__doc__.splitlines()[0]
    )
    parser.add_argument(
        "--trend",
        default="TREND.json",
        help="trend ledger path (default: TREND.json)",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_ingest = sub.add_parser(
        "ingest", help="append benchmark artifacts as trend rows"
    )
    p_ingest.add_argument("files", nargs="+")
    p_ingest.add_argument(
        "--no-stamp",
        action="store_true",
        help="omit stamp_unix (deterministic seeding of committed history)",
    )
    p_ingest.set_defaults(fn=_cmd_ingest)

    p_check = sub.add_parser(
        "check", help="gate the newest row vs the rolling baseline"
    )
    p_check.add_argument(
        "--strict-missing",
        action="store_true",
        help="exit 3 when any gated metric lacks a baseline "
        "(default: warn only if at least one metric was checked)",
    )
    p_check.set_defaults(fn=_cmd_check)

    p_render = sub.add_parser("render", help="write the trend HTML page")
    p_render.add_argument("--out", default="trend.html")
    p_render.set_defaults(fn=_cmd_render)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
