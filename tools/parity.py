"""Protocol-scale parity head-to-head: our TPU GBDT vs a CPU sklearn oracle.

Runs the FULL reference training protocol twice on identical data
(`/root/reference/src/model_train_test/model_tree_train_test.py:111-179`):

    clean -> engineer -> leakage drop -> hashed 80/20 split
    -> RFE to exactly 20 features (step 1)
    -> 20-candidate x 3-fold randomized search
    -> refit best, test ROC-AUC

Side "ours" is this framework end to end (rfe_select + randomized_search on
the accelerator). Side "oracle" is scikit-learn's
`HistGradientBoostingClassifier` — the strongest gradient-boosting oracle
available offline (the reference's XGBoost is not in the image) — driven
through the SAME protocol on the SAME matrices and the SAME stratified fold
masks (`stratified_kfold_masks`, seed 22, exactly what `randomized_search`
uses internally). The oracle's search space maps the reference's XGBoost
space onto HGB analogs (n_estimators->max_iter, colsample_bytree->
max_features, gamma->l2_regularization; XGB's row `subsample` has no HGB
analog and is dropped). Oracle RFE mirrors the reference's
`RFE(estimator, step=1)` using permutation importance on a training
subsample (HGB exposes no impurity/gain importances).

Usage (two processes so the oracle never touches the accelerator):

    python tools/parity.py ours   --rows 130000 --out PARITY_ours.json
    JAX_PLATFORMS=cpu python tools/parity.py oracle --rows 130000 \
        --out PARITY_oracle.json
    python tools/parity.py merge PARITY_ours.json PARITY_oracle.json \
        --out PARITY.json

The merge gates ``ours.test_auc >= oracle.test_auc - 0.005`` — the round-3
parity criterion. tests/test_parity.py runs the same head-to-head slow-marked
and gates the committed PARITY.json on every CI run.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import time

import numpy as np

#: The reference's RandomizedSearchCV space mapped onto
#: HistGradientBoostingClassifier parameters (model_tree_train_test.py:139-146).
HGB_SPACE = {
    "max_iter": [100, 200, 300],
    "max_depth": [3, 5, 7, 9],
    "learning_rate": [0.01, 0.05, 0.1],
    "max_features": [0.5, 0.8, 1.0],
    "l2_regularization": [0.0, 1.0, 5.0],
}

PARITY_MARGIN = 0.005  # ours must be within this of the oracle (or better)


def build_matrices(n_rows: int, seed: int):
    """Shared data side of the protocol: synthetic raw frame -> clean ->
    engineer -> leakage drop -> hashed split. Deterministic in (n_rows, seed),
    so the two processes reconstruct bit-identical matrices."""
    import jax.numpy as jnp

    from cobalt_smart_lender_ai_tpu.data import (
        clean_raw_frame,
        engineer_features,
        prepare_cleaned_frame,
        synthetic_lendingclub_frame,
        train_test_split_hashed,
    )
    from cobalt_smart_lender_ai_tpu.data.features import drop_training_leakage

    raw = synthetic_lendingclub_frame(n_rows=n_rows, seed=seed)
    cleaned, _ = clean_raw_frame(raw)
    tree_ff, _, _ = engineer_features(prepare_cleaned_frame(cleaned))
    ff = drop_training_leakage(tree_ff)
    X_train, X_test, y_train, y_test = train_test_split_hashed(ff.X, ff.y)
    n_pos = float(jnp.sum(y_train))
    spw = (float(X_train.shape[0]) - n_pos) / max(n_pos, 1.0)
    return {
        "X_train": X_train,
        "X_test": X_test,
        "y_train": y_train,
        "y_test": y_test,
        "feature_names": list(ff.feature_names),
        "spw": spw,
    }


def run_ours(
    mats, chunk_trees: int | str | None = "auto", halving: bool = True
) -> dict:
    """This framework's protocol on the shared matrices — the L3 block of
    pipeline.run_pipeline, run directly so both sides consume the same
    arrays."""
    import jax
    import jax.numpy as jnp

    from cobalt_smart_lender_ai_tpu.config import (
        GBDTConfig,
        MeshConfig,
        RFEConfig,
        TuneConfig,
    )
    from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTClassifier
    from cobalt_smart_lender_ai_tpu.ops.metrics import roc_auc
    from cobalt_smart_lender_ai_tpu.parallel.mesh import make_mesh
    from cobalt_smart_lender_ai_tpu.parallel.rfe import rfe_select
    from cobalt_smart_lender_ai_tpu.parallel.tune import randomized_search

    t0 = time.time()
    mesh = make_mesh(MeshConfig())
    spw = mats["spw"]
    rfe_cfg = dataclasses.replace(RFEConfig(), scale_pos_weight=spw)
    rfe = rfe_select(mats["X_train"], mats["y_train"], rfe_cfg, mesh=mesh)
    t_rfe = time.time() - t0
    selected = [
        n for n, keep in zip(mats["feature_names"], rfe.support_) if keep
    ]

    sel_idx = jnp.asarray(np.flatnonzero(rfe.support_))
    Xtr = jnp.take(jnp.asarray(mats["X_train"]), sel_idx, axis=1)
    Xte = jnp.take(jnp.asarray(mats["X_test"]), sel_idx, axis=1)
    base = GBDTConfig().replace(scale_pos_weight=spw)
    tune = dataclasses.replace(
        TuneConfig(), chunk_trees=chunk_trees, halving_enabled=halving
    )
    t1 = time.time()
    search = randomized_search(Xtr, mats["y_train"], base, tune, mesh)
    t_search = time.time() - t1

    est: GBDTClassifier = search.best_estimator_
    margin = est.predict_margin(Xte)
    test_auc = float(
        roc_auc(jnp.asarray(mats["y_test"], jnp.float32), margin)
    )
    halving_report = search.cv_results_.get("halving")
    return {
        "side": "ours",
        "backend": jax.devices()[0].platform,
        "scheduler": "halving" if halving_report is not None else "exhaustive",
        "halving": None
        if halving_report is None
        else {
            k: halving_report[k]
            for k in ("eta", "budgets", "pruned_candidates", "survivors")
        },
        "selected_features": selected,
        "best_params": search.best_params_,
        "cv_auc": float(search.best_score_),
        "test_auc": test_auc,
        "seconds": {
            "rfe": round(t_rfe, 1),
            "search": round(t_search, 1),
            "total": round(time.time() - t0, 1),
        },
    }


def run_oracle(mats, seed: int = 22) -> dict:
    """The CPU oracle: sklearn HistGradientBoostingClassifier through the
    same RFE-20(step 1) -> 20x3 search -> test eval protocol on the same
    matrices and fold masks."""
    from sklearn.ensemble import HistGradientBoostingClassifier
    from sklearn.inspection import permutation_importance
    from sklearn.metrics import roc_auc_score

    from cobalt_smart_lender_ai_tpu.parallel.tune import (
        sample_candidates,
        stratified_kfold_masks,
    )

    X_train = np.asarray(mats["X_train"], dtype=np.float64)
    X_test = np.asarray(mats["X_test"], dtype=np.float64)
    y_train = np.asarray(mats["y_train"])
    y_test = np.asarray(mats["y_test"])
    spw = mats["spw"]
    sw = np.where(y_train == 1, spw, 1.0)  # scale_pos_weight analog
    F = X_train.shape[1]

    t0 = time.time()
    # --- RFE to exactly 20, step 1 (model_tree_train_test.py:111-121).
    # Selector matches our RFEConfig (50 rounds, depth 6, class-weighted);
    # ranking signal is permutation importance on a 10k training subsample
    # (HGB has no native importances).
    rng = np.random.default_rng(42)
    sub = rng.choice(len(y_train), size=min(10_000, len(y_train)), replace=False)
    mask = np.ones(F, dtype=bool)
    while mask.sum() > 20:
        sel = HistGradientBoostingClassifier(
            max_iter=50, max_depth=6, random_state=42
        )
        sel.fit(X_train[:, mask], y_train, sample_weight=sw)
        imp = permutation_importance(
            sel,
            X_train[sub][:, mask],
            y_train[sub],
            scoring="roc_auc",
            n_repeats=1,
            random_state=0,
        ).importances_mean
        drop_local = int(np.argsort(imp, kind="stable")[0])
        mask[np.flatnonzero(mask)[drop_local]] = False
    t_rfe = time.time() - t0
    selected = [n for n, keep in zip(mats["feature_names"], mask) if keep]

    Xtr = X_train[:, mask]
    Xte = X_test[:, mask]

    # --- 20-candidate x 3-fold randomized search on the SAME folds ours uses.
    candidates = sample_candidates(HGB_SPACE, 20, seed)
    val_masks = stratified_kfold_masks(y_train, 3, seed)
    t1 = time.time()
    scores = np.zeros((len(candidates), 3))
    for ci, cand in enumerate(candidates):
        for fi in range(3):
            val = val_masks[fi]
            m = HistGradientBoostingClassifier(random_state=78, **cand)
            m.fit(Xtr[~val], y_train[~val], sample_weight=sw[~val])
            p = m.predict_proba(Xtr[val])[:, 1]
            scores[ci, fi] = roc_auc_score(y_train[val], p)
    mean_scores = scores.mean(axis=1)
    best_i = int(mean_scores.argmax())
    best = dict(candidates[best_i])
    t_search = time.time() - t1

    final = HistGradientBoostingClassifier(random_state=78, **best)
    final.fit(Xtr, y_train, sample_weight=sw)
    test_auc = float(roc_auc_score(y_test, final.predict_proba(Xte)[:, 1]))
    return {
        "side": "oracle",
        "backend": "cpu/sklearn-HistGradientBoostingClassifier",
        "selected_features": selected,
        "best_params": best,
        "cv_auc": float(mean_scores[best_i]),
        "test_auc": test_auc,
        "seconds": {
            "rfe": round(t_rfe, 1),
            "search": round(t_search, 1),
            "total": round(time.time() - t0, 1),
        },
    }


def run_head_to_head(
    n_rows: int,
    seed: int = 11,
    chunk_trees: int | str | None = "auto",
    halving: bool = True,
):
    """Both sides in one process (used by the slow-marked test, where the
    conftest pins everything to the virtual CPU mesh)."""
    mats = build_matrices(n_rows, seed)
    ours = run_ours(mats, chunk_trees=chunk_trees, halving=halving)
    oracle = run_oracle(mats)
    return merge(ours, oracle, n_rows=n_rows, seed=seed)


def merge(ours: dict, oracle: dict, **meta) -> dict:
    gap = ours["test_auc"] - oracle["test_auc"]
    return {
        "protocol": "clean->engineer->RFE-20(step1)->search(20x3)->test eval "
        "(model_tree_train_test.py:111-179)",
        **meta,
        "ours": ours,
        "oracle": oracle,
        "auc_gap_ours_minus_oracle": round(gap, 5),
        "parity_margin": PARITY_MARGIN,
        "parity_ok": bool(gap >= -PARITY_MARGIN),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("side", choices=["ours", "oracle", "both", "merge"])
    ap.add_argument("inputs", nargs="*", help="json files for merge")
    ap.add_argument("--rows", type=int, default=130_000)
    ap.add_argument("--seed", type=int, default=11)
    # Dispatch budget: "auto" derives per-bucket chunks from the workload
    # shape (parallel/budget.py — deliberately conservative after a 70s
    # dispatch was observed; 50-tree chunks crashed the tunneled TPU worker
    # once, and 12 was round 3's safe hardcode). An int pins it.
    ap.add_argument(
        "--chunk-trees",
        default="auto",
        type=lambda s: s if s == "auto" else (None if s == "none" else int(s)),
    )
    ap.add_argument(
        "--no-halving",
        action="store_true",
        help="exhaustive search scheduler (bit-identical to pre-halving "
        "rounds) instead of successive halving",
    )
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--trace-out",
        default=None,
        help="write the run's spans (+ device counter tracks) as Perfetto "
        "JSON to this path",
    )
    ap.add_argument(
        "--ledger-out",
        default=None,
        help="write a run ledger (env, side timings, program cost table) "
        "to this path; render with tools/obs_report.py",
    )
    args = ap.parse_args(argv)

    ledger = None
    if args.ledger_out:
        from cobalt_smart_lender_ai_tpu.telemetry import (
            RunLedger,
            install_device_metrics,
            install_program_metrics,
        )

        install_program_metrics()
        install_device_metrics()
        ledger = RunLedger(
            "parity",
            meta={
                "side": args.side,
                "rows": args.rows,
                "seed": args.seed,
                "halving": not args.no_halving,
            },
        )

    if args.side in ("ours", "both"):
        from cobalt_smart_lender_ai_tpu.compilecache import (
            bootstrap_compile_cache,
        )

        bootstrap_compile_cache()
    if args.side == "merge":
        loaded = [json.load(open(p)) for p in args.inputs]
        by_side = {d.get("side"): d for d in loaded}
        if set(by_side) != {"ours", "oracle"}:
            raise SystemExit(
                f"merge needs one 'ours' and one 'oracle' file, got sides "
                f"{[d.get('side') for d in loaded]}"
            )
        meta = {}
        for k in ("n_rows", "seed"):
            vals = {d.get(k) for d in loaded}
            if len(vals) != 1 or None in vals:
                raise SystemExit(
                    f"sides disagree on {k} ({vals}) — they did not run on "
                    "identical matrices; re-run with matching --rows/--seed"
                )
            meta[k] = vals.pop()
        result = merge(by_side["ours"], by_side["oracle"], **meta)
    elif args.side == "both":
        result = run_head_to_head(
            args.rows, args.seed, args.chunk_trees, halving=not args.no_halving
        )
    else:
        mats = build_matrices(args.rows, args.seed)
        result = (
            run_ours(
                mats, chunk_trees=args.chunk_trees, halving=not args.no_halving
            )
            if args.side == "ours"
            else run_oracle(mats)
        )
        result.update(n_rows=args.rows, seed=args.seed)
    print(json.dumps(result, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
    if ledger is not None:
        for side in ("ours", "oracle"):
            block = result.get(side) if args.side in ("both", "merge") else (
                result if result.get("side") == side else None
            )
            if isinstance(block, dict):
                for stage, secs in (block.get("seconds") or {}).items():
                    if stage != "total":
                        ledger.add_stage(f"{side}.{stage}", float(secs))
        ledger.set("parity", result)
        ledger.write(args.ledger_out)
    if args.trace_out:
        from cobalt_smart_lender_ai_tpu.telemetry import (
            default_tracer,
            render_chrome_trace,
        )

        with open(args.trace_out, "w") as f:
            f.write(render_chrome_trace(default_tracer()))
    return result


if __name__ == "__main__":
    main()
