"""Attribute the depth-9 search bucket's ~1.27 s/tree by ablation.

VERDICT r4 weakness #1: the 33-job depth-9 bucket at 130k x 20 x 255 bins
costs ~1.27 s/tree (with sibling subtraction) and the round-4 calibration
notes could not attribute ~1 s of it — the node-one-hot contraction alone
measured near MXU peak. `jax.profiler` device traces are unreliable over
this environment's tunneled backend, so this tool isolates each stage of
the per-level histogram pass by timing purpose-built variants of the SAME
block-scan structure (`ops/histogram.py _hist_matmul`) at the real bucket
shape:

    full        the real vmapped fit (fit_binned_resumable, hist_subtract)
    hist        histogram passes only (9 levels/tree, fixed node maps; no
                split eval / routing) — the budget model's A+B terms
    dot         contraction only: bin-one-hot AND rhs precomputed outside
                the timed scan (reads them from HBM instead of building)
    dot_bf16    `dot` with the rhs cast to bf16 — isolates any f32-operand
                MXU rate penalty
    onehot      bin-one-hot build + a trivial width-1 contraction — the
                one-hot construction stream without the real dot
    rhs         node-one-hot x (g|h|w) rhs build + trivial contraction
    route       split-eval chain (cumsum/argmax) + select_columns routing
                on precomputed histograms — everything that is NOT the
                histogram pass

Each variant is jitted once, warmed, and timed best-of-2 with the result
fetched as a scalar (block_until_ready lies over the tunnel). Timed regions
are sized >= ~10 s so the seconds-scale RPC jitter stays small. Prints one
JSON line per variant plus a derived attribution summary.

Usage:  python tools/ablate_d9.py [--rows 130000] [--jobs 33] [--trees 8]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp

# Real bucket shape: depth-9 candidates of the reference search space at the
# 130k-row parity scale (PARITY.json), 20 RFE-selected features, 255 bins.
DEPTH = 9
N_BINS = 255
N_FEATS = 20
ROW_BLOCK = 4096

# Sibling-subtraction contraction widths per level (left children only at
# parent width; level 0 direct) — models/gbdt.py fit_binned_resumable.
WIDTHS = [1] + [2 ** (lvl - 1) for lvl in range(1, DEPTH)]


def timed(fn, *args, reps: int = 2) -> float:
    """Best-of-`reps` wall seconds; forces execution via a scalar fetch."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.time()
        out = fn(*args)
        float(np.asarray(jax.tree.leaves(out)[0]).ravel()[0])
        best = min(best, time.time() - t0)
    return best


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=130_000)
    ap.add_argument("--jobs", type=int, default=33)
    ap.add_argument("--trees", type=int, default=8)
    ap.add_argument(
        "--only", default=None,
        help="comma-separated variant names to run (default: all)",
    )
    args = ap.parse_args()
    from cobalt_smart_lender_ai_tpu.compilecache import bootstrap_compile_cache

    bootstrap_compile_cache()

    N, J, T = args.rows, args.jobs, args.trees
    F, B = N_FEATS, N_BINS
    rng = np.random.default_rng(0)
    bins = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8))
    ghw = jnp.asarray(rng.normal(size=(J, 3, N)).astype(np.float32))
    # Fixed per-level node maps (uniform over the level's width): cost-faithful
    # stand-ins for the data-dependent routing of a real fit.
    nodes = [
        jnp.asarray(rng.integers(0, k, size=(N,), dtype=np.int32)) for k in WIDTHS
    ]

    n_blocks = -(-N // ROW_BLOCK)
    pad = n_blocks * ROW_BLOCK - N

    def _blocked(v, fill=0):
        v = jnp.pad(v, [(0, pad)] + [(0, 0)] * (v.ndim - 1)) if pad else v
        return v.reshape((n_blocks, ROW_BLOCK) + v.shape[1:])

    bins_b = _blocked(bins)  # (nb, R, F)
    iota = jnp.arange(B, dtype=jnp.int32)

    results: dict[str, float] = {}
    known = {"full", "hist", "dot", "dot_bf16", "onehot", "rhs", "route"}
    want = set(args.only.split(",")) if args.only else None
    if want is not None and not want <= known:
        ap.error(f"unknown variant(s) {sorted(want - known)}; known: {sorted(known)}")

    def record(name: str, seconds: float, per_tree_jobs: float) -> None:
        results[name] = seconds
        print(json.dumps({
            "variant": name,
            "seconds": round(seconds, 3),
            "s_per_tree": round(per_tree_jobs, 4),
            "shape": f"{N}x{F}x{B} J={J} T={T} depth={DEPTH}",
        }), flush=True)

    # ---- full: the real fit ------------------------------------------------
    if want is None or "full" in want:
        from cobalt_smart_lender_ai_tpu.models.gbdt import (
            GBDTHyperparams,
            fit_binned,
        )
        from cobalt_smart_lender_ai_tpu.config import GBDTConfig

        hp = GBDTHyperparams.from_config(
            GBDTConfig(n_estimators=T, max_depth=DEPTH, n_bins=B)
        )
        hps = jax.tree.map(lambda a: jnp.broadcast_to(a, (J,) + a.shape), hp)
        y = jnp.asarray((rng.random(N) < 0.2).astype(np.int32))
        sw = jnp.ones((N,), jnp.float32)
        fm = jnp.ones((F,), bool)
        keys = jax.random.split(jax.random.PRNGKey(0), J)

        @jax.jit
        def full(hps, keys):
            def one(hp_j, key):
                f = fit_binned(
                    bins, y, sw, fm, hp_j, key,
                    n_trees_cap=T, depth_cap=DEPTH, n_bins=B,
                )
                return f.leaf_value.sum()

            return jax.vmap(one)(hps, keys)

        t = timed(full, hps, keys)
        record("full", t, t / T)

    # ---- shared scan-variant builder --------------------------------------
    # Every variant runs T sequential "trees" x 9 levels of block-scans with
    # a scalar carried across trees (prevents cross-tree batching), vmapped
    # over J jobs exactly like the real fan-out (bins shared, ghw per job).
    def run_levels(tag, level_fn, extras=(), per_level_extras=None, jobs=J):
        """level_fn(carry_scalar, level_idx, ghw_j, *extras) -> scalar."""

        @jax.jit
        def run(ghw_all, *extra_args):
            def one_job(ghw_j):
                def tree_step(carry, _):
                    s = carry
                    for lvl in range(DEPTH):
                        ex = (
                            tuple(e[lvl] for e in per_level_extras)
                            if per_level_extras
                            else ()
                        )
                        s = level_fn(s, lvl, ghw_j, *extra_args, *ex)
                    return s, None

                out, _ = jax.lax.scan(
                    tree_step, jnp.float32(0.0), jnp.arange(T)
                )
                return out

            return jax.vmap(one_job)(ghw_all)

        t = timed(run, ghw[:jobs], *extras)
        record(tag, t, t / T)

    # ---- hist: the 9 real histogram passes per tree ------------------------
    if want is None or "hist" in want:
        from cobalt_smart_lender_ai_tpu.ops.histogram import gradient_histogram

        def hist_level(s, lvl, ghw_j):
            g = ghw_j[0] * (1.0 + 1e-12 * s)  # serialize trees via the carry
            h = gradient_histogram(
                bins, nodes[lvl], g, ghw_j[1], ghw_j[2],
                n_nodes=WIDTHS[lvl], n_bins=B, row_block=ROW_BLOCK,
            )
            return s + h.sum()

        run_levels("hist", hist_level)

    # ---- dot / dot_bf16: contraction with both operands precomputed --------
    oh_pre = (bins_b[..., None].astype(jnp.int32) == iota).astype(jnp.bfloat16)
    # (nb, R, F, B) bf16 — ~1.3GB at 130k; read from HBM by the timed scan.

    def make_dot(rhs_dtype):
        def dot_level(s, lvl, ghw_j, oh_all):
            K = WIDTHS[lvl]
            oh_node = jax.nn.one_hot(nodes[lvl], K, dtype=jnp.float32)
            rhs = (oh_node[:, None, :] * ghw_j.T[:, :, None]).reshape(N, 3 * K)
            rhs = (rhs * (1.0 + 1e-12 * s)).astype(rhs_dtype)
            rhs_b = _blocked(rhs)

            def body(acc, xs):
                oh_blk, r_blk = xs
                return acc + jnp.einsum(
                    "rfb,rk->fbk", oh_blk, r_blk,
                    preferred_element_type=jnp.float32,
                ), None

            acc, _ = jax.lax.scan(
                body,
                jnp.zeros((F, B, 3 * K), jnp.float32),
                (oh_all, rhs_b),
            )
            return s + acc.sum()

        return dot_level

    if want is None or "dot" in want:
        run_levels("dot", make_dot(jnp.float32), extras=(oh_pre,))
    if want is None or "dot_bf16" in want:
        run_levels("dot_bf16", make_dot(jnp.bfloat16), extras=(oh_pre,))

    # ---- onehot: build the bin one-hot, contract to width 1 ----------------
    if want is None or "onehot" in want:
        ones_r = jnp.ones((ROW_BLOCK, 1), jnp.bfloat16)

        def onehot_level(s, lvl, ghw_j):
            scale = (ghw_j[0, 0] * 1e-12 + 1.0).astype(jnp.bfloat16)

            def body(acc, bblk):
                oh = (
                    bblk[..., None].astype(jnp.int32) == iota
                ).astype(jnp.bfloat16) * scale
                return acc + jnp.einsum(
                    "rfb,rk->fbk", oh, ones_r,
                    preferred_element_type=jnp.float32,
                ), None

            acc, _ = jax.lax.scan(
                body, jnp.zeros((F, B, 1), jnp.float32), bins_b
            )
            return s + acc.sum() * (1.0 + 1e-12 * s)

        run_levels("onehot", onehot_level)

    # ---- rhs: build the node-one-hot rhs PER BLOCK, contract to width 1 ----
    if want is None or "rhs" in want:
        ones_1 = jnp.ones((ROW_BLOCK, 1), jnp.float32)
        nodes_b = [_blocked(nd) for nd in nodes]  # (nb, R) per level

        def rhs_level(s, lvl, ghw_j):
            K = WIDTHS[lvl]
            ghw_b = _blocked(ghw_j.T * (1.0 + 1e-12 * s))  # (nb, R, 3)

            def body(acc, xs):
                nblk, gblk = xs
                oh_node = jax.nn.one_hot(nblk, K, dtype=jnp.float32)
                rhs = (oh_node[:, None, :] * gblk[:, :, None]).reshape(
                    ROW_BLOCK, 3 * K
                )
                return acc + jnp.einsum(
                    "rk,rc->kc", rhs, ones_1,
                    preferred_element_type=jnp.float32,
                ), None

            acc, _ = jax.lax.scan(
                body, jnp.zeros((3 * K, 1), jnp.float32), (nodes_b[lvl], ghw_b)
            )
            return s + acc.sum()

        run_levels("rhs", rhs_level)

    # ---- route: split-eval chain + routing on precomputed histograms ------
    if want is None or "route" in want:
        from cobalt_smart_lender_ai_tpu.ops.histogram import select_columns

        hists = [
            jnp.asarray(
                rng.normal(size=(2 ** lvl, F, B, 2)).astype(np.float32)
            )
            for lvl in range(DEPTH)
        ]

        def route_level(s, lvl, ghw_j, hist_l):
            n_nodes = 2 ** lvl
            hist = hist_l * (1.0 + 1e-12 * s)
            miss = hist[:, :, 0, :]
            cum = jnp.cumsum(hist[:, :, 1:, :], axis=2)
            tot = cum[:, :, -1, :] + miss
            GL = cum[..., :-1, 0]
            HL = cum[..., :-1, 1]
            Gt = tot[..., 0][:, :, None]
            Ht = tot[..., 1][:, :, None]
            gain = GL * GL / (HL + 1.0) + (Gt - GL) ** 2 / (Ht - HL + 1.0)
            flat = gain.reshape(n_nodes, -1)
            best = jnp.argmax(flat, axis=1)
            bf = (best // (B - 2)).astype(jnp.int32)
            bt = (best % (B - 2)).astype(jnp.int32) + 1
            node = nodes[lvl]
            b_row = select_columns(bins, bf[node], exact_max=B).astype(jnp.int32)
            go_left = b_row <= bt[node]
            return s + go_left.sum().astype(jnp.float32)

        run_levels("route", route_level, per_level_extras=(hists,))

    # ---- attribution summary ----------------------------------------------
    if results:
        print(json.dumps({
            "summary": {k: round(v / T, 4) for k, v in results.items()},
            "note": (
                "s/tree per variant; hist ~ A+B budget terms; "
                "dot = contraction with operands precomputed; "
                "onehot/rhs = operand builds with trivial dots; "
                "route = split eval + routing (non-histogram)"
            ),
        }, indent=None), flush=True)


if __name__ == "__main__":
    main()
