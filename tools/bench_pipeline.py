#!/usr/bin/env python
"""Host-vs-device ingest bench: pandas L1/L2 against the jitted columnar
pipeline, rows/s at several frame sizes.

Both sides run the full raw-frame -> binned-feature-matrix flow:

- ``host``:   `clean_raw_frame` -> `prepare_cleaned_frame` ->
              `engineer_features` -> `ops.binning` (the pandas path the
              device pipeline must match bit-for-bit).
- ``device``: `tokenize_raw_frame` (the stringy host frontier) ->
              `run_device_ingest` (jitted ingest.* programs, sharded with
              ``--shards``).

Each side gets one untimed warmup pass per size to pay the compiles, then
the best of ``--repeats`` timed passes is kept (BENCH_BULK precedent).
The record carries ``host_cpu_cores`` because the honest comparison point
matters: a single-core container understates the pandas side less than a
big host would, and the CPU "devices" here are cores of the same chip —
on real TPU hardware the device side does not contend with the frontier.

    python tools/bench_pipeline.py --out BENCH_PIPE_r01.json
    python tools/perf_sentinel.py ingest BENCH_PIPE_r01.json --no-stamp
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from datetime import datetime

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: Frozen so reruns on other hosts benchmark the same frames.
TODAY = datetime(2026, 8, 1)


def _platform_tag() -> str:
    import jax

    return jax.devices()[0].platform


def _host_cpu_cores() -> int:
    if hasattr(os, "sched_getaffinity"):
        return len(os.sched_getaffinity(0))
    return os.cpu_count() or 1


def _time_best(fn, repeats: int) -> float:
    fn()  # warmup: compiles, caches, page-in
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def run_pipeline_bench(
    sizes: list[int], *, repeats: int, shards: int, n_bins: int
) -> dict:
    import jax

    from cobalt_smart_lender_ai_tpu.data.clean import clean_raw_frame
    from cobalt_smart_lender_ai_tpu.data.device_pipeline import (
        run_device_ingest,
        tokenize_raw_frame,
    )
    from cobalt_smart_lender_ai_tpu.data.features import (
        engineer_features,
        prepare_cleaned_frame,
    )
    from cobalt_smart_lender_ai_tpu.data.synthetic import (
        synthetic_lendingclub_frame,
    )
    from cobalt_smart_lender_ai_tpu.ops import binning
    from cobalt_smart_lender_ai_tpu.parallel.partitioner import (
        make_partitioner,
    )

    results: dict[str, dict] = {}
    for n in sizes:
        raw = synthetic_lendingclub_frame(n, seed=7)

        def host_pass():
            cleaned, _ = clean_raw_frame(raw.copy())
            prepared = prepare_cleaned_frame(cleaned, today=TODAY)
            tree, _, _ = engineer_features(prepared)
            spec = binning.compute_bin_edges(tree.X, n_bins=n_bins)
            jax.block_until_ready(binning.transform(spec, tree.X))

        def device_pass():
            tok = tokenize_raw_frame(raw.copy(), today=TODAY)
            res = run_device_ingest(
                tok,
                partitioner=make_partitioner(shards, kind_prefix="ingest"),
                n_bins=n_bins,
            )
            jax.block_until_ready(res.bins)

        print(f"[bench] size={n}: host path...", file=sys.stderr)
        host_s = _time_best(host_pass, repeats)
        print(f"[bench] size={n}: device path...", file=sys.stderr)
        dev_s = _time_best(device_pass, repeats)
        results[f"rows_{n}"] = {
            "host": {
                "rows_per_s": round(n / host_s, 1),
                "best_pass_ms": round(host_s * 1e3, 3),
            },
            "device": {
                "rows_per_s": round(n / dev_s, 1),
                "best_pass_ms": round(dev_s * 1e3, 3),
                "shards": shards,
            },
            "speedup": round(host_s / dev_s, 2),
        }
        print(
            f"[bench] size={n}: host {n / host_s:,.0f} rows/s, "
            f"device {n / dev_s:,.0f} rows/s "
            f"({host_s / dev_s:.2f}x)",
            file=sys.stderr,
        )

    record = {
        "bench": "pipeline_ingest",
        "n_bins": n_bins,
        "repeats": repeats,
        "platform": _platform_tag(),
        "devices": len(jax.devices()),
        "host_cpu_cores": _host_cpu_cores(),
        "results": results,
    }
    largest = f"rows_{max(sizes)}"
    record["speedup_largest"] = results[largest]["speedup"]
    return record


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--sizes", default="4000,16000,48000",
                        help="comma-separated synthetic frame sizes")
    parser.add_argument("--repeats", type=int, default=3,
                        help="timed passes per side (best is kept)")
    parser.add_argument("--shards", type=int, default=-1,
                        help="device-side ingest shards (-1 = all devices)")
    parser.add_argument("--n-bins", type=int, default=255)
    parser.add_argument("--out", default=None,
                        help="write the record here (default: stdout)")
    parser.add_argument("--force-devices", type=int, default=None,
                        help="set --xla_force_host_platform_device_count "
                        "before JAX loads (no-op if JAX is already up)")
    args = parser.parse_args(argv)

    if args.force_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.force_devices}"
        ).strip()

    sizes = sorted(int(s) for s in args.sizes.split(",") if s.strip())
    record = run_pipeline_bench(
        sizes,
        repeats=args.repeats,
        shards=args.shards,
        n_bins=args.n_bins,
    )
    text = json.dumps(record)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        print(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
