"""Registry garbage collection — keep-last-K sweep over published models.

Every retrain generation mints an immutable `models/<name>/v<N>` artifact;
nothing ever deletes one on the hot path (channel pointers must never
dangle). This tool is the offline sweep: for each registered model it keeps
every version a channel (``latest``/``canary``/``previous``) still points at
plus the newest ``--keep-last`` versions, and deletes the rest — record,
artifact npz, content pin, and features sidecar.

Dry-run by default: prints the would-delete report as JSON and touches
nothing until ``--apply`` is passed.

Usage:
    python tools/registry_gc.py [--store artifacts] [--keep-last 2] [--apply]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--store", default="artifacts")
    ap.add_argument("--registry-prefix", default="registry")
    ap.add_argument("--keep-last", type=int, default=2,
                    help="newest versions to keep per model, beyond whatever "
                    "the channels pin")
    ap.add_argument("--apply", action="store_true",
                    help="actually delete (default is a dry-run report)")
    args = ap.parse_args(argv)

    from cobalt_smart_lender_ai_tpu.io import ObjectStore
    from cobalt_smart_lender_ai_tpu.io.model_registry import ModelRegistry

    registry = ModelRegistry(
        ObjectStore(args.store), prefix=args.registry_prefix
    )
    report = registry.gc(keep_last=args.keep_last, dry_run=not args.apply)
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
