"""Measure real-fit s/tree at several workload shapes (budget recalibration).

Used to (re)fit `parallel/budget.py`'s cost-model constants from measured
points — round 5 rewired the routing (gather-free) and the fan-out
contraction, making the round-4 calibration points obsolete. Each probe jits
the REAL `fit_binned` under the fan-out's vmap at the given shape, warms it,
and reports best-of-2 s/tree (scalar-fetch timing; block_until_ready lies
over the tunnel).

Usage: python tools/probe_shapes.py [--probes d9j33,d5j33,d9j8,d7j12]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

PROBES = {
    # name: (rows, feats, bins, jobs, trees, depth)
    "d9j33": (130_000, 20, 255, 33, 8, 9),
    "d5j33": (130_000, 20, 255, 33, 8, 5),
    "d9j8": (130_000, 20, 255, 8, 8, 9),
    "d7j12": (130_000, 20, 255, 12, 12, 7),
    "d3full": (2_300_000, 100, 64, 1, 24, 3),  # the bench.py single-fit shape
}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--probes", default="d9j33,d5j33,d9j8")
    args = ap.parse_args()
    import jax
    import jax.numpy as jnp

    from cobalt_smart_lender_ai_tpu.config import GBDTConfig
    from cobalt_smart_lender_ai_tpu.compilecache import bootstrap_compile_cache
    from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTHyperparams, fit_binned
    from cobalt_smart_lender_ai_tpu.parallel.budget import est_tree_seconds

    bootstrap_compile_cache()
    for name in args.probes.split(","):
        N, F, B, J, T, D = PROBES[name]
        rng = np.random.default_rng(0)
        bins = jnp.asarray(rng.integers(0, B, size=(N, F), dtype=np.uint8))
        y = jnp.asarray((rng.random(N) < 0.2).astype(np.int32))
        sw = jnp.ones((N,), jnp.float32)
        fm = jnp.ones((F,), bool)
        hp = GBDTHyperparams.from_config(
            GBDTConfig(n_estimators=T, max_depth=D, n_bins=B)
        )
        hps = jax.tree.map(lambda a: jnp.broadcast_to(a, (J,) + a.shape), hp)
        keys = jax.random.split(jax.random.PRNGKey(0), J)

        @jax.jit
        def run(hps, keys):
            def one(hp_j, key):
                f = fit_binned(
                    bins, y, sw, fm, hp_j, key,
                    n_trees_cap=T, depth_cap=D, n_bins=B,
                )
                return f.leaf_value.sum()

            return jax.vmap(one)(hps, keys)

        out = run(hps, keys)
        float(np.asarray(out)[0])  # warm + force
        best = float("inf")
        for _ in range(2):
            t0 = time.time()
            out = run(hps, keys)
            float(np.asarray(out)[0])
            best = min(best, time.time() - t0)
        model = est_tree_seconds(N, F, B, D, J, hist_subtract=True)
        print(json.dumps({
            "probe": name,
            "shape": f"{N}x{F}x{B} J={J} T={T} depth={D}",
            "s_per_tree": round(best / T, 4),
            "model_s_per_tree": round(model, 4),
            "measured_over_model": round(best / T / max(model, 1e-12), 3),
        }), flush=True)


if __name__ == "__main__":
    main()
