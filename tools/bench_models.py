"""Measured throughput + quality for the neural model families.

Promotes the docstring numbers (models/train_loop.py) to driver-visible
evidence: one JSON artifact (MODELS_BENCH.json) with measured training
throughput and held-out AUC for MLP, FT-Transformer, and TabNet at a stated
scale on the current backend.

Method: the training loop is a host loop over one jitted epoch. A cold
full-length fit runs first — that wall time (compile included) is what a
user experiences, and it warms the per-epoch program — then a short and a
long fit run fully warm, and steady-state throughput is (rows x
extra_epochs) / (t_long - t_short). Timing trap on this backend: wall times are
taken after fetching a scalar from the outputs (block_until_ready does not
block over the tunnel; see .claude/skills/verify/SKILL.md).

Usage: python tools/bench_models.py [--rows 262144] [--out MODELS_BENCH.json]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _ready(model, Xte_args) -> float:
    """Force execution: fetch a scalar derived from predictions."""
    p = model.predict_proba(*Xte_args)
    return float(np.asarray(p).sum())


def bench_family(make_model, fit_args, test_args, y_test, short=2, long=12):
    from sklearn.metrics import roc_auc_score

    rows = int(np.asarray(fit_args[-1]).shape[0])
    # Cold full fit first: this is what a user experiences (compile
    # included) AND the warmup — the per-epoch train step compiles once per
    # process for these shapes, so without it the SHORT timed fit would eat
    # the whole compile, t_long - t_short would go negative (the long fit
    # runs cached), and the steady-state division would explode.
    t0 = time.time()
    m = make_model(long)
    m.fit(*fit_args)
    _ready(m, test_args)
    t_cold_full = time.time() - t0

    # Each timed fit runs twice and the MIN wall is kept: the tunneled
    # backend's per-RPC latency is additive noise measured in seconds
    # (single-run steady numbers swung 300x between invocations), and min
    # over repeats filters it the way microbenchmark best-of-N does.
    def timed_fit(epochs):
        walls = []
        for _ in range(2):
            t0 = time.time()
            m = make_model(epochs)
            m.fit(*fit_args)
            _ready(m, test_args)
            walls.append(time.time() - t0)
        return min(walls), len(m.history["loss"]), m

    t_short, e_short, _ = timed_fit(short)
    t_long, e_long, m = timed_fit(long)  # early stopping may trim e_long

    # Both timed fits run fully warm, so the epoch delta divides cleanly;
    # divide by the epochs actually run, not the configured count. The
    # fallback fires in two distinguishable situations: early stopping
    # clamped both fits to the same epoch count (a property of the model /
    # data), or timing noise made the long fit no slower than the short one
    # (a degraded measurement). Either way the reported number includes
    # per-fit fixed overheads — a LOWER BOUND on steady state, flagged as
    # such rather than silently reported as steady.
    # Delta noise floor: per-fit walls on the tunneled backend carry
    # seconds of RPC jitter even after best-of-2, so an epoch delta under
    # this is not a measurement — a 1.4s delta once yielded a "steady"
    # 4.8M rows/s for the MLP. Below the floor, report the whole-fit
    # lower bound instead (overheads included, flagged).
    NOISE_FLOOR_S = 5.0
    measurement = "steady"
    if e_long > e_short and t_long - t_short >= NOISE_FLOOR_S:
        steady = rows * (e_long - e_short) / (t_long - t_short)
    else:
        steady = rows * e_long / max(t_long, 1e-9)
        if e_long <= e_short:
            measurement = "lower_bound_early_stop_clamped"
        elif t_long <= t_short:
            measurement = "lower_bound_timing_noise"
        else:
            measurement = "lower_bound_delta_below_noise_floor"
    p = np.asarray(m.predict_proba(*test_args)[:, 1])
    auc = float(roc_auc_score(np.asarray(y_test), p))
    return {
        "rows": rows,
        "epochs_run": [e_short, e_long],
        # Same long fit cold vs warm: their difference IS the compile cost.
        "fit_seconds_incl_compile": round(t_cold_full, 1),
        "fit_seconds_warm": round(t_long, 1),
        "steady_rows_per_sec": round(steady),
        "throughput_measurement": measurement,
        "test_auc": round(auc, 4),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=262_144)
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    import jax

    from cobalt_smart_lender_ai_tpu.compilecache import bootstrap_compile_cache

    bootstrap_compile_cache()

    from cobalt_smart_lender_ai_tpu.config import (
        FTTransformerConfig,
        MLPConfig,
    )
    from cobalt_smart_lender_ai_tpu.data import (
        clean_raw_frame,
        engineer_features,
        prepare_cleaned_frame,
        synthetic_lendingclub_frame,
        train_test_split_hashed,
    )
    from cobalt_smart_lender_ai_tpu.data.features import drop_training_leakage
    from cobalt_smart_lender_ai_tpu.models.ft_transformer import (
        FTTransformerClassifier,
    )
    from cobalt_smart_lender_ai_tpu.models.nn import MLPClassifier
    from cobalt_smart_lender_ai_tpu.models.tabnet import (
        TabNetClassifier,
        TabNetConfig,
    )

    # The NN feature frame (numeric + label-encoded categoricals) is what the
    # reference's Keras path consumes (feature_engineering.py nn frame).
    raw = synthetic_lendingclub_frame(n_rows=args.rows, seed=13)
    cleaned, _ = clean_raw_frame(raw)
    _, nn_ff, plan = engineer_features(prepare_cleaned_frame(cleaned))
    # The reference's NN notebook drops the trainer leakage block before
    # fitting (04_model_training.ipynb c32); without this the nn frame still
    # carries out_prncp / total_pymnt etc. and AUC is a meaningless ~0.999.
    nn_ff = drop_training_leakage(nn_ff)
    Xtr, Xte, ytr, yte = train_test_split_hashed(nn_ff.X, nn_ff.y)
    Xtr_n, Xte_n = np.asarray(Xtr), np.asarray(Xte)
    ytr_n, yte_n = np.asarray(ytr), np.asarray(yte)
    # NaNs to 0 after the frames' imputation indicators already encoded them.
    Xtr_n = np.nan_to_num(Xtr_n, nan=0.0)
    Xte_n = np.nan_to_num(Xte_n, nan=0.0)

    # The nn frame carries each categorical as a label-code column named after
    # the raw column (data/features.py nn_names.append(c)); code len(vocab)
    # means missing, hence the +1 embedding row.
    names = list(nn_ff.feature_names)
    cat_cols = [i for i, n in enumerate(names) if n in plan.categorical_vocab]
    num_cols = [i for i in range(len(names)) if i not in cat_cols]
    vocab_sizes = tuple(
        len(plan.categorical_vocab[names[i]]) + 1 for i in cat_cols
    )

    results = {
        "backend": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
        "train_rows": int(Xtr_n.shape[0]),
        "features": len(names),
    }

    # Short/long spreads: with K epochs amortized per dispatch
    # (epochs_per_dispatch), both fits must span MULTIPLE dispatches or the
    # delta collapses into dispatch-count noise and only a lower bound comes
    # out (throughput_measurement flags it).
    results["mlp"] = bench_family(
        lambda e: MLPClassifier(MLPConfig(epochs=e, early_stop_patience=10_000)),
        (Xtr_n, ytr_n),
        (Xte_n,),
        yte_n,
        short=16,
        long=48,
    )
    print("mlp:", json.dumps(results["mlp"]))

    if cat_cols:
        ft_fit = (Xtr_n[:, num_cols], Xtr_n[:, cat_cols].astype(np.int32), ytr_n)
        ft_test = (Xte_n[:, num_cols], Xte_n[:, cat_cols].astype(np.int32))
        results["ft_transformer"] = bench_family(
            lambda e: FTTransformerClassifier(
                vocab_sizes, FTTransformerConfig(epochs=e)
            ),
            ft_fit,
            ft_test,
            yte_n,
            short=4,
            long=10,
        )
        print("ft_transformer:", json.dumps(results["ft_transformer"]))

    results["tabnet"] = bench_family(
        lambda e: TabNetClassifier(TabNetConfig(epochs=e)),
        (Xtr_n, ytr_n),
        (Xte_n,),
        yte_n,
        short=16,
        long=48,
    )
    print("tabnet:", json.dumps(results["tabnet"]))

    print(json.dumps(results, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=2)
    return results


if __name__ == "__main__":
    main()
