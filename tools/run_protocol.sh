#!/bin/bash
# Drive the full staged protocol bench end to end with per-stage retries.
# Each stage is its own process (tools/protocol_stages.py); a stage that
# wedges on a hung backend RPC is simply re-run — intermediates persist in
# $DIR and the per-stage walls recorded in $DIR/*.json are the timings the
# final BENCH_PROTOCOL.json sums.
#
# Usage: bash tools/run_protocol.sh [rows] [dir] [out]
set -u
ROWS="${1:-2300000}"
DIR="${2:-/tmp/proto_r4}"
OUT="${3:-BENCH_PROTOCOL.json}"
cd "$(dirname "$0")/.."
export PYTHONPATH="$PWD:${PYTHONPATH:-/root/.axon_site}"

log() { echo "[run_protocol $(date +%H:%M:%S)] $*"; }

if [ ! -f "$DIR/prep.json" ]; then
  for attempt in 1 2; do
    log "prep attempt $attempt (rows=$ROWS)"
    timeout 10800 python tools/protocol_stages.py prep --rows "$ROWS" --dir "$DIR" && break
  done
  [ -f "$DIR/prep.json" ] || { log "prep failed twice"; exit 1; }
fi

N=$(python - <<'EOF'
import io, json, contextlib, sys
sys.argv = ["protocol_stages", "stages"]
buf = io.StringIO()
sys.path.insert(0, "tools")
import protocol_stages
with contextlib.redirect_stdout(buf):
    protocol_stages.main(["stages"])
print(json.loads(buf.getvalue())["n_stages"])
EOF
)
log "search stages: $N"

i=0
while [ "$i" -lt "$N" ]; do
  if [ ! -f "$DIR/search$i.json" ]; then
    for attempt in 1 2 3; do
      log "search$i attempt $attempt"
      timeout 7200 python tools/protocol_stages.py "search$i" --dir "$DIR" && break
    done
    [ -f "$DIR/search$i.json" ] || { log "search$i failed 3x"; exit 1; }
  fi
  i=$((i+1))
done

for attempt in 1 2; do
  log "final attempt $attempt"
  timeout 7200 python tools/protocol_stages.py final --dir "$DIR" --out "$OUT" && break
done
[ -f "$OUT" ] || { log "final failed twice"; exit 1; }
log "done: $OUT"
