"""hp-axis fan-out scheduling evidence on the 8-virtual-device CPU mesh.

The real pod claim — CV x HPO jobs sharded over the ``hp`` mesh axis run
concurrently on separate chips — cannot be *timed* in this environment
(one physical TPU chip; the 8-device CPU mesh is 8 XLA devices backed by ONE
host core, so wall-clock cannot improve). What CAN be evidenced here:

1. Work division: with ``hp=8``, each device's shard_map block receives
   jobs/8 vmapped jobs (vs all jobs at ``hp=1``). This follows from the
   fan-out's partition specs (`parallel/tune.py` shards the job axis
   ``P(hp_axis)`` over the mesh); the per-shape ``jobs_per_device_block``
   recorded below is computed from that partition arithmetic, not
   re-measured — the *behavioral* evidence is item 2.
2. Score invariance: the same candidate grid scores identically on
   (hp=1, dp=8), (hp=2, dp=4), (hp=8, dp=1) meshes — the global cand_id RNG
   design (also gated by tests/test_parallel.py on every CI run).
3. Honest wall-clocks for the three shapes on the shared single core, as a
   sanity record (expected ~flat; any large regression would indicate a
   scheduling pathology, e.g. serialization overhead growing with hp).

Produces MESH_EXPERIMENT.json. Run with:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/mesh_experiment.py
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=40_000)
    ap.add_argument("--out", default="MESH_EXPERIMENT.json")
    args = ap.parse_args(argv)

    import jax

    from cobalt_smart_lender_ai_tpu.debug import force_virtual_cpu_devices

    # A sitecustomize may have pinned the tunneled axon backend; force the
    # 8-virtual-device CPU backend before the first backend touch.
    force_virtual_cpu_devices(8)

    import jax.numpy as jnp

    from cobalt_smart_lender_ai_tpu.config import GBDTConfig, MeshConfig
    from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTHyperparams
    from cobalt_smart_lender_ai_tpu.ops.binning import compute_bin_edges, transform
    from cobalt_smart_lender_ai_tpu.parallel.mesh import make_mesh
    from cobalt_smart_lender_ai_tpu.parallel.tune import (
        cross_validate_gbdt,
        stratified_kfold_masks,
    )

    assert len(jax.devices()) >= 8, "run on the 8-virtual-device CPU backend"

    rng = np.random.default_rng(0)
    n, f = args.rows, 20
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.logistic(size=n) * 0.7 > 0).astype(
        np.int32
    )
    Xd = jnp.asarray(X)
    spec = compute_bin_edges(Xd, n_bins=64)
    bins = transform(spec, Xd)
    yd = jnp.asarray(y)
    val_masks = jnp.asarray(stratified_kfold_masks(y, 2, seed=0))

    cands = [
        GBDTConfig(n_estimators=30, max_depth=4, n_bins=64, learning_rate=lr)
        for lr in (0.05, 0.1, 0.2, 0.3)
    ]
    hps = jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *[GBDTHyperparams.from_config(c) for c in cands],
    )

    results = {"rows": n, "jobs": len(cands) * 2, "shapes": []}
    scores = {}
    for hp_size in (1, 2, 8):
        mesh = make_mesh(MeshConfig(hp=hp_size))
        t0 = time.time()
        aucs = cross_validate_gbdt(
            mesh,
            bins,
            yd,
            hps,
            val_masks,
            jax.random.PRNGKey(0),
            n_trees_cap=30,
            depth_cap=4,
            n_bins=64,
        )
        aucs = np.asarray(aucs)
        wall = round(time.time() - t0, 2)
        n_jobs = aucs.size
        jobs_local = -(-n_jobs // hp_size)
        results["shapes"].append(
            {
                "mesh": {"hp": hp_size, "dp": 8 // hp_size},
                "wall_seconds_single_core_host": wall,
                "jobs_per_device_block": jobs_local,
            }
        )
        scores[hp_size] = aucs
    base = scores[1]
    for k, v in scores.items():
        np.testing.assert_allclose(
            v, base, atol=1e-5,
            err_msg=f"hp={k} scores diverge from hp=1",
        )
    results["scores_identical_across_shapes"] = True
    results["mean_auc"] = round(float(base.mean()), 4)
    results["note"] = (
        "8 virtual XLA devices share ONE physical core, so wall-clock "
        "cannot improve with hp here; jobs_per_device_block is derived "
        "from the fan-out's P(hp) partition spec (not re-measured), and "
        "the behavioral evidence is the measured score invariance across "
        "mesh shapes — the correctness half of the pod-scaling claim. "
        "tests/test_parallel.py gates the same invariance on every run."
    )
    print(json.dumps(results, indent=2))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(results, fh, indent=2)
    return results


if __name__ == "__main__":
    main()
