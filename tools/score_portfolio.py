"""Portfolio & stress-scenario driver — the offline batch workload.

Streams a portfolio CSV from the object store through the mesh-sharded bulk
margin+SHAP programs in checkpointed chunks, sweeps a counterfactual
`ScenarioGrid`, and lands scores, per-scenario deltas, and a JSON scenario
report back in the store under ``scenario_runs/<run-id>/``. A killed run
(preemption, OOM, or the deterministic ``--fail-after-chunks`` test hook)
resumes with ``--resume`` and produces scores bit-identical to an
uninterrupted run.

Usage:
    python tools/score_portfolio.py --store artifacts \
        --portfolio portfolios/book.csv --scenarios scenarios.json \
        --shards -1 --run-id 2026q3-stress [--resume] \
        [--ledger-out ledger.json] [--trace-out trace.json]

The model comes from the registry (``--model-name``/``--channel``, default
the ``latest`` champion) so the report carries version provenance and the
training feature sketch for PSI OOD flagging; ``--model-key`` bypasses the
registry for ad-hoc artifacts. ``--scenarios`` is a JSON file of grid axes::

    {"axes": [{"feature": "installment", "op": "add", "values": [25, 50]},
              {"feature": "annual_inc", "op": "mul", "values": [0.9, 1.0]}]}

``--synthetic-portfolio N`` writes an N-row synthetic portfolio at
``--portfolio`` when the key is absent (CI / demo bootstrap). Exit codes:
0 success, 3 interrupted-but-resumable (the ``--fail-after-chunks`` path).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _build_synthetic_portfolio(store, key: str, rows: int, seed: int) -> None:
    """An N-row serving-feature portfolio CSV from the synthetic generator
    (same clean -> engineer -> select path the retrain driver trains on)."""
    import pandas as pd

    from cobalt_smart_lender_ai_tpu.data import (
        clean_raw_frame,
        engineer_features,
        prepare_cleaned_frame,
        synthetic_lendingclub_frame,
    )
    from cobalt_smart_lender_ai_tpu.data import schema

    raw = synthetic_lendingclub_frame(n_rows=rows, seed=seed)
    cleaned, _ = clean_raw_frame(raw)
    tree_ff, _, _ = engineer_features(prepare_cleaned_frame(cleaned))
    ff = tree_ff.select(schema.SERVING_FEATURES)
    import numpy as np

    frame = pd.DataFrame(
        np.asarray(ff.X, dtype=np.float32), columns=list(ff.feature_names)
    )
    store.save_frame(key, frame)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--store", default="artifacts")
    ap.add_argument("--portfolio", default="portfolios/portfolio.csv",
                    help="store key of the portfolio CSV to score")
    ap.add_argument("--scenarios", default=None,
                    help="path to a ScenarioGrid JSON file (omit for a "
                    "baseline-only run)")
    ap.add_argument("--run-id", default=None,
                    help="run-versioned output namespace (default: "
                    "portfolio-<unixtime>)")
    ap.add_argument("--resume", action="store_true",
                    help="continue a killed run with the same --run-id")
    ap.add_argument("--shards", type=int, default=1,
                    help="bulk mesh shards: 0/1 single device, -1 all "
                    "visible devices, N an N-way dp mesh")
    ap.add_argument("--chunk-rows", type=int, default=2048)
    ap.add_argument("--no-shap", action="store_true",
                    help="skip SHAP attribution (margin-only sweep)")
    ap.add_argument("--model-name", default="gbdt")
    ap.add_argument("--channel", default="latest")
    ap.add_argument("--registry-prefix", default="registry")
    ap.add_argument("--model-key", default=None,
                    help="bypass the registry: load this artifact key "
                    "directly (no provenance / PSI baseline)")
    ap.add_argument("--deadline-s", type=float, default=None,
                    help="optional wall-clock budget; default None = batch "
                    "runs never abort themselves")
    ap.add_argument("--synthetic-portfolio", type=int, default=None,
                    metavar="ROWS",
                    help="generate an N-row synthetic portfolio at "
                    "--portfolio when the key does not exist")
    ap.add_argument("--seed", type=int, default=29)
    ap.add_argument("--fail-after-chunks", type=int, default=None,
                    help="deterministic kill hook: raise after K freshly "
                    "scored chunks (exit 3, checkpoint resumable) — "
                    "CI/test use")
    ap.add_argument("--ledger-out", default=None,
                    help="write a run ledger here; render with "
                    "tools/obs_report.py")
    ap.add_argument("--trace-out", default=None,
                    help="write the run's spans as Perfetto JSON here")
    args = ap.parse_args(argv)

    from cobalt_smart_lender_ai_tpu.compilecache import bootstrap_compile_cache
    from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, ObjectStore
    from cobalt_smart_lender_ai_tpu.reliability.deadline import start_deadline
    from cobalt_smart_lender_ai_tpu.scenario import (
        PortfolioInterrupted,
        PortfolioScorer,
        ScenarioGrid,
        load_portfolio,
    )

    bootstrap_compile_cache()
    store = ObjectStore(args.store)
    run_id = args.run_id or f"portfolio-{int(time.time())}"

    if args.synthetic_portfolio and not store.exists(args.portfolio):
        _build_synthetic_portfolio(
            store, args.portfolio, args.synthetic_portfolio, args.seed
        )

    grid = None
    if args.scenarios:
        with open(args.scenarios) as fh:
            grid = ScenarioGrid.from_json(json.load(fh))

    if args.model_key:
        scorer = PortfolioScorer(
            GBDTArtifact.load(store, args.model_key),
            store,
            shards=args.shards,
            chunk_rows=args.chunk_rows,
            compute_shap=not args.no_shap,
            model_info={"key": args.model_key, "channel": "direct"},
        )
    else:
        scorer = PortfolioScorer.from_registry(
            store,
            model_name=args.model_name,
            channel=args.channel,
            registry_prefix=args.registry_prefix,
            shards=args.shards,
            chunk_rows=args.chunk_rows,
            compute_shap=not args.no_shap,
        )

    ledger = None
    if args.ledger_out:
        from cobalt_smart_lender_ai_tpu.telemetry import (
            RunLedger,
            install_device_metrics,
            install_program_metrics,
        )

        install_program_metrics()
        install_device_metrics()
        ledger = RunLedger(
            "portfolio",
            meta={
                "run_id": run_id,
                "portfolio": args.portfolio,
                "shards": args.shards,
                "chunk_rows": args.chunk_rows,
                "resume": bool(args.resume),
            },
        )

    X, portfolio_meta = load_portfolio(
        store, args.portfolio, scorer.artifact.feature_names
    )

    def _finish_artifacts():
        if ledger is not None:
            ledger.write(args.ledger_out)
        if args.trace_out:
            from cobalt_smart_lender_ai_tpu.telemetry import (
                default_tracer,
                render_chrome_trace,
            )

            with open(args.trace_out, "w") as fh:
                fh.write(render_chrome_trace(default_tracer()))

    try:
        report = scorer.run(
            X,
            grid,
            run_id=run_id,
            resume=args.resume,
            deadline=start_deadline(args.deadline_s),
            fail_after_chunks=args.fail_after_chunks,
            ledger=ledger,
            portfolio_meta=portfolio_meta,
        )
    except PortfolioInterrupted as exc:
        if ledger is not None:
            ledger.set(
                "scenario_report",
                {"run_id": run_id, "interrupted": True,
                 "items_done": exc.items_done,
                 "items_total": exc.items_total},
            )
        _finish_artifacts()
        print(json.dumps({
            "run_id": run_id,
            "interrupted": True,
            "items_done": exc.items_done,
            "items_total": exc.items_total,
            "resume_with": "--resume",
        }))
        return 3

    if ledger is not None:
        ledger.fingerprint = report["fingerprint"]
    _finish_artifacts()
    print(json.dumps({
        "run_id": run_id,
        "report_key": report["keys"]["report"],
        "rows": report["portfolio"]["rows"],
        "scenarios": len(report["scenarios"]),
        "chunks_resumed": report["resume"]["chunks_resumed"],
        "chunks_scored": report["resume"]["chunks_scored"],
        "rows_per_second": report["telemetry"]["rows_per_second"],
        "shards": report["partitioner"]["shards"],
        "ood_scenarios": [
            b["id"] for b in report["scenarios"]
            if (b.get("drift") or {}).get("ood")
        ],
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
