"""Search-leg bench: successive halving vs exhaustive on the same grid.

Runs the reference 20x3 randomized-search grid (TuneConfig.param_space)
twice on identical data, folds and seed — once with the successive-halving
scheduler, once exhaustive — and reports the `cobalt_search_dispatch_seconds`
each mode actually spent dispatching tree work, the winner each mode picked,
and the winner's full-refit test AUC. This is the harness behind the PR-10
acceptance gate: halving must spend measurably fewer dispatch seconds while
the refit AUC stays within PARITY_MARGIN of the exhaustive winner's.

Single-mode invocations (``--mode halving|exhaustive``) emit the same JSON
for one scheduler plus the process's ``cobalt_compile_*`` counters — run one
twice with a shared ``--cache-dir`` to prove the persistent compile cache
eliminates the second process's XLA compiles (the CI `search-smoke` job).

    python tools/bench_search.py --smoke --mode both --out BENCH_SEARCH.json
    python tools/bench_search.py --smoke --mode halving --cache-dir /tmp/cc
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

# repo root (package import) + tools/ (parity.build_matrices import)
_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
sys.path.insert(0, _HERE)


def run_search(mats, tune_cfg, *, base, mesh):
    """One randomized_search over the shared matrices; returns the result
    plus the dispatch-seconds delta attributed to this run's scheduler."""
    import jax.numpy as jnp
    import numpy as np

    from cobalt_smart_lender_ai_tpu.ops.metrics import roc_auc
    from cobalt_smart_lender_ai_tpu.parallel.tune import randomized_search
    from cobalt_smart_lender_ai_tpu.telemetry import default_registry

    counter = default_registry().counter(
        "cobalt_search_dispatch_seconds", "", ("mode",)
    )
    before = {
        m: counter.labels(mode=m).value for m in ("halving", "exhaustive")
    }
    t0 = time.time()
    res = randomized_search(
        mats["X_train"], mats["y_train"], base, tune_cfg, mesh
    )
    wall = time.time() - t0
    deltas = {
        m: round(counter.labels(mode=m).value - before[m], 3)
        for m in ("halving", "exhaustive")
    }
    margin = res.best_estimator_.predict_margin(jnp.asarray(mats["X_test"]))
    test_auc = float(
        roc_auc(jnp.asarray(mats["y_test"], jnp.float32), margin)
    )
    report = res.cv_results_.get("halving")
    mode = "halving" if report is not None else "exhaustive"
    out = {
        "mode": mode,
        "wall_seconds": round(wall, 1),
        "dispatch_seconds": deltas[mode],
        "dispatch_seconds_by_mode": deltas,
        "best_params": res.best_params_,
        "cv_auc": round(float(res.best_score_), 6),
        "test_auc": round(test_auc, 6),
        "mean_test_score": np.round(
            res.cv_results_["mean_test_score"], 6
        ).tolist(),
    }
    if report is not None:
        out["halving"] = {
            k: report[k]
            for k in ("eta", "budgets", "rungs", "pruned_candidates",
                      "survivors", "dispatches")
        }
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rows", type=int, default=40_000)
    ap.add_argument("--seed", type=int, default=5)
    ap.add_argument(
        "--mode", choices=("both", "halving", "exhaustive"), default="both"
    )
    ap.add_argument(
        "--smoke",
        action="store_true",
        help="CI profile: small rows/bins, fixed small chunk so the "
        "schedule rungs even at toy scale, compile-cache threshold 0",
    )
    ap.add_argument(
        "--mini-grid",
        action="store_true",
        help="miniature 6x2 search grid (48-tree cap) instead of the 20x3 "
        "reference grid — the schedule still rungs and prunes, at a scale a "
        "1-core CI host finishes in minutes",
    )
    ap.add_argument(
        "--chunk-trees",
        default="auto",
        type=lambda s: s if s == "auto" else (None if s == "none" else int(s)),
    )
    ap.add_argument("--eta", type=int, default=2)
    ap.add_argument("--n-bins", type=int, default=None)
    ap.add_argument(
        "--cache-dir",
        default=None,
        help="persistent compile cache dir (default: framework default dir)",
    )
    ap.add_argument(
        "--force-devices", type=int, default=0,
        help="force an N-virtual-device CPU backend (CI mesh smoke)",
    )
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--trend-out",
        default=None,
        help="append this run's warm-dispatch/cache-miss metrics to the "
        "given TREND.json (gate with tools/perf_sentinel.py check)",
    )
    ap.add_argument(
        "--check",
        action="store_true",
        help="with --mode both: exit nonzero unless halving pruned "
        "candidates, spent fewer dispatch seconds than exhaustive, and "
        "the refit AUC is within the parity margin",
    )
    args = ap.parse_args(argv)

    if args.force_devices:
        from cobalt_smart_lender_ai_tpu.debug import force_virtual_cpu_devices

        force_virtual_cpu_devices(args.force_devices)

    from cobalt_smart_lender_ai_tpu.compilecache import (
        bootstrap_compile_cache,
        compile_stats,
    )
    from cobalt_smart_lender_ai_tpu.config import (
        CompileCacheConfig,
        GBDTConfig,
        MeshConfig,
        TuneConfig,
    )

    cache_cfg = CompileCacheConfig(
        cache_dir=args.cache_dir,
        min_compile_time_secs=0.0 if args.smoke else 5.0,
    )
    cache_dir = bootstrap_compile_cache(cache_cfg)

    import jax

    from cobalt_smart_lender_ai_tpu.parallel.mesh import make_mesh
    from parity import build_matrices

    if args.smoke:
        rows = min(args.rows, 6000)
        n_bins = args.n_bins or 32
        default_chunk = 12 if args.mini_grid else 25
        chunk = args.chunk_trees if args.chunk_trees != "auto" else default_chunk
    else:
        rows = args.rows
        n_bins = args.n_bins or GBDTConfig.n_bins
        chunk = args.chunk_trees

    mats = build_matrices(rows, args.seed)
    base = GBDTConfig().replace(n_bins=n_bins, scale_pos_weight=mats["spw"])
    mesh = make_mesh(MeshConfig())

    grid_overrides = {}
    grid_name = "TuneConfig.param_space 20x3 reference grid"
    if args.mini_grid:
        grid_overrides = dict(
            n_iter=6,
            cv_folds=2,
            param_space={
                "n_estimators": (24, 48),
                "max_depth": (2, 3),
                "learning_rate": (0.1, 0.3),
            },
        )
        grid_name = "mini 6x2 grid (48-tree cap)"

    def tune_for(halving: bool) -> TuneConfig:
        return dataclasses.replace(
            TuneConfig(),
            chunk_trees=chunk,
            halving_enabled=halving,
            halving_eta=args.eta,
            **grid_overrides,
        )

    runs = {}
    modes = (
        ("halving", "exhaustive") if args.mode == "both" else (args.mode,)
    )
    for mode in modes:
        print(f"[bench_search] running {mode} search on {rows} rows ...")
        result = run_search(
            mats, tune_for(mode == "halving"), base=base, mesh=mesh
        )
        if args.smoke:
            # At smoke scale the cold XLA compile wall dwarfs the tree
            # compute the scheduler saves, so the gated comparison is the
            # *warm* run (production search legs are warm: the persistent
            # cache is default-on and the first pass just populated it).
            # Cold numbers stay in the record for the compile-cache story.
            cold = result
            result = run_search(
                mats, tune_for(mode == "halving"), base=base, mesh=mesh
            )
            result["cold_dispatch_seconds"] = cold["dispatch_seconds"]
            result["cold_wall_seconds"] = cold["wall_seconds"]
        runs[mode] = result
        print(
            f"[bench_search] {mode}: dispatch "
            f"{runs[mode]['dispatch_seconds']}s, wall "
            f"{runs[mode]['wall_seconds']}s, test_auc "
            f"{runs[mode]['test_auc']}"
        )

    out = {
        "bench": "search_halving_vs_exhaustive",
        "rows": rows,
        "seed": args.seed,
        "n_bins": n_bins,
        "chunk_trees": chunk,
        "eta": args.eta,
        "grid": grid_name,
        "backend": jax.devices()[0].platform,
        "n_devices": jax.device_count(),
        "host_cpu_cores": os.cpu_count(),
        "measurement": (
            "warm (second in-process pass per mode; cold_* fields are the "
            "first pass that populated the caches)"
            if args.smoke
            else "single cold pass per mode"
        ),
        "compile_cache_dir": cache_dir,
        "compile": compile_stats(),
        "runs": runs,
    }

    failures = []
    if args.mode == "both":
        h, e = runs["halving"], runs["exhaustive"]
        out["dispatch_seconds_saved"] = round(
            e["dispatch_seconds"] - h["dispatch_seconds"], 3
        )
        out["refit_auc_gap"] = round(h["test_auc"] - e["test_auc"], 6)
        if args.check:
            if "halving" not in h:
                failures.append("halving scheduler did not engage")
            elif h["halving"]["pruned_candidates"] <= 0:
                failures.append("halving pruned no candidates")
            if h["dispatch_seconds"] >= e["dispatch_seconds"]:
                failures.append(
                    "halving dispatch seconds not below exhaustive "
                    f"({h['dispatch_seconds']} vs {e['dispatch_seconds']})"
                )
            if abs(out["refit_auc_gap"]) > 0.005:
                failures.append(
                    f"refit AUC gap {out['refit_auc_gap']} exceeds 0.005"
                )
    out["check_failures"] = failures

    if args.trend_out:
        import time

        from cobalt_smart_lender_ai_tpu.telemetry.trend import append_record

        append_record(
            args.trend_out, out, source="tools/bench_search.py",
            stamp=time.time(),
        )

    blob = json.dumps(out, indent=2, sort_keys=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(blob + "\n")
        print(f"[bench_search] wrote {args.out}")
    else:
        print(blob)
    if failures:
        print("[bench_search] CHECK FAILED: " + "; ".join(failures))
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
