"""Full-protocol bench split into restartable per-stage processes.

`bench.py --protocol` runs the whole `run_pipeline` protocol in one process;
on this environment's tunneled TPU backend, processes under sustained
dispatch load for >~1h wedge on a hung RPC (observed repeatedly mid-search).
This runner executes the SAME protocol — clean -> engineer -> leakage drop ->
hashed split -> RFE-20 step 1 -> 20x3 randomized search over the full
reference space (`model_tree_train_test.py:111-159`) -> final fit -> test
eval — as short, restartable stages with intermediate arrays persisted to a
scratch directory. Search scores are identical to `randomized_search`'s:
the same seed-22 candidate sample, the same stratified fold masks, and
global candidate ids keep every job's RNG stream equal to the joint
dispatch's (parallel/tune.py `cand_ids`).

Timing honesty: each stage records its own wall clock, INCLUDING its
re-upload of the persisted matrices (that overhead counts against us; a
single-process run would not pay it). The final stage sums stage walls into
the one BENCH_PROTOCOL.json shape `bench.py` embeds.

Usage (each stage is one process; rerun any stage that wedges):

    python tools/protocol_stages.py stages                     # list search stages
    python tools/protocol_stages.py prep    --rows 2300000 --dir /tmp/proto
    python tools/protocol_stages.py search0 --dir /tmp/proto   # ... searchN-1
    python tools/protocol_stages.py final   --dir /tmp/proto --out BENCH_PROTOCOL.json

The stage count is derived at runtime from the candidate sample through
`parallel.tune.search_buckets` (the `stages` subcommand prints it), so it can
never drift from `randomized_search`'s joint-dispatch bucketing.
"""

from __future__ import annotations

import argparse
import json
import time
from pathlib import Path

import numpy as np

import sys

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
from bench import NORTH_STAR_ROWS_PER_SEC_PER_CHIP  # single source of truth

#: Per-dispatch boosting-round chunks are derived from each stage's workload
#: shape against the dispatch budget (parallel/budget.py) — round 3's
#: hardcoded worst-case chunk of 2 made small runs host-sync-bound.
CHUNK_TREES = "auto"


def _atomic_write(path: Path, text: str) -> None:
    """Write-then-rename so a stage killed mid-write (run_protocol.sh wraps
    every stage in `timeout`) can never leave a truncated file that passes
    the shell's [ -f ] resume gate — the retry loop would skip the stage and
    a later stage would crash parsing corrupt JSON."""
    import os

    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(text)
    os.replace(tmp, path)


def _buckets(candidates, base):
    """Search stages: `parallel.tune.search_buckets`' EXACT bucketing (shared
    helper, so stage indices can never drift from the joint dispatch's), with
    any bucket of >6 candidates split in two so no stage runs >~30 min on
    this backend. Scores stay identical to the joint dispatch either way via
    global cand_ids."""
    from cobalt_smart_lender_ai_tpu.parallel.tune import search_buckets

    stages = []
    for idxs in search_buckets(candidates, base):
        if len(idxs) > 6:
            stages.append(idxs[: len(idxs) // 2])
            stages.append(idxs[len(idxs) // 2:])
        else:
            stages.append(idxs)
    return stages


def stage_prep(args):
    import dataclasses

    import jax
    import jax.numpy as jnp

    from cobalt_smart_lender_ai_tpu.config import PipelineConfig, RFEConfig
    from cobalt_smart_lender_ai_tpu.data.clean import clean_raw_frame
    from cobalt_smart_lender_ai_tpu.data.features import (
        drop_training_leakage,
        engineer_features,
        prepare_cleaned_frame,
    )
    from cobalt_smart_lender_ai_tpu.data.split import train_test_split_hashed
    from cobalt_smart_lender_ai_tpu.data.synthetic import (
        synthetic_lendingclub_frame,
    )
    from cobalt_smart_lender_ai_tpu.parallel.mesh import make_mesh
    from cobalt_smart_lender_ai_tpu.parallel.rfe import rfe_select

    cfg = PipelineConfig()
    t_gen0 = time.time()
    raw = synthetic_lendingclub_frame(n_rows=args.rows, seed=5)
    t_gen = time.time() - t_gen0

    timings = {}
    t0 = time.time()
    cleaned, _ = clean_raw_frame(
        raw, null_col_threshold=cfg.data.null_col_threshold
    )
    prepared = prepare_cleaned_frame(
        cleaned, row_null_allowance=cfg.data.row_null_allowance
    )
    tree_ff, _, _ = engineer_features(prepared)
    ff = drop_training_leakage(tree_ff)
    timings["clean_engineer"] = round(time.time() - t0, 1)

    t0 = time.time()
    X_train, X_test, y_train, y_test = train_test_split_hashed(
        ff.X, ff.y, test_fraction=cfg.data.test_fraction, seed=cfg.data.split_seed
    )
    n_pos = float(jnp.sum(y_train))
    spw = (float(X_train.shape[0]) - n_pos) / max(n_pos, 1.0)
    timings["split"] = round(time.time() - t0, 1)

    t0 = time.time()
    # Device-stepped elimination (K steps per dispatch, auto-derived) — the
    # default RFEConfig path since round 4.
    rfe_cfg = dataclasses.replace(RFEConfig(), scale_pos_weight=spw)
    rfe = rfe_select(X_train, y_train, rfe_cfg, mesh=make_mesh())
    timings["rfe"] = round(time.time() - t0, 1)
    selected = [n for n, k in zip(ff.feature_names, rfe.support_) if k]

    t0 = time.time()
    sel_idx = jnp.asarray(np.flatnonzero(rfe.support_))
    Xtr = np.asarray(jnp.take(X_train, sel_idx, axis=1), np.float32)
    Xte = np.asarray(jnp.take(X_test, sel_idx, axis=1), np.float32)
    timings["fetch_selected"] = round(time.time() - t0, 1)

    out = Path(args.dir)
    out.mkdir(parents=True, exist_ok=True)
    import os

    np.savez_compressed(
        out / "prep.tmp.npz",  # savez appends .npz unless already present
        Xtr=Xtr,
        Xte=Xte,
        y_train=np.asarray(y_train, np.int32),
        y_test=np.asarray(y_test, np.int32),
    )
    # npz first, json (the resume gate) last — both atomically, so the gate
    # file existing implies a complete npz.
    os.replace(out / "prep.tmp.npz", out / "prep.npz")
    _atomic_write(
        out / "prep.json",
        json.dumps(
            {
                "rows": args.rows,
                "spw": spw,
                "selected": selected,
                "datagen_seconds_excluded": round(t_gen, 1),
                "timings": timings,
                "device": str(jax.devices()[0]),
            }
        )
    )
    print(json.dumps({"stage": "prep", "timings": timings, "selected": selected}))


def _load_prep(dirpath):
    d = Path(dirpath)
    z = np.load(d / "prep.npz")
    meta = json.loads((d / "prep.json").read_text())
    return z, meta


def _search_setup(meta):
    from cobalt_smart_lender_ai_tpu.config import GBDTConfig, TuneConfig
    from cobalt_smart_lender_ai_tpu.parallel.tune import sample_candidates

    tune = TuneConfig()
    base = GBDTConfig(scale_pos_weight=meta["spw"])
    candidates = sample_candidates(tune.param_space, tune.n_iter, tune.seed)
    return tune, base, candidates


def stage_search(args, stage_idx: int):
    import jax
    import jax.numpy as jnp

    from cobalt_smart_lender_ai_tpu.ops.binning import compute_bin_edges, transform
    from cobalt_smart_lender_ai_tpu.parallel.mesh import make_mesh
    from cobalt_smart_lender_ai_tpu.parallel.tune import (
        cross_validate_gbdt,
        stack_candidates,
        stratified_kfold_masks,
    )

    t_wall0 = time.time()
    z, meta = _load_prep(args.dir)
    tune, base, candidates = _search_setup(meta)
    idxs = _buckets(candidates, base)[stage_idx]

    X = jnp.asarray(z["Xtr"])
    y_np = z["y_train"]
    spec = compute_bin_edges(X, n_bins=base.n_bins)
    bins = transform(spec, X)
    val_masks = jnp.asarray(stratified_kfold_masks(y_np, tune.cv_folds, tune.seed))
    hps, n_trees_cap, depth_cap = stack_candidates(
        [candidates[i] for i in idxs], base
    )
    aucs = cross_validate_gbdt(
        make_mesh(),
        bins,
        jnp.asarray(y_np),
        hps,
        val_masks,
        jax.random.PRNGKey(tune.seed),
        n_trees_cap=n_trees_cap,
        depth_cap=depth_cap,
        n_bins=base.n_bins,
        cand_ids=jnp.asarray(idxs, jnp.int32),
        chunk_trees=CHUNK_TREES,
    )
    wall = round(time.time() - t_wall0, 1)
    out = {
        "stage": f"search{stage_idx}",
        "cand_idxs": idxs,
        "depths": sorted({candidates[i]["max_depth"] for i in idxs}),
        "scores": np.asarray(aucs).tolist(),
        "seconds": wall,
    }
    _atomic_write(Path(args.dir) / f"search{stage_idx}.json", json.dumps(out))
    print(json.dumps(out))


def stage_final(args):
    import jax.numpy as jnp

    from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTClassifier
    from cobalt_smart_lender_ai_tpu.ops.metrics import roc_auc

    t_wall0 = time.time()
    z, meta = _load_prep(args.dir)
    tune, base, candidates = _search_setup(meta)
    n_stages = len(_buckets(candidates, base))
    scores = np.zeros((len(candidates), tune.cv_folds))
    search_seconds = 0.0
    for i in range(n_stages):
        s = json.loads((Path(args.dir) / f"search{i}.json").read_text())
        scores[s["cand_idxs"]] = np.asarray(s["scores"])
        search_seconds += s["seconds"]
    mean_auc = scores.mean(axis=1)
    best_i = int(mean_auc.argmax())
    best = dict(candidates[best_i])

    est = GBDTClassifier(base.replace(**best, chunk_trees="auto"))
    est.fit(z["Xtr"], z["y_train"])
    margin = est.predict_margin(jnp.asarray(z["Xte"]))
    test_auc = float(roc_auc(jnp.asarray(z["y_test"], jnp.float32), margin))
    final_wall = round(time.time() - t_wall0, 1)

    timings = dict(meta["timings"])
    timings["search"] = round(search_seconds, 1)
    timings["final_fit_eval"] = final_wall
    total = round(sum(timings.values()), 1)
    n_rows = meta["rows"]
    doc = {
        "metric": "full_protocol_rows_per_sec_per_chip",
        "value": round(n_rows / total, 1),
        "unit": (
            f"rows/s ({n_rows/1e6:.1f}M-row raw frame through the whole "
            f"protocol — clean+engineer+RFE-20(step1)+search(20x3, full "
            f"reference space)+final fit+eval — in {total:.0f}s on one chip; "
            f"test AUC {test_auc:.4f}, cv AUC {mean_auc[best_i]:.4f}; "
            "vs_baseline = x over the 4,791 rows/s/chip v4-8 <60s budget; "
            "staged run: per-stage processes with persisted intermediates, "
            "re-upload overhead included in each stage's wall)"
        ),
        "produced_by": "tools/protocol_stages.py (restartable staged runner)",
        "vs_baseline": round(n_rows / total / NORTH_STAR_ROWS_PER_SEC_PER_CHIP, 3),
        "seconds_total": total,
        "seconds_stage": timings,
        "seconds_synthetic_datagen_excluded": meta["datagen_seconds_excluded"],
        "test_auc": round(test_auc, 4),
        "cv_auc": round(float(mean_auc[best_i]), 4),
        "best_params": best,
        "n_rows": n_rows,
        "device": meta["device"],
        "selected_features": meta["selected"],
    }
    print(json.dumps(doc))
    if args.out:
        _atomic_write(Path(args.out), json.dumps(doc, indent=2))


def stage_list():
    """Print the runtime-derived search-stage layout (no accelerator work)."""
    from cobalt_smart_lender_ai_tpu.config import GBDTConfig, TuneConfig
    from cobalt_smart_lender_ai_tpu.parallel.tune import sample_candidates

    tune = TuneConfig()
    base = GBDTConfig()
    candidates = sample_candidates(tune.param_space, tune.n_iter, tune.seed)
    stages = _buckets(candidates, base)
    print(
        json.dumps(
            {
                "n_stages": len(stages),
                "stages": [
                    {
                        "stage": f"search{i}",
                        "cand_idxs": idxs,
                        "depths": sorted(
                            {candidates[j]["max_depth"] for j in idxs}
                        ),
                    }
                    for i, idxs in enumerate(stages)
                ],
            }
        )
    )


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "stage",
        help="'prep', 'final', 'stages' (list the runtime-derived search "
        "stages), or 'search<N>' — N in range(n_stages) per 'stages'",
    )
    ap.add_argument("--rows", type=int, default=2_300_000)
    ap.add_argument("--dir", default="/tmp/proto_bench")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    import logging

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s [%(levelname)s] %(message)s"
    )
    from cobalt_smart_lender_ai_tpu.compilecache import bootstrap_compile_cache

    bootstrap_compile_cache()  # stages re-run identical programs
    if args.stage == "prep":
        stage_prep(args)
    elif args.stage == "stages":
        stage_list()
    elif args.stage.startswith("search") and args.stage[len("search"):].isdigit():
        stage_search(args, int(args.stage[len("search"):]))
    elif args.stage == "final":
        stage_final(args)
    else:
        ap.error(f"unknown stage {args.stage!r}")


if __name__ == "__main__":
    main()
