"""Streamlit shell over `ui.core` — the L5 layer (cobalt_streamlit.py:1-173).

Run with::

    streamlit run cobalt_smart_lender_ai_tpu/ui/app.py --server.port=8001

Two modes, matching the reference sidebar radio: a single-borrower form (12
numeric inputs + 4 indicator checkboxes + hardship selectbox) posting to
``/predict`` and rendering the SHAP waterfall, and a bulk CSV upload posting
to ``/predict_bulk_csv`` with a results table, download button, and top-10
gain-importance bar chart. All data logic lives in `core`; this module only
draws. `streamlit` is an optional dependency (``pip install .[ui]``) — the
import is deferred so the package imports cleanly without it.

The API base URL comes from the ``API_URL`` env var (docker-compose wires
``http://api:8000`` exactly as the reference's compose file does), defaulting
to localhost for bare-metal runs.
"""

from __future__ import annotations

import hashlib
import os

from cobalt_smart_lender_ai_tpu.ui import core


def main() -> None:
    try:
        import streamlit as st
    except ImportError as e:  # pragma: no cover - exercised only without extra
        raise ImportError(
            "The UI needs streamlit: pip install 'cobalt-smart-lender-ai-tpu[ui]'"
        ) from e
    import matplotlib.pyplot as plt

    client = core.ApiClient(os.environ.get("API_URL", "http://localhost:8000"))

    st.set_page_config(page_title="Cobalt Loan Default Prediction", layout="wide")
    st.title("Loan Default Risk Predictor")
    menu = st.sidebar.radio(
        "Select Mode", ["Single Prediction", "Bulk Prediction + SHAP"]
    )

    if menu == "Single Prediction":
        st.subheader("Enter loan details for a single borrower")
        col1, col2 = st.columns(2)
        numeric: dict[str, float] = {}
        checkboxes: dict[str, bool] = {}
        with col1:
            for field, label, default in core.NUMERIC_INPUTS[:7]:
                if field == "term":
                    numeric[field] = st.selectbox(label, [36, 60], index=0)
                else:
                    numeric[field] = st.number_input(label, value=default)
        with col2:
            for field, label, default in core.NUMERIC_INPUTS[7:]:
                numeric[field] = st.number_input(label, value=default)
            for field, label in core.CHECKBOX_INPUTS:
                checkboxes[field] = st.checkbox(label)
            hardship = st.selectbox("Hardship Status", list(core.HARDSHIP_OPTIONS))

        if st.button("Predict Default Risk"):
            try:
                payload = core.build_single_payload(numeric, checkboxes, hardship)
                resp = client.predict(payload)
                st.success(
                    f"Estimated Default Probability: {resp['prob_default']:.2%}"
                )
                st.subheader("SHAP Explanation")
                wf = core.build_waterfall(resp, max_display=10)
                fig, ax = plt.subplots(figsize=(10, 6))
                core.render_waterfall(ax, wf)
                plt.tight_layout()
                st.pyplot(fig)
            except core.ServiceDegraded as e:
                # Operational backpressure (shed / breaker open / deadline),
                # not a user mistake — warn, don't stack-trace.
                st.warning(str(e))
            except Exception as e:
                st.error(f"Error during prediction: {e}")

    else:
        st.subheader("Upload CSV for Bulk Inference")
        uploaded = st.file_uploader("Upload CSV with required columns", type="csv")
        # Cached results belong to exactly one upload: replacing or removing
        # the file must drop them, or the page would keep rendering the
        # previous file's predictions under the new upload. Streamlit's
        # UploadedFile carries a stable per-upload file_id; fall back to a
        # content hash for harnesses (and streamlits) without one — that path
        # re-hashes the file each rerun, so prefer file_id when present.
        if uploaded is None:
            upload_key = None
        else:
            uid = getattr(uploaded, "file_id", None)
            if uid is None:
                uid = hashlib.md5(uploaded.getvalue()).hexdigest()
            upload_key = f"{uploaded.name}:{uid}"
        if st.session_state.get("bulk_upload_key") != upload_key:
            st.session_state.pop("bulk_results", None)
            st.session_state.pop("bulk_importance", None)
            st.session_state["bulk_upload_key"] = upload_key
        if uploaded and st.button("Run Bulk Prediction"):
            try:
                st.session_state["bulk_results"] = client.predict_bulk_csv(
                    uploaded.name, uploaded.getvalue()
                )
            except core.ServiceDegraded as e:
                st.session_state.pop("bulk_results", None)
                st.warning(str(e))
            except Exception as e:
                st.session_state.pop("bulk_results", None)
                st.error(f"Prediction failed: {e}")
            else:
                # Importance is fetched once per run, not per rerun: the
                # explorer's widgets retrigger the whole script, and
                # re-posting every record to /feature_importance_bulk on each
                # interaction would recompute bulk importances per keystroke.
                # Its failure must not discard the successful predictions —
                # the chart is simply skipped.
                try:
                    st.session_state["bulk_importance"] = (
                        client.feature_importance_bulk(
                            st.session_state["bulk_results"]
                        )
                    )
                except Exception as e:
                    st.session_state.pop("bulk_importance", None)
                    st.error(f"Feature importance unavailable: {e}")
        # Results live in session_state so the explorer's widgets survive
        # Streamlit's rerun-on-interaction (the button is only True on the
        # run it was clicked).
        records = st.session_state.get("bulk_results")
        if records is not None:
            try:
                df_result = core.coerce_results_frame(records)
                st.subheader("Prediction Results")
                st.dataframe(df_result)
                st.download_button(
                    "Download Results",
                    df_result.to_csv(index=False),
                    "bulk_predictions.csv",
                )
                importance = st.session_state.get("bulk_importance")
                if importance is not None:
                    st.subheader("Feature Importance (Top 10)")
                    imp = core.importance_series(importance)
                    fig, ax = plt.subplots()
                    ax.barh(list(imp.index)[::-1], list(imp.values)[::-1])
                    ax.set_xlabel("Importance (gain)")
                    ax.set_title("Top 10 Important Features")
                    st.pyplot(fig)

                # Per-row SHAP explorer — the reference notebook's row-slider
                # force plots (04_model_training.ipynb cells 25-26), served
                # live: pick a row, re-post it to /predict, waterfall it.
                if len(df_result):
                    st.subheader("Per-row SHAP Explorer")
                    row_idx = int(
                        st.number_input(
                            "Row to explain",
                            min_value=0,
                            max_value=len(df_result) - 1,
                            value=0,
                            step=1,
                        )
                    )
                    try:
                        row_resp = client.predict(
                            core.results_row_payload(df_result, row_idx)
                        )
                        st.caption(
                            f"Row {row_idx}: estimated default probability "
                            f"{row_resp['prob_default']:.2%}"
                        )
                        wf = core.build_waterfall(row_resp, max_display=10)
                        fig, ax = plt.subplots(figsize=(10, 6))
                        core.render_waterfall(ax, wf)
                        plt.tight_layout()
                        st.pyplot(fig)
                    except Exception as e:
                        st.info(f"Row explanation unavailable: {e}")
            except Exception as e:
                st.error(f"Rendering results failed: {e}")


if __name__ == "__main__":
    main()
