"""Framework-free UI logic — everything the Streamlit shell (`app.py`) does
except draw widgets.

The reference UI (`cobalt_streamlit.py`) mixes four concerns inside Streamlit
callbacks: building the request payload with the two alias renames (:76-82),
calling the API (:85, :140, :159), reconstructing a SHAP explanation from the
/predict response (:102-107), and coercing the bulk results to a numeric
frame (:145). Here each is a plain function over JSON-shaped dicts so the
whole UI data path is unit-testable against the in-process server without a
browser — and the Streamlit layer stays a thin render shell.

The waterfall math replaces `shap.plots.waterfall` (:109-113): the shap
package draws from (values, base_value, data); we compute the same top-10
ordering, residual "other features" collapse, and cumulative bar positions
directly, then render with matplotlib.
"""

from __future__ import annotations

import io
from dataclasses import dataclass
from typing import Any, Mapping, Sequence

import numpy as np
import pandas as pd

from cobalt_smart_lender_ai_tpu.data import schema

#: The single-prediction form's numeric inputs, in the reference's widget
#: order with its default values (cobalt_streamlit.py:46-63).
NUMERIC_INPUTS: tuple[tuple[str, str, float], ...] = (
    ("loan_amnt", "Loan Amount", 10000.0),
    ("term", "Term (months)", 36.0),
    ("installment", "Installment", 300.0),
    ("fico_range_low", "FICO Range Low", 660.0),
    ("last_fico_range_high", "Last FICO High", 700.0),
    ("open_il_12m", "Open IL Last 12m", 1.0),
    ("open_il_24m", "Open IL Last 24m", 2.0),
    ("max_bal_bc", "Max Balance on Bank Card", 2000.0),
    ("num_rev_accts", "Number of Revolving Accounts", 10.0),
    ("pub_rec_bankruptcies", "Bankruptcies", 0.0),
    ("emp_length_num", "Employment Length (years)", 3.0),
    ("earliest_cr_line_days", "Days Since First Credit Line", 4000.0),
)

#: Checkbox indicator columns (cobalt_streamlit.py:65-68).
CHECKBOX_INPUTS: tuple[tuple[str, str], ...] = (
    ("grade_E", "Grade E"),
    ("home_ownership_MORTGAGE", "Home Ownership: Mortgage"),
    ("verification_status_Verified", "Verified Status"),
    ("application_type_Joint_App", "Joint Application"),
)

#: Hardship selectbox options (cobalt_streamlit.py:70) — "ACTIVE" is the
#: implicit all-zeros baseline.
HARDSHIP_OPTIONS = ("ACTIVE", "BROKEN", "COMPLETE", "COMPLETED", "No_Hardship")


def build_single_payload(
    numeric: Mapping[str, float],
    checkboxes: Mapping[str, bool],
    hardship: str,
) -> dict[str, float]:
    """Assemble the /predict request body from form state, applying the two
    alias renames (cobalt_streamlit.py:76-82) so the wire keys are the
    canonical get_dummies names with spaces."""
    if hardship not in HARDSHIP_OPTIONS:
        raise ValueError(f"unknown hardship status {hardship!r}")
    payload: dict[str, float] = {
        field: float(numeric[field]) for field, _, _ in NUMERIC_INPUTS
    }
    for field, _ in CHECKBOX_INPUTS:
        payload[field] = int(bool(checkboxes.get(field, False)))
    for status in HARDSHIP_OPTIONS[1:]:
        payload[f"hardship_status_{status}"] = int(hardship == status)
    for old, new in schema.SERVING_FIELD_ALIASES.items():
        if old in payload:
            payload[new] = payload.pop(old)
    return payload


@dataclass(frozen=True)
class WaterfallItem:
    """One bar: feature label, signed contribution, bar start position."""

    label: str
    value: float
    start: float


@dataclass(frozen=True)
class Waterfall:
    """Data for a SHAP waterfall plot, base value at the bottom accumulating
    to the final margin f(x) at the top (shap.plots.waterfall semantics)."""

    base_value: float
    fx: float
    items: tuple[WaterfallItem, ...]  # drawn bottom-to-top


def build_waterfall(
    prediction: Mapping[str, Any], max_display: int = 10
) -> Waterfall:
    """Compute waterfall bars from a /predict response (the UI's shap
    Explanation reconstruction, cobalt_streamlit.py:102-113): order features
    by |phi| descending, keep the top ``max_display - 1``, collapse the rest
    into one "N other features" bar drawn first (bottom), then accumulate from
    base_value so the last bar ends at f(x) = base + sum(phi)."""
    values = np.asarray(prediction["shap_values"], dtype=np.float64)
    features = list(prediction["features"])
    row = prediction["input_row"]
    base = float(prediction["base_value"])
    order = np.argsort(-np.abs(values))
    shown = list(order[: max_display - 1]) if len(order) > max_display - 1 else list(order)
    rest = [i for i in order if i not in set(shown)]

    # Bottom-to-top: collapsed remainder first, then ascending |phi| so the
    # largest contribution sits adjacent to f(x) at the top.
    bars: list[tuple[str, float]] = []
    if rest:
        bars.append((f"{len(rest)} other features", float(values[rest].sum())))
    for i in reversed(shown):
        x = row.get(features[i])
        label = f"{x:g} = {features[i]}" if x is not None else features[i]
        bars.append((label, float(values[i])))

    items = []
    cum = base
    for label, v in bars:
        items.append(WaterfallItem(label=label, value=v, start=cum))
        cum += v
    return Waterfall(base_value=base, fx=cum, items=tuple(items))


def render_waterfall(ax, wf: Waterfall, fmt: str = "{:+.2f}") -> None:
    """Draw a Waterfall onto a matplotlib axes — the shap.plots.waterfall
    stand-in (red = pushes toward default, blue = away)."""
    pos_color, neg_color = "#d81b60", "#1e88e5"
    for y, item in enumerate(wf.items):
        ax.barh(
            y,
            item.value,
            left=item.start,
            color=pos_color if item.value >= 0 else neg_color,
            height=0.6,
        )
        ax.text(
            item.start + item.value / 2,
            y,
            fmt.format(item.value),
            va="center",
            ha="center",
            fontsize=8,
            color="white",
        )
    ax.axvline(wf.base_value, color="#999", lw=0.8, ls="--")
    ax.set_yticks(range(len(wf.items)))
    ax.set_yticklabels([item.label for item in wf.items], fontsize=8)
    ax.set_xlabel(
        f"margin (base {wf.base_value:.2f} → f(x) {wf.fx:.2f})", fontsize=8
    )


def coerce_results_frame(records: Sequence[Mapping[str, Any]]) -> pd.DataFrame:
    """Bulk predictions → numeric DataFrame. The server serializes NaN cells
    as the string "null" (reference `fillna("null")`); the UI coerces every
    column back to numeric with NaNs allowed (cobalt_streamlit.py:142-145)."""
    df = pd.DataFrame(list(records))
    return df.apply(pd.to_numeric, errors="coerce")


def results_row_payload(df: pd.DataFrame, idx: int) -> dict[str, float]:
    """Rebuild a /predict request body from row ``idx`` of the bulk results
    frame — the data step behind the per-row SHAP explorer (the reference
    notebook's ipywidgets row slider over force plots,
    notebooks/04_model_training.ipynb cells 25-26, surfaced in the bulk UI).

    The bulk CSV already carries the canonical (aliased) feature names, so
    the payload is just the 20 contract columns of that row; int-typed
    indicator fields are rounded back from the frame's float coercion."""
    if not 0 <= idx < len(df):
        raise ValueError(f"row {idx} out of range (0..{len(df) - 1})")
    row = df.iloc[idx]
    payload: dict[str, float] = {}
    missing = []
    for name in schema.SERVING_FEATURES:
        v = row.get(name)
        if v is None or pd.isna(v):
            missing.append(name)
            continue
        payload[name] = (
            int(round(float(v)))
            if name in schema.SERVING_INT_FEATURES
            else float(v)
        )
    if missing:
        raise ValueError(f"bulk frame lacks features for row {idx}: {missing}")
    return payload


def importance_series(top_features: Sequence[Mapping[str, Any]]) -> pd.Series:
    """`/feature_importance_bulk` response → Series for the barh chart
    (cobalt_streamlit.py:163-170), highest importance first."""
    return pd.Series(
        {item["feature"]: float(item["importance"]) for item in top_features}
    ).sort_values(ascending=False)


class ServiceDegraded(RuntimeError):
    """The serving tier answered but declined to score right now — shedding
    load (429), circuit open on its store (503 circuit_open), or past the
    request deadline (504). These are operational states, not user mistakes;
    the UI shows them as a friendly "busy, try again" banner instead of a
    stack trace."""

    def __init__(self, message: str, *, reason: str, retry_after_s=None):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s


class ApiClient:
    """Minimal HTTP client for the three serving endpoints — the `requests`
    calls the reference UI makes (cobalt_streamlit.py:85,140,159), pulled out
    so tests can exercise the full wire path in-process."""

    def __init__(
        self,
        base_url: str,
        timeout: float = 30.0,
        retries: int = 3,
        backoff_s: float = 0.2,
        sleep=None,
        max_retry_after_s: float = 5.0,
    ):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.retries = retries
        self.backoff_s = backoff_s
        self.max_retry_after_s = max_retry_after_s
        self._sleep = sleep  # injectable for tests; None = time.sleep

    def _retry_after_s(self, r, attempt: int) -> float:
        """Server-suggested wait from ``Retry-After``, capped so a pessimistic
        server can't stall the UI; falls back to the client's own backoff."""
        headers = getattr(r, "headers", None) or {}
        try:
            suggested = float(headers.get("Retry-After"))
        except (TypeError, ValueError):
            suggested = self.backoff_s * (2**attempt)
        return min(max(suggested, 0.0), self.max_retry_after_s)

    @staticmethod
    def _degraded(r) -> ServiceDegraded | None:
        """Map shed/breaker/deadline statuses to `ServiceDegraded`; any other
        status is handled by raise_for_status as before."""
        status = getattr(r, "status_code", None)
        if status not in (429, 503, 504):
            return None
        try:
            body = r.json()
        except Exception:
            body = {}
        code = body.get("error") if isinstance(body, dict) else None
        if status == 429:
            return ServiceDegraded(
                "The scoring service is at capacity; please retry in a moment.",
                reason="shed",
                retry_after_s=(getattr(r, "headers", None) or {}).get(
                    "Retry-After"
                ),
            )
        if status == 503 and code == "circuit_open":
            return ServiceDegraded(
                "The model store is temporarily unavailable; "
                "the service is backing off. Please retry shortly.",
                reason="circuit_open",
                retry_after_s=(getattr(r, "headers", None) or {}).get(
                    "Retry-After"
                ),
            )
        if status == 504 or code == "deadline_exceeded":
            return ServiceDegraded(
                "The request took longer than the serving deadline; "
                "try a smaller batch or retry.",
                reason="deadline",
            )
        return None

    def _post(self, path: str, **kwargs) -> Any:
        import time

        import requests

        # Retry connection-level failures (server restarting, transient
        # network) with exponential backoff, and 429 sheds honoring the
        # server's Retry-After. Other HTTP error statuses are real answers —
        # a 422 will not get better by asking again.
        sleep = self._sleep or time.sleep
        for attempt in range(self.retries):
            try:
                r = requests.post(
                    self.base_url + path, timeout=self.timeout, **kwargs
                )
            except requests.exceptions.ConnectionError:
                if attempt == self.retries - 1:
                    raise
                sleep(self.backoff_s * (2**attempt))
                continue
            if (
                getattr(r, "status_code", None) == 429
                and attempt < self.retries - 1
            ):
                sleep(self._retry_after_s(r, attempt))
                continue
            break
        degraded = self._degraded(r)
        if degraded is not None:
            raise degraded
        r.raise_for_status()
        return r.json()

    def predict(self, payload: Mapping[str, float]) -> dict:
        return self._post("/predict", json=dict(payload))

    def predict_bulk_csv(self, filename: str, csv_bytes: bytes) -> list[dict]:
        resp = self._post(
            "/predict_bulk_csv",
            files={"file": (filename, io.BytesIO(csv_bytes), "text/csv")},
        )
        return resp["predictions"]

    def feature_importance_bulk(
        self, records: Sequence[Mapping[str, Any]]
    ) -> list[dict]:
        resp = self._post("/feature_importance_bulk", json={"data": list(records)})
        return resp["top_features"]
