"""L5 UI layer — Streamlit front-end over the serving API.

`core` holds every piece of UI data logic (payload assembly with alias
renames, the SHAP-waterfall computation replacing the shap package, bulk
result coercion, the API client) as plain testable functions; `app` is the
Streamlit render shell (reference: src/streamlit_ui/cobalt_streamlit.py).
"""

from cobalt_smart_lender_ai_tpu.ui.core import (
    ApiClient,
    Waterfall,
    build_single_payload,
    build_waterfall,
    coerce_results_frame,
    importance_series,
    render_waterfall,
)

__all__ = [
    "ApiClient",
    "Waterfall",
    "build_single_payload",
    "build_waterfall",
    "coerce_results_frame",
    "importance_series",
    "render_waterfall",
]
