"""Framework-default persistent XLA compile cache + compile telemetry.

Every long-running entrypoint (pipeline, parity, retrain, serving, benches)
calls `bootstrap_compile_cache` at startup. It does two independent things:

1. Points JAX's persistent compilation cache at a shared on-disk directory
   (via `debug.enable_persistent_compile_cache`), so identical programs are
   compiled once *ever* per machine rather than once per process. Cold
   protocol runs on the tunneled backend spend 40-400s per program in XLA;
   a warm cache turns that into a disk read.
2. Registers `jax.monitoring` listeners that fold JAX's own compile events
   into the telemetry registry as the ``cobalt_compile_*`` families, so
   `/metrics`, bench JSONs and CI can prove statements like "the second
   process start compiled nothing".

Both are idempotent and degrade to no-ops (unwritable cache dir, missing
monitoring API) rather than failing the caller. Opt out of caching entirely
with ``COBALT_COMPILE_CACHE=0`` — telemetry listeners stay on regardless,
since knowing the compile wall is useful precisely when caching is off.

Exposed metrics (all from JAX's event stream, not wall-clock guesses):

- ``cobalt_compile_total`` / ``cobalt_compile_seconds`` — backend (XLA)
  compilations and their durations.
- ``cobalt_compile_cache_hits_total`` / ``cobalt_compile_cache_misses_total``
  — persistent-cache lookups.
- ``cobalt_compile_cache_saved_seconds_total`` — compile seconds the cache
  avoided (JAX's own estimate, recorded on each hit).
"""

from __future__ import annotations

import os
from typing import Any

from cobalt_smart_lender_ai_tpu.config import CompileCacheConfig
from cobalt_smart_lender_ai_tpu.telemetry import default_registry, log_buckets

# jax.monitoring event names (stable across jax 0.4.x; verified against the
# pinned install). Durations and counters arrive on separate listener APIs.
_EV_BACKEND_COMPILE = "/jax/core/compile/backend_compile_duration"
_EV_CACHE_HIT = "/jax/compilation_cache/cache_hits"
_EV_CACHE_MISS = "/jax/compilation_cache/cache_misses"
_EV_SAVED_SECS = "/jax/compilation_cache/compile_time_saved_sec"

_DISABLE_ENV = "COBALT_COMPILE_CACHE"
_MIN_SECS_ENV = "COBALT_COMPILE_CACHE_MIN_SECS"

_bootstrapped: str | None = None
_bootstrap_done = False
_listeners_installed = False


def _metrics() -> dict[str, Any]:
    reg = default_registry()
    return {
        "compiles": reg.counter(
            "cobalt_compile_total",
            "XLA backend compilations performed by this process",
        ),
        "compile_seconds": reg.histogram(
            "cobalt_compile_seconds",
            "wall seconds per XLA backend compilation",
            buckets=log_buckets(1e-3, 600.0, per_decade=3),
        ),
        "hits": reg.counter(
            "cobalt_compile_cache_hits_total",
            "persistent compile cache hits",
        ),
        "misses": reg.counter(
            "cobalt_compile_cache_misses_total",
            "persistent compile cache misses",
        ),
        "saved_seconds": reg.counter(
            "cobalt_compile_cache_saved_seconds_total",
            "compile seconds avoided by persistent-cache hits",
        ),
    }


def install_compile_telemetry() -> bool:
    """Register jax.monitoring listeners feeding ``cobalt_compile_*``.

    Idempotent; returns False when the monitoring API is unavailable.
    Listeners are process-global and cannot be unregistered, so they write
    through to `default_registry()` at call time rather than capturing
    metric objects from a registry that tests may reset.
    """
    global _listeners_installed
    if _listeners_installed:
        return True
    try:
        from jax import monitoring
    except ImportError:  # pragma: no cover - jax always ships monitoring
        return False

    def _on_event(event: str, **kw: Any) -> None:
        m = _metrics()
        if event == _EV_CACHE_HIT:
            m["hits"].inc()
        elif event == _EV_CACHE_MISS:
            m["misses"].inc()

    def _on_duration(event: str, duration_secs: float, **kw: Any) -> None:
        m = _metrics()
        if event == _EV_BACKEND_COMPILE:
            m["compiles"].inc()
            m["compile_seconds"].observe(duration_secs)
        elif event == _EV_SAVED_SECS:
            m["saved_seconds"].inc(max(0.0, duration_secs))

    try:
        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:  # pragma: no cover - defensive: API drift
        return False
    _listeners_installed = True
    return True


def bootstrap_compile_cache(
    config: CompileCacheConfig | None = None,
) -> str | None:
    """Enable the persistent compile cache with config/env policy applied.

    The single bootstrap shared by every entrypoint (pipeline, parity,
    retrain, serve, bench, tools): one source of truth for the cache dir
    and the min-compile-time persistence threshold. Precedence:

    - ``COBALT_COMPILE_CACHE=0|false|off|no`` disables caching outright
      (telemetry listeners still install).
    - ``COBALT_COMPILE_CACHE_MIN_SECS`` overrides the persistence
      threshold (CI smoke sets 0 so millisecond CPU compiles persist).
    - ``JAX_COMPILATION_CACHE_DIR`` overrides the directory (handled by
      `debug.enable_persistent_compile_cache`).
    - Otherwise ``config`` (default `CompileCacheConfig()`) decides.

    Idempotent: the first call wins and later calls return its result, so
    library code may call this freely without clobbering an entrypoint's
    explicit configuration. Returns the cache dir in effect, or None when
    caching is disabled or the directory is unwritable.
    """
    global _bootstrapped, _bootstrap_done
    install_compile_telemetry()
    if _bootstrap_done:
        return _bootstrapped
    cfg = config or CompileCacheConfig()
    if os.environ.get(_DISABLE_ENV, "").strip().lower() in (
        "0", "false", "off", "no",
    ):
        _bootstrap_done = True
        _bootstrapped = None
        return None
    if not cfg.enabled:
        _bootstrap_done = True
        _bootstrapped = None
        return None
    min_secs = cfg.min_compile_time_secs
    env_min = os.environ.get(_MIN_SECS_ENV)
    if env_min is not None:
        try:
            min_secs = float(env_min)
        except ValueError:
            pass
    from cobalt_smart_lender_ai_tpu.debug import enable_persistent_compile_cache

    _bootstrapped = enable_persistent_compile_cache(
        cfg.cache_dir, min_compile_time_secs=min_secs
    )
    _bootstrap_done = True
    return _bootstrapped


def compile_stats() -> dict[str, float]:
    """Current ``cobalt_compile_*`` counter values, for bench JSONs and CI
    assertions ("second process: hits > 0, misses == 0, ~0s compiling")."""
    m = _metrics()
    return {
        "backend_compiles": m["compiles"].value,
        "backend_compile_seconds": m["compile_seconds"].sum,
        "cache_hits": m["hits"].value,
        "cache_misses": m["misses"].value,
        "cache_saved_seconds": m["saved_seconds"].value,
    }
