"""Scenario-report reducers: score vectors in, decision-ready numbers out.

Everything here is pure numpy over already-final score/attribution arrays —
deliberately separated from the engine so the delta math is testable on
hand-computed inputs (``tests/test_scenario.py``) and so the report shape
is owned by one module:

- `delta_stats` — per-scenario PD shift distribution vs the baseline;
- `band_migration` — the PD-band transition matrix credit reviews read
  ("how many loans crossed a pricing band under this stress");
- `shap_top_movers` — which features' mean attribution moved most;
- `scenario_drift` — PSI of each perturbed feature against the model's
  *training* sketch (``telemetry.drift``), flagging stress points that
  push the portfolio out of the distribution the model was fit on. A flag
  is a warning in the report, never a failure: an OOD stress point is
  exactly what a severe scenario is for — but the reader must know the
  scores out there are extrapolation.

`write_report` lands the final JSON under the run's versioned prefix.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

import numpy as np

from cobalt_smart_lender_ai_tpu.telemetry.drift import FeatureSketch, psi

__all__ = [
    "DEFAULT_PD_BANDS",
    "band_labels",
    "band_migration",
    "delta_stats",
    "pd_band_index",
    "scenario_drift",
    "shap_top_movers",
    "write_report",
]

#: Default PD cut points — five bands in the shape of a consumer-credit
#: grade ladder. Reports label them `<2%`, `2-8%`, `8-20%`, `20-50%`, `>=50%`.
DEFAULT_PD_BANDS: tuple[float, ...] = (0.02, 0.08, 0.20, 0.50)


def pd_band_index(
    scores: np.ndarray, bands: Sequence[float] = DEFAULT_PD_BANDS
) -> np.ndarray:
    """Band index per row: ``searchsorted`` against the cut points, so band
    ``k`` is ``[bands[k-1], bands[k])`` and the top band is unbounded."""
    return np.searchsorted(
        np.asarray(bands, dtype=np.float64),
        np.asarray(scores, dtype=np.float64),
        side="right",
    )


def band_labels(bands: Sequence[float] = DEFAULT_PD_BANDS) -> list[str]:
    edges = [f"{100.0 * b:g}%" for b in bands]
    labels = [f"<{edges[0]}"]
    labels += [f"{edges[i]}-{edges[i + 1]}" for i in range(len(edges) - 1)]
    labels.append(f">={edges[-1]}")
    return labels


def delta_stats(
    baseline: np.ndarray, scenario: np.ndarray
) -> dict[str, float]:
    """Distribution of per-loan PD shifts under the scenario."""
    deltas = np.asarray(scenario, np.float64) - np.asarray(
        baseline, np.float64
    )
    return {
        "mean": float(deltas.mean()),
        "p50": float(np.percentile(deltas, 50)),
        "p95": float(np.percentile(deltas, 95)),
        "max": float(deltas.max()),
        "min": float(deltas.min()),
        "mean_abs": float(np.abs(deltas).mean()),
    }


def band_migration(
    baseline: np.ndarray,
    scenario: np.ndarray,
    bands: Sequence[float] = DEFAULT_PD_BANDS,
) -> dict[str, Any]:
    """PD-band transition counts: ``matrix[i][j]`` is loans that moved from
    baseline band ``i`` to scenario band ``j``; ``downgraded`` counts rows
    whose band index *rose* (worse credit), ``upgraded`` the reverse."""
    n_bands = len(bands) + 1
    b = pd_band_index(baseline, bands)
    s = pd_band_index(scenario, bands)
    matrix = np.zeros((n_bands, n_bands), dtype=np.int64)
    np.add.at(matrix, (b, s), 1)
    return {
        "bands": [float(x) for x in bands],
        "labels": band_labels(bands),
        "matrix": matrix.tolist(),
        "downgraded": int((s > b).sum()),
        "upgraded": int((s < b).sum()),
        "unchanged": int((s == b).sum()),
    }


def shap_top_movers(
    scenario_phi_mean: np.ndarray,
    baseline_phi_mean: np.ndarray,
    feature_names: Sequence[str],
    *,
    top_k: int = 8,
) -> list[dict[str, float | str]]:
    """Features ranked by how far their mean SHAP attribution moved under
    the scenario — "the stress loads onto these inputs"."""
    s = np.asarray(scenario_phi_mean, np.float64)
    b = np.asarray(baseline_phi_mean, np.float64)
    shift = s - b
    order = np.argsort(-np.abs(shift))[:top_k]
    return [
        {
            "feature": str(feature_names[j]),
            "mean_phi": float(s[j]),
            "baseline_mean_phi": float(b[j]),
            "shift": float(shift[j]),
        }
        for j in order
        if shift[j] != 0.0 or s[j] != 0.0
    ]


def scenario_drift(
    training_sketch: FeatureSketch,
    X_scenario: np.ndarray,
    feature_names: Sequence[str],
    perturbed: Sequence[str],
    *,
    alert: float = 0.25,
) -> dict[str, Any]:
    """PSI of each *perturbed* feature's scenario distribution against the
    training sketch. Features above ``alert`` land in ``ood_features`` —
    the report's "this stress point is extrapolation" warning."""
    index = {name: j for j, name in enumerate(feature_names)}
    sketch_index = {
        name: j for j, name in enumerate(training_sketch.feature_names)
    }
    scores: dict[str, float] = {}
    for name in perturbed:
        if name not in index or name not in sketch_index:
            continue
        col = np.asarray(X_scenario[:, index[name]], dtype=np.float64)
        edges = training_sketch.edges[sketch_index[name]]
        counts = np.zeros_like(training_sketch.counts[sketch_index[name]])
        finite = np.isfinite(col)
        idx = np.searchsorted(edges, col[finite], side="right")
        np.add.at(counts, idx, 1)
        counts[-1] += int((~finite).sum())
        scores[name] = round(
            psi(training_sketch.counts[sketch_index[name]], counts), 6
        )
    flagged = sorted(n for n, v in scores.items() if v > alert)
    return {
        "psi": scores,
        "psi_alert": float(alert),
        "ood_features": flagged,
        "ood": bool(flagged),
    }


def write_report(
    store: Any,
    run_prefix: str,
    report: Mapping[str, Any],
) -> str:
    """Land the scenario report at ``<run_prefix>report.json``."""
    key = f"{run_prefix}report.json"
    store.put_json(key, dict(report))
    return key
