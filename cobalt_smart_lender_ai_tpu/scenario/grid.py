"""Counterfactual scenario grids — the stress-sweep DSL.

A *scenario* is an ordered list of per-feature perturbations applied to the
portfolio's feature matrix before scoring: rate shocks (additive deltas),
income/DTI multipliers, or arbitrary ``set`` overrides. A *grid* is the
cross product of perturbation axes — the standard stress-testing shape
("every rate shock x every income haircut").

Determinism is the contract everything downstream leans on:

- `ScenarioGrid.expand` enumerates the cross product in a fixed order —
  axes in declaration order, the RIGHTMOST axis varying fastest (exactly
  `itertools.product`) — so scenario index ``i`` means the same
  perturbation on every run, which is what lets the portfolio scorer's
  chunk checkpoints name work items ``(scenario, chunk)`` and resume.
- Scenario ids are derived from the perturbations (``installment+50,
  annual_incx0.9``), not from enumeration state, so reports stay
  join-able across runs and grids.
- `to_json`/`from_json` round-trip the axes losslessly, order included;
  the JSON form is what ``tools/score_portfolio.py --scenarios`` reads
  and what the scorer folds into its config fingerprint.

Perturbations are expressed on the model's *serving features* (the
post-engineering matrix), not raw application fields — a "rate shock"
against this model's 20-feature contract lands on ``installment``
(payment re-amortization is the caller's concern, not the DSL's).
"""

from __future__ import annotations

import dataclasses
import itertools
import json
from typing import Any, Iterable, Mapping, Sequence

import numpy as np

__all__ = [
    "BASELINE",
    "Perturbation",
    "Scenario",
    "ScenarioAxis",
    "ScenarioGrid",
    "feature_delta",
    "feature_multiplier",
    "feature_set",
]

#: Supported per-feature operations, in report-legend order.
OPS = ("add", "mul", "set")


def _fmt(value: float) -> str:
    return f"{value:g}"


@dataclasses.dataclass(frozen=True)
class Perturbation:
    """One feature-column edit: ``add`` a delta, ``mul`` by a factor, or
    ``set`` an override."""

    feature: str
    op: str
    value: float

    def __post_init__(self):
        if self.op not in OPS:
            raise ValueError(f"op {self.op!r} not one of {OPS}")

    @property
    def label(self) -> str:
        if self.op == "add":
            return f"{self.feature}{self.value:+g}"
        if self.op == "mul":
            return f"{self.feature}x{_fmt(self.value)}"
        return f"{self.feature}={_fmt(self.value)}"

    def apply(self, col: np.ndarray) -> np.ndarray:
        if self.op == "add":
            return col + np.float32(self.value)
        if self.op == "mul":
            return col * np.float32(self.value)
        return np.full_like(col, np.float32(self.value))

    def to_json(self) -> dict:
        return {"feature": self.feature, "op": self.op,
                "value": float(self.value)}

    @classmethod
    def from_json(cls, obj: Mapping[str, Any]) -> "Perturbation":
        return cls(str(obj["feature"]), str(obj["op"]), float(obj["value"]))


@dataclasses.dataclass(frozen=True)
class Scenario:
    """A named, ordered bundle of perturbations — one grid point."""

    scenario_id: str
    perturbations: tuple[Perturbation, ...] = ()

    @property
    def is_baseline(self) -> bool:
        return not self.perturbations

    @property
    def features(self) -> tuple[str, ...]:
        """Perturbed feature names, first-occurrence order, deduplicated."""
        seen: dict[str, None] = {}
        for p in self.perturbations:
            seen.setdefault(p.feature, None)
        return tuple(seen)

    def apply(
        self, X: np.ndarray, feature_names: Sequence[str]
    ) -> np.ndarray:
        """The perturbed copy of ``X`` (float32, input left untouched).

        Raises KeyError for a feature the model does not serve — a typo'd
        grid must fail loudly before any scoring happens."""
        index = {name: j for j, name in enumerate(feature_names)}
        out = np.array(X, dtype=np.float32, copy=True)
        for p in self.perturbations:
            if p.feature not in index:
                raise KeyError(
                    f"scenario {self.scenario_id!r} perturbs unknown "
                    f"feature {p.feature!r}"
                )
            j = index[p.feature]
            out[:, j] = p.apply(out[:, j])
        return out

    def to_json(self) -> dict:
        return {
            "id": self.scenario_id,
            "perturbations": [p.to_json() for p in self.perturbations],
        }


#: The unperturbed portfolio — always scenario 0 of a sweep.
BASELINE = Scenario("baseline", ())


@dataclasses.dataclass(frozen=True)
class ScenarioAxis:
    """One swept dimension: the same (feature, op) at each of ``values``."""

    feature: str
    op: str
    values: tuple[float, ...]

    def __post_init__(self):
        if self.op not in OPS:
            raise ValueError(f"op {self.op!r} not one of {OPS}")
        if not self.values:
            raise ValueError(f"axis over {self.feature!r} has no values")
        object.__setattr__(
            self, "values", tuple(float(v) for v in self.values)
        )

    def points(self) -> list[Perturbation]:
        return [Perturbation(self.feature, self.op, v) for v in self.values]

    def to_json(self) -> dict:
        return {"feature": self.feature, "op": self.op,
                "values": list(self.values)}

    @classmethod
    def from_json(cls, obj: Mapping[str, Any]) -> "ScenarioAxis":
        return cls(str(obj["feature"]), str(obj["op"]),
                   tuple(obj["values"]))


def feature_delta(feature: str, deltas: Iterable[float]) -> ScenarioAxis:
    """Additive sweep — the rate-shock shape (`+25, +50, +100` on the
    payment/rate feature the model actually serves)."""
    return ScenarioAxis(feature, "add", tuple(deltas))


def feature_multiplier(feature: str, factors: Iterable[float]) -> ScenarioAxis:
    """Multiplicative sweep — income haircuts, DTI inflation."""
    return ScenarioAxis(feature, "mul", tuple(factors))


def feature_set(feature: str, values: Iterable[float]) -> ScenarioAxis:
    """Override sweep — pin a feature to fixed stress points."""
    return ScenarioAxis(feature, "set", tuple(values))


class ScenarioGrid:
    """Cross product of axes, expanded in a deterministic order."""

    def __init__(self, axes: Sequence[ScenarioAxis], name: str = "grid"):
        self.axes = tuple(axes)
        self.name = name

    def __len__(self) -> int:
        n = 1
        for ax in self.axes:
            n *= len(ax.values)
        return n if self.axes else 0

    def expand(self) -> list[Scenario]:
        """Every grid point, axes in declaration order, rightmost axis
        fastest (`itertools.product` semantics). Ids are derived from the
        perturbations, so they are stable across runs by construction."""
        if not self.axes:
            return []
        out = []
        for combo in itertools.product(*(ax.points() for ax in self.axes)):
            out.append(
                Scenario(",".join(p.label for p in combo), tuple(combo))
            )
        return out

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "axes": [ax.to_json() for ax in self.axes],
        }

    def to_json_str(self) -> str:
        return json.dumps(self.to_json(), sort_keys=True)

    @classmethod
    def from_json(cls, obj: Mapping[str, Any]) -> "ScenarioGrid":
        return cls(
            [ScenarioAxis.from_json(a) for a in obj.get("axes", [])],
            name=str(obj.get("name", "grid")),
        )
