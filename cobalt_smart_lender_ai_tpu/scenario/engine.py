"""PortfolioScorer — checkpointed, mesh-sharded offline batch scoring.

The serving path (`serve/service.py`) scores what an HTTP client sends; this
engine scores what a *risk review* needs: an entire portfolio swept through a
counterfactual `ScenarioGrid`, on the same compiled programs the bulk
endpoint dispatches (`parallel.partitioner`), at row counts no client will
ever POST. Three properties define it:

**Bit-exact resumability.** Work is a flat, deterministic list of
``(scenario, chunk)`` items — scenarios in grid-expansion order (baseline
first), chunks at fixed ``[i*chunk_rows, (i+1)*chunk_rows)`` boundaries.
Every chunk's scores land in the object store as an ``.npz`` artifact and
the run's `PipelineCheckpoint` manifest advances with a ``progress``
payload after each one. Kill the process after K chunks, rerun with
``resume=True``, and the remaining items are scored into the same
artifacts: the concatenated scores are *bit-identical* to an uninterrupted
run, because each row's result depends only on its own chunk's dispatch —
the same per-row argument behind `tests/test_partitioner.py`'s
mesh-vs-single parity. The shard count is deliberately NOT part of the
resume fingerprint: a run started on one mesh may finish on another and
still produce the same bits.

**Long-run deadline semantics.** `ServeConfig`'s between-dispatch deadline
exists to shed doomed *interactive* requests; a multi-hour batch sweep must
not inherit it. ``run(deadline=None)`` is the default and means "never
abort"; a caller that genuinely wants a wall-clock budget passes an
explicit `reliability.Deadline`, which is checked cooperatively between
dispatches (a tripped budget leaves a resumable checkpoint behind).

**Observability.** Dispatches are measured into
``cobalt_portfolio_dispatch_seconds`` (a measured family the run-ledger
attribution ratio is gated on), rows/throughput into
``cobalt_portfolio_rows_total`` / ``cobalt_portfolio_rows_per_second``,
each scenario gets a tracer span, and the compiled programs register under
the ``portfolio.*`` namespace so `tools/obs_report.py` renders a sweep
like any other run.
"""

from __future__ import annotations

import hashlib
import math
import time
from typing import Any, Callable, Mapping, Sequence

import numpy as np

from cobalt_smart_lender_ai_tpu.io.artifacts import GBDTArtifact
from cobalt_smart_lender_ai_tpu.io.model_registry import ModelRegistry
from cobalt_smart_lender_ai_tpu.io.store import ObjectStore
from cobalt_smart_lender_ai_tpu.parallel.partitioner import make_partitioner
from cobalt_smart_lender_ai_tpu.reliability.checkpoint import (
    PipelineCheckpoint,
    config_fingerprint,
)
from cobalt_smart_lender_ai_tpu.scenario.grid import BASELINE, Scenario, ScenarioGrid
from cobalt_smart_lender_ai_tpu.scenario.report import (
    DEFAULT_PD_BANDS,
    band_migration,
    delta_stats,
    pd_band_index,
    scenario_drift,
    shap_top_movers,
    write_report,
)
from cobalt_smart_lender_ai_tpu.telemetry.drift import FeatureSketch
from cobalt_smart_lender_ai_tpu.telemetry.metrics import default_registry
from cobalt_smart_lender_ai_tpu.telemetry.tracing import default_tracer

__all__ = ["PortfolioInterrupted", "PortfolioScorer", "load_portfolio"]


class PortfolioInterrupted(RuntimeError):
    """Raised by the ``fail_after_chunks`` test/CI kill hook after the Kth
    freshly scored chunk — the checkpoint on disk is valid and resumable.
    Production kills (OOM, preemption) leave exactly the same state; this
    exception just makes "die mid-sweep" deterministic for parity tests."""

    def __init__(self, run_id: str, items_done: int, items_total: int):
        super().__init__(
            f"portfolio run {run_id!r} interrupted after "
            f"{items_done}/{items_total} chunks (resumable)"
        )
        self.run_id = run_id
        self.items_done = items_done
        self.items_total = items_total


def load_portfolio(
    store: ObjectStore, key: str, feature_names: Sequence[str]
) -> tuple[np.ndarray, dict]:
    """A portfolio CSV object -> float32 matrix in the model's feature
    order. Missing columns become NaN (the trees route NaN like serving
    does); extra columns are ignored. Returns ``(X, meta)`` with the raw
    bytes' md5 — the identity the resume fingerprint pins."""
    data = store.get_bytes(key)
    from cobalt_smart_lender_ai_tpu.native import read_csv

    frame = read_csv(data, engine="auto")
    missing = [n for n in feature_names if n not in frame.columns]
    n = len(frame)
    cols = []
    for name in feature_names:
        if name in frame.columns:
            cols.append(
                np.asarray(frame[name], dtype=np.float32).reshape(n)
            )
        else:
            cols.append(np.full(n, np.nan, dtype=np.float32))
    X = np.stack(cols, axis=1) if cols else np.zeros((n, 0), np.float32)
    meta = {
        "key": key,
        "rows": int(n),
        "md5": hashlib.md5(data).hexdigest(),
        "missing_features": missing,
    }
    return X, meta


def _metrics():
    reg = default_registry()
    return {
        "rows": reg.counter(
            "cobalt_portfolio_rows_total",
            "Portfolio rows scored (per scenario pass)",
        ),
        "dispatches": reg.counter(
            "cobalt_portfolio_dispatches_total",
            "Bulk program dispatches issued by the portfolio scorer",
            ("kind",),
        ),
        "seconds": reg.histogram(
            "cobalt_portfolio_dispatch_seconds",
            "Blocking dispatch wall seconds (portfolio bulk programs)",
            ("kind",),
        ),
        "scenarios": reg.counter(
            "cobalt_portfolio_scenarios_total",
            "Scenario passes completed (baseline included)",
        ),
        "rows_per_s": reg.gauge(
            "cobalt_portfolio_rows_per_second",
            "Portfolio scoring throughput over the current run",
        ),
        "resumed": reg.counter(
            "cobalt_portfolio_chunks_resumed_total",
            "Chunks skipped on resume (already checkpointed)",
        ),
    }


class PortfolioScorer:
    """Stream a portfolio (+ scenario grid) through the partitioner's bulk
    margin/SHAP programs in fixed-size chunks, checkpointing every chunk.

    One instance compiles the programs once (for the padded chunk shape)
    and can `run` any number of sweeps against the same model."""

    def __init__(
        self,
        artifact: GBDTArtifact,
        store: ObjectStore,
        *,
        shards: int = 1,
        chunk_rows: int = 2048,
        compute_shap: bool = True,
        pd_bands: Sequence[float] = DEFAULT_PD_BANDS,
        training_sketch: FeatureSketch | None = None,
        psi_alert: float = 0.25,
        model_info: Mapping[str, Any] | None = None,
        prefix: str = "scenario_runs/",
        checkpoint_prefix: str = "checkpoints/",
        devices: Sequence[Any] | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if chunk_rows < 1:
            raise ValueError(f"chunk_rows must be >= 1, got {chunk_rows}")
        self.artifact = artifact
        self.store = store
        self.chunk_rows = int(chunk_rows)
        self.compute_shap = bool(compute_shap)
        self.pd_bands = tuple(float(b) for b in pd_bands)
        self.training_sketch = training_sketch
        self.psi_alert = float(psi_alert)
        self.model_info = dict(model_info or {})
        self.prefix = prefix if prefix.endswith("/") else prefix + "/"
        self._ckpt = PipelineCheckpoint(store, prefix=checkpoint_prefix)
        self._clock = clock
        self.partitioner = make_partitioner(
            shards, devices=devices, kind_prefix="portfolio"
        )
        # One compiled shape for the whole run: every chunk is zero-padded
        # to `padded_rows` (power-of-two rows per shard, like the serving
        # buckets) so a sweep is N dispatches of ONE executable, not a
        # recompile per ragged tail. Padding rows score garbage that is
        # sliced off before anything downstream sees it.
        n_shards = self.partitioner.n_shards
        per_shard = math.ceil(self.chunk_rows / n_shards)
        bucket = 1 << max(per_shard - 1, 0).bit_length()
        self.padded_rows = bucket * n_shards
        self._margin_fn: Callable | None = None
        self._shap_fn: Callable | None = None

    # -- construction from the registry ------------------------------------

    @classmethod
    def from_registry(
        cls,
        store: ObjectStore,
        *,
        model_name: str = "gbdt",
        channel: str = "latest",
        registry_prefix: str = "registry",
        **kwargs: Any,
    ) -> "PortfolioScorer":
        """Resolve the model by registry channel and inherit its provenance:
        the version/md5 land in the report's model block, and the training
        `FeatureSketch` (when the publisher recorded one) becomes the PSI
        baseline for OOD stress-point flagging."""
        registry = ModelRegistry(store, prefix=registry_prefix)
        mv = registry.channel_record(model_name, channel)
        if mv is None:
            raise LookupError(
                f"model registry has no {channel!r} channel for "
                f"{model_name!r} under {registry_prefix!r}"
            )
        artifact = GBDTArtifact.load(store, mv.key)
        sketch = None
        raw = mv.provenance.get("feature_sketch")
        if raw:
            sketch = FeatureSketch.from_json(raw)
        model_info = {
            "name": mv.name,
            "version": mv.version,
            "channel": channel,
            "key": mv.key,
            "md5": mv.md5,
            "kind": mv.kind,
            "config_hash": mv.provenance.get("config_hash"),
            "dataset_md5": mv.provenance.get("dataset_md5"),
        }
        kwargs.setdefault("training_sketch", sketch)
        kwargs.setdefault("model_info", model_info)
        return cls(artifact, store, **kwargs)

    # -- plumbing -----------------------------------------------------------

    def _compile(self) -> float:
        if self._margin_fn is not None:
            return 0.0
        t0 = self._clock()
        n_features = len(self.artifact.feature_names)
        self._margin_fn = self.partitioner.compile_margin(
            self.artifact.forest, n_features, self.padded_rows
        )
        if self.compute_shap:
            self._shap_fn = self.partitioner.compile_shap(
                self.artifact.forest, n_features, self.padded_rows
            )
        return self._clock() - t0

    def _model_md5(self) -> str:
        md5 = self.model_info.get("md5")
        if md5:
            return str(md5)
        return hashlib.md5(self.artifact.to_bytes()).hexdigest()

    def _fingerprint(self, portfolio_md5: str, n_rows: int, grid_json: dict) -> str:
        # The shard count is intentionally absent: sharding the row axis
        # cannot change any row's bits (partitioner contract), so a resume
        # on a different mesh must reuse the same checkpoint. The kernel
        # mode IS present: fused f32 margins are bit-identical to the
        # reference, but SHAP chunk bytes may differ at float tolerance, so
        # a resume never mixes chunks from two kernel implementations.
        from cobalt_smart_lender_ai_tpu.ops.score_pallas import kernel_mode

        return config_fingerprint(
            {
                "model_md5": self._model_md5(),
                "features": list(self.artifact.feature_names),
                "portfolio_md5": portfolio_md5,
                "rows": int(n_rows),
                "chunk_rows": self.chunk_rows,
                "grid": grid_json,
                "pd_bands": list(self.pd_bands),
                "shap": self.compute_shap,
                "kernel": kernel_mode(),
            }
        )

    def _chunk_key(self, run_prefix: str, si: int, ci: int) -> str:
        return f"{run_prefix}chunks/s{si:03d}_c{ci:05d}.npz"

    def _verified_resume_point(
        self, stage: str, fingerprint: str, chunk_keys: Sequence[str]
    ) -> int:
        """How many leading work items can be trusted: the manifest's
        fingerprint must match and every completed chunk artifact must
        still hash to its pinned md5 — otherwise start from zero."""
        manifest = self._ckpt.load(stage)
        if manifest is None or manifest.get("fingerprint") != fingerprint:
            return 0
        progress = manifest.get("progress") or {}
        done = int(progress.get("items_done", 0))
        done = max(0, min(done, len(chunk_keys)))
        pointers = manifest.get("pointers", {})
        for key in chunk_keys[:done]:
            ptr = pointers.get(key)
            if not ptr:
                return 0
            try:
                data = self.store.get_bytes(key)
            except Exception:
                return 0
            if (
                hashlib.md5(data).hexdigest() != ptr.get("md5")
                or len(data) != ptr.get("size")
            ):
                return 0
        return done

    @staticmethod
    def _sigmoid(margins: np.ndarray) -> np.ndarray:
        # Same expression as the serving path, so engine scores are
        # bit-comparable with predict_proba outputs.
        with np.errstate(over="ignore"):
            return 1.0 / (1.0 + np.exp(-margins))

    # -- the sweep ----------------------------------------------------------

    def run(
        self,
        X: np.ndarray,
        grid: ScenarioGrid | None = None,
        *,
        run_id: str,
        resume: bool = False,
        deadline: Any = None,
        fail_after_chunks: int | None = None,
        ledger: Any = None,
        portfolio_meta: Mapping[str, Any] | None = None,
    ) -> dict:
        """Score the portfolio under the baseline + every grid scenario.

        ``deadline=None`` (the default) means a batch run never 504s itself;
        an explicit `Deadline` is honored cooperatively between dispatches.
        ``resume=True`` continues a killed run with the same ``run_id``
        (and an unchanged model/portfolio/grid — anything else restarts).
        Returns the scenario report (also written to the store)."""
        metrics = _metrics()
        tracer = default_tracer()
        X = np.ascontiguousarray(X, dtype=np.float32)
        n_rows, n_features = X.shape
        if n_features != len(self.artifact.feature_names):
            raise ValueError(
                f"portfolio has {n_features} features, model expects "
                f"{len(self.artifact.feature_names)}"
            )
        if n_rows == 0:
            raise ValueError("portfolio is empty")

        grid_json = grid.to_json() if grid is not None else {"axes": []}
        scenarios: list[Scenario] = [BASELINE] + (
            grid.expand() if grid is not None else []
        )
        n_chunks = math.ceil(n_rows / self.chunk_rows)
        items = [
            (si, ci)
            for si in range(len(scenarios))
            for ci in range(n_chunks)
        ]
        run_prefix = f"{self.prefix}{run_id}/"
        chunk_keys = [self._chunk_key(run_prefix, si, ci) for si, ci in items]

        portfolio_md5 = hashlib.md5(X.tobytes()).hexdigest()
        fingerprint = self._fingerprint(portfolio_md5, n_rows, grid_json)
        stage = f"portfolio/{run_id}"

        timings: dict[str, float] = {}
        timings["compile"] = self._compile()

        done = 0
        if resume:
            done = self._verified_resume_point(stage, fingerprint, chunk_keys)
            if done:
                metrics["resumed"].inc(done)

        t_score0 = self._clock()
        rows_scored = 0
        fresh = 0
        k = 0
        for si, scenario in enumerate(scenarios):
            with tracer.span(
                "portfolio.scenario",
                scenario=scenario.scenario_id,
                rows=n_rows,
                chunks=n_chunks,
            ):
                for ci in range(n_chunks):
                    if k < done:
                        k += 1
                        continue
                    if deadline is not None:
                        deadline.check(
                            f"portfolio scenario {scenario.scenario_id!r} "
                            f"chunk {ci}"
                        )
                    lo = ci * self.chunk_rows
                    hi = min(n_rows, lo + self.chunk_rows)
                    chunk = scenario.apply(
                        X[lo:hi], self.artifact.feature_names
                    )
                    padded = np.zeros(
                        (self.padded_rows, n_features), dtype=np.float32
                    )
                    padded[: hi - lo] = chunk
                    t0 = time.perf_counter()
                    out = self._margin_fn(padded)
                    dt = time.perf_counter() - t0
                    metrics["seconds"].labels("margin").observe(dt)
                    metrics["dispatches"].labels("margin").inc()
                    margins = np.asarray(out)[: hi - lo]
                    arrays: dict[str, np.ndarray] = {
                        "scores": self._sigmoid(margins),
                        "n": np.asarray(hi - lo, dtype=np.int64),
                    }
                    if self._shap_fn is not None:
                        t0 = time.perf_counter()
                        phis, base = self._shap_fn(padded)
                        dt = time.perf_counter() - t0
                        metrics["seconds"].labels("shap").observe(dt)
                        metrics["dispatches"].labels("shap").inc()
                        phis = np.asarray(phis)[: hi - lo]
                        arrays["phi_sum"] = phis.sum(
                            axis=0, dtype=np.float64
                        )
                        arrays["base"] = np.asarray(base)
                    key = chunk_keys[k]
                    self.store.save_arrays(key, arrays)
                    self._ckpt.advance(
                        stage,
                        fingerprint=fingerprint,
                        new_outputs=[key],
                        progress={
                            "items_done": k + 1,
                            "items_total": len(items),
                            "scenario": scenario.scenario_id,
                            "chunk": ci,
                            "rows_done": rows_scored + (hi - lo),
                            "chunk_rows": self.chunk_rows,
                            "portfolio_md5": portfolio_md5,
                        },
                        extra={"run_prefix": run_prefix},
                    )
                    rows_scored += hi - lo
                    fresh += 1
                    k += 1
                    metrics["rows"].inc(hi - lo)
                    elapsed = self._clock() - t_score0
                    if elapsed > 0:
                        metrics["rows_per_s"].set(rows_scored / elapsed)
                    if (
                        fail_after_chunks is not None
                        and fresh >= fail_after_chunks
                        and k < len(items)
                    ):
                        raise PortfolioInterrupted(run_id, k, len(items))
            metrics["scenarios"].inc()
        timings["score"] = self._clock() - t_score0

        report = self._reduce(
            X,
            scenarios,
            grid_json,
            run_id=run_id,
            run_prefix=run_prefix,
            n_chunks=n_chunks,
            chunks_resumed=done,
            chunks_scored=fresh,
            rows_scored=rows_scored,
            portfolio_md5=portfolio_md5,
            portfolio_meta=portfolio_meta,
            fingerprint=fingerprint,
            timings=timings,
            tracer=tracer,
        )

        # Final manifest: progress complete + the report pinned alongside
        # the chunks, so `--resume` of a finished run is pure reduce.
        self._ckpt.advance(
            stage,
            fingerprint=fingerprint,
            new_outputs=[report["keys"]["report"]],
            progress={
                "items_done": len(items),
                "items_total": len(items),
                "complete": True,
                "rows_done": n_rows * len(scenarios),
                "chunk_rows": self.chunk_rows,
                "portfolio_md5": portfolio_md5,
            },
            extra={"run_prefix": run_prefix},
        )

        if ledger is not None:
            ledger.add_stages(timings)
            ledger.set("scenario_report", _slim(report))
        return report

    # -- reduction ----------------------------------------------------------

    def _load_scenario(
        self, run_prefix: str, si: int, n_chunks: int
    ) -> tuple[np.ndarray, np.ndarray | None]:
        scores, phi_sum = [], None
        for ci in range(n_chunks):
            arrays = self.store.load_arrays(
                self._chunk_key(run_prefix, si, ci)
            )
            scores.append(arrays["scores"])
            if "phi_sum" in arrays:
                phi_sum = (
                    arrays["phi_sum"]
                    if phi_sum is None
                    else phi_sum + arrays["phi_sum"]
                )
        return np.concatenate(scores), phi_sum

    def _reduce(
        self,
        X: np.ndarray,
        scenarios: list[Scenario],
        grid_json: dict,
        *,
        run_id: str,
        run_prefix: str,
        n_chunks: int,
        chunks_resumed: int,
        chunks_scored: int,
        rows_scored: int,
        portfolio_md5: str,
        portfolio_meta: Mapping[str, Any] | None,
        fingerprint: str,
        timings: dict[str, float],
        tracer: Any,
    ) -> dict:
        t0 = self._clock()
        n_rows = X.shape[0]
        feature_names = list(self.artifact.feature_names)
        with tracer.span("portfolio.reduce", scenarios=len(scenarios)):
            base_scores, base_phi = self._load_scenario(
                run_prefix, 0, n_chunks
            )
            base_phi_mean = (
                None if base_phi is None else base_phi / float(n_rows)
            )
            base_bands = np.bincount(
                pd_band_index(base_scores, self.pd_bands),
                minlength=len(self.pd_bands) + 1,
            )
            scores_keys = {"baseline": f"{run_prefix}scores/baseline.npy"}
            self.store.save_array(scores_keys["baseline"], base_scores)

            scenario_blocks = []
            for si in range(1, len(scenarios)):
                scenario = scenarios[si]
                scores, phi = self._load_scenario(run_prefix, si, n_chunks)
                skey = f"{run_prefix}scores/s{si:03d}.npy"
                dkey = f"{run_prefix}deltas/s{si:03d}.npy"
                self.store.save_array(skey, scores)
                self.store.save_array(
                    dkey,
                    np.asarray(scores, np.float64)
                    - np.asarray(base_scores, np.float64),
                )
                scores_keys[scenario.scenario_id] = skey
                block: dict[str, Any] = {
                    "id": scenario.scenario_id,
                    "index": si,
                    "perturbations": [
                        p.to_json() for p in scenario.perturbations
                    ],
                    "scores_key": skey,
                    "deltas_key": dkey,
                    "mean_pd": float(np.mean(scores)),
                    "delta": delta_stats(base_scores, scores),
                    "migration": band_migration(
                        base_scores, scores, self.pd_bands
                    ),
                }
                if phi is not None and base_phi_mean is not None:
                    block["shap_top"] = shap_top_movers(
                        phi / float(n_rows), base_phi_mean, feature_names
                    )
                if self.training_sketch is not None:
                    block["drift"] = scenario_drift(
                        self.training_sketch,
                        scenario.apply(X, feature_names),
                        feature_names,
                        scenario.features,
                        alert=self.psi_alert,
                    )
                scenario_blocks.append(block)
        timings["reduce"] = self._clock() - t0

        t0 = self._clock()
        baseline_block: dict[str, Any] = {
            "scores_key": scores_keys["baseline"],
            "mean_pd": float(np.mean(base_scores)),
            "p95_pd": float(np.percentile(base_scores, 95)),
            "band_counts": base_bands.tolist(),
        }
        if base_phi_mean is not None:
            baseline_block["mean_phi"] = {
                name: float(v)
                for name, v in zip(feature_names, base_phi_mean)
            }
        drift_note = None
        if self.training_sketch is None:
            drift_note = (
                "no training FeatureSketch available (model published "
                "without provenance sketch); PSI checks skipped"
            )
        report: dict[str, Any] = {
            "run_id": run_id,
            "created_unix": round(time.time(), 3),
            "fingerprint": fingerprint,
            "model": self.model_info
            or {"md5": self._model_md5(), "channel": "direct"},
            "portfolio": {
                "rows": int(n_rows),
                "md5": portfolio_md5,
                **dict(portfolio_meta or {}),
            },
            "grid": grid_json,
            "partitioner": self.partitioner.describe(),
            "chunk_rows": self.chunk_rows,
            "padded_rows": self.padded_rows,
            "n_chunks": int(n_chunks),
            "pd_bands": list(self.pd_bands),
            "baseline": baseline_block,
            "scenarios": scenario_blocks,
            "resume": {
                "chunks_total": len(scenarios) * n_chunks,
                "chunks_resumed": int(chunks_resumed),
                "chunks_scored": int(chunks_scored),
            },
            "keys": {
                "report": f"{run_prefix}report.json",
                "scores": scores_keys,
            },
        }
        if drift_note:
            report["drift_note"] = drift_note
        score_s = timings.get("score", 0.0)
        report["telemetry"] = {
            "rows_scored": int(rows_scored),
            "score_seconds": round(score_s, 6),
            "rows_per_second": (
                None if score_s <= 0 else round(rows_scored / score_s, 1)
            ),
        }
        write_report(self.store, run_prefix, report)
        timings["write"] = self._clock() - t0
        report["stages"] = {
            k: round(v, 6) for k, v in timings.items()
        }
        return report


def _slim(report: Mapping[str, Any]) -> dict:
    """The ledger-embedded view: everything except per-scenario arrays."""
    out = {
        k: report[k]
        for k in (
            "run_id",
            "fingerprint",
            "model",
            "portfolio",
            "grid",
            "partitioner",
            "chunk_rows",
            "n_chunks",
            "resume",
            "telemetry",
            "keys",
        )
        if k in report
    }
    out["scenarios"] = [
        {
            "id": b["id"],
            "mean_pd": b["mean_pd"],
            "delta_mean": b["delta"]["mean"],
            "downgraded": b["migration"]["downgraded"],
            "upgraded": b["migration"]["upgraded"],
            "ood_features": (b.get("drift") or {}).get("ood_features", []),
        }
        for b in report.get("scenarios", [])
    ]
    return out
