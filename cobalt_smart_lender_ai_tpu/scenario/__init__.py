"""Portfolio & stress scenarios — offline batch scoring over the mesh
(README "Portfolio & stress scenarios").

The serving stack answers "score this applicant now"; this package answers
the risk-review question "what happens to the *whole book* under stress":

- `grid` — the `ScenarioGrid` counterfactual DSL (rate shocks, income/DTI
  multipliers, arbitrary per-feature deltas, cross-product stress grids)
  with deterministic expansion ordering;
- `engine` — `PortfolioScorer`, chunked mesh-sharded scoring with
  chunk-level checkpoint/resume (kill after K chunks, resume, bit-identical
  scores) on the same compiled programs live serving dispatches;
- `report` — pure reducers (PD deltas, band-migration matrices, SHAP
  movers, PSI OOD flags) and the JSON report writer.

Surfaced as ``tools/score_portfolio.py``.
"""

from cobalt_smart_lender_ai_tpu.scenario.engine import (
    PortfolioInterrupted,
    PortfolioScorer,
    load_portfolio,
)
from cobalt_smart_lender_ai_tpu.scenario.grid import (
    BASELINE,
    Perturbation,
    Scenario,
    ScenarioAxis,
    ScenarioGrid,
    feature_delta,
    feature_multiplier,
    feature_set,
)
from cobalt_smart_lender_ai_tpu.scenario.report import (
    DEFAULT_PD_BANDS,
    band_labels,
    band_migration,
    delta_stats,
    pd_band_index,
    scenario_drift,
    shap_top_movers,
    write_report,
)

__all__ = [
    "BASELINE",
    "DEFAULT_PD_BANDS",
    "Perturbation",
    "PortfolioInterrupted",
    "PortfolioScorer",
    "Scenario",
    "ScenarioAxis",
    "ScenarioGrid",
    "band_labels",
    "band_migration",
    "delta_stats",
    "feature_delta",
    "feature_multiplier",
    "feature_set",
    "load_portfolio",
    "pd_band_index",
    "scenario_drift",
    "shap_top_movers",
    "write_report",
]
