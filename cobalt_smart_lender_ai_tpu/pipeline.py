"""End-to-end training pipeline — the composition the reference runs as
`model_tree_train_test.main()` (`model_tree_train_test.py:73-242`) plus the
two preprocessing CLIs it depends on (`clean_data.py:161-174`,
`feature_engineering.py:186-204`):

    raw frame -> clean -> engineer -> leakage drop -> hashed split (seed 22)
    -> scale_pos_weight -> RFE to 20 features -> 20x3 randomized search on
    the device mesh -> final eval -> metrics.json + persisted artifacts.

Differences from the reference are the TPU-native ones: every model fit runs
jitted on the mesh (RFE refits reuse one compiled program; the search is one
fan-out dispatch, not a joblib pool), the split is a stateless row hash, and
artifacts are self-describing npz files instead of pickles.

Stages round-trip through the `ObjectStore` when one is given (the
reference's S3 glue, SURVEY §1), so each stage's output is inspectable and
restartable; with no store the pipeline runs purely in memory.

Entry point::

    python -m cobalt_smart_lender_ai_tpu.pipeline --store artifacts \
        --synthetic-rows 100000
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any

import jax
import numpy as np
import pandas as pd

from cobalt_smart_lender_ai_tpu.config import (
    PipelineConfig,
    RFEConfig,
    TuneConfig,
)
from cobalt_smart_lender_ai_tpu.data.clean import clean_raw_frame
from cobalt_smart_lender_ai_tpu.data.features import (
    drop_training_leakage,
    engineer_features,
    prepare_cleaned_frame,
)
from cobalt_smart_lender_ai_tpu.data.split import train_test_split_hashed
from cobalt_smart_lender_ai_tpu.io import GBDTArtifact, ObjectStore, save_metrics
from cobalt_smart_lender_ai_tpu.ops.metrics import (
    binary_classification_report,
    roc_auc,
)
from cobalt_smart_lender_ai_tpu.parallel.mesh import make_mesh
from cobalt_smart_lender_ai_tpu.parallel.rfe import rfe_select
from cobalt_smart_lender_ai_tpu.parallel.tune import SearchResult, randomized_search

logger = logging.getLogger("cobalt_smart_lender_ai_tpu.pipeline")


@dataclasses.dataclass
class PipelineResult:
    """Everything `main()` logs/persists (model_tree_train_test.py:159-242)."""

    selected_features: tuple[str, ...]
    best_params: dict[str, Any]
    cv_auc: float
    test_auc: float
    metrics: dict[str, Any]
    artifact: GBDTArtifact
    search: SearchResult
    scale_pos_weight: float
    timings: dict[str, float]


def run_pipeline(
    config: PipelineConfig | None = None,
    raw: pd.DataFrame | None = None,
    store: ObjectStore | None = None,
    mesh=None,
    model_key: str | None = None,
) -> PipelineResult:
    """Run the full production path. ``raw`` takes precedence; otherwise the
    frame is loaded from ``store``'s `raw_key` (the reference loads its input
    CSV from S3, model_tree_train_test.py:77)."""
    cfg = config or PipelineConfig()
    timings: dict[str, float] = {}

    def tick(name: str, t0: float) -> float:
        timings[name] = round(time.time() - t0, 3)
        t = time.time()
        logger.info("%s done in %.2fs", name, timings[name])
        return t

    t = time.time()
    if raw is None:
        if store is None:
            raise ValueError("provide a raw frame or an object store")
        raw = store.load_frame(cfg.data.raw_key)
    logger.info("raw frame: %d rows x %d cols", len(raw), raw.shape[1])

    # --- L1 cleaning (clean_data.py:87-158) ---------------------------------
    cleaned, report = clean_raw_frame(
        raw, null_col_threshold=cfg.data.null_col_threshold
    )
    logger.info(
        "cleaned: %d rows, dropped %d null-heavy cols, %d dupes",
        report.n_rows_out,
        len(report.dropped_null_columns),
        report.n_duplicates_removed,
    )
    if store is not None and cfg.save_intermediate:
        store.save_frame(cfg.data.cleaned_key, cleaned)
    t = tick("clean", t)

    # --- L2 features (feature_engineering.py:44-184) ------------------------
    prepared = prepare_cleaned_frame(
        cleaned, row_null_allowance=cfg.data.row_null_allowance
    )
    tree_ff, nn_ff, plan = engineer_features(prepared)
    if store is not None and cfg.save_intermediate:
        store.save_frame(cfg.data.tree_key, tree_ff.to_pandas())
        store.save_frame(cfg.data.nn_key, nn_ff.to_pandas())
    t = tick("engineer", t)

    # --- L3 training (model_tree_train_test.py:73-242) ----------------------
    ff = drop_training_leakage(tree_ff)
    X_train, X_test, y_train, y_test = train_test_split_hashed(
        ff.X, ff.y, test_fraction=cfg.data.test_fraction, seed=cfg.data.split_seed
    )
    n_pos = float(jax.numpy.sum(y_train))  # scalar fetch, not the vector
    spw = (float(X_train.shape[0]) - n_pos) / max(n_pos, 1.0)
    logger.info(
        "split: %d train / %d test, scale_pos_weight=%.3f",
        X_train.shape[0],
        X_test.shape[0],
        spw,
    )
    mesh = mesh or make_mesh(cfg.mesh)

    rfe_cfg = dataclasses.replace(cfg.rfe, scale_pos_weight=spw)
    rfe = rfe_select(X_train, y_train, rfe_cfg, mesh=mesh)
    selected = tuple(
        n for n, keep in zip(ff.feature_names, rfe.support_) if keep
    )
    logger.info("RFE selected %d features: %s", len(selected), selected)
    t = tick("rfe", t)

    # Materialize the selected columns once (the reference trains its final
    # model on the 20-column frame); the search then fans out over the mesh.
    # Column-take stays on device — fetching the full matrices to host costs
    # ~minutes at 2.3M rows over a tunneled TPU.
    sel_idx = np.flatnonzero(rfe.support_)
    Xtr_sel = jax.numpy.take(X_train, jax.numpy.asarray(sel_idx), axis=1)
    Xte_sel = jax.numpy.take(X_test, jax.numpy.asarray(sel_idx), axis=1)
    base = cfg.gbdt.replace(scale_pos_weight=spw)
    search = randomized_search(
        Xtr_sel, y_train, base, cfg.tune, mesh  # callee fetches y once
    )
    logger.info(
        "search best CV AUC %.4f with %s", search.best_score_, search.best_params_
    )
    t = tick("search", t)

    # --- final eval (model_tree_train_test.py:171-179) ----------------------
    est = search.best_estimator_
    margin_test = est.predict_margin(Xte_sel)
    y_test_f = jax.numpy.asarray(y_test, jax.numpy.float32)
    test_auc = float(roc_auc(y_test_f, margin_test))
    y_pred = est.predict(Xte_sel)
    report_dict = binary_classification_report(y_test_f, y_pred)
    metrics = {
        # the reference's exact metrics.json schema
        # (model_tree_train_test.py:235-242)
        "auc": test_auc,
        "classification_report": report_dict,
        "best_params": search.best_params_,
    }
    logger.info("test ROC-AUC %.4f", test_auc)
    t = tick("eval", t)

    artifact = GBDTArtifact(
        forest=est.forest,
        bin_spec=est.bin_spec,
        feature_names=selected,
        plan=plan,
        config={
            "best_params": search.best_params_,
            "scale_pos_weight": spw,
            "split_seed": cfg.data.split_seed,
        },
        metrics=metrics,
    )
    if store is not None:
        key = model_key or cfg.serve.model_key
        artifact.save(store, key)
        save_metrics(store, key + ".metrics.json", metrics)
        # Plot artifacts (model_tree_train_test.py:184-210): confusion-matrix
        # heatmap + top-20 gain-importance bars, as PNG objects next to the
        # model the way the reference uploads them to S3. matplotlib is an
        # optional extra; without it the pipeline still completes.
        try:
            from cobalt_smart_lender_ai_tpu.io.plots import (
                render_confusion_matrix,
                render_feature_importance,
            )
            from cobalt_smart_lender_ai_tpu.models.gbdt import gain_importances
            from cobalt_smart_lender_ai_tpu.ops.metrics import confusion_matrix

            cm = np.asarray(confusion_matrix(y_test_f, y_pred))
            gains, _ = gain_importances(est.forest, len(selected))
            store.put_bytes(
                key + ".confusion_matrix.png", render_confusion_matrix(cm)
            )
            store.put_bytes(
                key + ".feature_importance.png",
                render_feature_importance(selected, np.asarray(gains)),
            )
        except Exception as exc:  # pragma: no cover - plots are optional
            # The PNGs are optional artifacts; a rendering failure (missing
            # matplotlib, headless-backend/font trouble) must not abort a run
            # whose expensive search/train already succeeded.
            logger.warning("plot artifacts skipped (%s)", exc)
        logger.info("artifact persisted at %s", key)

    return PipelineResult(
        selected_features=selected,
        best_params=search.best_params_,
        cv_auc=float(search.best_score_),
        test_auc=test_auc,
        metrics=metrics,
        artifact=artifact,
        search=search,
        scale_pos_weight=spw,
        timings=timings,
    )


def main(argv=None) -> PipelineResult:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--store", default=None, help="object-store URI")
    parser.add_argument(
        "--synthetic-rows",
        type=int,
        default=0,
        help="generate a synthetic raw table instead of loading raw_key",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--quick",
        action="store_true",
        help="slim search/RFE profile (4x2 search, RFE step 20) — minutes "
        "instead of the reference's full 20x3 protocol, for demos and smoke "
        "runs; quality lands in the same AUC regime",
    )
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s [%(levelname)s] %(message)s"
    )
    from cobalt_smart_lender_ai_tpu.debug import enable_persistent_compile_cache

    enable_persistent_compile_cache()
    cfg = PipelineConfig()
    if args.quick:
        cfg = dataclasses.replace(
            cfg,
            rfe=RFEConfig(n_select=20, step=20, n_estimators=20, max_depth=3),
            tune=TuneConfig(
                n_iter=4,
                cv_folds=2,
                chunk_trees="auto",
                param_space={
                    "n_estimators": (150, 300),
                    "max_depth": (3,),
                    "learning_rate": (0.05, 0.1),
                    "subsample": (0.8,),
                },
            ),
        )
    raw = None
    if args.synthetic_rows:
        from cobalt_smart_lender_ai_tpu.data.synthetic import (
            synthetic_lendingclub_frame,
        )

        raw = synthetic_lendingclub_frame(args.synthetic_rows, seed=args.seed)
    store = ObjectStore(args.store) if args.store else None
    result = run_pipeline(cfg, raw=raw, store=store)
    print(
        {
            "test_auc": result.test_auc,
            "cv_auc": result.cv_auc,
            "best_params": result.best_params,
            "n_selected": len(result.selected_features),
            "timings": result.timings,
        }
    )
    return result


if __name__ == "__main__":
    main()
