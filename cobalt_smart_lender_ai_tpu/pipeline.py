"""End-to-end training pipeline — the composition the reference runs as
`model_tree_train_test.main()` (`model_tree_train_test.py:73-242`) plus the
two preprocessing CLIs it depends on (`clean_data.py:161-174`,
`feature_engineering.py:186-204`):

    raw frame -> clean -> engineer -> leakage drop -> hashed split (seed 22)
    -> scale_pos_weight -> RFE to 20 features -> 20x3 randomized search on
    the device mesh -> final eval -> metrics.json + persisted artifacts.

Differences from the reference are the TPU-native ones: every model fit runs
jitted on the mesh (RFE refits reuse one compiled program; the search is one
fan-out dispatch, not a joblib pool), the split is a stateless row hash, and
artifacts are self-describing npz files instead of pickles.

Stages round-trip through the `ObjectStore` when one is given (the
reference's S3 glue, SURVEY §1), so each stage's output is inspectable and
restartable; with no store the pipeline runs purely in memory.

Resilience (`reliability/`): the store is wrapped in a `ResilientStore`
(bounded retry with backoff on transient faults, content-pointer
verification on reads), and after each stage a manifest pins the stage's
outputs (md5+size) and its config fingerprint. A run started with
``resume=True`` (CLI ``--resume``) skips every leading stage whose manifest
still validates — a crash mid-RFE or mid-search restarts from the last good
stage instead of from raw data; a changed config invalidates exactly the
stages that depend on it. `PipelineResult.stages_run`/``stages_skipped``
record what actually executed.

Entry point::

    python -m cobalt_smart_lender_ai_tpu.pipeline --store artifacts \
        --synthetic-rows 100000
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any

import jax
import numpy as np
import pandas as pd

from cobalt_smart_lender_ai_tpu.config import (
    PipelineConfig,
    RFEConfig,
    TuneConfig,
)
from cobalt_smart_lender_ai_tpu.compilecache import bootstrap_compile_cache
from cobalt_smart_lender_ai_tpu.data import schema
from cobalt_smart_lender_ai_tpu.data.clean import clean_raw_frame
from cobalt_smart_lender_ai_tpu.data.device_pipeline import (
    run_device_ingest,
    tokenize_raw_frame,
)
from cobalt_smart_lender_ai_tpu.data.features import (
    FeatureFrame,
    drop_training_leakage,
    engineer_features,
    prepare_cleaned_frame,
)
from cobalt_smart_lender_ai_tpu.parallel.partitioner import make_partitioner
from cobalt_smart_lender_ai_tpu.data.split import train_test_split_hashed
from cobalt_smart_lender_ai_tpu.io import (
    GBDTArtifact,
    ObjectStore,
    plan_from_json,
    plan_to_json,
    save_metrics,
)
from cobalt_smart_lender_ai_tpu.ops.metrics import (
    binary_classification_report,
    roc_auc,
)
from cobalt_smart_lender_ai_tpu.parallel.mesh import make_mesh
from cobalt_smart_lender_ai_tpu.parallel.rfe import rfe_select
from cobalt_smart_lender_ai_tpu.parallel.tune import SearchResult, randomized_search
from cobalt_smart_lender_ai_tpu.reliability import (
    PipelineCheckpoint,
    ResilientStore,
    config_fingerprint,
    policy_from_config,
)
from cobalt_smart_lender_ai_tpu.telemetry import (
    default_registry,
    log_buckets,
    record_span,
    span,
)

logger = logging.getLogger("cobalt_smart_lender_ai_tpu.pipeline")

#: Stage wall times land in the process-wide registry so a bench or notebook
#: run can dump them alongside the headline (`telemetry.snapshot()`). Stages
#: run seconds-to-minutes, so the bounds run well past the latency defaults.
_STAGE_SECONDS = default_registry().histogram(
    "cobalt_pipeline_stage_seconds",
    "wall time per pipeline stage (clean/engineer/rfe/search/refit/eval)",
    ("stage",),
    buckets=log_buckets(1e-2, 7200.0, per_decade=2),
)


@dataclasses.dataclass
class PipelineResult:
    """Everything `main()` logs/persists (model_tree_train_test.py:159-242)."""

    selected_features: tuple[str, ...]
    best_params: dict[str, Any]
    cv_auc: float
    test_auc: float
    metrics: dict[str, Any]
    artifact: GBDTArtifact
    search: SearchResult
    scale_pos_weight: float
    timings: dict[str, float]
    #: Stage-execution counters: which stages actually computed this run vs
    #: were restored from a valid checkpoint manifest (resume path).
    stages_run: tuple[str, ...] = ()
    stages_skipped: tuple[str, ...] = ()


def _tree_frame_to_feature_frame(df: pd.DataFrame) -> FeatureFrame:
    """Rebuild the engineered `FeatureFrame` from its persisted CSV (the
    inverse of `FeatureFrame.to_pandas`) — the resume path's restore of the
    engineer stage, matching how the reference's training script consumes
    the feature-engineering script's S3 output."""
    df = df.copy()
    y = jax.numpy.asarray(df.pop(schema.LABEL_COL).to_numpy(np.float32))
    X = jax.numpy.asarray(df.to_numpy(np.float32))
    return FeatureFrame(tuple(df.columns), X, y)


def run_pipeline(
    config: PipelineConfig | None = None,
    raw: pd.DataFrame | None = None,
    store: ObjectStore | None = None,
    mesh=None,
    model_key: str | None = None,
    resume: bool | None = None,
) -> PipelineResult:
    """Run the full production path. ``raw`` takes precedence; otherwise the
    frame is loaded from ``store``'s `raw_key` (the reference loads its input
    CSV from S3, model_tree_train_test.py:77). With ``resume=True`` (or
    ``config.reliability.resume``), stages whose checkpoint manifests still
    validate are restored from the store instead of recomputed.

    The whole run executes under a ``pipeline.run`` span; each stage records
    a child span plus a ``cobalt_pipeline_stage_seconds{stage}`` observation
    (both exported by `telemetry.snapshot`)."""
    with span("pipeline.run", resume=bool(resume)):
        return _run_pipeline(config, raw, store, mesh, model_key, resume)


def _run_pipeline(
    config: PipelineConfig | None,
    raw: pd.DataFrame | None,
    store: ObjectStore | None,
    mesh,
    model_key: str | None,
    resume: bool | None,
) -> PipelineResult:
    cfg = config or PipelineConfig()
    # Framework default, not a bench-only opt-in: every pipeline run shares
    # the persistent compile cache (COBALT_COMPILE_CACHE=0 to opt out) and
    # feeds the cobalt_compile_* telemetry. Idempotent — an entrypoint that
    # already bootstrapped with its own config wins.
    bootstrap_compile_cache(cfg.compile_cache)
    rel = cfg.reliability
    resume = rel.resume if resume is None else resume
    timings: dict[str, float] = {}
    stages_run: list[str] = []
    stages_skipped: list[str] = []

    def tick(name: str, t0: float) -> float:
        t = time.time()
        timings[name] = round(t - t0, 3)
        _STAGE_SECONDS.labels(stage=name).observe(max(0.0, t - t0))
        # after-the-fact span: the stage already measured itself; this
        # registers it in the ring parented under pipeline.run
        record_span(f"pipeline.{name}", t0, t)
        logger.info("%s done in %.2fs", name, timings[name])
        return t

    if (
        store is not None
        and rel.wrap_store
        and not isinstance(store, ResilientStore)
    ):
        store = ResilientStore(
            store, policy_from_config(rel), verify_reads=rel.verify_reads
        )
    ckpt = (
        PipelineCheckpoint(store, rel.checkpoint_prefix)
        if store is not None and rel.checkpoints
        else None
    )

    # Per-stage config fingerprints: a stage's manifest is invalidated by a
    # change to any config slice it depends on, and only by those.
    fp_clean = config_fingerprint("clean", cfg.data)
    fp_engineer = config_fingerprint("engineer", cfg.data)
    fp_rfe = config_fingerprint("rfe", cfg.data, cfg.rfe, cfg.mesh)
    fp_search = config_fingerprint(
        "search", cfg.data, cfg.rfe, cfg.gbdt, cfg.tune, cfg.mesh
    )

    # A stage may be skipped only if every stage upstream of it was skipped:
    # once something re-runs, downstream inputs can no longer be trusted.
    can_resume = resume and ckpt is not None
    skip_clean = can_resume and ckpt.valid("clean", fp_clean)
    skip_engineer = skip_clean and ckpt.valid("engineer", fp_engineer)
    skip_rfe = skip_engineer and ckpt.valid("rfe", fp_rfe)
    skip_search = skip_rfe and ckpt.valid("search", fp_search)

    t = time.time()

    # --- L1 cleaning (clean_data.py:87-158) ---------------------------------
    # --- L2 features (feature_engineering.py:44-184) ------------------------
    if skip_engineer:
        manifest = ckpt.load("engineer")
        plan = plan_from_json(manifest["extra"]["plan"])
        tree_ff = _tree_frame_to_feature_frame(store.load_frame(cfg.data.tree_key))
        stages_skipped += ["clean", "engineer"]
        logger.info(
            "resume: restored engineered frame (%d rows x %d features) from %s",
            tree_ff.n_rows,
            tree_ff.n_features,
            cfg.data.tree_key,
        )
        t = tick("restore", t)
    elif cfg.data.device_pipeline and not skip_clean:
        # Device-resident L1/L2 (data/device_pipeline.py): one host pass
        # tokenizes the stringy frontier, then clean/prepare/engineer/binning
        # run as jitted ingest.* programs with no host round-trips. The
        # logical stages are still "clean"+"engineer" (same checkpoint and
        # resume contract as the pandas path, whose parity is CI-gated);
        # only the timings split into host_frontier vs device_ingest so the
        # ledger stage table can quote the host residual directly.
        if raw is None:
            if store is None:
                raise ValueError("provide a raw frame or an object store")
            raw = store.load_frame(cfg.data.raw_key)
        logger.info("raw frame: %d rows x %d cols", len(raw), raw.shape[1])
        tok = tokenize_raw_frame(raw)
        t = tick("host_frontier", t)
        ingest = run_device_ingest(
            tok,
            partitioner=make_partitioner(
                cfg.data.ingest_shards, kind_prefix="ingest"
            ),
            n_bins=cfg.gbdt.n_bins,
            null_col_threshold=cfg.data.null_col_threshold,
            row_null_allowance=cfg.data.row_null_allowance,
            keep_cleaned=store is not None and cfg.save_intermediate,
        )
        tree_ff, nn_ff, plan = ingest.tree, ingest.nn, ingest.plan
        report = ingest.report
        logger.info(
            "device ingest: %d rows, dropped %d null-heavy cols, %d dupes, "
            "%d tree features binned",
            report.n_rows_out,
            len(report.dropped_null_columns),
            report.n_duplicates_removed,
            ingest.bins.shape[1],
        )
        if store is not None and cfg.save_intermediate:
            # The cleaned artifact keeps its key but stores the tokenized
            # representation (decoded categorical strings, numeric parses)
            # rather than raw string spellings — see DeviceIngestResult.
            store.save_frame(cfg.data.cleaned_key, ingest.cleaned)
            store.save_frame(cfg.data.tree_key, tree_ff.to_pandas())
            store.save_frame(cfg.data.nn_key, nn_ff.to_pandas())
            if ckpt is not None:
                ckpt.write(
                    "clean",
                    fingerprint=fp_clean,
                    outputs=[cfg.data.cleaned_key],
                )
                ckpt.write(
                    "engineer",
                    fingerprint=fp_engineer,
                    outputs=[cfg.data.tree_key, cfg.data.nn_key],
                    extra={"plan": plan_to_json(plan)},
                )
        stages_run += ["clean", "engineer"]
        t = tick("device_ingest", t)
    else:
        if skip_clean:
            cleaned = store.load_frame(cfg.data.cleaned_key)
            stages_skipped.append("clean")
            logger.info("resume: restored cleaned frame from %s", cfg.data.cleaned_key)
        else:
            if raw is None:
                if store is None:
                    raise ValueError("provide a raw frame or an object store")
                raw = store.load_frame(cfg.data.raw_key)
            logger.info("raw frame: %d rows x %d cols", len(raw), raw.shape[1])
            cleaned, report = clean_raw_frame(
                raw, null_col_threshold=cfg.data.null_col_threshold
            )
            logger.info(
                "cleaned: %d rows, dropped %d null-heavy cols, %d dupes",
                report.n_rows_out,
                len(report.dropped_null_columns),
                report.n_duplicates_removed,
            )
            if store is not None and cfg.save_intermediate:
                store.save_frame(cfg.data.cleaned_key, cleaned)
                if ckpt is not None:
                    ckpt.write(
                        "clean",
                        fingerprint=fp_clean,
                        outputs=[cfg.data.cleaned_key],
                    )
            stages_run.append("clean")
            t = tick("clean", t)

        prepared = prepare_cleaned_frame(
            cleaned, row_null_allowance=cfg.data.row_null_allowance
        )
        tree_ff, nn_ff, plan = engineer_features(prepared)
        if store is not None and cfg.save_intermediate:
            store.save_frame(cfg.data.tree_key, tree_ff.to_pandas())
            store.save_frame(cfg.data.nn_key, nn_ff.to_pandas())
            if ckpt is not None:
                # The plan rides in the manifest: it is what the resume path
                # needs to rebuild the artifact without re-engineering.
                ckpt.write(
                    "engineer",
                    fingerprint=fp_engineer,
                    outputs=[cfg.data.tree_key, cfg.data.nn_key],
                    extra={"plan": plan_to_json(plan)},
                )
        stages_run.append("engineer")
        t = tick("engineer", t)

    # --- L3 training (model_tree_train_test.py:73-242) ----------------------
    # The hashed split is stateless and cheap: recomputed every run (resumed
    # or not) so downstream stages always see identical train/test rows.
    ff = drop_training_leakage(tree_ff)
    X_train, X_test, y_train, y_test = train_test_split_hashed(
        ff.X, ff.y, test_fraction=cfg.data.test_fraction, seed=cfg.data.split_seed
    )
    n_pos = float(jax.numpy.sum(y_train))  # scalar fetch, not the vector
    spw = (float(X_train.shape[0]) - n_pos) / max(n_pos, 1.0)
    logger.info(
        "split: %d train / %d test, scale_pos_weight=%.3f",
        X_train.shape[0],
        X_test.shape[0],
        spw,
    )
    mesh = mesh or make_mesh(cfg.mesh)

    support = None
    if skip_rfe:
        extra = ckpt.load("rfe")["extra"]
        if extra.get("feature_names") == list(ff.feature_names):
            support = np.zeros(len(ff.feature_names), dtype=bool)
            support[np.asarray(extra["support_idx"], dtype=int)] = True
            selected = tuple(extra["selected"])
            stages_skipped.append("rfe")
            logger.info("resume: restored RFE selection (%d features)", len(selected))
        else:  # engineered columns drifted from under the manifest
            skip_rfe = skip_search = False
    if support is None:
        rfe_cfg = dataclasses.replace(cfg.rfe, scale_pos_weight=spw)
        rfe = rfe_select(X_train, y_train, rfe_cfg, mesh=mesh)
        support = np.asarray(rfe.support_)
        selected = tuple(
            n for n, keep in zip(ff.feature_names, support) if keep
        )
        logger.info("RFE selected %d features: %s", len(selected), selected)
        if ckpt is not None:
            ckpt.write(
                "rfe",
                fingerprint=fp_rfe,
                extra={
                    "support_idx": np.flatnonzero(support).tolist(),
                    "selected": list(selected),
                    "feature_names": list(ff.feature_names),
                    "scale_pos_weight": spw,
                },
            )
        stages_run.append("rfe")
        t = tick("rfe", t)

    # Materialize the selected columns once (the reference trains its final
    # model on the 20-column frame); the search then fans out over the mesh.
    # Column-take stays on device — fetching the full matrices to host costs
    # ~minutes at 2.3M rows over a tunneled TPU.
    sel_idx = np.flatnonzero(support)
    Xtr_sel = jax.numpy.take(X_train, jax.numpy.asarray(sel_idx), axis=1)
    Xte_sel = jax.numpy.take(X_test, jax.numpy.asarray(sel_idx), axis=1)
    base = cfg.gbdt.replace(scale_pos_weight=spw)
    if skip_search:
        # The search's expensive part (20x3 CV fan-out) is checkpointed as
        # its best params; the final estimator is a single refit with them —
        # exactly what `randomized_search` itself does after CV.
        from cobalt_smart_lender_ai_tpu.models.gbdt import GBDTClassifier

        extra = ckpt.load("search")["extra"]
        best_params = dict(extra["best_params"])
        est = GBDTClassifier(base.replace(**best_params))
        est.fit(Xtr_sel, np.asarray(y_train))
        search = SearchResult(
            best_params_=best_params,
            best_score_=float(extra["cv_auc"]),
            best_estimator_=est,
            cv_results_={},
        )
        stages_skipped.append("search")
        logger.info("resume: restored best params %s, refit only", best_params)
        t = tick("refit", t)
    else:
        search = randomized_search(
            Xtr_sel, y_train, base, cfg.tune, mesh  # callee fetches y once
        )
        if ckpt is not None:
            ckpt.write(
                "search",
                fingerprint=fp_search,
                extra={
                    "best_params": search.best_params_,
                    "cv_auc": float(search.best_score_),
                },
            )
        stages_run.append("search")
        t = tick("search", t)
    logger.info(
        "search best CV AUC %.4f with %s", search.best_score_, search.best_params_
    )

    # --- final eval (model_tree_train_test.py:171-179) ----------------------
    est = search.best_estimator_
    margin_test = est.predict_margin(Xte_sel)
    y_test_f = jax.numpy.asarray(y_test, jax.numpy.float32)
    test_auc = float(roc_auc(y_test_f, margin_test))
    y_pred = est.predict(Xte_sel)
    report_dict = binary_classification_report(y_test_f, y_pred)
    metrics = {
        # the reference's exact metrics.json schema
        # (model_tree_train_test.py:235-242)
        "auc": test_auc,
        "classification_report": report_dict,
        "best_params": search.best_params_,
    }
    logger.info("test ROC-AUC %.4f", test_auc)
    stages_run.append("eval")
    t = tick("eval", t)

    artifact = GBDTArtifact(
        forest=est.forest,
        bin_spec=est.bin_spec,
        feature_names=selected,
        plan=plan,
        config={
            "best_params": search.best_params_,
            "scale_pos_weight": spw,
            "split_seed": cfg.data.split_seed,
        },
        metrics=metrics,
    )
    if store is not None:
        key = model_key or cfg.serve.model_key
        artifact.save(store, key)
        save_metrics(store, key + ".metrics.json", metrics)
        # Plot artifacts (model_tree_train_test.py:184-210): confusion-matrix
        # heatmap + top-20 gain-importance bars, as PNG objects next to the
        # model the way the reference uploads them to S3. matplotlib is an
        # optional extra; without it the pipeline still completes.
        try:
            from cobalt_smart_lender_ai_tpu.io.plots import (
                render_confusion_matrix,
                render_feature_importance,
            )
            from cobalt_smart_lender_ai_tpu.models.gbdt import gain_importances
            from cobalt_smart_lender_ai_tpu.ops.metrics import confusion_matrix

            cm = np.asarray(confusion_matrix(y_test_f, y_pred))
            gains, _ = gain_importances(est.forest, len(selected))
            store.put_bytes(
                key + ".confusion_matrix.png", render_confusion_matrix(cm)
            )
            store.put_bytes(
                key + ".feature_importance.png",
                render_feature_importance(selected, np.asarray(gains)),
            )
        except Exception as exc:  # pragma: no cover - plots are optional
            # The PNGs are optional artifacts; a rendering failure (missing
            # matplotlib, headless-backend/font trouble) must not abort a run
            # whose expensive search/train already succeeded.
            logger.warning("plot artifacts skipped (%s)", exc)
        logger.info("artifact persisted at %s", key)

    return PipelineResult(
        selected_features=selected,
        best_params=search.best_params_,
        cv_auc=float(search.best_score_),
        test_auc=test_auc,
        metrics=metrics,
        artifact=artifact,
        search=search,
        scale_pos_weight=spw,
        timings=timings,
        stages_run=tuple(stages_run),
        stages_skipped=tuple(stages_skipped),
    )


def main(argv=None) -> PipelineResult:
    import argparse

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--store", default=None, help="object-store URI")
    parser.add_argument(
        "--synthetic-rows",
        type=int,
        default=0,
        help="generate a synthetic raw table instead of loading raw_key",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--resume",
        action="store_true",
        help="skip stages whose checkpoint manifests still validate (crash "
        "recovery: restart from the last good stage instead of raw data)",
    )
    parser.add_argument(
        "--quick",
        action="store_true",
        help="slim search/RFE profile (4x2 search, RFE step 20) — minutes "
        "instead of the reference's full 20x3 protocol, for demos and smoke "
        "runs; quality lands in the same AUC regime",
    )
    parser.add_argument(
        "--no-halving",
        action="store_true",
        help="exhaustive hyper-parameter search (every candidate trained to "
        "its full n_estimators) instead of the successive-halving scheduler",
    )
    parser.add_argument(
        "--trace-out",
        default=None,
        help="write the run's stage spans as Chrome Trace Event / Perfetto "
        "JSON to this path (open in ui.perfetto.dev)",
    )
    parser.add_argument(
        "--ingest-shards",
        type=int,
        default=1,
        help="row shards for the device-ingest feature/binning programs "
        "(1 = single device, -1 = all visible devices)",
    )
    parser.add_argument(
        "--pandas-ingest",
        action="store_true",
        help="run L1/L2 through the host pandas path instead of the "
        "device-resident pipeline (parity fallback)",
    )
    parser.add_argument(
        "--ledger-out",
        default=None,
        help="write a run ledger (JSON: config fingerprint, env/devices, "
        "stage durations, search rungs, program cost table) to this path; "
        "render it with tools/obs_report.py",
    )
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s [%(levelname)s] %(message)s"
    )
    bootstrap_compile_cache()
    cfg = PipelineConfig()
    if args.quick:
        cfg = dataclasses.replace(
            cfg,
            rfe=RFEConfig(n_select=20, step=20, n_estimators=20, max_depth=3),
            tune=TuneConfig(
                n_iter=4,
                cv_folds=2,
                chunk_trees="auto",
                param_space={
                    "n_estimators": (150, 300),
                    "max_depth": (3,),
                    "learning_rate": (0.05, 0.1),
                    "subsample": (0.8,),
                },
            ),
        )
    if args.no_halving:
        cfg = dataclasses.replace(
            cfg, tune=dataclasses.replace(cfg.tune, halving_enabled=False)
        )
    if args.pandas_ingest or args.ingest_shards != 1:
        cfg = dataclasses.replace(
            cfg,
            data=dataclasses.replace(
                cfg.data,
                device_pipeline=not args.pandas_ingest,
                ingest_shards=args.ingest_shards,
            ),
        )
    raw = None
    if args.synthetic_rows:
        from cobalt_smart_lender_ai_tpu.data.synthetic import (
            synthetic_lendingclub_frame,
        )

        raw = synthetic_lendingclub_frame(args.synthetic_rows, seed=args.seed)
    store = ObjectStore(args.store) if args.store else None
    ledger = None
    if args.ledger_out:
        from cobalt_smart_lender_ai_tpu.telemetry import (
            RunLedger,
            install_device_metrics,
            install_program_metrics,
        )

        # Publish the observatory families onto the process registry up
        # front so the ledger's metrics snapshot carries them too.
        install_program_metrics()
        install_device_metrics()
        ledger = RunLedger(
            "pipeline",
            fingerprint=config_fingerprint(
                "search", cfg.data, cfg.rfe, cfg.gbdt, cfg.tune, cfg.mesh
            ),
            meta={
                "quick": bool(args.quick),
                "halving": not args.no_halving,
                "synthetic_rows": int(args.synthetic_rows),
                "seed": int(args.seed),
                "resume": bool(args.resume),
                "store": args.store,
            },
        )
    result = run_pipeline(cfg, raw=raw, store=store, resume=args.resume)
    if ledger is not None:
        ledger.add_stages(result.timings)
        ledger.set(
            "final_metrics",
            {
                "test_auc": result.test_auc,
                "cv_auc": result.cv_auc,
                "best_params": result.best_params,
                "n_selected": len(result.selected_features),
            },
        )
        halving_report = result.search.cv_results_.get("halving")
        if halving_report is not None:
            ledger.set("search_halving", halving_report)
        ledger.set(
            "stages_run",
            {
                "run": list(result.stages_run),
                "skipped": list(result.stages_skipped),
            },
        )
        ledger.write(args.ledger_out)
        logging.getLogger(__name__).info(
            "run ledger written to %s", args.ledger_out
        )
    if args.trace_out:
        from cobalt_smart_lender_ai_tpu.telemetry import (
            default_tracer,
            render_chrome_trace,
        )

        with open(args.trace_out, "w") as fh:
            fh.write(render_chrome_trace(default_tracer()))
        logging.getLogger(__name__).info(
            "perfetto trace written to %s", args.trace_out
        )
    print(
        {
            "test_auc": result.test_auc,
            "cv_auc": result.cv_auc,
            "best_params": result.best_params,
            "n_selected": len(result.selected_features),
            "timings": result.timings,
            "stages_run": result.stages_run,
            "stages_skipped": result.stages_skipped,
        }
    )
    return result


if __name__ == "__main__":
    main()
