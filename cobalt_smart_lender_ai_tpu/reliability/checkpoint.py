"""Pipeline stage manifests — checkpoint/resume for `run_pipeline`.

After each stage the pipeline writes ``<prefix><stage>.json`` to the object
store: the stage's output keys with md5+size pointers, a fingerprint of the
config slice the stage depends on, and a small ``extra`` payload for stages
whose result is data rather than store objects (RFE's selected features,
the search's best params). On ``--resume`` a stage is skipped iff its
manifest exists, the fingerprint still matches, and every output object's
bytes still hash to the pinned md5 — so a crash mid-RFE or mid-search
restarts from the last good stage instead of from raw data, and a config
change invalidates exactly the stages that depend on it.

Manifest format (``"format": 1``)::

    {
      "format": 1,
      "stage": "engineer",
      "fingerprint": "9f3a...",
      "outputs": ["dataset/2-intermediate/cleaned_02_tree.csv", ...],
      "pointers": {"<key>": {"key": ..., "md5": ..., "size": ...}, ...},
      "extra": {...},
      "progress": {...}          # optional: partial-stage position
    }

Whole-stage manifests (the pipeline) never emit ``progress``; long streaming
stages (the portfolio scorer) call `advance` after every chunk so a kill can
resume mid-stage — the payload carries whatever position the owner needs
(chunk index, rows done, content fingerprint). Manifests written before this
field existed load unchanged: ``progress`` is simply absent and `progress()`
returns None.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
from typing import Any, Mapping, Sequence

from cobalt_smart_lender_ai_tpu.io.store import ObjectStore

logger = logging.getLogger(__name__)

MANIFEST_FORMAT = 1


def config_fingerprint(*parts: Any) -> str:
    """Stable hex digest of config dataclasses / plain JSON-able values.

    Dataclasses are flattened with `dataclasses.asdict`; anything JSON can't
    serialize falls back to ``str`` — the goal is change *detection*, not a
    canonical encoding."""
    norm = [
        dataclasses.asdict(p) if dataclasses.is_dataclass(p) else p for p in parts
    ]
    payload = json.dumps(norm, sort_keys=True, default=str).encode()
    return hashlib.sha256(payload).hexdigest()[:16]


class PipelineCheckpoint:
    """Read/write/validate per-stage manifests in an object store."""

    def __init__(self, store: ObjectStore, prefix: str = "checkpoints/"):
        self.store = store
        self.prefix = prefix

    def manifest_key(self, stage: str) -> str:
        return f"{self.prefix}{stage}.json"

    def write(
        self,
        stage: str,
        *,
        fingerprint: str,
        outputs: Sequence[str] = (),
        extra: Mapping[str, Any] | None = None,
        progress: Mapping[str, Any] | None = None,
    ) -> dict:
        """Pin each output's current content (also writing its
        ``<key>.ptr.json`` so `ResilientStore` verifies later reads) and
        persist the stage manifest. ``progress`` (optional) marks a
        partially complete stage; when None the key is omitted entirely so
        whole-stage manifests stay byte-identical to format-1 files written
        before the field existed."""
        pointers = {key: self.store.write_pointer(key) for key in outputs}
        manifest = {
            "format": MANIFEST_FORMAT,
            "stage": stage,
            "fingerprint": fingerprint,
            "outputs": list(outputs),
            "pointers": pointers,
            "extra": dict(extra or {}),
        }
        if progress is not None:
            manifest["progress"] = dict(progress)
        self.store.put_json(self.manifest_key(stage), manifest)
        return manifest

    def advance(
        self,
        stage: str,
        *,
        fingerprint: str,
        new_outputs: Sequence[str] = (),
        progress: Mapping[str, Any] | None = None,
        extra: Mapping[str, Any] | None = None,
    ) -> dict:
        """Append partial progress to a stage without re-pinning history.

        Loads the existing manifest (when its fingerprint still matches —
        a config change discards stale progress and starts over), pins only
        ``new_outputs``, and replaces the ``progress`` payload. A streaming
        stage calling this after every chunk pays O(chunk) per call instead
        of `write`'s O(all outputs so far) re-hash."""
        manifest = self.load(stage)
        if manifest is None or manifest.get("fingerprint") != fingerprint:
            manifest = {
                "format": MANIFEST_FORMAT,
                "stage": stage,
                "fingerprint": fingerprint,
                "outputs": [],
                "pointers": {},
                "extra": dict(extra or {}),
            }
        elif extra is not None:
            manifest["extra"] = dict(extra)
        for key in new_outputs:
            manifest["pointers"][key] = self.store.write_pointer(key)
            if key not in manifest["outputs"]:
                manifest["outputs"].append(key)
        if progress is not None:
            manifest["progress"] = dict(progress)
        self.store.put_json(self.manifest_key(stage), manifest)
        return manifest

    def progress(
        self, stage: str, fingerprint: str | None = None
    ) -> dict | None:
        """The stage's partial-progress payload, or None when the stage has
        none (including every pre-progress manifest). With ``fingerprint``,
        progress recorded under a different config reads as None — resuming
        code treats it exactly like a fresh start."""
        manifest = self.load(stage)
        if manifest is None:
            return None
        if (
            fingerprint is not None
            and manifest.get("fingerprint") != fingerprint
        ):
            return None
        progress = manifest.get("progress")
        return dict(progress) if isinstance(progress, dict) else None

    def load(self, stage: str) -> dict | None:
        """The stage's manifest, or None when missing/unreadable/foreign."""
        try:
            manifest = self.store.get_json(self.manifest_key(stage))
        except Exception:
            return None
        if (
            not isinstance(manifest, dict)
            or manifest.get("format") != MANIFEST_FORMAT
        ):
            return None
        return manifest

    def valid(self, stage: str, fingerprint: str) -> bool:
        """True iff the stage can be skipped: manifest present, config slice
        unchanged, and every pinned output still hashes to its manifest md5
        (checked against the manifest itself, not the mutable ``.ptr.json``,
        so a rewritten pointer cannot launder drifted bytes)."""
        manifest = self.load(stage)
        if manifest is None or manifest.get("fingerprint") != fingerprint:
            return False
        for key in manifest.get("outputs", []):
            ptr = manifest.get("pointers", {}).get(key)
            if not ptr:
                return False
            try:
                data = self.store.get_bytes(key)
            except Exception:
                return False
            if (
                hashlib.md5(data).hexdigest() != ptr.get("md5")
                or len(data) != ptr.get("size")
            ):
                logger.info("checkpoint %s: output %s drifted", stage, key)
                return False
        return True

    def invalidate(self, stage: str) -> None:
        self.store.delete(self.manifest_key(stage))
