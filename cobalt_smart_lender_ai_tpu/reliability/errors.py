"""Request-path error taxonomy — one module both HTTP adapters map from.

PR 2 gave the storage side a shared failure vocabulary (`InjectedFault`,
`CorruptObjectError`); the request path had none: the stdlib and FastAPI
adapters each grew their own ad-hoc status mapping, and anything unexpected
collapsed into an untyped HTTP 500. Here every serving failure mode is a
`RequestError` subclass carrying its HTTP status and a stable machine-readable
``code``, so

- both adapters translate identically (`error_response` is the whole mapping),
- clients (`ui.core.ApiClient`) can tell *degraded* states (shed, breaker
  open, deadline) from real faults without parsing prose, and
- the chaos soak can assert "zero untyped 500s": any 500 whose body lacks an
  ``error`` code is a bug escape, not a policy decision.

The taxonomy (see README "Serving guarantees"):

==== ====================== ==================================================
422  ``invalid_input``      request failed the serving schema
413  ``payload_too_large``  bulk CSV over ``max_bulk_rows``/``max_bulk_bytes``
429  ``shed``               admission control refused (rate / in-flight cap);
                            always carries ``Retry-After``
503  ``circuit_open``       a store-backed dependency is failing fast;
                            carries ``Retry-After`` (time until half-open)
504  ``deadline_exceeded``  cooperative cancellation hit the request deadline
500  ``reload_failed``      hot model swap failed and was rolled back
500  ``worker_dead``        the micro-batch worker thread died with requests
                           queued; they are failed typed, never left hanging
==== ====================== ==================================================
"""

from __future__ import annotations

import math


class RequestError(Exception):
    """Base of the serving taxonomy: HTTP ``status`` + stable ``code``.

    ``retry_after_s`` (when set) becomes a ``Retry-After`` header so clients
    pace their retries off the server's own estimate instead of guessing.
    """

    status: int = 500
    code: str = "internal"

    def __init__(self, detail: str = "", *, retry_after_s: float | None = None):
        super().__init__(detail)
        self.detail = detail or self.code
        self.retry_after_s = retry_after_s

    def body(self) -> dict:
        """JSON body: FastAPI's ``detail`` convention + the typed ``code``."""
        return {"detail": self.detail, "error": self.code}

    def headers(self) -> dict[str, str]:
        if self.retry_after_s is None:
            return {}
        # Ceil to a whole second with a floor of 1: "Retry-After: 0" is an
        # invitation to hammer-retry in a busy loop.
        return {"Retry-After": str(max(1, math.ceil(self.retry_after_s)))}


class ValidationError(RequestError, ValueError):
    """Input failed the serving schema; adapters map it to HTTP 422.

    Still a `ValueError` — pre-taxonomy callers catching ValueError keep
    working (this class moved here from `serve.service`, which re-exports it).
    """

    status = 422
    code = "invalid_input"


class PayloadTooLarge(RequestError, ValueError):
    """Bulk request over the configured size bounds — HTTP 413. Rejected
    *before* parse/score: an unbounded CSV can OOM the host or trigger a
    fresh multi-second XLA compile for an arbitrary batch bucket."""

    status = 413
    code = "payload_too_large"


class RequestShed(RequestError):
    """Admission control refused the request (token bucket empty or in-flight
    cap reached) — HTTP 429 with ``Retry-After``. Shedding is deliberate:
    bounded rejection beats an unbounded queue collapsing the service."""

    status = 429
    code = "shed"


class CircuitOpenError(RequestError):
    """A store-backed dependency's circuit breaker is open: fail fast (HTTP
    503 + ``Retry-After``) instead of tying up a worker in doomed retries."""

    status = 503
    code = "circuit_open"


class DeadlineExceeded(RequestError):
    """The request's wall-clock budget expired at a cooperative cancellation
    checkpoint — HTTP 504. Work already paid for is abandoned: past the
    deadline the client is gone, and a late 200 helps nobody."""

    status = 504
    code = "deadline_exceeded"


class ReloadFailed(RequestError):
    """Hot model swap failed validation and was rolled back; the previous
    model keeps serving. Typed 500: operator error, not overload."""

    status = 500
    code = "reload_failed"


class WorkerDead(RequestError):
    """The micro-batch worker thread exited while requests were queued. The
    watchdog resolves every orphaned future with this typed 500 (a hanging
    client is worse than a failed one) and restarts the worker. At the fleet
    level this is a replica-*internal* failure, so hedged failover may retry
    it once on a different replica — unlike the client-error codes above."""

    status = 500
    code = "worker_dead"


class PromotionRejected(RequestError):
    """The canary promotion gate said no (or there is no canary to promote)
    — HTTP 409. Carries the gate's structured ``report`` (sample counts,
    per-check verdicts, machine-readable reasons) in the body so the retrain
    driver and operators see *why* without parsing prose."""

    status = 409
    code = "promotion_rejected"

    def __init__(self, detail: str = "", *, report: dict | None = None):
        super().__init__(detail)
        self.report = report or {}

    def body(self) -> dict:
        return {**super().body(), "report": self.report}


class RollbackFailed(RequestError):
    """A rollback was requested but there is no ``previous`` channel to
    restore (or the registry is unavailable) — HTTP 409, not a 500: the
    serving model is untouched and still healthy."""

    status = 409
    code = "rollback_failed"


def error_response(exc: RequestError) -> tuple[int, dict, dict[str, str]]:
    """The single adapter-side mapping: (HTTP status, JSON body, headers)."""
    return exc.status, exc.body(), exc.headers()
