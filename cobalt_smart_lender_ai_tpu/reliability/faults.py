"""`FaultInjectingStore` — seeded, deterministic fault injection for tests.

Every resilience claim in the tree is exercised under injected faults
(tests/test_reliability.py) rather than asserted: a pipeline run against a
store that drops ~one in five calls must still complete, a corrupted read
must be detected by pointer verification and healed by a retry. The double
is deterministic — one `random.Random(seed)` drawn once per rate-gated call
in call order — so a failing seed reproduces exactly.
"""

from __future__ import annotations

import dataclasses
import random
import time
import weakref
from collections import Counter
from typing import Callable, Iterator, Mapping

from cobalt_smart_lender_ai_tpu.io.store import ObjectStore
from cobalt_smart_lender_ai_tpu.telemetry import (
    MetricsRegistry,
    default_registry,
)


class InjectedFault(ConnectionError):
    """Deliberate transient failure (ConnectionError so the default retry
    predicate classifies it transient, like a dropped backend connection)."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """Fault profile for one store operation.

    - ``rate`` — probability an individual call raises `InjectedFault`.
    - ``fail_after`` — deterministic variant: the first N calls succeed,
      every later call faults (until ``max_faults`` is spent).
    - ``corrupt_rate`` — ``get`` only: probability the returned bytes are
      corrupted (first byte flipped) instead of raising.
    - ``max_faults`` — total fault budget for the operation; ``None`` means
      unbounded. A bounded budget guarantees eventual success under retry.
    - ``delay_s`` / ``delay_jitter_s`` — latency injection: every call (even
      ones that then fault) sleeps ``delay_s`` plus a seeded uniform draw in
      ``[0, delay_jitter_s)`` through the store's injectable ``sleep``, so
      deadline and breaker tests exercise a *slow* store deterministically
      against a fake clock. Delays do not consume ``max_faults``.
    """

    rate: float = 0.0
    fail_after: int | None = None
    corrupt_rate: float = 0.0
    max_faults: int | None = None
    delay_s: float = 0.0
    delay_jitter_s: float = 0.0


class FaultInjectingStore(ObjectStore):
    """Wraps any `ObjectStore`; injects faults per-operation per `FaultSpec`.

    ``faults`` maps operation name (``"put"``, ``"get"``, ``"exists"``,
    ``"delete"``, ``"list"``) to its spec; unlisted operations run clean.
    ``calls`` / ``injected`` / ``delays`` / ``delayed_s`` are per-operation
    counters tests assert against. ``sleep`` is injectable (default
    `time.sleep`) so latency injection composes with a fake clock.
    """

    OPS = ("put", "get", "exists", "delete", "list")

    def __new__(cls, *args, **kwargs):  # bypass ObjectStore's URI dispatch
        return object.__new__(cls)

    def __init__(
        self,
        inner: ObjectStore,
        *,
        seed: int = 0,
        faults: Mapping[str, FaultSpec] | None = None,
        sleep: Callable[[float], None] = time.sleep,
        registry: MetricsRegistry | None = None,
    ):
        self.inner = inner
        self.uri = inner.uri
        self.faults = dict(faults or {})
        unknown = set(self.faults) - set(self.OPS)
        if unknown:
            raise ValueError(f"unknown fault ops {sorted(unknown)}; use {self.OPS}")
        self._rng = random.Random(seed)
        self._sleep = sleep
        self.calls: Counter[str] = Counter()
        self.injected: Counter[str] = Counter()
        self.delays: Counter[str] = Counter()
        self.delayed_s: dict[str, float] = {}
        self._register_metrics(
            registry if registry is not None else default_registry()
        )

    def _register_metrics(self, reg: MetricsRegistry) -> None:
        """Mirror the per-operation counters into the registry with
        collect-time callbacks: the Counters above stay the single writer
        (tests keep asserting on them), and a scrape during a fault drill
        shows what the drill actually injected. Callbacks hold only a weak
        reference — a collected store reads NaN, never a crash or a leak."""
        self_ref = weakref.ref(self)

        def _sample(attr: str, op: str) -> Callable[[], float]:
            def read() -> float:
                store = self_ref()
                if store is None:
                    raise LookupError("fault store was garbage-collected")
                return float(getattr(store, attr).get(op, 0.0))

            return read

        families = (
            (
                "calls",
                "cobalt_store_fault_calls_total",
                "store calls seen by the fault-injecting wrapper",
            ),
            (
                "injected",
                "cobalt_store_faults_injected_total",
                "faults injected (raised errors + corrupted reads)",
            ),
            (
                "delays",
                "cobalt_store_fault_delays_total",
                "store calls given injected latency",
            ),
            (
                "delayed_s",
                "cobalt_store_fault_delay_seconds_total",
                "total injected latency",
            ),
        )
        for attr, name, help_text in families:
            fam = reg.counter(name, help_text, ("op",))
            for op in self.OPS:
                fam.labels(op=op).set_function(_sample(attr, op))

    # -- fault engine ---------------------------------------------------------
    def _budget_left(self, op: str, spec: FaultSpec) -> bool:
        return spec.max_faults is None or self.injected[op] < spec.max_faults

    def _maybe_delay(self, op: str, spec: FaultSpec) -> None:
        """Latency injection, before any fault draw: a slow backend is slow
        whether or not the call then fails. Jitter draws from the shared
        seeded rng only when configured, so specs without jitter leave the
        fault-draw sequence of existing seeds untouched."""
        delay = spec.delay_s
        if spec.delay_jitter_s:
            delay += spec.delay_jitter_s * self._rng.random()
        if delay > 0.0:
            self.delays[op] += 1
            self.delayed_s[op] = self.delayed_s.get(op, 0.0) + delay
            self._sleep(delay)

    def _inject(self, op: str) -> None:
        """Count the call; apply injected latency; raise if this call draws
        a fault."""
        self.calls[op] += 1
        spec = self.faults.get(op)
        if spec is None:
            return
        self._maybe_delay(op, spec)
        if not self._budget_left(op, spec):
            return
        if spec.fail_after is not None and self.calls[op] > spec.fail_after:
            self.injected[op] += 1
            raise InjectedFault(f"injected {op} fault (call {self.calls[op]})")
        if spec.rate and self._rng.random() < spec.rate:
            self.injected[op] += 1
            raise InjectedFault(f"injected {op} fault (call {self.calls[op]})")

    def _maybe_corrupt(self, data: bytes) -> bytes:
        spec = self.faults.get("get")
        if (
            spec is not None
            and spec.corrupt_rate
            and self._budget_left("get", spec)
            and self._rng.random() < spec.corrupt_rate
        ):
            self.injected["get"] += 1
            return bytes([data[0] ^ 0xFF]) + data[1:] if data else b"\x00"
        return data

    # -- byte-blob contract ---------------------------------------------------
    def put_bytes(self, key: str, data: bytes) -> None:
        self._inject("put")
        self.inner.put_bytes(key, data)

    def get_bytes(self, key: str) -> bytes:
        self._inject("get")
        return self._maybe_corrupt(self.inner.get_bytes(key))

    def exists(self, key: str) -> bool:
        self._inject("exists")
        return self.inner.exists(key)

    def delete(self, key: str) -> None:
        self._inject("delete")
        self.inner.delete(key)

    def list(self, prefix: str = "") -> Iterator[str]:
        self._inject("list")
        return self.inner.list(prefix)
