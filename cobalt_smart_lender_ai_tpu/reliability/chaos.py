"""`ChaosPlan` — scriptable replica murder for the serving fleet.

`FaultInjectingStore` made storage failures a seeded, deterministic test
primitive; this module does the same for *replica* failures so the fleet
supervision layer (`serve/supervisor.py`) is exercised under real injected
chaos instead of asserted. A plan arms per-replica faults and injects them
at the micro-batch worker's chaos checkpoint:

- ``kill_worker``   — the batcher worker thread raises `WorkerKilled` and
                      exits, orphaning its queue (the watchdog's job to fix).
- ``hang_dispatch`` — the worker wedges before dispatch for ``hang_s``
                      (releasable via `ChaosPlan.release`), so queue age
                      grows and deadline-bounded probes time out.
- ``error_storm``   — dispatches raise `ChaosError` (a replica-*internal*
                      failure: futures resolve with it, the worker lives,
                      hedged failover and the error EWMA see it).
- ``add_latency``   — dispatches sleep ``delay_s`` plus a seeded jitter
                      draw, for tail-latency and queue-age scenarios.

Determinism mirrors `FaultInjectingStore`: one `random.Random(seed)` drawn
in call order, an injectable ``sleep`` and ``clock``, per-kind event
counters mirrored into the metrics registry behind a weakref.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
import weakref
from collections import Counter
from typing import Callable

from cobalt_smart_lender_ai_tpu.telemetry import (
    MetricsRegistry,
    default_registry,
    get_logger,
)

_LOG = get_logger("reliability.chaos")

KINDS = ("kill", "hang", "error", "delay")


class ChaosError(RuntimeError):
    """Injected replica-internal dispatch failure. Deliberately *not* a
    `RequestError`: it models an unexpected bug inside one replica, the
    exact class of failure hedged failover retries elsewhere."""


class WorkerKilled(BaseException):
    """Raised at the worker's chaos checkpoint. A `BaseException` on
    purpose: the worker loop contains batch-level `Exception`s, so this is
    the one thing that escapes and genuinely kills the daemon thread
    mid-queue — exactly what the watchdog exists to survive."""


@dataclasses.dataclass(frozen=True)
class ChaosSpec:
    """One armed fault profile for one replica.

    - ``kill_worker`` — raise `WorkerKilled` through the worker loop.
    - ``hang_s`` — wedge the worker this long before dispatching.
    - ``error_rate`` — probability a dispatch raises `ChaosError`.
    - ``error_after`` — deterministic variant: first N dispatches clean,
      later ones raise (until ``max_events`` is spent).
    - ``delay_s`` / ``delay_jitter_s`` — added dispatch latency; jitter is a
      seeded uniform draw in ``[0, delay_jitter_s)``.
    - ``max_events`` — fault budget; ``None`` means unbounded. A bounded
      budget guarantees the chaos eventually stops and the fleet can heal.
    """

    kill_worker: bool = False
    hang_s: float = 0.0
    error_rate: float = 0.0
    error_after: int | None = None
    delay_s: float = 0.0
    delay_jitter_s: float = 0.0
    max_events: int | None = None


@dataclasses.dataclass
class _Armed:
    """A `ChaosSpec` plus its mutable spend state."""

    replica: int
    spec: ChaosSpec
    spent: int = 0
    dispatches: int = 0

    def budget_left(self) -> bool:
        return self.spec.max_events is None or self.spent < self.spec.max_events


class ChaosPlan:
    """Arms faults per replica index and injects them into a fleet.

    Usage::

        plan = ChaosPlan(seed=7)
        plan.kill_worker(replica=1)
        plan.error_storm(replica=1, rate=1.0, max_events=20)
        plan.inject(fleet)          # or a single ScorerService (replica 0)
        ...
        plan.release()              # un-wedge hangs, detach all hooks

    Hooks attach to every replica's `MicroBatcher`; arming *after* inject
    takes effect immediately (hooks read the armed list dynamically), so a
    bench can inject once and schedule kills mid-run. A replica rebuilt by
    the supervisor gets a fresh batcher with no hook — healing clears chaos
    by construction, like a real process restart would.
    """

    def __init__(
        self,
        *,
        seed: int = 0,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
        registry: MetricsRegistry | None = None,
    ):
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._clock = clock
        self._lock = threading.Lock()
        self._armed: list[_Armed] = []
        self._hooked: list = []  # batchers we attached to, for release()
        self._released = threading.Event()
        self.events: Counter[str] = Counter()
        self.last_event_at: dict[str, float] = {}
        # The injected fleet's EventJournal (picked up by `inject`): every
        # fault lands in the control-plane record as a ``chaos.inject``
        # event, so a postmortem shows the kill right before the
        # quarantine it provoked. Weakref — chaos must never keep a fleet
        # alive.
        self._journal_ref: Callable[[], object | None] = lambda: None
        self._register_metrics(
            registry if registry is not None else default_registry()
        )

    # -- arming ---------------------------------------------------------------
    def arm(self, replica: int, spec: ChaosSpec) -> "ChaosPlan":
        with self._lock:
            self._armed.append(_Armed(replica=int(replica), spec=spec))
        return self

    def kill_worker(self, replica: int = 0, *, max_events: int = 1) -> "ChaosPlan":
        return self.arm(replica, ChaosSpec(kill_worker=True, max_events=max_events))

    def hang_dispatch(
        self, replica: int = 0, hang_s: float = 1.0, *, max_events: int = 1
    ) -> "ChaosPlan":
        return self.arm(replica, ChaosSpec(hang_s=hang_s, max_events=max_events))

    def error_storm(
        self,
        replica: int = 0,
        rate: float = 1.0,
        *,
        error_after: int | None = None,
        max_events: int | None = None,
    ) -> "ChaosPlan":
        return self.arm(
            replica,
            ChaosSpec(error_rate=rate, error_after=error_after, max_events=max_events),
        )

    def add_latency(
        self,
        replica: int = 0,
        delay_s: float = 0.01,
        *,
        jitter_s: float = 0.0,
        max_events: int | None = None,
    ) -> "ChaosPlan":
        return self.arm(
            replica,
            ChaosSpec(delay_s=delay_s, delay_jitter_s=jitter_s, max_events=max_events),
        )

    # -- injection ------------------------------------------------------------
    def inject(self, target) -> "ChaosPlan":
        """Attach to every replica batcher of ``target`` (a `ReplicaSet` or a
        single `ScorerService`, treated as replica 0)."""
        replicas = getattr(target, "replicas", None) or [target]
        journal = getattr(target, "journal", None)
        if journal is not None:
            self._journal_ref = weakref.ref(journal)
        for i, rep in enumerate(replicas):
            batcher = getattr(rep, "batcher", None)
            if batcher is None:
                continue
            batcher._chaos = _ReplicaChaos(self, i)
            self._hooked.append(weakref.ref(batcher))
        return self

    def release(self) -> None:
        """Un-wedge any hanging worker and detach every hook; the plan stops
        injecting even if a batcher still holds a stale reference."""
        self._released.set()
        with self._lock:
            self._armed.clear()
        for ref in self._hooked:
            batcher = ref()
            if batcher is not None:
                batcher._chaos = None
        self._hooked.clear()

    # -- the injection engine (called from worker threads) --------------------
    def _record(self, kind: str, replica: int | None = None) -> None:
        self.events[kind] += 1
        self.last_event_at[kind] = self._clock()
        journal = self._journal_ref()
        if journal is not None:
            try:
                journal.emit(
                    "chaos",
                    "inject",
                    replica=replica,
                    payload={"fault": kind},
                    cause={"plan": "chaos", "fault": kind},
                )
            except Exception:
                pass  # chaos must inject its fault even if journaling fails

    def _hang(self, duration: float) -> None:
        # Under the default real sleep, hang on the release event so
        # `release()` can un-wedge a worker early; an injected (fake-clock)
        # sleep is called directly so tests stay deterministic.
        if self._sleep is time.sleep:
            self._released.wait(timeout=duration)
        else:
            self._sleep(duration)

    def _on_dispatch(self, replica: int) -> None:
        """Chaos checkpoint: runs in the worker loop before each dispatch.
        Raising `WorkerKilled` here escapes the per-batch containment and
        kills the thread; other kinds sleep or raise `ChaosError` (which the
        worker resolves the batch's futures with)."""
        if self._released.is_set():
            return
        with self._lock:
            armed = [a for a in self._armed if a.replica == replica]
            for a in armed:
                a.dispatches += 1
        for a in armed:
            spec = a.spec
            if not a.budget_left():
                continue
            if spec.delay_s or spec.delay_jitter_s:
                delay = spec.delay_s + spec.delay_jitter_s * self._rng.random()
                a.spent += 1
                self._record("delay", replica)
                self._sleep(delay)
            if spec.hang_s and a.budget_left():
                a.spent += 1
                self._record("hang", replica)
                _LOG.warning("chaos_hang", replica=replica, hang_s=spec.hang_s)
                self._hang(spec.hang_s)
            if spec.kill_worker and a.budget_left():
                a.spent += 1
                self._record("kill", replica)
                _LOG.warning("chaos_kill_worker", replica=replica)
                raise WorkerKilled(f"chaos killed replica {replica} worker")
            storm = spec.error_rate and (
                spec.error_after is None or a.dispatches > spec.error_after
            )
            if storm and a.budget_left() and self._rng.random() < spec.error_rate:
                a.spent += 1
                self._record("error", replica)
                raise ChaosError(
                    f"chaos error storm on replica {replica} "
                    f"(dispatch {a.dispatches})"
                )

    # -- metrics --------------------------------------------------------------
    def _register_metrics(self, reg: MetricsRegistry) -> None:
        """Mirror per-kind event counts behind a weakref, `FaultInjectingStore`
        style: the Counter stays the single writer, a collected plan reads as
        absent rather than crashing the scrape."""
        self_ref = weakref.ref(self)

        def _sample(kind: str) -> Callable[[], float]:
            def read() -> float:
                plan = self_ref()
                if plan is None:
                    raise LookupError("chaos plan was garbage-collected")
                return float(plan.events.get(kind, 0))

            return read

        fam = reg.counter(
            "cobalt_chaos_events_total",
            "chaos faults injected into replica workers",
            ("kind",),
        )
        for kind in KINDS:
            fam.labels(kind=kind).set_function(_sample(kind))


class _ReplicaChaos:
    """The per-batcher hook: binds a plan to one replica index. The batcher
    only ever calls `on_dispatch`; keeping the plan behind a weakref means a
    dropped plan silently stops injecting."""

    __slots__ = ("_plan", "replica")

    def __init__(self, plan: ChaosPlan, replica: int):
        self._plan = weakref.ref(plan)
        self.replica = replica

    def on_dispatch(self) -> None:
        plan = self._plan()
        if plan is not None:
            plan._on_dispatch(self.replica)
