"""Circuit breaker (closed → open → half-open) for store-backed operations.

`reliability.retry` protects a *single* call against a *transient* blip. A
flapping or down store is a different failure shape: every caller pays the
full retry schedule before failing, workers pile up in backoff sleeps, and
the store gets hammered exactly when it is least able to answer. The breaker
adds the missing memory across calls:

- **closed** — calls pass through; ``failure_threshold`` *consecutive*
  failures trip it open (any success resets the streak).
- **open** — calls fail immediately with `errors.CircuitOpenError` (HTTP 503
  + ``Retry-After``) for ``reset_timeout_s``; no load reaches the store.
- **half-open** — after the timeout, up to ``half_open_max_calls`` probe
  calls pass; one success closes the circuit, one failure re-opens it and
  restarts the timer. Excess calls during probing fail fast.

The clock is injectable, state transitions are recorded in ``transitions``
(observable history, not just current state), and everything is guarded by
one lock so the ThreadingHTTPServer adapter can share a breaker across
request threads.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Wrap store-backed calls: ``breaker.call(lambda: artifact_load(...))``.

    Every exception from the wrapped call counts as a failure — a store that
    keeps raising *anything* (transient or not) is a store to back off from;
    the caller still sees the original exception, so deterministic errors
    keep their type.
    """

    def __init__(
        self,
        *,
        failure_threshold: int = 5,
        reset_timeout_s: float = 30.0,
        half_open_max_calls: int = 1,
        clock: Callable[[], float] = time.monotonic,
        name: str = "store",
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if half_open_max_calls < 1:
            raise ValueError("half_open_max_calls must be >= 1")
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.half_open_max_calls = half_open_max_calls
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at: float | None = None
        self._probes_in_flight = 0
        #: Transition history ("open"/"half_open"/"closed" in order) —
        #: observable so tests assert the *path* taken, not just the end state.
        self.transitions: list[str] = []
        self.opened_count = 0
        self.fast_failures = 0  # calls rejected without touching the store
        #: Optional observer ``(old_state, new_state) -> None`` invoked on
        #: every transition (the serving layer journals breaker flips as
        #: control-plane events). Runs under the breaker lock, so it must
        #: not call back into the breaker; any exception it raises is
        #: swallowed — observation never breaks the state machine.
        self.on_transition: Callable[[str, str], None] | None = None

    # -- state machine (lock held for every mutation) --------------------------

    def _transition_locked(self, to: str) -> None:
        old = self._state
        self._state = to
        self.transitions.append(to)
        if to == OPEN:
            self._opened_at = self._clock()
            self.opened_count += 1
        elif to == CLOSED:
            self._consecutive_failures = 0
        elif to == HALF_OPEN:
            self._probes_in_flight = 0
        if self.on_transition is not None:
            try:
                self.on_transition(old, to)
            except Exception:
                pass

    def _poll_locked(self) -> str:
        """Advance open → half-open once the reset timeout has elapsed."""
        if (
            self._state == OPEN
            and self._clock() - self._opened_at >= self.reset_timeout_s
        ):
            self._transition_locked(HALF_OPEN)
        return self._state

    @property
    def state(self) -> str:
        with self._lock:
            return self._poll_locked()

    @property
    def consecutive_failures(self) -> int:
        return self._consecutive_failures

    def _reject_locked(self) -> None:
        from cobalt_smart_lender_ai_tpu.reliability.errors import (
            CircuitOpenError,
        )

        self.fast_failures += 1
        if self._state == OPEN:
            remaining = self.reset_timeout_s - (self._clock() - self._opened_at)
            detail = f"{self.name} circuit open"
        else:  # half-open with all probe slots taken
            remaining = self.reset_timeout_s
            detail = f"{self.name} circuit half-open, probe in flight"
        raise CircuitOpenError(
            detail, retry_after_s=max(remaining, 1e-3)
        )

    def call(self, fn: Callable[[], Any]) -> Any:
        """Run ``fn`` under the breaker; raise `CircuitOpenError` without
        calling it when the circuit is open (or probing at capacity)."""
        with self._lock:
            state = self._poll_locked()
            if state == OPEN:
                self._reject_locked()
            if state == HALF_OPEN:
                if self._probes_in_flight >= self.half_open_max_calls:
                    self._reject_locked()
                self._probes_in_flight += 1
        try:
            result = fn()
        except BaseException:
            self._record_failure()
            raise
        self._record_success()
        return result

    def _record_success(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._transition_locked(CLOSED)
            self._consecutive_failures = 0

    def _record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                # The probe failed: the dependency is still down; re-open and
                # restart the timer.
                self._transition_locked(OPEN)
            elif self._state == CLOSED:
                self._consecutive_failures += 1
                if self._consecutive_failures >= self.failure_threshold:
                    self._transition_locked(OPEN)


def breaker_from_config(
    rel, clock: Callable[[], float] = time.monotonic, name: str = "store"
) -> CircuitBreaker:
    """Build from a `config.ReliabilityConfig` (config.py stays
    dependency-free, mirroring `retry.policy_from_config`)."""
    return CircuitBreaker(
        failure_threshold=rel.breaker_failure_threshold,
        reset_timeout_s=rel.breaker_reset_s,
        half_open_max_calls=rel.breaker_half_open_max,
        clock=clock,
        name=name,
    )
