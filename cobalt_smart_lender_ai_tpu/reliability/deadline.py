"""Per-request deadlines with cooperative cancellation checkpoints.

A JAX dispatch cannot be interrupted mid-flight, so cancellation is
cooperative: the service calls `Deadline.check` at the points where abandoning
the request is cheap (after validation, between batch chunks, before the
optional SHAP program). A tripped checkpoint raises
`errors.DeadlineExceeded` (HTTP 504) and the worker is freed immediately
instead of finishing work whose client has already given up.

The clock is injectable (`time.monotonic` by default) so deadline behavior is
asserted against fake clocks in tier-1 — no test ever sleeps for real.
"""

from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable, TypeVar

from cobalt_smart_lender_ai_tpu.reliability.errors import DeadlineExceeded

_T = TypeVar("_T")


class Deadline:
    """An absolute expiry point on an injectable monotonic clock."""

    __slots__ = ("budget_s", "_expires_at", "_clock")

    def __init__(
        self, budget_s: float, clock: Callable[[], float] = time.monotonic
    ):
        if budget_s < 0:
            raise ValueError(f"deadline budget must be >= 0, got {budget_s}")
        self.budget_s = float(budget_s)
        self._clock = clock
        self._expires_at = clock() + float(budget_s)

    def remaining(self) -> float:
        """Seconds left; negative once expired."""
        return self._expires_at - self._clock()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def exceeded(self, checkpoint: str = "request") -> DeadlineExceeded:
        """Build (without raising) the `DeadlineExceeded` this deadline would
        raise at ``checkpoint``. The micro-batch scheduler resolves queued
        requests' futures with it — raising in the batcher thread would tear
        down the batch, not the one expired request."""
        return DeadlineExceeded(
            f"deadline of {self.budget_s:g}s exceeded at {checkpoint!r} "
            f"({-self.remaining():.3f}s over budget)"
        )

    def check(self, checkpoint: str = "request") -> None:
        """Cooperative cancellation point: raise `DeadlineExceeded` if the
        budget is spent. ``checkpoint`` names where the request died so 504
        bodies say what was abandoned, not just that something was."""
        if self.remaining() <= 0.0:
            raise self.exceeded(checkpoint)


async def await_under_deadline(
    awaitable: Awaitable[_T],
    deadline: Deadline | None,
    checkpoint: str = "request",
) -> _T:
    """Await ``awaitable`` under a loop-scheduled timeout.

    The async twin of `Deadline.check`: instead of a thread parked on
    ``Future.result()`` discovering the expiry only when the worker resolves
    it, the event loop itself schedules the 504 — ``deadline.remaining()``
    becomes an ``asyncio.wait_for`` timer, so a queued request whose budget
    runs out resolves `DeadlineExceeded` without consuming a batch slot or
    waking any worker.

    The awaitable is shielded: on timeout it is *abandoned*, not cancelled —
    the micro-batch worker still owns the underlying future and resolves it
    later (the queued entry is skipped as expired at the next collection,
    which is also where the ``expired{where="queued"}`` counter increments
    exactly once). `MicroBatcher.submit_async` attaches the done-callback
    that retrieves the abandoned future's eventual exception.
    """
    if deadline is None:
        return await awaitable
    fut = asyncio.ensure_future(awaitable)
    try:
        return await asyncio.wait_for(
            asyncio.shield(fut), timeout=max(0.0, deadline.remaining())
        )
    except (asyncio.TimeoutError, TimeoutError):
        raise deadline.exceeded(checkpoint) from None


def start_deadline(
    budget_s: float | None, clock: Callable[[], float] = time.monotonic
) -> Deadline | None:
    """Begin a request deadline; ``None`` budget means no deadline (callers
    guard checkpoints with ``if deadline is not None``)."""
    return None if budget_s is None else Deadline(budget_s, clock)
