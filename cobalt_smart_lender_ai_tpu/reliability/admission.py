"""Admission control: token-bucket rate limiting + a bounded in-flight cap.

The stdlib adapter is a ThreadingHTTPServer and the FastAPI adapter an async
loop — without admission control, overload turns into an unbounded queue of
threads/tasks all waiting on the same accelerator, latency grows without
bound, and every client times out (the classic congestion-collapse shape).
Here excess load is *shed* at the door as `errors.RequestShed` (HTTP 429 with
``Retry-After``): the requests that are admitted finish fast, and the ones
that are not get an honest, immediate answer with the server's own estimate
of when to come back.

Two independent gates, both optional:

- **Token bucket** — sustained request rate capped at ``rate_rps`` with
  bursts up to ``burst``; refill is computed from the injectable clock, so
  behavior is exact under fake clocks (no background refill thread).
- **In-flight cap** — at most ``max_in_flight`` requests executing at once;
  this is the gate that actually protects the accelerator, since one slow
  dispatch holds its slot for its whole duration.

All counters (`admitted`, `shed_rate`, `shed_capacity`, `in_flight`) are
observable so tests and `/readyz` report what admission actually did.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Iterator

from cobalt_smart_lender_ai_tpu.reliability.errors import RequestShed


class TokenBucket:
    """Classic token bucket over an injectable monotonic clock."""

    def __init__(
        self,
        rate_rps: float,
        burst: float,
        clock: Callable[[], float] = time.monotonic,
    ):
        if rate_rps <= 0:
            raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
        if burst < 1:
            raise ValueError(f"burst must be >= 1, got {burst}")
        self.rate_rps = float(rate_rps)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._stamp = clock()
        self._lock = threading.Lock()

    def _refill_locked(self) -> None:
        now = self._clock()
        self._tokens = min(
            self.burst, self._tokens + (now - self._stamp) * self.rate_rps
        )
        self._stamp = now

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            self._refill_locked()
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def retry_after_s(self, n: float = 1.0) -> float:
        """Time until ``n`` tokens will have accumulated — the honest
        ``Retry-After`` for a shed request."""
        with self._lock:
            self._refill_locked()
            deficit = n - self._tokens
            return max(0.0, deficit / self.rate_rps)

    def resize(self, rate_rps: float, burst: float) -> None:
        """Swap the bucket's rate/burst in place (fleet resize). Accrued
        tokens are refilled at the OLD rate first, then clamped to the new
        burst — a shrink can't leave a stale oversized balance."""
        if rate_rps <= 0:
            raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
        if burst < 1:
            raise ValueError(f"burst must be >= 1, got {burst}")
        with self._lock:
            self._refill_locked()
            self.rate_rps = float(rate_rps)
            self.burst = float(burst)
            self._tokens = min(self._tokens, self.burst)


class AdmissionController:
    """Gate every scoring request through ``with admission.admit():``.

    Raises `RequestShed` (HTTP 429 + ``Retry-After``) instead of queueing.
    Health/readiness and admin routes are deliberately *not* gated — an
    overloaded instance must still be observable and operable.
    """

    def __init__(
        self,
        *,
        rate_rps: float | None = None,
        burst: float = 16,
        max_in_flight: int | None = None,
        shed_retry_after_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.bucket = (
            None if rate_rps is None else TokenBucket(rate_rps, burst, clock)
        )
        self.max_in_flight = max_in_flight
        self.shed_retry_after_s = shed_retry_after_s
        # Per-unit base values for `rescale`: the configured limits describe
        # what ONE replica can absorb; a fleet multiplies them by its size.
        self._base_rate_rps = rate_rps
        self._base_burst = burst
        self._base_max_in_flight = max_in_flight
        self.scale_units = 1
        self._lock = threading.Lock()
        self.in_flight = 0
        self.admitted = 0
        self.shed_rate = 0
        self.shed_capacity = 0

    def rescale(self, units: int) -> dict:
        """Recompute capacity for ``units`` serving replicas: shedding
        thresholds must track actual capacity, or a scale-up keeps shedding
        at the old single-replica limits (and a scale-down queues load the
        shrunken fleet can no longer absorb)."""
        units = max(1, int(units))
        self.scale_units = units
        if self._base_max_in_flight is not None:
            self.max_in_flight = self._base_max_in_flight * units
        if self.bucket is not None and self._base_rate_rps is not None:
            self.bucket.resize(
                self._base_rate_rps * units, max(1, self._base_burst * units)
            )
        return {
            "units": units,
            "max_in_flight": self.max_in_flight,
            "rate_rps": None if self.bucket is None else self.bucket.rate_rps,
        }

    @contextlib.contextmanager
    def admit(self) -> Iterator[None]:
        if self.bucket is not None and not self.bucket.try_acquire():
            with self._lock:
                self.shed_rate += 1
            raise RequestShed(
                "request rate limit exceeded",
                # At least a millisecond: a drained bucket's deficit can
                # round to 0 between the failed acquire and this estimate.
                retry_after_s=max(self.bucket.retry_after_s(), 1e-3),
            )
        with self._lock:
            if (
                self.max_in_flight is not None
                and self.in_flight >= self.max_in_flight
            ):
                self.shed_capacity += 1
                raise RequestShed(
                    f"server at capacity ({self.max_in_flight} requests in "
                    "flight)",
                    retry_after_s=self.shed_retry_after_s,
                )
            self.in_flight += 1
            self.admitted += 1
        try:
            yield
        finally:
            with self._lock:
                self.in_flight -= 1

    def stats(self) -> dict:
        with self._lock:
            return {
                "in_flight": self.in_flight,
                "admitted": self.admitted,
                "shed_rate": self.shed_rate,
                "shed_capacity": self.shed_capacity,
                "max_in_flight": self.max_in_flight,
                "scale_units": self.scale_units,
            }


def admission_from_config(
    rel, clock: Callable[[], float] = time.monotonic
) -> AdmissionController:
    """Build from a `config.ReliabilityConfig` (kept here so config.py stays
    dependency-free, mirroring `retry.policy_from_config`)."""
    return AdmissionController(
        rate_rps=rel.rate_limit_rps,
        burst=rel.rate_limit_burst,
        max_in_flight=rel.max_in_flight,
        shed_retry_after_s=rel.shed_retry_after_s,
        clock=clock,
    )
