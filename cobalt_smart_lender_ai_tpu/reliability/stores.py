"""`ResilientStore` — retrying, read-verifying wrapper over any `ObjectStore`.

Every pipeline/serving store access funnels through the five byte-blob
primitives, so wrapping those five with `call_with_retry` makes the whole
I/O surface (frames, artifacts, metrics, manifests — the conveniences are
inherited and compose over the wrapped primitives) survive transient
backend failures. Reads additionally verify against the content-addressed
``<key>.ptr.json`` pointer when one exists: a corrupted read raises
`CorruptObjectError`, which the retry policy treats as transient (a re-read
can return clean bytes), so corruption is healed when it is transient and
surfaced when it is not.
"""

from __future__ import annotations

import hashlib
import json
import random
import time
from typing import Callable, Iterator

from cobalt_smart_lender_ai_tpu.io.store import PTR_SUFFIX, ObjectStore
from cobalt_smart_lender_ai_tpu.reliability.retry import RetryPolicy, call_with_retry


class CorruptObjectError(RuntimeError):
    """Read bytes do not match the object's content-addressed pointer."""


class ResilientStore(ObjectStore):
    """Retry + verify wrapper; same `ObjectStore` contract as the backend it
    wraps. ``retries`` counts backoff sleeps actually taken — observable so
    fault-injection tests assert recovery happened *via retries* rather than
    by luck.
    """

    def __new__(cls, *args, **kwargs):  # bypass ObjectStore's URI dispatch
        return object.__new__(cls)

    def __init__(
        self,
        inner: ObjectStore,
        policy: RetryPolicy | None = None,
        *,
        verify_reads: bool = True,
        sleep: Callable[[float], None] = time.sleep,
        monotonic: Callable[[], float] = time.monotonic,
        rng: random.Random | None = None,
    ):
        self.inner = inner
        self.uri = inner.uri
        self.policy = policy or RetryPolicy()
        self.verify_reads = verify_reads
        self._sleep = sleep
        self._monotonic = monotonic
        self._rng = rng or random.Random(0)
        self.retries = 0

    def _call(self, fn):
        def count(_attempt, _exc):
            self.retries += 1

        return call_with_retry(
            fn,
            self.policy,
            sleep=self._sleep,
            monotonic=self._monotonic,
            rng=self._rng,
            on_retry=count,
        )

    # -- byte-blob contract, each primitive retried as a unit -----------------
    def put_bytes(self, key: str, data: bytes) -> None:
        self._call(lambda: self.inner.put_bytes(key, data))

    def get_bytes(self, key: str) -> bytes:
        return self._call(lambda: self._verified_get(key))

    def exists(self, key: str) -> bool:
        return self._call(lambda: self.inner.exists(key))

    def delete(self, key: str) -> None:
        self._call(lambda: self.inner.delete(key))

    def list(self, prefix: str = "") -> Iterator[str]:
        # Materialize inside the retried attempt: a generator that dies
        # mid-iteration cannot be resumed, a list can be re-fetched whole.
        return iter(self._call(lambda: list(self.inner.list(prefix))))

    # -- read verification ----------------------------------------------------
    def _verified_get(self, key: str) -> bytes:
        # Each backend call carries its own retry budget (three calls inside
        # one retried unit would compound per-call failure odds); the outer
        # `_call` in `get_bytes` then re-runs the whole read when the bytes
        # fail verification.
        data = self._call(lambda: self.inner.get_bytes(key))
        if not self.verify_reads or key.endswith(PTR_SUFFIX):
            return data
        ptr_key = key + PTR_SUFFIX
        if not self._call(lambda: self.inner.exists(ptr_key)):
            return data  # unpinned object: nothing to verify against
        try:
            ptr = json.loads(self._call(lambda: self.inner.get_bytes(ptr_key)).decode())
        except (ValueError, UnicodeDecodeError) as exc:
            # A corrupted pointer blob is as transient as a corrupted object:
            # re-read rather than dying on the JSON parse.
            raise CorruptObjectError(f"pointer for {key!r} unreadable: {exc}")
        if (
            hashlib.md5(data).hexdigest() != ptr.get("md5")
            or len(data) != ptr.get("size")
        ):
            raise CorruptObjectError(
                f"object {key!r} does not match its content pointer "
                f"(got {len(data)} bytes, pinned {ptr.get('size')})"
            )
        return data
