"""Resilience layer (SURVEY gap: "no checkpoint/resume, no fault tolerance").

The reference dies on the first transient failure anywhere: an S3 read that
times out kills a preprocessing script, a crash mid-search throws away hours
of RFE work, and a SHAP failure at serve time 500s the request. This package
provides the four primitives the rest of the framework wires in:

- `retry` — `RetryPolicy` (bounded attempts, exponential backoff + jitter,
  deadline, retryable-exception predicate) and `call_with_retry`, with the
  clock/sleep/rng injectable so tests never sleep for real.
- `stores` — `ResilientStore`, an `ObjectStore` wrapper that retries
  transient failures per the policy and verifies content-addressed
  `<key>.ptr.json` pointers on read (a corrupted read is retried, not
  silently consumed).
- `faults` — `FaultInjectingStore`, a seeded, deterministic test double that
  injects failure-rate / fail-after-N / corrupted-bytes faults per
  operation, so every resilience claim in the test suite is exercised under
  real (injected) faults instead of asserted.
- `checkpoint` — `PipelineCheckpoint`: per-stage manifests (outputs, md5+size
  pointers, config fingerprint) that `pipeline.run_pipeline` writes after
  each stage and its `--resume` path validates to skip stages whose outputs
  still verify.
"""

from cobalt_smart_lender_ai_tpu.reliability.checkpoint import (
    PipelineCheckpoint,
    config_fingerprint,
)
from cobalt_smart_lender_ai_tpu.reliability.faults import (
    FaultInjectingStore,
    FaultSpec,
    InjectedFault,
)
from cobalt_smart_lender_ai_tpu.reliability.retry import (
    RetryPolicy,
    call_with_retry,
    is_transient_store_error,
    policy_from_config,
)
from cobalt_smart_lender_ai_tpu.reliability.stores import (
    CorruptObjectError,
    ResilientStore,
)

__all__ = [
    "CorruptObjectError",
    "FaultInjectingStore",
    "FaultSpec",
    "InjectedFault",
    "PipelineCheckpoint",
    "ResilientStore",
    "RetryPolicy",
    "call_with_retry",
    "config_fingerprint",
    "is_transient_store_error",
    "policy_from_config",
]
