"""Resilience layer (SURVEY gap: "no checkpoint/resume, no fault tolerance").

The reference dies on the first transient failure anywhere: an S3 read that
times out kills a preprocessing script, a crash mid-search throws away hours
of RFE work, and a SHAP failure at serve time 500s the request. This package
provides the primitives the rest of the framework wires in:

Storage-side (PR 2):

- `retry` — `RetryPolicy` (bounded attempts, exponential backoff + jitter,
  deadline, retryable-exception predicate) and `call_with_retry`, with the
  clock/sleep/rng injectable so tests never sleep for real.
- `stores` — `ResilientStore`, an `ObjectStore` wrapper that retries
  transient failures per the policy and verifies content-addressed
  `<key>.ptr.json` pointers on read (a corrupted read is retried, not
  silently consumed).
- `faults` — `FaultInjectingStore`, a seeded, deterministic test double that
  injects failure-rate / fail-after-N / corrupted-bytes / latency faults per
  operation, so every resilience claim in the test suite is exercised under
  real (injected) faults instead of asserted.
- `checkpoint` — `PipelineCheckpoint`: per-stage manifests (outputs, md5+size
  pointers, config fingerprint) that `pipeline.run_pipeline` writes after
  each stage and its `--resume` path validates to skip stages whose outputs
  still verify.

Request-path hardening (PR 3 — the classic SRE stability patterns):

- `errors` — the one serving error taxonomy (`RequestError` + typed
  subclasses with HTTP status, stable code, `Retry-After`) both HTTP
  adapters map identically via `error_response`.
- `deadline` — `Deadline` / `start_deadline`: per-request wall-clock budgets
  with cooperative cancellation checkpoints (`DeadlineExceeded` → 504).
- `admission` — `TokenBucket` + `AdmissionController`: rate limiting and a
  bounded in-flight cap that shed overload as `RequestShed` (429 +
  ``Retry-After``) instead of queueing unboundedly.
- `breaker` — `CircuitBreaker` (closed/open/half-open, injectable clock)
  wrapping store-backed serving operations so a flapping store fails fast
  (`CircuitOpenError` → 503) instead of tying up workers in retries.

Fleet-level chaos (PR 17 — the supervision layer's test primitive):

- `chaos` — `ChaosPlan`: seeded, clock-injectable replica murder (kill the
  batcher worker, hang dispatch, error-storm, add latency) armed per replica
  index, so `serve.supervisor` heals under real injected failures in tests
  and the `chaos-fleet` CI job.

Synthetic load (PR 18 — the autoscaler's test primitive):

- `traffic` — `TrafficShape` / `TenantPopulation` / `TrafficGenerator`:
  seeded open-loop arrival schedules (diurnal, bursty, flash-crowd, ramp)
  over a Zipf-weighted tenant population, so `serve.autoscaler` scales and
  browns out under realistic load in tests and the `autoscale-smoke` CI
  job (``bench_serve.py --traffic``).
"""

from cobalt_smart_lender_ai_tpu.reliability.admission import (
    AdmissionController,
    TokenBucket,
    admission_from_config,
)
from cobalt_smart_lender_ai_tpu.reliability.breaker import (
    CircuitBreaker,
    breaker_from_config,
)
from cobalt_smart_lender_ai_tpu.reliability.checkpoint import (
    PipelineCheckpoint,
    config_fingerprint,
)
from cobalt_smart_lender_ai_tpu.reliability.chaos import (
    ChaosError,
    ChaosPlan,
    ChaosSpec,
    WorkerKilled,
)
from cobalt_smart_lender_ai_tpu.reliability.deadline import (
    Deadline,
    await_under_deadline,
    start_deadline,
)
from cobalt_smart_lender_ai_tpu.reliability.errors import (
    CircuitOpenError,
    DeadlineExceeded,
    PayloadTooLarge,
    PromotionRejected,
    ReloadFailed,
    RequestError,
    RequestShed,
    RollbackFailed,
    ValidationError,
    WorkerDead,
    error_response,
)
from cobalt_smart_lender_ai_tpu.reliability.faults import (
    FaultInjectingStore,
    FaultSpec,
    InjectedFault,
)
from cobalt_smart_lender_ai_tpu.reliability.retry import (
    RetryPolicy,
    call_with_retry,
    is_transient_store_error,
    policy_from_config,
)
from cobalt_smart_lender_ai_tpu.reliability.stores import (
    CorruptObjectError,
    ResilientStore,
)
from cobalt_smart_lender_ai_tpu.reliability.traffic import (
    TenantPopulation,
    TrafficGenerator,
    TrafficShape,
    shape_by_name,
)

__all__ = [
    "AdmissionController",
    "ChaosError",
    "ChaosPlan",
    "ChaosSpec",
    "CircuitBreaker",
    "CircuitOpenError",
    "CorruptObjectError",
    "Deadline",
    "DeadlineExceeded",
    "FaultInjectingStore",
    "FaultSpec",
    "InjectedFault",
    "PayloadTooLarge",
    "PipelineCheckpoint",
    "PromotionRejected",
    "ReloadFailed",
    "RequestError",
    "RequestShed",
    "ResilientStore",
    "RetryPolicy",
    "RollbackFailed",
    "TenantPopulation",
    "TokenBucket",
    "TrafficGenerator",
    "TrafficShape",
    "ValidationError",
    "WorkerDead",
    "WorkerKilled",
    "admission_from_config",
    "breaker_from_config",
    "call_with_retry",
    "config_fingerprint",
    "error_response",
    "is_transient_store_error",
    "policy_from_config",
    "shape_by_name",
    "await_under_deadline",
    "start_deadline",
]
