"""Synthetic workload generation for load testing the serving fleet.

The chaos harness (`reliability.chaos`) answers "does the fleet survive
*failure*?"; this module supplies the other axis — "does it survive *load*
it wasn't provisioned for?" — by replaying a synthetic tenant population
through the async client harness (`bench_serve.py --traffic <shape>`) with
realistic arrival processes:

- `TrafficShape` — a composable intensity curve over normalized run phase
  ``[0, 1]`` → multiplier ``[0, 1]``; the named shapes (``diurnal``,
  ``bursty``, ``flash_crowd``, ``ramp``, ``steady``) can be added, scaled
  and clamped to build new profiles.
- `TenantPopulation` — a seeded population with Zipf-ish request weights and
  per-tenant payload jitter, so score-cache behavior under load is realistic
  (hot tenants repeat, cold tenants churn) without any real data.
- `TrafficGenerator` — turns (shape, base/peak RPS, request mix) into an
  **open-loop** arrival schedule: a time-varying Poisson process sampled per
  tick. Open-loop matters — closed-loop clients self-throttle when the
  server slows down, hiding exactly the overload the autoscaler exists to
  absorb (coordinated omission).

Everything is seeded and clock-free: the schedule is a pure function of
(seed, shape, rates, duration), so a failing CI run replays exactly.
"""

from __future__ import annotations

import dataclasses
import math
import random
from typing import Callable, Iterator, Sequence

#: Request kinds a generated arrival can carry. ``single`` posts one row to
#: ``/predict``; ``bulk`` posts ``bulk_rows`` rows of CSV; ``shap`` posts a
#: single row to ``/feature_importance_bulk`` (the SHAP-bearing route — the
#: one the brownout ladder degrades first).
KIND_SINGLE = "single"
KIND_BULK = "bulk"
KIND_SHAP = "shap"
KINDS = (KIND_SINGLE, KIND_BULK, KIND_SHAP)


@dataclasses.dataclass(frozen=True)
class TrafficShape:
    """A named intensity curve: ``phase in [0, 1] -> multiplier in [0, 1]``.

    The multiplier interpolates between ``base_rps`` (0.0) and ``peak_rps``
    (1.0) in `TrafficGenerator.target_rps`. Shapes compose: ``a + b``
    averages two curves, ``a.scaled(0.5)`` attenuates one — both return new
    shapes, clamped back into [0, 1].
    """

    name: str
    fn: Callable[[float], float]

    def at(self, phase: float) -> float:
        return min(1.0, max(0.0, float(self.fn(min(1.0, max(0.0, phase))))))

    def __add__(self, other: "TrafficShape") -> "TrafficShape":
        return TrafficShape(
            f"{self.name}+{other.name}",
            lambda p, a=self, b=other: 0.5 * (a.at(p) + b.at(p)),
        )

    def scaled(self, k: float) -> "TrafficShape":
        return TrafficShape(
            f"{self.name}*{k:g}", lambda p, a=self, k=k: k * a.at(p)
        )


def _diurnal(phase: float) -> float:
    # One full day compressed into the run: trough at the start/end, peak
    # mid-run (raised cosine).
    return 0.5 - 0.5 * math.cos(2.0 * math.pi * phase)


def _ramp(phase: float) -> float:
    return phase


def _flash_crowd(phase: float) -> float:
    # Quiet baseline, a near-instant spike to peak at 30% of the run, a
    # plateau, then exponential decay back to baseline — the news-link /
    # cron-stampede shape. The plateau is long enough (25% of the run) for
    # burn-rate windows to see sustained overload, and the decay leaves the
    # tail of the run quiet enough for scale-down + brownout release.
    if phase < 0.30:
        return 0.05
    if phase < 0.55:
        return 1.0
    return 0.05 + 0.95 * math.exp(-12.0 * (phase - 0.55))


def steady(level: float = 1.0) -> TrafficShape:
    return TrafficShape("steady", lambda p, lvl=level: lvl)


def bursty(seed: int = 0, n_bursts: int = 6, floor: float = 0.15) -> TrafficShape:
    """Baseline load with ``n_bursts`` seeded square bursts to peak. The
    burst placement is drawn once at construction, so the shape is a pure
    function afterward (same seed -> same curve)."""
    rng = random.Random(seed ^ 0x5EED)
    bursts = sorted(
        (rng.uniform(0.05, 0.90), rng.uniform(0.03, 0.08))
        for _ in range(n_bursts)
    )

    def fn(phase: float) -> float:
        for start, width in bursts:
            if start <= phase < start + width:
                return 1.0
        return floor

    return TrafficShape("bursty", fn)


def shape_by_name(name: str, seed: int = 0) -> TrafficShape:
    """Resolve a CLI/CI shape name; raises ValueError on unknown names so
    ``bench_serve.py --traffic`` fails loudly, not with a silent steady run."""
    shapes = {
        "diurnal": TrafficShape("diurnal", _diurnal),
        "ramp": TrafficShape("ramp", _ramp),
        "flash_crowd": TrafficShape("flash_crowd", _flash_crowd),
        "bursty": bursty(seed),
        "steady": steady(),
    }
    try:
        return shapes[name]
    except KeyError:
        raise ValueError(
            f"unknown traffic shape {name!r}; known: {sorted(shapes)}"
        ) from None


class TenantPopulation:
    """A seeded synthetic tenant base with Zipf-weighted request volume.

    Each tenant owns a stable base feature row (drawn once from the seeded
    RNG) plus per-request jitter on the float features: tenant-level
    repetition exercises the score cache the way real traffic does, while
    jitter keeps the cache from absorbing *everything*.

    ``fields`` is the serving payload's canonical field list and
    ``int_fields`` the subset that must stay integral (one-hot / count
    columns) — passed in by the caller so this module never imports the
    serving layer.
    """

    def __init__(
        self,
        fields: Sequence[str],
        int_fields: Sequence[str] = (),
        *,
        n_tenants: int = 16,
        seed: int = 0,
        jitter: float = 0.05,
        base_rows: Sequence[dict] | None = None,
    ):
        if n_tenants < 1:
            raise ValueError(f"n_tenants must be >= 1, got {n_tenants}")
        self.fields = list(fields)
        self.int_fields = frozenset(int_fields)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)
        # Zipf-ish volume weights: tenant k gets weight 1/(k+1).
        self._weights = [1.0 / (k + 1.0) for k in range(n_tenants)]
        total = sum(self._weights)
        self._cum = []
        acc = 0.0
        for w in self._weights:
            acc += w / total
            self._cum.append(acc)
        if base_rows is not None:
            # Caller-supplied rows (e.g. real validated serving payloads, so
            # every generated request clears the input schema) — cycled to
            # fill the population.
            if not base_rows:
                raise ValueError("base_rows must be non-empty when given")
            self._base_rows = [
                dict(base_rows[k % len(base_rows)]) for k in range(n_tenants)
            ]
        else:
            self._base_rows = [
                {
                    f: (self._rng.randint(0, 1) if f in self.int_fields
                        else round(self._rng.uniform(0.0, 10.0), 4))
                    for f in self.fields
                }
                for _ in range(n_tenants)
            ]

    def __len__(self) -> int:
        return len(self._base_rows)

    def pick(self, rng: random.Random) -> int:
        u = rng.random()
        for tenant, cum in enumerate(self._cum):
            if u <= cum:
                return tenant
        return len(self._cum) - 1

    def payload(self, tenant: int, rng: random.Random) -> dict:
        """One request row for ``tenant``: the stable base with jittered
        floats. Zero jitter -> byte-identical repeats (pure cache traffic)."""
        base = self._base_rows[tenant]
        if self.jitter <= 0.0:
            return dict(base)
        out = {}
        for f, v in base.items():
            if f in self.int_fields:
                out[f] = v
            else:
                out[f] = round(v * (1.0 + rng.uniform(-self.jitter, self.jitter)), 6)
        return out


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One open-loop request: fire at ``t`` seconds after run start."""

    t: float
    kind: str
    tenant: int
    payload: dict


class TrafficGenerator:
    """Seeded open-loop arrival schedule over a `TrafficShape`.

    The run is divided into ``tick_s`` ticks; each tick's arrival count is
    Poisson with mean ``target_rps(t) * tick_s`` and arrivals land uniformly
    inside the tick. The whole schedule is materialized up front (pure
    function of the seed), so the client harness only has to sleep-and-fire.
    """

    def __init__(
        self,
        shape: TrafficShape,
        *,
        base_rps: float,
        peak_rps: float,
        duration_s: float,
        tenants: TenantPopulation,
        seed: int = 0,
        tick_s: float = 0.25,
        mix: dict | None = None,
        bulk_rows: int = 64,
    ):
        if peak_rps < base_rps:
            raise ValueError(
                f"peak_rps ({peak_rps}) must be >= base_rps ({base_rps})"
            )
        if duration_s <= 0 or tick_s <= 0:
            raise ValueError("duration_s and tick_s must be > 0")
        self.shape = shape
        self.base_rps = float(base_rps)
        self.peak_rps = float(peak_rps)
        self.duration_s = float(duration_s)
        self.tick_s = float(tick_s)
        self.tenants = tenants
        self.seed = int(seed)
        self.bulk_rows = int(bulk_rows)
        mix = dict(mix or {KIND_SINGLE: 0.85, KIND_SHAP: 0.10, KIND_BULK: 0.05})
        unknown = set(mix) - set(KINDS)
        if unknown:
            raise ValueError(f"unknown request kinds in mix: {sorted(unknown)}")
        total = sum(mix.values())
        if total <= 0:
            raise ValueError(f"mix must have positive mass, got {mix}")
        self.mix = {k: mix.get(k, 0.0) / total for k in KINDS}

    def target_rps(self, t: float) -> float:
        """Instantaneous open-loop target at ``t`` seconds into the run."""
        phase = t / self.duration_s
        return self.base_rps + (self.peak_rps - self.base_rps) * self.shape.at(
            phase
        )

    def ticks(self) -> Iterator[tuple[float, float]]:
        """(tick start time, target RPS) pairs across the run — what a
        dashboard or test plots against observed throughput."""
        t = 0.0
        while t < self.duration_s:
            yield t, self.target_rps(t)
            t += self.tick_s

    def _poisson(self, rng: random.Random, mean: float) -> int:
        # Knuth for small means (ticks are sub-second, mean is small); the
        # normal approximation guards pathological tick/rate combinations.
        if mean <= 0.0:
            return 0
        if mean > 64.0:
            return max(0, int(round(rng.gauss(mean, math.sqrt(mean)))))
        limit = math.exp(-mean)
        k, p = 0, 1.0
        while p > limit:
            k += 1
            p *= rng.random()
        return k - 1

    def _kind(self, rng: random.Random) -> str:
        u = rng.random()
        acc = 0.0
        for kind in KINDS:
            acc += self.mix[kind]
            if u <= acc:
                return kind
        return KIND_SINGLE

    def schedule(self) -> list[Arrival]:
        """Materialize the full arrival schedule, sorted by fire time."""
        rng = random.Random(self.seed)
        arrivals: list[Arrival] = []
        for t0, rps in self.ticks():
            for _ in range(self._poisson(rng, rps * self.tick_s)):
                at = t0 + rng.random() * self.tick_s
                if at >= self.duration_s:
                    continue
                tenant = self.tenants.pick(rng)
                arrivals.append(
                    Arrival(
                        t=at,
                        kind=self._kind(rng),
                        tenant=tenant,
                        payload=self.tenants.payload(tenant, rng),
                    )
                )
        arrivals.sort(key=lambda a: a.t)
        return arrivals

    def summary(self) -> dict:
        """Shape metadata for bench records / TREND rows."""
        sched = self.schedule()
        kinds = {k: 0 for k in KINDS}
        for a in sched:
            kinds[a.kind] += 1
        return {
            "shape": self.shape.name,
            "base_rps": self.base_rps,
            "peak_rps": self.peak_rps,
            "duration_s": self.duration_s,
            "seed": self.seed,
            "arrivals": len(sched),
            "kinds": kinds,
            "tenants": len(self.tenants),
        }
