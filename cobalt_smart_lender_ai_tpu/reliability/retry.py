"""Bounded retry with exponential backoff — the one retry policy every layer
shares (the seed tree's only resilience primitive was the narrow
`debug.retry_first_dispatch`, scoped to first-dispatch RPC deaths).

Design constraints, in order:

- **Deterministic under test.** `call_with_retry` takes ``sleep``,
  ``monotonic`` and ``rng`` so the backoff schedule is asserted against a
  fake clock — tier-1 never sleeps for real.
- **Never retries a deterministic failure.** The default predicate treats
  connection/timeout-shaped errors (and injected/corruption faults) as
  transient; `FileNotFoundError`, `StoreKeyError`, validation errors and
  everything else deterministic re-raises on the first attempt.
- **Bounded twice.** `max_attempts` caps the count; `deadline_s` caps wall
  time — whichever is hit first ends the loop with the last real exception
  (no wrapper exception to unwrap at call sites).
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Any, Callable

logger = logging.getLogger(__name__)


def is_transient_store_error(exc: BaseException) -> bool:
    """Default retryable predicate for store I/O.

    Transient: dropped connections, timeouts, interrupted syscalls, injected
    faults (`InjectedFault` subclasses ConnectionError) and detected
    corruption (`CorruptObjectError` — a re-read can return clean bytes).
    Deterministic (never retried): missing objects, escaping keys, type and
    validation errors.
    """
    from cobalt_smart_lender_ai_tpu.reliability.stores import CorruptObjectError

    if isinstance(exc, CorruptObjectError):
        return True
    if isinstance(
        exc, (FileNotFoundError, IsADirectoryError, NotADirectoryError, PermissionError)
    ):
        return False
    return isinstance(exc, (ConnectionError, TimeoutError, InterruptedError, OSError))


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded attempts + exponential backoff + jitter + deadline.

    Delay before retry ``i`` (0-based) is
    ``min(base_delay_s * multiplier**i, max_delay_s)`` scaled by a uniform
    factor in ``[1 - jitter, 1 + jitter]``.
    """

    max_attempts: int = 4
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.1
    deadline_s: float | None = None
    retryable: Callable[[BaseException], bool] = is_transient_store_error

    def delay(self, failure_index: int, rng: random.Random) -> float:
        raw = min(
            self.base_delay_s * self.multiplier**failure_index, self.max_delay_s
        )
        if self.jitter:
            raw *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(raw, 0.0)


def policy_from_config(rel) -> RetryPolicy:
    """Build a `RetryPolicy` from a `config.ReliabilityConfig` (kept here so
    config.py stays dependency-free)."""
    return RetryPolicy(
        max_attempts=rel.max_attempts,
        base_delay_s=rel.base_delay_s,
        max_delay_s=rel.max_delay_s,
        multiplier=rel.backoff_multiplier,
        jitter=rel.jitter,
        deadline_s=rel.deadline_s,
    )


def call_with_retry(
    fn: Callable[[], Any],
    policy: RetryPolicy | None = None,
    *,
    sleep: Callable[[float], None] = time.sleep,
    monotonic: Callable[[], float] = time.monotonic,
    rng: random.Random | None = None,
    on_retry: Callable[[int, BaseException], None] | None = None,
) -> Any:
    """Run ``fn()`` under ``policy``; re-raise the last exception when the
    attempt or deadline budget is exhausted or the failure is not retryable.

    ``on_retry(failure_index, exc)`` fires before each backoff sleep —
    callers use it for retry counters and logging.
    """
    policy = policy or RetryPolicy()
    rng = rng or random.Random()
    start = monotonic()
    for attempt in range(max(policy.max_attempts, 1)):
        try:
            return fn()
        except BaseException as exc:
            last_attempt = attempt >= policy.max_attempts - 1
            if last_attempt or not policy.retryable(exc):
                raise
            delay = policy.delay(attempt, rng)
            if (
                policy.deadline_s is not None
                and monotonic() - start + delay > policy.deadline_s
            ):
                raise
            if on_retry is not None:
                on_retry(attempt, exc)
            logger.debug(
                "transient failure (attempt %d/%d), retrying in %.3fs: %s",
                attempt + 1,
                policy.max_attempts,
                delay,
                exc,
            )
            sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
