"""Quantile binning for histogram gradient boosting.

XGBoost's C++ core pre-bins features into integer histograms (`hist` tree
method) before split search; this is the JAX/XLA equivalent. Bin 0 is reserved
for missing values (NaN); real values occupy bins ``1 .. n_bins-1`` bounded by
``n_bins - 2`` per-feature quantile edges. The learned-missing-direction split
predicate in ``models/gbdt.py`` relies on this layout.

Everything here is jitted device code: quantile computation is a device-side
``nanquantile`` and the transform is a vmapped ``searchsorted``, so the full
2.3M-row table is binned on TPU without a host round-trip.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class BinSpec:
    """Per-feature quantile bin edges.

    ``edges`` has shape ``(F, n_bins - 2)``, sorted ascending per row; entries
    may repeat when a feature has few distinct values (the duplicate bins are
    simply empty). All-NaN features get ``+inf`` edges so every value lands in
    bin 1.
    """

    edges: jax.Array  # (F, n_bins - 2) float32

    @property
    def n_features(self) -> int:
        return self.edges.shape[0]

    @property
    def n_bins(self) -> int:
        return self.edges.shape[1] + 2


jax.tree_util.register_dataclass(BinSpec, data_fields=["edges"], meta_fields=[])


@partial(jax.jit, static_argnames=("n_bins",))
def compute_bin_edges(X: jax.Array, n_bins: int = 255) -> BinSpec:
    """Quantile edges per feature, NaN-aware. ``X`` is ``(N, F)`` float."""
    qs = jnp.linspace(0.0, 1.0, n_bins - 1)[1:-1]  # n_bins - 3 interior quantiles
    # nanquantile -> (n_bins - 3, F); pad the top with +inf so we always have
    # n_bins - 2 edges and the top bin captures the maximum.
    interior = jnp.nanquantile(X.astype(jnp.float32), qs, axis=0).T  # (F, n_bins - 3)
    top = jnp.full((X.shape[1], 1), jnp.inf, dtype=jnp.float32)
    edges = jnp.concatenate([interior, top], axis=1)
    return BinSpec(edges=jnp.where(jnp.isnan(edges), jnp.inf, edges))


@jax.jit
def transform(spec: BinSpec, X: jax.Array) -> jax.Array:
    """Map ``(N, F)`` float values to ``(N, F)`` uint8/int32 bin indices.

    A finite value v lands in bin ``1 + #{edges < v}`` (so the split predicate
    ``bin <= t``  <=>  ``v <= edges[t-1]``); NaN lands in bin 0.

    On TPU the per-element binary search lowers terribly (serialized loops);
    a brute compare-count against all edges is pure VPU work and vastly
    faster, run over row blocks so the (R, F, B-2) compare transient stays
    bounded. CPU keeps the O(log B) searchsorted.
    """
    Xf = X.astype(jnp.float32)
    dtype = jnp.uint8 if spec.n_bins <= 256 else jnp.int32

    if jax.default_backend() == "cpu":
        def per_feature(edges_f: jax.Array, col: jax.Array) -> jax.Array:
            return jnp.searchsorted(edges_f, col, side="left") + 1

        bins = jax.vmap(per_feature, in_axes=(0, 1), out_axes=1)(spec.edges, Xf)
        return jnp.where(jnp.isnan(Xf), 0, bins).astype(dtype)

    N, F = Xf.shape
    n_edges = spec.edges.shape[1]
    R = min(N, max(512, (1 << 26) // max(F * n_edges, 1)))
    n_blocks = -(-N // R)
    pad = n_blocks * R - N
    Xp = jnp.pad(Xf, ((0, pad), (0, 0))) if pad else Xf

    def body(_, xblk):
        # bin = 1 + #{edges < v} == 1 + #{v > edges}; NaN compares False
        # everywhere -> count 0, remapped to bin 0 below.
        cnt = jnp.sum(
            xblk[:, :, None] > spec.edges[None, :, :], axis=2, dtype=jnp.int32
        )
        return None, jnp.where(jnp.isnan(xblk), 0, cnt + 1).astype(dtype)

    _, blocks = jax.lax.scan(body, None, Xp.reshape(n_blocks, R, F))
    return blocks.reshape(n_blocks * R, F)[:N]


@partial(jax.jit, static_argnames=("n_bins",))
def bin_edges_and_transform(
    X: jax.Array, n_bins: int = 255
) -> tuple[BinSpec, jax.Array]:
    """Fused quantile sketch + binning: one program computes the per-feature
    edges AND maps every value through them, so the device-resident ingest
    flow (`data/device_pipeline.py`) goes features -> GBDT sketch with no
    host round-trip between the two. Identical math to calling
    ``compute_bin_edges`` then ``transform`` back to back (the parity test
    asserts the composed outputs bit-match)."""
    spec = compute_bin_edges(X, n_bins=n_bins)
    return spec, transform(spec, X)


def float_threshold(spec: BinSpec, feature: jax.Array, thr_bin: jax.Array) -> jax.Array:
    """Convert a (tree-tensor) bin threshold to the float-space threshold used
    by the serving predict path: ``go_left = x <= edges[feature, thr_bin - 1]``.

    Trivial splits carry ``thr_bin = n_bins - 1``; the explicit clamp maps them
    to the +inf top edge (edges' last column), i.e. everything routes left.
    """
    idx = jnp.clip(thr_bin - 1, 0, spec.edges.shape[1] - 1)
    return spec.edges[feature, idx]
