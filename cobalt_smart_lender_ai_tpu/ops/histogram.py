"""Gradient/hessian histograms — the hot op of histogram GBDT.

XGBoost builds per-node (feature, bin) gradient histograms in multithreaded
C++ (`hist` method). Two XLA formulations are provided:

- ``segsum`` — one joint (node, feature, bin) segment-sum per channel. Ideal
  on CPU; on TPU, XLA lowers scatter-add to a serialized loop (~17ns per
  (row, feature) update measured on v5e) — far too slow for the hot path.
- ``matmul`` — one-hot bin masks contracted against node-partitioned (g, h)
  columns on the MXU, accumulated over row blocks with `lax.scan` so the
  one-hot never materializes in HBM at full size. This is how histograms are
  built TPU-natively: trade redundant FLOPs (xB one-hot width) for systolic
  throughput.

Under a `dp`-sharded mesh each device builds partial histograms of its row
shard and a `psum` over ICI reduces them (`parallel/sharded.py`) — the GBDT
analog of data-parallel gradient all-reduce.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _hist_segsum(bins, node_local, g, h, n_nodes: int, n_bins: int) -> jax.Array:
    N, F = bins.shape
    feat_ids = jnp.arange(F, dtype=jnp.int32)[None, :]
    seg = (
        (node_local.astype(jnp.int32)[:, None] * F + feat_ids) * n_bins
        + bins.astype(jnp.int32)
    ).reshape(-1)
    n_segments = n_nodes * F * n_bins

    def channel(v: jax.Array) -> jax.Array:
        # Per-channel 1-D segment-sums: (N·F, 2)-shaped data would be tiled to
        # lane width 128 on TPU (64x HBM inflation); flat vectors tile cleanly.
        data = jnp.broadcast_to(v[:, None], (N, F)).reshape(-1)
        return jax.ops.segment_sum(data, seg, num_segments=n_segments)

    out = jnp.stack([channel(g), channel(h)], axis=-1)
    return out.reshape(n_nodes, F, n_bins, 2)


def _hist_matmul(
    bins, node_local, g, h, n_nodes: int, n_bins: int, row_block: int
) -> jax.Array:
    N, F = bins.shape
    K = n_nodes
    oh_node = jax.nn.one_hot(node_local, K, dtype=jnp.float32)  # (N, K)
    rhs = jnp.concatenate(
        [oh_node * g[:, None], oh_node * h[:, None]], axis=1
    )  # (N, 2K)
    # Cap the block so the transient one-hot (R, F, B) stays <= 2^26 elements
    # (256MB at f32) even if XLA fails to fuse it into the contraction.
    R = min(row_block, N, max(512, (1 << 26) // max(F * n_bins, 1)))
    n_blocks = -(-N // R)
    pad = n_blocks * R - N
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))  # bin 0, but rhs pad is 0
        rhs = jnp.pad(rhs, ((0, pad), (0, 0)))
    bins_b = bins.reshape(n_blocks, R, F)
    rhs_b = rhs.reshape(n_blocks, R, 2 * K)
    iota = jnp.arange(n_bins, dtype=jnp.int32)

    def body(acc, xs):
        bblk, rblk = xs
        oh = (bblk.astype(jnp.int32)[:, :, None] == iota[None, None, :]).astype(
            jnp.float32
        )  # (R, F, B) — lives only inside the scan step
        acc = acc + jnp.einsum(
            "rfb,rk->fbk", oh, rblk, preferred_element_type=jnp.float32
        )
        return acc, None

    acc, _ = jax.lax.scan(
        body, jnp.zeros((F, n_bins, 2 * K), jnp.float32), (bins_b, rhs_b)
    )
    return acc.reshape(F, n_bins, 2, K).transpose(3, 0, 1, 2)  # (K, F, B, 2)


@partial(jax.jit, static_argnames=("n_nodes", "n_bins", "impl", "row_block"))
def gradient_histogram(
    bins: jax.Array,  # (N, F) uint8/int32 bin indices
    node_local: jax.Array,  # (N,) int32 — row's node index within the level, [0, n_nodes)
    g: jax.Array,  # (N,) float32 gradients (already sample-weighted)
    h: jax.Array,  # (N,) float32 hessians
    *,
    n_nodes: int,
    n_bins: int,
    impl: str = "auto",
    row_block: int = 32768,
) -> jax.Array:
    """Return ``(n_nodes, F, n_bins, 2)`` sums of (g, h) per bucket."""
    if impl == "auto":
        impl = "segsum" if jax.default_backend() == "cpu" else "matmul"
    if impl == "segsum":
        return _hist_segsum(bins, node_local, g, h, n_nodes, n_bins)
    if impl == "matmul":
        return _hist_matmul(bins, node_local, g, h, n_nodes, n_bins, row_block)
    raise ValueError(f"unknown histogram impl {impl!r}")
