"""Gradient/hessian histograms — the hot op of histogram GBDT.

XGBoost builds per-node (feature, bin) gradient histograms in multithreaded
C++ (`hist` method); this is the XLA equivalent: one fused segment-sum over a
joint (node, feature, bin) index computes the histograms of *every* node of a
tree level in a single device pass. Under a `dp`-sharded mesh each device
builds partial histograms of its row shard and a `psum` over ICI reduces them
(see `parallel/sharded.py`), which is the GBDT analog of data-parallel
gradient all-reduce.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n_nodes", "n_bins"))
def gradient_histogram(
    bins: jax.Array,  # (N, F) uint8/int32 bin indices
    node_local: jax.Array,  # (N,) int32 — row's node index within the level, [0, n_nodes)
    g: jax.Array,  # (N,) float32 gradients (already sample-weighted)
    h: jax.Array,  # (N,) float32 hessians
    *,
    n_nodes: int,
    n_bins: int,
) -> jax.Array:
    """Return ``(n_nodes, F, n_bins, 2)`` sums of (g, h) per bucket."""
    N, F = bins.shape
    feat_ids = jnp.arange(F, dtype=jnp.int32)[None, :]
    seg = (node_local.astype(jnp.int32)[:, None] * F + feat_ids) * n_bins + bins.astype(
        jnp.int32
    )  # (N, F)
    data = jnp.stack([g, h], axis=-1)  # (N, 2)
    data = jnp.broadcast_to(data[:, None, :], (N, F, 2)).reshape(N * F, 2)
    out = jax.ops.segment_sum(data, seg.reshape(-1), num_segments=n_nodes * F * n_bins)
    return out.reshape(n_nodes, F, n_bins, 2)
