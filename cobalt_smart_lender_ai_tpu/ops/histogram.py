"""Gradient/hessian/cover histograms — the hot op of histogram GBDT.

XGBoost builds per-node (feature, bin) gradient histograms in multithreaded
C++ (`hist` method). Two XLA formulations are provided:

- ``segsum`` — one joint (node, feature, bin) segment-sum per channel. Ideal
  on CPU; on TPU, XLA lowers scatter-add to a serialized loop — far too slow
  for the hot path.
- ``matmul`` — one-hot bin masks contracted against node-partitioned
  (g, h, w) columns on the MXU, accumulated over row blocks with `lax.scan`
  so the one-hot never materializes in HBM at full size. This is how
  histograms are built TPU-natively: trade redundant FLOPs (xB one-hot
  width) for systolic throughput.

Three channels per bucket: gradient, hessian, and the row-weight "cover".
Carrying cover as a histogram channel makes the per-level node cover a free
by-product (sum the w channel over one feature's bins) instead of a separate
scatter-add — measured ~5ms/level saved at 500k rows on v5e.

Measured on TPU v5e: the bf16 one-hot beats f32 ~3x (500k-row microbench),
and inside the full fit at bench scale (2.3M rows x 100 features x 64 bins,
depth 3) the whole three-pass-per-tree loop runs at ~48ms/tree with the
swept 4096-row block (`models/gbdt.py` hist_row_block). The bf16 mask is
exact (0/1); note the MXU at default matmul precision may also round the
f32 (g, h) operand to bf16 — accepted deliberately for the histogram: split
gains are rank statistics robust to ~0.4% operand rounding (XGBoost's own
hist method is single-precision), accumulation stays f32, and the 0/1 cover
channel remains exact. Leaf values, which feed predictions directly, are
summed at Precision.HIGHEST in models/gbdt.py instead. A hand-written
Pallas kernel (`ops/hist_pallas.py`) was benchmarked against this
formulation and lost ~2x in-fit; see its docstring for the numbers.

Under a `dp`-sharded mesh each device builds partial histograms of its row
shard and a `psum` over ICI reduces them (`parallel/sharded.py`) — the GBDT
analog of data-parallel gradient all-reduce.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _hist_segsum(bins, node_local, g, h, w, n_nodes: int, n_bins: int) -> jax.Array:
    N, F = bins.shape
    feat_ids = jnp.arange(F, dtype=jnp.int32)[None, :]
    seg = (
        (node_local.astype(jnp.int32)[:, None] * F + feat_ids) * n_bins
        + bins.astype(jnp.int32)
    ).reshape(-1)
    n_segments = n_nodes * F * n_bins

    def channel(v: jax.Array) -> jax.Array:
        # Per-channel 1-D segment-sums: (N·F, 3)-shaped data would be tiled to
        # lane width 128 on TPU (43x HBM inflation); flat vectors tile cleanly.
        data = jnp.broadcast_to(v[:, None], (N, F)).reshape(-1)
        return jax.ops.segment_sum(data, seg, num_segments=n_segments)

    out = jnp.stack([channel(g), channel(h), channel(w)], axis=-1)
    return out.reshape(n_nodes, F, n_bins, 3)


def _hist_matmul_acc(
    bins, node_local, g, h, w, n_nodes: int, n_bins: int, row_block: int
) -> jax.Array:
    N, F = bins.shape
    K = n_nodes
    # Cap the block so the transient one-hot (R, F, B) stays <= 2^27 elements
    # (256MB at bf16) even if XLA fails to fuse it into the contraction;
    # callers can pick smaller blocks via row_block (swept at bench scale:
    # see fit_binned's hist_row_block).
    R = min(row_block, N, max(512, (1 << 27) // max(F * n_bins, 1)))
    n_blocks = -(-N // R)
    pad = n_blocks * R - N
    if pad:
        # Padding: bin 0 rows with zero (g, h, w) contribute nothing.
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        node_local = jnp.pad(node_local, (0, pad))
        g, h, w = (jnp.pad(v, (0, pad)) for v in (g, h, w))
    bins_b = bins.reshape(n_blocks, R, F)
    node_b = node_local.reshape(n_blocks, R)
    ghw_b = jnp.stack([g, h, w], axis=1).reshape(n_blocks, R, 3)
    iota = jnp.arange(n_bins, dtype=jnp.int32)

    def body(acc, xs):
        bblk, nblk, ghwblk = xs
        # The (R, 3K) node-one-hot x channel rhs is built PER BLOCK: doing it
        # for all N rows up front materializes an O(N*3K) tensor — 8GB at
        # 1.84M rows x 64 nodes x 12 vmapped jobs, the full-protocol OOM —
        # while the per-block transient is O(R*3K) and lives only in the
        # scan step. rhs stays f32: gradient precision is not traded away.
        oh_node = jax.nn.one_hot(nblk, K, dtype=jnp.float32)  # (R, K)
        rblk = (oh_node[:, None, :] * ghwblk[:, :, None]).reshape(R, 3 * K)
        # bf16 one-hot: exact 0/1 mask at half the bytes of f32 (3x faster
        # pass measured on v5e); contraction accumulates in f32.
        oh = (bblk.astype(jnp.int32)[:, :, None] == iota[None, None, :]).astype(
            jnp.bfloat16
        )  # (R, F, B) — lives only inside the scan step
        acc = acc + jnp.einsum(
            "rfb,rk->fbk", oh, rblk, preferred_element_type=jnp.float32
        )
        return acc, None

    acc, _ = jax.lax.scan(
        body,
        jnp.zeros((F, n_bins, 3 * K), jnp.float32),
        (bins_b, node_b, ghw_b),
    )
    return acc.reshape(F, n_bins, 3, K)


def _hist_matmul(
    bins, node_local, g, h, w, n_nodes: int, n_bins: int, row_block: int
) -> jax.Array:
    acc = _hist_matmul_acc(bins, node_local, g, h, w, n_nodes, n_bins, row_block)
    return acc.transpose(3, 0, 1, 2)  # (K, F, B, 3)


@partial(jax.jit, static_argnames=("n_nodes", "n_bins", "impl", "row_block"))
def gradient_histogram(
    bins: jax.Array,  # (N, F) uint8/int32 bin indices
    node_local: jax.Array,  # (N,) int32 — row's node index within the level, [0, n_nodes)
    g: jax.Array,  # (N,) float32 gradients (already sample-weighted)
    h: jax.Array,  # (N,) float32 hessians
    w: jax.Array,  # (N,) float32 cover weights (1.0 where the row trains)
    *,
    n_nodes: int,
    n_bins: int,
    impl: str = "auto",
    row_block: int = 32768,
) -> jax.Array:
    """Return ``(n_nodes, F, n_bins, 3)`` sums of (g, h, w) per bucket.

    Node cover falls out as ``hist[:, f, :, 2].sum(-1)`` for any fixed
    feature ``f`` (every row lands in exactly one bin per feature).
    """
    if impl == "auto":
        # "matmul" wins on TPU: a hand-written Pallas kernel (ops/hist_pallas)
        # was benchmarked at 2.3M x 100 x 64 and LOST in-fit (300-tree fit
        # 40.7s pallas vs 20.2s matmul on v5e) — XLA pipelines the one-hot +
        # narrow-dot chain across the level's row blocks better than the
        # straightforward kernel. It remains available as impl="pallas".
        impl = "segsum" if jax.default_backend() == "cpu" else "matmul"
    if impl == "segsum":
        return _hist_segsum(bins, node_local, g, h, w, n_nodes, n_bins)
    if impl == "matmul":
        return _hist_matmul(bins, node_local, g, h, w, n_nodes, n_bins, row_block)
    if impl == "pallas":
        from cobalt_smart_lender_ai_tpu.ops.hist_pallas import hist_pallas

        return hist_pallas(bins, node_local, g, h, w, n_nodes=n_nodes, n_bins=n_bins)
    raise ValueError(f"unknown histogram impl {impl!r}")


def _hist_matmul_jobs(
    bins, node_J, g_J, h_J, w_J, n_nodes: int, n_bins: int, row_block: int
) -> jax.Array:
    """Joint all-jobs histogram: ONE flat ``(F*B, R) x (R, J*3K)`` dot per row
    block, with the job axis folded into the rhs non-contracting dim.

    Why this exists: under ``vmap`` (the CV x HPO fan-out), the per-job einsum
    becomes a dot whose rhs has TWO non-contracting dims (jobs x channels).
    XLA-TPU lowers that as a degenerate-spatial *convolution* (window = jobs,
    pad = jobs-1) — measured as the unexplained ~1 s/tree of the depth-9
    search bucket (round-5 ablation: the histogram pass alone is 0.24 s/tree,
    the full fit 1.28; the optimized HLO shows `convolution(... window={size=
    1x33 pad=0_0x32_32})` ops in place of the contraction). Folding jobs into
    a single flat rhs dim leaves a plain 2-D dot the MXU runs at full rate.
    Returns ``(F, n_bins, J, 3, K)``."""
    J, N = node_J.shape
    F = bins.shape[1]
    K = n_nodes
    R = min(row_block, N, max(512, (1 << 27) // max(F * n_bins, 1)))
    n_blocks = -(-N // R)
    pad = n_blocks * R - N
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        node_J = jnp.pad(node_J, ((0, 0), (0, pad)))
        g_J, h_J, w_J = (jnp.pad(v, ((0, 0), (0, pad))) for v in (g_J, h_J, w_J))
    bins_b = bins.reshape(n_blocks, R, F)
    # Row-major blocking with jobs minor: the per-block rhs is then built
    # directly in (R, J, 3K) order — no transpose inside the scan step.
    node_b = node_J.T.reshape(n_blocks, R, J)
    ghw_b = jnp.stack([g_J, h_J, w_J], axis=-1).transpose(1, 0, 2).reshape(
        n_blocks, R, J, 3
    )
    iota = jnp.arange(n_bins, dtype=jnp.int32)

    def body(acc, xs):
        bblk, nblk, gblk = xs  # (R, F), (R, J), (R, J, 3)
        oh_node = jax.nn.one_hot(nblk, K, dtype=jnp.float32)  # (R, J, K)
        rhs = (oh_node[:, :, None, :] * gblk[:, :, :, None]).reshape(
            R, J * 3 * K
        )
        oh = (
            bblk.astype(jnp.int32)[:, :, None] == iota
        ).astype(jnp.bfloat16).reshape(R, F * n_bins)
        acc = acc + jax.lax.dot_general(
            oh, rhs.astype(jnp.bfloat16), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc, None

    acc, _ = jax.lax.scan(
        body,
        jnp.zeros((F * n_bins, J * 3 * K), jnp.float32),
        (bins_b, node_b, ghw_b),
    )
    return acc.reshape(F, n_bins, J, 3, K)


def _channels_matmul_vmappable(
    bins, node_local, g, h, w, *, n_nodes: int, n_bins: int, row_block: int
):
    """The TPU matmul channel-split path with a custom batching rule: the
    unbatched case runs the single-job block scan; a vmapped call (jobs
    batched, bins shared) runs the joint `_hist_matmul_jobs` dot instead of
    letting XLA conv-ify the batched contraction."""

    def _single(bins, node_local, g, h, w):
        acc = _hist_matmul_acc(
            bins, node_local, g, h, w, n_nodes, n_bins, row_block
        )  # (F, B, 3, K)
        return tuple(acc[:, :, c, :].transpose(2, 0, 1) for c in range(3))

    @jax.custom_batching.custom_vmap
    def f(bins, node_local, g, h, w):
        return _single(bins, node_local, g, h, w)

    @f.def_vmap
    def _rule(axis_size, in_batched, bins_b, node_b, g_b, h_b, w_b):
        bins_bat, node_bat, g_bat, h_bat, w_bat = in_batched
        if (not bins_bat) and node_bat and g_bat and h_bat and w_bat:
            acc = _hist_matmul_jobs(
                bins_b, node_b, g_b, h_b, w_b, n_nodes, n_bins, row_block
            )  # (F, B, J, 3, K)
            outs = tuple(
                acc[:, :, :, c, :].transpose(2, 3, 0, 1) for c in range(3)
            )  # each (J, K, F, B)
            return outs, (True, True, True)
        # Uncommon batching pattern (e.g. per-job bins): plain vmap of the
        # single-job impl — correct, may conv-ify, not a hot path.
        outs = jax.vmap(
            _single,
            in_axes=tuple(0 if b else None for b in in_batched),
        )(bins_b, node_b, g_b, h_b, w_b)
        return outs, (True, True, True)

    return f(bins, node_local, g, h, w)


@partial(jax.jit, static_argnames=("n_nodes", "n_bins", "impl", "row_block"))
def gradient_histogram_channels(
    bins: jax.Array,
    node_local: jax.Array,
    g: jax.Array,
    h: jax.Array,
    w: jax.Array,
    *,
    n_nodes: int,
    n_bins: int,
    impl: str = "auto",
    row_block: int = 32768,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Channel-split `gradient_histogram`: ``(g, h, w)`` sums as THREE
    ``(n_nodes, F, n_bins)`` arrays instead of one ``(n_nodes, F, n_bins, 3)``.

    Same sums, different layout — and on TPU the layout is the whole point:
    a trailing channel axis of 3 is lane-padded to 128 (T(8,128) tiling, 42x
    memory/compute inflation), and every consumer slicing ``[..., :2]`` drags
    that padding through the cumsum/gain chain. The round-5 depth-9 ablation
    (tools/ablate_d9.py) measured the histogram passes at 0.24 s/tree of a
    1.28 s/tree fit — the other ~1 s was consumers operating on
    minor-dim-2/3 arrays. The split form keeps BINS on the lane axis
    (255 -> 256) everywhere."""
    if impl == "auto":
        impl = "segsum" if jax.default_backend() == "cpu" else "matmul"
    if impl == "matmul":
        # custom_vmap wrapper: a vmapped call (the CV x HPO fan-out) runs ONE
        # joint flat dot over all jobs instead of the conv XLA would emit.
        return _channels_matmul_vmappable(
            bins, node_local, g, h, w,
            n_nodes=n_nodes, n_bins=n_bins, row_block=row_block,
        )
    stacked = gradient_histogram(
        bins, node_local, g, h, w,
        n_nodes=n_nodes, n_bins=n_bins, impl=impl, row_block=row_block,
    )
    return tuple(stacked[..., c] for c in range(3))


def select_columns(M: jax.Array, idx: jax.Array, *, exact_max: int) -> jax.Array:
    """Row-wise column select ``M[i, idx[i]]`` as an MXU-friendly one-hot
    contraction on TPU (a 500k-row gather costs ~3ms on v5e; the one-hot dot
    is below measurement noise), falling back to a plain gather on CPU.

    ``exact_max`` must bound the values of ``M``; when it fits bf16's integer
    range (<= 256) the mask and data ride bf16 exactly, otherwise f32 (exact
    to 2^24).
    """
    if jax.default_backend() == "cpu":
        rows = jnp.arange(M.shape[0], dtype=jnp.int32)
        return M[rows, idx]
    dtype = jnp.bfloat16 if exact_max <= 256 else jnp.float32
    oh = jax.nn.one_hot(idx, M.shape[1], dtype=dtype)
    out = jnp.einsum(
        "nf,nf->n", M.astype(dtype), oh, preferred_element_type=jnp.float32
    )
    return out.astype(M.dtype)
