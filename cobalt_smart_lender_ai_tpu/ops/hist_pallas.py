"""Pallas TPU kernel for the gradient-histogram hot op — written to test
whether hand scheduling beats XLA's `matmul` formulation. **Measured answer:
no.** At 2.3M rows x 100 features x 64 bins on v5e, a standalone pass is
~52ms (XLA matmul: ~47ms) and a full 300-tree fit is 40.7s with this kernel
vs 20.2s with the XLA path — XLA pipelines the one-hot build + narrow dot
across the level's row blocks better than this straightforward kernel, and
both formulations are bound by the same VPU-side one-hot construction rate
(cost is n_nodes-independent in both). The kernel is kept as
``gradient_histogram(..., impl="pallas")`` — correct, tested, and a working
example of the VMEM-resident-accumulator pattern — but `impl="auto"` picks
the XLA matmul on TPU (SURVEY §7 hard part (a): "Pallas kernel for
scatter-add *if XLA's is insufficient*" — it is sufficient).

Formulation (same math as `_hist_matmul`):

    out[f*B + b, c] = sum_r [bins[r, f] == b] * rhs[r, c]

with ``rhs = node_one_hot * (g | h | w)`` of width ``C = 3 * n_nodes``.
Grid iterates over row blocks; the (F*B, C) accumulator lives in VMEM across
the whole grid (constant output index map) and is written back once. Each
row block loops over feature tiles of ``FT`` features, building a
(R, FT*B) bf16 one-hot (exact: values are 0/1) and issuing one
``dot_general`` per tile — M = FT*B is MXU-friendly (~512), the contraction
K = R is long, and the narrow N = C rides the lanes.

Supported for the shapes GBDT training produces (C <= 128 and accumulator
<= a few MB, see `pallas_supported`). Numerics match `_hist_matmul` to f32
accumulation order (both accumulate in f32 from exact bf16 one-hots;
max observed deviation 8e-6 at 100k rows).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _pick_tiles(F: int, n_bins: int) -> tuple[int, int]:
    """(row_block, feature_tile): keep every VMEM-resident buffer (one-hot
    tile, bin-id pattern, accumulator) comfortably under the ~16MB scoped
    VMEM budget while the dot's N dimension (FT * n_bins ~ 512) fills the
    lanes and the contraction K = row_block stays long."""
    ft = max(1, 512 // n_bins)
    return 1024, ft


def _hist_kernel(bins_ref, rhs_ref, out_ref, *, n_bins: int, ft: int):
    @pl.when(pl.program_id(0) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    # int32 compares (Mosaic rejects bf16 equality on this target); the
    # resulting one-hot is cast to bf16 for the MXU.
    b32 = bins_ref[:].astype(jnp.int32)  # (R, F_pad)
    R = b32.shape[0]
    n_tiles = b32.shape[1] // ft
    tile_cols = ft * n_bins
    # pltpu.repeat is element-wise (it lowers to jnp.repeat: f0 f0 ... f1 f1
    # ...), so the one-hot column layout is feature-major:
    # col = f_local * n_bins + bin.
    bin_id = jax.lax.broadcasted_iota(jnp.int32, (R, tile_cols), 1) % n_bins
    rhs = rhs_ref[:]
    for t in range(n_tiles):  # static unroll: F_pad/ft tiles
        tile = b32[:, t * ft : (t + 1) * ft]  # (R, ft)
        rep = pltpu.repeat(tile, n_bins, 1)  # (R, ft*B), tile-repeated
        oh = (rep == bin_id).astype(jnp.bfloat16)  # (R, ft*B) exact 0/1
        # Output rides (C, cols): C = 3K is narrow (<= 128), so keeping it on
        # the sublane side makes the accumulator ~C x F*B instead of a
        # lane-padded (F*B, 128) buffer — 8x less VMEM.
        acc = jax.lax.dot_general(
            rhs,
            oh,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (C, ft*B)
        out_ref[:, t * tile_cols : (t + 1) * tile_cols] += acc


@functools.partial(jax.jit, static_argnames=("n_nodes", "n_bins", "interpret"))
def hist_pallas(
    bins: jax.Array,  # (N, F) uint8/int32 bin indices
    node_local: jax.Array,  # (N,) int32 in [0, n_nodes)
    g: jax.Array,
    h: jax.Array,
    w: jax.Array,
    *,
    n_nodes: int,
    n_bins: int,
    interpret: bool = False,
) -> jax.Array:
    """Drop-in for `_hist_matmul`: returns (n_nodes, F, n_bins, 3)."""
    N, F = bins.shape
    K = n_nodes
    C = 3 * K
    R, ft = _pick_tiles(F, n_bins)

    oh_node = jax.nn.one_hot(node_local, K, dtype=jnp.float32)
    rhs = jnp.concatenate(
        [oh_node * g[:, None], oh_node * h[:, None], oh_node * w[:, None]],
        axis=1,
    )  # (N, 3K) f32 — channel-major: [g x K | h x K | w x K]

    F_pad = -(-F // ft) * ft
    N_pad = -(-N // R) * R
    if F_pad != F:
        bins = jnp.pad(bins, ((0, 0), (0, F_pad - F)))
    if N_pad != N:
        # Padded rows carry rhs = 0, so their one-hot hits contribute nothing.
        bins = jnp.pad(bins, ((0, N_pad - N), (0, 0)))
        rhs = jnp.pad(rhs, ((0, N_pad - N), (0, 0)))

    out = pl.pallas_call(
        functools.partial(_hist_kernel, n_bins=n_bins, ft=ft),
        grid=(N_pad // R,),
        in_specs=[
            pl.BlockSpec((R, F_pad), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((R, C), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (C, F_pad * n_bins), lambda i: (0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((C, F_pad * n_bins), jnp.float32),
        interpret=interpret,
    )(bins, rhs)

    # Column layout: tile-major, then feature-within-tile, then bin (see the
    # pltpu.repeat note in the kernel). C layout: channel-major [g|h|w] x K.
    n_tiles = F_pad // ft
    arr = out.reshape(3, K, n_tiles, ft, n_bins)
    arr = arr.transpose(1, 2, 3, 4, 0)  # (K, n_tiles, ft, B, 3)
    return arr.reshape(K, F_pad, n_bins, 3)[:, :F]


def pallas_supported(F: int, n_bins: int, n_nodes: int) -> bool:
    """Shape guard: C must ride one lane register and the VMEM-resident
    accumulator must stay small."""
    C = 3 * n_nodes
    _, ft = _pick_tiles(F, n_bins)
    F_pad = -(-F // ft) * ft
    acc_bytes = F_pad * n_bins * C * 4
    return C <= 128 and acc_bytes <= (6 << 20)


__all__ = ["hist_pallas", "pallas_supported"]
